"""The router core: fan requests over worker replicas, lose nothing.

One :class:`Router` owns N worker transports and gives upstream clients
the same JSONL protocol one ``dpathsim serve`` process speaks — with
one process no longer being one failure domain. The design center is
*robustness*, wired through the existing resilience primitives:

- **Routing** (hashring.py): consistent-hash-by-row for cache affinity
  (the same row keeps hitting the replica whose tiers hold it), or
  contiguous row ranges; either yields a deterministic preference order
  that failover, hedging, and fencing all walk.
- **Failure detection**: per-worker heartbeats (``health`` op — pongs
  carry queue depth and the consistency token) catch *death* and
  *stalls* (miss limit exceeded → the worker is routed around and its
  in-flight work re-dispatched); transport EOF/broken-pipe catches
  death instantly. A stall-suspected worker that pongs again is
  readmitted — suspicion is not a death sentence.
- **Zero lost requests**: every admitted request lives in the pending
  table until exactly one response resolves it. A worker dying
  mid-batch re-dispatches its pending work to a surviving replica;
  retried work is idempotent (dedup by ``request_id`` at both ends —
  the worker replays mutation acks, the router keeps only the first
  answer).
- **Hedged requests**: a query in flight longer than the hedge
  threshold gets a duplicate sent to the next replica in preference
  order; first answer wins, the loser's arrival is counted and
  dropped. This bounds the p99 a stalled-but-not-dead replica causes.
- **Deadlines**: the protocol's ``deadline_ms`` budget is re-computed
  at every (re)dispatch — a failover or hedge never grants more time
  than the caller has left, and an expired budget fails fast instead
  of burning a replica (resilience.Deadline).
- **Admission**: the pending table is bounded; past it, submissions
  shed (:class:`RouterShed`) — and a worker that sheds locally pushes
  the request to the next replica, so the router only sheds when every
  replica is saturated.
- **Delta fencing**: ``update`` broadcasts carry the chained
  ``(base_fp, delta_seq)`` token. The router records each epoch's
  affected-row set; a replica that missed a broadcast is *fenced* —
  never handed a query for an affected row — until catch-up (ordered
  replay of the missed updates, idempotent by request id) brings its
  token to the head. No stale row can escape.

Chaos seams: ``heartbeat`` (a probe that never happened) and
``delta_broadcast`` (a worker missing an update) fire here;
``worker_dispatch`` fires in the worker (worker.py). See
tests/test_router.py and ``make chaos-router``.

**Fleet observability plane** (DESIGN.md §24): the router is where the
fleet's N per-process truths become one. Every routed request gets a
fleet-level root span whose per-attempt dispatch spans (primary /
hedge / failover, siblings) carry their context to the workers on the
wire — one stitched cross-process trace per request. The maintenance
loop scrapes each worker's ``metrics`` op and merges the registries
EXACTLY (same bucket edges ⇒ bucket-wise sums, obs/fleet.py), the SLO
engine (obs/slo.py) evaluates declarative objectives over the merged
stream with multi-window burn-rate alerts, and a tail-sampling flight
recorder (obs/flight.py) retroactively keeps every slow / errored /
shed / hedged / failed-over / ann-degraded request — dumped via the
``flight_dump`` op and at SIGTERM drain, while the workers can still
answer the final span-ring scrape.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from concurrent.futures import Future

from ..obs import fleet as obs_fleet
from ..obs.flight import FlightRecorder
from ..obs.metrics import get_registry
from ..obs.slo import SLOEngine, default_specs
from ..obs.trace import get_tracer, to_wire
from ..resilience import Deadline, inject
from ..utils.logging import runtime_event
from .hashring import make_policy
from .transport import WorkerGone

ROUTED_OPS = frozenset({"topk", "scores"})

# worker statuses
UP = "up"
SUSPECT = "suspect"      # heartbeat-missed: routed around, resurrectable
DOWN = "down"            # transport-dead: gone for good
DRAINING = "draining"    # autoscale drain: routed around, never readmitted


class RouterShed(RuntimeError):
    """Admission refused: the router's pending table is at its bound
    (or every replica is saturated)."""


@dataclasses.dataclass
class RouterConfig:
    routing: str = "hash"            # hash | range
    vnodes: int = 64
    max_inflight: int = 512          # admission bound on pending requests
    default_deadline_ms: float | None = None
    heartbeat_interval_s: float = 0.25
    heartbeat_miss_limit: int = 4    # unanswered intervals before SUSPECT
    hedge_ms: float | None = 100.0   # None disables hedged requests
    worker_queue_limit: int = 256    # per-replica saturation threshold
    max_attempts: int = 4            # distinct replicas tried per request
    update_timeout_s: float = 60.0
    drain_timeout_s: float = 30.0
    # how long a request may sit PARKED (no replica currently eligible:
    # every candidate suspected or fenced) before it fails; a transient
    # all-suspect blip — e.g. a stalled box starving every worker of
    # CPU for a second — must not turn into client-visible errors
    park_timeout_s: float = 10.0
    # -- fleet observability (DESIGN.md §24) ---------------------------
    # metrics scrape cadence: the maintenance loop pulls each worker's
    # `metrics` op and merges the registries exactly (0 disables; the
    # satellite artifact forwarding still leaves per-worker files)
    scrape_interval_s: float = 5.0
    # declarative SLO specs evaluated over the merged stream; () ships
    # the defaults (availability / p99 latency / update-visible
    # staleness / ann recall floor, obs/slo.py)
    slo_specs: tuple = ()
    # flight-recorder tail threshold: a request slower than this is
    # kept even if nothing else went wrong. None derives it from the
    # latency SLO's threshold (the p99 target IS the tail definition)
    slow_ms: float | None = None
    flight_capacity: int = 256
    # span-ring scrape bound per worker (trace op payload)
    trace_scrape_limit: int = 20_000
    # -- firehose update pipelining (router/firehose.py, DESIGN.md §30)
    # bounded update-queue admission: >0 routes ``update`` ops through
    # the coalescing pump; past the bound submitters get an immediate
    # ``backpressure`` error (the update-side shed signal). 0 keeps
    # the legacy one-broadcast-per-update path.
    update_queue: int = 0
    # max queued updates folded into ONE broadcast (the product-rule
    # ΔC composes; conflicting windows split automatically)
    update_coalesce: int = 8
    # how long the pump lingers for more queued updates before
    # broadcasting what it has
    update_flush_ms: float = 5.0
    # keep every epoch's replay payload even after all live replicas
    # pass it — required for autoscale: a freshly SPAWNED worker boots
    # the base graph and must replay the full epoch chain to catch up
    retain_replay: bool = False


class _WorkerState:
    __slots__ = (
        "wid", "transport", "status", "epoch", "queue_depth",
        "last_pong", "assigned", "catchup_active", "token",
        "last_health", "pong_seq", "last_metrics", "metrics_seq",
        "metrics_mono", "trace_part", "trace_seq",
    )

    def __init__(self, wid: str, transport):
        self.wid = wid
        self.transport = transport
        self.status = UP
        self.epoch = 0               # index into the router's epoch log
        self.queue_depth = 0
        self.last_pong = time.monotonic()
        self.assigned: set[str] = set()   # request ids in flight here
        self.catchup_active = False
        self.token: tuple[str, int] | None = None
        self.last_health: dict = {}
        self.pong_seq = 0
        # fleet observability: the last scraped registry snapshot (the
        # merge input), the last scraped span-ring export, and their
        # reply sequence counters (waited scrapes poll on these)
        self.last_metrics: dict | None = None
        self.metrics_seq = 0
        self.metrics_mono = 0.0
        self.trace_part: dict | None = None
        self.trace_seq = 0


class _Pending:
    __slots__ = (
        "rid", "req", "key", "row", "future", "deadline", "tried",
        "assigned", "hedged", "hedge_sent", "t0", "failovers", "parked",
        "span", "attempt_spans",
    )

    def __init__(self, rid: str, req: dict, key, row, future, deadline,
                 span=None):
        self.rid = rid
        self.req = req
        self.key = key
        self.row = row
        self.future = future
        self.deadline = deadline
        self.tried: list[str] = []
        self.assigned: set[str] = set()
        self.hedged = False      # hedge CONSIDERED (one shot per request)
        self.hedge_sent = False  # hedge actually dispatched
        self.failovers = 0
        self.parked = False
        self.t0 = time.monotonic()
        # tracing: the fleet-level root span and one child span per
        # dispatch ATTEMPT (primary / hedge / failover) — siblings
        # under the root, each carried to a worker on the wire so its
        # subtree grows there. None when tracing is off or this
        # request's head was sampled out.
        self.span = span
        self.attempt_spans: dict[str, object] = {}


class _Epoch:
    """One entry of the delta log: the consistency token after this
    update, the wire request to replay for catch-up, and the rows it
    affected (None = all rows; epoch 0 is the base graph)."""

    __slots__ = ("token", "wire_req", "affected", "rid")

    def __init__(self, token, wire_req=None, affected=None, rid=None):
        self.token = tuple(token)
        self.wire_req = wire_req
        self.affected = affected
        self.rid = rid


class _UpdatePending:
    __slots__ = ("rid", "client_id", "future", "waiting", "acks",
                 "failures", "t0", "epoch_index", "first_result", "wire",
                 "span", "target_spans")

    def __init__(self, rid, client_id, future, waiting, wire, span=None):
        self.rid = rid
        self.client_id = client_id
        self.future = future
        self.waiting: set[str] = set(waiting)
        self.acks: dict[str, dict] = {}
        self.failures: dict[str, str] = {}
        self.t0 = time.monotonic()
        self.epoch_index: int | None = None
        self.first_result: dict | None = None
        self.wire = wire  # replayable request (catch-up; same request_id)
        # tracing: root span for the broadcast + one child per target
        # replica (the wire carries each child's context, so the
        # worker-side delta application stitches under it)
        self.span = span
        self.target_spans: dict[str, object] = {}


class Router:
    """Owns worker transports and the pending table. ``transports`` is
    ``{worker_id: transport}`` (not yet started); :meth:`start` brings
    them up, verifies they serve the same graph, and starts the
    heartbeat/hedge maintenance thread."""

    def __init__(self, transports: dict, config: RouterConfig | None = None):
        if not transports:
            raise ValueError("router needs at least one worker")
        self.config = config or RouterConfig()
        self._lock = threading.RLock()
        self.workers: dict[str, _WorkerState] = {
            wid: _WorkerState(wid, t) for wid, t in transports.items()
        }
        self._pending: dict[str, _Pending] = {}
        self._updates: dict[str, _UpdatePending] = {}
        self._epochs: list[_Epoch] = []
        self._epoch_by_token: dict[tuple, int] = {}
        self._compacted_to = 0
        self._rid_seq = itertools.count(1)
        self._hb_seq = itertools.count(1)
        self._mx_seq = itertools.count(1)
        self._tr_seq = itertools.count(1)
        self._update_seq = itertools.count(1)
        self._update_lock = threading.Lock()  # serializes broadcasts
        self._draining = False
        self._closed = threading.Event()
        self._maintenance: threading.Thread | None = None
        # firehose update queue (config.update_queue > 0): submissions
        # land here; the pump thread drains, coalesces, broadcasts.
        # Guarded by _uq_cv's lock (its own leaf lock — the pump must
        # be able to block for arrivals without holding _lock).
        self._uq_cv = threading.Condition()
        self._uq: list[tuple[dict, Future]] = []
        self._uq_pump: threading.Thread | None = None
        self.updates_coalesced = 0   # updates folded into fewer wires
        self.update_broadcasts = 0   # coalesced broadcasts sent
        self.update_backpressure = 0
        self.policy = None
        self.n = 0
        # counters (per-process registry; the router is one per process)
        reg = get_registry()
        self._m_requests = reg.counter(
            "dpathsim_router_requests_total",
            "router requests by outcome",
        )
        self._m_failovers = reg.counter(
            "dpathsim_router_failovers_total",
            "re-dispatches after worker death/stall/retriable failure",
        )
        self._m_hedges = reg.counter(
            "dpathsim_router_hedges_total", "hedged duplicate dispatches"
        ).labels()
        self._m_dups = reg.counter(
            "dpathsim_router_dup_responses_total",
            "late/duplicate worker responses dropped by request-id dedup",
        ).labels()
        self._m_fence_skips = reg.counter(
            "dpathsim_router_fence_skips_total",
            "routing decisions that skipped a fenced replica",
        ).labels()
        self._m_latency = reg.histogram(
            "dpathsim_router_request_seconds",
            "router submit-to-resolve latency by outcome",
        )
        # firehose plane: queue depth is the autoscale/backpressure
        # signal, the coalesce counters are the pipelining evidence
        self._m_uq_depth = reg.gauge(
            "dpathsim_update_queue_depth",
            "updates admitted but not yet broadcast",
        ).labels()
        self._m_uq_backpressure = reg.counter(
            "dpathsim_update_backpressure_total",
            "updates refused at the queue bound",
        ).labels()
        self._m_uq_coalesced = reg.counter(
            "dpathsim_updates_coalesced_total",
            "updates folded into a shared broadcast",
        ).labels()
        self._m_uq_group = reg.histogram(
            "dpathsim_update_group_size",
            "updates per coalesced broadcast",
            bounds=tuple(float(1 << i) for i in range(9)),
        ).labels()
        # -- fleet observability plane (DESIGN.md §24) ------------------
        # SLO engine over the merged metric stream; alerts surface as
        # counters/gauges (inside the engine) AND router log events
        # (the callback — obs cannot emit events itself, layering)
        specs = tuple(self.config.slo_specs) or default_specs()
        self.slo = SLOEngine(specs, on_alert=self._on_slo_alert)
        # tail-sampling flight recorder: slow threshold from config,
        # else the latency SLO's own p99 target — "slower than the SLO
        # says p99 may be" IS the tail worth keeping
        slow_ms = self.config.slow_ms
        if slow_ms is None:
            slow_ms = next(
                (s.threshold * 1e3 for s in specs
                 if s.kind == "latency" and s.threshold), 1000.0,
            )
        self._slow_s = float(slow_ms) / 1e3
        self.flight = FlightRecorder(self.config.flight_capacity)
        self._shutdown_dumped = False
        # optional shutdown artifact paths (set by the CLI): written
        # during drain, BEFORE workers terminate — a SIGTERM must not
        # destroy the evidence it should be dumping
        self.flight_out: str | None = None
        self.fleet_trace_out: str | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self, ready_timeout: float = 180.0) -> None:
        # membership is mutable under the lock (add/remove/reap_worker)
        # — snapshot the seed set; nothing else can mutate it before
        # start() returns, but the discipline is uniform
        with self._lock:
            seed = list(self.workers.values())
        for w in seed:
            w.transport.start(self._on_message, self._on_death)
        tokens = {}
        for w in seed:
            info = w.transport.wait_ready(ready_timeout)
            tokens[w.wid] = (info.get("base_fp"), int(info.get("delta_seq", 0)))
            w.token = tokens[w.wid]
            self.n = int(info.get("n", self.n))
        base = next(iter(tokens.values()))
        if any(t != base for t in tokens.values()):
            raise ValueError(
                f"workers disagree on the base graph: {tokens} — every "
                "replica must serve the same dataset/config"
            )
        # transports are live (reader threads deliver _on_message, which
        # touches the epoch log under the lock) — so hold it here too
        with self._lock:
            self._epochs.append(_Epoch(token=base))
            self._epoch_by_token[tuple(base)] = 0
        # pong clocks start NOW, not at construction: worker startup
        # (backend build + warmup) happens between __init__ and here,
        # and counting it as silence would mark every worker stalled
        # on the first probe
        now = time.monotonic()
        for w in seed:
            w.last_pong = now
        with self._lock:
            self._rebuild_policy()
        self._maintenance = threading.Thread(
            target=self._maintenance_loop, name="pathsim-router-maint",
            daemon=True,
        )
        self._maintenance.start()
        if self.config.update_queue > 0:
            self._uq_pump = threading.Thread(
                target=self._update_pump, name="pathsim-router-updates",
                daemon=True,
            )
            self._uq_pump.start()
        runtime_event(
            "router_ready", workers=len(seed), n=self.n,
            routing=self.config.routing, fingerprint=base[0],
        )

    def close(self) -> None:
        self._closed.set()
        with self._lock:
            targets = list(self.workers.values())
        for w in targets:
            w.transport.close()

    def drain(self) -> bool:
        """Graceful stop: reject new work, resolve everything pending,
        drain the workers. True if everything flushed in time."""
        with self._lock:
            self._draining = True
        deadline = time.monotonic() + self.config.drain_timeout_s
        clean = True
        with self._lock:
            # seed the accounting: a zero/negative drain timeout must
            # still report the LIVE backlog it abandons
            pending, updates = len(self._pending), len(self._updates)
        while time.monotonic() < deadline:
            with self._lock:
                pending, updates = len(self._pending), len(self._updates)
            with self._uq_cv:
                queued = len(self._uq)
            if not pending and not updates and not queued:
                break
            time.sleep(0.005)
        else:
            clean = False
        # shutdown dumps happen HERE — pending flushed, workers still
        # alive — because the flight/trace artifacts need one last
        # span-ring scrape, and a terminated worker can't answer it
        self._shutdown_dumps()
        with self._lock:
            targets = list(self.workers.values())
        for w in targets:
            if w.transport.alive:
                try:
                    w.transport.terminate()
                except Exception:
                    pass
        runtime_event(
            "router_drain", clean=clean, pending=pending, updates=updates,
        )
        return clean

    # -- submission --------------------------------------------------------

    def submit(self, req: dict) -> Future:
        """Admit one protocol request; returns a Future of the response
        dict. Raises :class:`RouterShed` at the admission bound."""
        op = req.get("op", "topk")
        fut: Future = Future()
        with self._lock:
            draining = self._draining
        if draining:
            fut.set_result({
                "id": req.get("id"), "ok": False, "error": "draining",
                "draining": True,
            })
            return fut
        if op == "ping":
            fut.set_result({"id": req.get("id"), "ok": True,
                            "result": {"pong": True}})
            return fut
        if op in ("stats", "health"):
            fut.set_result({"id": req.get("id"), "ok": True,
                            "result": self.stats()})
            return fut
        if op == "update":
            if self.config.update_queue > 0:
                return self._enqueue_update(req, fut)
            return self._submit_update(req, fut)
        if op == "invalidate":
            return self._submit_invalidate(req, fut)
        if op == "fleet_metrics":
            resp = {"id": req.get("id"), "ok": True,
                    "result": self.fleet_metrics(
                        refresh=bool(req.get("refresh", True))
                    )}
            if req.get("request_id") is not None:
                resp["request_id"] = req["request_id"]
            fut.set_result(resp)
            return fut
        if op == "flight_dump":
            resp = {"id": req.get("id"), "ok": True,
                    "result": self.flight_dump(path=req.get("path"))}
            if req.get("request_id") is not None:
                resp["request_id"] = req["request_id"]
            fut.set_result(resp)
            return fut
        if op not in ROUTED_OPS:
            fut.set_result({"id": req.get("id"), "ok": False,
                            "error": f"unknown op {op!r}"})
            return fut
        # the fleet-level trace ROOT: head sampling decides here, once,
        # for the whole fleet — workers inherit the decision on the
        # wire (a sampled-out root sends {"sampled": false} downstream)
        root = get_tracer().start_span(
            "router.request", op=op, row=req.get("row"),
        )
        with self._lock:
            if len(self._pending) >= self.config.max_inflight:
                self._m_requests.inc(outcome="shed")
                runtime_event(
                    "router_shed", depth=self.config.max_inflight,
                    echo=False,
                )
                get_tracer().finish(root, outcome="shed")
                self.flight.keep(
                    ["shed"],
                    trace_id=root.trace_id if root else None,
                    op=op, row=req.get("row"), where="admission",
                )
                raise RouterShed(
                    f"router pending table at bound "
                    f"({self.config.max_inflight})"
                )
            rid = f"r{next(self._rid_seq)}"
            row = req.get("row")
            row = int(row) if row is not None else None
            key = row if row is not None else str(
                req.get("source") or req.get("source_id") or ""
            )
            deadline = Deadline.from_ms(
                req.get("deadline_ms", self.config.default_deadline_ms)
            )
            p = _Pending(rid, req, key, row, fut, deadline, span=root)
            self._pending[rid] = p
        verdict = self._dispatch(p)
        if verdict is not None:
            self._park_or_fail(p, verdict)
        return fut

    def request(self, req: dict, timeout: float = 60.0) -> dict:
        """Synchronous convenience: submit + wait."""
        return self.submit(req).result(timeout=timeout)

    # -- routing -----------------------------------------------------------

    def _eligible(self, p: _Pending, exclude) -> tuple[str | None, str]:
        """Pick the next replica for ``p`` under the lock. Returns
        (worker_id, reason-if-none)."""
        saturated = fenced = exhausted = 0
        for wid in self.policy.preference(p.key):
            # the policy can briefly lag membership (reaped workers
            # stay in the last ring until a live set exists again) —
            # a missing id is simply not eligible
            w = self.workers.get(wid)
            if w is None or w.status != UP or not w.transport.alive:
                continue
            if wid in exclude:
                exhausted += 1  # alive, but this request already tried it
                continue
            if self._fenced(w, p.row):
                fenced += 1
                self._m_fence_skips.inc()
                continue
            if w.queue_depth >= self.config.worker_queue_limit:
                saturated += 1
                continue
            return wid, ""
        if saturated:
            return None, "saturated"
        if fenced:
            return None, "fenced"
        if exhausted:
            # every live replica already refused this request (shed /
            # transient failure): surface that, don't park — the client
            # retrying later IS the backoff
            return None, "exhausted"
        return None, "no live workers"

    def _fenced(self, w: _WorkerState, row: int | None) -> bool:
        """Is this replica forbidden from answering for ``row``? True
        when it missed a delta whose affected set could cover the query
        (unknown rows — label queries — only go to caught-up replicas
        while any fence is active)."""
        head = len(self._epochs) - 1
        if w.epoch >= head:
            return False
        for epoch in self._epochs[w.epoch + 1:]:
            if epoch.affected is None or row is None:
                return True
            if row in epoch.affected:
                return True
        return False

    def _dispatch(self, p: _Pending, exclude: set | None = None,
                  kind: str | None = None) -> str | None:
        """Send ``p`` to the best eligible replica. None on success, an
        error string when no replica can take it. ``kind`` labels the
        attempt span ("hedge" from the hedge scan; otherwise derived:
        first try = "primary", re-dispatch = "failover")."""
        exclude = set(exclude or ())
        tracer = get_tracer()
        while True:
            if p.deadline is not None and p.deadline.expired:
                return "deadline exceeded"
            with self._lock:
                if p.rid not in self._pending:
                    return None  # already resolved (late failover race)
                if len(p.tried) >= self.config.max_attempts:
                    return "max attempts exhausted"
                wid, why = self._eligible(p, exclude | set(p.tried))
                if wid is None:
                    return why
                w = self.workers[wid]
                attempt = None
                if p.span is not None:
                    # one span per dispatch ATTEMPT, all siblings under
                    # the request root: a hedged-then-failed-over
                    # request reads as three parallel subtrees, each
                    # continuing into its worker's process
                    attempt = tracer.start_span(
                        "router.dispatch", parent=p.span.context,
                        worker=wid,
                        kind=kind or (
                            "primary" if not p.tried else "failover"
                        ),
                        attempt=len(p.tried),
                    )
                    stale = p.attempt_spans.pop(wid, None)
                    tracer.finish(stale, outcome="superseded")
                    p.attempt_spans[wid] = attempt
                p.tried.append(wid)
                p.assigned.add(wid)
                w.assigned.add(p.rid)
            wire = dict(p.req)
            wire["id"] = p.rid
            wire["request_id"] = p.rid
            if p.deadline is not None:
                wire["deadline_ms"] = max(p.deadline.remaining_ms(), 0.0)
            if tracer.enabled:
                # the worker parents under THIS attempt's span; a
                # sampled-out request propagates the drop instead, so
                # the fleet-wide rate stays exactly the configured 1/N
                wire["trace"] = to_wire(
                    attempt.context if attempt is not None else None,
                    sampled=attempt is not None,
                )
            try:
                w.transport.send(wire)
                return None
            except WorkerGone:
                with self._lock:
                    p.assigned.discard(wid)
                    w.assigned.discard(p.rid)
                    tracer.finish(
                        p.attempt_spans.pop(wid, None),
                        outcome="send_failed",
                    )
                self._mark_down(wid, DOWN, "send failed")
                exclude.add(wid)

    # -- resolution --------------------------------------------------------

    def _resolve(self, p: _Pending, resp: dict) -> None:
        elapsed = time.monotonic() - p.t0
        client_resp = dict(resp)
        client_resp["id"] = p.req.get("id")
        client_resp["request_id"] = p.rid
        outcome = "ok" if resp.get("ok") else "error"
        if p.failovers:
            client_resp["failovers"] = p.failovers
        if p.hedge_sent:
            client_resp["hedged"] = True
        self._m_requests.inc(outcome=outcome)
        self._m_latency.observe(elapsed, outcome=outcome)
        # seal the trace: outstanding attempt spans (hedge losers, the
        # straggler a failover abandoned) finish as superseded, then
        # the root closes with the outcome — one complete tree per
        # request no matter how many replicas touched it
        tracer = get_tracer()
        with self._lock:
            attempts = list(p.attempt_spans.values())
            p.attempt_spans.clear()
        for span in attempts:
            tracer.finish(span, outcome="superseded")
        tracer.finish(p.span, outcome=outcome)
        # tail sampling: the flight recorder keeps EVERY request whose
        # outcome is worth explaining, independent of the head-sampling
        # coin flip (obs/flight.py — 100% of errored/shed/hedged/
        # failed-over/slow/ann-degraded requests, by construction)
        ann_fb = (resp.get("result") or {}).get("ann_fallback") \
            if isinstance(resp.get("result"), dict) else None
        reasons = []
        if outcome == "error":
            reasons.append("error")
        if resp.get("shed"):
            reasons.append("shed")
        if p.hedge_sent:
            reasons.append("hedged")
        if p.failovers:
            reasons.append("failover")
        if ann_fb is not None:
            reasons.append("ann_fallback")
        if elapsed > self._slow_s:
            reasons.append("slow")
        if reasons:
            self.flight.keep(
                reasons,
                trace_id=p.span.trace_id if p.span is not None else None,
                rid=p.rid, op=p.req.get("op", "topk"), row=p.row,
                elapsed_ms=round(elapsed * 1e3, 3),
                workers=list(p.tried), outcome=outcome,
                error=resp.get("error"), ann_fallback=ann_fb,
                failovers=p.failovers,
            )
        p.future.set_result(client_resp)

    def _park_or_fail(self, p: _Pending, verdict: str) -> None:
        """No replica can take ``p`` right now. Hard verdicts fail;
        saturation sheds (the ISSUE contract: when every replica is
        saturated the router says so immediately, it does not queue
        unboundedly); transient unavailability — every candidate
        suspected or fenced — PARKS the request for the maintenance
        loop to retry, because a worker coming back (pong) or catching
        up (delta replay) makes it dispatchable again."""
        if verdict in ("deadline exceeded", "max attempts exhausted"):
            self._fail(p, verdict)
            return
        if verdict == "saturated":
            self._fail(p, "all replicas saturated", shed=True)
            return
        if verdict == "exhausted":
            self._fail(p, "all replicas refused", shed=True)
            return
        with self._lock:
            recoverable = any(
                w.status in (UP, SUSPECT) and (
                    w.transport.alive or w.status == SUSPECT
                )
                for w in self.workers.values()
            )
            if recoverable and p.rid in self._pending:
                p.parked = True
                p.tried.clear()  # a resurrected replica gets a fresh try
                runtime_event("router_parked", rid=p.rid,
                              reason=verdict, echo=False)
                return
        self._fail(p, verdict)

    def _retry_parked(self, now: float) -> None:
        ready: list[_Pending] = []
        cfg = self.config
        with self._lock:
            for p in self._pending.values():
                if p.parked:
                    ready.append(p)
        for p in ready:
            if p.deadline is not None and p.deadline.expired:
                self._fail(p, "deadline exceeded")
                continue
            if (
                p.deadline is None
                and now - p.t0 > cfg.park_timeout_s
            ):
                self._fail(p, "no live workers")
                continue
            with self._lock:
                if p.rid not in self._pending:
                    continue
                p.parked = False
            verdict = self._dispatch(p)
            if verdict is not None:
                self._park_or_fail(p, verdict)

    def _fail(self, p: _Pending, error: str, **flags) -> None:
        with self._lock:
            if self._pending.pop(p.rid, None) is None:
                return
            for wid in p.assigned:
                self.workers[wid].assigned.discard(p.rid)
        resp = {"ok": False, "error": error, **flags}
        if error == "deadline exceeded":
            resp["deadline_exceeded"] = True
        if error in ("saturated", "shed"):
            resp["shed"] = True
        self._resolve(p, resp)

    def _on_message(self, wid: str, obj: dict) -> None:
        if "event" in obj:
            return  # ready/drained events: informational here
        rid = obj.get("id")
        if isinstance(rid, str) and rid.startswith("hb:"):
            self._on_pong(wid, obj)
            return
        if isinstance(rid, str) and rid.startswith("mx:"):
            self._on_metrics(wid, obj)
            return
        if isinstance(rid, str) and rid.startswith("tr:"):
            self._on_trace(wid, obj)
            return
        if isinstance(rid, str) and rid.startswith(("up:", "cu:")):
            self._on_update_ack(wid, rid, obj)
            return
        if isinstance(rid, str) and rid.startswith("inv:"):
            return  # broadcast invalidate ack: fire-and-forget

        with self._lock:
            p = self._pending.get(rid) if isinstance(rid, str) else None
            if p is not None and obj.get("ok"):
                del self._pending[rid]
                for awid in p.assigned:
                    self.workers[awid].assigned.discard(rid)
                # the winning attempt closes with the answer; the
                # losers are sealed as superseded inside _resolve
                get_tracer().finish(
                    p.attempt_spans.pop(wid, None), outcome="ok"
                )
        if p is None:
            # hedge loser, or a stall-suspected worker answering after
            # its work was already failed over — dedup: drop + count
            self._m_dups.inc()
            return
        if obj.get("ok"):
            self._resolve(p, obj)
            return
        # failed response: reroute retriable failures, surface the rest
        retriable = bool(
            obj.get("shed") or obj.get("draining") or obj.get("transient")
        ) and not obj.get("deadline_exceeded")
        if not retriable:
            with self._lock:
                if self._pending.pop(p.rid, None) is None:
                    return
                for awid in p.assigned:
                    self.workers[awid].assigned.discard(p.rid)
            self._resolve(p, obj)
            return
        with self._lock:
            p.assigned.discard(wid)
            self.workers[wid].assigned.discard(p.rid)
            get_tracer().finish(
                p.attempt_spans.pop(wid, None), outcome="worker_error"
            )
            if p.assigned:
                return  # a hedge is still in flight; let it race
        p.failovers += 1
        self._m_failovers.inc(reason="worker_error")
        verdict = self._dispatch(p)
        if verdict is not None:
            self._park_or_fail(p, verdict)

    def _on_death(self, wid: str, reason: str) -> None:
        self._mark_down(wid, DOWN, reason)

    def _mark_down(self, wid: str, status: str, reason: str) -> None:
        with self._lock:
            w = self.workers.get(wid)
            if w is None or w.status == DOWN:
                return
            if w.status == status:
                return
            w.status = status
            orphans = [
                self._pending[rid]
                for rid in w.assigned
                if rid in self._pending
            ]
            w.assigned.clear()
            for p in orphans:
                p.assigned.discard(wid)
                get_tracer().finish(
                    p.attempt_spans.pop(wid, None), outcome="worker_down"
                )
        runtime_event(
            "router_worker_down", worker_id=wid, status=status,
            reason=reason, orphaned=len(orphans),
        )
        get_registry().counter(
            "dpathsim_router_worker_down_total",
            "workers marked down/suspect, by cause",
        ).inc(status=status)
        for p in orphans:
            with self._lock:
                if p.rid not in self._pending or p.assigned:
                    continue  # resolved meanwhile, or hedged elsewhere
            p.failovers += 1
            self._m_failovers.inc(reason=reason.split(" ")[0] or "death")
            verdict = self._dispatch(p)
            if verdict is not None:
                self._park_or_fail(p, verdict)

    # -- heartbeats, stall detection, hedging ------------------------------

    def _maintenance_loop(self) -> None:
        cfg = self.config
        interval = cfg.heartbeat_interval_s
        hedge_s = (cfg.hedge_ms / 1e3) if cfg.hedge_ms else None
        tick = min(interval, (hedge_s / 4) if hedge_s else interval)
        tick = max(tick, 0.005)
        next_probe = 0.0
        next_scrape = 0.0
        while not self._closed.wait(tick):
            now = time.monotonic()
            if now >= next_probe:
                next_probe = now + interval
                self._probe_workers(now)
            if cfg.scrape_interval_s and now >= next_scrape:
                next_scrape = now + cfg.scrape_interval_s
                # merge + SLO first, over the PREVIOUS round's replies
                # (a scrape is async — evaluating right after sending
                # would always read stale-by-one snapshots anyway, and
                # this way one tick is one coherent evaluate-then-ask)
                try:
                    self._evaluate_slo(now)
                except Exception as exc:
                    runtime_event("fleet_slo_error", error=repr(exc))
                self._scrape_workers()
            if hedge_s is not None:
                self._hedge_scan(now, hedge_s)
            self._retry_parked(now)
            self._sweep_updates(now)

    def _probe_workers(self, now: float) -> None:
        cfg = self.config
        with self._lock:
            targets = list(self.workers.values())
        for w in targets:
            if w.status == DOWN or not w.transport.alive:
                continue
            try:
                # the heartbeat seam: an injected error here is a probe
                # that never happened — enough of them and a healthy
                # worker goes SUSPECT (and comes back at the next pong)
                inject.fire("heartbeat")
                w.transport.send(
                    {"id": f"hb:{w.wid}:{next(self._hb_seq)}",
                     "op": "health"}
                )
            except inject.InjectedFault:
                pass
            except WorkerGone:
                self._mark_down(w.wid, DOWN, "heartbeat send failed")
                continue
            silence = now - w.last_pong
            if (
                w.status == UP
                and silence > cfg.heartbeat_interval_s * cfg.heartbeat_miss_limit
            ):
                self._mark_down(
                    w.wid, SUSPECT,
                    f"stall {silence * 1e3:.0f}ms without pong",
                )

    def _on_pong(self, wid: str, obj: dict) -> None:
        if not obj.get("ok"):
            return
        result = obj.get("result") or {}
        token = (result.get("base_fp"), int(result.get("delta_seq", 0)))
        catchup_from = None
        with self._lock:
            w = self.workers.get(wid)
            if w is None or w.status == DOWN:
                return
            w.last_pong = time.monotonic()
            w.queue_depth = int(result.get("queue_depth", 0))
            w.token = token
            w.last_health = result
            w.pong_seq += 1
            if w.status == SUSPECT:
                # the stall cleared: readmit (its in-flight work was
                # already failed over; dedup absorbs any late answers)
                w.status = UP
                runtime_event("router_worker_up", worker_id=wid,
                              echo=False)
            epoch = self._epoch_of(token)
            if epoch is None:
                # a token outside our history: divergent replica —
                # fence it from everything (epoch −1 predates epoch 0)
                w.epoch = -1
            else:
                w.epoch = max(w.epoch, epoch)
            if (
                w.epoch < len(self._epochs) - 1
                and not w.catchup_active
            ):
                w.catchup_active = True
                catchup_from = w.epoch + 1
            self._compact_epochs()
        if catchup_from is not None:
            self._send_catchup(wid, catchup_from)

    def _epoch_of(self, token) -> int | None:
        return self._epoch_by_token.get(tuple(token))

    def _compact_epochs(self) -> None:
        """Drop the replay payload (and affected set) of epochs every
        live replica has passed — called under the lock after an epoch
        advance. Without this a long-lived router retains every delta's
        full edge lists forever. Compacted entries keep their token
        (the epoch index must stay stable) with ``affected=None``,
        which only a divergent (epoch −1) replica would ever consult —
        and None means "all rows", exactly the conservative fence such
        a replica already gets."""
        if self.config.retain_replay:
            # autoscale mode: a spawned worker boots the base graph
            # (epoch 0) and catches up by replaying the WHOLE chain —
            # compacting any payload would strand it fenced forever
            return
        live = [
            w.epoch for w in self.workers.values()
            if w.status != DOWN and w.epoch >= 0
        ]
        if not live:
            return
        horizon = min(live)
        for i in range(max(self._compacted_to, 1), horizon + 1):
            self._epochs[i].wire_req = None
            self._epochs[i].affected = None
        self._compacted_to = max(self._compacted_to, horizon + 1)

    def _hedge_scan(self, now: float, hedge_s: float) -> None:
        stragglers: list[_Pending] = []
        with self._lock:
            for p in self._pending.values():
                if p.hedged or (now - p.t0) < hedge_s:
                    continue
                if p.deadline is not None and p.deadline.expired:
                    continue
                if len(p.assigned) != 1:
                    continue
                p.hedged = True  # one hedge attempt per request
                stragglers.append(p)
        for p in stragglers:
            # a failed hedge dispatch is not a request failure — the
            # original is still in flight; only a hedge that actually
            # went out is counted and flagged (a 1-replica router must
            # not fabricate hedge accounting)
            if self._dispatch(
                p, exclude=set(p.tried), kind="hedge"
            ) is None and (
                len(p.assigned) > 1
            ):
                p.hedge_sent = True
                self._m_hedges.inc()
                runtime_event(
                    "router_hedge", rid=p.rid, row=p.row,
                    waited_ms=round((now - p.t0) * 1e3, 1), echo=False,
                )

    # -- delta broadcast & fencing -----------------------------------------

    def _submit_update(self, req: dict, fut: Future) -> Future:
        tracer = get_tracer()
        with self._update_lock:
            seq = next(self._update_seq)
            urid = f"u{seq}"
            wire = dict(req)
            wire["request_id"] = urid
            wire["want_rows"] = True
            wire.pop("id", None)  # per-worker ids are stamped per send
            root = tracer.start_span("router.update", rid=urid)
            with self._lock:
                targets = [
                    w for w in self.workers.values()
                    if w.status == UP and w.transport.alive
                ]
                if not targets:
                    tracer.finish(root, outcome="no_workers")
                    fut.set_result({"id": req.get("id"), "ok": False,
                                    "error": "no live workers"})
                    return fut
                up = _UpdatePending(
                    urid, req.get("id"), fut, [w.wid for w in targets],
                    wire, span=root,
                )
                self._updates[urid] = up
            for w in targets:
                per_wire = dict(wire)
                per_wire["id"] = f"up:{w.wid}:{seq}"
                if root is not None:
                    # one broadcast span per replica, the wire carrying
                    # its context: every replica's delta application
                    # stitches under the ONE router.update tree (and a
                    # background ann refresh it schedules links back
                    # to its serve.op span — obs/trace.py)
                    bspan = tracer.start_span(
                        "router.broadcast", parent=root.context,
                        worker=w.wid,
                    )
                    up.target_spans[w.wid] = bspan
                    per_wire["trace"] = to_wire(bspan.context)
                elif tracer.enabled:
                    per_wire["trace"] = to_wire(None, sampled=False)
                try:
                    # the delta_broadcast seam: an injected error means
                    # THIS worker misses the update — it will lag the
                    # token head and be fenced until catch-up
                    inject.fire("delta_broadcast")
                    w.transport.send(per_wire)
                except (inject.InjectedFault, WorkerGone) as exc:
                    self._update_failure(urid, w.wid, repr(exc))
        return fut

    # -- firehose update pipelining (router/firehose.py) -------------------

    def _enqueue_update(self, req: dict, fut: Future) -> Future:
        """Bounded admission for the firehose path: queue the update
        for the coalescing pump, or refuse immediately with a
        ``backpressure`` error — the update-side twin of query shed."""
        with self._uq_cv:
            if self._closed.is_set():
                fut.set_result({
                    "id": req.get("id"), "ok": False,
                    "error": "draining", "draining": True,
                })
                return fut
            if len(self._uq) >= self.config.update_queue:
                self.update_backpressure += 1
                self._m_uq_backpressure.inc()
                runtime_event(
                    "router_update_backpressure",
                    depth=self.config.update_queue, echo=False,
                )
                fut.set_result({
                    "id": req.get("id"), "ok": False,
                    "error": "update queue full",
                    "backpressure": True, "shed": True,
                })
                return fut
            self._uq.append((req, fut))
            self._m_uq_depth.set(len(self._uq))
            self._uq_cv.notify()
        return fut

    def _update_pump(self) -> None:
        """Drain → coalesce → broadcast, strictly in admission order.
        One pump thread per router, so coalesced broadcasts stay
        totally ordered (a delta chain applied out of order is a
        different graph)."""
        from .firehose import coalesce_update_groups

        flush_s = max(self.config.update_flush_ms, 0.0) / 1e3
        while not self._closed.is_set():
            with self._uq_cv:
                while not self._uq and not self._closed.is_set():
                    self._uq_cv.wait(0.2)
                if self._closed.is_set():
                    break
            if flush_s:
                time.sleep(flush_s)  # linger: let the window fill
            with self._uq_cv:
                batch = self._uq[:]
                del self._uq[:]
                self._m_uq_depth.set(0)
            if not batch:
                continue
            reqs = [r for r, _f in batch]
            futs = {id(r): f for r, f in batch}
            for group in coalesce_update_groups(
                reqs, max(self.config.update_coalesce, 1)
            ):
                self._broadcast_group(group, futs)
        # shutdown: whatever is still queued (enqueued mid-iteration,
        # or arriving between close() and the enqueue-side closed
        # check) must be resolved, never left hanging a caller's
        # fut.result()
        with self._uq_cv:
            leftover = self._uq[:]
            del self._uq[:]
            self._m_uq_depth.set(0)
        for req, fut in leftover:
            if not fut.done():
                fut.set_result({
                    "id": req.get("id"), "ok": False,
                    "error": "draining", "draining": True,
                })

    def _broadcast_group(self, group, futs: dict) -> None:
        """One coalesced broadcast; resolves every member future. A
        merged window the workers reject wholesale (e.g. an id/row
        aliased edge pair the record-level fold could not cancel)
        falls back to sequential per-member broadcasts — coalescing is
        a throughput optimization and must never fail an update the
        sequential path would have applied."""
        n = len(group.members)
        self.update_broadcasts += 1
        self._m_uq_group.observe(n)
        if n > 1:
            self.updates_coalesced += n
            self._m_uq_coalesced.inc(n)

        def broadcast_one(wire_req: dict) -> dict:
            inner: Future = Future()
            self._submit_update(dict(wire_req), inner)
            try:
                return inner.result(
                    timeout=self.config.update_timeout_s + 5.0
                )
            except Exception as exc:  # timeout: surface, don't hang
                return {"ok": False, "error": repr(exc)}

        resp = broadcast_one(group.merged_wire) if n > 1 else (
            broadcast_one(group.members[0])
        )
        if n > 1 and not resp.get("ok"):
            # fall back to sequential members ONLY on deterministic
            # wholesale rejection (every replica answered with an
            # error). An ack TIMEOUT is ambiguous — a slow replica may
            # yet apply the merge, and re-broadcasting members on top
            # would double-apply and fork its token off the epoch
            # history; surface the failure to the members instead.
            missed = (resp.get("detail") or {}).get("missed") or {}
            ambiguous = not missed or any(
                "timeout" in str(v) for v in missed.values()
            )
            if not ambiguous:
                runtime_event(
                    "router_coalesce_fallback", members=n,
                    error=str(resp.get("error", "?")),
                )
                for req in group.members:
                    r = broadcast_one(req)
                    fut = futs.get(id(req))
                    if fut is not None and not fut.done():
                        fut.set_result({**r, "id": req.get("id")})
                return
        for req in group.members:
            fut = futs.get(id(req))
            if fut is not None and not fut.done():
                out = dict(resp)
                out["id"] = req.get("id")
                if n > 1:
                    out["coalesced"] = n
                fut.set_result(out)

    def _on_update_ack(self, wid: str, rid: str, obj: dict) -> None:
        """An ``update`` response — from the broadcast (``up:``) or a
        catch-up replay (``cu:``). Either way the ack's token tells us
        where this replica now stands in the epoch log."""
        urid = f"u{rid.rsplit(':', 1)[1]}"
        is_catchup = rid.startswith("cu:")
        if not obj.get("ok"):
            if is_catchup:
                with self._lock:
                    w = self.workers.get(wid)
                    if w is not None:
                        # drop the in-progress flag: the next pong
                        # showing lag retries the replay
                        w.catchup_active = False
                runtime_event(
                    "router_catchup_failed", worker_id=wid, rid=urid,
                    error=obj.get("error", "?"),
                )
            else:
                self._update_failure(urid, wid, obj.get("error", "?"))
            return
        result = obj.get("result") or {}
        token = (result.get("base_fp"), int(result.get("delta_seq", 0)))
        finished = None
        next_catchup = None
        with self._lock:
            up = self._updates.get(urid)
            if up is not None:
                if up.epoch_index is None:
                    # first ack defines the epoch: its token and
                    # affected set (None = rebuild = all rows). Later
                    # acks must agree — replicas are deterministic.
                    affected = result.get("affected_row_list")
                    self._epochs.append(_Epoch(
                        token=token,
                        wire_req=up.wire,
                        affected=(
                            frozenset(affected) if affected is not None
                            else None
                        ),
                        rid=urid,
                    ))
                    up.epoch_index = len(self._epochs) - 1
                    self._epoch_by_token[tuple(token)] = up.epoch_index
                    up.first_result = result
                elif tuple(token) != self._epochs[up.epoch_index].token:
                    runtime_event(
                        "router_token_divergence", worker_id=wid,
                        got=token,
                        expected=self._epochs[up.epoch_index].token,
                    )
            w = self.workers.get(wid)
            if w is not None:
                epoch = self._epoch_of(token)
                w.token = token
                w.epoch = epoch if epoch is not None else -1
                if is_catchup:
                    if 0 <= w.epoch < len(self._epochs) - 1:
                        next_catchup = w.epoch + 1  # keep replaying
                    else:
                        w.catchup_active = False
            if up is not None:
                up.waiting.discard(wid)
                up.acks[wid] = result
                get_tracer().finish(
                    up.target_spans.pop(wid, None), outcome="ack"
                )
                # a replica that missed the broadcast but caught up
                # before the update finished has APPLIED it — it must
                # not be reported as both applied and lagging
                up.failures.pop(wid, None)
                if not up.waiting:
                    finished = self._updates.pop(urid)
            self._compact_epochs()
        if next_catchup is not None:
            self._send_catchup(wid, next_catchup)
        if finished is not None:
            self._finish_update(finished)

    def _update_failure(self, urid: str, wid: str, error: str) -> None:
        finished = None
        with self._lock:
            up = self._updates.get(urid)
            if up is None:
                return
            up.waiting.discard(wid)
            up.failures[wid] = error
            get_tracer().finish(
                up.target_spans.pop(wid, None), outcome="missed",
                error=error,
            )
            if not up.waiting:
                finished = self._updates.pop(urid)
        runtime_event(
            "router_update_miss", worker_id=wid, rid=urid, error=error,
        )
        if finished is not None:
            self._finish_update(finished)

    def _finish_update(self, up: _UpdatePending) -> None:
        ok = up.epoch_index is not None
        tracer = get_tracer()
        for span in up.target_spans.values():
            tracer.finish(span, outcome="timeout")
        up.target_spans.clear()
        tracer.finish(up.span, outcome="ok" if ok else "failed")
        result = {
            "applied": sorted(up.acks),
            "missed": dict(up.failures),
            "lagging": sorted(up.failures),
        }
        if up.first_result is not None:
            result.update({
                k: up.first_result[k]
                for k in ("mode", "affected_rows", "delta_seq", "base_fp",
                          "fingerprint", "n")
                if k in up.first_result
            })
        runtime_event(
            "router_update", rid=up.rid, applied=len(up.acks),
            missed=len(up.failures), echo=False,
        )
        up.future.set_result({
            "id": up.client_id, "ok": ok,
            **({"result": result} if ok else
               {"error": "update applied on no replica", "detail": result}),
        })

    def _sweep_updates(self, now: float) -> None:
        expired: list[_UpdatePending] = []
        with self._lock:
            for urid, up in list(self._updates.items()):
                if now - up.t0 > self.config.update_timeout_s:
                    for wid in list(up.waiting):
                        up.failures[wid] = "ack timeout"
                    up.waiting.clear()
                    expired.append(self._updates.pop(urid))
        for up in expired:
            self._finish_update(up)

    def _send_catchup(self, wid: str, from_epoch: int) -> None:
        """Replay the FIRST missed update to a lagging replica; its ack
        advances the epoch and triggers the next replay (ordered — a
        delta chain applied out of order is a different graph)."""
        with self._lock:
            w = self.workers.get(wid)
            if w is None or w.status != UP:
                if w is not None:
                    w.catchup_active = False
                return
            if from_epoch >= len(self._epochs) or from_epoch < 1:
                w.catchup_active = False
                return
            epoch = self._epochs[from_epoch]
            if epoch.wire_req is None:
                # nothing replayable (shouldn't happen: every epoch > 0
                # records its wire request) — leave the replica fenced
                w.catchup_active = False
                runtime_event(
                    "router_catchup_impossible", worker_id=wid,
                    epoch=from_epoch,
                )
                return
            wire = dict(epoch.wire_req)
            wire["id"] = f"cu:{wid}:{epoch.rid[1:]}"
        runtime_event(
            "router_catchup", worker_id=wid, epoch=from_epoch,
            rid=epoch.rid, echo=False,
        )
        try:
            w.transport.send(wire)
        except WorkerGone:
            self._mark_down(wid, DOWN, "catchup send failed")

    def _submit_invalidate(self, req: dict, fut: Future) -> Future:
        acked = 0
        with self._lock:
            targets = list(self.workers.values())
        for w in targets:
            if w.status != UP or not w.transport.alive:
                continue
            try:
                w.transport.send({
                    "id": f"inv:{w.wid}", "op": "invalidate",
                })
                acked += 1
            except WorkerGone:
                self._mark_down(w.wid, DOWN, "send failed")
        fut.set_result({
            "id": req.get("id"), "ok": True,
            "result": {"invalidated": True, "workers": acked},
        })
        return fut

    # -- dynamic membership (router/autoscale.py, DESIGN.md §30) -----------

    def _rebuild_policy(self) -> None:
        """Re-derive the routing policy over the CURRENT live set —
        caller holds the lock. Hash-ring membership changes move some
        rows' affinity (those rows re-warm on their new replica); the
        fencing/failover machinery is membership-agnostic."""
        live = [
            wid for wid, w in self.workers.items()
            if w.status not in (DOWN, DRAINING)
        ]
        if live:
            self.policy = make_policy(
                self.config.routing, live, n_rows=max(self.n, 1),
                vnodes=self.config.vnodes,
            )

    def add_worker(self, wid: str, transport,
                   ready_timeout: float = 180.0) -> dict:
        """Bring one NEW replica into the live fleet (the autoscale
        spawn primitive): start its transport, wait for ready, verify
        it serves a token from our epoch history (a fresh boot is
        epoch 0 — the base graph), register it, and rebuild the
        routing policy. The worker's first pong triggers the ordered
        catch-up replay of every missed epoch (idempotent by request
        id), and it stays fenced from affected rows until caught up —
        spawning can never serve stale data, only warm up."""
        transport.start(self._on_message, self._on_death)
        info = transport.wait_ready(ready_timeout)
        token = (info.get("base_fp"), int(info.get("delta_seq", 0)))
        with self._lock:
            if wid in self.workers:
                raise ValueError(f"worker id {wid!r} already registered")
            epoch = self._epoch_of(token)
            if epoch is None:
                raise ValueError(
                    f"spawned worker {wid} serves token {token} outside "
                    "this router's epoch history — wrong dataset/config"
                )
            w = _WorkerState(wid, transport)
            w.token = token
            w.epoch = epoch
            w.last_pong = time.monotonic()
            self.workers[wid] = w
            self._rebuild_policy()
            lag = len(self._epochs) - 1 - epoch
        runtime_event(
            "router_worker_added", worker_id=wid, epoch=epoch, lag=lag,
        )
        get_registry().counter(
            "dpathsim_autoscale_workers_added_total",
            "workers spawned into the live fleet",
        ).inc()
        return info

    def remove_worker(self, wid: str) -> bool:
        """Begin a graceful drain of one replica (the autoscale drain
        primitive): mark it DRAINING (routed around from this instant,
        never readmitted), rebuild the policy, and request the drain —
        SIGTERM for subprocess transports, the in-band ``drain`` op
        in-proc. In-flight work completes (new queries get retriable
        ``draining`` errors the failover path reroutes); the clean
        exit surfaces as transport death, after which
        :meth:`reap_workers` removes the entry."""
        with self._lock:
            w = self.workers.get(wid)
            if w is None or w.status in (DOWN, DRAINING):
                return False
            w.status = DRAINING
            self._rebuild_policy()
        runtime_event("router_worker_draining", worker_id=wid)
        get_registry().counter(
            "dpathsim_autoscale_workers_drained_total",
            "workers drained out of the live fleet",
        ).inc()
        try:
            w.transport.terminate()
        except Exception:
            pass  # already dead: on_death handles the bookkeeping
        return True

    def reap_workers(self) -> list[str]:
        """Drop DOWN workers whose transports are gone (drained or
        dead) from the table. Autoscale calls this per tick so cycled
        worker ids don't accumulate; chaos benches that never reap
        keep their post-mortem state, unchanged."""
        reaped = []
        with self._lock:
            for wid, w in list(self.workers.items()):
                if w.status == DOWN and not w.transport.alive:
                    del self.workers[wid]
                    reaped.append(wid)
            if reaped:
                self._rebuild_policy()
        for wid in reaped:
            runtime_event("router_worker_reaped", worker_id=wid,
                          echo=False)
        return reaped

    # -- fleet observability plane (DESIGN.md §24) -------------------------

    def _scrape_workers(self) -> None:
        """Ask every live worker for its registry snapshot (the
        ``metrics`` op); replies land in :meth:`_on_metrics`. Send
        failures are the heartbeat path's business — here they are
        simply skipped (the merge uses whatever snapshots exist)."""
        with self._lock:
            targets = list(self.workers.values())
        for w in targets:
            if w.status == DOWN or not w.transport.alive:
                continue
            try:
                w.transport.send(
                    {"id": f"mx:{w.wid}:{next(self._mx_seq)}",
                     "op": "metrics"}
                )
            except WorkerGone:
                continue

    def _on_metrics(self, wid: str, obj: dict) -> None:
        if not obj.get("ok"):
            return
        result = obj.get("result") or {}
        registry = result.get("registry")
        if not isinstance(registry, dict):
            return
        with self._lock:
            w = self.workers.get(wid)
            if w is None:
                return
            w.last_metrics = registry
            w.metrics_seq += 1
            w.metrics_mono = time.monotonic()

    def _on_trace(self, wid: str, obj: dict) -> None:
        if not obj.get("ok"):
            return
        result = obj.get("result") or {}
        if "spans" not in result:
            return
        with self._lock:
            w = self.workers.get(wid)
            if w is None:
                return
            w.trace_part = {**result, "process": f"worker {wid}"}
            w.trace_seq += 1

    def metric_parts(self) -> dict:
        """The merge inputs: the router's own registry plus every
        worker's last scraped snapshot, keyed by identity. A worker
        never scraped (or dead before its first scrape) simply isn't a
        part — the merge is exact over what exists."""
        parts = {"router": get_registry().snapshot()}
        with self._lock:
            for wid, w in self.workers.items():
                if w.last_metrics is not None:
                    parts[wid] = w.last_metrics
        return parts

    def _evaluate_slo(self, now: float) -> None:
        merged, _ = obs_fleet.merge_registry_snapshots(self.metric_parts())
        self.slo.observe(merged, now)

    def _on_slo_alert(self, info: dict) -> None:
        # the router LOG surface the ISSUE asks for: burn-rate alerts
        # as structured events alongside the engine's counters/gauges
        runtime_event(
            "slo_alert", slo=info["slo"], kind=info["kind"],
            objective=info["objective"],
            burn={k: round(v, 3) for k, v in info["burn"].items()},
        )

    def _wait_scraped(self, seq0: dict, attr: str, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                done = all(
                    w.status == DOWN or not w.transport.alive
                    or getattr(w, attr) > seq0.get(wid, 0)
                    for wid, w in self.workers.items()
                )
            if done:
                return
            time.sleep(0.005)

    def fleet_metrics(self, refresh: bool = True,
                      timeout: float = 5.0) -> dict:
        """The ``fleet_metrics`` op payload: merged (bucket-exact)
        registries with per-worker snapshots' provenance, SLO status,
        and the router's own stats block. ``refresh`` forces a fresh
        scrape round and waits for it — a one-shot ``dpathsim
        fleet-stats`` must not read a snapshot that predates the
        question."""
        if refresh:
            with self._lock:
                seq0 = {w.wid: w.metrics_seq
                        for w in self.workers.values()}
            self._scrape_workers()
            self._wait_scraped(seq0, "metrics_seq", timeout)
        parts = self.metric_parts()
        merged, unmergeable = obs_fleet.merge_registry_snapshots(parts)
        now = time.monotonic()
        with self._lock:
            scrape_age = {
                wid: (
                    round(now - w.metrics_mono, 3)
                    if w.last_metrics is not None else None
                )
                for wid, w in self.workers.items()
            }
        return {
            "router": self.stats()["router"],
            "merged": merged,
            "unmergeable": unmergeable,
            "scrape_age_s": scrape_age,
            "workers_scraped": sorted(k for k in parts if k != "router"),
            "slo": self.slo.snapshot(),
            "flight": {
                "kept_total": self.flight.kept_total,
                "dropped": self.flight.dropped,
                "capacity": self.flight.capacity,
            },
        }

    def collect_trace_parts(self, timeout: float = 5.0) -> list[dict]:
        """The stitched-export inputs: this process's span ring plus a
        fresh ``trace``-op scrape of every live worker's. Dead workers
        contribute whatever their last scrape caught (a SIGKILL takes
        its un-scraped spans with it — the router-side attempt spans
        still record that the dispatch happened)."""
        with self._lock:
            seq0 = {w.wid: w.trace_seq for w in self.workers.values()}
            targets = list(self.workers.values())
        limit = self.config.trace_scrape_limit
        for w in targets:
            if w.status == DOWN or not w.transport.alive:
                continue
            try:
                w.transport.send(
                    {"id": f"tr:{w.wid}:{next(self._tr_seq)}",
                     "op": "trace", "limit": limit}
                )
            except WorkerGone:
                continue
        self._wait_scraped(seq0, "trace_seq", timeout)
        parts = [{**get_tracer().export_state(limit=limit),
                  "process": "router"}]
        with self._lock:
            for w in self.workers.values():
                if w.trace_part is not None:
                    parts.append(w.trace_part)
        return parts

    def write_fleet_trace(self, path: str,
                          parts: list[dict] | None = None) -> int:
        """One stitched Perfetto file for the whole fleet; returns the
        span-event count. ``parts`` reuses an already-collected scrape
        (the shutdown path shares one round across both dumps)."""
        if parts is None:
            parts = self.collect_trace_parts()
        n = obs_fleet.write_fleet_trace(path, parts)
        runtime_event("fleet_trace_written", path=path, spans=n)
        return n

    def flight_dump(self, path: str | None = None,
                    parts: list[dict] | None = None) -> dict:
        """The ``flight_dump`` op: records + kept span trees, written
        atomically when ``path`` is given, inline (records only — span
        trees can be arbitrarily large) otherwise."""
        if path is None:
            return self.flight.snapshot()
        if parts is None:
            parts = (
                self.collect_trace_parts()
                if get_tracer().enabled else []
            )
        info = self.flight.dump(path, parts)
        runtime_event("flight_dump", **info)
        return info

    def _shutdown_dumps(self) -> None:
        """Drain-time artifacts (flight recording, stitched trace) —
        once, best-effort: a failing dump must not block the drain.
        ONE span-ring scrape feeds both dumps; each worker's ring is a
        trace-op round trip of up to 20k spans, not something to ask
        for twice at shutdown."""
        if self._shutdown_dumped:
            return
        self._shutdown_dumped = True
        try:
            parts = None
            if (self.flight_out or self.fleet_trace_out) and (
                get_tracer().enabled
            ):
                parts = self.collect_trace_parts()
            if self.flight_out:
                self.flight_dump(self.flight_out, parts=parts or [])
            if self.fleet_trace_out:
                self.write_fleet_trace(
                    self.fleet_trace_out, parts=parts or []
                )
        except Exception as exc:
            runtime_event("fleet_dump_failed", error=repr(exc))

    # -- introspection -----------------------------------------------------

    def worker_health(self, wid: str, timeout: float = 10.0) -> dict:
        """A FRESH health snapshot from one worker: probe, wait for the
        pong (benches read compile counts around a measurement window,
        so a cached pong from before the window is not good enough)."""
        with self._lock:
            w = self.workers.get(wid)
            if w is None or w.status == DOWN:
                return {}
            seq0 = w.pong_seq
        try:
            w.transport.send(
                {"id": f"hb:{wid}:{next(self._hb_seq)}", "op": "health"}
            )
        except WorkerGone:
            return {}
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if w.pong_seq > seq0:
                    return dict(w.last_health)
            time.sleep(0.005)
        return {}

    def stats(self) -> dict:
        with self._uq_cv:
            queued = len(self._uq)
        with self._lock:
            head = len(self._epochs) - 1
            return {
                "router": {
                    "workers": {
                        w.wid: {
                            "status": w.status,
                            "queue_depth": w.queue_depth,
                            "assigned": len(w.assigned),
                            "epoch": w.epoch,
                            "lag": head - w.epoch,
                            "token": list(w.token) if w.token else None,
                            # ANN index epoch from the last pong (None =
                            # exact-only replica): operators see which
                            # replicas hold a fresh candidate index;
                            # queries never NEED one — an ann request on
                            # an index-less replica answers exactly
                            "index": w.last_health.get("index"),
                            # per-mode index-epoch map (generalizes
                            # the ANN-only key above): exact / ann /
                            # learned, each with its own epoch — a
                            # learned request re-dispatched onto a
                            # tower-less replica still answers, exactly
                            "modes": w.last_health.get("modes"),
                        }
                        for w in self.workers.values()
                    },
                    "pending": len(self._pending),
                    "updates_pending": len(self._updates),
                    "epochs": head + 1,
                    "routing": self.config.routing,
                    "draining": self._draining,
                    "n": self.n,
                    # firehose pipelining accounting (DESIGN.md §30)
                    "firehose": {
                        "update_queue": self.config.update_queue,
                        "queued": queued,
                        "coalesced": self.updates_coalesced,
                        "broadcasts": self.update_broadcasts,
                        "backpressure": self.update_backpressure,
                    },
                    "obs": {
                        "slo_alerts": dict(self.slo.alert_counts),
                        "flight_kept": self.flight.kept_total,
                        "flight_dropped": self.flight.dropped,
                        "scrape_interval_s":
                            self.config.scrape_interval_s,
                    },
                },
            }
