"""Closed-loop fleet autoscale: load signals drive spawn/drain.

PR 6 gave the fleet the two primitives this module composes — a worker
can be drained gracefully (SIGTERM / in-band drain: in-flight work
completes, new work reroutes) and a lagging replica catches up by
ordered idempotent replay of the epoch log. What was missing is the
loop: nobody *decided* to spawn or drain. The :class:`Autoscaler`
closes it (ROADMAP item 3, DESIGN.md §30):

- **Signals**, evaluated per tick from the router's own state: mean
  worker queue depth (the pongs' load signal, as a fraction of the
  per-replica saturation bound), query+update shed deltas (admission
  already refused work — capacity is definitionally short), and the
  PR-9 SLO engine's burn status (an objective actively burning its
  error budget).
- **Hysteresis**: scale up after ``up_consecutive`` consecutive high
  ticks, down after ``down_consecutive`` consecutive low ticks, with a
  cooldown after every action — measured in *ticks*, so the decision
  sequence is a deterministic function of the signal sequence (the
  firehose bench replays a load step and asserts the exact reactions).
- **Actions**: spawn = build a transport from the worker factory,
  ``router.add_worker`` (the new replica boots the base graph, is
  fenced, and catches up by epoch replay — it can never serve stale
  rows, only warm up); drain = ``router.remove_worker`` on the
  highest-numbered live replica (deterministic victim), which
  completes its in-flight work and exits 0.
- **Decision log**: every tick appends ``{tick, action, reason,
  signals, workers}`` — the auditable trail ``stats()`` and the bench
  artifact expose; ``dpathsim_autoscale_*`` metric families carry the
  same truth for dashboards.

Ticking is external by default (``tick()``) so tests and benches drive
it deterministically; ``start()`` runs the same tick on a timer thread
for the CLI.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time

from ..obs.metrics import get_registry
from ..utils.logging import runtime_event
from .core import DRAINING, UP


@dataclasses.dataclass
class AutoscaleConfig:
    min_workers: int = 1
    max_workers: int = 4
    eval_interval_s: float = 1.0      # timer mode only; ticks are the unit
    # high-water: mean UP-worker queue depth as a fraction of the
    # router's per-replica saturation bound (worker_queue_limit)
    queue_high_frac: float = 0.5
    queue_low_frac: float = 0.05
    # router-side backlog per UP worker (admitted, unresolved): the
    # synchronous twin of the pong-reported queue depth — a burst
    # shows up here immediately, not a heartbeat later
    pending_high: float = 64.0
    pending_low: float = 2.0
    # sheds (query admission + update backpressure) per tick that
    # count as a high signal on their own
    shed_high: int = 1
    # treat any burning SLO as a high signal
    burn_high: bool = True
    up_consecutive: int = 2
    down_consecutive: int = 5
    cooldown_ticks: int = 5           # ticks of enforced hold after an action
    ready_timeout_s: float = 180.0
    decision_log_limit: int = 512


class Autoscaler:
    """One per router. ``worker_factory(wid) -> transport`` builds an
    UNSTARTED transport for a fresh replica (the CLI passes the same
    subprocess argv the initial fleet used; benches pass in-proc
    factories). Not thread-safe against concurrent ``tick`` calls —
    drive it from one place (the timer thread or the bench loop)."""

    def __init__(self, router, worker_factory,
                 config: AutoscaleConfig | None = None):
        self.router = router
        self.factory = worker_factory
        self.config = config or AutoscaleConfig()
        self.decisions: list[dict] = []
        self._tick_n = 0
        self._hi = 0
        self._lo = 0
        self._last_action_tick = -(10 ** 9)
        self._shed_prev: float | None = None
        seq = 0
        for wid in router.workers:
            m = re.fullmatch(r"w(\d+)", wid)
            if m:
                seq = max(seq, int(m.group(1)) + 1)
        self._wid_next = seq
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        reg = get_registry()
        self._m_workers = reg.gauge(
            "dpathsim_autoscale_workers",
            "live (UP) worker replicas as the autoscaler sees them",
        ).labels()
        self._m_decisions = reg.counter(
            "dpathsim_autoscale_decisions_total",
            "autoscale decisions by action",
        )
        self._m_spawn_s = reg.histogram(
            "dpathsim_autoscale_spawn_seconds",
            "transport start + ready wait per spawned worker",
        ).labels()

    # -- signal collection -------------------------------------------------

    def _shed_total(self) -> float:
        reg = get_registry()
        return (
            reg.counter(
                "dpathsim_router_requests_total",
                "router requests by outcome",
            ).labels(outcome="shed").value
            + reg.counter(
                "dpathsim_update_backpressure_total",
                "updates refused at the queue bound",
            ).labels().value
        )

    def _signals(self) -> dict:
        r = self.router
        with r._lock:
            up = [w for w in r.workers.values() if w.status == UP]
            draining = [
                w.wid for w in r.workers.values() if w.status == DRAINING
            ]
            depths = [w.queue_depth for w in up]
            up_ids = sorted(w.wid for w in up)
            pending = len(r._pending)
        shed_now = self._shed_total()
        shed_delta = (
            shed_now - self._shed_prev
            if self._shed_prev is not None else 0.0
        )
        self._shed_prev = shed_now
        burning = sorted(
            name for name, s in r.slo.snapshot().items()
            if s.get("status") == "burning"
        )
        limit = max(r.config.worker_queue_limit, 1)
        mean_depth = (sum(depths) / len(depths)) if depths else 0.0
        return {
            "up": up_ids,
            "draining": draining,
            "mean_queue_depth": round(mean_depth, 2),
            "queue_frac": round(mean_depth / limit, 4),
            "pending_per_worker": round(pending / max(len(up), 1), 2),
            "shed_delta": int(shed_delta),
            "burning_slos": burning,
        }

    # -- the loop ----------------------------------------------------------

    def tick(self) -> dict:
        """Evaluate once, maybe act; returns (and logs) the decision
        record. Deterministic: the record is a pure function of the
        observed signal sequence and the config thresholds."""
        cfg = self.config
        self._tick_n += 1
        self.router.reap_workers()
        sig = self._signals()
        n_up = len(sig["up"])
        self._m_workers.set(n_up)
        high = (
            sig["queue_frac"] >= cfg.queue_high_frac
            or sig["pending_per_worker"] >= cfg.pending_high
            or sig["shed_delta"] >= cfg.shed_high
            or (cfg.burn_high and bool(sig["burning_slos"]))
        )
        low = (
            sig["queue_frac"] <= cfg.queue_low_frac
            and sig["pending_per_worker"] <= cfg.pending_low
            and sig["shed_delta"] == 0
            and not sig["burning_slos"]
        )
        self._hi = self._hi + 1 if high else 0
        self._lo = self._lo + 1 if low else 0
        in_cooldown = (
            self._tick_n - self._last_action_tick < cfg.cooldown_ticks
        )
        action, reason = "hold", "signals within band"
        if sig["draining"]:
            reason = f"drain of {sig['draining']} still settling"
        elif in_cooldown:
            reason = "cooldown"
        elif (
            self._hi >= cfg.up_consecutive
            and n_up < cfg.max_workers
        ):
            action, reason = "spawn", (
                f"{self._hi} consecutive high ticks "
                f"(queue_frac={sig['queue_frac']}, "
                f"pending={sig['pending_per_worker']}, "
                f"shed={sig['shed_delta']}, "
                f"burning={sig['burning_slos']})"
            )
        elif self._hi >= cfg.up_consecutive:
            reason = f"high but at max_workers={cfg.max_workers}"
        elif (
            self._lo >= cfg.down_consecutive
            and n_up > cfg.min_workers
        ):
            action, reason = "drain", (
                f"{self._lo} consecutive low ticks"
            )
        record = {
            "tick": self._tick_n,
            "action": action,
            "reason": reason,
            "signals": sig,
            "workers": n_up,
        }
        if action == "spawn":
            record["spawned"] = self._spawn(record)
        elif action == "drain":
            record["drained"] = self._drain(sig["up"])
        if action != "hold":
            self._last_action_tick = self._tick_n
            self._hi = self._lo = 0
        self._m_decisions.inc(action=action)
        self.decisions.append(record)
        del self.decisions[:-cfg.decision_log_limit]
        runtime_event(
            "autoscale_decision", echo=(action != "hold"), **{
                k: v for k, v in record.items() if k != "signals"
            },
            **{f"sig_{k}": v for k, v in record["signals"].items()},
        )
        return record

    def _spawn(self, record: dict) -> str | None:
        wid = f"w{self._wid_next}"
        self._wid_next += 1
        t0 = time.perf_counter()
        transport = None
        try:
            transport = self.factory(wid)
            self.router.add_worker(
                wid, transport,
                ready_timeout=self.config.ready_timeout_s,
            )
        except Exception as exc:
            record["spawn_error"] = repr(exc)
            runtime_event("autoscale_spawn_failed", worker_id=wid,
                          error=repr(exc))
            # the transport may already be STARTED (add_worker starts
            # it before validating): reap the child, or every failed
            # spawn attempt leaks one worker process
            if transport is not None:
                try:
                    transport.close()
                except Exception:
                    pass
            return None
        self._m_spawn_s.observe(time.perf_counter() - t0)
        return wid

    def _drain(self, up_ids: list) -> str | None:
        if not up_ids:
            return None
        # deterministic victim: the highest-numbered live replica
        # (never the seed workers first, never ambiguous)
        def sort_key(wid: str):
            m = re.fullmatch(r"w(\d+)", wid)
            return (int(m.group(1)) if m else -1, wid)

        victim = max(up_ids, key=sort_key)
        return victim if self.router.remove_worker(victim) else None

    # -- timer mode (the CLI) ----------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="pathsim-autoscale", daemon=True,
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.config.eval_interval_s):
            try:
                self.tick()
            except Exception as exc:  # keep ticking; report
                runtime_event("autoscale_tick_error", error=repr(exc))

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def snapshot(self) -> dict:
        return {
            "ticks": self._tick_n,
            "decisions": self.decisions[-32:],
            "config": dataclasses.asdict(self.config),
        }
