"""Router-side block scheduling for batch campaigns.

The serving router (core.py) is a request router: admission, hedging,
consistency fencing for single-row queries. A campaign is a different
shape — a known, finite work-list of row blocks, every block
idempotent and read-only — so it gets its own scheduler instead of
widening ``ROUTED_OPS``: a shared block queue drained by whichever
worker is free (self-balancing: a slow replica simply takes fewer
blocks), bounded in-flight per worker, straggler re-dispatch after a
latency multiple (first answer wins; ``batch_blocks`` is idempotent so
duplicated work is only wasted, never wrong), and requeue-on-death via
the transport's ``on_death``.

Consistency is campaign-scoped, not request-scoped: every worker's
ready token ``(base_fp, delta_seq)`` must equal the campaign spec's —
a mismatched worker is excluded up front (counted), and a worker that
answers ``stale batch campaign`` (its token moved mid-campaign) is
fenced for the remainder. If no worker matches, the campaign refuses
loudly rather than mixing graph versions.

Results travel as JSON (the wire's native encoding); f64 survives the
round-trip exactly (shortest-repr), so fleet shards are bit-identical
to single-host shards — the parity gate in ``make batch-smoke`` checks
exactly this.
"""

from __future__ import annotations

import threading
import time

from ..obs.metrics import get_registry
from ..utils.logging import runtime_event
from .transport import WorkerGone


class BatchFleetError(RuntimeError):
    """The campaign cannot make progress: no eligible worker remains
    (all dead, fenced, or token-mismatched)."""


class BlockScheduler:
    """Fan a campaign's pending blocks across worker transports.

    Owns the transports for the campaign's duration: ``start()`` wires
    the message/death callbacks and fences ready tokens; callers hand
    in freshly-constructed (unstarted) transports, exactly like
    ``Router`` does.
    """

    def __init__(
        self,
        transports: dict,
        max_inflight: int = 2,
        straggler_after_s: float = 30.0,
        ready_timeout_s: float = 120.0,
    ):
        self._transports = dict(transports)
        self._max_inflight = max(int(max_inflight), 1)
        self._straggler_after_s = float(straggler_after_s)
        self._ready_timeout_s = float(ready_timeout_s)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._tokens: dict[str, tuple] = {}
        self._fenced: set[str] = set()
        self._inflight: dict[str, dict] = {}   # rid → dispatch record
        self._pending: list = []
        self._results: list = []
        self._failure: Exception | None = None
        self._seq = 0
        reg = get_registry()
        self._m_dispatch = reg.counter(
            "dpathsim_batch_dispatch_total",
            "fleet block dispatches by kind (first try, straggler "
            "re-dispatch, death requeue)",
        )
        self._m_fenced = reg.counter(
            "dpathsim_batch_worker_fenced_total",
            "workers excluded from a campaign (token mismatch or "
            "stale answer mid-campaign)",
        )

    def start(self) -> None:
        for wid, t in self._transports.items():
            t.start(self._on_message, self._on_death)
        for wid, t in self._transports.items():
            info = t.wait_ready(self._ready_timeout_s)
            self._tokens[wid] = (
                info.get("base_fp"), int(info.get("delta_seq", 0))
            )

    def close(self) -> None:
        for t in self._transports.values():
            close = getattr(t, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass

    # -- callbacks (transport reader threads) -----------------------------

    def _on_message(self, wid: str, obj: dict) -> None:
        rid = obj.get("request_id")
        if not isinstance(rid, str) or not rid.startswith("bb:"):
            return
        with self._cv:
            rec = self._inflight.pop(rid, None)
            if rec is None:
                return  # straggler's late twin: first answer won
            # drop the block's OTHER outstanding dispatches, if any
            for orid in list(self._inflight):
                if self._inflight[orid]["block"] == rec["block"]:
                    del self._inflight[orid]
            if obj.get("ok"):
                lo, hi = rec["block"]
                self._results.append((lo, hi, obj.get("result") or {}))
            else:
                err = str(obj.get("error", "batch_blocks failed"))
                if "stale batch campaign" in err:
                    # this worker's graph moved mid-campaign: fence it
                    # and requeue the block for a consistent peer
                    self._fenced.add(wid)
                    self._m_fenced.inc(reason="stale")
                    self._pending.append(rec["block"])
                elif obj.get("transient"):
                    self._pending.append(rec["block"])
                else:
                    self._failure = BatchFleetError(
                        f"worker {wid} failed block {rec['block']}: {err}"
                    )
            self._cv.notify_all()

    def _on_death(self, wid: str, reason: str) -> None:
        with self._cv:
            self._fenced.add(wid)
            runtime_event(
                "batch_worker_death", echo=False,
                worker_id=wid, reason=reason,
            )
            for rid in list(self._inflight):
                rec = self._inflight[rid]
                if rec["worker"] == wid:
                    del self._inflight[rid]
                    self._pending.append(rec["block"])
                    self._m_dispatch.inc(kind="death_requeue")
            self._cv.notify_all()

    # -- scheduling --------------------------------------------------------

    def _eligible(self, spec) -> list[str]:
        want = (spec.base_fp, int(spec.delta_seq))
        out = []
        for wid, t in self._transports.items():
            if wid in self._fenced or not t.alive:
                continue
            if self._tokens.get(wid) != want:
                continue
            out.append(wid)
        return out

    def _dispatch(self, spec, wid: str, block, kind: str) -> None:
        lo, hi = block
        self._seq += 1
        rid = f"bb:{lo}:{hi}:{self._seq}"
        req = {
            "id": self._seq,
            "op": "batch_blocks",
            "request_id": rid,
            "lo": int(lo),
            "hi": int(hi),
            "mode": spec.mode,
            "metapath": spec.metapath,
            "variant": spec.variant,
            "base_fp": spec.base_fp,
            "delta_seq": int(spec.delta_seq),
            # both campaign parameters ride every dispatch (the handler
            # reads only its mode's field; defaults match the wire's)
            "k": int(spec.k) if spec.k is not None else 10,
            "tau": float(spec.tau) if spec.tau is not None else 0.5,
        }
        try:
            self._transports[wid].send(req)
        except WorkerGone:
            self._fenced.add(wid)
            self._pending.append(block)
            return
        self._inflight[rid] = {
            "worker": wid, "block": block, "t": time.perf_counter(),
        }
        self._m_dispatch.inc(kind=kind)

    def map_blocks(self, spec, blocks):
        """Yield ``(lo, hi, result)`` for every block, in completion
        order. Raises :class:`BatchFleetError` when no eligible worker
        can finish the campaign."""
        with self._cv:
            self._pending = [tuple(b) for b in blocks]
            self._results = []
            self._inflight.clear()
            self._failure = None
            need = len(self._pending)
        got = 0
        while got < need:
            with self._cv:
                if self._failure is not None:
                    raise self._failure
                if not self._results:
                    workers = self._eligible(spec)
                    if not workers and not self._inflight:
                        raise BatchFleetError(
                            "no eligible batch worker: token mismatch, "
                            "fenced, or dead "
                            f"(want {(spec.base_fp, spec.delta_seq)}, "
                            f"have {self._tokens})"
                        )
                    load = {w: 0 for w in workers}
                    for rec in self._inflight.values():
                        if rec["worker"] in load:
                            load[rec["worker"]] += 1
                    progressed = False
                    for w in sorted(workers, key=lambda w: load[w]):
                        if not self._pending:
                            break
                        if load[w] >= self._max_inflight:
                            continue
                        self._dispatch(
                            spec, w, self._pending.pop(0), "primary"
                        )
                        load[w] += 1
                        progressed = True
                    # straggler re-dispatch: a block outstanding past
                    # the threshold gets a second copy on the least-
                    # loaded OTHER worker; first answer wins
                    now = time.perf_counter()
                    for rid, rec in list(self._inflight.items()):
                        if now - rec["t"] < self._straggler_after_s:
                            continue
                        others = [
                            w for w in workers
                            if w != rec["worker"]
                            and load.get(w, 99) < self._max_inflight
                        ]
                        dupes = sum(
                            1 for r in self._inflight.values()
                            if r["block"] == rec["block"]
                        )
                        if others and dupes < 2:
                            w = min(others, key=lambda w: load[w])
                            self._dispatch(
                                spec, w, rec["block"], "straggler"
                            )
                            load[w] += 1
                            progressed = True
                    if not self._results and self._failure is None:
                        self._cv.wait(
                            timeout=0.25 if progressed else 0.05
                        )
                ready, self._results = self._results, []
            # yield OUTSIDE the lock: the consumer's per-block callback
            # may re-enter the scheduler (e.g. kill a transport, whose
            # on_death takes the cv on this very thread)
            for lo, hi, result in ready:
                got += 1
                yield lo, hi, result
