"""Hierarchical tracing spans with cross-thread context propagation.

"Where did this request's p99 go" needs one connected timeline per
request — but a served query crosses three threads (the submitting
client, the coalescer's dispatcher, its completer), so a thread-local
"current span" alone cannot connect it. This tracer provides both
halves:

- **In-thread**: ``tracer.span(name)`` is a context manager that
  parents to the calling thread's current span (a ``contextvars``
  slot) and restores it on exit — nested ``with`` blocks become a
  span tree with zero caller bookkeeping.
- **Cross-thread**: ``tracer.start_span(...)`` / ``tracer.finish(...)``
  split the lifecycle so a span can open on one thread and close on
  another (the coalescer's enqueue span opens at ``submit`` and closes
  when the dispatcher picks the request up); ``tracer.activate(ctx)``
  re-roots the current-span slot on a worker thread so downstream
  ``span()`` calls parent into the migrated trace.

Every span carries ``(trace_id, span_id, parent_id)``; a root span's
``span_id`` is its ``trace_id``, and children inherit the trace id
through whichever propagation path delivered the parent. That triple is
what the connectivity test walks and what Perfetto's JSON args expose.

Clock discipline: span timestamps are ``time.monotonic_ns()`` — the
SAME monotonic clock ``utils.logging.timestamps()`` stamps into every
JSONL event as ``ts_mono``, so events and spans join on one axis. The
wall anchor (one ``time.time()`` reading at tracer init, the sanctioned
exception to the no-wall-clock-durations rule) maps monotonic
timestamps onto the epoch microseconds Chrome/Perfetto expect.

Finished spans land in a bounded ring (``max_spans``, oldest dropped) —
tracing a long-lived server must never grow without bound. Disabled
(the default), ``span()`` costs one attribute check; the serving hot
path stays unmeasurable.

Cross-PROCESS propagation (the fleet tier, DESIGN.md §24): span ids are
globally unique — each tracer draws a random 48-bit id base at init, so
a router process and its worker subprocesses can never mint colliding
ids — and a span's identity travels on the JSONL protocol as a tiny
``trace`` dict (:func:`to_wire` / :func:`from_wire`). The receiving
process activates the wire context and every span it opens parents into
the ORIGINATING process's trace; merging the per-process rings
(:func:`obs.fleet.fleet_chrome_trace`) yields one stitched Perfetto
timeline. The wire context also carries the HEAD sampling decision:
``{"sampled": false}`` tells the receiver to create zero spans for this
request (the dropped-head sentinel travels with the request), so the
configured 1/N rate holds fleet-wide instead of per process.

Head-based sampling (``sample_every``): span bookkeeping is
GIL-serialized Python, so tracing EVERY request costs tens of
microseconds of serialized work per request — fine for debugging, too
much to leave on under CPU-bound load. The production posture (the
same one Dapper-style tracers ship) is to decide at the trace HEAD:
every Nth root span starts a trace, and an unsampled request creates
ZERO span objects anywhere downstream (children only exist under a
live parent). ``sample_every=1`` (the default) traces everything;
sampled-in traces are complete and connected either way. ``device_annotations=True`` additionally
pushes each span name into ``jax.profiler``'s TraceAnnotation stack so
spans show up inside a ``--profile-dir`` device trace, attaching the
host-side hierarchy to the XLA op timeline.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import dataclasses
import itertools
import json
import os
import threading
import time
from typing import Any, Iterator

_MONO_NS = time.monotonic_ns


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """The propagatable identity of a live span: everything a child on
    another thread needs to parent correctly."""

    trace_id: int
    span_id: int


# The sampled-OUT marker: when a trace head is dropped by head
# sampling, its scope's current-span slot holds this sentinel instead
# of None, so parentless spans underneath it are recognized as
# descendants of a dropped head (suppressed outright) rather than as
# fresh heads — otherwise every nested "root" would tick the sampler
# again and the configured 1/N rate would not hold. Real ids start at
# a positive id base, so (0, 0) can never collide with a live span.
_DROPPED = SpanContext(0, 0)


def to_wire(ctx: "SpanContext | None", sampled: bool = True) -> dict:
    """A span context as the protocol's ``trace`` field. ``ctx=None``
    with ``sampled=False`` propagates a dropped-head decision (the
    receiver must create no spans); ``ctx=None`` with ``sampled=True``
    is an empty dict — "no opinion", the receiver traces on its own."""
    if ctx is None or ctx is _DROPPED:
        return {"sampled": False} if not sampled or ctx is _DROPPED else {}
    return {"trace_id": int(ctx.trace_id), "span_id": int(ctx.span_id)}


def from_wire(trace: dict | None) -> SpanContext | None:
    """Parse a protocol ``trace`` field back into the context to
    ``activate()``. Returns None (no propagation — local behavior
    unchanged), the dropped-head sentinel (``sampled: false`` — spans
    suppressed downstream), or a live remote parent context."""
    if not trace:
        return None
    if trace.get("sampled") is False:
        return _DROPPED
    tid, sid = trace.get("trace_id"), trace.get("span_id")
    if tid is None or sid is None:
        return None
    return SpanContext(int(tid), int(sid))


class Span:
    """One timed operation. Mutable only through the tracer (``finish``
    seals it); ``args`` entries must be JSON-safe."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "t_start_ns", "t_end_ns", "tid", "thread_name", "args",
    )

    def __init__(
        self,
        name: str,
        trace_id: int,
        span_id: int,
        parent_id: int | None,
        args: dict[str, Any],
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t_start_ns = _MONO_NS()
        self.t_end_ns: int | None = None
        t = threading.current_thread()
        self.tid = t.ident or 0
        self.thread_name = t.name
        self.args = args

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    @property
    def duration_s(self) -> float:
        end = self.t_end_ns if self.t_end_ns is not None else _MONO_NS()
        return (end - self.t_start_ns) / 1e9

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t_start_ns": self.t_start_ns,
            "t_end_ns": self.t_end_ns,
            "tid": self.tid,
            "thread": self.thread_name,
            "args": dict(self.args),
        }


class Tracer:
    """Span factory + finished-span ring + current-span propagation."""

    def __init__(
        self,
        enabled: bool = False,
        max_spans: int = 200_000,
        device_annotations: bool = False,
        sample_every: int = 1,
    ):
        self.enabled = enabled
        self.device_annotations = device_annotations
        self.sample_every = int(sample_every)
        self._lock = threading.Lock()
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=max_spans
        )
        # Globally-unique ids: a random 48-bit base per tracer, local
        # counter on top. Two processes of one fleet can never mint the
        # same span id, so cross-process stitching (trace contexts on
        # the wire, rings merged at export) needs no id translation.
        # The base is strictly positive, so the (0, 0) dropped-head
        # sentinel stays uncollidable.
        self._id_base = (
            (int.from_bytes(os.urandom(6), "big") | 1) << 24
        )
        self._ids = itertools.count(1)
        # root admissions seen, for deterministic head sampling
        # (itertools.count is C-level and GIL-atomic: no lock needed)
        self._root_seen = itertools.count()
        self._current: contextvars.ContextVar[SpanContext | None] = (
            contextvars.ContextVar("pathsim_current_span", default=None)
        )
        # wall anchor: the one sanctioned wall-clock reading — maps
        # monotonic ns onto epoch µs for Chrome trace-event ts fields
        self._wall_anchor_us = time.time() * 1e6 - _MONO_NS() / 1e3

    def configure(
        self,
        enabled: bool | None = None,
        max_spans: int | None = None,
        device_annotations: bool | None = None,
        sample_every: int | None = None,
    ) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = enabled
            if device_annotations is not None:
                self.device_annotations = device_annotations
            if sample_every is not None:
                if sample_every < 1:
                    raise ValueError(
                        f"sample_every must be >= 1, got {sample_every}"
                    )
                self.sample_every = int(sample_every)
            if max_spans is not None and max_spans != self._spans.maxlen:
                self._spans = collections.deque(
                    self._spans, maxlen=max_spans
                )

    # -- lifecycle -----------------------------------------------------------

    def current(self) -> SpanContext | None:
        return self._current.get()

    def start_span(
        self,
        name: str,
        parent: SpanContext | None = None,
        **args: Any,
    ) -> Span | None:
        """Open a span (cross-thread form: caller owns ``finish``).
        ``parent=None`` parents to the calling thread's current span;
        pass an explicit context to parent across a thread hop. Returns
        None when tracing is disabled — ``finish(None)`` is a no-op, so
        callers need no enabled-checks of their own.

        A parentless span is a trace HEAD: with ``sample_every=n`` only
        every nth head starts a trace (the rest return None, and their
        would-be children never exist). Spans with a live parent are
        never dropped — a sampled-in trace is always complete — and
        spans under a DROPPED head are always suppressed without
        ticking the sampler (one head decision per trace, whichever
        call happens to sit outermost)."""
        if not self.enabled:
            return None
        if parent is None:
            parent = self._current.get()
        if parent is _DROPPED:
            return None
        if parent is None and self.sample_every > 1:
            if next(self._root_seen) % self.sample_every:
                return None
        span_id = self._id_base + next(self._ids)
        if parent is None:
            trace_id, parent_id = span_id, None
        else:
            trace_id, parent_id = parent.trace_id, parent.span_id
        return Span(name, trace_id, span_id, parent_id, args)

    def finish(self, span: Span | None, **args: Any) -> None:
        """Seal a span and land it in the ring. First finish wins: a
        second call is a no-op, so overlapping error paths (a batch
        failing after some members already resolved) can finish
        defensively without duplicating ring entries or rewriting an
        already-recorded outcome."""
        if span is None or span.t_end_ns is not None:
            return
        span.args.update(args)
        span.t_end_ns = _MONO_NS()
        with self._lock:
            self._spans.append(span)

    @contextlib.contextmanager
    def span(self, name: str, parent: SpanContext | None = None,
             **args: Any) -> Iterator[Span | None]:
        """In-thread form: opens, becomes the current span, restores on
        exit. Exceptions mark the span (``error=repr``) and propagate."""
        if not self.enabled:
            yield None
            return
        s = self.start_span(name, parent=parent, **args)
        if s is None:
            # sampled out (or enabled flipped off mid-call): poison the
            # scope with the dropped sentinel so parentless spans
            # underneath neither trace nor tick the sampler again
            token = self._current.set(_DROPPED)
            try:
                yield None
            finally:
                self._current.reset(token)
            return
        token = self._current.set(s.context)
        annotation = None
        if self.device_annotations:
            try:
                import jax

                annotation = jax.profiler.TraceAnnotation(name)
                annotation.__enter__()
            except Exception:
                annotation = None
        try:
            yield s
        except BaseException as exc:
            self.finish(s, error=repr(exc))
            raise
        else:
            self.finish(s)
        finally:
            if annotation is not None:
                try:
                    annotation.__exit__(None, None, None)
                except Exception:
                    pass
            self._current.reset(token)

    @contextlib.contextmanager
    def child_span(self, name: str, **args: Any) -> Iterator[Span | None]:
        """Like :meth:`span`, but only when a current span exists —
        the form for mid-pipeline segments (host transfer, cache fill)
        that must vanish when their request's trace head was sampled
        out, instead of starting orphan root traces."""
        cur = self._current.get()
        if not self.enabled or cur is None or cur is _DROPPED:
            yield None
            return
        with self.span(name, **args) as s:
            yield s

    @contextlib.contextmanager
    def activate(self, ctx: SpanContext | None) -> Iterator[None]:
        """Re-root the calling thread's current span to ``ctx`` — the
        receiving half of a cross-thread handoff: spans opened inside
        parent into the migrated trace."""
        token = self._current.set(ctx)
        try:
            yield
        finally:
            self._current.reset(token)

    # -- inspection / export -------------------------------------------------

    def spans(self) -> list[Span]:
        """Finished spans, oldest first (ring-bounded)."""
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def chrome_trace(self) -> dict:
        """Finished spans as Chrome trace-event JSON (the format
        Perfetto and chrome://tracing load): one complete ("X") event
        per span, per-thread tracks, span identity in ``args``."""
        pid = os.getpid()
        events: list[dict] = []
        seen_tids: dict[int, str] = {}
        for s in self.spans():
            end_ns = s.t_end_ns if s.t_end_ns is not None else s.t_start_ns
            events.append(
                {
                    "name": s.name,
                    "cat": "pathsim",
                    "ph": "X",
                    "pid": pid,
                    "tid": s.tid,
                    "ts": self._wall_anchor_us + s.t_start_ns / 1e3,
                    "dur": (end_ns - s.t_start_ns) / 1e3,
                    "args": {
                        "trace_id": s.trace_id,
                        "span_id": s.span_id,
                        "parent_id": s.parent_id,
                        **s.args,
                    },
                }
            )
            seen_tids.setdefault(s.tid, s.thread_name)
        for tid, tname in seen_tids.items():
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_state(self, limit: int | None = None) -> dict:
        """The ring as JSON-safe state for cross-process merging: span
        dicts, this process's pid, and the wall anchor that maps its
        monotonic timestamps onto epoch µs (all fleet processes share
        one host clock, so anchored timestamps align across exports).
        ``limit`` keeps only the newest N spans — the ``trace`` protocol
        op's payload must stay bounded on the wire."""
        spans = self.spans()
        if limit is not None and len(spans) > limit:
            spans = spans[-limit:]
        return {
            "pid": os.getpid(),
            "wall_anchor_us": self._wall_anchor_us,
            "spans": [s.to_dict() for s in spans],
        }

    def write_chrome_trace(self, path: str) -> int:
        """Dump the ring as Perfetto-loadable JSON (atomic rename —
        a trace viewer must never read a half-written file). Returns
        the number of span events written."""
        doc = self.chrome_trace()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return sum(1 for e in doc["traceEvents"] if e["ph"] == "X")


_TRACER = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return _TRACER
