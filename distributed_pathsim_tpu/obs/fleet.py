"""Fleet-level observability: exact registry merge + stitched traces.

PR 4 gave every process its own truth (registry + span ring); PRs 6–7
made the system a fleet of processes — so "what is the p99" became N
disagreeing per-worker answers. This module is the single-truth layer
the router builds on (DESIGN.md §24):

- **Exact histogram merge**: every histogram in this repo uses one
  bucket geometry per family, carried in the snapshot (``bounds``).
  Same edges ⇒ merging is bucket-wise integer addition — *exact*, not
  an approximation: the merged cell is bit-identical (counts, min/max,
  every bucket) to a single registry that observed the union of the
  samples, and therefore so is every quantile computed from it (the
  shared :func:`~.metrics.quantile_from_counts`). The merge is
  associative and commutative (integer sums are), so scrape order,
  partial scrapes, and re-merges can never change the answer —
  property-tested in tests/test_fleet_obs.py. Cells with mismatched
  geometry are refused loudly (``unmergeable``), never silently summed.
- **Per-worker labels preserved**: the fleet Prometheus export renders
  every worker's series with a ``worker`` label added — PromQL's
  ``sum by (le)`` over them is exact for the same reason the local
  merge is. The merged aggregate feeds the SLO engine and
  ``dpathsim fleet-stats``.
- **Stitched traces**: each process exports its span ring with its pid
  and wall anchor (:meth:`~.trace.Tracer.export_state`);
  :func:`fleet_chrome_trace` lays them onto one Perfetto timeline
  (anchored epoch µs align across processes on one host), and
  :func:`audit_fleet_traces` walks every parent link across process
  boundaries — the "zero broken parent links" gate of
  ``make fleet-obs-smoke``.

Layering: like the rest of ``obs/``, this module imports nothing from
outside the package — the router calls in, never the reverse.
"""

from __future__ import annotations

import json
import math

from .export import (
    IntervalFileExporter,
    _fmt_labels,
    _fmt_value,
    atomic_write,
)
from .metrics import quantile_from_counts


class MergeError(ValueError):
    """Cells cannot be merged exactly (mismatched type or geometry)."""


def merge_histogram_cells(cells: list[dict], bounds: list[float]) -> dict:
    """Exact merge of histogram cell snapshots sharing ``bounds``:
    bucket-wise sum (integers — associative, commutative, exact),
    summed count/underflow/overflow, min of mins / max of maxes.
    Quantiles recomputed from the merged buckets with the same
    estimator a live cell uses."""
    n = len(bounds)
    counts = [0] * n
    underflow = overflow = count = 0
    total = 0.0
    vmin, vmax = math.inf, -math.inf
    for c in cells:
        cc = c["_counts"]
        if len(cc) != n:
            raise MergeError(
                f"histogram geometry mismatch: {len(cc)} buckets vs "
                f"{n} — cells must share edges to merge exactly"
            )
        for i, v in enumerate(cc):
            counts[i] += v
        underflow += c["underflow"]
        overflow += c["overflow"]
        count += c["count"]
        total += c["sum"]
        if c["count"]:
            vmin = min(vmin, c["min"])
            vmax = max(vmax, c["max"])
    merged = {
        "count": count,
        "sum": total,
        "min": None if count == 0 else vmin,
        "max": None if count == 0 else vmax,
        "underflow": underflow,
        "overflow": overflow,
    }
    for key, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
        v = quantile_from_counts(
            tuple(bounds), counts, underflow, count, vmin, vmax, q
        )
        merged[key] = None if math.isnan(v) else v
    merged["_counts"] = counts
    return merged


def _merge_scalar_cells(cells: list[dict]) -> dict:
    """Counters/gauges merge by sum, with the per-worker min/max kept
    alongside: a fleet queue depth or request total is the sum, but a
    floor-style SLO over a ratio gauge (ann recall) must judge the
    WORST replica, which the sum would hide."""
    vals = [float(c["value"]) for c in cells]
    return {
        "value": sum(vals),
        "min": min(vals),
        "max": max(vals),
        "cells": len(vals),
    }


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def merge_registry_snapshots(
    parts: dict[str, dict],
) -> tuple[dict, list[str]]:
    """Merge per-process registry snapshots (``worker_id → snapshot``)
    into one fleet snapshot with the same family shape. Cells are
    grouped by their label set across workers and merged exactly;
    families whose cells cannot merge (bucket-geometry disagreement —
    a replica on different code) land in the returned ``unmergeable``
    list instead of poisoning the rest."""
    merged: dict = {}
    unmergeable: list[str] = []
    names: dict[str, None] = {}
    for snap in parts.values():
        for name in snap:
            names.setdefault(name)
    for name in names:
        fams = [
            (wid, snap[name]) for wid, snap in parts.items()
            if name in snap
        ]
        kinds = {f["type"] for _, f in fams}
        if len(kinds) != 1:
            unmergeable.append(name)
            continue
        kind = next(iter(kinds))
        bounds = None
        if kind == "histogram":
            all_bounds = {tuple(f.get("bounds") or ()) for _, f in fams}
            if len(all_bounds) != 1 or () in all_bounds:
                unmergeable.append(name)
                continue
            bounds = list(next(iter(all_bounds)))
        by_labels: dict[tuple, list[dict]] = {}
        label_of: dict[tuple, dict] = {}
        for _, fam in fams:
            for cell in fam["values"]:
                key = _label_key(cell["labels"])
                by_labels.setdefault(key, []).append(cell)
                label_of.setdefault(key, dict(cell["labels"]))
        try:
            values = []
            for key in sorted(by_labels):
                cells = by_labels[key]
                if kind == "histogram":
                    out = merge_histogram_cells(cells, bounds)
                else:
                    out = _merge_scalar_cells(cells)
                values.append({"labels": label_of[key], **out})
        except MergeError:
            unmergeable.append(name)
            continue
        merged[name] = {
            "type": kind,
            "help": fams[0][1].get("help", ""),
            "values": values,
        }
        if bounds is not None:
            merged[name]["bounds"] = bounds
    return merged, unmergeable


# -- Prometheus rendering from snapshots -------------------------------------


def render_fleet_prometheus(parts: dict[str, dict]) -> str:
    """Prometheus text 0.0.4 over per-process snapshots, every series
    carrying a ``worker`` label — per-worker resolution preserved, and
    (same edges everywhere) ``sum by (le)`` aggregation in PromQL is
    exactly the bucket-wise merge :func:`merge_registry_snapshots`
    performs locally."""
    names: dict[str, tuple[str, str]] = {}
    for snap in parts.values():
        for name, fam in snap.items():
            names.setdefault(name, (fam["type"], fam.get("help", "")))
    lines: list[str] = []
    for name in sorted(names):
        kind, help_ = names[name]
        lines.append(f"# HELP {name} {help_ or name}")
        lines.append(f"# TYPE {name} {kind}")
        for wid in sorted(parts):
            fam = parts[wid].get(name)
            if fam is None or fam["type"] != kind:
                continue
            bounds = fam.get("bounds") or []
            for cell in fam["values"]:
                labels = {**cell["labels"], "worker": wid}
                if kind == "histogram":
                    cum = cell["underflow"]
                    for bound, c in zip(bounds, cell["_counts"]):
                        cum += c
                        le = 'le="{}"'.format(_fmt_value(bound))
                        lines.append(
                            f"{name}_bucket{_fmt_labels(labels, le)} {cum}"
                        )
                    le_inf = 'le="+Inf"'
                    lines.append(
                        f"{name}_bucket{_fmt_labels(labels, le_inf)}"
                        f" {cell['count']}"
                    )
                    lines.append(
                        f"{name}_sum{_fmt_labels(labels)}"
                        f" {_fmt_value(cell['sum'])}"
                    )
                    lines.append(
                        f"{name}_count{_fmt_labels(labels)} {cell['count']}"
                    )
                else:
                    lines.append(
                        f"{name}{_fmt_labels(labels)}"
                        f" {_fmt_value(cell['value'])}"
                    )
    return "\n".join(lines) + "\n"


def write_fleet_textfile(path: str, parts: dict[str, dict]) -> None:
    """One atomic fleet scrape (same contract as
    :func:`~.export.write_textfile`)."""
    atomic_write(path, render_fleet_prometheus(parts))


class FleetTextfileExporter(IntervalFileExporter):
    """The router's interval exporter: re-renders the fleet Prometheus
    textfile from the latest scraped snapshots, plus (optionally) the
    full ``fleet_metrics`` JSON beside it (``<path>.json``) — the file
    ``dpathsim fleet-stats`` reads. Lifecycle (immediate first write,
    interval thread, final write on stop) from
    :class:`~.export.IntervalFileExporter`."""

    thread_name = "pathsim-fleet-export"

    def __init__(
        self,
        path: str,
        parts_fn,
        interval_s: float = 5.0,
        snapshot_fn=None,
    ):
        super().__init__(interval_s)
        self.path = path
        self.parts_fn = parts_fn
        self.snapshot_fn = snapshot_fn

    def write(self) -> None:
        write_fleet_textfile(self.path, self.parts_fn())
        if self.snapshot_fn is not None:
            atomic_write(
                f"{self.path}.json", json.dumps(self.snapshot_fn())
            )


# -- stitched traces ---------------------------------------------------------


def fleet_chrome_trace(trace_parts: list[dict]) -> dict:
    """Per-process tracer exports (:meth:`Tracer.export_state`) merged
    onto ONE Chrome/Perfetto timeline: each part keeps its pid lane,
    monotonic timestamps map through each process's own wall anchor
    (same host ⇒ one epoch axis), and span identity rides in ``args``
    exactly as the single-process export does — so a router-rooted
    request renders as one tree crossing process lanes."""
    events: list[dict] = []
    for part in trace_parts:
        pid = int(part.get("pid", 0))
        anchor = float(part.get("wall_anchor_us", 0.0))
        seen_tids: dict[int, str] = {}
        for s in part.get("spans", ()):
            end_ns = (
                s["t_end_ns"] if s.get("t_end_ns") is not None
                else s["t_start_ns"]
            )
            tid = int(s.get("tid", 0))
            events.append(
                {
                    "name": s["name"],
                    "cat": "pathsim",
                    "ph": "X",
                    "pid": pid,
                    "tid": tid,
                    "ts": anchor + s["t_start_ns"] / 1e3,
                    "dur": (end_ns - s["t_start_ns"]) / 1e3,
                    "args": {
                        "trace_id": s["trace_id"],
                        "span_id": s["span_id"],
                        "parent_id": s["parent_id"],
                        **s.get("args", {}),
                    },
                }
            )
            seen_tids.setdefault(tid, s.get("thread", ""))
        for tid, tname in seen_tids.items():
            events.append(
                {
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": tname},
                }
            )
        events.append(
            {
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": part.get("process", f"pid {pid}")},
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_fleet_trace(path: str, trace_parts: list[dict]) -> int:
    """Dump the merged fleet timeline atomically; returns the span
    event count."""
    doc = fleet_chrome_trace(trace_parts)
    atomic_write(path, json.dumps(doc))
    return sum(1 for e in doc["traceEvents"] if e["ph"] == "X")


def audit_fleet_traces(trace_parts: list[dict]) -> dict:
    """Walk every parent link across the merged exports — the
    correctness gate for cross-process stitching. A *broken* link is a
    span whose ``parent_id`` resolves to no exported span (or to a span
    of a different trace); a trace is *stitched* when its spans come
    from ≥2 pids and every link in it resolves. Spans lost with a
    SIGKILLed worker simply aren't exported — absence of a subtree is
    not a broken link, a dangling parent reference is."""
    by_id: dict[int, dict] = {}
    by_trace: dict[int, list[tuple[int, dict]]] = {}
    for part in trace_parts:
        pid = int(part.get("pid", 0))
        for s in part.get("spans", ()):
            by_id[s["span_id"]] = s
            by_trace.setdefault(s["trace_id"], []).append((pid, s))
    traces = cross = stitched = broken_total = 0
    for tid, members in by_trace.items():
        traces += 1
        pids = {pid for pid, _ in members}
        broken = 0
        for _, s in members:
            parent = s.get("parent_id")
            if parent is None:
                continue
            ps = by_id.get(parent)
            if ps is None or ps["trace_id"] != tid:
                broken += 1
        broken_total += broken
        if len(pids) >= 2:
            cross += 1
            if broken == 0:
                stitched += 1
    return {
        "traces": traces,
        "cross_process_traces": cross,
        "stitched_cross_process": stitched,
        "broken_parent_links": broken_total,
        "total_spans": len(by_id),
        "processes": len(trace_parts),
    }


# -- the `dpathsim fleet-stats` renderer -------------------------------------


def _cells(merged: dict, metric: str) -> list[dict]:
    fam = merged.get(metric)
    return fam["values"] if fam else []


def _sum_matching(merged: dict, metric: str, **labels) -> float:
    total = 0.0
    for cell in _cells(merged, metric):
        if all(cell["labels"].get(k) == v for k, v in labels.items()):
            total += cell.get("value", cell.get("count", 0.0))
    return total


def render_fleet_stats(data: dict) -> str:
    """The ``dpathsim fleet-stats`` one-shot summary: worker table,
    fleet-exact merged latency per op, headline counters, SLO status.
    ``data`` is a ``fleet_metrics`` result (or the JSON the router's
    ``--metrics-file`` exporter writes beside the .prom)."""
    lines: list[str] = []
    router = data.get("router") or {}
    workers = router.get("workers") or {}
    up = sum(1 for w in workers.values() if w.get("status") == "up")
    lines.append(
        f"fleet: {len(workers)} workers ({up} up)"
        f"  routing={router.get('routing', '?')}"
        f"  epochs={router.get('epochs', '?')}"
        f"  pending={router.get('pending', '?')}"
        + ("  DRAINING" if router.get("draining") else "")
    )
    if workers:
        lines.append("")
        lines.append(
            f"{'worker':<8}{'status':<9}{'queue':>6}{'lag':>5}"
            f"{'assigned':>9}  index"
        )
        for wid in sorted(workers):
            w = workers[wid]
            idx = w.get("index")
            idx_s = (
                f"epoch={idx.get('epoch')}" if isinstance(idx, dict)
                else "-"
            )
            lines.append(
                f"{wid:<8}{w.get('status', '?'):<9}"
                f"{w.get('queue_depth', 0):>6}{w.get('lag', 0):>5}"
                f"{w.get('assigned', 0):>9}  {idx_s}"
            )
    merged = data.get("merged") or {}
    # three latency views, all merged fleet-exact: the router's
    # submit-to-resolve (what clients feel), the workers' serve path
    # by outcome (where topk actually runs — the async worker loop
    # doesn't route topk through the per-op protocol histogram), and
    # the per-protocol-op view (updates, scrapes, health)
    for title, metric, axis in (
        ("router latency (submit→resolve)",
         "dpathsim_router_request_seconds", "outcome"),
        ("serve latency (worker topk path)",
         "dpathsim_serve_request_seconds", "outcome"),
        ("protocol op latency", "dpathsim_request_seconds", "op"),
    ):
        cells = [c for c in _cells(merged, metric) if c["count"]]
        if not cells:
            continue
        lines.append("")
        lines.append(f"{title} — merged fleet-exact histograms:")
        lines.append(
            f"{axis:<16}{'count':>9}{'p50ms':>10}{'p95ms':>10}"
            f"{'p99ms':>10}"
        )
        for cell in cells:
            name = cell["labels"].get(axis, "?")
            lines.append(
                f"{name:<16}{cell['count']:>9}"
                f"{(cell['p50'] or 0) * 1e3:>10.3f}"
                f"{(cell['p95'] or 0) * 1e3:>10.3f}"
                f"{(cell['p99'] or 0) * 1e3:>10.3f}"
            )
    counters = []
    for label, metric, kw in (
        ("ok", "dpathsim_router_requests_total", {"outcome": "ok"}),
        ("error", "dpathsim_router_requests_total", {"outcome": "error"}),
        ("shed", "dpathsim_router_requests_total", {"outcome": "shed"}),
        ("failovers", "dpathsim_router_failovers_total", {}),
        ("hedges", "dpathsim_router_hedges_total", {}),
        ("dup_responses", "dpathsim_router_dup_responses_total", {}),
        ("ann_fallbacks", "dpathsim_ann_fallbacks_total", {}),
    ):
        v = _sum_matching(merged, metric, **kw)
        if v:
            counters.append(f"{label}={int(v)}")
    if counters:
        lines.append("")
        lines.append("counters: " + "  ".join(counters))
    slo = data.get("slo") or {}
    if slo:
        lines.append("")
        lines.append("slo:")
        lines.append(
            f"{'name':<18}{'objective':>10}{'status':>9}"
            f"{'alerts':>8}  burn rates"
        )
        for name in sorted(slo):
            s = slo[name]
            burns = "  ".join(
                f"{w}={b:.2f}" for w, b in sorted(
                    (s.get("burn") or {}).items()
                )
            )
            lines.append(
                f"{name:<18}{s.get('objective', 0) * 100:>9.2f}%"
                f"{s.get('status', '?'):>9}{s.get('alerts', 0):>8}  {burns}"
            )
    return "\n".join(lines)
