"""Tail-sampled flight recorder: keep the requests worth explaining.

Head sampling (obs/trace.py) decides *before* a request runs whether to
trace it — cheap, but blind: the request you need to explain (the p99
straggler, the one that failed over through a dead worker) is exactly
the one a 1-in-N coin flip probably dropped. Tail sampling decides
*after* the outcome is known. This recorder is the fleet's tail: the
router classifies every resolved request (slow past the p99 target,
errored, shed, hedged, failed over, degraded from ann) and admits the
interesting ones into a bounded ring — 100% of them, independent of
the head-sampling rate, which keeps doing its job for the *ordinary*
traffic.

What a record holds: the request's identity (rid, op, row), outcome,
per-attempt worker history, timing, reasons — and its ``trace_id``.
When tracing is on and the request's head was sampled in, the full
cross-process span tree is recoverable: :meth:`dump` filters the
per-process tracer exports the caller provides down to the kept trace
ids and writes records + span trees as one atomic JSON (the ``spans``
section is directly loadable by :func:`obs.fleet.fleet_chrome_trace`).
A head-sampled-out request still keeps its record — metadata is never
dropped; only the span tree needs the head's cooperation.

Memory discipline matches the tracer ring: a bounded deque, oldest
evicted, eviction counted (``dpathsim_flight_dropped_total``) so a
ring too small for the failure rate is visible instead of silent.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

from .metrics import get_registry

# the classification vocabulary — the router's reasons and the tests'
# assertions share one spelling
REASONS = (
    "slow", "error", "shed", "hedged", "failover", "ann_fallback",
)


class FlightRecorder:
    """Bounded keep-ring of interesting-request records."""

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"flight capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self.kept_total = 0
        self.dropped = 0
        reg = get_registry()
        self._m_kept = reg.counter(
            "dpathsim_flight_kept_total",
            "requests admitted to the flight recorder, by reason "
            "(a request with several reasons counts once per reason)",
        )
        self._m_dropped = reg.counter(
            "dpathsim_flight_dropped_total",
            "flight records evicted by the ring bound",
        ).labels()

    def keep(
        self,
        reasons: list[str] | tuple[str, ...],
        trace_id: int | None = None,
        **meta,
    ) -> None:
        """Admit one record. ``reasons`` is the non-empty classification
        (see :data:`REASONS`); ``meta`` is JSON-safe request detail."""
        record = {
            "reasons": list(reasons),
            "trace_id": trace_id,
            "t_mono": time.monotonic(),
            **meta,
        }
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
                self._m_dropped.inc()
            self._ring.append(record)
            self.kept_total += 1
        for reason in reasons:
            self._m_kept.inc(reason=reason)

    def records(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def kept_trace_ids(self) -> set[int]:
        with self._lock:
            return {
                r["trace_id"] for r in self._ring
                if r.get("trace_id") is not None
            }

    def snapshot(self) -> dict:
        with self._lock:
            records = [dict(r) for r in self._ring]
            return {
                "capacity": self.capacity,
                "kept_total": self.kept_total,
                "dropped": self.dropped,
                "records": records,
            }

    def dump(
        self,
        path: str,
        trace_parts: list[dict] | None = None,
    ) -> dict:
        """Write records + the kept requests' span trees atomically
        (temp file + rename — a dump raced by SIGTERM must never leave
        half a file). ``trace_parts`` are per-process tracer exports
        (router + scraped workers); only spans belonging to kept trace
        ids are retained, each part keeping its pid/wall-anchor so the
        dump's ``spans`` section feeds ``fleet_chrome_trace`` directly.
        Returns the accounting the ``flight_dump`` op answers with."""
        snap = self.snapshot()
        kept = {
            r["trace_id"] for r in snap["records"]
            if r.get("trace_id") is not None
        }
        parts_out = []
        n_spans = 0
        for part in trace_parts or ():
            spans = [
                s for s in part.get("spans", ())
                if s["trace_id"] in kept
            ]
            n_spans += len(spans)
            parts_out.append({
                "pid": part.get("pid"),
                "process": part.get("process"),
                "wall_anchor_us": part.get("wall_anchor_us"),
                "spans": spans,
            })
        from .export import atomic_write

        doc = {**snap, "spans": parts_out}
        atomic_write(path, json.dumps(doc))
        return {
            "path": path,
            "records": len(snap["records"]),
            "kept_total": snap["kept_total"],
            "dropped": snap["dropped"],
            "spans": n_spans,
        }
