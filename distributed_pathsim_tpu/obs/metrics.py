"""Process-wide metrics registry: counters, gauges, streaming histograms.

Until this layer existed the system's quantitative self-knowledge was 18
scattered ``runtime_event`` JSONL lines and ad-hoc ``perf_counter``
deltas — "what is the cache hit rate right now" meant replaying logs.
The registry holds live aggregates instead:

- **Counter** — monotone totals (cache hits, sheds, retries, compiles);
- **Gauge** — last-write-wins instantaneous values (queue depth);
- **Histogram** — bounded-memory streaming latency distributions:
  geometric buckets (``buckets_per_decade`` per power of ten) between
  ``lo`` and ``hi``, plus underflow/overflow, plus exact min/max/sum.
  p50/p95/p99 come from cumulative bucket counts with log-linear
  interpolation inside the landing bucket, clamped to the observed
  min/max — no samples are ever stored, so memory is O(buckets) no
  matter how many observations land (~1 KB per label set at the
  default resolution). Relative quantile error is bounded by the
  bucket width ratio (10^(1/16) ≈ 15% worst case at the default 16
  buckets/decade), verified against ``numpy.percentile`` on
  adversarial distributions by test.

Label support is Prometheus-shaped: a metric family (name + help) fans
out into cells keyed by sorted ``(label, value)`` tuples. Hot paths
bind a cell ONCE (``counter.labels(tier="result")``) and pay one lock +
one add per event thereafter — no registry lookup, no string formatting.

The whole subsystem honors one global switch (``enabled``): disabled,
every ``inc``/``set``/``observe`` is a single attribute check and a
return, so a run that never asks for metrics cannot measure their cost.

Thread safety: creation (get-or-create of families/cells) takes the
registry lock; per-cell mutation takes the cell's own lock — client
threads, the coalescer's dispatcher/completer pair, and the Prometheus
exporter thread all touch these concurrently.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Iterator

# -- histogram bucket geometry ----------------------------------------------

DEFAULT_LO = 1e-6  # 1 µs — below any latency this system can resolve
DEFAULT_HI = 100.0  # 100 s — beyond any single request we'd serve
DEFAULT_BUCKETS_PER_DECADE = 16


def geometric_bounds(
    lo: float = DEFAULT_LO,
    hi: float = DEFAULT_HI,
    buckets_per_decade: int = DEFAULT_BUCKETS_PER_DECADE,
) -> tuple[float, ...]:
    """Upper bucket bounds, geometric between ``lo`` and ``hi``
    inclusive. Bound i covers (bound[i-1], bound[i]]."""
    if not (0 < lo < hi):
        raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
    n = int(math.ceil(math.log10(hi / lo) * buckets_per_decade))
    ratio = 10.0 ** (1.0 / buckets_per_decade)
    bounds = [lo * ratio**i for i in range(n + 1)]
    bounds[-1] = max(bounds[-1], hi)
    return tuple(bounds)


def quantile_from_counts(
    bounds: tuple[float, ...],
    counts: list[int],
    underflow: int,
    count: int,
    vmin: float,
    vmax: float,
    q: float,
) -> float:
    """The quantile estimator as a pure function of bucket state —
    the ONE implementation, shared by live cells and by merged fleet
    snapshots (obs/fleet.py), so a quantile computed at the router from
    exactly-merged buckets is the same number the worker would have
    reported for the same samples. Log-linear interpolation inside the
    landing bucket, clamped to the observed min/max; tail-INCLUSIVE
    nearest-rank convention (target = q·count) — see
    ``_HistogramCell.quantile`` for why the strict walk under-reports
    discrete tails."""
    if count == 0:
        return math.nan
    target = q * count
    cum = float(underflow)
    if target <= cum:
        # inside the underflow bucket: all we know is [min, lo]
        return vmin
    prev_bound = bounds[0]
    for i, c in enumerate(counts):
        if c:
            if target <= cum + c:
                frac = (target - cum) / c
                blo = max(prev_bound, vmin)
                bhi = min(bounds[i], vmax)
                if blo >= bhi:
                    return bhi
                # log-linear: geometric buckets make log-space
                # interpolation the unbiased choice
                return math.exp(
                    math.log(blo) + frac * (math.log(bhi) - math.log(blo))
                )
            cum += c
        prev_bound = bounds[i]
    return vmax  # overflow bucket


class _HistogramCell:
    """One label set's streaming distribution. Bounded memory: bucket
    counts + scalar aggregates, never samples."""

    __slots__ = (
        "_lock", "bounds", "counts", "underflow", "overflow",
        "count", "sum", "min", "max", "_reg",
    )

    def __init__(self, bounds: tuple[float, ...], reg: "MetricsRegistry"):
        self._lock = threading.Lock()
        self.bounds = bounds
        self.counts = [0] * len(bounds)
        self.underflow = 0
        self.overflow = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._reg = reg

    def observe(self, value: float) -> None:
        if not self._reg.enabled:
            return
        v = float(value)
        with self._lock:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            if v <= self.bounds[0]:
                self.underflow += 1
            elif v > self.bounds[-1]:
                self.overflow += 1
            else:
                self.counts[self._bucket_index(v)] += 1

    def _bucket_index(self, v: float) -> int:
        # binary search over the geometric bounds: first bound >= v
        lo, hi = 0, len(self.bounds) - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if self.bounds[mid] < v:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (q in [0, 1]). Log-linear
        interpolation inside the landing bucket, clamped to the exact
        observed min/max (which also makes <lo and >hi values exact at
        the distribution's edges).

        The rank convention is deliberately tail-INCLUSIVE (nearest
        rank, target = q·count): a q·(count−1) walk with a strict
        comparison lands one sample short of the slow mass when the
        tail is a few discrete samples — nine 1 ms requests plus one
        1 s request would report p99 ≈ 1 ms, a 1000× under-report of
        exactly the signal a latency quantile exists to surface."""
        with self._lock:
            return quantile_from_counts(
                self.bounds, self.counts, self.underflow, self.count,
                self.min, self.max, q,
            )

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self.counts)
            base = {
                "count": self.count,
                "sum": self.sum,
                "min": None if self.count == 0 else self.min,
                "max": None if self.count == 0 else self.max,
                "underflow": self.underflow,
                "overflow": self.overflow,
            }
        for key, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            v = self.quantile(q)
            # None, not NaN: snapshots feed json.dumps, and the bare
            # NaN token Python emits is invalid JSON to strict parsers
            base[key] = None if math.isnan(v) else v
        base["_counts"] = counts
        return base

    def reset(self) -> None:
        with self._lock:
            self.counts = [0] * len(self.bounds)
            self.underflow = self.overflow = 0
            self.count = 0
            self.sum = 0.0
            self.min = math.inf
            self.max = -math.inf


class _ScalarCell:
    """One label set's scalar (counter or gauge)."""

    __slots__ = ("_lock", "value", "_reg")

    def __init__(self, reg: "MetricsRegistry"):
        self._lock = threading.Lock()
        self.value = 0.0
        self._reg = reg

    def inc(self, n: float = 1.0) -> None:
        if not self._reg.enabled:
            return
        with self._lock:
            self.value += n

    def set(self, v: float) -> None:
        if not self._reg.enabled:
            return
        with self._lock:
            self.value = float(v)

    def get(self) -> float:
        with self._lock:
            return self.value

    def reset(self) -> None:
        with self._lock:
            self.value = 0.0


def _label_key(labels: dict[str, Any]) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _MetricFamily:
    """Shared machinery: name + help + {label tuple → cell}."""

    kind = "untyped"

    def __init__(self, name: str, help: str, registry: "MetricsRegistry"):
        self.name = name
        self.help = help
        self.registry = registry
        self._cells: dict[tuple, Any] = {}
        self._lock = threading.Lock()

    def _make_cell(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def labels(self, **labels: Any):
        """Get-or-create the cell for one label set. Hot paths call
        this once at setup and keep the cell."""
        key = _label_key(labels)
        cell = self._cells.get(key)
        if cell is None:
            with self._lock:
                cell = self._cells.get(key)
                if cell is None:
                    cell = self._cells[key] = self._make_cell()
        return cell

    def cells(self) -> Iterator[tuple[tuple, Any]]:
        with self._lock:
            return iter(list(self._cells.items()))

    def reset(self) -> None:
        for _, cell in self.cells():
            cell.reset()


class Counter(_MetricFamily):
    """Monotone total. ``inc()`` on the bare family hits the unlabeled
    cell; ``labels(...)`` binds a labeled cell for hot paths."""

    kind = "counter"

    def _make_cell(self) -> _ScalarCell:
        return _ScalarCell(self.registry)

    def inc(self, n: float = 1.0, **labels: Any) -> None:
        self.labels(**labels).inc(n)


class Gauge(_MetricFamily):
    """Last-write-wins instantaneous value."""

    kind = "gauge"

    def _make_cell(self) -> _ScalarCell:
        return _ScalarCell(self.registry)

    def set(self, v: float, **labels: Any) -> None:
        self.labels(**labels).set(v)

    def inc(self, n: float = 1.0, **labels: Any) -> None:
        self.labels(**labels).inc(n)


class Histogram(_MetricFamily):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        registry: "MetricsRegistry",
        bounds: tuple[float, ...] | None = None,
    ):
        super().__init__(name, help, registry)
        self.bounds = bounds or geometric_bounds()

    def _make_cell(self) -> _HistogramCell:
        return _HistogramCell(self.bounds, self.registry)

    def observe(self, value: float, **labels: Any) -> None:
        self.labels(**labels).observe(value)


class MetricsRegistry:
    """Get-or-create home for every metric family in the process."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._metrics: dict[str, _MetricFamily] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, factory, kind: str):
        fam = self._metrics.get(name)
        if fam is None:
            with self._lock:
                fam = self._metrics.get(name)
                if fam is None:
                    fam = self._metrics[name] = factory()
        if fam.kind != kind:
            raise TypeError(
                f"metric {name!r} already registered as {fam.kind}, "
                f"requested {kind}"
            )
        return fam

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(
            name, lambda: Counter(name, help, self), "counter"
        )

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(
            name, lambda: Gauge(name, help, self), "gauge"
        )

    def histogram(
        self, name: str, help: str = "",
        bounds: tuple[float, ...] | None = None,
    ) -> Histogram:
        fam = self._get_or_create(
            name, lambda: Histogram(name, help, self, bounds), "histogram"
        )
        # A family's geometry is fixed by whoever registered it first;
        # silently handing a later caller different buckets than it
        # asked for would corrupt its counts with no error, so conflict
        # is loud (mirrors the kind-mismatch check above).
        if bounds is not None and tuple(bounds) != fam.bounds:
            raise TypeError(
                f"histogram {name!r} already registered with bounds "
                f"{fam.bounds}, requested {tuple(bounds)}"
            )
        return fam

    def families(self) -> list[_MetricFamily]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda f: f.name)

    def snapshot(self) -> dict:
        """The full registry as one JSON-safe dict — the ``metrics``
        protocol op's payload and the extended ``stats()`` source.
        Histogram cells carry p50/p95/p99 precomputed (the caller
        wants quantiles, not raw bucket arrays; ``_counts`` stays for
        tooling that does)."""
        out: dict = {}
        for fam in self.families():
            values = []
            for key, cell in fam.cells():
                labels = dict(key)
                if fam.kind == "histogram":
                    values.append({"labels": labels, **cell.snapshot()})
                else:
                    values.append({"labels": labels, "value": cell.get()})
            out[fam.name] = {
                "type": fam.kind, "help": fam.help, "values": values
            }
            if fam.kind == "histogram":
                # bucket geometry rides the snapshot: an exact merge at
                # the router (obs/fleet.py) is only defined over cells
                # sharing edges, and the merge must be able to CHECK
                # that instead of assuming it
                out[fam.name]["bounds"] = list(fam.bounds)
        return out

    def reset(self) -> None:
        """Zero every cell IN PLACE — bound cells held by hot paths
        stay valid (a registry swap would silently orphan them)."""
        for fam in self.families():
            fam.reset()


# -- process-wide default ----------------------------------------------------

_REGISTRY = MetricsRegistry(enabled=True)
_REGISTRY_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process registry (tests needing full isolation). Hot
    paths that bound cells before the swap keep writing to the OLD
    registry — prefer ``get_registry().reset()`` unless that isolation
    is exactly what you want. Returns the previous registry."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        prev, _REGISTRY = _REGISTRY, registry
    return prev
