"""SLO engine: declarative objectives, multi-window burn-rate alerts.

An SLO here is a *good-fraction* objective over the merged fleet metric
stream (obs/fleet.py): of the events this spec covers, at least
``objective`` must be good. Four kinds map the repo's own signals onto
that shape:

- ``availability`` — a counter family split good/total by labels
  (router requests with ``outcome="ok"`` vs all outcomes);
- ``latency`` — a histogram family + a threshold: good = samples whose
  bucket bound is ≤ the threshold (conservative: a bucket straddling
  the threshold counts bad). Because the fleet merge is bucket-exact,
  this is the same count a single global registry would report;
- ``staleness`` — identical math over the update-visible-by histogram
  (``dpathsim_serve_update_seconds``): ROADMAP item 5's
  bounded-staleness SLO, measured;
- ``gauge_floor`` — a ratio gauge judged against a floor on its WORST
  replica (merged ``min``), folded into the good-fraction stream one
  observation per evaluation (the ann score-recall floor).

Alerting is the multi-window burn-rate scheme (the SRE-workbook one):
``burn = error_rate / error_budget`` computed over each configured
window from cumulative (good, total) deltas; an alert fires only when
EVERY window burns past its threshold — the short window proves it's
happening *now*, the long one proves it isn't a blip — and alerts are
rate-limited per spec. Burn rates surface as
``dpathsim_slo_burn_rate{slo,window}`` gauges and alerts as
``dpathsim_slo_alerts_total{slo}``; the *log* surface is the caller's
(the router passes a ``runtime_event`` callback — obs imports nothing
from the rest of the package, so it cannot emit events itself).
"""

from __future__ import annotations

import dataclasses
import json
from collections import deque
from typing import Callable

from .metrics import get_registry

KINDS = ("availability", "latency", "staleness", "gauge_floor")

# (window_seconds, burn_threshold): the classic fast/slow pairing,
# scaled to this repo's scrape cadence. Tests/smokes override with
# second-scale windows; production overrides via --slo-specs.
DEFAULT_WINDOWS = ((60.0, 14.4), (300.0, 6.0))


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative objective. ``labels`` filters the metric's cells
    (subset match); ``good_labels`` marks the good subset (availability
    kind); ``threshold`` is the latency/staleness bound in seconds, or
    the gauge floor. ``windows`` is ``((seconds, burn_threshold), ...)``
    — every window must burn for an alert."""

    name: str
    kind: str
    metric: str
    objective: float
    threshold: float | None = None
    labels: tuple = ()
    good_labels: tuple = ()
    windows: tuple = DEFAULT_WINDOWS

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown SLO kind {self.kind!r}; choose one of {KINDS}"
            )
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective} — "
                "1.0 leaves a zero error budget and burn is undefined"
            )
        if self.kind in ("latency", "staleness", "gauge_floor") and (
            self.threshold is None
        ):
            raise ValueError(f"SLO kind {self.kind!r} needs a threshold")
        if not self.windows:
            raise ValueError("an SLO needs at least one window")


def default_specs(
    latency_p99_s: float = 0.25,
    staleness_p99_s: float = 5.0,
    availability: float = 0.999,
    recall_floor: float = 0.98,
    cold_start_floor: float = 0.95,
    windows: tuple = DEFAULT_WINDOWS,
) -> tuple[SLOSpec, ...]:
    """The shipped fleet objectives — every one reads a metric this
    repo already emits, so the engine works on day one with no config:
    availability and p99 latency over the router's request stream,
    update-visible-by staleness over the delta path, the ann
    score-recall floor (worst replica), and the learned tier's
    cold-start answerability floor (fraction of appended rows already
    absorbed into the towers, worst replica — a replica falling behind
    on absorbs is answering its cold-start authors through counted
    fallbacks instead of the learned arm)."""
    return (
        SLOSpec(
            name="availability", kind="availability",
            metric="dpathsim_router_requests_total",
            objective=availability, good_labels=(("outcome", "ok"),),
            windows=windows,
        ),
        SLOSpec(
            name="latency_p99", kind="latency",
            metric="dpathsim_router_request_seconds",
            objective=0.99, threshold=latency_p99_s, windows=windows,
        ),
        SLOSpec(
            name="update_visible", kind="staleness",
            metric="dpathsim_serve_update_seconds",
            objective=0.99, threshold=staleness_p99_s, windows=windows,
        ),
        SLOSpec(
            name="ann_recall", kind="gauge_floor",
            metric="dpathsim_ann_recall_ratio",
            objective=0.99, threshold=recall_floor, windows=windows,
        ),
        SLOSpec(
            name="cold_start_answerable", kind="gauge_floor",
            metric="dpathsim_learned_cold_start_ratio",
            objective=0.99, threshold=cold_start_floor,
            windows=windows,
        ),
    )


def specs_from_json(text: str) -> tuple[SLOSpec, ...]:
    """Parse a JSON list of spec dicts (the ``--slo-specs`` file).
    Label maps become the tuple form; unknown keys are rejected loudly
    (a typoed field silently ignored would be an SLO that never
    fires)."""
    raw = json.loads(text)
    if not isinstance(raw, list):
        raise ValueError("SLO spec file must be a JSON list of objects")
    specs = []
    fields = {f.name for f in dataclasses.fields(SLOSpec)}
    for entry in raw:
        unknown = set(entry) - fields
        if unknown:
            raise ValueError(
                f"unknown SLO spec fields {sorted(unknown)} in "
                f"{entry.get('name', '?')!r}"
            )
        for key in ("labels", "good_labels"):
            if isinstance(entry.get(key), dict):
                entry[key] = tuple(sorted(entry[key].items()))
        if "windows" in entry:
            entry["windows"] = tuple(
                (float(w), float(b)) for w, b in entry["windows"]
            )
        specs.append(SLOSpec(**entry))
    return tuple(specs)


def _matches(cell_labels: dict, want: tuple) -> bool:
    return all(cell_labels.get(k) == str(v) for k, v in want)


def good_total_from_snapshot(
    spec: SLOSpec, merged: dict
) -> tuple[float, float]:
    """Extract this spec's CUMULATIVE (good, total) from a merged fleet
    snapshot. For ``gauge_floor`` the return is the instantaneous
    verdict ``(1|0, 1)`` — the engine accumulates it."""
    fam = merged.get(spec.metric)
    if not fam:
        return 0.0, 0.0
    cells = [
        c for c in fam["values"] if _matches(c["labels"], spec.labels)
    ]
    if spec.kind == "availability":
        total = sum(c["value"] for c in cells)
        good = sum(
            c["value"] for c in cells
            if _matches(c["labels"], spec.good_labels)
        )
        return good, total
    if spec.kind in ("latency", "staleness"):
        bounds = fam.get("bounds") or []
        good = total = 0.0
        for c in cells:
            total += c["count"]
            good += c["underflow"]
            for bound, n in zip(bounds, c["_counts"]):
                if bound <= spec.threshold:
                    good += n
        return good, total
    # gauge_floor: the worst replica must clear the floor
    if not cells:
        return 0.0, 0.0
    worst = min(c.get("min", c["value"]) for c in cells)
    return (1.0 if worst >= spec.threshold else 0.0), 1.0


class SLOEngine:
    """Evaluates specs over a stream of merged snapshots.

    ``observe(merged, now)`` is called by the router after each scrape
    merge; it appends each spec's cumulative (good, total) to a
    monotonic-time ring, computes every window's burn rate from the
    deltas, publishes the gauges, and fires rate-limited alerts through
    ``on_alert`` when all windows burn. Windowed deltas over
    *cumulative* counters make the math insensitive to scrape jitter
    and to how many evaluations land inside a window."""

    def __init__(
        self,
        specs: tuple[SLOSpec, ...],
        on_alert: Callable[[dict], None] | None = None,
        min_alert_gap_s: float = 30.0,
    ):
        self.specs = tuple(specs)
        self.on_alert = on_alert
        self.min_alert_gap_s = float(min_alert_gap_s)
        max_w = max(
            (w for spec in self.specs for w, _ in spec.windows),
            default=0.0,
        )
        self._horizon = max_w * 1.5 + 1.0
        self._series: dict[str, deque] = {
            s.name: deque() for s in self.specs
        }
        self._cum_gauge: dict[str, tuple[float, float]] = {}
        self._last_alert: dict[str, float] = {}
        self._burn: dict[str, dict[str, float]] = {
            s.name: {} for s in self.specs
        }
        self.alert_counts: dict[str, int] = {s.name: 0 for s in self.specs}
        reg = get_registry()
        self._g_burn = reg.gauge(
            "dpathsim_slo_burn_rate",
            "error-budget burn rate per SLO and window (1.0 = burning "
            "exactly the budget)",
        )
        self._c_alerts = reg.counter(
            "dpathsim_slo_alerts_total",
            "multi-window burn-rate alerts fired, by SLO",
        )

    def observe(self, merged: dict, now: float) -> list[dict]:
        """Fold one merged snapshot in; returns the alerts fired (also
        delivered via ``on_alert``). ``now`` is monotonic seconds —
        burn windows are durations, never wall clock."""
        alerts: list[dict] = []
        for spec in self.specs:
            good, total = good_total_from_snapshot(spec, merged)
            if spec.kind == "gauge_floor":
                pg, pt = self._cum_gauge.get(spec.name, (0.0, 0.0))
                good, total = pg + good, pt + total
                self._cum_gauge[spec.name] = (good, total)
            series = self._series[spec.name]
            series.append((now, good, total))
            while series and series[0][0] < now - self._horizon:
                series.popleft()
            burns: dict[str, float] = {}
            burning = True
            budget = 1.0 - spec.objective
            for window_s, threshold in spec.windows:
                base = series[0]
                for sample in series:
                    if sample[0] >= now - window_s:
                        base = sample
                        break
                dg = good - base[1]
                dt = total - base[2]
                if dt <= 0:
                    burn = 0.0
                else:
                    burn = max(0.0, 1.0 - dg / dt) / budget
                key = f"{window_s:g}s"
                burns[key] = burn
                self._g_burn.set(burn, slo=spec.name, window=key)
                if burn < threshold or dt <= 0:
                    burning = False
            self._burn[spec.name] = burns
            if burning:
                last = self._last_alert.get(spec.name)
                if last is None or now - last >= self.min_alert_gap_s:
                    self._last_alert[spec.name] = now
                    self.alert_counts[spec.name] += 1
                    self._c_alerts.inc(slo=spec.name)
                    info = {
                        "slo": spec.name,
                        "kind": spec.kind,
                        "objective": spec.objective,
                        "burn": dict(burns),
                        "good": good,
                        "total": total,
                    }
                    alerts.append(info)
                    if self.on_alert is not None:
                        self.on_alert(info)
        return alerts

    def snapshot(self) -> dict:
        """Per-SLO status for ``fleet_metrics`` / ``fleet-stats``."""
        out = {}
        for spec in self.specs:
            series = self._series[spec.name]
            good, total = (series[-1][1], series[-1][2]) if series else (0, 0)
            burns = self._burn[spec.name]
            out[spec.name] = {
                "kind": spec.kind,
                "metric": spec.metric,
                "objective": spec.objective,
                "threshold": spec.threshold,
                "good": good,
                "total": total,
                "burn": dict(burns),
                "alerts": self.alert_counts[spec.name],
                "status": (
                    "burning"
                    if burns and all(
                        burns.get(f"{w:g}s", 0.0) >= t
                        for w, t in spec.windows
                    ) and total > 0
                    else "ok"
                ),
            }
        return out
