"""Exporters: Prometheus text exposition + Perfetto trace files.

The registry (metrics.py) and tracer (trace.py) hold live state; this
module is the only place that knows on-disk/wire formats:

- :func:`render_prometheus` — the registry as Prometheus text
  exposition format 0.0.4 (``# HELP``/``# TYPE`` headers, cumulative
  ``_bucket{le=...}`` histogram series, ``_sum``/``_count``);
- :func:`write_textfile` — one atomic snapshot: write to a temp file
  in the target directory, ``os.replace`` over the destination, so a
  node-exporter textfile collector (or a test) can never read a
  half-written scrape;
- :class:`PrometheusTextfileExporter` — a daemon thread re-writing the
  textfile on an interval (``--metrics-file`` on ``dpathsim serve``),
  with a final write on ``stop()`` so shutdown state is never lost;
- :func:`write_chrome_trace` — the tracer ring as Perfetto-loadable
  JSON (delegates to the tracer, which owns the clock anchor).
"""

from __future__ import annotations

import math
import os
import threading

from .metrics import MetricsRegistry, get_registry
from .trace import Tracer, get_tracer


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: dict[str, str], extra: str = "") -> str:
    parts = [
        f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
    ]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def atomic_write(path: str, text: str) -> None:
    """THE atomic file write: temp file in the destination directory
    (``os.replace`` must not cross filesystems) + rename, so no reader
    — a textfile collector, a trace viewer, a dump raced by SIGTERM —
    can ever see half a file. Every obs artifact goes through here."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
    os.replace(tmp, path)


def render_prometheus(registry: MetricsRegistry | None = None) -> str:
    """Prometheus text exposition format 0.0.4. Histograms render with
    cumulative ``le`` buckets (underflow folds into the first bound,
    overflow into ``+Inf``), which is exactly how promql's
    ``histogram_quantile`` expects them."""
    registry = registry or get_registry()
    lines: list[str] = []
    for fam in registry.families():
        lines.append(f"# HELP {fam.name} {fam.help or fam.name}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for key, cell in fam.cells():
            labels = dict(key)
            if fam.kind == "histogram":
                snap = cell.snapshot()
                cum = snap["underflow"]
                for bound, c in zip(cell.bounds, snap["_counts"]):
                    cum += c
                    le = 'le="{}"'.format(_fmt_value(bound))
                    lines.append(
                        f"{fam.name}_bucket{_fmt_labels(labels, le)} {cum}"
                    )
                le_inf = 'le="+Inf"'
                lines.append(
                    f"{fam.name}_bucket{_fmt_labels(labels, le_inf)}"
                    f" {snap['count']}"
                )
                lines.append(
                    f"{fam.name}_sum{_fmt_labels(labels)}"
                    f" {_fmt_value(snap['sum'])}"
                )
                lines.append(
                    f"{fam.name}_count{_fmt_labels(labels)} {snap['count']}"
                )
            else:
                lines.append(
                    f"{fam.name}{_fmt_labels(labels)}"
                    f" {_fmt_value(cell.get())}"
                )
    return "\n".join(lines) + "\n"


def write_textfile(
    path: str, registry: MetricsRegistry | None = None
) -> None:
    """One atomic Prometheus snapshot (see :func:`atomic_write`)."""
    atomic_write(path, render_prometheus(registry))


class IntervalFileExporter:
    """The interval-writer lifecycle, shared by the per-process and
    fleet exporters: a daemon thread calls :meth:`write` every
    ``interval_s`` (plus once at start, so the file is visible
    immediately), swallowing transient OSErrors (metrics export must
    never take the server down — the next interval retries); ``stop()``
    performs a final write so the file always reflects the process's
    last state. Start/stop are idempotent. Subclasses implement
    :meth:`write`."""

    thread_name = "pathsim-export"

    def __init__(self, interval_s: float = 5.0):
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def write(self) -> None:  # pragma: no cover - overridden
        raise NotImplementedError

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        # the immediate first write is LOUD: an unwritable path is a
        # config error the operator must see at startup, not a file
        # that silently never appears
        self.write()
        self._thread = threading.Thread(
            target=self._loop, name=self.thread_name, daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.write()
            except OSError:
                pass

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=10)
        self._thread = None
        try:
            self.write()  # shutdown state preserved
        except OSError:
            pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class PrometheusTextfileExporter(IntervalFileExporter):
    """Background interval writer for the textfile-collector pattern
    (``--metrics-file`` on the per-process CLIs)."""

    thread_name = "pathsim-metrics-export"

    def __init__(
        self,
        path: str,
        interval_s: float = 5.0,
        registry: MetricsRegistry | None = None,
    ):
        super().__init__(interval_s)
        self.path = path
        self._registry = registry

    def write(self) -> None:
        write_textfile(self.path, self._registry)


def write_chrome_trace(path: str, tracer: Tracer | None = None) -> int:
    """Dump the tracer's finished-span ring as Perfetto-loadable JSON;
    returns the number of span events written."""
    return (tracer or get_tracer()).write_chrome_trace(path)
