"""Observability: hierarchical tracing spans, metrics, exporters.

See DESIGN.md §20. Public surface:

- :func:`get_tracer` / :class:`Tracer` / :class:`SpanContext` —
  hierarchical spans with cross-thread context propagation, exportable
  as Chrome/Perfetto trace-event JSON (trace.py);
- :func:`get_registry` / :class:`MetricsRegistry` — process-wide
  counters, gauges, and bounded-memory streaming histograms
  (metrics.py);
- :func:`render_prometheus` / :func:`write_textfile` /
  :class:`PrometheusTextfileExporter` / :func:`write_chrome_trace` —
  the on-disk/wire formats (export.py);
- :func:`configure` — the one switch the CLIs and benches flip.

Layering: this package imports nothing from the rest of
``distributed_pathsim_tpu`` — everything else (serving, resilience,
engine, driver, backends, utils) imports obs, never the reverse.
"""

from __future__ import annotations

from .export import (
    PrometheusTextfileExporter,
    render_prometheus,
    write_chrome_trace,
    write_textfile,
)
from .metrics import (
    MetricsRegistry,
    geometric_bounds,
    get_registry,
    set_registry,
)
from .trace import Span, SpanContext, Tracer, get_tracer

__all__ = [
    "MetricsRegistry",
    "PrometheusTextfileExporter",
    "Span",
    "SpanContext",
    "Tracer",
    "configure",
    "dump_trace",
    "geometric_bounds",
    "get_registry",
    "get_tracer",
    "render_prometheus",
    "set_registry",
    "write_chrome_trace",
    "write_textfile",
]


def configure(
    metrics: bool | None = None,
    tracing: bool | None = None,
    max_spans: int | None = None,
    device_annotations: bool | None = None,
    trace_sample: int | None = None,
) -> None:
    """Flip the process-wide observability switches. ``None`` leaves a
    switch untouched. Metrics default ON (aggregation is cheap and the
    ``metrics``/``stats`` ops should always have answers); tracing
    defaults OFF (span objects per request are only worth it when
    someone will read the trace). ``trace_sample=n`` traces every nth
    request head (1 = all; sustained production traffic wants a larger
    n — span bookkeeping is serialized Python, see DESIGN.md §20)."""
    if metrics is not None:
        get_registry().enabled = metrics
    get_tracer().configure(
        enabled=tracing,
        max_spans=max_spans,
        device_annotations=device_annotations,
        sample_every=trace_sample,
    )


def dump_trace(path: str) -> str:
    """Write the span ring as Perfetto-loadable JSON and return the
    one-line human summary both CLIs print at exit (the CLI prints it —
    library code never writes raw stderr, lint_telemetry R2)."""
    n = write_chrome_trace(path)
    return f"trace: {n} spans -> {path} (load in https://ui.perfetto.dev)"
