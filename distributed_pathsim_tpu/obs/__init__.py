"""Observability: hierarchical tracing spans, metrics, exporters.

See DESIGN.md §20 (single process) and §24 (fleet). Public surface:

- :func:`get_tracer` / :class:`Tracer` / :class:`SpanContext` —
  hierarchical spans with cross-thread context propagation, exportable
  as Chrome/Perfetto trace-event JSON (trace.py); :func:`to_wire` /
  :func:`from_wire` carry span identity + the sampling decision across
  process boundaries on the JSONL protocol;
- :func:`get_registry` / :class:`MetricsRegistry` — process-wide
  counters, gauges, and bounded-memory streaming histograms
  (metrics.py);
- :func:`render_prometheus` / :func:`write_textfile` /
  :class:`PrometheusTextfileExporter` / :func:`write_chrome_trace` —
  the on-disk/wire formats (export.py);
- fleet.py — exact (bucket-wise, associative) merge of per-process
  registry snapshots, per-worker-labeled fleet Prometheus export,
  stitched multi-process Perfetto traces + the parent-link audit;
- :class:`SLOEngine` / :class:`SLOSpec` (slo.py) — declarative
  objectives over the merged stream with multi-window burn-rate
  alerts;
- :class:`FlightRecorder` (flight.py) — the tail-sampling complement
  to head sampling: retroactively keep slow/errored/shed/hedged/
  failed-over requests' records and span trees;
- :func:`configure` — the one switch the CLIs and benches flip.

Layering: this package imports nothing from the rest of
``distributed_pathsim_tpu`` — everything else (serving, resilience,
engine, driver, backends, utils) imports obs, never the reverse.
"""

from __future__ import annotations

from .export import (
    PrometheusTextfileExporter,
    render_prometheus,
    write_chrome_trace,
    write_textfile,
)
from .fleet import (
    FleetTextfileExporter,
    audit_fleet_traces,
    fleet_chrome_trace,
    merge_histogram_cells,
    merge_registry_snapshots,
    render_fleet_prometheus,
    render_fleet_stats,
    write_fleet_textfile,
    write_fleet_trace,
)
from .flight import FlightRecorder
from .metrics import (
    MetricsRegistry,
    geometric_bounds,
    get_registry,
    quantile_from_counts,
    set_registry,
)
from .slo import SLOEngine, SLOSpec, default_specs, specs_from_json
from .trace import (
    Span,
    SpanContext,
    Tracer,
    from_wire,
    get_tracer,
    to_wire,
)

__all__ = [
    "FleetTextfileExporter",
    "FlightRecorder",
    "MetricsRegistry",
    "PrometheusTextfileExporter",
    "SLOEngine",
    "SLOSpec",
    "Span",
    "SpanContext",
    "Tracer",
    "audit_fleet_traces",
    "configure",
    "default_specs",
    "dump_trace",
    "fleet_chrome_trace",
    "from_wire",
    "geometric_bounds",
    "get_registry",
    "get_tracer",
    "merge_histogram_cells",
    "merge_registry_snapshots",
    "quantile_from_counts",
    "render_fleet_prometheus",
    "render_fleet_stats",
    "render_prometheus",
    "set_registry",
    "specs_from_json",
    "to_wire",
    "write_chrome_trace",
    "write_fleet_textfile",
    "write_fleet_trace",
    "write_textfile",
]


def configure(
    metrics: bool | None = None,
    tracing: bool | None = None,
    max_spans: int | None = None,
    device_annotations: bool | None = None,
    trace_sample: int | None = None,
) -> None:
    """Flip the process-wide observability switches. ``None`` leaves a
    switch untouched. Metrics default ON (aggregation is cheap and the
    ``metrics``/``stats`` ops should always have answers); tracing
    defaults OFF (span objects per request are only worth it when
    someone will read the trace). ``trace_sample=n`` traces every nth
    request head (1 = all; sustained production traffic wants a larger
    n — span bookkeeping is serialized Python, see DESIGN.md §20)."""
    if metrics is not None:
        get_registry().enabled = metrics
    get_tracer().configure(
        enabled=tracing,
        max_spans=max_spans,
        device_annotations=device_annotations,
        sample_every=trace_sample,
    )


def dump_trace(path: str) -> str:
    """Write the span ring as Perfetto-loadable JSON and return the
    one-line human summary both CLIs print at exit (the CLI prints it —
    library code never writes raw stderr, lint_telemetry R2)."""
    n = write_chrome_trace(path)
    return f"trace: {n} spans -> {path} (load in https://ui.perfetto.dev)"
