"""Single-device jit'd dense backend (BASELINE.json config 2).

The minimum end-to-end TPU slice: the whole commuting-matrix chain is one
jit-compiled program of staged matmuls on device. f32 throughout with f32
accumulation — exact for integer path counts below 2²⁴ (dblp-scale row
sums are ≤ ~1.2e4; validity is asserted, not assumed). ``highest``
matmul precision keeps the MXU from silently dropping to bf16 inputs,
which WOULD truncate counts ≥ 257 (SURVEY.md §7).

All-pairs scoring runs fully on device: the pallas fused
matmul+normalize kernel on TPU (M never hits HBM), the equivalent XLA
program elsewhere — the host only receives the final score matrix.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import chain
from ..ops import pallas_kernels as pk
from ..ops import sparse as sp
from .base import PathSimBackend, register_backend


@jax.jit
def _chain_outputs(blocks):
    """(M, rowsums) for a non-symmetric oriented chain, on device.

    ``highest`` matmul precision: counts are integers, bf16-pass matmuls
    would truncate them.
    """
    with jax.default_matmul_precision("highest"):
        m = chain.chain_product(blocks, xp=jnp)
        rowsums = jnp.sum(m, axis=1)
    return m, rowsums


@functools.partial(jax.jit, static_argnames=("shape",))
def _half_outputs_coo(rows, cols, weights, shape):
    """(C, rowsums) assembled on device from the host-folded COO factor.

    Adjacency blocks are ~0.1% dense at DBLP scale; shipping the folded
    half-chain as COO and scatter-adding it into C on device replaces a
    multi-GB host→HBM transfer plus an O(N·P·V) GEMM with an O(nnz)
    scatter — the half-chain becomes free relative to the scoring pass.
    """
    c = jnp.zeros(shape, dtype=weights.dtype).at[rows, cols].add(weights)
    with jax.default_matmul_precision("highest"):
        return c, chain.rowsums_from_half(c, xp=jnp)


@jax.jit
def _m_from_half(c):
    with jax.default_matmul_precision("highest"):
        return jnp.matmul(c, c.T)


@jax.jit
def _rowsums_asym(blocks):
    """Row sums of an arbitrary chain by folding the ones-vector from the
    right — never materializes anything wider than a block."""
    with jax.default_matmul_precision("highest"):
        return chain.rowsums_general(blocks, xp=jnp)


@register_backend("jax")
class JaxDenseBackend(PathSimBackend):
    """Dense chain on one device (TPU when available, else host backend)."""

    def __init__(self, hin, metapath, dtype=jnp.float32, device=None,
                 use_pallas: bool | None = None, exact_counts: bool = True,
                 **options):
        """``exact_counts=False`` mirrors the sparse backend's approx
        mode: waives the f32 2^24 exact-integer guard for graphs whose
        path counts overflow it by construction (scores are
        scale-invariant ratios in C, so the cost is ~1e-6 relative
        rounding, inside the 1e-5 gate — jax_sparse.py has the full
        argument). Needed when the dense-resident path runs the
        million-author regime on a TPU (C [1M, V] is only ~256 MB at
        V=64; the guard, not memory, is what would refuse it)."""
        super().__init__(hin, metapath, **options)
        self.dtype = dtype
        self.exact_counts = exact_counts
        self.use_pallas = pk.pallas_supported() if use_pallas is None else use_pallas
        self._symmetric = metapath.is_symmetric
        if self._symmetric:
            # Sparse-first: only the folded COO indices cross host→device
            # (O(nnz), not O(N·P) dense blocks); C is scatter-assembled
            # inside jit. See _half_outputs_coo.
            coo = sp.half_chain_coo(hin, metapath)
            self._c_shape = coo.shape
            self._coo = tuple(
                jax.device_put(jnp.asarray(a, dt), device)
                for a, dt in (
                    (coo.rows, jnp.int32),
                    (coo.cols, jnp.int32),
                    (coo.weights, dtype),
                )
            )
            self._blocks = None
        else:
            host_blocks = chain.oriented_dense_blocks(
                hin, metapath.steps, dtype=np.float32
            )
            self._blocks = [
                jax.device_put(jnp.asarray(b, dtype=dtype), device)
                for b in host_blocks
            ]
        self._m = None
        self._rowsums = None
        self._half_cache = None

    def _half(self):
        """(C, rowsums) on device for a symmetric chain.

        Cached: the factor is a per-graph constant, and on a tunneled
        TPU every re-dispatch costs a ~70 ms RPC — repeated topk() calls
        (rank-all driver loops, benchmark reps) should pay for the
        scoring pass, not for re-assembling an immutable array."""
        if self._half_cache is None:
            rows, cols, weights = self._coo
            self._half_cache = _half_outputs_coo(
                rows, cols, weights, self._c_shape
            )
        return self._half_cache

    def _compute(self):
        if self._m is None:
            if self._symmetric:
                c, rowsums = self._half()
                m = _m_from_half(c)
            else:
                m, rowsums = _chain_outputs(self._blocks)
            self._m = np.asarray(m, dtype=np.float64)
            self._rowsums = np.asarray(rowsums, dtype=np.float64)
            self._check_exact(self._rowsums)
        return self._m, self._rowsums

    def _check_exact(self, rowsums: np.ndarray) -> None:
        if self.exact_counts:
            chain.check_exact_counts(rowsums.max(initial=0.0), self.dtype)

    def commuting_matrix(self) -> np.ndarray:
        return self._compute()[0]

    def global_walks(self) -> np.ndarray:
        if self._rowsums is None and self._m is None:
            # cheap path: rowsums without materializing M
            if self._symmetric:
                _, rowsums = self._half()
            else:
                rowsums = _rowsums_asym(self._blocks)
            self._rowsums = np.asarray(rowsums, dtype=np.float64)
            self._check_exact(self._rowsums)
        elif self._rowsums is None:
            self._compute()
        return self._rowsums

    def pairwise_row(self, source_index: int) -> np.ndarray:
        return self._compute()[0][source_index]

    # -- on-device scoring fast paths -------------------------------------

    def all_pairs_scores(self, variant: str = "rowsum") -> np.ndarray:
        if not self._symmetric or variant != "rowsum":
            return super().all_pairs_scores(variant)
        c, rowsums = self._half()
        if self.use_pallas:
            if pk.fits_vmem(c.shape[1]):
                scores = pk.fused_scores(c, rowsums)
            else:
                scores = pk.fused_scores_ktiled(c, rowsums)
        else:
            scores = pk.fused_scores_reference(c, rowsums)
        # Fetch + exactness check AFTER the kernel dispatch (async, so
        # the transfer rides along) — and only once per backend: the
        # rowsums are as immutable as the graph.
        if self._rowsums is None:
            self._rowsums = np.asarray(rowsums, dtype=np.float64)
            self._check_exact(self._rowsums)
        return np.asarray(scores)

    def topk(self, k: int = 10, mask_self: bool = True):
        """Per-source top-k (values, indices), fully on device."""
        if not self._symmetric:
            raise ValueError("topk fast path requires a symmetric metapath")
        c, rowsums = self._half()
        if self.use_pallas and k <= pk._CAND and pk.twopass_fits(c.shape[0]):
            # Fastest path: candidate extraction + XLA reduce (handles
            # any V internally). Beyond the candidate-buffer HBM budget
            # (~92k rows — twopass_fits) the fold kernel takes over.
            vals, idxs = pk.fused_topk_twopass(
                c, rowsums, k=k, mask_self=mask_self
            )
        elif self.use_pallas and not pk.fits_vmem(c.shape[1]):
            vals, idxs = pk.fused_topk_ktiled(c, rowsums, k=k, mask_self=mask_self)
        elif self.use_pallas:
            vals, idxs = pk.fused_topk(c, rowsums, k=k, mask_self=mask_self)
        else:
            scores = pk.fused_scores_reference(c, rowsums)
            if mask_self:
                n = scores.shape[0]
                scores = jnp.where(jnp.eye(n, dtype=bool), -jnp.inf, scores)
            vals, idxs = jax.lax.top_k(scores, k)
        if self._rowsums is None:
            self._rowsums = np.asarray(rowsums, dtype=np.float64)
            self._check_exact(self._rowsums)
        # One batched transfer for both outputs: on the tunneled TPU two
        # np.asarray fetches are two ~70 ms round-trips.
        vals_h, idxs_h = jax.device_get((vals, idxs))
        return np.asarray(vals_h), np.asarray(idxs_h)
