"""Single-device jit'd dense backend (BASELINE.json config 2).

The minimum end-to-end TPU slice: the whole commuting-matrix chain is one
jit-compiled program of staged matmuls on device. f32 throughout with f32
accumulation — exact for integer path counts below 2²⁴ (dblp-scale row
sums are ≤ ~1.2e4; validity is asserted, not assumed). ``highest``
matmul precision keeps the MXU from silently dropping to bf16 inputs,
which WOULD truncate counts ≥ 257 (SURVEY.md §7).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import chain
from .base import PathSimBackend, register_backend

# f32 represents every integer exactly up to 2**24.
_F32_EXACT_INT_MAX = float(2**24)


@functools.partial(jax.jit, static_argnames=("symmetric",))
def _chain_outputs(blocks, symmetric: bool):
    """Compute (M, rowsums) for the oriented chain on device.

    ``highest`` matmul precision: counts are integers, bf16-pass matmuls
    would truncate them.
    """
    with jax.default_matmul_precision("highest"):
        if symmetric:
            c = chain.half_product(blocks, xp=jnp)
            m = jnp.matmul(c, c.T)
            rowsums = chain.rowsums_from_half(c, xp=jnp)
        else:
            m = chain.chain_product(blocks, xp=jnp)
            rowsums = jnp.sum(m, axis=1)
    return m, rowsums


@register_backend("jax")
class JaxDenseBackend(PathSimBackend):
    """Dense chain on one device (TPU when available, else host backend)."""

    def __init__(self, hin, metapath, dtype=jnp.float32, device=None, **options):
        super().__init__(hin, metapath, **options)
        self.dtype = dtype
        steps = metapath.half() if metapath.is_symmetric else metapath.steps
        host_blocks = chain.oriented_dense_blocks(hin, steps, dtype=np.float32)
        self._blocks = [
            jax.device_put(jnp.asarray(b, dtype=dtype), device) for b in host_blocks
        ]
        self._symmetric = metapath.is_symmetric
        self._m = None
        self._rowsums = None

    def _compute(self):
        if self._m is None:
            m, rowsums = _chain_outputs(self._blocks, self._symmetric)
            self._m = np.asarray(m, dtype=np.float64)
            self._rowsums = np.asarray(rowsums, dtype=np.float64)
            if self.dtype == jnp.float32 and self._rowsums.max(initial=0.0) >= _F32_EXACT_INT_MAX:
                raise OverflowError(
                    "path counts exceed f32 exact-integer range (2^24); "
                    "rerun with dtype=jnp.float64 (requires JAX_ENABLE_X64)"
                )
        return self._m, self._rowsums

    def commuting_matrix(self) -> np.ndarray:
        return self._compute()[0]

    def global_walks(self) -> np.ndarray:
        return self._compute()[1]

    def pairwise_row(self, source_index: int) -> np.ndarray:
        return self._compute()[0][source_index]
