"""Single-device jit'd dense backend (BASELINE.json config 2).

The minimum end-to-end TPU slice: the whole commuting-matrix chain is one
jit-compiled program of staged matmuls on device. f32 throughout with f32
accumulation — exact for integer path counts below 2²⁴ (dblp-scale row
sums are ≤ ~1.2e4; validity is asserted, not assumed). ``highest``
matmul precision keeps the MXU from silently dropping to bf16 inputs,
which WOULD truncate counts ≥ 257 (SURVEY.md §7).

All-pairs scoring runs fully on device: the pallas fused
matmul+normalize kernel on TPU (M never hits HBM), the equivalent XLA
program elsewhere — the host only receives the final score matrix.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import chain
from ..ops import pallas_kernels as pk
from ..ops import planner
from .base import PathSimBackend, register_backend


@functools.lru_cache(maxsize=None)
def _chain_outputs_for(order):
    """(M, rowsums) program for a non-symmetric oriented chain, on
    device, in the PLAN's association order. One jitted program per
    order tree (lru-cached at module level, like every other compiled
    core here): the plan resolves outside the jit, so a rebuilt backend
    over the same chain reuses the compiled program — the zero
    steady-state-recompile contract extended to general metapaths.

    ``highest`` matmul precision: counts are integers, bf16-pass
    matmuls would truncate them.
    """

    @jax.jit
    def run(blocks):
        with jax.default_matmul_precision("highest"):
            m = planner.execute_dense_order(order, list(blocks), xp=jnp)
            rowsums = jnp.sum(m, axis=1)
        return m, rowsums

    return run


@functools.partial(jax.jit, static_argnames=("shape",))
def _half_outputs_coo(rows, cols, weights, shape):
    """(C, rowsums) assembled on device from the host-folded COO factor.

    Adjacency blocks are ~0.1% dense at DBLP scale; shipping the folded
    half-chain as COO and scatter-adding it into C on device replaces a
    multi-GB host→HBM transfer plus an O(N·P·V) GEMM with an O(nnz)
    scatter — the half-chain becomes free relative to the scoring pass.
    """
    c = jnp.zeros(shape, dtype=weights.dtype).at[rows, cols].add(weights)
    with jax.default_matmul_precision("highest"):
        return c, chain.rowsums_from_half(c, xp=jnp)


@jax.jit
def _m_from_half(c):
    with jax.default_matmul_precision("highest"):
        return jnp.matmul(c, c.T)


@jax.jit
def _apply_coo_delta(c, rows, cols, weights):
    """Patch the device-resident factor with a signed COO delta. The
    delta is padded to a power-of-two nnz (weight-0 entries scatter
    harmlessly), so steady-state updates reuse one compiled program per
    nnz bucket — the recompile-free-serving contract."""
    return c.at[rows, cols].add(weights)


@jax.jit
def _rowsums_only(c):
    with jax.default_matmul_precision("highest"):
        return chain.rowsums_from_half(c, xp=jnp)


@jax.jit
def _diag_from_half(c):
    """diag(M)[i] = Σ_v C[i,v]² — the textbook-PathSim denominator,
    without materializing M."""
    return jnp.sum(c * c, axis=1)


@jax.jit
def _pairwise_rows_half(c, rows):
    """M[rows, :] = C[rows] @ Cᵀ — one batched GEMM for a whole serving
    bucket. jit specializes on the (static) batch length, so the serving
    layer's power-of-two padding means XLA compiles exactly one program
    per bucket; every request batch after warmup reuses a cached
    executable."""
    with jax.default_matmul_precision("highest"):
        return jnp.matmul(jnp.take(c, rows, axis=0), c.T)


@jax.jit
def _rowsums_asym(blocks):
    """Row sums of an arbitrary chain by folding the ones-vector from the
    right — never materializes anything wider than a block (a vector
    fold is association-optimal already; the planner sanctions it)."""
    with jax.default_matmul_precision("highest"):
        return planner.rowsums_fold(blocks, xp=jnp)


def _pad_coo_bucket(rows, cols, weights):
    """Pad a COO triple to a power-of-two nnz bucket (floor 8): both
    the construction-time factor scatter and the delta patch trace on
    the padded length, so steady-state rebuilds and updates reuse one
    compiled program per bucket. Pad entries carry weight 0 at (0, 0)
    and scatter harmlessly. One definition for both sites — the
    compile-cache keying must never drift between them."""
    nnz = int(rows.shape[0])
    bucket = max(8, 1 << (max(nnz, 1) - 1).bit_length())
    r = np.zeros(bucket, dtype=np.int64)
    c = np.zeros(bucket, dtype=np.int64)
    w = np.zeros(bucket, dtype=np.float64)
    r[:nnz] = rows
    c[:nnz] = cols
    w[:nnz] = weights
    return r, c, w


@register_backend("jax")
class JaxDenseBackend(PathSimBackend):
    """Dense chain on one device (TPU when available, else host backend)."""

    def __init__(self, hin, metapath, dtype=jnp.float32, device=None,
                 use_pallas: bool | None = None, exact_counts: bool = True,
                 **options):
        """``exact_counts=False`` mirrors the sparse backend's approx
        mode: waives the f32 2^24 exact-integer guard for graphs whose
        path counts overflow it by construction (scores are
        scale-invariant ratios in C, so the cost is ~1e-6 relative
        rounding, inside the 1e-5 gate — jax_sparse.py has the full
        argument). Needed when the dense-resident path runs the
        million-author regime on a TPU (C [1M, V] is only ~256 MB at
        V=64; the guard, not memory, is what would refuse it)."""
        super().__init__(hin, metapath, **options)
        self.dtype = dtype
        self.exact_counts = exact_counts
        self.use_pallas = pk.pallas_supported() if use_pallas is None else use_pallas
        self._symmetric = metapath.is_symmetric
        if self._symmetric:
            # Sparse-first: only the folded COO indices cross host→device
            # (O(nnz), not O(N·P) dense blocks); C is scatter-assembled
            # inside jit. See _half_outputs_coo. The fold is plan-ordered
            # and shares sub-chains through the serving memo when one is
            # installed (ops/planner.py).
            coo = planner.fold_half(
                hin, metapath, memo=self._subchain_memo, plan=self.plan
            )
            self._c_shape = coo.shape
            # Pad the factor COO to a power-of-two nnz bucket before it
            # becomes a traced shape: _half_outputs_coo specializes on
            # nnz, and a rebuilt backend over a delta-drifted graph
            # (serving's lazy metapath-engine rebuilds, the delta-
            # fallback path) would otherwise recompile the scatter on
            # every rebuild. Pad entries scatter 0.0 at (0, 0) —
            # harmless — and steady-state rebuilds reuse one compiled
            # program per bucket.
            rows, cols, w = _pad_coo_bucket(
                coo.rows, coo.cols, coo.weights
            )
            self._coo = tuple(
                jax.device_put(jnp.asarray(a, dt), device)
                for a, dt in (
                    (rows, jnp.int32),
                    (cols, jnp.int32),
                    (w, dtype),
                )
            )
            self._blocks = None
        else:
            host_blocks = chain.oriented_dense_blocks(
                hin, metapath.steps, dtype=np.float32
            )
            self._blocks = [
                jax.device_put(jnp.asarray(b, dtype=dtype), device)
                for b in host_blocks
            ]
        self._m = None
        self._rowsums = None
        self._half_cache = None

    def _half(self):
        """(C, rowsums) on device for a symmetric chain.

        Cached: the factor is a per-graph constant, and on a tunneled
        TPU every re-dispatch costs a ~70 ms RPC — repeated topk() calls
        (rank-all driver loops, benchmark reps) should pay for the
        scoring pass, not for re-assembling an immutable array."""
        if self._half_cache is None:
            rows, cols, weights = self._coo
            self._half_cache = _half_outputs_coo(
                rows, cols, weights, self._c_shape
            )
        return self._half_cache

    def _compute(self):
        if self._m is None:
            if self._symmetric:
                c, rowsums = self._half()
                m = _m_from_half(c)
            else:
                m, rowsums = _chain_outputs_for(self.plan.order_tree())(
                    self._blocks
                )
            self._m = np.asarray(m, dtype=np.float64)
            self._rowsums = np.asarray(rowsums, dtype=np.float64)
            self._check_exact(self._rowsums)
        return self._m, self._rowsums

    def _check_exact(self, rowsums: np.ndarray) -> None:
        if self.exact_counts:
            chain.check_exact_counts(rowsums.max(initial=0.0), self.dtype)

    # Device/host caches stay at capacity shape; returns trim to the
    # logical sizes (padded slots carry no edges → zero counts).

    def commuting_matrix(self) -> np.ndarray:
        return self._compute()[0][: self.n_sources, : self.n_targets]

    def global_walks(self) -> np.ndarray:
        if self._rowsums is None and self._m is None:
            # cheap path: rowsums without materializing M
            if self._symmetric:
                _, rowsums = self._half()
            else:
                rowsums = _rowsums_asym(self._blocks)
            self._rowsums = np.asarray(rowsums, dtype=np.float64)
            self._check_exact(self._rowsums)
        elif self._rowsums is None:
            self._compute()
        return self._rowsums[: self.n_sources]

    def pairwise_row(self, source_index: int) -> np.ndarray:
        if self._symmetric:
            # One GEMV against the cached half factor (the C6/C7 chain
            # identity) — materializing M here would be O(N²) memory
            # and crashes outright at reconstruction scale (a 227k-
            # author single-source query is a 206 GB M).
            c, rowsums = self._half()
            with jax.default_matmul_precision("highest"):
                row = chain.pairwise_row_from_half(c, source_index, xp=jnp)
            # same exactness contract as every other primitive: the
            # f32 2^24 guard must hold even when pairwise_row is the
            # FIRST (or only) call on this backend
            if self._rowsums is None:
                self._rowsums = np.asarray(rowsums, dtype=np.float64)
                self._check_exact(self._rowsums)
            return np.asarray(row, dtype=np.float64)[: self.n_targets]
        return self._compute()[0][source_index, : self.n_targets]

    def pairwise_rows(self, rows) -> np.ndarray:
        """Batched M[rows, :] — host view of :meth:`pairwise_rows_device`
        (the serving layer uses the device handle directly to overlap
        transfer with the next bucket's dispatch)."""
        out = self.pairwise_rows_device(rows)
        if out is None:
            return super().pairwise_rows(rows)
        return np.asarray(out, dtype=np.float64)[:, : self.n_targets]

    def pairwise_rows_device(self, rows):
        """Batched row counts as a DEVICE array (async dispatch: the
        call returns before the GEMM finishes, which is what lets the
        serving layer double-buffer — issue bucket N+1 while bucket N's
        result transfers to host). Returns None when no device fast
        path exists (asymmetric chain: counts come from the cached M)."""
        if not self._symmetric:
            return None
        c, rowsums = self._half()
        out = _pairwise_rows_half(
            c, jnp.asarray(np.asarray(rows, dtype=np.int64), dtype=jnp.int32)
        )
        # same exactness contract as pairwise_row: guard even when this
        # is the first call on the backend
        if self._rowsums is None:
            self._rowsums = np.asarray(rowsums, dtype=np.float64)
            self._check_exact(self._rowsums)
        return out

    def _apply_delta_impl(self, plan) -> None:
        """Patch the device-resident half factor in place: one scatter
        of the signed ΔC (padded to a power-of-two nnz bucket) plus one
        rowsums GEMV — both shape-stable, so a warm service absorbs the
        update with zero new XLA compiles in steady state. f32 adds of
        small integers are exact below the 2^24 guard, so the patched
        factor equals a rebuilt one bit-for-bit."""
        from .base import DeltaUnsupported

        if not self._symmetric:
            raise DeltaUnsupported(
                "jax backend patches only the symmetric half factor"
            )
        dc = plan.delta_c
        rows, cols, w = _pad_coo_bucket(dc.rows, dc.cols, dc.weights)
        c, _ = self._half()
        c_new = _apply_coo_delta(
            c,
            jnp.asarray(rows, dtype=jnp.int32),
            jnp.asarray(cols, dtype=jnp.int32),
            jnp.asarray(w, dtype=self.dtype),
        )
        # _half_cache is the single authority for (C, rowsums) — the
        # construction-time COO arrays are now stale and never re-read
        # (only _half() consults them, and only while the cache is
        # empty, which it never again is).
        self._half_cache = (c_new, _rowsums_only(c_new))
        self._m = None
        self._rowsums = None  # next host fetch re-runs the exact guard

    # -- on-device scoring fast paths -------------------------------------

    def _denominator_device(self, c, rowsums, variant: str):
        """The fused kernels take an arbitrary denominator vector —
        "rowsum" passes the global-walk row sums (reference semantics),
        "diagonal" passes diag(M)[i] = Σ_v C[i,v]² (textbook PathSim,
        Sun et al.; SURVEY.md §3.3) computed without materializing M.
        diag(M) ≤ rowsums(M) elementwise (colsum_v ≥ C[i,v]), so the
        f32 exact-count guard on the row sums covers both."""
        if variant == "rowsum":
            return rowsums
        if variant == "diagonal":
            return _diag_from_half(c)
        raise ValueError(f"unknown PathSim variant {variant!r}")

    def _scores_variant(self, n: int, v: int) -> str:
        """Pallas-vs-XLA choice for the dense all-pairs scores — the
        KERNELS_r05 finding as a tuned knob (the fused Pallas kernel
        wins at 8k, XLA's own fusion at 32k). Untuned default keeps the
        pre-tuning behavior: Pallas whenever it is available."""
        from .. import tuning

        return tuning.choose(
            "scores_variant", n=n, v=v,
            dtype=str(np.dtype(self.dtype)), default="pallas",
        )

    def all_pairs_scores(self, variant: str = "rowsum") -> np.ndarray:
        if not self._symmetric:
            return super().all_pairs_scores(variant)
        c, rowsums = self._half()
        d = self._denominator_device(c, rowsums, variant)
        use_pallas = (
            self.use_pallas
            and self._scores_variant(int(c.shape[0]), int(c.shape[1]))
            == "pallas"
        )
        if use_pallas:
            if pk.fits_vmem(c.shape[1]):
                scores = pk.fused_scores(c, d)
            else:
                scores = pk.fused_scores_ktiled(c, d)
        else:
            scores = pk.fused_scores_reference(c, d)
        # Fetch + exactness check AFTER the kernel dispatch (async, so
        # the transfer rides along) — and only once per backend: the
        # rowsums are as immutable as the graph.
        if self._rowsums is None:
            self._rowsums = np.asarray(rowsums, dtype=np.float64)
            self._check_exact(self._rowsums)
        n = self.n_sources
        return np.asarray(scores)[:n, :n]

    def topk(self, k: int = 10, mask_self: bool = True,
             variant: str = "rowsum"):
        """Per-source top-k (values, indices), fully on device. Both
        score variants ride the same fused kernels — only the
        denominator vector differs (_denominator_device)."""
        if not self._symmetric:
            raise ValueError("topk fast path requires a symmetric metapath")
        c, rowsums = self._half()
        d = self._denominator_device(c, rowsums, variant)
        if self.use_pallas and k <= pk._CAND and pk.twopass_fits(c.shape[0]):
            # Fastest path: candidate extraction + XLA reduce (handles
            # any V internally). Beyond the candidate-buffer HBM budget
            # (~92k rows — twopass_fits) the rect row-tile streaming
            # path takes over at the same kernel speed.
            vals, idxs = pk.fused_topk_twopass(
                c, d, k=k, mask_self=mask_self
            )
        elif (
            self.use_pallas
            and mask_self                      # rect always self-excludes
            and self.dtype == jnp.float32
            and pk.rect_supported(c.shape[1], k)
        ):
            # Square two-pass outgrew its candidate buffer (~92k rows):
            # stream row tiles through the rectangular two-pass kernel
            # instead of falling off the cliff onto the single-pass fold
            # (measured 8× slower at 32k — KERNELS_r03.json). Same
            # kernel family the sparse streaming tier uses at 1M rows.
            vals, idxs = self._topk_rect_stream(c, d, k)
        elif self.use_pallas and not pk.fits_vmem(c.shape[1]):
            vals, idxs = pk.fused_topk_ktiled(c, d, k=k, mask_self=mask_self)
        elif self.use_pallas:
            vals, idxs = pk.fused_topk(c, d, k=k, mask_self=mask_self)
        else:
            scores = pk.fused_scores_reference(c, d)
            if mask_self:
                n = scores.shape[0]
                scores = jnp.where(jnp.eye(n, dtype=bool), -jnp.inf, scores)
            vals, idxs = jax.lax.top_k(scores, k)
        if self._rowsums is None:
            self._rowsums = np.asarray(rowsums, dtype=np.float64)
            self._check_exact(self._rowsums)
        # One batched transfer for both outputs: on the tunneled TPU two
        # np.asarray fetches are two ~70 ms round-trips. Row trim drops
        # capacity-padded sources; padded COLUMNS need no mask — their
        # scores are exactly 0 (no edges → zero counts and denominator)
        # and every real column ties at 0 with a LOWER index, so the
        # ascending-index tie-break keeps them out whenever k ≤ n−1.
        vals_h, idxs_h = jax.device_get((vals, idxs))
        n = self.n_sources
        return np.asarray(vals_h)[:n], np.asarray(idxs_h)[:n]

    # Row-tile width for the rect streaming path (halved until the
    # packed candidate buffer fits its HBM budget at large N).
    _RECT_TILE_ROWS = 8192

    def _topk_rect_stream(self, c, d, k: int):
        """Per-source top-k beyond the square two-pass candidate-buffer
        budget: pad (C, denominators) to kernel shape once, then score
        each row tile against the full column range with the rectangular
        two-pass kernel. Results stay on device ([N, k] is tiny); the
        caller does the single batched fetch."""
        n = c.shape[0]
        tile_rows = self._RECT_TILE_ROWS
        while tile_rows > 256 and not pk.rect_fits(n, tile_rows):
            tile_rows //= 2
        cc, dc = pk.rect_pad_factor(c, d)
        # Extend the stripe-aligned pad to a whole number of row tiles
        # so every dynamic_slice below is full-size (a clamped slice
        # would silently re-rank earlier rows).
        full = -(-cc.shape[0] // tile_rows) * tile_rows
        if full > cc.shape[0]:
            cc = jnp.pad(cc, ((0, full - cc.shape[0]), (0, 0)))
            dc = jnp.pad(dc, (0, full - dc.shape[0]))
        interp = not pk.pallas_supported()
        outs = []
        for i0 in range(0, n, tile_rows):
            ci = jax.lax.dynamic_slice(cc, (i0, 0), (tile_rows, cc.shape[1]))
            di = jax.lax.dynamic_slice(dc, (i0,), (tile_rows,))
            row_ids = i0 + jnp.arange(tile_rows, dtype=jnp.int32)
            outs.append(
                pk.fused_topk_twopass_rect(
                    ci, cc, di, dc, row_ids,
                    k=k, n_true_cols=n, interpret=interp,
                )
            )
        vals = jnp.concatenate([v for v, _ in outs])[:n]
        idxs = jnp.concatenate([i for _, i in outs])[:n]
        return vals, idxs
