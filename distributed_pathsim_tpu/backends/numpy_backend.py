"""NumPy f64 dense oracle (BASELINE.json config 1).

The ground-truth backend: float64 keeps path counts exact far past f32's
2²⁴ integer range (SURVEY.md §7 "Path counts are integers"). Every other
backend is tested against this one; this one is tested against the
reference's own run-log arithmetic (SURVEY.md Appendix A golden vectors).
"""

from __future__ import annotations

import numpy as np

from ..ops import chain
from ..ops import planner
from .base import DeltaUnsupported, PathSimBackend, register_backend


@register_backend("numpy")
class NumpyBackend(PathSimBackend):
    def __init__(self, hin, metapath, dtype=np.float64, **options):
        super().__init__(hin, metapath, **options)
        self.dtype = dtype
        if metapath.is_symmetric:
            # Plan-ordered sparse fold, densified once: identical
            # integers to the historical dense half_product (path
            # counts are exact in f64 under any association order),
            # without ever materializing the [N, P] intermediate.
            self._c = planner.dense_half(
                hin, metapath, dtype=dtype, memo=self._subchain_memo
            )
            self._blocks = None
        else:
            self._c = None
            self._blocks = chain.oriented_dense_blocks(hin, metapath.steps, dtype=dtype)
        self._m: np.ndarray | None = None
        self._rowsums: np.ndarray | None = None

    # Internal caches stay at capacity shape (delta updates patch them
    # in place); every return value is trimmed to the logical size.

    def commuting_matrix(self) -> np.ndarray:
        if self._m is None:
            if self._c is not None:
                self._m = chain.commuting_matrix_from_half(self._c, xp=np)
            else:
                # DP-ordered association (the planner's whole point on
                # asymmetric chains): identical integers to the naive
                # left-to-right fold, measurably fewer FLOPs.
                self._m = planner.execute_dense(self.plan, self._blocks, xp=np)
        return self._m[: self.n_sources, : self.n_targets]

    def global_walks(self) -> np.ndarray:
        if self._rowsums is None:
            if self._c is not None:
                self._rowsums = chain.rowsums_from_half(self._c, xp=np)
            else:
                self._rowsums = planner.rowsums_fold(self._blocks, xp=np)
        return self._rowsums[: self.n_sources]

    def pairwise_row(self, source_index: int) -> np.ndarray:
        n = self.n_targets
        if self._m is not None:
            return self._m[source_index, :n]
        if self._c is not None:
            return chain.pairwise_row_from_half(self._c, source_index, xp=np)[:n]
        # general chain: fold source one-hot from the left
        v = self._blocks[0][source_index]
        for b in self._blocks[1:]:
            v = v @ b
        return v[:n]

    def pairwise_rows(self, rows) -> np.ndarray:
        """Batched M[rows, :] as ONE GEMM against the half factor (or a
        row-sliced chain fold) — the serving coalescer's dispatch unit.
        f64 path counts are exact integers below 2⁵³, so the GEMM's sum
        order cannot diverge from the per-row GEMV."""
        rows = np.asarray(rows, dtype=np.int64)
        n = self.n_targets
        if self._m is not None:
            return self._m[rows][:, :n]
        if self._c is not None:
            return (self._c[rows] @ self._c.T)[:, :n]
        v = self._blocks[0][rows]
        for b in self._blocks[1:]:
            v = v @ b
        return v[:, :n]

    def _apply_delta_impl(self, plan) -> None:
        """Patch the dense half factor with the signed ΔC scatter —
        f64 integer adds are exact, so the patched C equals a rebuilt C
        bit-for-bit — and drop the derived caches (M, rowsums), which
        recompute lazily from the patched factor through the very same
        code paths a fresh build uses."""
        if self._c is None:
            raise DeltaUnsupported(
                "numpy backend patches only the symmetric half factor"
            )
        dc = plan.delta_c
        np.add.at(self._c, (dc.rows, dc.cols), dc.weights.astype(self.dtype))
        self._m = None
        self._rowsums = None
