"""Sparse/tiled backend (BASELINE.json config 5 path).

For graphs where dense adjacency blocks (N×P) don't fit: the half-chain
factor C is folded sparsely on the host (ops/sparse.py), then all device
work is static-shaped scatter + tile GEMMs. Serves the same primitives as
the dense backends at dblp scale, plus streaming ``topk`` over row tiles
for graphs whose full N×N score matrix can't exist.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ops import sparse as sp
from ..ops.metapath import MetaPath
from .base import PathSimBackend, register_backend

# Refuse to densify all-pairs outputs beyond this many entries (16k×16k
# f64 ≈ 2 GB); larger graphs must use the streaming top-k path.
_DENSE_M_MAX_ENTRIES = 1 << 28


@register_backend("jax-sparse")
class JaxSparseBackend(PathSimBackend):
    def __init__(
        self,
        hin,
        metapath: MetaPath,
        tile_rows: int = 4096,
        dtype=jnp.float32,
        exact_counts: bool = True,
        **options,
    ):
        """``exact_counts=False`` waives the f32 2^24 exact-integer guard
        for graphs whose path counts overflow it by construction (the
        million-author regime): scores are scale-invariant in C, so the
        cost is only f32 rounding (~√V·2⁻²⁴ relative, inside the ≤1e-5
        gate), not truncation of the ranking product."""
        super().__init__(hin, metapath, **options)
        if not metapath.is_symmetric:
            raise ValueError("jax-sparse requires a symmetric metapath")
        self._c = sp.half_chain_coo(hin, metapath)
        self.n = self._c.shape[0]
        self.exact_counts = exact_counts
        self.tiled = sp.TiledHalfChain(
            self._c,
            tile_rows=min(tile_rows, max(self.n, 8)),
            dtype=dtype,
            exact_counts=exact_counts,
        )
        self._rowsums: np.ndarray | None = None
        self._m: np.ndarray | None = None

    def global_walks(self) -> np.ndarray:
        if self._rowsums is None:
            self._rowsums = self.tiled.rowsums()
        return self._rowsums

    def commuting_matrix(self) -> np.ndarray:
        if self._m is None:
            if self.n * self.n > _DENSE_M_MAX_ENTRIES:
                raise MemoryError(
                    f"dense M would be {self.n}x{self.n}; use topk_scores()"
                )
            t = self.tiled
            m = np.zeros((t.n_tiles * t.tile_rows, t.n_tiles * t.tile_rows))
            for i in range(t.n_tiles):
                for j in range(i, t.n_tiles):
                    tile = np.asarray(t.m_tile(i, j), dtype=np.float64)
                    m[
                        i * t.tile_rows : (i + 1) * t.tile_rows,
                        j * t.tile_rows : (j + 1) * t.tile_rows,
                    ] = tile
                    if j != i:
                        m[
                            j * t.tile_rows : (j + 1) * t.tile_rows,
                            i * t.tile_rows : (i + 1) * t.tile_rows,
                        ] = tile.T
            self._m = m[: self.n, : self.n]
        return self._m

    def pairwise_row(self, source_index: int) -> np.ndarray:
        t = self.tiled
        ti, off = divmod(source_index, t.tile_rows)
        src_tile = t.tile(ti)
        out = np.zeros(t.n_tiles * t.tile_rows, dtype=np.float64)
        for j in range(t.n_tiles):
            tile = np.asarray(
                sp.tile_outer(src_tile[off : off + 1], t.tile(j)),
                dtype=np.float64,
            )
            out[j * t.tile_rows : (j + 1) * t.tile_rows] = tile[0]
        return out[: self.n]

    def _run_config(self, k: int) -> dict:
        """Checkpoint identity: graph fingerprint + tiling + k. A reused
        directory from a different run must fail, not resume."""
        import hashlib

        c = self._c
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(c.rows, dtype=np.int64).tobytes())
        h.update(np.ascontiguousarray(c.cols, dtype=np.int64).tobytes())
        h.update(np.ascontiguousarray(c.weights, dtype=np.float64).tobytes())
        digest = h.hexdigest()[:16]
        return {
            "n": int(self.n),
            "v": int(c.shape[1]),
            "nnz": int(c.rows.shape[0]),
            "digest": digest,
            "tile_rows": int(self.tiled.tile_rows),
            "k": int(k),
            "metapath": self.metapath.name,
            "dtype": str(np.dtype(self.tiled.dtype)),
            "exact_counts": bool(self.exact_counts),
            # Bump whenever the numeric regime of saved units changes —
            # v2 = on-device f32 score division + lax.top_k tie-breaks.
            # Prevents resuming tiles written under different math.
            "format": "stream-topk-v2",
        }

    def topk_scores(self, k: int = 10, variant: str = "rowsum",
                    checkpoint_dir: str | None = None):
        """Streaming per-source top-k over row tiles: never materializes
        more than one [tile, tile] score block. Returns (values, indices)
        arrays of shape [N, k].

        ``checkpoint_dir``: persist each completed row tile and skip it on
        restart — the all-pairs analog of the reference's per-stage
        append-and-flush crash resilience (SURVEY.md §5).
        """
        if variant != "rowsum":
            raise ValueError("streaming top-k supports the rowsum variant")
        ckpt = None
        if checkpoint_dir is not None:
            from ..utils.checkpoint import CheckpointManager

            ckpt = CheckpointManager(
                checkpoint_dir,
                config=self._run_config(k),
                # Directories written before these identity keys existed
                # used exactly these values — keep them resumable.
                config_defaults={"dtype": "float32", "exact_counts": True},
            )
        t = self.tiled
        # Row sums live on device for the whole pass; the merge loop below
        # never brings a score tile to the host (sp.stream_merge_topk) —
        # only the [tile, k] winners per completed row tile come back.
        # Lazily built: a run resuming entirely from checkpoint never
        # touches the graph at all.
        d_dev = None

        def rowsums_device():
            nonlocal d_dev
            if d_dev is None:
                d_pad = np.zeros(t.n_tiles * t.tile_rows)
                d_pad[: self.n] = self.global_walks()
                d_dev = jnp.asarray(d_pad, dtype=t.dtype)
            return d_dev

        vals = np.full((self.n, k), -np.inf)
        idxs = np.zeros((self.n, k), dtype=np.int64)
        for i in range(t.n_tiles):
            i0 = i * t.tile_rows
            rows_here = min(t.tile_rows, self.n - i0)
            key = f"topk{k}_rowtile_{i}"
            if ckpt is not None and ckpt.is_done(key):
                unit = ckpt.load_unit(key)
                vals[i0 : i0 + rows_here] = unit["vals"]
                idxs[i0 : i0 + rows_here] = unit["idxs"]
                continue
            ci = t.tile(i)
            d_dev = rowsums_device()
            di = d_dev[i0 : i0 + t.tile_rows]
            best_v = jnp.full((t.tile_rows, k), -jnp.inf, dtype=t.dtype)
            best_i = jnp.zeros((t.tile_rows, k), dtype=jnp.int32)
            for j in range(t.n_tiles):
                j0 = j * t.tile_rows
                best_v, best_i = sp.stream_merge_topk(
                    ci, t.tile(j), di, d_dev[j0 : j0 + t.tile_rows],
                    best_v, best_i,
                    jnp.int32(i0), jnp.int32(j0), k=k, n_true=self.n,
                )
            vals[i0 : i0 + rows_here] = np.asarray(
                best_v[:rows_here], dtype=np.float64
            )
            idxs[i0 : i0 + rows_here] = np.asarray(
                best_i[:rows_here], dtype=np.int64
            )
            if ckpt is not None:
                ckpt.save_unit(
                    key,
                    vals=vals[i0 : i0 + rows_here],
                    idxs=idxs[i0 : i0 + rows_here],
                )
        return vals, idxs
