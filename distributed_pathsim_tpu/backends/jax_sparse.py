"""Sparse/tiled backend (BASELINE.json config 5 path).

For graphs where dense adjacency blocks (N×P) don't fit: the half-chain
factor C is folded sparsely on the host (ops/sparse.py), then all device
work is static-shaped scatter + tile GEMMs. Serves the same primitives as
the dense backends at dblp scale, plus streaming ``topk`` over row tiles
for graphs whose full N×N score matrix can't exist.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import resilience
from ..ops import pallas_kernels as pk
from ..ops import sparse as sp
from ..ops.metapath import MetaPath
from ..resilience.preemption import handler as _preemption
from .base import PathSimBackend, register_backend

# Refuse to densify all-pairs outputs beyond this many entries (16k×16k
# f64 ≈ 2 GB); larger graphs must use the streaming top-k path.
_DENSE_M_MAX_ENTRIES = 1 << 28


@register_backend("jax-sparse")
class JaxSparseBackend(PathSimBackend):
    # Dense C on device unlocks the scanned streaming pass (one dispatch
    # per ROW tile instead of n_tiles² — the tunnel round-trips, not the
    # GEMMs, dominated the 1M-author pass). 4 GB covers ~2.7M authors at
    # V=384 f32; beyond it the per-(i,j) dispatch loop takes over.
    _DENSE_C_DEVICE_BUDGET = 4 << 30

    def __init__(
        self,
        hin,
        metapath: MetaPath,
        tile_rows: int | None = None,
        dtype=jnp.float32,
        exact_counts: bool = True,
        dense_c_budget_bytes: int | None = None,
        rect_kernel: bool | None = None,
        factor_format: str | None = None,
        **options,
    ):
        """``exact_counts=True`` (default) delivers EXACT integer counts
        and bit-exact-vs-f64 scores at any scale. Below the f32 2^24
        exact-integer range the plain f32 pass is already exact; past it
        the backend switches to the two-phase exact path automatically:
        the f32 MXU pass runs as a top-k_cand candidate PREFILTER, and
        an O(N·k) host pass rescores every candidate in f64 (integers
        < 2^53: exact), with a per-row error-bound soundness check and
        a full-row exact fallback where it cannot certify the candidate
        set (see _exact_topk_rescore). The reference's counts are exact
        integers (DPathSim_APVPA.py:86-88 — ``int(total_path)``), so
        "matching" at the million-author scale must not silently round.

        ``exact_counts=False`` waives all of that for pure-ranking runs:
        scores are scale-invariant in C, so f32 rounding costs only
        ~√V·2⁻²⁴ relative (inside the ≤1e-5 gate); rankings may swap
        near-exact ties. Cheaper — no rescore pass."""
        super().__init__(hin, metapath, **options)
        if not metapath.is_symmetric:
            raise ValueError("jax-sparse requires a symmetric metapath")
        self.exact_counts = exact_counts
        self._dtype = dtype
        self._dense_c_budget = (
            self._DENSE_C_DEVICE_BUDGET
            if dense_c_budget_bytes is None
            else int(dense_c_budget_bytes)
        )
        self._rect_kernel = rect_kernel
        from ..ops import planner

        coo = planner.fold_half(
            hin, metapath, memo=self._subchain_memo, plan=self.plan
        )
        from .. import tuning

        if tile_rows is None:
            # tuned streaming tile width, keyed on the folded factor's
            # real (N, V, density). Resolved ONCE here and pinned: a
            # delta rebind reuses it, so tile program shapes stay
            # stable across updates (the recompile-free contract).
            tile_rows = int(
                tuning.choose(
                    "sparse_tile_rows",
                    n=coo.shape[0], v=coo.shape[1],
                    nnz=int(coo.rows.shape[0]),
                    dtype=str(np.dtype(dtype)),
                    default=4096,
                )
            )
        self._tile_rows_req = tile_rows
        # the scatter-pad floor is pinned at build for the same reason:
        # a delta rebind that re-consulted the table with its drifted
        # nnz (or a density that crossed a decade bucket) could flip
        # the compiled scatter's pad shape mid-serve — exactly the
        # steady-state recompile the floor exists to prevent
        self._nnz_floor_req = int(
            tuning.choose(
                "sparse_nnz_floor",
                n=coo.shape[0], v=coo.shape[1],
                nnz=int(coo.rows.shape[0]),
                dtype=str(np.dtype(dtype)),
                default=1,
            )
        )
        # Resident factor layout (DESIGN.md §29): resolved ONCE at
        # build through the tuning registry (never a silent default —
        # the heuristic default IS "coo", the uncompressed layout every
        # release before this one shipped) and pinned: a delta rebind
        # must patch the SAME representation, not re-decide it.
        from ..ops import packed as pkd

        if factor_format is None:
            factor_format = str(
                tuning.choose(
                    "factor_format",
                    n=coo.shape[0], v=coo.shape[1],
                    nnz=int(coo.rows.shape[0]),
                    dtype=str(np.dtype(dtype)),
                    default="coo",
                )
            )
        if factor_format not in pkd.FACTOR_FORMATS:
            raise ValueError(
                f"unknown factor_format {factor_format!r}; choose from "
                f"{pkd.FACTOR_FORMATS}"
            )
        self._factor_format = factor_format
        self._bind_factor(coo)

    def _bind_factor(self, factor) -> None:
        """Bind a (new) half-chain factor: overflow-mode detection,
        tiling, cache reset. __init__ and the delta-update hook share
        this so a patched backend can never drift from a fresh build.
        ``factor`` is a COO (packed here when the ``factor_format``
        knob says so) or an already-patched PackedFactor from the
        delta path. ``self.n`` is the LOGICAL source count — the
        factor's row axis may be capacity-padded (data/delta.py
        headroom); padded rows carry no entries and every sweep below
        is masked/trimmed to n.
        """
        from ..ops import packed as pkd

        self.n = self.hin.type_size(self.metapath.source_type)
        tile_rows_eff = min(
            self._tile_rows_req, max(int(factor.shape[0]), 8)
        )
        if pkd.is_packed(factor):
            self._factor = factor
            self._c = None
        elif self._factor_format != "coo":
            # chunk granularity = tile granularity, so every tile
            # decode touches exactly its own chunks
            self._factor = pkd.make_factor(
                factor, self._factor_format, chunk_rows=tile_rows_eff
            )
            self._c = None
        else:
            self._factor = factor
            self._c = factor
        dtype = self._dtype
        # Overflow detection (same cheap-bound → tight-per-row ladder
        # the TiledHalfChain guard uses, but the outcome is a MODE, not
        # a refusal): d_i ≥ M[i,j] ≥ every partial sum (non-negative
        # data) and C[i,v] ≤ √M[i,i], so max rowsum < 2^24 proves the
        # whole f32 pipeline exact; past it the rescore phase restores
        # exactness.
        self._exact_rescore = False
        self._host_rowsums = None
        from ..ops import chain as _chain

        if (
            self.exact_counts
            and _chain.effective_device_dtype(dtype) == np.float32
        ):
            colsum = np.asarray(
                pkd.factor_colsum(self._factor), dtype=np.float64
            )
            if float((colsum**2).sum()) >= _chain.F32_EXACT_INT_MAX:
                rs = pkd.factor_rowsums_weighted(
                    self._factor, colsum
                )[: self.n]
                if rs.max(initial=0.0) >= _chain.F32_EXACT_INT_MAX:
                    self._exact_rescore = True
                    self._host_rowsums = rs
        self.tiled = sp.TiledHalfChain(
            self._factor,
            # clamp to the factor's CAPACITY-padded row axis, not the
            # logical n: n grows on node appends, and a tile shape tied
            # to it would retrace every tile program per append —
            # exactly the recompile the capacity invariant exists to
            # prevent. factor.shape[0] is delta-stable by construction.
            tile_rows=tile_rows_eff,
            nnz_bucket_floor=self._nnz_floor_req,
            dtype=dtype,
            # in rescore mode the f32 tiles are a prefilter by design;
            # the tiled guard would refuse what the rescore phase fixes
            exact_counts=self.exact_counts and not self._exact_rescore,
        )
        self._rect_factor = None
        self._rowsums: np.ndarray | None = None
        self._diag: np.ndarray | None = None
        self._m: np.ndarray | None = None
        self._c_sum = None
        self._indptr = None
        # memory-headroom visibility (the number this whole layout tier
        # is about): resident factor bytes, labeled by format
        from ..obs.metrics import get_registry

        get_registry().gauge(
            "dpathsim_factor_bytes",
            "resident half-chain factor bytes by layout format",
        ).labels(format=self._factor_format).set(
            float(pkd.factor_bytes(self._factor))
        )

    def _apply_delta_impl(self, plan) -> None:
        """Rebind to the plan's already-patched COO factor (ΔC came
        from the delta-COO product rule — the chain is never refolded)
        and rebuild the tiling. Host cost is one O(nnz) re-sort; device
        tiles re-densify lazily through the SAME compiled scatter
        (tile_rows/V unchanged by the capacity invariant, scatter pad
        in power-of-two buckets), so steady-state updates compile
        nothing."""
        self.hin = plan.hin_new  # logical n may have grown (appends)
        if self._c is None and plan.delta_c is not None:
            # packed layouts: O(Δ) chunk-granular patch of the resident
            # representation (ops/packed.patch_factor) — bit-identical
            # in content to the plan's patched COO, but the 24-byte/nnz
            # arrays are never materialized
            from ..ops import packed as pkd

            self._bind_factor(
                pkd.patch_factor(self._factor, plan.delta_c)
            )
        else:
            self._bind_factor(plan.half_new)

    def factor_info(self) -> dict:
        from ..ops import packed as pkd

        nnz = pkd.factor_nnz(self._factor)
        return {
            "format": self._factor_format,
            "bytes": pkd.factor_bytes(self._factor),
            "nnz": nnz,
            "coo_bytes": 24 * nnz,  # int64 rows + int64 cols + f64 w
        }

    @property
    def _n_live_tiles(self) -> int:
        """Row tiles that contain any LOGICAL row. Tiles past this hold
        only capacity padding (no COO entries) — every sweep skips them;
        the last live tile's padded tail rows are masked via n_true."""
        return -(-self.n // self.tiled.tile_rows)

    def _use_rect_kernel(self, k: int) -> bool:
        """The rectangular Pallas kernel serves the f32 streaming regime
        (V ≤ 128, k < 16) on a real TPU, within its candidate-buffer
        HBM budget (shrink ``tile_rows`` to stay inside it at larger N);
        ``rect_kernel=True`` forces it elsewhere (interpret — tests)."""
        fits = (
            jnp.dtype(self.tiled.dtype) == jnp.float32
            and pk.rect_supported(self.tiled.v, k)
            and pk.rect_fits(self.n, self.tiled.tile_rows)
        )
        if self._rect_kernel is not None:
            return self._rect_kernel and fits
        return fits and pk.pallas_supported()

    def global_walks(self) -> np.ndarray:
        if self._rowsums is None:
            # rescore mode: the device f32 GEMV rounds past 2^24; the
            # host f64 accumulation (integers < 2^53) is exact and was
            # already computed by the overflow detector.
            self._rowsums = (
                self._host_rowsums if self._exact_rescore
                else self.tiled.rowsums()[: self.n]
            )
        return self._rowsums

    def commuting_matrix(self) -> np.ndarray:
        if self._m is None:
            if self.n * self.n > _DENSE_M_MAX_ENTRIES:
                raise MemoryError(
                    f"dense M would be {self.n}x{self.n}; use topk_scores()"
                )
            if self._exact_rescore:
                # counts past 2^24: device f32 tiles would round — do
                # the (small-n by the gate above) product in host f64
                c = self._densify_rows_f64(np.arange(self.n))
                self._m = c @ c.T
                return self._m
            t = self.tiled
            m = np.zeros((t.n_tiles * t.tile_rows, t.n_tiles * t.tile_rows))
            for i in range(self._n_live_tiles):
                for j in range(i, self._n_live_tiles):
                    tile = np.asarray(t.m_tile(i, j), dtype=np.float64)
                    m[
                        i * t.tile_rows : (i + 1) * t.tile_rows,
                        j * t.tile_rows : (j + 1) * t.tile_rows,
                    ] = tile
                    if j != i:
                        m[
                            j * t.tile_rows : (j + 1) * t.tile_rows,
                            i * t.tile_rows : (i + 1) * t.tile_rows,
                        ] = tile.T
            self._m = m[: self.n, : self.n]
        return self._m

    def pairwise_row(self, source_index: int) -> np.ndarray:
        if self._exact_rescore:
            return self.pairwise_row_exact(source_index)
        t = self.tiled
        ti, off = divmod(source_index, t.tile_rows)
        src_tile = t.tile(ti)
        out = np.zeros(t.n_tiles * t.tile_rows, dtype=np.float64)
        for j in range(self._n_live_tiles):
            tile = np.asarray(
                sp.tile_outer(src_tile[off : off + 1], t.tile(j)),
                dtype=np.float64,
            )
            out[j * t.tile_rows : (j + 1) * t.tile_rows] = tile[0]
        return out[: self.n]

    def pairwise_rows(self, rows) -> np.ndarray:
        """Batched M[rows, :] for the serving coalescer: the B source
        factor rows are gathered into one dense [B, V] device block and
        swept across the column tiles — n_tiles dispatches for the whole
        bucket instead of B·n_tiles. Under the exact-count guard every
        f32 tile product is an exact integer, so this agrees bit-for-bit
        with the per-row sweep; in exact-rescore mode (counts past 2²⁴)
        each row takes the exact f64 host path instead."""
        rows = np.asarray(rows, dtype=np.int64)
        if self._exact_rescore:
            return np.stack(
                [self.pairwise_row_exact(int(r)) for r in rows]
            )
        t = self.tiled
        src = jnp.asarray(self._densify_rows_f64(rows), dtype=t.dtype)
        out = np.zeros(
            (rows.shape[0], t.n_tiles * t.tile_rows), dtype=np.float64
        )
        for j in range(self._n_live_tiles):
            tile = np.asarray(sp.tile_outer(src, t.tile(j)), dtype=np.float64)
            out[:, j * t.tile_rows : (j + 1) * t.tile_rows] = tile
        return out[:, : self.n]

    def _run_config(self, k: int, symmetric: bool = True,
                    variant: str = "rowsum") -> dict:
        """Checkpoint identity: graph fingerprint + tiling + k + score
        variant + compute path. A reused directory from a different run
        must fail, not resume."""
        import hashlib

        from ..ops import packed as pkd

        c = self._c
        if c is not None:
            # historical digest (raw arrays, pre-canonicalization
            # order) so existing COO-mode checkpoint dirs stay
            # resumable
            h = hashlib.sha256()
            h.update(np.ascontiguousarray(c.rows, dtype=np.int64).tobytes())
            h.update(np.ascontiguousarray(c.cols, dtype=np.int64).tobytes())
            h.update(
                np.ascontiguousarray(c.weights, dtype=np.float64).tobytes()
            )
            digest = h.hexdigest()[:16]
        else:
            digest = pkd.content_digest(self._factor)
        scanned = self.tiled.dense_bytes() <= self._dense_c_budget
        return {
            "n": int(self.n),
            "v": int(self._factor.shape[1]),
            "nnz": pkd.factor_nnz(self._factor),
            "factor_format": self._factor_format,
            "digest": digest,
            "tile_rows": int(self.tiled.tile_rows),
            "k": int(k),
            "metapath": self.metapath.name,
            "dtype": str(np.dtype(self.tiled.dtype)),
            "exact_counts": bool(self.exact_counts),
            "variant": variant,
            # The active compute path is checkpoint identity too: the
            # rect kernel's f32 rounding and tie-break indices can
            # differ from the fold paths', so a run started on one path
            # (e.g. CPU fold) must not silently resume on another
            # (TPU rect) and mix numerics across row tiles.
            "compute_path": (
                "sym" if symmetric
                else "rect" if scanned and self._use_rect_kernel(k)
                else "scan-fold" if scanned
                else "tile-fold"
            ),
            # Bump whenever the numeric regime OR resume protocol of
            # saved units changes — v2 = full sweep, per-row-tile units
            # skipped independently on resume; v3-sym = symmetric
            # half-sweep whose resume point is the rolling sym_partials
            # unit. Prevents resuming units written under either
            # different math or different cross-tile data flow.
            "format": "stream-topk-v3-sym" if symmetric else "stream-topk-v2",
        }

    def topk_scores(self, k: int = 10, variant: str = "rowsum",
                    checkpoint_dir: str | None = None,
                    symmetric: bool = False):
        """Streaming per-source top-k (see _topk_scores_f32 for the
        pass mechanics). In exact-rescore mode (counts past 2^24,
        exact_counts=True) the f32 pass runs widened to k_cand
        candidates per row and the exact host phase reduces them to the
        true top-k — bit-exact vs f64 arithmetic, certified per row."""
        if not self._exact_rescore:
            return self._topk_scores_f32(k, variant, checkpoint_dir,
                                         symmetric)
        # k+5 margin keeps k=10 inside the rect kernel's k<16 gate
        # (candidate-set soundness is CERTIFIED per row afterwards, so
        # the margin size affects fallback cost, never correctness)
        k_cand = min(max(k + 5, (3 * k) // 2), max(self.n - 1, 1))
        cv, ci = self._topk_scores_f32(k_cand, variant, checkpoint_dir,
                                       symmetric)
        return self._exact_topk_rescore(k, cv, ci, variant)

    def _topk_scores_f32(self, k: int = 10, variant: str = "rowsum",
                         checkpoint_dir: str | None = None,
                         symmetric: bool = False):
        """Streaming per-source top-k over row tiles: never materializes
        more than one [tile, tile] score block. Returns (values, indices)
        arrays of shape [N, k].

        ``symmetric=True``: exploit M's symmetry — each (i, j≥i) tile is
        scored once and folded into BOTH row blocks, halving the GEMM
        work. MEASURED SLOWER for this workload (1.6× at 65k authors,
        V=64, CPU host): the streaming pass is selection-bound, not
        GEMM-bound, and the mirrored fold adds a transposed selection
        per tile — so the default stays the full sweep. The option
        exists (correct, tested, resumable) for regimes where the GEMM
        dominates (wide V, accelerator tile products). It also costs
        O(N·k) device memory for the in-flight running bests (84 MB at
        1M authors, k=10). ``symmetric=False`` is the v2 full sweep
        (independent row tiles; resume skips completed tiles).

        ``checkpoint_dir``: persist each completed row tile and resume on
        restart — the all-pairs analog of the reference's per-stage
        append-and-flush crash resilience (SURVEY.md §5). The symmetric
        pass additionally rolls a ``sym_partials`` unit (the running
        bests of not-yet-finished row tiles) so a killed half-sweep
        restarts at its last completed outer tile, not from scratch.
        """
        ckpt = None
        if checkpoint_dir is not None:
            from ..utils.checkpoint import CheckpointManager

            ckpt = CheckpointManager(
                checkpoint_dir,
                config=self._run_config(k, symmetric, variant),
                # Directories written before these identity keys existed
                # used exactly these values — keep them resumable.
                # (compute_path has NO default on purpose: the path an
                # old directory used cannot be known, so it must fail
                # loudly rather than risk mixed numerics.)
                config_defaults={"dtype": "float32", "exact_counts": True,
                                 "variant": "rowsum",
                                 # pre-compressed-layout directories
                                 # were all COO by definition
                                 "factor_format": "coo"},
            )
        if symmetric:
            return self._topk_scores_symmetric(k, ckpt, variant)
        t = self.tiled
        # Row sums live on device for the whole pass; the merge loop below
        # never brings a score tile to the host (sp.stream_merge_topk) —
        # only the [tile, k] winners per completed row tile come back.
        # Lazily built (_denoms_device_padded): a run resuming entirely
        # from checkpoint never touches the graph at all.
        rowsums_device = self._denoms_device_padded(variant)
        vals, idxs = self._empty_result(k)
        scanned = t.dense_bytes() <= self._dense_c_budget

        # Software pipeline over row tiles: dispatch is async in JAX, so
        # keeping a few tiles in flight lets the host fetch + checkpoint
        # of tile i overlap the device compute of tile i+1 — on the
        # tunneled TPU the fetch round-trip is ~0.2 s/tile, a real
        # fraction of the pass. Results still land (and checkpoint) in
        # tile order; a crash loses only the in-flight tiles, same as
        # the unpipelined loop.
        pending: list[tuple[int, int, int, object, object]] = []

        def _drain_one():
            i_, i0_, rows_, bv_, bi_ = pending.pop(0)
            bv_, bi_ = jax.device_get((bv_, bi_))
            vals[i0_ : i0_ + rows_] = np.asarray(bv_[:rows_], dtype=np.float64)
            idxs[i0_ : i0_ + rows_] = np.asarray(bi_[:rows_], dtype=np.int64)
            if ckpt is not None:
                ckpt.save_unit(
                    f"topk{k}_rowtile_{i_}",
                    vals=vals[i0_ : i0_ + rows_],
                    idxs=idxs[i0_ : i0_ + rows_],
                )

        for i in range(self._n_live_tiles):
            i0 = i * t.tile_rows
            rows_here = min(t.tile_rows, self.n - i0)
            key = f"topk{k}_rowtile_{i}"
            if ckpt is not None and ckpt.is_done(key):
                unit = ckpt.load_unit(key)
                vals[i0 : i0 + rows_here] = unit["vals"]
                idxs[i0 : i0 + rows_here] = unit["idxs"]
                continue
            # Preemption point: everything in `pending` is flushed (and
            # checkpointed) first, so the manifest covers every tile the
            # device finished — the restart redoes only tile i onward.
            if _preemption.requested():
                while pending:
                    _drain_one()
                _preemption.check(
                    checkpoint_dir=str(ckpt.dir) if ckpt is not None else None
                )
            try:
                best_v, best_i = resilience.resilient_call(
                    "tile_execute",
                    lambda i=i, i0=i0: self._topk_row_tile(
                        i, i0, k, variant, rowsums_device, scanned
                    ),
                )
            except BaseException:
                # The tiles in `pending` finished on the device before
                # this one failed — flush them to the checkpoint (best
                # effort: the device may be wedged) so the failure costs
                # one tile of progress, not the pipeline depth.
                if ckpt is not None:
                    try:
                        while pending:
                            _drain_one()
                    except Exception:
                        pass
                raise
            pending.append((i, i0, rows_here, best_v, best_i))
            while len(pending) >= self._PIPELINE_DEPTH:
                _drain_one()
        while pending:
            _drain_one()
        return vals, idxs

    def _topk_row_tile(self, i: int, i0: int, k: int, variant: str,
                       rowsums_device, scanned: bool):
        """One row tile's streaming top-k dispatch — the ``tile_execute``
        resilience seam's unit of retry. Stateless w.r.t. the sweep
        (the rect factor cache is rebuilt idempotently), so recomputing
        a tile after a transient failure yields identical results."""
        t = self.tiled
        d_all = rowsums_device()
        if scanned and self._use_rect_kernel(k):
            # Fastest path: the rectangular two-pass Pallas kernel
            # scores this row tile against the whole column range on
            # the MXU (packed candidate extraction, exact reduce) —
            # measured 4.6× the lax.scan fold at N=1M, V=64 on a
            # v5e (740 s → 162 s rank-all; SCALE_r03_TPU.json).
            # The factor is padded to kernel shape once (cached):
            # the kernel skips its own O(N·128) pad on every call.
            # The cache is VARIANT-KEYED: dc is the denominator
            # vector, and reusing a rowsum-padded dc for a diagonal
            # pass would silently score the wrong variant.
            if (
                self._rect_factor is None
                or self._rect_factor[0] != variant
            ):
                self._rect_factor = (
                    variant,
                    *pk.rect_pad_factor(t.dense_device(), d_all),
                )
                # the rect path only ever slices the padded copy —
                # holding the unpadded dense C too would double the
                # factor's HBM residency for the whole pass
                t.drop_dense()
            _, cc, dc = self._rect_factor
            ci = jax.lax.dynamic_slice(
                cc, (i0, 0), (t.tile_rows, cc.shape[1])
            )
            di = jax.lax.dynamic_slice(dc, (i0,), (t.tile_rows,))
            row_ids = i0 + jnp.arange(t.tile_rows, dtype=jnp.int32)
            return pk.fused_topk_twopass_rect(
                ci, cc, di, dc, row_ids,
                k=k, n_true_cols=self.n,
                interpret=not pk.pallas_supported(),
            )
        if scanned:
            # One dispatch for the whole column sweep (lax.scan on
            # device) — same fold order and numerics as the tile
            # loop below, minus n_tiles round-trips per row tile.
            return sp.stream_row_tile_topk(
                t.dense_device(), d_all, jnp.int32(i0),
                k=k, n_true=self.n, tile_rows=t.tile_rows,
            )
        ci = t.tile(i)
        di = d_all[i0 : i0 + t.tile_rows]
        best_v = jnp.full((t.tile_rows, k), -jnp.inf, dtype=t.dtype)
        best_i = jnp.zeros((t.tile_rows, k), dtype=jnp.int32)
        for j in range(self._n_live_tiles):
            j0 = j * t.tile_rows
            best_v, best_i = sp.stream_merge_topk(
                ci, t.tile(j), di, d_all[j0 : j0 + t.tile_rows],
                best_v, best_i,
                jnp.int32(i0), jnp.int32(j0), k=k, n_true=self.n,
            )
        return best_v, best_i

    # In-flight row tiles (device [tile, k] pairs — tiny); 3 keeps one
    # tile fetching, one computing, one queued.
    _PIPELINE_DEPTH = 3

    _PARTIALS_PREFIX = "sym_partials_after_"
    # Partials snapshot cadence: resume redoes at most this many outer
    # tiles; saving every tile would cost O(n_tiles²·tile_rows·k) I/O
    # and a device sync per iteration for resilience nobody needs.
    _PARTIALS_EVERY = 8

    def diag_walks(self) -> np.ndarray:
        """diag(M)[i] = Σ_v C[i,v]² — the textbook-PathSim denominator
        (SURVEY.md §3.3), straight from the summed COO (O(nnz), no dense
        C, no M). diag ≤ M's row sums elementwise, so the f32 guard on
        the row sums covers it."""
        if self._diag is None:
            if self._c is None:
                from ..ops import packed as pkd

                self._diag = pkd.factor_diag(self._factor)[: self.n]
            else:
                s = self._c.summed()
                self._diag = np.bincount(
                    s.rows, weights=s.weights**2, minlength=self.n
                ).astype(np.float64)
        return self._diag

    def _denoms_device_padded(self, variant: str = "rowsum"):
        """Lazy padded denominator vector on device, shared by both
        sweeps: a run resuming entirely from checkpoint must never touch
        the graph. The streaming kernels take an arbitrary denominator —
        the variant only changes which vector rides along."""
        if variant not in ("rowsum", "diagonal"):
            raise ValueError(f"unknown PathSim variant {variant!r}")
        t = self.tiled
        d_dev = None

        def denoms_device():
            nonlocal d_dev
            if d_dev is None:
                d_pad = np.zeros(t.n_tiles * t.tile_rows)
                d_pad[: self.n] = (
                    self.global_walks() if variant == "rowsum"
                    else self.diag_walks()
                )
                d_dev = jnp.asarray(d_pad, dtype=t.dtype)
            return d_dev

        return denoms_device

    def _empty_result(self, k: int):
        return (
            np.full((self.n, k), -np.inf),
            np.zeros((self.n, k), dtype=np.int64),
        )

    def _topk_scores_symmetric(self, k: int, ckpt, variant: str = "rowsum"):
        """Symmetric half-sweep: outer tile i, inner j ∈ [i, n_tiles);
        each off-diagonal tile folds into row blocks i AND j
        (sp.stream_merge_topk_pair). Row block r is complete when outer
        iteration r finishes — contributions (i<r, j=r) arrived during
        earlier outer iterations, (r, j≥r) during its own. Tie-break
        order (ascending global column per row) is preserved because
        every row block's folds arrive in ascending column order.

        Resume protocol: every _PARTIALS_EVERY outer tiles a snapshot of
        the not-yet-finished row blocks lands under its OWN unit key
        (``sym_partials_after_{i}``) — save_unit writes all arrays before
        the manifest references them, so a crash mid-save can never
        yield a manifest-complete unit with mixed-iteration contents.
        The previous snapshot is dropped only after the new one is
        durable. A restart resumes from the newest snapshot, redoing at
        most _PARTIALS_EVERY outer tiles (their row units are simply
        overwritten with identical results)."""
        import jax

        t = self.tiled
        rowsums_device = self._denoms_device_padded(variant)
        vals, idxs = self._empty_result(k)
        empty_v = jnp.full((t.tile_rows, k), -jnp.inf, dtype=t.dtype)
        empty_i = jnp.zeros((t.tile_rows, k), dtype=jnp.int32)
        n_live = self._n_live_tiles
        best = {j: (empty_v, empty_i) for j in range(n_live)}

        start = 0
        prev_key = None
        if ckpt is not None:
            snaps = [
                key for key in ckpt.done_keys()
                if key.startswith(self._PARTIALS_PREFIX)
            ]
            if snaps:
                prev_key = max(
                    snaps, key=lambda s: int(s[len(self._PARTIALS_PREFIX):])
                )
                # A crash between save_unit(new) and drop_unit(prev)
                # leaves an older snapshot behind (~80 MB each at 1M
                # authors) — resume keeps only the newest.
                for stale in snaps:
                    if stale != prev_key:
                        ckpt.drop_unit(stale)
                after = int(prev_key[len(self._PARTIALS_PREFIX):])
                part = ckpt.load_unit(prev_key)
                # Rows ≤ after were saved before the snapshot (ordering
                # guarantee of the save sequence below); reload them.
                for i in range(after + 1):
                    unit = ckpt.load_unit(f"topk{k}_rowtile_{i}")
                    i0 = i * t.tile_rows
                    rows_here = min(t.tile_rows, self.n - i0)
                    vals[i0 : i0 + rows_here] = unit["vals"]
                    idxs[i0 : i0 + rows_here] = unit["idxs"]
                for pos, j in enumerate(range(after + 1, n_live)):
                    best[j] = (
                        jnp.asarray(part["vals"][pos], dtype=t.dtype),
                        jnp.asarray(part["idxs"][pos], dtype=jnp.int32),
                    )
                start = after + 1

        for i in range(start, n_live):
            # Preemption point (outer-tile boundary): every finished row
            # unit is already durable; a fresh partials snapshot makes
            # the restart resume exactly here instead of at the last
            # cadence snapshot.
            if _preemption.requested():
                if ckpt is not None and i > start:
                    prev_key = self._save_sym_partials(
                        ckpt, best, after=i - 1, prev_key=prev_key, k=k
                    )
                _preemption.check(
                    checkpoint_dir=str(ckpt.dir) if ckpt is not None else None
                )
            i0 = i * t.tile_rows
            rows_here = min(t.tile_rows, self.n - i0)
            ci = t.tile(i)
            d_all = rowsums_device()
            di = d_all[i0 : i0 + t.tile_rows]
            bv, bi = best[i]
            # Each merge is one tile_execute attempt: results are
            # assigned only on success, so a retried merge never folds
            # the same tile into the running best twice (the merge is
            # NOT idempotent — a duplicate fold would duplicate
            # candidate indices in the top-k list).
            bv, bi = resilience.resilient_call(
                "tile_execute",
                lambda: sp.stream_merge_topk(
                    ci, ci, di, di, bv, bi,
                    jnp.int32(i0), jnp.int32(i0), k=k, n_true=self.n,
                ),
            )
            for j in range(i + 1, n_live):
                j0 = j * t.tile_rows
                cj = t.tile(j)
                dj = d_all[j0 : j0 + t.tile_rows]
                bjv, bji = best[j]
                bv, bi, bjv, bji = resilience.resilient_call(
                    "tile_execute",
                    lambda cj=cj, dj=dj, j0=j0, bv=bv, bi=bi, bjv=bjv,
                    bji=bji: sp.stream_merge_topk_pair(
                        ci, cj, di, dj, bv, bi, bjv, bji,
                        jnp.int32(i0), jnp.int32(j0), k=k, n_true=self.n,
                    ),
                )
                best[j] = (bjv, bji)
            vals[i0 : i0 + rows_here] = np.asarray(
                bv[:rows_here], dtype=np.float64
            )
            idxs[i0 : i0 + rows_here] = np.asarray(
                bi[:rows_here], dtype=np.int64
            )
            del best[i]  # complete; its state is in vals/idxs now
            if ckpt is not None:
                ckpt.save_unit(
                    f"topk{k}_rowtile_{i}",
                    vals=vals[i0 : i0 + rows_here],
                    idxs=idxs[i0 : i0 + rows_here],
                )
                last = i == n_live - 1
                if i % self._PARTIALS_EVERY == self._PARTIALS_EVERY - 1 or last:
                    prev_key = self._save_sym_partials(
                        ckpt, best, after=i, prev_key=prev_key, k=k
                    )
        return vals, idxs

    def _save_sym_partials(self, ckpt, best: dict, after: int,
                           prev_key: str | None, k: int) -> str:
        """Snapshot the running bests of row tiles > ``after`` under
        ``sym_partials_after_{after}`` and drop the superseded snapshot
        only once the new one is durable (save_unit writes all arrays
        before the manifest references them). Idempotent: re-saving the
        same key overwrites identical contents."""
        t = self.tiled
        rest = range(after + 1, self._n_live_tiles)
        jax.block_until_ready([best[j][0] for j in rest])
        new_key = f"{self._PARTIALS_PREFIX}{after}"
        ckpt.save_unit(
            new_key,
            vals=np.stack(
                [np.asarray(best[j][0]) for j in rest]
            ) if len(rest) else np.zeros((0, t.tile_rows, k)),
            idxs=np.stack(
                [np.asarray(best[j][1]) for j in rest]
            ) if len(rest) else np.zeros(
                (0, t.tile_rows, k), dtype=np.int32
            ),
        )
        if prev_key is not None and prev_key != new_key:
            ckpt.drop_unit(prev_key)  # only after the new one is durable
        return new_key

    # ------------------------------------------------------------------
    # Exact-counts phase (counts past 2^24): f64 host rescoring of the
    # f32 pass's candidates. TPU-first split of labor — selection stays
    # on the MXU in f32; exactness costs one O(N·k_cand·V) host einsum
    # over integers < 2^53 (exact in f64), not f64 in the hot loop.
    # ------------------------------------------------------------------

    def _csr_factor(self):
        """(coalesced row-major COO, indptr) for the rescore helpers.
        ``self._c`` itself must NOT be assumed sorted or duplicate-free:
        a single-step half-chain (APA) comes back as the raw adjacency
        block, unsorted and with duplicate coordinates — ``summed()``
        canonicalizes (same defense diag_walks uses)."""
        if getattr(self, "_c_sum", None) is None:
            self._c_sum = self._c.summed()
            self._indptr = np.searchsorted(
                self._c_sum.rows, np.arange(self.n + 1)
            )
        return self._c_sum, self._indptr

    def _densify_rows_f64(self, rows: np.ndarray) -> np.ndarray:
        """Dense f64 [len(rows), V] gather of arbitrary factor rows,
        fully vectorized (the flat-expansion idiom from coo_matmul).
        Packed layouts gather through the sanctioned accessor — same
        exact integers, chunk-transient decode instead of a resident
        CSR copy."""
        if self._c is None:
            from ..ops import packed as pkd

            return pkd.gather_rows_dense(
                self._factor, np.asarray(rows, dtype=np.int64)
            )
        s, indptr = self._csr_factor()
        rows = np.asarray(rows, dtype=np.int64)
        starts = indptr[rows]
        counts = indptr[rows + 1] - starts
        total = int(counts.sum())
        out = np.zeros((rows.shape[0], self.tiled.v), dtype=np.float64)
        if total:
            ridx = np.repeat(np.arange(rows.shape[0]), counts)
            cum = np.concatenate([[0], np.cumsum(counts)])
            flat = np.repeat(starts, counts) + (
                np.arange(total) - np.repeat(cum[:-1], counts)
            )
            out[ridx, s.cols[flat]] = s.weights[flat]
        return out

    def _f32_score_relerr_bound(self) -> float:
        """Rigorous relative-error bound on a score from the f32 pass:
        non-negative data makes the GEMM's error ≤ (V+2)u·m (standard
        forward bound with Σ|terms| = m), plus input casts (C entries
        and colsums may themselves exceed 2^24), the denominator GEMV,
        and the final divide — (2V+16)u covers every path, doubled for
        defense. u = 2^-24."""
        return (2.0 * self.tiled.v + 16.0) * 2.0**-24 * 2.0

    def _exact_topk_rescore(self, k: int, cand_vals: np.ndarray,
                            cand_idxs: np.ndarray, variant: str):
        """Reduce the f32 pass's [N, k_cand] candidates to the exact
        top-k. Per chunk of rows: gather candidate factor rows dense
        (f64), one einsum for the pairwise walks, exact normalize,
        lexicographic (−score, column) selection — the oracle's
        tie-break. Soundness certificate per row: any non-candidate j
        has f32 score ≤ the last kept candidate's, so its TRUE score is
        ≤ that·(1+ε); if the exact k-th candidate beats that bound (or
        every non-self column is already a candidate, or the last f32
        score is exactly 0 — zero scores are error-free for integer
        data), the candidate set provably contains the true top-k.
        Rows that fail the certificate get a full exact row sweep."""
        d = np.asarray(
            self.global_walks() if variant == "rowsum"
            else self.diag_walks(),
            dtype=np.float64,
        )
        n, v = self.n, self.tiled.v
        k_cand = cand_idxs.shape[1]
        kk = min(k, k_cand)
        eps = self._f32_score_relerr_bound()
        out_v = np.full((n, k), -np.inf)
        out_i = np.zeros((n, k), dtype=np.int64)
        chunk = max(64, int((256 << 20) // max(k_cand * v * 8, 1)))
        flagged: list[np.ndarray] = []
        all_cands = n - 1 <= k_cand
        for i0 in range(0, n, chunk):
            i1 = min(i0 + chunk, n)
            rows = np.arange(i0, i1)
            ci = self._densify_rows_f64(rows)
            cid = np.asarray(cand_idxs[i0:i1], dtype=np.int64)
            valid = np.isfinite(cand_vals[i0:i1])
            safe_id = np.where(valid, cid, 0)
            cj = self._densify_rows_f64(safe_id.ravel()).reshape(
                i1 - i0, k_cand, v
            )
            m = np.einsum("tv,tcv->tc", ci, cj)
            den = d[rows][:, None] + d[safe_id]
            sc = np.where(den > 0, 2.0 * m / np.where(den > 0, den, 1.0),
                          0.0)
            sc = np.where(valid, sc, -np.inf)
            order = np.lexsort((safe_id, -sc), axis=-1)[:, :kk]
            out_v[i0:i1, :kk] = np.take_along_axis(sc, order, axis=1)
            out_i[i0:i1, :kk] = np.take_along_axis(safe_id, order, axis=1)
            if not all_cands:
                last_f32 = np.asarray(cand_vals[i0:i1, -1],
                                      dtype=np.float64)
                kth = out_v[i0:i1, kk - 1]
                sound = (last_f32 == 0.0) | (kth > last_f32 * (1.0 + eps))
                if not sound.all():
                    flagged.append(rows[~sound])
        # surfaced in scale artifacts: how often the certificate failed
        self._last_fallback_rows = int(
            sum(f.shape[0] for f in flagged)
        )
        if flagged:
            self._exact_full_rows(np.concatenate(flagged), d, k,
                                  out_v, out_i)
        return out_v, out_i

    def _exact_full_rows(self, rows: np.ndarray, d: np.ndarray, k: int,
                         out_v: np.ndarray, out_i: np.ndarray) -> None:
        """Exact f64 scores of ``rows`` against EVERY column, top-k with
        the (−score, ascending column) tie-break — the uncertifiable-row
        fallback. Needed exactly when score TIES span the candidate
        boundary (equal integer counts + equal degrees — common in the
        low-count tail), because the oracle's ascending-column tie-break
        then depends on columns the prefilter never kept. Both axes are
        chunked: the score block never exceeds ~256 MB regardless of how
        many rows were flagged."""
        n = self.n
        col_chunk = max(256, int((64 << 20) // max(self.tiled.v * 8, 1)))
        row_chunk = max(64, int((256 << 20) // max(col_chunk * 8, 1)))
        for r0 in range(0, rows.shape[0], row_chunk):
            rblk = rows[r0 : r0 + row_chunk]
            ci = self._densify_rows_f64(rblk)
            di = d[rblk]
            best_v = np.full((rblk.shape[0], 0), -np.inf)
            best_c = np.zeros((rblk.shape[0], 0), dtype=np.int64)
            for j0 in range(0, n, col_chunk):
                j1 = min(j0 + col_chunk, n)
                cj = self._densify_rows_f64(np.arange(j0, j1))
                m = ci @ cj.T
                den = di[:, None] + d[j0:j1][None, :]
                sc = np.where(
                    den > 0, 2.0 * m / np.where(den > 0, den, 1.0), 0.0
                )
                cols = np.broadcast_to(np.arange(j0, j1),
                                       sc.shape).copy()
                sc = np.where(cols == rblk[:, None], -np.inf, sc)  # self
                kk = min(k, sc.shape[1])
                o = np.lexsort((cols, -sc), axis=-1)[:, :kk]
                merged_v = np.concatenate(
                    [best_v, np.take_along_axis(sc, o, axis=1)], axis=1
                )
                merged_c = np.concatenate(
                    [best_c, np.take_along_axis(cols, o, axis=1)], axis=1
                )
                o = np.lexsort((merged_c, -merged_v), axis=-1)[:, :k]
                best_v = np.take_along_axis(merged_v, o, axis=1)
                best_c = np.take_along_axis(merged_c, o, axis=1)
            kk = best_v.shape[1]
            out_v[rblk, :kk] = best_v
            out_i[rblk, :kk] = best_c

    def pairwise_row_exact(self, source_index: int) -> np.ndarray:
        """M[source, :] with exact f64 host arithmetic — the rescore-
        mode analog of pairwise_row for the driver's reporting path
        (the reference prints exact integer counts,
        DPathSim_APVPA.py:86-88)."""
        ci = self._densify_rows_f64(np.array([source_index]))[0]
        out = np.zeros(self.n, dtype=np.float64)
        chunk = max(256, int((128 << 20) // max(self.tiled.v * 8, 1)))
        for j0 in range(0, self.n, chunk):
            j1 = min(j0 + chunk, self.n)
            out[j0:j1] = self._densify_rows_f64(np.arange(j0, j1)) @ ci
        return out
