"""Slice-aware half-chain factor build for partitioned serving.

One partition worker's arithmetic state is a *row slice* of the dense
half-chain factor ``C`` plus its slice of the denominator vector — the
same two arrays the single-host index build reads
(:func:`~..index.build.half_chain_and_denominators`), restricted to the
rows the partition holds. The fold itself reuses the sparse machinery
(``ops.sparse.half_chain_coo``) over the partition's *sliced* HIN:
axis-type blocks carry only held rows' edges, so the fold touches only
held work and its COO output has support exclusively on held rows — the
slice is free, not a post-hoc filter.

Denominators need one global exchange: for the rowsum variant,
``d = C · g`` with ``g = Σ_rows C`` summed over EVERY partition's rows.
Each holder computes the column-sum contribution of each range it holds
(exact integer sums, so contributions from different holders of the
same range are bit-identical and the router may take any one); the
router sums one contribution per range and broadcasts ``g`` back
(DESIGN.md §26). Until ``g`` arrives a partition cannot score anything.

The factor-slice attributes built here (``c_held`` / ``held_slot_of`` /
``range_slots``) form the surface the PT001 analyzer pass guards: only
the partition exchange layer may touch them.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..data.partition import PartitionMap

# The factor-slice surface the PT001 analyzer pass guards: attribute
# names that expose raw held-row factor state. Only the partition
# exchange layer (this module + serving/partition.py) may touch them —
# any other package code reading them is reading factor rows it does
# not own, which is exactly the coupling that would silently break the
# ownership contract. (Registry style mirrors PROTOCOL_OPS/WC001: the
# analyzer parses this literal, so the rule and the code can't drift.)
FACTOR_SURFACE = frozenset({"c_held", "held_slot_of", "range_slots"})


@dataclasses.dataclass
class FactorSlice:
    """The held rows' dense factor slice and its row bookkeeping.

    ``c_held`` is f64 [n_held, V] (exact integer counts, V = padded
    target width of the half chain); ``rows`` the global row ids of the
    slots in order; ``held_slot_of`` the inverse map (−1 = not held);
    ``range_slots`` maps each held range index to its [lo, hi) slot
    window inside ``c_held``.
    """

    c_held: np.ndarray
    rows: np.ndarray
    held_slot_of: np.ndarray
    range_slots: dict[int, tuple[int, int]]

    @property
    def v(self) -> int:
        return int(self.c_held.shape[1])

    @property
    def n_held(self) -> int:
        return int(self.c_held.shape[0])

    def holds(self, row: int) -> bool:
        return 0 <= row < self.held_slot_of.shape[0] and self.held_slot_of[row] >= 0


def build_factor_slice(
    hin_slice, metapath, pmap: PartitionMap, held: tuple[int, ...]
) -> FactorSlice:
    """Fold the (sliced) HIN's half chain and densify only the held
    rows. ``hin_slice`` must be the output of
    :func:`~..data.partition.slice_hin` for exactly ``held`` — the fold
    produces no support outside the held ranges, which is asserted, not
    assumed."""
    from ..ops import planner

    coo = planner.fold_half(hin_slice, metapath).summed()
    rows_list = []
    range_slots: dict[int, tuple[int, int]] = {}
    at = 0
    for g in held:
        lo, hi = pmap.range_of(g)
        rows_list.append(np.arange(lo, hi, dtype=np.int64))
        range_slots[g] = (at, at + (hi - lo))
        at += hi - lo
    rows = (
        np.concatenate(rows_list) if rows_list
        else np.empty(0, dtype=np.int64)
    )
    held_slot_of = np.full(pmap.n, -1, dtype=np.int64)
    held_slot_of[rows] = np.arange(rows.shape[0], dtype=np.int64)
    c_held = np.zeros((rows.shape[0], coo.shape[1]), dtype=np.float64)
    if coo.rows.shape[0]:
        src = coo.rows.astype(np.int64)
        in_logical = src < pmap.n  # capacity-padded slots carry no rows
        src, cols, w = src[in_logical], coo.cols[in_logical], (
            coo.weights[in_logical]
        )
        slots = held_slot_of[src]
        if (slots < 0).any():
            raise ValueError(
                "sliced half chain has support outside the held ranges "
                "— slice_hin and build_factor_slice disagree on the axis"
            )
        c_held[slots, cols] = w
    return FactorSlice(
        c_held=c_held, rows=rows, held_slot_of=held_slot_of,
        range_slots=range_slots,
    )


def range_colsums(
    fs: FactorSlice, held: tuple[int, ...]
) -> dict[int, dict]:
    """Per-held-range column-sum contributions as sparse wire payloads
    ``{range: {"cols": [...], "vals": [...]}}`` — exact integer sums,
    so any holder's contribution for a range equals any other's."""
    out = {}
    for g in held:
        lo, hi = fs.range_slots[g]
        colsum = fs.c_held[lo:hi].sum(axis=0)
        nz = np.flatnonzero(colsum)
        out[g] = {
            "cols": [int(c) for c in nz],
            "vals": [float(colsum[c]) for c in nz],
        }
    return out


def patch_factor_slice(fs: FactorSlice, delta_c, n_logical: int) -> np.ndarray:
    """Apply a signed half-chain delta (``ops.sparse.COOMatrix``,
    support restricted to held rows) to the dense slice in place.
    Returns the sorted global rows whose factor row changed — the rows
    whose denominators must be recomputed against the new global
    colsum."""
    if delta_c.rows.shape[0] == 0:
        return np.empty(0, dtype=np.int64)
    src = delta_c.rows.astype(np.int64)
    in_logical = src < n_logical
    src = src[in_logical]
    cols = delta_c.cols[in_logical]
    w = delta_c.weights[in_logical]
    slots = fs.held_slot_of[src]
    if (slots < 0).any():
        raise ValueError(
            "half-chain delta touches rows this partition does not hold "
            "— the router's delta filter and the slice disagree"
        )
    np.add.at(fs.c_held, (slots, cols), w.astype(np.float64))
    return np.unique(src)
