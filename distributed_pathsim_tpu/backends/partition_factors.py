"""Slice-aware half-chain factor build for partitioned serving.

One partition worker's arithmetic state is a *row slice* of the dense
half-chain factor ``C`` plus its slice of the denominator vector — the
same two arrays the single-host index build reads
(:func:`~..index.build.half_chain_and_denominators`), restricted to the
rows the partition holds. The fold itself reuses the sparse machinery
(``ops.sparse.half_chain_coo``) over the partition's *sliced* HIN:
axis-type blocks carry only held rows' edges, so the fold touches only
held work and its COO output has support exclusively on held rows — the
slice is free, not a post-hoc filter.

Denominators need one global exchange: for the rowsum variant,
``d = C · g`` with ``g = Σ_rows C`` summed over EVERY partition's rows.
Each holder computes the column-sum contribution of each range it holds
(exact integer sums, so contributions from different holders of the
same range are bit-identical and the router may take any one); the
router sums one contribution per range and broadcasts ``g`` back
(DESIGN.md §26). Until ``g`` arrives a partition cannot score anything.

The factor-slice attributes built here (``c_held`` / ``held_slot_of`` /
``range_slots``) form the surface the PT001 analyzer pass guards: only
the partition exchange layer may touch them.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..data.partition import PartitionMap

# The factor-slice surface the PT001 analyzer pass guards: attribute
# names that expose raw held-row factor state. Only the partition
# exchange layer (this module + serving/partition.py) may touch them —
# any other package code reading them is reading factor rows it does
# not own, which is exactly the coupling that would silently break the
# ownership contract. (Registry style mirrors PROTOCOL_OPS/WC001: the
# analyzer parses this literal, so the rule and the code can't drift.)
# ``packed_held`` is the compressed twin of ``c_held`` (the
# factor_format knob, DESIGN.md §29) — same ownership rules.
FACTOR_SURFACE = frozenset({
    "c_held", "packed_held", "held_slot_of", "range_slots",
})


@dataclasses.dataclass
class FactorSlice:
    """The held rows' factor slice and its row bookkeeping.

    The arithmetic state lives in exactly ONE of two layouts, chosen
    by the ``factor_format`` tuning knob at build: ``c_held`` — dense
    f64 [n_held, V] (exact integer counts, V = padded target width of
    the half chain) — or ``packed_held``, the compressed slot-space
    factor (ops/packed.py) whose windows decode transiently per op.
    ``rows`` is the global row ids of the slots in order;
    ``held_slot_of`` the inverse map (−1 = not held); ``range_slots``
    maps each held range index to its [lo, hi) slot window. Every
    consumer outside this module goes through the accessor methods, so
    the two layouts can never produce different numbers: both speak
    exact f64 integers in slot space.
    """

    c_held: np.ndarray | None
    rows: np.ndarray
    held_slot_of: np.ndarray
    range_slots: dict[int, tuple[int, int]]
    packed_held: object | None = None  # ops.packed.PackedFactor
    _v: int = 0
    factor_format: str = "coo"

    @property
    def v(self) -> int:
        if self._v:
            return int(self._v)
        if self.c_held is not None:  # direct-constructed dense slices
            return int(self.c_held.shape[1])
        return int(self.packed_held.shape[1])

    @property
    def n_held(self) -> int:
        if self.c_held is not None:
            return int(self.c_held.shape[0])
        return int(self.packed_held.shape[0])

    def holds(self, row: int) -> bool:
        return 0 <= row < self.held_slot_of.shape[0] and self.held_slot_of[row] >= 0

    # -- layout-independent arithmetic accessors ---------------------------
    #
    # Exact f64 integer arithmetic either way: the dense path slices
    # c_held, the packed path decodes windows through the sanctioned
    # ops/packed accessors — bit-identical numbers by construction.

    def window_dense(self, lo_slot: int, hi_slot: int) -> np.ndarray:
        """Dense f64 [hi−lo, V] view/materialization of a slot window
        (the partial_* GEMM operand)."""
        if self.c_held is not None:
            return self.c_held[lo_slot:hi_slot]
        from ..ops import packed as pkd

        span = pkd.row_slice(self.packed_held, lo_slot, hi_slot)
        out = np.zeros((hi_slot - lo_slot, self.v), dtype=np.float64)
        if span.rows.shape[0]:
            out[span.rows - lo_slot, span.cols] = span.weights
        return out

    def row_dense(self, slot: int) -> np.ndarray:
        """One held row's dense factor tile (the tile_pull payload)."""
        return self.window_dense(slot, slot + 1)[0]

    def window_colsum(self, lo_slot: int, hi_slot: int) -> np.ndarray:
        """Exact column sums of a slot window (colsum contributions)."""
        if self.c_held is not None:
            return self.c_held[lo_slot:hi_slot].sum(axis=0)
        from ..ops import packed as pkd

        span = pkd.row_slice(self.packed_held, lo_slot, hi_slot)
        out = np.zeros(self.v, dtype=np.float64)
        if span.rows.shape[0]:
            np.add.at(out, span.cols, span.weights)
        return out

    def matvec(self, g: np.ndarray) -> np.ndarray:
        """``C_held @ g`` over every held slot (denominator init)."""
        if self.c_held is not None:
            return self.c_held @ g
        from ..ops import packed as pkd

        return pkd.factor_rowsums_weighted(self.packed_held, g)

    def rows_matvec(self, slots: np.ndarray, g: np.ndarray) -> np.ndarray:
        """``C_held[slots] @ g`` (post-delta denominator re-encode)."""
        if self.c_held is not None:
            return self.c_held[slots] @ g
        from ..ops import packed as pkd

        return pkd.gather_rows_dense(self.packed_held, slots) @ g

    def factor_bytes(self) -> int:
        """Resident factor bytes as held — the number the max-N-per-
        partition curve divides the worker budget by."""
        if self.c_held is not None:
            return int(self.c_held.nbytes)
        from ..ops import packed as pkd

        return pkd.factor_bytes(self.packed_held)


def build_factor_slice(
    hin_slice, metapath, pmap: PartitionMap, held: tuple[int, ...],
    factor_format: str = "coo",
) -> FactorSlice:
    """Fold the (sliced) HIN's half chain and hold only the held rows
    — dense when ``factor_format == "coo"``, packed through the
    sanctioned ops/packed factory otherwise (a compressed slice
    divides into the per-worker memory budget, which is what raises
    max-N per partition). ``hin_slice`` must be the output of
    :func:`~..data.partition.slice_hin` for exactly ``held`` — the fold
    produces no support outside the held ranges, which is asserted, not
    assumed."""
    from ..ops import planner
    from ..ops import sparse as sp

    coo = planner.fold_half(hin_slice, metapath).summed()
    rows_list = []
    range_slots: dict[int, tuple[int, int]] = {}
    at = 0
    for g in held:
        lo, hi = pmap.range_of(g)
        rows_list.append(np.arange(lo, hi, dtype=np.int64))
        range_slots[g] = (at, at + (hi - lo))
        at += hi - lo
    rows = (
        np.concatenate(rows_list) if rows_list
        else np.empty(0, dtype=np.int64)
    )
    held_slot_of = np.full(pmap.n, -1, dtype=np.int64)
    held_slot_of[rows] = np.arange(rows.shape[0], dtype=np.int64)
    src = coo.rows.astype(np.int64)
    in_logical = src < pmap.n  # capacity-padded slots carry no rows
    src, cols, w = src[in_logical], coo.cols[in_logical], (
        coo.weights[in_logical]
    )
    slots = held_slot_of[src]
    if (slots < 0).any():
        raise ValueError(
            "sliced half chain has support outside the held ranges "
            "— slice_hin and build_factor_slice disagree on the axis"
        )
    if factor_format == "coo":
        c_held = np.zeros((rows.shape[0], coo.shape[1]), dtype=np.float64)
        if src.shape[0]:
            c_held[slots, cols] = w
        return FactorSlice(
            c_held=c_held, rows=rows, held_slot_of=held_slot_of,
            range_slots=range_slots, _v=int(coo.shape[1]),
            factor_format="coo",
        )
    from ..ops import packed as pkd

    packed_held = pkd.make_factor(
        sp.COOMatrix(
            rows=slots, cols=cols.astype(np.int64),
            weights=w.astype(np.float64),
            shape=(int(rows.shape[0]), int(coo.shape[1])),
        ),
        factor_format,
    )
    return FactorSlice(
        c_held=None, rows=rows, held_slot_of=held_slot_of,
        range_slots=range_slots, packed_held=packed_held,
        _v=int(coo.shape[1]), factor_format=factor_format,
    )


def range_colsums(
    fs: FactorSlice, held: tuple[int, ...]
) -> dict[int, dict]:
    """Per-held-range column-sum contributions as sparse wire payloads
    ``{range: {"cols": [...], "vals": [...]}}`` — exact integer sums,
    so any holder's contribution for a range equals any other's
    (whatever layout each holds its slice in)."""
    out = {}
    for g in held:
        lo, hi = fs.range_slots[g]
        colsum = fs.window_colsum(lo, hi)
        nz = np.flatnonzero(colsum)
        out[g] = {
            "cols": [int(c) for c in nz],
            "vals": [float(colsum[c]) for c in nz],
        }
    return out


def patch_factor_slice(fs: FactorSlice, delta_c, n_logical: int) -> np.ndarray:
    """Apply a signed half-chain delta (``ops.sparse.COOMatrix``,
    support restricted to held rows) to the slice in place — a dense
    scatter-add, or the packed layouts' chunk-granular
    ``patch_factor`` (both O(Δ)-row-granular, both recompile-free:
    nothing here touches a device shape). Returns the sorted global
    rows whose factor row changed — the rows whose denominators must
    be recomputed against the new global colsum."""
    if delta_c.rows.shape[0] == 0:
        return np.empty(0, dtype=np.int64)
    src = delta_c.rows.astype(np.int64)
    in_logical = src < n_logical
    src = src[in_logical]
    cols = delta_c.cols[in_logical]
    w = delta_c.weights[in_logical]
    slots = fs.held_slot_of[src]
    if (slots < 0).any():
        raise ValueError(
            "half-chain delta touches rows this partition does not hold "
            "— the router's delta filter and the slice disagree"
        )
    if fs.c_held is not None:
        np.add.at(fs.c_held, (slots, cols), w.astype(np.float64))
    else:
        from ..ops import packed as pkd
        from ..ops import sparse as sp

        fs.packed_held = pkd.patch_factor(
            fs.packed_held,
            sp.COOMatrix(
                rows=slots, cols=cols.astype(np.int64),
                weights=w.astype(np.float64),
                shape=(fs.n_held, fs.v),
            ),
        )
    return np.unique(src)
