"""Multi-device sharded backend (BASELINE.json config 3).

Routes the symmetric half-chain through parallel/sharded.py on a 1-D
``dp`` mesh: the half-chain factor C (host-folded from COO, [N, V]) is
row-sharded so each device owns the rows of M it will compute; the only
collectives are one ``psum`` (column totals for row sums) and either one
``all_gather`` or a ``ppermute`` ring for the all-pairs product /
distributed top-k. Works identically on 8 virtual CPU devices (tests)
and real TPU slices — same program, same collectives, different mesh.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import jax

from ..ops import chain
from ..parallel.mesh import make_mesh
from ..parallel.multihost import distributed_first_block, make_hybrid_mesh
from ..parallel.sharded import (
    choose_allpairs_strategy,
    sharded_chain_outputs,
    sharded_topk,
)
from .base import PathSimBackend, register_backend


def _fetch(x) -> np.ndarray:
    """Bring a (possibly cross-process) sharded array to this host.

    Single-process: plain fetch. Multi-process: ``np.asarray`` on an
    array spanning non-addressable devices raises, so gather it to every
    host first — callers of the dense-output APIs accept that cost; the
    big-N paths (``topk``) only ever fetch [N, k] winners."""
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


@register_backend("jax-sharded")
class JaxShardedBackend(PathSimBackend):
    def __init__(
        self,
        hin,
        metapath,
        n_devices: int | None = None,
        allpairs_strategy: str = "auto",
        dtype=jnp.float32,
        **options,
    ):
        super().__init__(hin, metapath, **options)
        if not metapath.is_symmetric:
            raise ValueError(
                "jax-sharded requires a symmetric metapath (M = C Cᵀ); "
                "use the dense backend for asymmetric chains"
            )
        if jax.process_count() > 1:
            # host_row_range's contiguous-ownership contract only holds
            # for the hosts-outermost, process-is-granule construction;
            # a flat jax.devices() slice could interleave processes (and
            # slicing away another process's devices would break the
            # local-data assembly outright).
            if n_devices is not None:
                raise ValueError(
                    "n_devices is a single-process knob; a multi-host "
                    "run always uses every device in the job"
                )
            self.mesh = make_hybrid_mesh(tp=1)
        else:
            self.mesh = make_mesh(n_devices)
        self.n = hin.type_size(metapath.source_type)
        if allpairs_strategy == "auto":
            # C is [N, V] with V the palindrome midpoint type's size
            v = hin.type_size(metapath.node_types[len(metapath.steps) // 2])
            allpairs_strategy = choose_allpairs_strategy(
                self.n, v, self.mesh.shape["dp"], np.dtype(dtype).itemsize
            )
        self.allpairs_strategy = allpairs_strategy

        # Sparse-first: fold the half-chain to COO on host (O(nnz)); the
        # dense [N, V] factor C is then assembled HOST-LOCALLY — each
        # process densifies only its own row range and the global
        # row-sharded array comes from make_array_from_process_local_data
        # (parallel/multihost.py). Single-process that's the full range
        # (identical result to a plain device_put); on a multi-host mesh
        # no host ever materializes all of C, which is what the
        # million-author configuration requires. The sharded program then
        # starts at C (empty ``rest``): same collectives, far less data.
        self._np_dtype = np.dtype(dtype)
        from ..ops import planner

        self._install_coo(
            planner.fold_half(
                hin, metapath, memo=self._subchain_memo, plan=self.plan
            )
        )

    def _install_coo(self, coo) -> None:
        """Bind a (new) folded half-chain COO: exactness guard, host
        sort, distributed dense assembly, derived-cache reset. Shared by
        __init__ and the delta-update hook — a patched backend runs the
        identical assembly a fresh build does (same sharded programs:
        the factor's capacity shape never changes under a non-fallback
        delta, so nothing recompiles)."""
        np_dtype = self._np_dtype
        self._check_exact_coo(coo, np_dtype)
        self._coo_shape = coo.shape
        self._coo_nnz = int(coo.rows.shape[0])
        order = np.argsort(coo.rows, kind="stable")
        rows_s = coo.rows[order]
        cols_s = coo.cols[order]
        w_s = coo.weights[order]

        def load_rows(a: int, b: int) -> np.ndarray:
            lo, hi = np.searchsorted(rows_s, [a, b])
            out = np.zeros((b - a, coo.shape[1]), dtype=np.float64)
            np.add.at(out, (rows_s[lo:hi] - a, cols_s[lo:hi]), w_s[lo:hi])
            return out.astype(np_dtype)  # exact: _check_exact_coo guards

        self._first = distributed_first_block(
            load_rows, coo.shape[0], coo.shape[1], self.mesh, dtype=np_dtype
        )
        # kept (they're alive in the load_rows closure anyway) so the
        # checkpoint fingerprint can be computed LAZILY — hashing
        # hundreds of MB of COO on every no-checkpoint construction
        # would be pure startup waste
        self._coo_sorted = (rows_s, cols_s, w_s)
        self._coo_digest_cache = None
        self._m: np.ndarray | None = None
        self._rowsums: np.ndarray | None = None

    def _apply_delta_impl(self, plan) -> None:
        """Re-install the plan's patched factor (ΔC from the delta-COO
        product rule — no chain refold): one host re-sort plus the
        host-local dense row assembly, reusing every compiled sharded
        program (shapes pinned by the capacity invariant). The
        distributed M/rowsums recompute lazily on the next query through
        the exact same collectives a fresh build would run."""
        self.hin = plan.hin_new
        self.n = self.hin.type_size(self.metapath.source_type)
        self._install_coo(plan.half_new)

    @property
    def _coo_digest(self) -> str:
        if getattr(self, "_coo_digest_cache", None) is None:
            import hashlib

            rows_s, cols_s, w_s = self._coo_sorted
            h = hashlib.sha256()
            h.update(np.ascontiguousarray(rows_s, dtype=np.int64).tobytes())
            h.update(np.ascontiguousarray(cols_s, dtype=np.int64).tobytes())
            h.update(np.ascontiguousarray(w_s, dtype=np.float64).tobytes())
            self._coo_digest_cache = h.hexdigest()[:16]
        return self._coo_digest_cache

    @staticmethod
    def _check_exact_coo(coo, dtype) -> None:
        """Exact per-row overflow check — C entries are multiplicities,
        so no cheap bound on the rowsums exists. Computed straight from
        the COO (O(nnz), no dense C needed): rowsum_i = Σ_e w_e ·
        colsum[col_e] over this row's entries. Shared guard handles the
        effective-device-dtype subtlety (f64 without x64 is still f32)."""
        if chain.effective_device_dtype(dtype) != np.float32:
            return
        colsum = np.bincount(
            coo.cols, weights=coo.weights, minlength=coo.shape[1]
        )
        rs = np.bincount(
            coo.rows,
            weights=coo.weights * colsum[coo.cols],
            minlength=coo.shape[0],
        )
        chain.check_exact_counts(rs.max(initial=0.0), dtype)

    def _compute(self, want_m: bool):
        if self._rowsums is None or (want_m and self._m is None):
            # rest=() — this backend always starts from the fully folded C
            m, rowsums = sharded_chain_outputs(
                self._first,
                (),
                mesh=self.mesh,
                allpairs_strategy=self.allpairs_strategy,
                want_m=want_m,
            )
            self._rowsums = _fetch(rowsums).astype(np.float64)[: self.n]
            if want_m:
                self._m = _fetch(m).astype(np.float64)[: self.n, : self.n]

    def global_walks(self) -> np.ndarray:
        self._compute(want_m=False)
        return self._rowsums

    def commuting_matrix(self) -> np.ndarray:
        self._compute(want_m=True)
        return self._m

    def pairwise_row(self, source_index: int) -> np.ndarray:
        return self.commuting_matrix()[source_index]

    def pairwise_rows(self, rows) -> np.ndarray:
        """Batched M[rows, :]: one fancy-index gather from the (already
        sharded-computed, host-resident) commuting matrix — the serving
        bucket costs a memcpy, not B row copies through the base-class
        loop. The first call pays the distributed M build; a warm
        serving process holds M for its lifetime."""
        return self.commuting_matrix()[np.asarray(rows, dtype=np.int64)]

    def topk(self, k: int = 10, mask_self: bool = True,
             variant: str = "rowsum"):
        """Distributed per-row top-k via the ppermute ring: no device
        ever holds more than an [n_loc, n_loc] score tile, and only
        [N, k] winners come back to the host. The ring-step kernel
        (rect-Pallas vs jnp fold) is resolved HERE, outside
        sharded_topk's jit cache, so a tuning table installed after a
        prior trace still takes effect."""
        vals, idxs = sharded_topk(
            self._first,
            (),
            mesh=self.mesh,
            k=k,
            n_true=self.n,
            mask_self=mask_self,
            variant=variant,
            use_pallas=self._use_ring_pallas(k),
        )
        return (
            _fetch(vals).astype(np.float64)[: self.n],
            _fetch(idxs).astype(np.int64)[: self.n],
        )

    def _use_ring_pallas(self, k: int) -> bool:
        from ..parallel.sharded import resolve_ring_kernel

        return resolve_ring_kernel(self.n, self._coo_shape[1], k)

    def _ring_run_config(self, k: int, variant: str,
                         use_pallas: bool) -> dict:
        """Checkpoint identity for the stepwise ring: graph fingerprint
        + mesh size (row-block boundaries!) + k + variant + compute
        path. A directory from a different mesh, graph, or fold path
        must fail loudly, not resume."""
        return {
            "n": int(self.n),
            "v": int(self._coo_shape[1]),
            "nnz": self._coo_nnz,
            "digest": self._coo_digest,
            "n_devices": int(self.mesh.shape["dp"]),
            "k": int(k),
            "metapath": self.metapath.name,
            "variant": variant,
            "dtype": str(self._np_dtype),  # resume must keep numerics
            "compute_path": "ring-pallas" if use_pallas else "ring-fold",
            "format": "ring-topk-v1",
        }

    def topk_scores(self, k: int = 10, variant: str = "rowsum",
                    checkpoint_dir: str | None = None,
                    use_pallas: bool | None = None,
                    checkpoint_every_steps: int = 1):
        """Ring top-k with mid-ring checkpoint/resume — the sharded
        tier's analog of jax-sparse's resumable streaming pass (and the
        reference's append-mode partial results, SURVEY.md §5, at mesh
        scale). One ring step per dispatch; the [N, k] running bests
        checkpoint every ``checkpoint_every_steps`` steps. Results are
        identical to :meth:`topk` at any kill/resume point (same fold,
        same tie-breaks). driver.rank_all routes its ``checkpoint_dir``
        here."""
        from ..parallel.sharded import sharded_topk_stepwise

        if checkpoint_dir is None and use_pallas is None:
            # no resume requested: the fused single-dispatch ring is
            # strictly better (no per-step host round-trips)
            return self.topk(k=k, variant=variant)
        if use_pallas is None:
            use_pallas = self._use_ring_pallas(k)
        ckpt = None
        if checkpoint_dir is not None:
            from ..utils.checkpoint import CheckpointManager

            ckpt = CheckpointManager(
                checkpoint_dir,
                config=self._ring_run_config(k, variant, use_pallas),
            )
        vals, idxs = sharded_topk_stepwise(
            self._first, (), mesh=self.mesh, k=k, n_true=self.n,
            variant=variant, use_pallas=use_pallas, ckpt=ckpt,
            every=checkpoint_every_steps,
        )
        return (
            _fetch(vals).astype(np.float64)[: self.n],
            _fetch(idxs).astype(np.int64)[: self.n],
        )
