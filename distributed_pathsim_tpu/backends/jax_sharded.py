"""Multi-device sharded backend (BASELINE.json config 3).

Routes the symmetric half-chain through parallel/sharded.py on a 1-D
``dp`` mesh: rows of the commuting matrix are computed where their slice
of the first adjacency block lives; the only collectives are one ``psum``
(column totals for row sums) and either one ``all_gather`` or a
``ppermute`` ring for the all-pairs product. Works identically on 8
virtual CPU devices (tests) and real TPU slices — same program, same
collectives, different mesh.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ops import chain
from ..parallel.mesh import make_mesh
from ..parallel.sharded import (
    replicate,
    shard_first_block_rows,
    sharded_chain_outputs,
)
from .base import PathSimBackend, register_backend


@register_backend("jax-sharded")
class JaxShardedBackend(PathSimBackend):
    def __init__(
        self,
        hin,
        metapath,
        n_devices: int | None = None,
        allpairs_strategy: str = "allgather",
        dtype=jnp.float32,
        **options,
    ):
        super().__init__(hin, metapath, **options)
        if not metapath.is_symmetric:
            raise ValueError(
                "jax-sharded requires a symmetric metapath (M = C Cᵀ); "
                "use the dense backend for asymmetric chains"
            )
        self.mesh = make_mesh(n_devices)
        self.allpairs_strategy = allpairs_strategy
        self.n = hin.type_size(metapath.source_type)

        host_blocks = chain.oriented_dense_blocks(
            hin, metapath.half(), dtype=np.float32
        )
        self._first = shard_first_block_rows(
            host_blocks[0].astype(np.dtype(dtype)), self.mesh
        )
        self._rest = [
            replicate(b.astype(np.dtype(dtype)), self.mesh) for b in host_blocks[1:]
        ]
        self._m: np.ndarray | None = None
        self._rowsums: np.ndarray | None = None

    def _compute(self, want_m: bool):
        if self._rowsums is None or (want_m and self._m is None):
            m, rowsums = sharded_chain_outputs(
                self._first,
                tuple(self._rest),
                mesh=self.mesh,
                allpairs_strategy=self.allpairs_strategy,
                want_m=want_m,
            )
            self._rowsums = np.asarray(rowsums, dtype=np.float64)[: self.n]
            if want_m:
                self._m = np.asarray(m, dtype=np.float64)[: self.n, : self.n]

    def global_walks(self) -> np.ndarray:
        self._compute(want_m=False)
        return self._rowsums

    def commuting_matrix(self) -> np.ndarray:
        self._compute(want_m=True)
        return self._m

    def pairwise_row(self, source_index: int) -> np.ndarray:
        return self.commuting_matrix()[source_index]
