"""Multi-device sharded backend (BASELINE.json config 3).

Routes the symmetric half-chain through parallel/sharded.py on a 1-D
``dp`` mesh: the half-chain factor C (host-folded from COO, [N, V]) is
row-sharded so each device owns the rows of M it will compute; the only
collectives are one ``psum`` (column totals for row sums) and either one
``all_gather`` or a ``ppermute`` ring for the all-pairs product /
distributed top-k. Works identically on 8 virtual CPU devices (tests)
and real TPU slices — same program, same collectives, different mesh.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ops import chain
from ..ops import sparse as sp
from ..parallel.mesh import make_mesh
from ..parallel.sharded import (
    shard_first_block_rows,
    sharded_chain_outputs,
    sharded_topk,
)
from .base import PathSimBackend, register_backend


@register_backend("jax-sharded")
class JaxShardedBackend(PathSimBackend):
    def __init__(
        self,
        hin,
        metapath,
        n_devices: int | None = None,
        allpairs_strategy: str = "allgather",
        dtype=jnp.float32,
        **options,
    ):
        super().__init__(hin, metapath, **options)
        if not metapath.is_symmetric:
            raise ValueError(
                "jax-sharded requires a symmetric metapath (M = C Cᵀ); "
                "use the dense backend for asymmetric chains"
            )
        self.mesh = make_mesh(n_devices)
        self.allpairs_strategy = allpairs_strategy
        self.n = hin.type_size(metapath.source_type)

        # Sparse-first: fold the half-chain to COO on host and densify
        # only the [N, V] factor C — V (the contracted width, e.g.
        # #venues) is orders of magnitude smaller than the N×P adjacency
        # this used to shard, so host memory and host→device transfer
        # drop accordingly. The sharded program then starts at C (empty
        # ``rest``): same collectives, far less data.
        coo = sp.half_chain_coo(hin, metapath)
        c_host = np.zeros(coo.shape, dtype=np.float64)
        np.add.at(c_host, (coo.rows, coo.cols), coo.weights)
        self._check_exact(c_host, dtype)
        self._first = shard_first_block_rows(
            c_host.astype(np.dtype(dtype)), self.mesh
        )
        self._m: np.ndarray | None = None
        self._rowsums: np.ndarray | None = None

    @staticmethod
    def _check_exact(c_host: np.ndarray, dtype) -> None:
        """Exact per-row overflow check — C entries are multiplicities,
        so no cheap bound on the rowsums exists. O(N·V), trivial next to
        the assembly just done. Shared guard handles the
        effective-device-dtype subtlety (f64 without x64 is still f32)."""
        if chain.effective_device_dtype(dtype) != np.float32:
            return
        rs = c_host @ c_host.sum(axis=0)
        chain.check_exact_counts(rs.max(initial=0.0), dtype)

    def _compute(self, want_m: bool):
        if self._rowsums is None or (want_m and self._m is None):
            # rest=() — this backend always starts from the fully folded C
            m, rowsums = sharded_chain_outputs(
                self._first,
                (),
                mesh=self.mesh,
                allpairs_strategy=self.allpairs_strategy,
                want_m=want_m,
            )
            self._rowsums = np.asarray(rowsums, dtype=np.float64)[: self.n]
            if want_m:
                self._m = np.asarray(m, dtype=np.float64)[: self.n, : self.n]

    def global_walks(self) -> np.ndarray:
        self._compute(want_m=False)
        return self._rowsums

    def commuting_matrix(self) -> np.ndarray:
        self._compute(want_m=True)
        return self._m

    def pairwise_row(self, source_index: int) -> np.ndarray:
        return self.commuting_matrix()[source_index]

    def topk(self, k: int = 10, mask_self: bool = True):
        """Distributed per-row top-k via the ppermute ring: no device
        ever holds more than an [n_loc, n_loc] score tile, and only
        [N, k] winners come back to the host."""
        vals, idxs = sharded_topk(
            self._first,
            (),
            mesh=self.mesh,
            k=k,
            n_true=self.n,
            mask_self=mask_self,
        )
        return (
            np.asarray(vals, dtype=np.float64)[: self.n],
            np.asarray(idxs, dtype=np.int64)[: self.n],
        )
