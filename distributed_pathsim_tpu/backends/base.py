"""Backend interface and registry.

A backend binds an :class:`EncodedHIN` + compiled :class:`MetaPath` and
serves the two primitives the reference's algorithm layer is built from
(``DPathSim_APVPA.py:70-109``), batched:

- ``global_walks()`` — the "global walk" count for EVERY source node at
  once (row sums of the commuting matrix M; the reference runs one
  distributed join per node for this)
- ``pairwise_row(s)`` — ``M[s, :]``, the "pairwise walk" count from source
  ``s`` to EVERY target at once (the reference runs one join per pair)

plus all-pairs conveniences. The ``backend=`` flag of BASELINE.json routes
through :func:`get_backend` / :func:`create_backend`.
"""

from __future__ import annotations

import abc
import time
from typing import Any, Callable

import numpy as np

from ..data.encode import EncodedHIN
from ..ops import planner
from ..ops.metapath import MetaPath
from ..ops import pathsim


class DeltaUnsupported(RuntimeError):
    """The backend has no incremental-update path for this chain shape
    (e.g. an asymmetric metapath: no half factor to patch). Callers fall
    back to a full rebuild — a capability miss, never a correctness
    failure."""


class PathSimBackend(abc.ABC):
    """Common surface for all execution backends.

    **Capacity invariant**: the bound HIN may reserve index headroom
    (data/delta.py) — adjacency blocks then have padded shapes and the
    backend's device/host arrays are built at capacity. Every
    host-visible result (rows, sums, matrices, scores) is trimmed to
    the LOGICAL size ``n_sources``, so padding is invisible to callers
    and results are bit-identical to an unpadded build (padded factor
    rows hold no edges, so they contribute zero counts everywhere).
    """

    name: str = "abstract"

    def __init__(self, hin: EncodedHIN, metapath: MetaPath, **options: Any):
        self.hin = hin
        self.metapath = metapath
        self.options = options
        # The chain is data: every backend executes an EvalPlan —
        # DP-ordered association over the (half-)chain with estimated
        # FLOPs/density on every node (ops/planner.py, DESIGN.md §28).
        # Plan construction is memoized per (HIN, metapath), so N
        # backends over one graph share one stats scan.
        self.plan = planner.plan_metapath(hin, metapath)
        # Workload-level sub-chain memo (serving passes the shared
        # SubchainCache so concurrent metapath engines share folds).
        self._subchain_memo = options.get("subchain_memo")

    def describe_plan(self) -> dict:
        """The auditable plan dump: association order + per-node cost
        estimates (the ``stats()``/bench surface of plan choices)."""
        return self.plan.to_dict()

    def factor_info(self) -> dict | None:
        """Resident factor accounting for the memory-headroom surface
        (``stats()["factor"]`` + the ``dpathsim_factor_bytes`` gauge):
        ``{"format", "bytes", "nnz", "coo_bytes"}``, or None for
        backends with no resident sparse factor. ``coo_bytes`` is the
        24-byte/nnz uncompressed equivalent, so the reduction ratio is
        readable straight off the stats block."""
        return None

    @property
    def n_sources(self) -> int:
        """Logical source-node count (never the padded capacity).
        Read dynamically: a delta update can append nodes."""
        return self.hin.type_size(self.metapath.source_type)

    @property
    def n_targets(self) -> int:
        """Logical target-node count (== n_sources for symmetric
        chains; the column-axis trim for asymmetric ones)."""
        return self.hin.type_size(self.metapath.target_type)

    # -- primitives (each backend implements) -----------------------------

    @abc.abstractmethod
    def global_walks(self) -> np.ndarray:
        """Row sums of M for every source node: float[N], integer-valued."""

    @abc.abstractmethod
    def pairwise_row(self, source_index: int) -> np.ndarray:
        """M[source, :]: float[N], integer-valued."""

    @abc.abstractmethod
    def commuting_matrix(self) -> np.ndarray:
        """The full M (dense). Backends for huge graphs may refuse."""

    # -- derived ----------------------------------------------------------

    def diagonal(self) -> np.ndarray:
        return np.diagonal(self.commuting_matrix()).copy()

    def _denominators(self, variant: str) -> np.ndarray:
        if variant == "rowsum":
            return self.global_walks()
        if variant == "diagonal":
            return self.diagonal()
        raise ValueError(f"unknown variant {variant!r}")

    def scores_from_source(
        self, source_index: int, variant: str = "rowsum"
    ) -> np.ndarray:
        # Counts are exact integers whatever the carry dtype (guarded ≤
        # 2^24 for f32); normalizing in f64 on host makes the scores
        # carry-dtype-independent.
        d = np.asarray(self._denominators(variant), dtype=np.float64)
        row = np.asarray(self.pairwise_row(source_index), dtype=np.float64)
        return pathsim.score_row(row, d[source_index], d, xp=np)

    # -- batched multi-row path (serving layer) ----------------------------
    #
    # The serving coalescer pads concurrent single-source queries into
    # power-of-two shape buckets and dispatches them here. The contract:
    # every row of a batched result is bit-identical to the unbatched
    # call for that row. That holds because (a) path counts are exact
    # integers under each backend's dtype guard, so any summation order
    # yields the same numbers, and (b) normalization + top-k selection
    # run through the same f64 host code either way.

    def pairwise_rows(self, rows) -> np.ndarray:
        """M[rows, :] stacked: float[B, N], integer-valued. Backends
        override with one batched dispatch; the fallback loops."""
        return np.stack(
            [
                np.asarray(self.pairwise_row(int(r)), dtype=np.float64)
                for r in np.asarray(rows, dtype=np.int64)
            ]
        )

    def scores_rows(self, rows, variant: str = "rowsum") -> np.ndarray:
        """Score rows for a batch of sources: f64 [B, N]."""
        rows = np.asarray(rows, dtype=np.int64)
        d = np.asarray(self._denominators(variant), dtype=np.float64)
        m = np.asarray(self.pairwise_rows(rows), dtype=np.float64)
        return pathsim.score_rows(m, d[rows], d, xp=np)

    def topk_rows(self, rows, k: int = 10, variant: str = "rowsum"):
        """Batched per-source top-k: (values f64 [B, k], indices int64
        [B, k]), self pairs excluded, ordered (descending score,
        ascending column) — the oracle tie order. ``k`` is clamped to
        N−1 (a self pair can never rank)."""
        rows = np.asarray(rows, dtype=np.int64)
        scores = self.scores_rows(rows, variant=variant)
        scores[np.arange(rows.shape[0]), rows] = -np.inf
        return pathsim.topk_from_score_rows(
            scores, min(k, max(scores.shape[1] - 1, 1))
        )

    def topk_row(self, row: int, k: int = 10, variant: str = "rowsum"):
        """Single-source top-k — the B=1 case of :meth:`topk_rows`
        (identical code path, so batched vs unbatched can never
        diverge)."""
        vals, idxs = self.topk_rows(
            np.asarray([row], dtype=np.int64), k=k, variant=variant
        )
        return vals[0], idxs[0]

    def all_pairs_scores(self, variant: str = "rowsum") -> np.ndarray:
        m = np.asarray(self.commuting_matrix(), dtype=np.float64)
        rowsums = (
            np.asarray(self.global_walks(), dtype=np.float64)
            if variant == "rowsum"
            else None
        )
        return pathsim.score_matrix(m, rowsums=rowsums, variant=variant, xp=np)

    # -- incremental updates (delta-ingestion engine, data/delta.py) -------

    def apply_delta(self, plan) -> None:
        """Absorb one :class:`~..data.delta.DeltaPlan` in place: patch
        the half factor, denominators, and derived caches from the
        plan's signed ΔC instead of rebuilding — O(Δ + affected rows),
        zero new XLA compiles in steady state (every patched array keeps
        its shape; that's what the capacity headroom buys).

        Raises :class:`DeltaUnsupported` when this backend/chain has no
        patch path; the caller (PathSimService.update) falls back to a
        full rebuild. A ``fallback`` plan is a caller bug — the plan
        already decided this delta must rebuild."""
        if plan.fallback:
            raise ValueError(
                f"plan requires full rebuild ({plan.reason}); "
                "apply_delta must not be called with a fallback plan"
            )
        impl = getattr(self, "_apply_delta_impl", None)
        if impl is None:
            raise DeltaUnsupported(
                f"backend {self.name!r} has no incremental update path"
            )
        impl(plan)
        self.hin = plan.hin_new


_REGISTRY: dict[str, Callable[..., PathSimBackend]] = {}


def register_backend(name: str):
    def deco(cls):
        cls.name = name
        _REGISTRY[name] = cls
        return cls

    return deco


def get_backend(name: str) -> Callable[..., PathSimBackend]:
    # Import side-effect registration for the built-ins on first use.
    from . import numpy_backend, jax_dense, jax_sharded, jax_sparse  # noqa: F401

    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown backend {name!r}; available: {sorted(_REGISTRY)}"
        ) from None


def available_backends() -> list[str]:
    from . import numpy_backend, jax_dense, jax_sharded, jax_sparse  # noqa: F401

    return sorted(_REGISTRY)


def create_backend(
    name: str, hin: EncodedHIN, metapath: MetaPath, **options: Any
) -> PathSimBackend:
    """Construct a backend, visible to obs: init count + duration land
    in the registry (a serving process rebuilding backends at delta-
    fallback rate shows up as a moving ``backend_inits`` line), and the
    init runs inside a ``backend.init`` span so bootstrap traces show
    where the half-chain fold / device transfer time went."""
    from ..obs.metrics import get_registry
    from ..obs.trace import get_tracer

    t0 = time.perf_counter()
    with get_tracer().span("backend.init", backend=name):
        backend = get_backend(name)(hin, metapath, **options)
    reg = get_registry()
    reg.counter(
        "dpathsim_backend_inits_total", "backend constructions by name"
    ).inc(backend=name)
    reg.histogram(
        "dpathsim_backend_init_seconds", "backend construction duration"
    ).observe(time.perf_counter() - t0, backend=name)
    return backend
