"""Compressed sparse factor layouts: blocked-CSR and bit-packed chunks.

The resident COO half-chain factor is 24 bytes/nnz (int64 rows + int64
cols + f64 weights) — the fleet's scale ceiling (~14 GB host RSS at
4.19M authors, SCALE_4M_r03.json; in partition mode it divides straight
into each worker's budget). Both compression papers in PAPERS.md
(arXiv 2409.02208, arXiv 1708.07271) land the same move: *reorder*,
then store narrow. This module implements it as first-class factor
representations behind one sanctioned factory:

- ``blocked``: row-chunked CSR. Per chunk of ``chunk_rows`` rows:
  a per-row count table, column ids in the hub-first PERMUTED column
  space (data/compress.py) as the narrowest uint that fits the chunk's
  actual index range, weights as the narrowest uint that fits the
  chunk's actual count range (f64 fallback for non-integer data —
  loud, never lossy). Typically 3-6 bytes/nnz.
- ``bitpacked``: ``blocked`` plus bit-level column packing: within
  each chunk, rows are laid out hub-first and their permuted column
  ids delta-encoded (first column absolute, then gap−1), then packed
  into fixed-width blocks of ``_BLOCK_NNZ`` values — each block
  stores its own bit width, so hub blocks (dense rows, tiny gaps)
  pack at 2-5 bits/value while tail blocks pay only for themselves.
  Typically 1.5-3 bytes/nnz.

**Hard contracts.** (1) Bit parity: every accessor returns ORIGINAL
ids and exact f64 integer weights — ``as_coo(make_factor(c, fmt))``
is the canonical (row-major sorted, coalesced, zero-free) form of
``c``, so counts, f64 scores, and top-k tie order downstream are
bit-identical to the COO path by construction; the permutations of
data/compress.py never escape this module. (2) Recompile/realloc
stability: every chunk buffer is allocated at a pow-2 capacity bucket
(floor ``_PACK_BUCKET_FLOOR``), so a delta patch that drifts a chunk's
nnz inside its bucket rewrites in place-sized arrays — resident bytes
and downstream scatter-pad buckets stay put, which is what keeps the
delta path recompile-free. (3) O(Δ) patches: ``patch_factor``
re-encodes only the chunks a delta touches (the same row-granular
contract ``ops.sparse.coo_apply_delta`` has).

**Boundary (CF001).** The chunk internals below are the compressed
layout's private coordinate system. The ONLY sanctioned surface is
``SANCTIONED_FACTORY``; the analyzer pass (analysis/compress_rules.py)
parses ``PACKED_SURFACE``/``SANCTIONED_FACTORY`` out of this module
and asserts no call chain from outside the factor modules reaches the
constructors/accessors except through it — a module that reads
``.chunks`` directly would be reading permuted-space ids as if they
were global columns, which is exactly the silent corruption the
boundary exists to prevent.
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from ..data.compress import PermutationPair, degree_order
from . import sparse as sp

# Rows per chunk: the re-encode granularity of a delta patch and the
# natural alignment of the jax-sparse tile extraction (backends pass
# their tile_rows). A layout invariant, not a measured perf knob — the
# measured knob is `factor_format` in tuning/registry.py.
_PACK_CHUNK_ROWS = 4096
# Values per fixed-width bit-packing block (bitpacked format): each
# block stores its own bit width, so this is the granularity of the
# width adaptation. Layout invariant (sanctioned in the registry).
_BLOCK_NNZ = 256
# Pow-2 capacity bucket floor for chunk buffers: allocations never
# shrink below this, so tiny chunks don't fragment and delta-drifted
# nnz stays inside one bucket (sanctioned in the registry).
_PACK_BUCKET_FLOOR = 64

FACTOR_FORMATS = ("coo", "blocked", "bitpacked")

# Attribute surface of the packed representation (analysis/CF001,
# registry style mirrors FACTOR_SURFACE/PROTOCOL_OPS): reading these
# outside the factor modules means consuming permuted-space layout
# internals as if they were graph data.
PACKED_SURFACE = frozenset({"chunks", "row_counts", "block_bits", "col_perm"})

# The sanctioned doorway (analysis/CF001): every function name here is
# a public factory/accessor whose outputs speak ORIGINAL ids; the
# reachability pass cuts call edges into these, so "reaches a packed
# constructor/accessor" means "reaches it around the factory".
SANCTIONED_FACTORY = frozenset({
    "make_factor", "as_coo", "row_slice", "row_range_nnz",
    "gather_rows_dense", "factor_colsum", "factor_rowsums_weighted",
    "factor_diag", "factor_bytes", "factor_nnz", "patch_factor",
    "packed_matmul", "fold_half", "is_packed", "is_canonical",
    "content_digest",
})


def _bits_needed(v: np.ndarray) -> np.ndarray:
    """Bits to represent each value (min 1 — a zero still occupies a
    slot in its block)."""
    v = np.asarray(v, dtype=np.uint64)
    out = np.ones(v.shape, dtype=np.uint8)
    nz = v > 0
    if nz.any():
        out[nz] = np.floor(np.log2(v[nz].astype(np.float64))).astype(
            np.uint8
        ) + 1
    return out


def _bucket_capacity(n: int) -> int:
    """Pow-2 capacity bucket ≥ n (floored): the realloc-stability
    contract of chunk buffers."""
    n = max(int(n), _PACK_BUCKET_FLOOR)
    return 1 << (n - 1).bit_length()


def _narrow_uint_dtype(max_value: int):
    for dt in (np.uint8, np.uint16, np.uint32, np.uint64):
        if max_value <= np.iinfo(dt).max:
            return np.dtype(dt)
    raise OverflowError(f"value {max_value} exceeds uint64")


def _at_capacity(arr: np.ndarray, nnz_like: int) -> np.ndarray:
    """Copy into a pow-2-capacity buffer (live region [:len(arr)])."""
    cap = _bucket_capacity(nnz_like)
    out = np.zeros(cap, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


def _weights_narrow(w: np.ndarray) -> tuple[np.ndarray, bool]:
    """Narrowest uint storage for integer-count weights; f64 fallback
    (flagged) for anything that isn't a positive integer < 2^53 — a
    fallback is lossless, a wrap would be silent corruption, so the
    dtype is always chosen from the ACTUAL value range."""
    w = np.asarray(w, dtype=np.float64)
    if w.shape[0] == 0:
        return w.astype(np.uint8), False
    wmax = float(w.max(initial=0.0))
    integral = bool(
        (w > 0).all() and (w == np.floor(w)).all() and wmax < 2.0**53
    )
    if not integral:
        return w.copy(), True
    return w.astype(_narrow_uint_dtype(int(wmax))), False


@dataclasses.dataclass(frozen=True)
class _Chunk:
    """One ``chunk_rows``-row span of the factor, encoded.

    Entries live in chunk-local LAYOUT order: rows hub-first
    (descending count, ascending local row — re-derivable from
    ``row_counts``, so the order costs no storage), columns ascending
    in PERMUTED space within each row. ``weights``/``cols``/``bits``
    buffers are pow-2-capacity allocations; the live region is
    ``[:nnz]`` (resp. the encoded bit length).
    """

    row0: int
    n_rows: int
    nnz: int
    row_counts: np.ndarray          # uint32 [n_rows], ORIGINAL row order
    weights: np.ndarray             # layout order; narrow uint or f64
    cols: np.ndarray | None         # blocked: permuted cols, layout order
    bits: np.ndarray | None         # bitpacked: uint8 bit stream
    block_bits: np.ndarray | None   # bitpacked: uint8 width per block

    def nbytes(self) -> int:
        total = self.row_counts.nbytes + self.weights.nbytes
        if self.cols is not None:
            total += self.cols.nbytes
        if self.bits is not None:
            total += self.bits.nbytes + self.block_bits.nbytes
        return total


def _layout_order(row_counts: np.ndarray) -> np.ndarray:
    """Hub-first row layout of one chunk: local rows sorted by
    (descending count, ascending local row). Deterministic, derived —
    encode and decode can never disagree."""
    n = row_counts.shape[0]
    return np.lexsort(
        (np.arange(n), -row_counts.astype(np.int64))
    )


def _pack_bit_blocks(vals: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Pack uint values into fixed-width blocks of ``_BLOCK_NNZ``:
    each block's width adapts to its own max value. Returns
    (uint8 bit stream, uint8 width-per-block)."""
    nnz = vals.shape[0]
    if nnz == 0:
        return np.zeros(0, dtype=np.uint8), np.zeros(0, dtype=np.uint8)
    vals = vals.astype(np.uint64)
    nb = -(-nnz // _BLOCK_NNZ)
    widths = np.empty(nb, dtype=np.uint8)
    pieces: list[np.ndarray] = []
    for b in range(nb):
        blk = vals[b * _BLOCK_NNZ : (b + 1) * _BLOCK_NNZ]
        w = int(_bits_needed(np.asarray([blk.max()]))[0])
        widths[b] = w
        shifts = np.arange(w - 1, -1, -1, dtype=np.uint64)
        pieces.append(
            ((blk[:, None] >> shifts[None, :]) & 1).astype(np.uint8).ravel()
        )
    stream = np.packbits(np.concatenate(pieces))
    return stream, widths


def _unpack_bit_blocks(
    stream: np.ndarray, widths: np.ndarray, nnz: int
) -> np.ndarray:
    """Inverse of :func:`_pack_bit_blocks` → uint64 [nnz]."""
    if nnz == 0:
        return np.zeros(0, dtype=np.uint64)
    sizes = np.full(widths.shape[0], _BLOCK_NNZ, dtype=np.int64)
    sizes[-1] = nnz - _BLOCK_NNZ * (widths.shape[0] - 1)
    total_bits = int((sizes * widths.astype(np.int64)).sum())
    flat = np.unpackbits(stream, count=total_bits).astype(np.uint64)
    out = np.empty(nnz, dtype=np.uint64)
    bit_at = 0
    val_at = 0
    for b in range(widths.shape[0]):
        w = int(widths[b])
        n = int(sizes[b])
        block = flat[bit_at : bit_at + n * w].reshape(n, w)
        powers = (np.uint64(1) << np.arange(
            w - 1, -1, -1, dtype=np.uint64
        ))
        out[val_at : val_at + n] = block @ powers
        bit_at += n * w
        val_at += n
    return out


def _pack_chunk(
    fmt: str,
    row0: int,
    n_rows: int,
    rows_local: np.ndarray,
    pcols: np.ndarray,
    weights: np.ndarray,
) -> _Chunk:
    """Encode one chunk from its (local row, permuted col, f64 weight)
    triples (any input order; duplicates must already be coalesced)."""
    row_counts = np.bincount(
        rows_local, minlength=n_rows
    ).astype(np.uint32)
    nnz = int(rows_local.shape[0])
    order_rows = _layout_order(row_counts)
    rank = np.empty(n_rows, dtype=np.int64)
    rank[order_rows] = np.arange(n_rows)
    order = np.lexsort((pcols, rank[rows_local]))
    pcols_l = pcols[order].astype(np.uint64)
    w_narrow, f64_fallback = _weights_narrow(weights[order])
    if f64_fallback:
        # lossless but 8 B/nnz instead of 1-2: an operator watching
        # dpathsim_factor_bytes deserves a signal explaining why
        # compression degraded, not just a bigger number
        _record_f64_fallback(fmt)
    w_cap = _at_capacity(w_narrow, nnz)
    if fmt == "blocked":
        cmax = int(pcols_l.max(initial=0))
        cols = _at_capacity(
            pcols_l.astype(_narrow_uint_dtype(cmax)), nnz
        )
        return _Chunk(
            row0=row0, n_rows=n_rows, nnz=nnz, row_counts=row_counts,
            weights=w_cap, cols=cols, bits=None, block_bits=None,
        )
    # bitpacked: delta-encode within rows (layout order): the first
    # column of a row is absolute, later ones store gap−1 (columns are
    # strictly ascending in permuted space after coalescing).
    counts_layout = row_counts[order_rows].astype(np.int64)
    starts = np.concatenate([[0], np.cumsum(counts_layout)])[:-1]
    first = np.zeros(nnz, dtype=bool)
    first[starts[counts_layout > 0]] = True
    vals = pcols_l.copy()
    nf = ~first
    if nf.any():
        vals[nf] = pcols_l[nf] - pcols_l[np.flatnonzero(nf) - 1] - 1
    stream, widths = _pack_bit_blocks(vals)
    return _Chunk(
        row0=row0, n_rows=n_rows, nnz=nnz, row_counts=row_counts,
        weights=w_cap, cols=None,
        bits=_at_capacity(stream, stream.shape[0]),
        block_bits=widths,
    )


def _decode_chunk(
    f: "PackedFactor", ch: _Chunk
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One chunk → (global rows int64, ORIGINAL cols int64, f64
    weights), row-major sorted with ascending original columns within
    each row — i.e. already in canonical COO order for its row span."""
    nnz = ch.nnz
    if nnz == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z.copy(), np.zeros(0, dtype=np.float64)
    order_rows = _layout_order(ch.row_counts)
    counts_layout = ch.row_counts[order_rows].astype(np.int64)
    rows_layout = np.repeat(order_rows, counts_layout)
    if ch.cols is not None:
        pcols = ch.cols[:nnz].astype(np.int64)
    else:
        vals = _unpack_bit_blocks(
            ch.bits, ch.block_bits, nnz
        ).astype(np.int64)
        # Invert the per-row delta encoding with one segmented cumsum:
        # adj = head value at each row start, gap+1 elsewhere, so the
        # running sum minus the sum BEFORE the segment is exactly the
        # reconstructed permuted column.
        starts = np.concatenate([[0], np.cumsum(counts_layout)])[:-1]
        live = counts_layout > 0
        adj = vals + 1
        adj[starts[live]] = vals[starts[live]]
        csum = np.cumsum(adj)
        seg_base = np.concatenate([[0], csum])[starts[live]]
        pcols = csum - np.repeat(seg_base, counts_layout[live])
    cols = f.col_perm.invert(pcols)
    # canonical order: (local row, ORIGINAL col) ascending — the
    # layout's permuted-space order is an encoding detail and must not
    # leak into the boundary.
    order = np.lexsort((cols, rows_layout))
    rows = rows_layout[order] + ch.row0
    return (
        rows.astype(np.int64),
        cols[order].astype(np.int64),
        ch.weights[:nnz].astype(np.float64)[order],
    )


@dataclasses.dataclass(frozen=True)
class PackedFactor:
    """A compressed resident factor: chunked, permuted, narrow.

    Construct ONLY through :func:`make_factor`; read ONLY through the
    ``SANCTIONED_FACTORY`` accessors (analysis/CF001). ``colsum`` is
    the exact f64 column-total vector in ORIGINAL column space — kept
    here because every consumer needs it and recomputing it would
    force a full decode."""

    fmt: str
    shape: tuple[int, int]
    nnz: int
    chunk_rows: int
    chunks: tuple[_Chunk, ...]
    col_perm: PermutationPair
    colsum: np.ndarray
    perm_bytes: int = 0  # 0 for identity; fixed at construction
    promotions: int = 0

    def nbytes(self) -> int:
        return int(
            sum(ch.nbytes() for ch in self.chunks)
            + self.colsum.nbytes
            + self.perm_bytes
        )


def _canonical_coo(c: sp.COOMatrix) -> sp.COOMatrix:
    """Row-major sorted, coalesced, zero-free — the canonical form a
    pack/unpack round trip reproduces. Already-canonical inputs (the
    common case: ``_matmul_summed`` output) pass through untouched."""
    if is_canonical(c):
        return c
    return sp.coo_nonzero(c.summed())


def is_packed(x) -> bool:
    return isinstance(x, PackedFactor)


def is_canonical(c) -> bool:
    """True when a COO factor is already row-major sorted, coalesced,
    and zero-free — i.e. a pack/unpack round trip reproduces it
    entry-for-entry IN ORDER, not just in content. Callers that must
    hand back byte-identical arrays (the sub-chain memo) pack only
    canonical entries; packed factors are canonical by construction."""
    if is_packed(c):
        return True
    key = c.rows.astype(np.int64) * c.shape[1] + c.cols.astype(np.int64)
    return bool(
        c.rows.shape[0] == 0
        or (np.diff(key) > 0).all() and (c.weights != 0.0).all()
    )


def make_factor(
    c: sp.COOMatrix,
    fmt: str,
    chunk_rows: int | None = None,
    permute: bool = True,
):
    """The sanctioned factory: a COO factor → its resident
    representation for ``fmt``. ``"coo"`` returns the input unchanged
    (the zero-cost arm every consumer already speaks); packed formats
    canonicalize, compute the hub-first column permutation
    (data/compress.py), and encode per chunk. ``chunk_rows`` should
    match the consumer's row-tile granularity (the jax-sparse backend
    passes its tile width) so tile extraction decodes exactly the
    chunks it needs."""
    if fmt not in FACTOR_FORMATS:
        raise ValueError(
            f"unknown factor format {fmt!r}; choose from {FACTOR_FORMATS}"
        )
    if fmt == "coo":
        return c
    if is_packed(c):
        raise TypeError("make_factor takes a COO factor, not a packed one")
    cc = _canonical_coo(c)
    chunk_rows = int(chunk_rows or _PACK_CHUNK_ROWS)
    if chunk_rows < 1:
        raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
    rows = cc.rows.astype(np.int64)
    cols = cc.cols.astype(np.int64)
    w = cc.weights.astype(np.float64)
    if permute:
        # only the COLUMN marginal is needed here — the row layout
        # order is chunk-local and derived from the count tables, so
        # computing (and discarding) a full row permutation would
        # waste an O(N log N) sort and N-sized transients per pack
        col_pair = PermutationPair.from_perm(
            degree_order(np.bincount(cols, minlength=int(cc.shape[1])))
        )
    else:
        col_pair = PermutationPair.identity(cc.shape[1])
    if cc.shape[1] < np.iinfo(np.int32).max:
        # the stored permutation is part of the resident footprint
        # (nbytes counts it honestly) — at wide V (APA: V = #papers)
        # int32 halves that cost
        col_pair = PermutationPair(
            perm=col_pair.perm.astype(np.int32),
            inv=col_pair.inv.astype(np.int32),
        )
    pcols_all = col_pair.apply(cols)
    n_chunks = max(1, -(-cc.shape[0] // chunk_rows))
    bounds = np.arange(n_chunks + 1) * chunk_rows
    starts = np.searchsorted(rows, bounds[:-1], side="left")
    stops = np.searchsorted(rows, bounds[1:], side="left")
    chunks = []
    for i in range(n_chunks):
        r0 = i * chunk_rows
        nr = min(chunk_rows, cc.shape[0] - r0)
        s, e = int(starts[i]), int(stops[i])
        chunks.append(_pack_chunk(
            fmt, r0, nr, rows[s:e] - r0, pcols_all[s:e], w[s:e],
        ))
    colsum = np.zeros(cc.shape[1], dtype=np.float64)
    if rows.shape[0]:
        np.add.at(colsum, cols, w)
    return PackedFactor(
        fmt=fmt, shape=cc.shape, nnz=int(rows.shape[0]),
        chunk_rows=chunk_rows, chunks=tuple(chunks), col_perm=col_pair,
        colsum=colsum,
        perm_bytes=(
            0 if not permute
            else int(col_pair.perm.nbytes + col_pair.inv.nbytes)
        ),
    )


def as_coo(f) -> sp.COOMatrix:
    """Packed → canonical COO (row-major sorted, coalesced, zero-free,
    ORIGINAL ids) — the host-boundary inverse of :func:`make_factor`.
    COO inputs pass through (so consumers can hold either)."""
    if not is_packed(f):
        return f
    parts = [_decode_chunk(f, ch) for ch in f.chunks if ch.nnz]
    if not parts:
        z = np.zeros(0, dtype=np.int64)
        return sp.COOMatrix(
            rows=z, cols=z.copy(),
            weights=np.zeros(0, dtype=np.float64), shape=f.shape,
        )
    return sp.COOMatrix(
        rows=np.concatenate([p[0] for p in parts]),
        cols=np.concatenate([p[1] for p in parts]),
        weights=np.concatenate([p[2] for p in parts]),
        shape=f.shape,
    )


def row_slice(f: PackedFactor, r0: int, r1: int) -> sp.COOMatrix:
    """Entries with row in ``[r0, r1)`` as canonical COO (global row
    ids, original cols) — decodes ONLY the chunks the span touches,
    which is the O(span-nnz) contract the tile extraction and the
    partition windows rely on."""
    r0, r1 = int(r0), int(r1)
    lo = max(0, r0 // f.chunk_rows)
    hi = min(len(f.chunks), -(-r1 // f.chunk_rows))
    rows_l, cols_l, w_l = [], [], []
    for ch in f.chunks[lo:hi]:
        if ch.nnz == 0:
            continue
        rows, cols, w = _decode_chunk(f, ch)
        if r0 > ch.row0 or r1 < ch.row0 + ch.n_rows:
            keep = (rows >= r0) & (rows < r1)
            rows, cols, w = rows[keep], cols[keep], w[keep]
        rows_l.append(rows)
        cols_l.append(cols)
        w_l.append(w)
    if not rows_l:
        z = np.zeros(0, dtype=np.int64)
        return sp.COOMatrix(
            rows=z, cols=z.copy(),
            weights=np.zeros(0, dtype=np.float64), shape=f.shape,
        )
    return sp.COOMatrix(
        rows=np.concatenate(rows_l), cols=np.concatenate(cols_l),
        weights=np.concatenate(w_l), shape=f.shape,
    )


def row_range_nnz(f: PackedFactor, r0: int, r1: int) -> int:
    """Exact nnz of rows ``[r0, r1)`` — O(span rows) from the per-row
    count tables, no decode."""
    r0, r1 = max(0, int(r0)), min(int(f.shape[0]), int(r1))
    total = 0
    lo = r0 // f.chunk_rows
    hi = -(-r1 // f.chunk_rows)
    for ch in f.chunks[lo:hi]:
        a = max(r0 - ch.row0, 0)
        b = min(r1 - ch.row0, ch.n_rows)
        if b > a:
            total += int(ch.row_counts[a:b].sum())
    return total


def gather_rows_dense(
    f: PackedFactor, rows, dtype=np.float64
) -> np.ndarray:
    """Dense [len(rows), V] gather of arbitrary factor rows in
    ORIGINAL column space — the packed analog of the rescore path's
    CSR gather. Each touched chunk decodes once per call."""
    rows = np.asarray(rows, dtype=np.int64)
    out = np.zeros((rows.shape[0], f.shape[1]), dtype=dtype)
    if rows.shape[0] == 0:
        return out
    chunk_of = rows // f.chunk_rows
    for ci in np.unique(chunk_of):
        ch = f.chunks[int(ci)]
        if ch.nnz == 0:
            continue
        crows, ccols, cw = _decode_chunk(f, ch)
        sel = np.flatnonzero(chunk_of == ci)
        # positions of each requested row's entries inside the chunk
        order = np.argsort(crows, kind="stable")
        crows_s = crows[order]
        starts = np.searchsorted(crows_s, rows[sel], side="left")
        stops = np.searchsorted(crows_s, rows[sel], side="right")
        counts = stops - starts
        total = int(counts.sum())
        if total == 0:
            continue
        ridx = np.repeat(sel, counts)
        cum = np.concatenate([[0], np.cumsum(counts)])
        flat = np.repeat(starts, counts) + (
            np.arange(total) - np.repeat(cum[:-1], counts)
        )
        out[ridx, ccols[order][flat]] = cw[order][flat]
    return out


def factor_colsum(f) -> np.ndarray:
    """Exact f64 column totals in ORIGINAL column space."""
    if is_packed(f):
        return f.colsum
    colsum = np.zeros(f.shape[1], dtype=np.float64)
    if f.rows.shape[0]:
        np.add.at(colsum, f.cols, f.weights)
    return colsum


def factor_rowsums_weighted(f, colvec: np.ndarray) -> np.ndarray:
    """``rs[i] = Σ_j w_ij · colvec[col_ij]`` in exact f64 (integer
    inputs < 2^53) — the host rowsum/denominator path, chunk-streamed
    so the transient never exceeds one chunk."""
    colvec = np.asarray(colvec, dtype=np.float64)
    if not is_packed(f):
        rs = np.zeros(f.shape[0], dtype=np.float64)
        if f.rows.shape[0]:
            np.add.at(rs, f.rows, f.weights * colvec[f.cols])
        return rs
    rs = np.zeros(f.shape[0], dtype=np.float64)
    for ch in f.chunks:
        if ch.nnz == 0:
            continue
        rows, cols, w = _decode_chunk(f, ch)
        np.add.at(rs, rows, w * colvec[cols])
    return rs


def factor_diag(f) -> np.ndarray:
    """``diag[i] = Σ_j w_ij²`` (the textbook-PathSim denominator),
    chunk-streamed."""
    if not is_packed(f):
        s = f.summed()
        d = np.zeros(f.shape[0], dtype=np.float64)
        if s.rows.shape[0]:
            np.add.at(d, s.rows, s.weights**2)
        return d
    d = np.zeros(f.shape[0], dtype=np.float64)
    for ch in f.chunks:
        if ch.nnz == 0:
            continue
        rows, _, w = _decode_chunk(f, ch)
        np.add.at(d, rows, w**2)
    return d


def factor_bytes(f) -> int:
    """Resident bytes of the factor as held (capacity buckets
    included — this is the honest number the bench and the
    ``dpathsim_factor_bytes`` gauge report)."""
    if is_packed(f):
        return f.nbytes()
    return int(f.rows.nbytes + f.cols.nbytes + f.weights.nbytes)


def factor_nnz(f) -> int:
    return int(f.nnz if is_packed(f) else f.rows.shape[0])


def content_digest(f) -> str:
    """sha256[:16] over the CANONICAL coalesced content (sorted rows/
    cols int64 + weights f64) — format-independent: a packed factor
    and its COO equivalent digest identically, so checkpoint/cache
    identity survives a format flip. Memoized per factor object."""
    if is_packed(f):
        cache = f.__dict__.get("_digest_cache")
        if cache is not None:
            return cache
        # One transient decode: the hash must consume all-rows, then
        # all-cols, then all-weights (the COO path's byte stream) so a
        # packed factor and its COO twin digest identically.
        digest = content_digest(as_coo(f))
        object.__setattr__(f, "_digest_cache", digest)
        return digest
    cc = _canonical_coo(f)
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(cc.rows, dtype=np.int64).tobytes())
    h.update(np.ascontiguousarray(cc.cols, dtype=np.int64).tobytes())
    h.update(np.ascontiguousarray(cc.weights, dtype=np.float64).tobytes())
    return h.hexdigest()[:16]


def _record_f64_fallback(fmt: str) -> None:
    from ..obs.metrics import get_registry

    get_registry().counter(
        "dpathsim_packed_f64_fallback_total",
        "packed chunks stored with f64 weights (non-integer data — "
        "lossless, but the narrow-count compression did not apply)",
    ).inc(format=fmt)


def _record_promotion(fmt: str) -> None:
    from ..obs.metrics import get_registry

    get_registry().counter(
        "dpathsim_packed_promotions_total",
        "packed-chunk weight dtype widenings (loud, never a wrap)",
    ).inc(format=fmt)


def patch_factor(f: PackedFactor, delta_c: sp.COOMatrix) -> PackedFactor:
    """Apply a signed half-chain delta (ΔC from the delta product
    rule) to a packed factor, re-encoding ONLY the chunks whose rows
    the delta touches — the packed analog of
    :func:`~.sparse.coo_apply_delta`, same row-granular O(Δ +
    touched-chunk-nnz) contract, bit-identical result content. A
    patched chunk whose counts outgrow their narrow dtype is
    re-encoded wider (counted on ``promotions`` and the
    ``dpathsim_packed_promotions_total`` metric) — promotion is loud,
    wrap-around is impossible because dtypes are always re-chosen from
    the actual post-patch values."""
    if not is_packed(f):
        raise TypeError("patch_factor patches packed factors only")
    if delta_c.rows.shape[0] == 0:
        return f
    if tuple(delta_c.shape) != tuple(f.shape):
        raise ValueError(
            f"delta shape {delta_c.shape} != factor {f.shape}"
        )
    dc = _canonical_coo(delta_c)
    drows = dc.rows.astype(np.int64)
    touched = np.unique(drows // f.chunk_rows)
    chunks = list(f.chunks)
    promotions = f.promotions
    for ci in touched:
        ch = chunks[int(ci)]
        crows, ccols, cw = _decode_chunk(f, ch)
        span = sp.COOMatrix(
            rows=crows, cols=ccols, weights=cw, shape=f.shape
        )
        mask = drows // f.chunk_rows == ci
        sub = sp.COOMatrix(
            rows=drows[mask], cols=dc.cols[mask],
            weights=dc.weights[mask], shape=f.shape,
        )
        patched = sp.coo_apply_delta(span, sub)
        patched = sp.coo_nonzero(patched.summed())
        keep = (
            (patched.rows >= ch.row0)
            & (patched.rows < ch.row0 + ch.n_rows)
        )
        new_chunk = _pack_chunk(
            f.fmt, ch.row0, ch.n_rows,
            patched.rows[keep].astype(np.int64) - ch.row0,
            f.col_perm.apply(patched.cols[keep]),
            patched.weights[keep].astype(np.float64),
        )
        if new_chunk.weights.dtype.itemsize > ch.weights.dtype.itemsize:
            promotions += 1
            _record_promotion(f.fmt)
        chunks[int(ci)] = new_chunk
    # Integer counts (< 2^53, the uint chunk invariant) make f64
    # addition order-exact, so the O(Δ) incremental colsum equals a
    # from-scratch accumulation bit-for-bit. Non-integer data (the f64
    # fallback) has no such order-independence — recompute chunk-wise
    # from the patched entries so the patched factor's colsum always
    # equals what a fresh pack of the same content would carry.
    int_exact = bool(
        (dc.weights == np.floor(dc.weights)).all()
        and all(ch.weights.dtype.kind == "u" for ch in chunks)
    )
    if int_exact:
        dcolsum = np.zeros(f.shape[1], dtype=np.float64)
        np.add.at(dcolsum, dc.cols, dc.weights.astype(np.float64))
        colsum = f.colsum + dcolsum
    else:
        colsum = np.zeros(f.shape[1], dtype=np.float64)
        for ch in chunks:
            if ch.nnz:
                _, ccols, cw = _decode_chunk(f, ch)
                np.add.at(colsum, ccols, cw)
    return PackedFactor(
        fmt=f.fmt, shape=f.shape,
        nnz=int(sum(ch.nnz for ch in chunks)),
        chunk_rows=f.chunk_rows, chunks=tuple(chunks),
        col_perm=f.col_perm, colsum=colsum,
        perm_bytes=f.perm_bytes, promotions=promotions,
    )


def packed_matmul(a, b) -> sp.COOMatrix:
    """Exact COO product of two factors in any representation — the
    same host join (and therefore the same exact integers, row-major
    sorted) as ``ops.sparse._matmul_summed`` on the COO path."""
    return sp._matmul_summed(as_coo(a), as_coo(b))


def fold_half(
    hin, metapath, fmt: str, memo=None, chunk_rows: int | None = None,
):
    """Plan-ordered half-chain fold → resident factor in ``fmt`` —
    the packed twin of ``planner.fold_half`` (which it delegates to,
    so the fold itself stays behind the planner doorway / MP001)."""
    from . import planner

    coo = planner.fold_half(hin, metapath, memo=memo)
    return make_factor(coo, fmt, chunk_rows=chunk_rows)
