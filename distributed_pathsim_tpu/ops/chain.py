"""Commuting-matrix chain evaluation.

The reference computes one entry (or one row sum) of the commuting matrix
per distributed 4-way join, ``2N-1`` joins per run (``DPathSim_APVPA.py:
28-68``). Here the chain is evaluated as staged matmuls — O(1) GEMMs for
the whole all-pairs problem. Functions are array-library agnostic: pass
``numpy`` for the f64 oracle or ``jax.numpy`` inside jit for TPU.

Key identities used throughout (SURVEY.md §3.3, verified against the
reference's own run log):

- symmetric path:  M = C @ Cᵀ,  C = product of the first half
- row sums without M:  rowsum(M) = C @ (Σ_x C[x, :])   (symmetric)
                       rowsum(M) = B₁ @ (B₂ @ … (Bₖ @ 1))  (general)
- pairwise row:  M[s, :] = (C[s, :] @ Cᵀ)  — one GEMV
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from ..data.encode import EncodedHIN
from .metapath import MetaPath, Step


def oriented_dense_blocks(
    hin: EncodedHIN,
    steps: Sequence[Step],
    dtype: Any = np.float64,
) -> list[np.ndarray]:
    """Materialize the oriented dense adjacency block for each step
    (host-side numpy; backends move/convert as needed)."""
    out = []
    for st in steps:
        b = hin.block(st.relationship)
        dense = b.to_dense(dtype=dtype)
        out.append(dense.T if st.reverse else dense)
    return out


def chain_product(blocks: Sequence[Any], xp: Any = np):
    """Left-to-right product of the oriented chain.

    Left-to-right is optimal for metapaths that start from a large node
    set and contract through small ones (A×P · P×V → A×V stays small);
    callers with pathological shapes can pre-associate.
    """
    m = blocks[0]
    for b in blocks[1:]:
        m = xp.matmul(m, b)
    return m


def half_product(hin_blocks: Sequence[Any], xp: Any = np):
    """C for a symmetric chain: product of the first-half oriented blocks."""
    return chain_product(hin_blocks, xp=xp)


def commuting_matrix_from_half(c, xp: Any = np):
    """M = C @ Cᵀ (symmetric by construction)."""
    return xp.matmul(c, c.T if hasattr(c, "T") else xp.transpose(c))


def rowsums_from_half(c, xp: Any = np):
    """rowsum(M) = C @ (Σ_x C[x, :]) — the reference's "global walk" for
    every node at once, without materializing M. O(N·V) instead of O(N²)."""
    total = xp.sum(c, axis=0)
    return xp.matmul(c, total)


def rowsums_general(blocks: Sequence[Any], xp: Any = np):
    """rowsum(M) for an arbitrary oriented chain: fold the all-ones vector
    from the right — never materializes anything wider than a block."""
    last = blocks[-1]
    v = xp.ones((last.shape[-1],), dtype=last.dtype)
    for b in reversed(blocks):
        v = xp.matmul(b, v)
    return v


def pairwise_row_from_half(c, source_index: int, xp: Any = np):
    """M[source, :] = C[source] @ Cᵀ — one GEMV, the batched analog of the
    reference's per-pair motif query."""
    return xp.matmul(c, c[source_index])
