"""Commuting-matrix chain evaluation.

The reference computes one entry (or one row sum) of the commuting matrix
per distributed 4-way join, ``2N-1`` joins per run (``DPathSim_APVPA.py:
28-68``). Here the chain is evaluated as staged matmuls — O(1) GEMMs for
the whole all-pairs problem. Functions are array-library agnostic: pass
``numpy`` for the f64 oracle or ``jax.numpy`` inside jit for TPU.

Key identities used throughout (SURVEY.md §3.3, verified against the
reference's own run log):

- symmetric path:  M = C @ Cᵀ,  C = product of the first half
- row sums without M:  rowsum(M) = C @ (Σ_x C[x, :])   (symmetric)
                       rowsum(M) = B₁ @ (B₂ @ … (Bₖ @ 1))  (general)
- pairwise row:  M[s, :] = (C[s, :] @ Cᵀ)  — one GEMV
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from ..data.encode import EncodedHIN
from .metapath import MetaPath, Step


# f32 represents every integer exactly up to 2**24. Path counts are
# integers (SURVEY.md §7 hard parts): a silently rounded count corrupts
# every downstream score, so backends refuse loudly past this range.
F32_EXACT_INT_MAX = float(2**24)


def effective_device_dtype(requested: Any) -> np.dtype:
    """The dtype device arrays will actually carry.

    Without JAX x64 mode, a float64 request silently downcasts to f32 at
    ``device_put`` — so an overflow guard keyed on the *requested* dtype
    would wave through exactly the corruption it exists to stop.
    """
    dt = np.dtype(requested)
    if dt == np.float64:
        import jax

        if not jax.config.jax_enable_x64:
            return np.dtype(np.float32)
    return dt


def check_exact_counts(max_count: float, requested_dtype: Any) -> None:
    """Refuse when integer path counts exceed the exact-integer range of
    the dtype the device will actually use (single shared guard — keep
    every backend's contract identical)."""
    if effective_device_dtype(requested_dtype) != np.float32:
        return
    if max_count >= F32_EXACT_INT_MAX:
        raise OverflowError(
            "path counts exceed f32 exact-integer range (2^24); "
            "construct with dtype=jnp.float64 AND set JAX_ENABLE_X64=1 "
            "(without x64 mode, f64 arrays silently downcast to f32 on "
            "device)"
        )


def oriented_dense_blocks(
    hin: EncodedHIN,
    steps: Sequence[Step],
    dtype: Any = np.float64,
) -> list[np.ndarray]:
    """Materialize the oriented dense adjacency block for each step
    (host-side numpy; backends move/convert as needed)."""
    out = []
    for st in steps:
        b = hin.block(st.relationship)
        dense = b.to_dense(dtype=dtype)
        out.append(dense.T if st.reverse else dense)
    return out


def chain_product(blocks: Sequence[Any], xp: Any = np):
    """Left-to-right product of the oriented chain.

    Left-to-right is optimal for metapaths that start from a large node
    set and contract through small ones (A×P · P×V → A×V stays small);
    callers with pathological shapes can pre-associate.
    """
    m = blocks[0]
    for b in blocks[1:]:
        m = xp.matmul(m, b)
    return m


def half_product(hin_blocks: Sequence[Any], xp: Any = np):
    """C for a symmetric chain: product of the first-half oriented blocks."""
    return chain_product(hin_blocks, xp=xp)


def commuting_matrix_from_half(c, xp: Any = np):
    """M = C @ Cᵀ (symmetric by construction)."""
    return xp.matmul(c, c.T if hasattr(c, "T") else xp.transpose(c))


def rowsums_from_half(c, xp: Any = np):
    """rowsum(M) = C @ (Σ_x C[x, :]) — the reference's "global walk" for
    every node at once, without materializing M. O(N·V) instead of O(N²)."""
    total = xp.sum(c, axis=0)
    return xp.matmul(c, total)


def rowsums_general(blocks: Sequence[Any], xp: Any = np):
    """rowsum(M) for an arbitrary oriented chain: fold the all-ones vector
    from the right — never materializes anything wider than a block."""
    last = blocks[-1]
    v = xp.ones((last.shape[-1],), dtype=last.dtype)
    for b in reversed(blocks):
        v = xp.matmul(b, v)
    return v


def pairwise_row_from_half(c, source_index: int, xp: Any = np):
    """M[source, :] = C[source] @ Cᵀ — one GEMV, the batched analog of the
    reference's per-pair motif query."""
    return xp.matmul(c, c[source_index])
