"""PathSim score normalization.

The reference's score (``DPathSim_APVPA.py:51-52``) is the *row-sum
variant*: sim(x,y) = 2·M[x,y] / (Σ_z M[x,z] + Σ_z M[y,z]), because its
"global walk" motif leaves ``author_2`` unconstrained (SURVEY.md §3.3 —
verified to the last digit against the reference's run log). The textbook
PathSim of Sun et al. normalizes by the diagonal instead:
sim(x,y) = 2·M[x,y] / (M[x,x] + M[y,y]). Both variants are provided;
``variant="rowsum"`` is the default and the parity target.

Degenerate denominators: with integer path counts, denom == 0 implies
M[x,y] == 0 (the numerator is bounded by either row sum); the reference
would raise ZeroDivisionError there (plain Python division). We define
the score as 0.0 in that case — the only semantic divergence, and only
on inputs that crash the reference.
"""

from __future__ import annotations

from typing import Any

import numpy as np

VARIANTS = ("rowsum", "diagonal")


def _denominators(m, rowsums, variant: str, xp: Any):
    if variant == "rowsum":
        if rowsums is None:
            rowsums = xp.sum(m, axis=1)
        return rowsums
    if variant == "diagonal":
        return xp.diagonal(m)
    raise ValueError(f"unknown PathSim variant {variant!r}; choose {VARIANTS}")


def score_matrix(m, rowsums=None, variant: str = "rowsum", xp: Any = np):
    """All-pairs scores: sim = 2·M / (d[:, None] + d[None, :])."""
    d = _denominators(m, rowsums, variant, xp)
    denom = d[:, None] + d[None, :]
    return xp.where(denom > 0, 2.0 * m / xp.where(denom > 0, denom, 1), 0.0)


def score_row(m_row, d_source, d, xp: Any = np):
    """Scores from one source against all targets, given its pairwise row
    ``m_row = M[s, :]`` and the denominator vector ``d``."""
    denom = d_source + d
    return xp.where(denom > 0, 2.0 * m_row / xp.where(denom > 0, denom, 1), 0.0)
