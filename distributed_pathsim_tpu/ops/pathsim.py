"""PathSim score normalization.

The reference's score (``DPathSim_APVPA.py:51-52``) is the *row-sum
variant*: sim(x,y) = 2·M[x,y] / (Σ_z M[x,z] + Σ_z M[y,z]), because its
"global walk" motif leaves ``author_2`` unconstrained (SURVEY.md §3.3 —
verified to the last digit against the reference's run log). The textbook
PathSim of Sun et al. normalizes by the diagonal instead:
sim(x,y) = 2·M[x,y] / (M[x,x] + M[y,y]). Both variants are provided;
``variant="rowsum"`` is the default and the parity target.

Degenerate denominators: with integer path counts, denom == 0 implies
M[x,y] == 0 (the numerator is bounded by either row sum); the reference
would raise ZeroDivisionError there (plain Python division). We define
the score as 0.0 in that case — the only semantic divergence, and only
on inputs that crash the reference.
"""

from __future__ import annotations

from typing import Any

import numpy as np

VARIANTS = ("rowsum", "diagonal")


def jax_exact():
    """The jax module iff device arithmetic stays bit-identical to the
    numpy f64 path: without x64 mode an f64 operand silently downcasts
    to f32 at device_put (ops/chain.effective_device_dtype), which
    breaks the exact-integer-counts contract every parity gate rests
    on — so no x64, no jax. Callers treat None as "score on host"."""
    try:
        import jax
    except Exception:  # pragma: no cover - jax is baked into the image
        return None
    if not jax.config.jax_enable_x64:
        return None
    return jax


def _denominators(m, rowsums, variant: str, xp: Any):
    if variant == "rowsum":
        if rowsums is None:
            rowsums = xp.sum(m, axis=1)
        return rowsums
    if variant == "diagonal":
        return xp.diagonal(m)
    raise ValueError(f"unknown PathSim variant {variant!r}; choose {VARIANTS}")


def score_matrix(m, rowsums=None, variant: str = "rowsum", xp: Any = np):
    """All-pairs scores: sim = 2·M / (d[:, None] + d[None, :])."""
    d = _denominators(m, rowsums, variant, xp)
    denom = d[:, None] + d[None, :]
    return xp.where(denom > 0, 2.0 * m / xp.where(denom > 0, denom, 1), 0.0)


def score_row(m_row, d_source, d, xp: Any = np):
    """Scores from one source against all targets, given its pairwise row
    ``m_row = M[s, :]`` and the denominator vector ``d``."""
    denom = d_source + d
    return xp.where(denom > 0, 2.0 * m_row / xp.where(denom > 0, denom, 1), 0.0)


def score_rows(m_rows, d_sources, d, xp: Any = np):
    """Batched :func:`score_row`: ``m_rows`` [B, N], ``d_sources`` [B].

    Same arithmetic per row (broadcast in place of the scalar), so a row
    scored here is bit-identical to the unbatched call — the serving
    layer's coalesced dispatch depends on that."""
    denom = d_sources[:, None] + d[None, :]
    return xp.where(denom > 0, 2.0 * m_rows / xp.where(denom > 0, denom, 1), 0.0)


def score_candidates(m_cand, d_sources, d_cand, xp: Any = np):
    """Candidate-restricted :func:`score_rows`: ``m_cand`` [B, C] holds
    the pairwise counts for an explicit candidate-column set, ``d_cand``
    [B, C] those columns' denominators. Entry-for-entry the same f64
    arithmetic as the full-row call, so a candidate scored here is
    bit-identical to its column in ``score_rows`` — the ANN serving
    path's exact-rerank contract rests on that."""
    denom = d_sources[:, None] + d_cand
    return xp.where(denom > 0, 2.0 * m_cand / xp.where(denom > 0, denom, 1), 0.0)


def topk_from_candidate_scores(scores: np.ndarray, cols: np.ndarray, k: int):
    """Top-k over an explicit candidate set with the oracle tie order.

    ``scores`` f64 [B, C] and ``cols`` int64 [B, C] give each
    candidate's score and GLOBAL column index; entries with ``cols < 0``
    are padding and never returned. Ordering is (descending score,
    ascending global column) — the :func:`topk_from_score_rows` order
    restricted to the candidate set, so whenever the true top-k is a
    subset of the candidates the result is bit-identical to the
    full-row call, boundary ties included. Duplicated candidate columns
    are deduplicated (they carry identical scores by construction).
    Returns (values f64 [B, k], indices int64 [B, k]), short rows
    padded with (−inf, 0) exactly like the full-row primitive."""
    b = scores.shape[0]
    vals = np.full((b, k), -np.inf)
    idxs = np.zeros((b, k), dtype=np.int64)
    for i in range(b):
        keep = cols[i] >= 0
        c, s = cols[i][keep], scores[i][keep]
        if c.size == 0:
            continue
        if c.size > k:
            # O(C) partition to the k-boundary first — the sort and
            # dedup then touch only the boundary's tie set, not all C
            # candidates (the same trick topk_from_score_rows uses);
            # every score tied with the k-th is kept, so boundary tie
            # order is exact
            kth = -np.partition(-s, k - 1)[k - 1]
            top = s >= kth
            ct, st = c[top], s[top]
            cu, first = np.unique(ct, return_index=True)
            if cu.shape[0] >= k or ct.shape[0] == c.shape[0]:
                c, s = cu, st[first]
            else:
                # duplicated columns ate the partition's k guarantee:
                # fall back to deduping the full candidate list
                c, first = np.unique(c, return_index=True)
                s = s[first]
        else:
            c, first = np.unique(c, return_index=True)
            s = s[first]
        order = np.lexsort((c, -s))[:k]
        vals[i, : order.shape[0]] = s[order]
        idxs[i, : order.shape[0]] = c[order]
    return vals, idxs


def topk_from_score_rows(scores: np.ndarray, k: int):
    """Host top-k over score rows with the oracle tie order.

    ``scores`` is f64 [B, N] with excluded entries (self pairs) already
    −inf. Returns (values f64 [B, k], indices int64 [B, k]) ordered by
    (descending score, ascending column) — exactly
    ``np.argsort(-row, kind="stable")[:k]``, the driver/oracle order —
    but via an O(N) partition plus a sort over only the candidate set
    (every column tied with the k-th value is kept as a candidate, so
    boundary ties order identically to the full sort)."""
    b, n = scores.shape
    k = min(k, n)
    vals = np.full((b, k), -np.inf)
    idxs = np.zeros((b, k), dtype=np.int64)
    for i in range(b):
        s = scores[i]
        if k >= n:
            order = np.lexsort((np.arange(n), -s))[:k]
        else:
            kth = -np.partition(-s, k - 1)[k - 1]
            # kth == −inf (fewer than k finite scores) keeps every
            # column: −inf >= −inf, so the candidate set is complete.
            cand = np.nonzero(s >= kth)[0]
            order = cand[np.lexsort((cand, -s[cand]))[:k]]
        vals[i, : order.shape[0]] = s[order]
        idxs[i, : order.shape[0]] = order
    return vals, idxs
