"""PathSim score normalization.

The reference's score (``DPathSim_APVPA.py:51-52``) is the *row-sum
variant*: sim(x,y) = 2·M[x,y] / (Σ_z M[x,z] + Σ_z M[y,z]), because its
"global walk" motif leaves ``author_2`` unconstrained (SURVEY.md §3.3 —
verified to the last digit against the reference's run log). The textbook
PathSim of Sun et al. normalizes by the diagonal instead:
sim(x,y) = 2·M[x,y] / (M[x,x] + M[y,y]). Both variants are provided;
``variant="rowsum"`` is the default and the parity target.

Degenerate denominators: with integer path counts, denom == 0 implies
M[x,y] == 0 (the numerator is bounded by either row sum); the reference
would raise ZeroDivisionError there (plain Python division). We define
the score as 0.0 in that case — the only semantic divergence, and only
on inputs that crash the reference.
"""

from __future__ import annotations

from typing import Any

import numpy as np

VARIANTS = ("rowsum", "diagonal")


def _denominators(m, rowsums, variant: str, xp: Any):
    if variant == "rowsum":
        if rowsums is None:
            rowsums = xp.sum(m, axis=1)
        return rowsums
    if variant == "diagonal":
        return xp.diagonal(m)
    raise ValueError(f"unknown PathSim variant {variant!r}; choose {VARIANTS}")


def score_matrix(m, rowsums=None, variant: str = "rowsum", xp: Any = np):
    """All-pairs scores: sim = 2·M / (d[:, None] + d[None, :])."""
    d = _denominators(m, rowsums, variant, xp)
    denom = d[:, None] + d[None, :]
    return xp.where(denom > 0, 2.0 * m / xp.where(denom > 0, denom, 1), 0.0)


def score_row(m_row, d_source, d, xp: Any = np):
    """Scores from one source against all targets, given its pairwise row
    ``m_row = M[s, :]`` and the denominator vector ``d``."""
    denom = d_source + d
    return xp.where(denom > 0, 2.0 * m_row / xp.where(denom > 0, denom, 1), 0.0)


def score_rows(m_rows, d_sources, d, xp: Any = np):
    """Batched :func:`score_row`: ``m_rows`` [B, N], ``d_sources`` [B].

    Same arithmetic per row (broadcast in place of the scalar), so a row
    scored here is bit-identical to the unbatched call — the serving
    layer's coalesced dispatch depends on that."""
    denom = d_sources[:, None] + d[None, :]
    return xp.where(denom > 0, 2.0 * m_rows / xp.where(denom > 0, denom, 1), 0.0)


def topk_from_score_rows(scores: np.ndarray, k: int):
    """Host top-k over score rows with the oracle tie order.

    ``scores`` is f64 [B, N] with excluded entries (self pairs) already
    −inf. Returns (values f64 [B, k], indices int64 [B, k]) ordered by
    (descending score, ascending column) — exactly
    ``np.argsort(-row, kind="stable")[:k]``, the driver/oracle order —
    but via an O(N) partition plus a sort over only the candidate set
    (every column tied with the k-th value is kept as a candidate, so
    boundary ties order identically to the full sort)."""
    b, n = scores.shape
    k = min(k, n)
    vals = np.full((b, k), -np.inf)
    idxs = np.zeros((b, k), dtype=np.int64)
    for i in range(b):
        s = scores[i]
        if k >= n:
            order = np.lexsort((np.arange(n), -s))[:k]
        else:
            kth = -np.partition(-s, k - 1)[k - 1]
            # kth == −inf (fewer than k finite scores) keeps every
            # column: −inf >= −inf, so the candidate set is complete.
            cand = np.nonzero(s >= kth)[0]
            order = cand[np.lexsort((cand, -s[cand]))[:k]]
        vals[i, : order.shape[0]] = s[order]
        idxs[i, : order.shape[0]] = order
    return vals, idxs
