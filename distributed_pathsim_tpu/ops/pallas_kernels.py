"""Pallas TPU kernels for the PathSim hot path.

The framework's FLOPs live in ``M = C @ Cᵀ`` followed by the elementwise
normalization ``S = 2M / (d_i + d_j)`` (reference semantics, SURVEY.md
§3.3). Done naively, M (N×N) is written to HBM by the matmul and read
back by the normalize — at 10k+ authors that traffic dominates. The fused
kernel computes each [bm × bn] tile of M on the MXU and normalizes it in
VMEM before it ever leaves the chip: M never exists in HBM.

Also here: a fused top-k variant that reduces each row tile to its k best
scores on-chip (for the million-author regime where even S is too big to
materialize).

All kernels are f32 with f32 accumulation (integer path counts — bf16
would truncate, SURVEY.md §7) and have jnp reference implementations used
as CPU fallbacks and test oracles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Tile sizes: multiples of the f32 (8, 128) VMEM tile; 256×256 output
# tiles keep C tiles + out tile well under VMEM while saturating the MXU.
_BM = 256
_BN = 256


def _ceil_to(x: int, k: int) -> int:
    return ((x + k - 1) // k) * k


def _scores_kernel(c_i_ref, c_j_ref, d_i_ref, d_j_ref, out_ref):
    """One [bm, bn] tile: matmul on MXU + normalization in VMEM.

    HIGHEST precision forces full-f32 MXU passes: path counts are
    integers, and the default bf16 passes truncate counts ≥ 257.
    """
    m = jnp.dot(
        c_i_ref[:],
        c_j_ref[:].T,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    denom = d_i_ref[:] + d_j_ref[:].T  # [bm,1] + [1,bn]
    out_ref[:] = jnp.where(denom > 0, 2.0 * m / jnp.where(denom > 0, denom, 1.0), 0.0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_scores(c: jax.Array, rowsums: jax.Array, interpret: bool = False):
    """All-pairs PathSim scores from the half-chain factor, fused.

    c: [N, V] f32, rowsums: [N] f32 → scores [N, N] f32.
    Rows are padded to the tile size inside; padded rows have rowsum 0 and
    produce score 0 (the where-guard), then are sliced away.
    """
    n, v = c.shape
    n_pad = _ceil_to(max(n, 8), _BM)
    v_pad = _ceil_to(max(v, 128), 128)
    c_p = jnp.zeros((n_pad, v_pad), dtype=jnp.float32).at[:n, :v].set(c)
    d_p = jnp.zeros((n_pad, 1), dtype=jnp.float32).at[:n, 0].set(rowsums)

    grid = (n_pad // _BM, n_pad // _BN)
    out = pl.pallas_call(
        _scores_kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, n_pad), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BM, v_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((_BN, v_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((_BM, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((_BN, 1), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((_BM, _BN), lambda i, j: (i, j)),
        interpret=interpret,
    )(c_p, c_p, d_p, d_p)
    return out[:n, :n]


@jax.jit
def fused_scores_reference(c: jax.Array, rowsums: jax.Array):
    """Pure-XLA fallback with identical semantics (CPU, or no-pallas)."""
    with jax.default_matmul_precision("highest"):
        m = jnp.matmul(c, c.T)
    denom = rowsums[:, None] + rowsums[None, :]
    return jnp.where(denom > 0, 2.0 * m / jnp.where(denom > 0, denom, 1.0), 0.0)


def _topk_kernel(k: int, mask_self: bool, n_true: int, c_i_ref, c_j_ref,
                 d_i_ref, d_j_ref, vals_ref, idxs_ref):
    """Row-tile top-k: fold each [bm, bn] score tile into the running
    [bm, k_pad] best values/indices. Grid is (rows, cols) with cols
    innermost; the running state lives in the output refs (same row block
    for every j step, so revisiting is safe).

    ``lax.top_k`` has no Pallas TPU lowering, so selection is k unrolled
    rounds of max-extract over the merged candidates — pure VPU reductions
    (k is small; each round is O(bm·(k_pad+bn)) vector work).
    """
    j = pl.program_id(1)

    m = jnp.dot(
        c_i_ref[:],
        c_j_ref[:].T,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    denom = d_i_ref[:] + d_j_ref[:].T
    s = jnp.where(denom > 0, 2.0 * m / jnp.where(denom > 0, denom, 1.0), 0.0)
    bm, bn = s.shape
    col_base = j * bn
    cols = col_base + jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 1)
    # Only PADDING columns (index ≥ n_true) are ruled out with -inf; real
    # zero-degree targets keep score 0 exactly like the unfused oracle.
    s = jnp.where(cols < n_true, s, -jnp.inf)
    if mask_self:
        i = pl.program_id(0)
        rows = i * bm + jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 0)
        s = jnp.where(rows == cols, -jnp.inf, s)

    @pl.when(j == 0)
    def _init():
        vals_ref[:] = jnp.full_like(vals_ref, -jnp.inf)
        idxs_ref[:] = jnp.zeros_like(idxs_ref)

    merged_v = jnp.concatenate([vals_ref[:], s], axis=1)
    merged_i = jnp.concatenate([idxs_ref[:], cols], axis=1)
    mcols = jax.lax.broadcasted_iota(jnp.int32, merged_v.shape, 1)
    out_col = jax.lax.broadcasted_iota(jnp.int32, (bm, vals_ref.shape[1]), 1)
    new_v = jnp.full((bm, vals_ref.shape[1]), -jnp.inf, dtype=vals_ref.dtype)
    new_i = jnp.zeros((bm, idxs_ref.shape[1]), dtype=idxs_ref.dtype)
    big = jnp.int32(2**30)
    for t in range(k):
        vmax = jnp.max(merged_v, axis=1, keepdims=True)
        # first column achieving the max (deterministic tie-break)
        pos = jnp.min(
            jnp.where(merged_v == vmax, mcols, big), axis=1, keepdims=True
        )
        imax = jnp.max(
            jnp.where(mcols == pos, merged_i, jnp.int32(0)), axis=1, keepdims=True
        )
        new_v = jnp.where(out_col == t, vmax, new_v)
        new_i = jnp.where(out_col == t, imax, new_i)
        merged_v = jnp.where(mcols == pos, -jnp.inf, merged_v)
    vals_ref[:] = new_v
    idxs_ref[:] = new_i


@functools.partial(jax.jit, static_argnames=("k", "mask_self", "interpret"))
def fused_topk(
    c: jax.Array,
    rowsums: jax.Array,
    k: int = 10,
    mask_self: bool = True,
    interpret: bool = False,
):
    """Per-row top-k scores without materializing the score matrix.

    Returns (values [N, k] f32, indices [N, k] int32).
    """
    n, v = c.shape
    n_pad = _ceil_to(max(n, 8), _BM)
    v_pad = _ceil_to(max(v, 128), 128)
    k_pad = _ceil_to(k, 128)  # lane-aligned output minor dim
    c_p = jnp.zeros((n_pad, v_pad), dtype=jnp.float32).at[:n, :v].set(c)
    d_p = jnp.zeros((n_pad, 1), dtype=jnp.float32).at[:n, 0].set(rowsums)

    grid = (n_pad // _BM, n_pad // _BN)
    vals, idxs = pl.pallas_call(
        functools.partial(_topk_kernel, k, mask_self, n),
        out_shape=(
            jax.ShapeDtypeStruct((n_pad, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, k_pad), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BM, v_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((_BN, v_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((_BM, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((_BN, 1), lambda i, j: (j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((_BM, k_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((_BM, k_pad), lambda i, j: (i, 0)),
        ),
        interpret=interpret,
    )(c_p, c_p, d_p, d_p)
    return vals[:n, :k], idxs[:n, :k]


def pallas_supported() -> bool:
    """Pallas TPU kernels need a real TPU backend; elsewhere callers use
    interpret mode (tests) or the XLA reference."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# VMEM is ~16 MB/core; each grid step holds two [tile, v_pad] C blocks
# plus the output tile. The kernels do not (yet) tile the contraction
# dim, so wide half-chain factors (e.g. APA's author×paper C) must take
# the XLA path instead of overflowing VMEM.
_VMEM_BUDGET_BYTES = 12 << 20


def fits_vmem(v: int) -> bool:
    v_pad = _ceil_to(max(v, 128), 128)
    needed = (_BM + _BN) * v_pad * 4 + _BM * _BN * 4
    return needed <= _VMEM_BUDGET_BYTES
