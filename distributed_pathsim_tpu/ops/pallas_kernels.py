"""Pallas TPU kernels for the PathSim hot path.

The framework's FLOPs live in ``M = C @ Cᵀ`` followed by the elementwise
normalization ``S = 2M / (d_i + d_j)`` (reference semantics, SURVEY.md
§3.3). Done naively, M (N×N) is written to HBM by the matmul and read
back by the normalize — at 10k+ authors that traffic dominates. The fused
kernel computes each [bm × bn] tile of M on the MXU and normalizes it in
VMEM before it ever leaves the chip: M never exists in HBM.

Also here: a fused top-k variant that reduces each row tile to its k best
scores on-chip (for the million-author regime where even S is too big to
materialize).

All kernels are f32 with f32 accumulation (integer path counts — bf16
would truncate, SURVEY.md §7) and have jnp reference implementations used
as CPU fallbacks and test oracles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tile sizes: multiples of the f32 (8, 128) VMEM tile; 256×256 output
# tiles keep C tiles + out tile well under VMEM while saturating the MXU.
_BM = 256
_BN = 256
# Contraction tile for the K-tiled variants (wide half-chain factors,
# e.g. APA where V = #papers): two [256, 512] C tiles + the f32
# accumulator stay well inside VMEM at any V.
_BK = 512


def _ceil_to(x: int, k: int) -> int:
    return ((x + k - 1) // k) * k


def tile_fits_vmem(bm: int, bn: int, v: int) -> bool:
    """Whether an output tile (bm, bn) at contraction width ``v`` fits
    the single-pass kernels' VMEM budget (two [tile, v_pad] C blocks +
    the out tile). The feasibility gate every tile choice — heuristic
    or tuned — must pass."""
    v_pad = _ceil_to(max(v, 128), 128)
    return (bm + bn) * v_pad * 4 + bm * bn * 4 <= _VMEM_BUDGET_BYTES


def _heuristic_scores_tiles(n: int, v: int) -> tuple[int, int]:
    """fused_scores' built-in tile heuristic. The on-chip sweep
    (KERNELS_r05.json, v5e, V=384): (256, 512) reaches 90.3% of the
    f32 MXU ceiling at N=8k (XLA's GEMM: 86.7%), (512, 1024) 85.3% at
    N=32k (XLA: 87.0%), vs 74–80% for the old (256, 256) default.
    Wider tiles hold bigger [tile, v_pad] C blocks, so the pick must
    honor the same VMEM budget fits_vmem() polices — at wide V the
    sweep winners would not fit and the floor config stays."""
    for bm, bn in ((256, 512),) if n <= 16384 else ((512, 1024), (256, 512)):
        if tile_fits_vmem(bm, bn, v):
            return bm, bn
    return _BM, _BN


def _default_scores_tiles(n: int, v: int) -> tuple[int, int]:
    """Resolved output tile: the dispatch table's measured choice for
    this (device, shape) key when one is installed, the heuristic
    otherwise — and the heuristic again if a tuned choice no longer
    passes the VMEM gate (a table must never push a kernel over a
    hardware budget)."""
    from .. import tuning

    bm, bn = tuning.choose(
        "scores_tile", n=n, v=v,
        default=lambda: _heuristic_scores_tiles(n, v),
    )
    # sanitize BEFORE the budget check: Mosaic needs sublane-aligned
    # rows and lane-aligned columns, and a hand-built table entry must
    # cost performance at worst, never a lowering failure
    bm = max(8, _ceil_to(int(bm), 8))
    bn = max(128, _ceil_to(int(bn), 128))
    if not tile_fits_vmem(bm, bn, v):
        return _heuristic_scores_tiles(n, v)
    return bm, bn


def _tile_dot(c_i_ref, c_j_ref):
    """One MXU pass of the tile product. HIGHEST precision forces
    full-f32 passes: path counts are integers, and the default bf16
    passes truncate counts ≥ 257."""
    return jnp.dot(
        c_i_ref[:],
        c_j_ref[:].T,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def _normalize(m, d_i_ref, d_j_ref):
    """S = 2M / (d_i ⊕ d_j), zero where the denominator is zero —
    shared by every kernel so their numerics can never drift apart."""
    denom = d_i_ref[:] + d_j_ref[:].T  # [bm,1] + [1,bn]
    return jnp.where(denom > 0, 2.0 * m / jnp.where(denom > 0, denom, 1.0), 0.0)


def _mask_tile(s, i, j, n_true: int, mask_self: bool):
    """-inf out padding columns (index ≥ n_true) and, optionally,
    self-pairs. Real zero-degree targets keep score 0 exactly like the
    unfused oracle. Returns (masked s, global column indices)."""
    bm, bn = s.shape
    cols = j * bn + jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 1)
    s = jnp.where(cols < n_true, s, -jnp.inf)
    if mask_self:
        rows = i * bm + jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 0)
        s = jnp.where(rows == cols, -jnp.inf, s)
    return s, cols


def _scores_kernel(c_i_ref, c_j_ref, d_i_ref, d_j_ref, out_ref):
    """One [bm, bn] tile: matmul on MXU + normalization in VMEM."""
    out_ref[:] = _normalize(_tile_dot(c_i_ref, c_j_ref), d_i_ref, d_j_ref)


def fused_scores(c: jax.Array, rowsums: jax.Array, interpret: bool = False,
                 bm: int | None = None, bn: int | None = None):
    """All-pairs PathSim scores from the half-chain factor, fused.

    c: [N, V] f32, rowsums: [N] f32 → scores [N, N] f32.
    Rows are padded to the tile size inside; padded rows have rowsum 0 and
    produce score 0 (the where-guard), then are sliced away.

    ``bm``/``bn`` override the output tile (perf sweeps): arithmetic
    intensity per HBM byte grows ∝ tile edge, so larger tiles close the
    gap to XLA's GEMM — but every config must be validated ON CHIP
    (scripts/kernel_bench.py --sweep-tiles; Mosaic VMEM/layout limits
    don't reproduce in interpret mode). With no override the tile comes
    from the tuning dispatch (_default_scores_tiles) — resolved HERE,
    outside the jitted core, so a table installed mid-process is never
    frozen into a cached trace.
    """
    n, v = c.shape
    if bm is None and bn is None:
        bm, bn = _default_scores_tiles(int(n), int(v))
    else:
        bm = _BM if bm is None else bm
        bn = _BN if bn is None else bn
    return _fused_scores_jit(c, rowsums, interpret, bm, bn)


@functools.partial(
    jax.jit, static_argnames=("interpret", "bm", "bn")
)
def _fused_scores_jit(c: jax.Array, rowsums: jax.Array, interpret: bool,
                      bm: int, bn: int):
    n, v = c.shape
    # pad to a multiple of BOTH tile dims: the grid floor-divides by
    # each, and a pad that only covers the larger one would leave
    # output tiles unwritten for non-dividing (bm, bn) pairs
    import math

    n_pad = _ceil_to(max(n, 8), math.lcm(bm, bn))
    v_pad = _ceil_to(max(v, 128), 128)
    c_p = jnp.zeros((n_pad, v_pad), dtype=jnp.float32).at[:n, :v].set(c)
    d_p = jnp.zeros((n_pad, 1), dtype=jnp.float32).at[:n, 0].set(rowsums)

    grid = (n_pad // bm, n_pad // bn)
    out = pl.pallas_call(
        _scores_kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, n_pad), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, v_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, v_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((bm, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        interpret=interpret,
    )(c_p, c_p, d_p, d_p)
    return out[:n, :n]


@jax.jit
def fused_scores_reference(c: jax.Array, rowsums: jax.Array):
    """Pure-XLA fallback with identical semantics (CPU, or no-pallas)."""
    with jax.default_matmul_precision("highest"):
        m = jnp.matmul(c, c.T)
    denom = rowsums[:, None] + rowsums[None, :]
    return jnp.where(denom > 0, 2.0 * m / jnp.where(denom > 0, denom, 1.0), 0.0)


def _topk_kernel(k: int, mask_self: bool, n_true: int, c_i_ref, c_j_ref,
                 d_i_ref, d_j_ref, vals_ref, idxs_ref):
    """Row-tile top-k: fold each [bm, bn] score tile into the running
    [bm, k_pad] best values/indices. Grid is (rows, cols) with cols
    innermost; the running state lives in the output refs (same row block
    for every j step, so revisiting is safe).

    ``lax.top_k`` has no Pallas TPU lowering, so selection is k unrolled
    rounds of max-extract over the merged candidates — pure VPU reductions
    (k is small; each round is O(bm·(k_pad+bn)) vector work).
    """
    i = pl.program_id(0)
    j = pl.program_id(1)

    s = _normalize(_tile_dot(c_i_ref, c_j_ref), d_i_ref, d_j_ref)
    s, cols = _mask_tile(s, i, j, n_true, mask_self)

    @pl.when(j == 0)
    def _init():
        vals_ref[:] = jnp.full_like(vals_ref, -jnp.inf)
        idxs_ref[:] = jnp.zeros_like(idxs_ref)

    _fold_tile_topk(k, s, cols, vals_ref, idxs_ref)


def _fold_tile_topk(k: int, s, cols, vals_ref, idxs_ref):
    """Merge one masked score tile ``s`` (with global column indices
    ``cols``) into the running [bm, k_pad] best refs: k unrolled rounds
    of max-extract over the merged candidates — pure VPU reductions."""
    bm = s.shape[0]
    merged_v = jnp.concatenate([vals_ref[:], s], axis=1)
    merged_i = jnp.concatenate([idxs_ref[:], cols], axis=1)
    mcols = jax.lax.broadcasted_iota(jnp.int32, merged_v.shape, 1)
    out_col = jax.lax.broadcasted_iota(jnp.int32, (bm, vals_ref.shape[1]), 1)
    new_v = jnp.full((bm, vals_ref.shape[1]), -jnp.inf, dtype=vals_ref.dtype)
    new_i = jnp.zeros((bm, idxs_ref.shape[1]), dtype=idxs_ref.dtype)
    big = jnp.int32(2**30)
    for t in range(k):
        vmax = jnp.max(merged_v, axis=1, keepdims=True)
        # first column achieving the max (deterministic tie-break)
        pos = jnp.min(
            jnp.where(merged_v == vmax, mcols, big), axis=1, keepdims=True
        )
        imax = jnp.max(
            jnp.where(mcols == pos, merged_i, jnp.int32(0)), axis=1, keepdims=True
        )
        new_v = jnp.where(out_col == t, vmax, new_v)
        new_i = jnp.where(out_col == t, imax, new_i)
        merged_v = jnp.where(mcols == pos, -jnp.inf, merged_v)
    vals_ref[:] = new_v
    idxs_ref[:] = new_i


def fused_topk(
    c: jax.Array,
    rowsums: jax.Array,
    k: int = 10,
    mask_self: bool = True,
    interpret: bool = False,
    bm: int | None = None,
):
    """Per-row top-k scores without materializing the score matrix.

    Returns (values [N, k] f32, indices [N, k] int32).

    ``bm`` overrides the row tile (rows folded per grid step); default
    is the tuning dispatch's ``topk_rowtile`` choice for this shape,
    resolved outside the jitted core (same staleness argument as
    :func:`fused_scores`).
    """
    n, v = c.shape
    if bm is None:
        from .. import tuning

        bm = int(tuning.choose("topk_rowtile", n=int(n), v=int(v),
                               default=_BM))
        # same hardware gates as _default_scores_tiles: sublane
        # alignment, then the VMEM budget for the [bm, v_pad] row block
        # next to the [_BN, v_pad] column block — a tuned row tile must
        # cost performance at worst, never a Mosaic failure
        bm = max(8, _ceil_to(bm, 8))
        if not tile_fits_vmem(bm, _BN, int(v)):
            bm = _BM
    return _fused_topk_jit(c, rowsums, k, mask_self, interpret, bm)


@functools.partial(
    jax.jit, static_argnames=("k", "mask_self", "interpret", "bm")
)
def _fused_topk_jit(c: jax.Array, rowsums: jax.Array, k: int,
                    mask_self: bool, interpret: bool, bm: int):
    import math

    n, v = c.shape
    n_pad = _ceil_to(max(n, 8), math.lcm(bm, _BN))
    v_pad = _ceil_to(max(v, 128), 128)
    k_pad = _ceil_to(k, 128)  # lane-aligned output minor dim
    c_p = jnp.zeros((n_pad, v_pad), dtype=jnp.float32).at[:n, :v].set(c)
    d_p = jnp.zeros((n_pad, 1), dtype=jnp.float32).at[:n, 0].set(rowsums)

    grid = (n_pad // bm, n_pad // _BN)
    vals, idxs = pl.pallas_call(
        functools.partial(_topk_kernel, k, mask_self, n),
        out_shape=(
            jax.ShapeDtypeStruct((n_pad, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, k_pad), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, v_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((_BN, v_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((bm, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((_BN, 1), lambda i, j: (j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((bm, k_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, k_pad), lambda i, j: (i, 0)),
        ),
        interpret=interpret,
    )(c_p, c_p, d_p, d_p)
    return vals[:n, :k], idxs[:n, :k]


# ---------------------------------------------------------------------------
# K-tiled variants: the contraction (V) axis is tiled too, so arbitrarily
# wide half-chain factors (APA: V = #papers) stay on the fused path. The
# partial M tile accumulates in a VMEM scratch across the innermost grid
# axis; normalization / top-k folding happens once, on the last K step.
# ---------------------------------------------------------------------------


def _scores_kernel_kt(n_kb, c_i_ref, c_j_ref, d_i_ref, d_j_ref, out_ref,
                      acc_ref):
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += _tile_dot(c_i_ref, c_j_ref)

    @pl.when(kb == n_kb - 1)
    def _finish():
        out_ref[:] = _normalize(acc_ref[:], d_i_ref, d_j_ref)


def _default_k_tile(n: int, v: int) -> int:
    """Contraction tile of the K-tiled variants: the tuning dispatch's
    choice (lane-aligned, clamped to the padded width) or the _BK
    heuristic. Resolved outside the jitted cores."""
    from .. import tuning

    bk = int(tuning.choose("k_tile", n=n, v=v, default=_BK))
    return max(128, _ceil_to(bk, 128))


def fused_scores_ktiled(c: jax.Array, rowsums: jax.Array,
                        interpret: bool = False, bk: int | None = None):
    """fused_scores for contraction widths that exceed one VMEM tile."""
    n, v = c.shape
    if bk is None:
        bk = _default_k_tile(int(n), int(v))
    return _fused_scores_ktiled_jit(c, rowsums, interpret, bk)


@functools.partial(jax.jit, static_argnames=("interpret", "bk"))
def _fused_scores_ktiled_jit(c: jax.Array, rowsums: jax.Array,
                             interpret: bool, bk: int):
    n, v = c.shape
    n_pad = _ceil_to(max(n, 8), _BM)
    bk = min(bk, _ceil_to(max(v, 128), 128))
    v_pad = _ceil_to(max(v, 128), bk)
    n_kb = v_pad // bk
    c_p = jnp.zeros((n_pad, v_pad), dtype=jnp.float32).at[:n, :v].set(c)
    d_p = jnp.zeros((n_pad, 1), dtype=jnp.float32).at[:n, 0].set(rowsums)

    grid = (n_pad // _BM, n_pad // _BN, n_kb)
    out = pl.pallas_call(
        functools.partial(_scores_kernel_kt, n_kb),
        out_shape=jax.ShapeDtypeStruct((n_pad, n_pad), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BM, bk), lambda i, j, kb: (i, kb)),
            pl.BlockSpec((_BN, bk), lambda i, j, kb: (j, kb)),
            pl.BlockSpec((_BM, 1), lambda i, j, kb: (i, 0)),
            pl.BlockSpec((_BN, 1), lambda i, j, kb: (j, 0)),
        ],
        out_specs=pl.BlockSpec((_BM, _BN), lambda i, j, kb: (i, j)),
        scratch_shapes=[pltpu.VMEM((_BM, _BN), jnp.float32)],
        interpret=interpret,
    )(c_p, c_p, d_p, d_p)
    return out[:n, :n]


def _topk_kernel_kt(k, mask_self, n_true, n_kb, c_i_ref, c_j_ref,
                    d_i_ref, d_j_ref, vals_ref, idxs_ref, acc_ref):
    # program_id must be read at kernel top level — inside a pl.when body
    # it fails to lower in interpret mode.
    i = pl.program_id(0)
    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init_acc():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += _tile_dot(c_i_ref, c_j_ref)

    @pl.when(kb == n_kb - 1)
    def _finish():
        s = _normalize(acc_ref[:], d_i_ref, d_j_ref)
        s, cols = _mask_tile(s, i, j, n_true, mask_self)

        @pl.when(j == 0)
        def _init_out():
            vals_ref[:] = jnp.full_like(vals_ref, -jnp.inf)
            idxs_ref[:] = jnp.zeros_like(idxs_ref)

        _fold_tile_topk(k, s, cols, vals_ref, idxs_ref)


def fused_topk_ktiled(
    c: jax.Array,
    rowsums: jax.Array,
    k: int = 10,
    mask_self: bool = True,
    interpret: bool = False,
    bk: int | None = None,
):
    """fused_topk for contraction widths that exceed one VMEM tile."""
    n, v = c.shape
    if bk is None:
        bk = _default_k_tile(int(n), int(v))
    return _fused_topk_ktiled_jit(c, rowsums, k, mask_self, interpret, bk)


@functools.partial(
    jax.jit, static_argnames=("k", "mask_self", "interpret", "bk")
)
def _fused_topk_ktiled_jit(
    c: jax.Array,
    rowsums: jax.Array,
    k: int,
    mask_self: bool,
    interpret: bool,
    bk: int,
):
    n, v = c.shape
    n_pad = _ceil_to(max(n, 8), _BM)
    bk = min(bk, _ceil_to(max(v, 128), 128))
    v_pad = _ceil_to(max(v, 128), bk)
    n_kb = v_pad // bk
    k_pad = _ceil_to(k, 128)
    c_p = jnp.zeros((n_pad, v_pad), dtype=jnp.float32).at[:n, :v].set(c)
    d_p = jnp.zeros((n_pad, 1), dtype=jnp.float32).at[:n, 0].set(rowsums)

    grid = (n_pad // _BM, n_pad // _BN, n_kb)
    vals, idxs = pl.pallas_call(
        functools.partial(_topk_kernel_kt, k, mask_self, n, n_kb),
        out_shape=(
            jax.ShapeDtypeStruct((n_pad, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, k_pad), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BM, bk), lambda i, j, kb: (i, kb)),
            pl.BlockSpec((_BN, bk), lambda i, j, kb: (j, kb)),
            pl.BlockSpec((_BM, 1), lambda i, j, kb: (i, 0)),
            pl.BlockSpec((_BN, 1), lambda i, j, kb: (j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((_BM, k_pad), lambda i, j, kb: (i, 0)),
            pl.BlockSpec((_BM, k_pad), lambda i, j, kb: (i, 0)),
        ),
        scratch_shapes=[pltpu.VMEM((_BM, _BN), jnp.float32)],
        interpret=interpret,
    )(c_p, c_p, d_p, d_p)
    return vals[:n, :k], idxs[:n, :k]


# ---------------------------------------------------------------------------
# Two-pass top-k: the single-pass kernels above fold every score tile
# into a running [bm, k_pad] buffer with k max-extract rounds over the
# merged candidates — measured on a v5e, that fold costs ~12× the score
# matmul at N=32k (the selection is pure VPU work serialized against
# the MXU). The two-pass design removes the merge entirely:
#
#   pass 1 (pallas): per [bm × bn] tile, extract the tile-local top-C
#     candidates (C = 16 ≥ k) straight out of the score tile — k rounds
#     of max-extract over ONE tile, no concatenated running buffer —
#     and write the [bm, C] winners to an HBM candidate buffer (~25%
#     of the score matrix's bytes once HBM lane padding is counted —
#     see the note at _TWOPASS_CAND_MAX_BYTES — vs 100% + a second
#     full read for an unfused scores+top_k).
#     Layout: Mosaic requires an output block's lane dim to be a
#     multiple of 128 OR equal to the array's lane dim, so the [bm, C]
#     blocks land in distinct ROW blocks of a [n_j·N_pad, C] buffer
#     (row j·N_pad + i·bm; lane dim C == array lane dim at every
#     shape) rather than C-wide column slices — the latter lowers only
#     when n_j == 1, which is exactly the trap interpret-mode tests
#     can't see.
#   pass 2 (XLA): exact hierarchical top-k over the candidates
#     (ops/sparse.chunked_row_topk) — any global top-k element is its
#     tile's top-k, so this is exact for k ≤ C.
#
# A wider bn (1024 vs 256) amortizes per-tile fixed work; extraction
# cost per column is k·4 VPU passes versus the fold's ~10 passes over
# a (k_pad + bn)-wide merge.
# ---------------------------------------------------------------------------

_CAND = 16  # candidates kept per tile; exact for k <= _CAND
_BN_WIDE = 1024
# The candidate buffer is [(N_pad/_BN_WIDE)·N_pad, _CAND] f32+i32. TPU
# HBM layouts are (8, 128)-tiled, so the 16-wide minor dim is padded to
# 128 lanes: the PHYSICAL footprint is n_j·N_pad·128·8 B ≈ N_pad²
# bytes — ~25% of the (never-materialized) f32 score matrix, ~1 GB at
# the 32k bench shape. The budget admits up to ~92k authors; beyond
# that the single-pass fold kernel (O(N·k_pad) state) takes over.
_TWOPASS_CAND_MAX_BYTES = 8 << 30
_HBM_LANE = 128  # minor-dim padding granularity of TPU HBM tiles


def twopass_fits(n: int) -> bool:
    """True when fused_topk_twopass's candidate buffer fits the HBM
    budget at this row count; callers fall back to fused_topk beyond."""
    n_pad = _ceil_to(max(n, 8), max(_BM, _BN_WIDE))
    lanes = max(_CAND, _HBM_LANE)
    cand_bytes = (n_pad // _BN_WIDE) * n_pad * lanes * 8
    return cand_bytes <= _TWOPASS_CAND_MAX_BYTES


def _extract_tile_topk(s, j, bn: int, k: int, cand: int, vals_ref, cols_ref):
    """Write the top-``k`` of each row of masked score tile ``s`` into
    the [bm, cand] output refs (values desc, -inf beyond k; global
    column ids). Only k rounds run — a tile can contribute at most k of
    the global top-k, so lanes k..cand-1 stay -inf by construction.
    Tie-break: smallest column — matches ``lax.top_k``."""
    bm = s.shape[0]
    lcols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    out_col = jax.lax.broadcasted_iota(jnp.int32, (bm, cand), 1)
    big = jnp.int32(2**30)
    new_v = jnp.full((bm, cand), -jnp.inf, dtype=s.dtype)
    new_c = jnp.zeros((bm, cand), dtype=jnp.int32)
    for t in range(k):
        vmax = jnp.max(s, axis=1, keepdims=True)
        pos = jnp.min(jnp.where(s == vmax, lcols, big), axis=1, keepdims=True)
        new_v = jnp.where(out_col == t, vmax, new_v)
        new_c = jnp.where(out_col == t, j * bn + pos, new_c)
        s = jnp.where(lcols == pos, -jnp.inf, s)
    vals_ref[:] = new_v
    cols_ref[:] = new_c


def _topk2_kernel(k: int, cand: int, bn: int, mask_self: bool, n_true: int,
                  c_i_ref, c_j_ref, d_i_ref, d_j_ref, vals_ref, cols_ref):
    i = pl.program_id(0)
    j = pl.program_id(1)
    s = _normalize(_tile_dot(c_i_ref, c_j_ref), d_i_ref, d_j_ref)
    s, _ = _mask_tile(s, i, j, n_true, mask_self)
    _extract_tile_topk(s, j, bn, k, cand, vals_ref, cols_ref)


def _topk2_kernel_kt(k: int, cand: int, bn: int, mask_self: bool,
                     n_true: int, n_kb: int, c_i_ref, c_j_ref, d_i_ref,
                     d_j_ref, vals_ref, cols_ref, acc_ref):
    i = pl.program_id(0)
    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init_acc():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += _tile_dot(c_i_ref, c_j_ref)

    @pl.when(kb == n_kb - 1)
    def _finish():
        s = _normalize(acc_ref[:], d_i_ref, d_j_ref)
        s, _ = _mask_tile(s, i, j, n_true, mask_self)
        _extract_tile_topk(s, j, bn, k, cand, vals_ref, cols_ref)


@functools.partial(
    jax.jit, static_argnames=("k", "mask_self", "interpret")
)
def fused_topk_twopass(
    c: jax.Array,
    rowsums: jax.Array,
    k: int = 10,
    mask_self: bool = True,
    interpret: bool = False,
):
    """Exact per-row top-k via tile-candidate extraction + host-free
    XLA reduction (see block comment above). Requires k <= 16; callers
    fall back to :func:`fused_topk` beyond that. Handles any V by
    tiling the contraction axis when it exceeds one VMEM tile."""
    if k > _CAND:
        raise ValueError(f"fused_topk_twopass supports k <= {_CAND}")
    from . import sparse as _sp

    n, v = c.shape
    bn = _BN_WIDE
    n_pad = _ceil_to(max(n, 8), max(_BM, bn))
    bk = min(_BK, _ceil_to(max(v, 128), 128))
    v_pad = _ceil_to(max(v, 128), bk)
    n_kb = v_pad // bk
    c_p = jnp.zeros((n_pad, v_pad), dtype=jnp.float32).at[:n, :v].set(c)
    d_p = jnp.zeros((n_pad, 1), dtype=jnp.float32).at[:n, 0].set(rowsums)

    n_j = n_pad // bn
    n_bi = n_pad // _BM  # row blocks per column-tile stripe
    grid_ij = (n_bi, n_j)
    common = dict(
        out_shape=(
            jax.ShapeDtypeStruct((n_j * n_pad, _CAND), jnp.float32),
            jax.ShapeDtypeStruct((n_j * n_pad, _CAND), jnp.int32),
        ),
        interpret=interpret,
    )
    if n_kb == 1:
        vals, cols = pl.pallas_call(
            functools.partial(_topk2_kernel, k, _CAND, bn, mask_self, n),
            grid=grid_ij,
            in_specs=[
                pl.BlockSpec((_BM, v_pad), lambda i, j: (i, 0)),
                pl.BlockSpec((bn, v_pad), lambda i, j: (j, 0)),
                pl.BlockSpec((_BM, 1), lambda i, j: (i, 0)),
                pl.BlockSpec((bn, 1), lambda i, j: (j, 0)),
            ],
            out_specs=(
                pl.BlockSpec((_BM, _CAND), lambda i, j: (j * n_bi + i, 0)),
                pl.BlockSpec((_BM, _CAND), lambda i, j: (j * n_bi + i, 0)),
            ),
            **common,
        )(c_p, c_p, d_p, d_p)
    else:
        vals, cols = pl.pallas_call(
            functools.partial(
                _topk2_kernel_kt, k, _CAND, bn, mask_self, n, n_kb
            ),
            grid=grid_ij + (n_kb,),
            in_specs=[
                pl.BlockSpec((_BM, bk), lambda i, j, kb: (i, kb)),
                pl.BlockSpec((bn, bk), lambda i, j, kb: (j, kb)),
                pl.BlockSpec((_BM, 1), lambda i, j, kb: (i, 0)),
                pl.BlockSpec((bn, 1), lambda i, j, kb: (j, 0)),
            ],
            out_specs=(
                pl.BlockSpec(
                    (_BM, _CAND), lambda i, j, kb: (j * n_bi + i, 0)
                ),
                pl.BlockSpec(
                    (_BM, _CAND), lambda i, j, kb: (j * n_bi + i, 0)
                ),
            ),
            scratch_shapes=[pltpu.VMEM((_BM, bn), jnp.float32)],
            **common,
        )(c_p, c_p, d_p, d_p)

    # [n_j·n_pad, C] (stripe-major rows) → per-row candidate lists
    # [n, n_j·C]. Candidate order after the transpose is (tile,
    # desc-value) with in-tile ties at ascending column, so
    # chunked_row_topk's flat-top_k tie-break (lowest candidate index)
    # resolves equal values to the lowest global column.
    vals = (
        vals.reshape(n_j, n_pad, _CAND)
        .transpose(1, 0, 2)
        .reshape(n_pad, n_j * _CAND)
    )
    cols = (
        cols.reshape(n_j, n_pad, _CAND)
        .transpose(1, 0, 2)
        .reshape(n_pad, n_j * _CAND)
    )
    fv, fc = _sp.chunked_row_topk(vals[:n], cols[:n], k=k)
    return fv, fc


# ---------------------------------------------------------------------------
# Rectangular two-pass top-k: one ROW TILE of sources against the whole
# column range — the streaming tier's hot op (config 5: N up to millions,
# V ≪ 128). The XLA fold it replaces (tiny-K GEMM + lax.top_k slabs per
# [T, T] tile) measured ~5.5 s per 8192-row tile at N=1M on a v5e; the
# MXU + packed-extraction kernel does the same row tile in one fused
# sweep. Candidate layout: _GROUP column tiles pack their [bm, 16]
# winners into ONE 128-lane block, so the HBM buffer has no lane-padding
# blowup (a 16-lane minor dim is physically padded 8× by the (8,128)
# HBM tile — see _TWOPASS_CAND_MAX_BYTES).
# ---------------------------------------------------------------------------

_GROUP = _HBM_LANE // _CAND  # column tiles per packed candidate block


def _extract_group_topk(s, base_col, k: int, cand: int, g: int, buf_v, buf_c):
    """Fold the top-``k+1`` of each row of masked score tile ``s`` into
    lane segment ``g`` of the packed [bm, _GROUP·cand] candidate
    buffers (same max-extract rounds and lowest-column tie-break as
    _extract_tile_topk). k+1, not k: the caller drops self-pair
    candidates AFTER extraction, and the tile containing a row's self
    column must still contribute k non-self candidates — with only k
    kept, a top-k that lives entirely in the self tile would lose its
    k-th element."""
    bm = s.shape[0]
    lcols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    out_col = jax.lax.broadcasted_iota(jnp.int32, buf_v.shape, 1)
    big = jnp.int32(2**30)
    for t in range(min(k + 1, cand)):
        vmax = jnp.max(s, axis=1, keepdims=True)
        pos = jnp.min(jnp.where(s == vmax, lcols, big), axis=1, keepdims=True)
        buf_v = jnp.where(out_col == g * cand + t, vmax, buf_v)
        buf_c = jnp.where(out_col == g * cand + t, base_col + pos, buf_c)
        s = jnp.where(lcols == pos, -jnp.inf, s)
    return buf_v, buf_c


def _topk2_rect_kernel(k: int, cand: int, bn: int, group: int, n_true: int,
                       c_i_ref, c_j_ref, d_i_ref, d_j_ref, vals_ref,
                       cols_ref):
    """One [bm × group·bn] stripe: ``group`` MXU tile products, each
    extracted into its packed lane segment. No self-masking here — the
    caller excludes self-pairs on the candidate list (the k+1 kept
    candidates keep that exact).

    The group sweep is a ``fori_loop``, NOT a Python unroll: Mosaic
    stack-allocates every unrolled iteration's score-tile temporaries
    in scoped VMEM, and 8 unrolled groups × (k+1) extraction rounds
    measured 18–20 MB of stack against the 16 MB v5e limit; the loop
    keeps one iteration live."""
    j = pl.program_id(1)
    bm = c_i_ref.shape[0]
    ci = c_i_ref[:]

    def body(g, carry):
        buf_v, buf_c = carry
        cj = c_j_ref[pl.ds(g * bn, bn), :]
        dj = d_j_ref[pl.ds(g * bn, bn), :]
        s = _normalize(
            jnp.dot(
                ci,
                cj.T,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            ),
            d_i_ref,
            dj,
        )
        base_col = (j * group + g) * bn
        cols = base_col + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols < n_true, s, -jnp.inf)
        return _extract_group_topk(s, base_col, k, cand, g, buf_v, buf_c)

    buf_v = jnp.full((bm, group * cand), -jnp.inf, dtype=jnp.float32)
    buf_c = jnp.zeros((bm, group * cand), dtype=jnp.int32)
    buf_v, buf_c = jax.lax.fori_loop(0, group, body, (buf_v, buf_c))
    vals_ref[:] = buf_v
    cols_ref[:] = buf_c


# Column tile per group member. The original fully-unrolled kernel
# blew the 16 MB VMEM stack at bn=512 (19.8 MB) AND bn=256 (18.0 MB) —
# that's what forced the fori_loop, under which only one iteration's
# score-tile temporaries are live. bn=256 is the value validated
# on-chip with the loop; wider tiles are untried there, not impossible.
_RECT_BN = 256
# Candidate-buffer HBM budget (f32+i32, 128-lane packed — no lane
# padding waste). Per row tile of T rows against N columns the buffer
# is (n_pad/stripe)·t_pad rows × 128 lanes × 8 B = n_pad·(t_pad/16) B:
# 4.3 GB at N=1M, tile_rows=8192 (measured to fit alongside dense C
# and the reshape transients on a 16 GB v5e). The budget scales
# inversely with tile_rows — larger N stays on the rect path by
# choosing a smaller row tile.
_RECT_CAND_MAX_BYTES = 4500 << 20


# Widest contraction the rect kernel holds un-tiled: the [group·bn,
# v_pad] column stripe is a 4 MB VMEM block at 512 — comfortable now
# that the group sweep is a fori_loop (one iteration's temporaries
# live). Covers the narrow configs (64-venue config 5, the 384-venue
# canonical bench shape); wider factors take the K-tiled rect kernel
# below (real DBLP has thousands of venues at dblp_large scale —
# /root/reference/dblp/dblp_small.gexf already carries 85 at 1/123rd
# scale — so wide V must keep the fused fast path, not fall back).
_RECT_VMAX = 512


def rect_supported(v: int, k: int) -> bool:
    """Any factor width stays on the rect fast path: v ≤ _RECT_VMAX
    runs the un-tiled stripe kernel, wider V the K-tiled variant
    (contraction tiled at _BK, [bm, stripe] accumulator in VMEM
    scratch). The only hard gate left is self-exclusion headroom on
    the candidate list (k < _CAND)."""
    return k < _CAND


def _rect_vpad(v: int) -> int:
    """Padded contraction width shared by rect_pad_factor and the
    kernel wrapper (they must agree for the pre-padded fast path):
    lane-aligned when the un-tiled kernel serves, _BK-aligned when the
    K-tiled kernel does."""
    v_pad = _ceil_to(max(v, 128), 128)
    if v_pad > _RECT_VMAX:
        v_pad = _ceil_to(v_pad, _BK)
    return v_pad


def rect_pad_factor(c: jax.Array, d: jax.Array):
    """Pad a [N, V] factor and its rowsums ONCE to the rect kernel's
    expected [n_pad, v_pad] / [n_pad] shapes (stripe-aligned rows,
    lane-aligned columns), so per-row-tile kernel calls skip the
    O(N·v_pad) re-pad."""
    n, v = c.shape
    stripe = _GROUP * _RECT_BN
    n_pad = _ceil_to(max(n, 8), stripe)
    v_pad = _rect_vpad(v)
    cc = jnp.zeros((n_pad, v_pad), dtype=jnp.float32).at[:n, :v].set(c)
    dc = jnp.zeros((n_pad,), dtype=jnp.float32).at[:n].set(d)
    return cc, dc


def rect_fits(n_cols: int, tile_rows: int) -> bool:
    """True when one row tile's packed candidate buffer fits the HBM
    budget (the rect analog of :func:`twopass_fits` — without it a
    large-N rank-all would OOM mid-pass instead of taking the fold
    path)."""
    stripe = _GROUP * _RECT_BN
    n_pad = _ceil_to(max(n_cols, 8), stripe)
    t_pad = _ceil_to(max(tile_rows, 8), _BM)
    cand_bytes = (n_pad // stripe) * t_pad * _HBM_LANE * 8
    return cand_bytes <= _RECT_CAND_MAX_BYTES


def _extract_stripe_topk(s, base_col, k: int, lanes: int):
    """Top-``k+1`` of each row of the masked [bm, stripe] score block,
    written into lanes 0..k of fresh [bm, lanes] buffers (-inf beyond;
    global column ids; lowest-column tie-break like ``lax.top_k``).

    Stripe-level extraction is exact for the same reason the per-tile
    variant is: any row's global top-k element is inside its stripe's
    top-(k+1) even after one self-pair drop. The rounds run in a
    ``fori_loop`` — the round temporaries are [bm, stripe] (2 MB at
    256×2048), and Mosaic stack-allocates every unrolled iteration's
    copies (the lesson from _topk2_rect_kernel's group sweep), so only
    one round may be live."""
    bm, stripe = s.shape
    lcols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    out_col = jax.lax.broadcasted_iota(jnp.int32, (bm, lanes), 1)
    big = jnp.int32(2**30)

    def body(t, carry):
        s, buf_v, buf_c = carry
        vmax = jnp.max(s, axis=1, keepdims=True)
        pos = jnp.min(jnp.where(s == vmax, lcols, big), axis=1, keepdims=True)
        buf_v = jnp.where(out_col == t, vmax, buf_v)
        buf_c = jnp.where(out_col == t, base_col + pos, buf_c)
        s = jnp.where(lcols == pos, -jnp.inf, s)
        return s, buf_v, buf_c

    buf_v = jnp.full((bm, lanes), -jnp.inf, dtype=s.dtype)
    buf_c = jnp.zeros((bm, lanes), dtype=jnp.int32)
    _, buf_v, buf_c = jax.lax.fori_loop(
        0, min(k + 1, lanes), body, (s, buf_v, buf_c)
    )
    return buf_v, buf_c


def _topk2_rect_kernel_kt(k: int, lanes: int, stripe: int, n_true: int,
                          n_kb: int, c_i_ref, c_j_ref, d_i_ref, d_j_ref,
                          vals_ref, cols_ref, acc_ref):
    """Wide-V rect stripe: the contraction axis rides the innermost
    grid dim, partial [bm, stripe] products accumulate in VMEM scratch,
    and the stripe is normalized + extracted once on the last K step.
    Unlike the un-tiled kernel there is no per-group lane packing: the
    whole stripe's top-(k+1) lands in one 128-lane block directly, so
    the candidate buffer has the same no-waste HBM layout."""
    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += _tile_dot(c_i_ref, c_j_ref)

    @pl.when(kb == n_kb - 1)
    def _finish():
        s = _normalize(acc_ref[:], d_i_ref, d_j_ref)
        base_col = j * stripe
        cols = base_col + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols < n_true, s, -jnp.inf)
        buf_v, buf_c = _extract_stripe_topk(s, base_col, k, lanes)
        vals_ref[:] = buf_v
        cols_ref[:] = buf_c


@functools.partial(
    jax.jit, static_argnames=("k", "n_true_cols", "interpret")
)
def fused_topk_twopass_rect(
    c_rows: jax.Array,
    c_cols: jax.Array,
    d_rows: jax.Array,
    d_cols: jax.Array,
    row_ids: jax.Array,
    k: int = 10,
    n_true_cols: int | None = None,
    interpret: bool = False,
):
    """Exact per-row top-k of the [T, N] score block
    ``S = 2·(c_rows @ c_colsᵀ) / (d_rows ⊕ d_cols)`` with self-pairs
    excluded, never materializing S.

    c_rows: [T, V] row-tile factor; c_cols: [N, V] full factor;
    d_rows/d_cols: matching rowsums; row_ids: [T] int32 global row
    indices (self-exclusion: any candidate whose column equals its
    row's global id is dropped on the candidate list — exact because
    each tile keeps _CAND > k candidates). Requires rect_supported(V, k).

    Callable inside a ``shard_map`` ONLY with ``check_vma=False`` (the
    ring fold does this): jax's pallas loop discharge does not
    propagate varying-axis metadata, and annotating the out_shapes
    does not rescue the checked mode — verified empirically.
    """
    t, v = c_rows.shape
    n, _ = c_cols.shape
    if not rect_supported(v, k):
        raise ValueError(
            f"fused_topk_twopass_rect requires k<{_CAND}"
        )
    if n_true_cols is None:
        n_true_cols = n
    bn = _RECT_BN
    stripe = _GROUP * bn
    t_pad = _ceil_to(max(t, 8), _BM)
    n_pad = _ceil_to(max(n, 8), stripe)
    v_pad = _rect_vpad(v)
    # Skip the pads when the caller hands kernel-shaped arrays (the
    # streaming backend pre-pads its cached dense C once): re-padding
    # the full column factor here would re-execute an O(N·128) copy on
    # every per-row-tile call.
    if c_rows.shape == (t_pad, v_pad) and c_rows.dtype == jnp.float32:
        cr = c_rows
    else:
        cr = (
            jnp.zeros((t_pad, v_pad), dtype=jnp.float32)
            .at[:t, :v].set(c_rows)
        )
    if c_cols.shape == (n_pad, v_pad) and c_cols.dtype == jnp.float32:
        cc = c_cols
    else:
        cc = (
            jnp.zeros((n_pad, v_pad), dtype=jnp.float32)
            .at[:n, :v].set(c_cols)
        )
    if d_rows.shape == (t_pad,) and d_rows.dtype == jnp.float32:
        dr = d_rows.reshape(t_pad, 1)
    else:
        dr = jnp.zeros((t_pad, 1), dtype=jnp.float32).at[:t, 0].set(d_rows)
    if d_cols.shape == (n_pad,) and d_cols.dtype == jnp.float32:
        dc = d_cols.reshape(n_pad, 1)
    else:
        dc = jnp.zeros((n_pad, 1), dtype=jnp.float32).at[:n, 0].set(d_cols)

    n_bi = t_pad // _BM
    n_js = n_pad // stripe
    out_shape = (
        jax.ShapeDtypeStruct((n_js * t_pad, _GROUP * _CAND), jnp.float32),
        jax.ShapeDtypeStruct((n_js * t_pad, _GROUP * _CAND), jnp.int32),
    )
    out_specs = (
        pl.BlockSpec((_BM, _GROUP * _CAND), lambda i, j, *_: (j * n_bi + i, 0)),
        pl.BlockSpec((_BM, _GROUP * _CAND), lambda i, j, *_: (j * n_bi + i, 0)),
    )
    if v_pad <= _RECT_VMAX:
        vals, cols = pl.pallas_call(
            functools.partial(
                _topk2_rect_kernel, k, _CAND, bn, _GROUP, n_true_cols
            ),
            grid=(n_bi, n_js),
            in_specs=[
                pl.BlockSpec((_BM, v_pad), lambda i, j: (i, 0)),
                pl.BlockSpec((stripe, v_pad), lambda i, j: (j, 0)),
                pl.BlockSpec((_BM, 1), lambda i, j: (i, 0)),
                pl.BlockSpec((stripe, 1), lambda i, j: (j, 0)),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(cr, cc, dr, dc)
    else:
        # Wide V: tile the contraction at _BK (innermost grid axis),
        # accumulate the [bm, stripe] stripe in VMEM scratch (2 MB at
        # 256×2048 — alongside the [stripe, _BK] column block's 4 MB
        # and the [bm, _BK] row block, comfortably inside VMEM at any
        # factor width).
        n_kb = v_pad // _BK
        vals, cols = pl.pallas_call(
            functools.partial(
                _topk2_rect_kernel_kt, k, _GROUP * _CAND, stripe,
                n_true_cols, n_kb
            ),
            grid=(n_bi, n_js, n_kb),
            in_specs=[
                pl.BlockSpec((_BM, _BK), lambda i, j, kb: (i, kb)),
                pl.BlockSpec((stripe, _BK), lambda i, j, kb: (j, kb)),
                pl.BlockSpec((_BM, 1), lambda i, j, kb: (i, 0)),
                pl.BlockSpec((stripe, 1), lambda i, j, kb: (j, 0)),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((_BM, stripe), jnp.float32)],
            interpret=interpret,
        )(cr, cc, dr, dc)

    width = n_js * _GROUP * _CAND
    vals = (
        vals.reshape(n_js, t_pad, _GROUP * _CAND)
        .transpose(1, 0, 2)
        .reshape(t_pad, width)[:t]
    )
    cols = (
        cols.reshape(n_js, t_pad, _GROUP * _CAND)
        .transpose(1, 0, 2)
        .reshape(t_pad, width)[:t]
    )
    # Self-exclusion on the candidate list (exact: each tile kept
    # _CAND > k candidates, so dropping one leaves >= k).
    vals = jnp.where(cols == row_ids[:, None], -jnp.inf, vals)
    from . import sparse as _sp

    return _sp.chunked_row_topk(vals, cols, k=k)


def pallas_supported() -> bool:
    """Pallas TPU kernels need a real TPU backend; elsewhere callers use
    interpret mode (tests) or the XLA reference."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# VMEM is ~16 MB/core; the single-pass kernels hold two [tile, v_pad] C
# blocks plus the output tile. Wider half-chain factors (e.g. APA's
# author×paper C) take the *_ktiled variants, which tile the contraction
# axis and fit at any V.
_VMEM_BUDGET_BYTES = 12 << 20


def fits_vmem(v: int) -> bool:
    """True when V fits the single-pass kernels' VMEM budget; callers
    switch to the K-tiled kernels (not the XLA path) otherwise."""
    v_pad = _ceil_to(max(v, 128), 128)
    needed = (_BM + _BN) * v_pad * 4 + _BM * _BN * 4
    return needed <= _VMEM_BUDGET_BYTES
