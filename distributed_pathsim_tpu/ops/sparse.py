"""Sparse half-chain machinery: host COO folding + device scatter/GEMM.

TPU-first split of labor (BASELINE.json config 5): the *structure* of the
half-chain product — which (source, venue)-style pairs exist — is a
sort/searchsorted join on the host (O(nnz log nnz), numpy); the *numbers*
— duplicate accumulation, row sums, all-pairs tiles — run on device as
scatter-adds and dense GEMMs over static-shaped tiles. This replaces the
reference's per-query 4-way distributed hash join with one precomputed
join reused by every query, and it never builds a P×V or N×N dense
intermediate.

Why not jax.experimental.sparse BCOO end-to-end: BCOO sparse-sparse
products on TPU lower to gather/scatter loops XLA can't tile onto the
MXU; folding structure on host and batching the arithmetic into dense
tiles keeps the FLOPs where the hardware wants them.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class COOMatrix:
    """Weighted COO with possibly-duplicate coordinates ("unsummed")."""

    rows: np.ndarray  # int [nnz]
    cols: np.ndarray  # int [nnz]
    weights: np.ndarray  # float64 [nnz]
    shape: tuple[int, int]

    def summed(self) -> "COOMatrix":
        """Coalesce duplicates (host)."""
        key = self.rows.astype(np.int64) * self.shape[1] + self.cols
        uniq, inv = np.unique(key, return_inverse=True)
        w = np.zeros(uniq.shape[0], dtype=np.float64)
        np.add.at(w, inv, self.weights)
        return COOMatrix(
            rows=(uniq // self.shape[1]).astype(np.int64),
            cols=(uniq % self.shape[1]).astype(np.int64),
            weights=w,
            shape=self.shape,
        )


def coo_from_block(block) -> COOMatrix:
    return COOMatrix(
        rows=block.rows.astype(np.int64),
        cols=block.cols.astype(np.int64),
        weights=np.ones(block.rows.shape[0], dtype=np.float64),
        shape=block.shape,
    )


def coo_matmul(a: COOMatrix, b: COOMatrix) -> COOMatrix:
    """Host COO·COO join on the shared middle index.

    Sort b by row, locate each a-edge's matching slice via searchsorted,
    expand pairs, multiply weights. Output is unsummed (duplicates carry
    partial products) — coalesce with .summed() when needed.
    """
    if a.shape[1] != b.shape[0]:
        raise ValueError(f"shape mismatch {a.shape} @ {b.shape}")
    order = np.argsort(b.rows, kind="stable")
    b_rows = b.rows[order]
    b_cols = b.cols[order]
    b_w = b.weights[order]

    start = np.searchsorted(b_rows, a.cols, side="left")
    stop = np.searchsorted(b_rows, a.cols, side="right")
    counts = stop - start
    total = int(counts.sum())

    # For each a-edge i, take b entries [start[i], stop[i]).
    a_idx = np.repeat(np.arange(a.rows.shape[0]), counts)
    # offsets within each slice: ramp resetting at slice boundaries
    cum = np.concatenate([[0], np.cumsum(counts)])
    within = np.arange(total) - np.repeat(cum[:-1], counts)
    b_idx = np.repeat(start, counts) + within

    return COOMatrix(
        rows=a.rows[a_idx],
        cols=b_cols[b_idx],
        weights=a.weights[a_idx] * b_w[b_idx],
        shape=(a.shape[0], b.shape[1]),
    )


def _matmul_summed(a: COOMatrix, b: COOMatrix) -> COOMatrix:
    """One coalesced COO product: the native C++ SpGEMM when built
    (identical output: row-major sorted, exact integer accumulation),
    else the numpy join."""
    from ..native import coo_native

    if coo_native.available():
        return coo_native.coo_matmul_summed(a, b)
    return coo_matmul(a, b).summed()


def fold_half_chain(blocks) -> COOMatrix:
    """Fold oriented COO blocks left-to-right into the half-chain factor C
    (coalesced)."""
    acc = blocks[0]
    for b in blocks[1:]:
        acc = _matmul_summed(acc, b)
    return acc


# ---------------------------------------------------------------------------
# Delta algebra: O(Δ·deg) updates to the half-chain factor
# ---------------------------------------------------------------------------
#
# The half-chain factor C is the one precomputed join every backend
# shares; a graph delta must patch it without refolding the chain. For a
# 2-block half C = A·B the product rule gives an exact COO identity:
#
#     ΔC = ΔA·B_new + A_old·ΔB
#
# where ΔA/ΔB carry SIGNED weights (+1 per added edge, −1 per removed
# edge). Each term is a coo_matmul over only the delta's nnz — O(Δ·deg),
# never O(nnz). For a 1-block half, ΔC = ΔA directly. Longer halves
# (none exist in the DBLP schema family) would need the intermediate
# partial products the backends don't keep, so they diff the refolded
# factor instead — still recompile-free, just not O(Δ).


def coo_nonzero(c: COOMatrix) -> COOMatrix:
    """Drop explicit zeros (a removed-then-unchanged coordinate after
    coalescing) so downstream support-based reasoning sees true nnz."""
    keep = c.weights != 0.0
    if keep.all():
        return c
    return COOMatrix(
        rows=c.rows[keep], cols=c.cols[keep], weights=c.weights[keep],
        shape=c.shape,
    )


def coo_delta_fold(
    old_blocks: list[COOMatrix], delta_blocks: list[COOMatrix]
) -> COOMatrix:
    """ΔC for a half chain, by the product rule (coalesced, zero-free,
    signed). ``old_blocks`` are the PRE-delta oriented blocks,
    ``delta_blocks`` the signed edge deltas in the same orientation
    (empty deltas allowed — nnz 0)."""
    if len(old_blocks) == 1:
        return coo_nonzero(delta_blocks[0].summed())
    if len(old_blocks) == 2:
        a_old, b_old = old_blocks
        da, db = delta_blocks
        b_new = COOMatrix(
            rows=np.concatenate([b_old.rows, db.rows]),
            cols=np.concatenate([b_old.cols, db.cols]),
            weights=np.concatenate([b_old.weights, db.weights]),
            shape=b_old.shape,
        )
        term1 = coo_matmul(da, b_new)
        term2 = coo_matmul(a_old, db)
        merged = COOMatrix(
            rows=np.concatenate([term1.rows, term2.rows]),
            cols=np.concatenate([term1.cols, term2.cols]),
            weights=np.concatenate([term1.weights, term2.weights]),
            shape=term1.shape,
        )
        return coo_nonzero(merged.summed())
    # General chain: diff the refolded factor (exact, not O(Δ) — the
    # backends keep no intermediate partials to apply the product rule
    # against). Callers treat a wide ΔC like any other; recompile-free
    # serving is preserved either way. The refold goes through the
    # planner doorway (plan-ordered, MP001) like every other fold.
    from . import planner

    new_blocks = []
    for ob, db in zip(old_blocks, delta_blocks):
        new_blocks.append(
            COOMatrix(
                rows=np.concatenate([ob.rows, db.rows]),
                cols=np.concatenate([ob.cols, db.cols]),
                weights=np.concatenate([ob.weights, db.weights]),
                shape=ob.shape,
            ).summed()
        )
    c_new = planner.fold_blocks(new_blocks)
    c_old = planner.fold_blocks([b.summed() for b in old_blocks])
    merged = COOMatrix(
        rows=np.concatenate([c_new.rows, c_old.rows]),
        cols=np.concatenate([c_new.cols, c_old.cols]),
        weights=np.concatenate([c_new.weights, -c_old.weights]),
        shape=c_new.shape,
    )
    return coo_nonzero(merged.summed())


def coo_apply_delta(c: COOMatrix, delta_c: COOMatrix) -> COOMatrix:
    """Patch C row-granularly: rows untouched by ΔC are kept verbatim
    (one boolean mask + memcpy — no global re-sort, no global
    coalesce); touched rows are re-coalesced from their old entries
    plus ΔC. Exact for signed integer weights; entries cancelled to
    zero are dropped so the patched factor's support equals a rebuilt
    factor's."""
    if delta_c.rows.shape[0] == 0:
        return c
    if c.shape != delta_c.shape:
        raise ValueError(f"delta shape {delta_c.shape} != factor {c.shape}")
    touched = np.unique(delta_c.rows)
    hit = np.isin(c.rows, touched)
    patched = COOMatrix(
        rows=np.concatenate([c.rows[hit], delta_c.rows]),
        cols=np.concatenate([c.cols[hit], delta_c.cols]),
        weights=np.concatenate([c.weights[hit], delta_c.weights]),
        shape=c.shape,
    )
    patched = coo_nonzero(patched.summed())
    return COOMatrix(
        rows=np.concatenate([c.rows[~hit], patched.rows]),
        cols=np.concatenate([c.cols[~hit], patched.cols]),
        weights=np.concatenate([c.weights[~hit], patched.weights]),
        shape=c.shape,
    )


def affected_source_rows(
    c_old: COOMatrix,
    c_new: COOMatrix,
    delta_c: COOMatrix,
    n_logical: int,
) -> np.ndarray:
    """Sound superset of the source rows whose SCORE row changes under
    ΔC, for both denominator variants. Derivation (M = C·Cᵀ, d the
    rowsum or diagonal denominator):

    - R = rows of ΔC: their counts row and denominator change.
    - d may also change for rows supported on Δcolsum's columns
      (rowsum variant: d_i = Σ_v C[i,v]·colsum[v]).
    - score(i, j) = 2M[i,j]/(d_i+d_j) changes for i ∉ R∪D only through
      M[i,j] (j ∈ R, needs C[i] ∩ cols(ΔC)) or d_j (j ∈ D, needs
      M[i,j] ≠ 0, i.e. C[i] ∩ supp(C[j])).

    So with W = cols(ΔC) ∪ cols(C rows in R∪D), every changed score row
    lies in R ∪ {i : C_new[i] has support on W} — a couple of O(nnz)
    vectorized masks, no score is ever computed. (A zero M entry stays
    score 0 whatever the denominators do, which is what bounds the
    2-hop spread to supp(C[j]).)"""
    if delta_c.rows.shape[0] == 0:
        return np.empty(0, dtype=np.int64)
    r_rows = np.unique(delta_c.rows)
    dcolsum = np.zeros(c_old.shape[1], dtype=np.float64)
    np.add.at(dcolsum, delta_c.cols, delta_c.weights)
    dv_cols = np.flatnonzero(dcolsum)
    # D superset: rows of the NEW factor supported on Δcolsum columns,
    # plus R (removals can only shrink support of rows already in R).
    col_hit = np.zeros(c_old.shape[1], dtype=bool)
    col_hit[np.unique(delta_c.cols)] = True
    col_hit[dv_cols] = True
    d_sup = np.union1d(r_rows, np.unique(c_new.rows[col_hit[c_new.cols]]))
    # W: ΔC's columns plus every column supported by a row in R ∪ D
    # (old and new support both, so removed overlap still invalidates).
    row_hit = np.zeros(c_old.shape[0], dtype=bool)
    row_hit[d_sup] = True
    w_mask = col_hit.copy()
    w_mask[np.unique(c_old.cols[row_hit[c_old.rows]])] = True
    w_mask[np.unique(c_new.cols[row_hit[c_new.rows]])] = True
    affected = np.union1d(
        r_rows, np.unique(c_new.rows[w_mask[c_new.cols]])
    )
    affected = np.union1d(affected, np.unique(c_old.rows[w_mask[c_old.cols]]))
    return affected[affected < n_logical].astype(np.int64)


def dense_half_chain(hin, metapath, dtype=np.float32) -> np.ndarray:
    """DEPRECATED shim → :func:`ops.planner.dense_half` (the planner
    owns chain evaluation since the metapath-IR refactor, DESIGN.md
    §28). Kept one release for external callers/tests."""
    from . import planner

    return planner.dense_half(hin, metapath, dtype=dtype)


def half_chain_coo(hin, metapath) -> COOMatrix:
    """DEPRECATED shim → :func:`ops.planner.fold_half` (plan-ordered,
    bit-identical to the historical left-to-right fold). This was the
    one structural join the whole run needs — the sparse analog of the
    reference's per-query 4-way motif join (DPathSim_APVPA.py:72-84);
    it now lives behind the planner doorway so sub-chain memoization
    and DP ordering apply uniformly. Kept one release for external
    callers/tests."""
    from . import planner

    return planner.fold_half(hin, metapath)


# ---------------------------------------------------------------------------
# Device side: static-shaped scatter + tile GEMMs
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_rows", "n_cols"))
def densify_tile(rows, cols, weights, n_rows: int, n_cols: int):
    """Scatter a (padded) COO slice into a dense [n_rows, n_cols] tile.
    Padding entries must carry weight 0 (they scatter harmlessly)."""
    out = jnp.zeros((n_rows, n_cols), dtype=weights.dtype)
    return out.at[rows, cols].add(weights)


@jax.jit
def tile_outer(c_tile_i, c_tile_j):
    """One [Ti, Tj] tile of M = C Cᵀ."""
    with jax.default_matmul_precision("highest"):
        return jnp.matmul(c_tile_i, c_tile_j.T)


@jax.jit
def tile_rowsums(c_tile, colsum_total):
    with jax.default_matmul_precision("highest"):
        return jnp.matmul(c_tile, colsum_total)


@functools.partial(jax.jit, static_argnames=("k",))
def tile_topk(scores_tile, k: int):
    """Per-row top-k of a scores tile: values and column indices."""
    return jax.lax.top_k(scores_tile, k)


def chunked_row_topk(s, cols, k: int, chunk: int = 512):
    """Exact per-row top-k of a wide tile, hierarchically: top-k inside
    each ``chunk``-wide column slab (narrow, cheap sorts), then top-k
    over the surviving n_chunks·k candidates. Any global top-k element
    is its slab's top-k, so this is exact — but the sort work drops from
    O(W log W) per row to O(W log chunk), which on both CPU and TPU is
    the difference between the top-k and the GEMM dominating a
    streaming pass. Tie-breaks match a flat ``lax.top_k`` (ascending
    column): slabs are scanned in column order and ``top_k`` prefers
    earlier (lower-column) positions on equal values.

    ``cols`` carries each element's global column id. Returns
    ([T, kk] values, [T, kk] global columns) with kk = min(k, W).
    """
    t, w = s.shape
    if w <= max(chunk, k):  # narrow tile: flat top_k is already cheap
        kk = min(k, w)
        v, p = jax.lax.top_k(s, kk)
        return v, jnp.take_along_axis(cols, p, axis=1)
    pad = (-w) % chunk
    if pad:
        s = jnp.pad(s, ((0, 0), (0, pad)), constant_values=-jnp.inf)
        # Continue each row's column ids past the edge (not constant 0):
        # if a padding slot is ever selected (row with fewer than k
        # candidates at pathological k/chunk combinations), it must not
        # alias global column 0 — the flat-lax.top_k contract reports
        # in-order positions, and a monotone continuation preserves that.
        cont = cols[:, -1:] + 1 + jnp.arange(pad, dtype=cols.dtype)
        cols = jnp.concatenate([cols, cont], axis=1)
    n_chunks = s.shape[1] // chunk
    kk = min(k, chunk)
    v3, p3 = jax.lax.top_k(s.reshape(t, n_chunks, chunk), kk)
    c3 = jnp.take_along_axis(cols.reshape(t, n_chunks, chunk), p3, axis=2)
    cand_v = v3.reshape(t, n_chunks * kk)
    cand_c = c3.reshape(t, n_chunks * kk)
    kf = min(k, cand_v.shape[1])
    v, p = jax.lax.top_k(cand_v, kf)
    return v, jnp.take_along_axis(cand_c, p, axis=1)


@functools.partial(jax.jit, static_argnames=("k", "n_true"))
def stream_merge_topk_pair(ci, cj, di, dj, bi_v, bi_i, bj_v, bj_i,
                           i0, j0, k: int, n_true: int):
    """Score ONE [Ti, Tj] tile and fold it into BOTH running top-ks:
    tile i's rows directly and tile j's rows via the transpose — the
    score matrix is symmetric (M = C·Cᵀ, denom symmetric), so one GEMM
    serves two row blocks. This is the off-diagonal workhorse of the
    symmetric streaming pass: half the GEMMs of the naive full sweep.
    """
    with jax.default_matmul_precision("highest"):
        m = jnp.matmul(ci, cj.T)
    denom = di[:, None] + dj[None, :]
    s = jnp.where(denom > 0, 2.0 * m / jnp.where(denom > 0, denom, 1.0), 0.0)
    rows = i0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = j0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    # Mask padding on BOTH axes (each is the column axis of one of the
    # two folds) and self-pairs (symmetric by construction).
    s = jnp.where(cols >= n_true, -jnp.inf, s)
    s = jnp.where(rows >= n_true, -jnp.inf, s)
    s = jnp.where(rows == cols, -jnp.inf, s)
    tile_v, tile_i = chunked_row_topk(s, cols, k)
    merged_v = jnp.concatenate([bi_v, tile_v], axis=1)
    merged_i = jnp.concatenate([bi_i, tile_i], axis=1)
    v, p = jax.lax.top_k(merged_v, k)
    bi_v, bi_i = v, jnp.take_along_axis(merged_i, p, axis=1)

    st = s.T  # [Tj, Ti]; columns of the transposed view are tile i rows
    tile_vt, tile_it = chunked_row_topk(st, rows.T, k)
    merged_v = jnp.concatenate([bj_v, tile_vt], axis=1)
    merged_i = jnp.concatenate([bj_i, tile_it], axis=1)
    v, p = jax.lax.top_k(merged_v, k)
    bj_v, bj_i = v, jnp.take_along_axis(merged_i, p, axis=1)
    return bi_v, bi_i, bj_v, bj_i


def _fold_score_tile(ci, cj, di, dj, best_v, best_i, i0, j0,
                     k: int, n_true: int):
    """The shared fold: GEMM, normalize, mask (self-pairs + padding
    columns ≥ n_true), hierarchical per-tile top-k, merge with the
    carried [Ti, k] best. One definition serves both the per-tile
    dispatch path and the scanned row-tile path so their numerics (and
    tie-breaks) can never drift apart."""
    with jax.default_matmul_precision("highest"):
        m = jnp.matmul(ci, cj.T)
    denom = di[:, None] + dj[None, :]
    s = jnp.where(denom > 0, 2.0 * m / jnp.where(denom > 0, denom, 1.0), 0.0)
    rows = i0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = j0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(cols >= n_true, -jnp.inf, s)
    s = jnp.where(rows == cols, -jnp.inf, s)
    # Hierarchical prefilter keeps the expensive sort narrow; the final
    # merge with the carried best is over ≤ k + n_chunks·k candidates.
    tile_v, tile_i = chunked_row_topk(s, cols, k)
    merged_v = jnp.concatenate([best_v, tile_v], axis=1)
    merged_i = jnp.concatenate([best_i, tile_i], axis=1)
    v, p = jax.lax.top_k(merged_v, k)
    return v, jnp.take_along_axis(merged_i, p, axis=1)


@functools.partial(jax.jit, static_argnames=("k", "n_true"))
def stream_merge_topk(ci, cj, di, dj, best_v, best_i, i0, j0,
                      k: int, n_true: int):
    """Fold one [Ti, Tj] score tile into the running per-row top-k,
    entirely on device. Only the final [Ti, k] result ever reaches the
    host — O(N·k) transfer for the whole streaming pass instead of
    O(N²) score traffic.

    i0/j0 are traced scalars so every (i, j) tile pair reuses one
    compiled program.
    """
    return _fold_score_tile(ci, cj, di, dj, best_v, best_i, i0, j0,
                            k, n_true)


@functools.partial(
    jax.jit, static_argnames=("k", "n_true", "tile_rows")
)
def stream_row_tile_topk(c_all, d_all, i0, k: int, n_true: int,
                         tile_rows: int):
    """One row tile's top-k in ONE dispatch: ``lax.scan`` the shared
    fold over every column tile of the device-resident dense C.

    Cuts the per-(i, j) dispatch loop's n_tiles² host→device round
    trips to n_tiles — but measured only 756 s → 740 s at N=1M on the
    tunneled v5e: the pass is compute-bound in this fold's tiny-K GEMM
    + ``lax.top_k`` slab sorts, which is what motivated the rectangular
    Pallas kernel (162 s; ``pallas_kernels.fused_topk_twopass_rect``,
    DESIGN.md §11). Kept as the general-dtype / wide-V fallback.
    Requires dense C on device (caller gates on its byte size);
    identical fold order and numerics to the per-tile path by
    construction.
    """
    n_pad, _ = c_all.shape
    n_tiles = n_pad // tile_rows
    i0 = jnp.asarray(i0, dtype=jnp.int32)
    zero = jnp.int32(0)  # literal 0 would trace as int64 under x64
    ci = jax.lax.dynamic_slice(
        c_all, (i0, zero), (tile_rows, c_all.shape[1])
    )
    di = jax.lax.dynamic_slice(d_all, (i0,), (tile_rows,))
    init = (
        jnp.full((tile_rows, k), -jnp.inf, dtype=c_all.dtype),
        jnp.zeros((tile_rows, k), dtype=jnp.int32),
    )
    j0s = jnp.arange(n_tiles, dtype=jnp.int32) * tile_rows

    def body(carry, j0):
        best_v, best_i = carry
        cj = jax.lax.dynamic_slice(
            c_all, (j0, zero), (tile_rows, c_all.shape[1])
        )
        dj = jax.lax.dynamic_slice(d_all, (j0,), (tile_rows,))
        return _fold_score_tile(ci, cj, di, dj, best_v, best_i,
                                i0, j0, k, n_true), None

    (bv, bi), _ = jax.lax.scan(body, init, j0s)
    return bv, bi


class TiledHalfChain:
    """Row-tiled dense view of a sparse half-chain factor C [N, V].

    Host keeps C as CSR-sorted COO — or, behind the ``factor_format``
    knob, as a compressed :class:`~.packed.PackedFactor` whose chunks
    align with the tile rows, in which case each tile's COO span is
    decoded transiently through the sanctioned accessors and the full
    24-byte/nnz arrays are never resident (the whole point of the
    compressed formats, DESIGN.md §29). Tiles of ``tile_rows`` rows
    are densified on device on demand either way; the device programs,
    scatter-pad buckets, and numerics are identical by construction.
    V (the contracted output width, e.g. #venues) is assumed tileable
    as one dense axis — it is orders of magnitude smaller than N in
    every target config.
    """

    def __init__(
        self,
        c,
        tile_rows: int = 4096,
        dtype=jnp.float32,
        max_cached_tiles: int | None = None,
        exact_counts: bool = True,
        nnz_bucket_floor: int | None = None,
    ):
        from . import packed as _packed

        self.n, self.v = c.shape
        self.tile_rows = int(tile_rows)
        self.dtype = dtype
        self._packed = c if _packed.is_packed(c) else None
        self.n_tiles = (self.n + self.tile_rows - 1) // self.tile_rows
        if self._packed is None:
            order = np.argsort(c.rows, kind="stable")
            self._rows = c.rows[order]
            self._cols = c.cols[order]
            self._weights = c.weights[order]
            # per-tile COO extents
            bounds = np.arange(self.n_tiles + 1) * self.tile_rows
            self._tile_start = np.searchsorted(
                self._rows, bounds[:-1], side="left"
            )
            self._tile_stop = np.searchsorted(
                self._rows, bounds[1:], side="left"
            )
            tile_nnz = self._tile_stop - self._tile_start
        else:
            self._rows = self._cols = self._weights = None
            self._tile_start = self._tile_stop = None
            tile_nnz = np.asarray([
                _packed.row_range_nnz(
                    c, i * self.tile_rows, (i + 1) * self.tile_rows
                )
                for i in range(self.n_tiles)
            ], dtype=np.int64)
        max_nnz = int(tile_nnz.max()) if self.n_tiles else 0
        # Round the per-tile scatter pad up to a power of two: the
        # densify_tile program's traced shape is this pad, so a graph
        # delta that nudges the densest tile's nnz would otherwise
        # recompile the scatter on every update. Pow-of-two buckets mean
        # steady-state deltas reuse the compiled program; the extra pad
        # entries carry weight 0 and scatter harmlessly. The bucket
        # FLOOR is a tuned knob (``sparse_nnz_floor``): a higher floor
        # wastes pad entries but keeps more delta-drifted nnz inside
        # one compiled scatter program.
        if nnz_bucket_floor is None:
            from .. import tuning

            nnz_bucket_floor = int(
                tuning.choose(
                    "sparse_nnz_floor", n=self.n, v=self.v,
                    nnz=_packed.factor_nnz(c), default=1,
                )
            )
        self._nnz_bucket_floor = max(1, int(nnz_bucket_floor))
        self._max_nnz = (
            max(self._nnz_bucket_floor, 1 << (max_nnz - 1).bit_length())
            if max_nnz else 0
        )
        # Bounded LRU of densified tiles: default keeps ≤256 MB of C tiles
        # on device, so streaming passes over huge N don't accumulate the
        # whole dense C (which would defeat the tiled design).
        if max_cached_tiles is None:
            tile_bytes = self.tile_rows * self.v * np.dtype(dtype).itemsize
            max_cached_tiles = max(2, (256 << 20) // max(tile_bytes, 1))
        self._max_cached = int(max_cached_tiles)
        self._cache: dict[int, jax.Array] = {}  # insertion-ordered → LRU
        # Exact global column totals, accumulated in f64 on host: rowsums
        # are C @ colsum_total and must stay integer-exact. The packed
        # factor carries its exact colsum (kept patched by the delta
        # path); the COO path accumulates it here — same numbers.
        if self._packed is not None:
            colsum = np.asarray(
                _packed.factor_colsum(self._packed), dtype=np.float64
            )
        else:
            colsum = np.zeros(self.v, dtype=np.float64)
            np.add.at(colsum, self._cols, self._weights)
        self.colsum_total = colsum
        # f32 carries exact integers only to 2^24; a silently truncated
        # count would corrupt every downstream score, so refuse loudly.
        # Cheap bound first: c[i,v] ≤ colsum[v] gives
        # rowsum_i = Σ_v c[i,v]·colsum[v] ≤ Σ_v colsum[v]²  (colsum.sum()
        # is NOT a bound — C entries are multiplicities, not 0/1).
        #
        # ``exact_counts=False`` waives the guard: PathSim scores are
        # invariant under C → αC (M and d are both quadratic in C), so
        # what f32 loses on huge counts is only rounding — relative
        # error ~√V·2⁻²⁴ per score (~1e-6 at V=64), inside the ≤1e-5
        # gate. Rankings may swap near-exact ties. This is the intended
        # regime for the million-author configuration, where counts
        # exceed 2^24 by construction but exact integers don't matter.
        from . import chain as _chain

        if exact_counts and _chain.effective_device_dtype(dtype) == np.float32:
            if float((colsum**2).sum()) >= _chain.F32_EXACT_INT_MAX:
                self._check_exact_rowsums(dtype)

    def _check_exact_rowsums(self, dtype) -> None:
        """Tight per-row check, only run when the cheap bound trips."""
        from . import chain as _chain
        from . import packed as _packed

        if self._packed is not None:
            rs = _packed.factor_rowsums_weighted(
                self._packed, self.colsum_total
            )
        else:
            rs = np.zeros(self.n, dtype=np.float64)
            np.add.at(
                rs, self._rows, self._weights * self.colsum_total[self._cols]
            )
        _chain.check_exact_counts(rs.max(initial=0.0), dtype)

    def _tile_span(self, i: int):
        """Tile i's COO span as (local rows, cols, f64 weights) —
        sliced views on the resident COO, or a transient decode of the
        packed chunks the span touches."""
        if self._packed is None:
            s, e = int(self._tile_start[i]), int(self._tile_stop[i])
            return (
                self._rows[s:e] - i * self.tile_rows,
                self._cols[s:e],
                self._weights[s:e],
            )
        from . import packed as _packed

        span = _packed.row_slice(
            self._packed, i * self.tile_rows, (i + 1) * self.tile_rows
        )
        return span.rows - i * self.tile_rows, span.cols, span.weights

    def tile(self, i: int) -> jax.Array:
        """Dense [tile_rows, V] tile i of C (padded rows are zero)."""
        if i in self._cache:
            self._cache[i] = self._cache.pop(i)  # refresh LRU position
            return self._cache[i]
        t_rows, t_cols, t_w = self._tile_span(i)
        nnz = t_rows.shape[0]
        # Pad every tile's COO slice to the same max nnz so one compiled
        # scatter program serves all tiles (static shapes for XLA).
        rows = np.zeros(self._max_nnz, dtype=np.int32)
        cols = np.zeros(self._max_nnz, dtype=np.int32)
        w = np.zeros(self._max_nnz, dtype=np.float64)
        rows[:nnz] = t_rows
        cols[:nnz] = t_cols
        w[:nnz] = t_w
        t = densify_tile(
            jnp.asarray(rows),
            jnp.asarray(cols),
            jnp.asarray(w, dtype=self.dtype),
            n_rows=self.tile_rows,
            n_cols=self.v,
        )
        while len(self._cache) >= self._max_cached:
            self._cache.pop(next(iter(self._cache)))  # evict LRU
        self._cache[i] = t
        return t

    def dense_bytes(self) -> int:
        """Device bytes of the full padded dense C [n_tiles·tile_rows, V]."""
        return (
            self.n_tiles * self.tile_rows * self.v
            * np.dtype(self.dtype).itemsize
        )

    def dense_device(self) -> jax.Array:
        """The whole dense C on device, scatter-assembled once from the
        COO factor (O(nnz) transfer). Deliberately OUTSIDE the tile LRU
        budget: callers gate on :meth:`dense_bytes` — at V ≪ N the dense
        factor is tiny relative to any score tile work (268 MB at 1M
        authors, V=64, f32) and holding it enables the scanned streaming
        pass (one dispatch per row tile instead of n_tiles²)."""
        if getattr(self, "_dense_c", None) is None:
            if self._packed is None:
                rows, cols, w = self._rows, self._cols, self._weights
            else:
                # one transient decode; the dense device factor it
                # feeds is strictly larger than the decoded arrays
                from . import packed as _packed

                span = _packed.as_coo(self._packed)
                rows, cols, w = span.rows, span.cols, span.weights
            self._dense_c = densify_tile(
                jnp.asarray(rows, dtype=jnp.int32),
                jnp.asarray(cols, dtype=jnp.int32),
                jnp.asarray(w, dtype=self.dtype),
                n_rows=self.n_tiles * self.tile_rows,
                n_cols=self.v,
            )
        return self._dense_c

    def drop_dense(self) -> None:
        """Release the cached dense C. A caller that re-padded the
        factor to kernel shape holds the only copy it needs — keeping
        both would double the factor's HBM residency for the whole pass
        (unpadded + lane-padded ≈ 0.8 GB combined at 1M authors, V=64).
        The next :meth:`dense_device` call rebuilds from COO (O(nnz))."""
        self._dense_c = None

    def rowsums(self) -> np.ndarray:
        out = np.zeros(self.n_tiles * self.tile_rows, dtype=np.float64)
        total = jnp.asarray(self.colsum_total, dtype=self.dtype)
        for i in range(self.n_tiles):
            out[i * self.tile_rows : (i + 1) * self.tile_rows] = np.asarray(
                tile_rowsums(self.tile(i), total), dtype=np.float64
            )
        return out[: self.n]

    def m_tile(self, i: int, j: int) -> jax.Array:
        return tile_outer(self.tile(i), self.tile(j))
