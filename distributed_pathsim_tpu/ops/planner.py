"""Sparsity-aware metapath evaluation planner (DESIGN.md §28).

Before this layer every chain evaluation in the package was code: the
backends called the ``ops/chain.py`` fold primitives directly, always
left-to-right, and the serving tier could answer exactly the one
metapath its backend was built for. Atrapos (arXiv:2201.04058) makes
the case this module implements: metapath evaluation cost is dominated
by the *association order* of the adjacency-matrix chain, the right
order is predictable from cheap per-factor sparsity statistics, and a
workload of concurrent metapath queries shares sub-chains worth
memoizing. So the chain becomes **data**:

- :func:`plan_metapath` compiles a :class:`~.metapath.MetaPath` plus
  per-factor :class:`FactorStats` (nnz, density, log2 degree
  histograms) into an :class:`EvalPlan` — a DP-optimal association
  tree over the chain with the density-propagation cost estimate
  recorded on every node, so every ordering choice is auditable.
  Symmetric metapaths plan the palindromic half chain (``M = C·Cᵀ``);
  general chains plan the full product and fall back to the
  ``rowsums_general`` right-fold for row sums (a vector fold is
  already association-optimal).
- The ``execute_*`` / ``fold_*`` functions are the **only sanctioned
  doorway** to the chain-fold primitives — the MP001 analyzer pass
  (analysis/metapath_ir.py) seeds ``chain_product`` / ``half_product``
  / ``rowsums_general`` / ``fold_half_chain`` and asserts nothing
  outside this module reaches them except through it.
- :class:`SubchainCache` is the workload-level memo: sub-chain results
  keyed by ``(factor fingerprints, orientation, span)`` so concurrent
  metapath lanes (APVPA, APA, APTPA through the serving coalescer)
  share common sub-chains, and a delta update invalidates only the
  entries whose factors changed. Keys are *content* fingerprints, so a
  hit is bit-identical to a cold fold by construction.

Every ordering choice is **bit-invisible**: path counts are exact
integers in every carry dtype the backends guard (f64 < 2⁵³, f32 <
2²⁴), so any association order produces identical integers — which is
the whole reason ordering is a free performance lever here. The
planner's knobs (``plan_density_cutover``, ``plan_dp_max_len``,
``plan_memo_budget_mb``) live in the tuning registry with real
``dpathsim tune`` arms.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
import threading
from collections import OrderedDict
from typing import Any, Sequence

import numpy as np

from . import chain
from . import sparse as sp
from .metapath import MetaPath, Step

# Log2 degree-histogram buckets: bucket b counts nodes with degree in
# [2^(b-1), 2^b); bucket 0 counts degree-0 nodes. 24 buckets cover any
# graph this repo can encode (int32 index spaces).
_DEG_BUCKETS = 24


def _deg_hist(deg: np.ndarray) -> tuple[int, ...]:
    if deg.size == 0:
        return (0,) * _DEG_BUCKETS
    buckets = np.zeros(_DEG_BUCKETS, dtype=np.int64)
    nz = deg[deg > 0]
    buckets[0] = int(deg.size - nz.size)
    if nz.size:
        b = np.minimum(
            np.floor(np.log2(nz)).astype(np.int64) + 1, _DEG_BUCKETS - 1
        )
        np.add.at(buckets, b, 1)
    return tuple(int(x) for x in buckets)


@dataclasses.dataclass(frozen=True)
class FactorStats:
    """Sparsity statistics of one oriented chain factor — everything
    the cost model consumes. ``row_deg``/``col_deg`` are the exact
    per-index degree vectors (excluded from equality/repr: they exist
    so leaf-leaf products can be costed *exactly* via the join-size
    identity Σ_k coldeg_A(k)·rowdeg_B(k); the compressed histograms
    are the auditable summary that lands in plan dumps)."""

    shape: tuple[int, int]
    nnz: int
    density: float
    row_deg_hist: tuple[int, ...]
    col_deg_hist: tuple[int, ...]
    row_deg: np.ndarray | None = dataclasses.field(
        default=None, repr=False, compare=False
    )
    col_deg: np.ndarray | None = dataclasses.field(
        default=None, repr=False, compare=False
    )


def factor_stats_from_coo(
    rows: np.ndarray, cols: np.ndarray, shape: tuple[int, int]
) -> FactorStats:
    m, n = int(shape[0]), int(shape[1])
    nnz = int(rows.shape[0])
    row_deg = np.bincount(rows, minlength=m).astype(np.int64)
    col_deg = np.bincount(cols, minlength=n).astype(np.int64)
    return FactorStats(
        shape=(m, n),
        nnz=nnz,
        density=nnz / max(m * n, 1),
        row_deg_hist=_deg_hist(row_deg),
        col_deg_hist=_deg_hist(col_deg),
        row_deg=row_deg,
        col_deg=col_deg,
    )


def factor_stats(hin, step: Step) -> FactorStats:
    """Oriented stats for one metapath step against the bound HIN."""
    b = hin.block(step.relationship)
    rows, cols, shape = b.rows, b.cols, b.shape
    if step.reverse:
        rows, cols, shape = cols, rows, (shape[1], shape[0])
    return factor_stats_from_coo(rows, cols, shape)


@dataclasses.dataclass(frozen=True)
class PlanNode:
    """One node of the association tree. ``lo:hi`` is the step span it
    covers; ``est_flops`` is the estimated cost of *this* product
    (0 for leaves), ``total_flops`` the cumulative subtree cost — both
    recorded so a plan dump explains every choice the DP made."""

    lo: int
    hi: int
    shape: tuple[int, int]
    est_nnz: float
    est_density: float
    est_flops: float
    total_flops: float
    step: Step | None = None
    left: "PlanNode | None" = None
    right: "PlanNode | None" = None
    stats: FactorStats | None = dataclasses.field(
        default=None, repr=False, compare=False
    )

    @property
    def is_leaf(self) -> bool:
        return self.left is None and self.right is None

    def order_tree(self):
        """Hashable nested-tuple association order (leaf = step index)
        — what the jit-per-order caches key on."""
        if self.is_leaf:
            return self.lo
        return (self.left.order_tree(), self.right.order_tree())

    def describe(self, labels: Sequence[str]) -> str:
        if self.is_leaf:
            return labels[self.lo]
        return (
            f"({self.left.describe(labels)}·{self.right.describe(labels)})"
        )

    def to_dict(self, labels: Sequence[str]) -> dict:
        d = {
            "span": [self.lo, self.hi],
            "expr": self.describe(labels),
            "shape": list(self.shape),
            "est_nnz": round(float(self.est_nnz), 3),
            "est_density": float(self.est_density),
            "est_flops": round(float(self.est_flops), 3),
            "total_flops": round(float(self.total_flops), 3),
        }
        if not self.is_leaf:
            d["left"] = self.left.to_dict(labels)
            d["right"] = self.right.to_dict(labels)
        return d


@dataclasses.dataclass(frozen=True)
class EvalPlan:
    """A compiled evaluation plan for one metapath: the association
    tree (over the half chain when ``mode == "half"``, the full chain
    otherwise), the left-to-right baseline cost for comparison, and
    the labels the audit dump renders spans with."""

    metapath: MetaPath
    mode: str  # "half" (symmetric: M = C·Cᵀ) | "general"
    root: PlanNode
    naive_flops: float
    dp: bool  # False: DP skipped (chain over the size cutoff)
    labels: tuple[str, ...]

    @property
    def est_flops(self) -> float:
        return self.root.total_flops

    def order(self) -> str:
        return self.root.describe(self.labels)

    def order_tree(self):
        return self.root.order_tree()

    def steps(self) -> tuple[Step, ...]:
        mp = self.metapath
        return mp.half() if self.mode == "half" else mp.steps

    def summary(self) -> dict:
        return {
            "metapath": self.metapath.name,
            "mode": self.mode,
            "order": self.order(),
            "est_flops": round(float(self.est_flops), 3),
            "naive_flops": round(float(self.naive_flops), 3),
            "dp": self.dp,
        }

    def to_dict(self) -> dict:
        out = self.summary()
        out["tree"] = self.root.to_dict(self.labels)
        return out


# ---------------------------------------------------------------------------
# Cost model: density propagation (Atrapos §4)
# ---------------------------------------------------------------------------


def _product_estimate(
    a: PlanNode, b: PlanNode, dense_cutover: float, cost: str
) -> tuple[float, float, float]:
    """(est_nnz, est_density, est_flops) of A·B under the named cost
    model.

    ``cost="sparse"`` (COO joins — the half-chain fold, delta
    refolds): the expected join size under independent uniform
    placement, 2·nnz(A)·nnz(B)/r scalar mul-adds over the shared
    dimension r; when BOTH operands are leaves the join size is exact
    (Σ_k coldeg_A(k)·rowdeg_B(k)). Past ``dense_cutover`` density on
    both sides the dense model takes over (a near-dense join costs
    like a GEMM, and the sparse estimator under-costs that regime).

    ``cost="dense"`` (the backends' general-chain GEMMs): a dense
    matmul pays 2·m·r·n regardless of zeros, so sparsity must not
    seduce the DP into an order that is only cheap for a format the
    executor does not use.

    Output density propagates either way as 1−(1−dₐ·d_b)^r — the
    standard Boolean-product estimator (Atrapos §4), computed via
    expm1/log1p so near-0 and near-1 densities stay stable; it rides
    every node for the audit dump and the sparse cost of parents."""
    m, r = a.shape
    _, n = b.shape
    p = a.est_density * b.est_density
    if p >= 1.0:
        est_density = 1.0
    else:
        est_density = -math.expm1(r * math.log1p(-min(p, 1.0 - 1e-12)))
    est_density = min(max(est_density, 0.0), 1.0)
    est_nnz = est_density * m * n
    dense_flops = 2.0 * float(m) * float(r) * float(n)
    if cost == "dense":
        return est_nnz, est_density, dense_flops
    if a.est_density >= dense_cutover and b.est_density >= dense_cutover:
        return est_nnz, est_density, dense_flops
    if (
        a.stats is not None
        and b.stats is not None
        and a.stats.col_deg is not None
        and b.stats.row_deg is not None
    ):
        # leaf·leaf: the join size is exact, Σ_k coldeg_A(k)·rowdeg_B(k)
        joins = 2.0 * float(
            a.stats.col_deg.astype(np.float64) @ b.stats.row_deg
        )
    else:
        joins = 2.0 * a.est_nnz * b.est_nnz / max(r, 1)
    return est_nnz, est_density, joins


def _leaf(i: int, st: Step | None, stats: FactorStats) -> PlanNode:
    return PlanNode(
        lo=i,
        hi=i + 1,
        shape=stats.shape,
        est_nnz=float(stats.nnz),
        est_density=float(stats.density),
        est_flops=0.0,
        total_flops=0.0,
        step=st,
        stats=stats,
    )


def _combine(a: PlanNode, b: PlanNode, dense_cutover: float,
             cost: str) -> PlanNode:
    est_nnz, est_density, flops = _product_estimate(a, b, dense_cutover, cost)
    return PlanNode(
        lo=a.lo,
        hi=b.hi,
        shape=(a.shape[0], b.shape[1]),
        est_nnz=est_nnz,
        est_density=est_density,
        est_flops=flops,
        total_flops=a.total_flops + b.total_flops + flops,
        left=a,
        right=b,
    )


def _left_to_right(leaves: list[PlanNode], dense_cutover: float,
                   cost: str) -> PlanNode:
    acc = leaves[0]
    for leaf in leaves[1:]:
        acc = _combine(acc, leaf, dense_cutover, cost)
    return acc


def _dp_order(leaves: list[PlanNode], dense_cutover: float,
              cost: str) -> PlanNode:
    """Classic interval DP over the chain, ties broken toward the
    smallest split (deterministic plans for equal-cost orders)."""
    n = len(leaves)
    best: dict[tuple[int, int], PlanNode] = {
        (i, i + 1): leaves[i] for i in range(n)
    }
    for span in range(2, n + 1):
        for i in range(0, n - span + 1):
            j = i + span
            winner: PlanNode | None = None
            for k in range(i + 1, j):
                cand = _combine(
                    best[(i, k)], best[(k, j)], dense_cutover, cost
                )
                if winner is None or cand.total_flops < winner.total_flops:
                    winner = cand
            best[(i, j)] = winner
    return best[(0, n)]


def _plan_knobs(n: int, length: int, nnz: int) -> tuple[float, int]:
    """(density cutover, DP length cutoff) via the tuning registry —
    the heuristics are the documented defaults, so an absent table
    means exactly the built-in behavior."""
    from .. import tuning

    cutover = float(
        tuning.choose(
            "plan_density_cutover", n=n, v=length, nnz=nnz, default=0.25
        )
    )
    dp_max = int(
        tuning.choose(
            "plan_dp_max_len", n=n, v=length, nnz=nnz, default=16
        )
    )
    return cutover, dp_max


def _record_plan_metrics(plan: EvalPlan) -> None:
    from ..obs.metrics import get_registry

    get_registry().counter(
        "dpathsim_plan_builds_total",
        "evaluation plans compiled, by metapath and factorization mode",
    ).inc(metapath=plan.metapath.name, mode=plan.mode)


def plan_chain(
    stats: Sequence[FactorStats],
    steps: Sequence[Step | None] | None = None,
    dense_cutover: float | None = None,
    dp_max_len: int | None = None,
    cost: str = "sparse",
) -> tuple[PlanNode, float, bool]:
    """Order an arbitrary factor chain: (root, naive_flops, dp_ran).
    The shared core of :func:`plan_metapath` and :func:`fold_blocks`.
    ``cost`` names the executor's model — "sparse" for COO joins,
    "dense" for GEMM chains (see :func:`_product_estimate`)."""
    if not stats:
        raise ValueError("cannot plan an empty chain")
    if steps is None:
        steps = [None] * len(stats)
    if dense_cutover is None or dp_max_len is None:
        c, d = _plan_knobs(
            stats[0].shape[0], len(stats), sum(s.nnz for s in stats)
        )
        dense_cutover = c if dense_cutover is None else dense_cutover
        dp_max_len = d if dp_max_len is None else dp_max_len
    leaves = [_leaf(i, st, s) for i, (st, s) in enumerate(zip(steps, stats))]
    naive = _left_to_right(leaves, dense_cutover, cost)
    if len(leaves) <= 2 or len(leaves) > dp_max_len:
        return naive, naive.total_flops, False
    root = _dp_order(leaves, dense_cutover, cost)
    return root, naive.total_flops, True


def plan_metapath(
    hin,
    metapath: MetaPath,
    dense_cutover: float | None = None,
    dp_max_len: int | None = None,
) -> EvalPlan:
    """Compile the metapath's evaluation plan against the bound HIN.

    Memoized per (HIN, metapath name, knob overrides) with the same
    frozen-dataclass side-table idiom ``graph_fingerprint`` uses, so
    backends, the half-chain fold, and the serving tier share one plan
    per graph instead of re-scanning factor stats."""
    cache = hin.__dict__.get("_eval_plan_cache")
    if cache is None:
        cache = {}
        object.__setattr__(hin, "_eval_plan_cache", cache)
    ck = (metapath.name, dense_cutover, dp_max_len)
    hit = cache.get(ck)
    if hit is not None:
        return hit
    if metapath.is_symmetric:
        steps = metapath.half()
        mode = "half"
        types = metapath.node_types[: len(steps) + 1]
    else:
        steps = metapath.steps
        mode = "general"
        types = metapath.node_types
    stats = [factor_stats(hin, st) for st in steps]
    labels = tuple(
        f"{types[i][0].upper()}{types[i + 1][0].upper()}"
        for i in range(len(steps))
    )
    root, naive, dp = plan_chain(
        stats, steps, dense_cutover=dense_cutover, dp_max_len=dp_max_len,
        # the half chain folds as sparse COO joins; a general chain
        # executes as dense GEMMs in every backend — the cost model
        # must match the executor, not the storage format
        cost=("sparse" if mode == "half" else "dense"),
    )
    plan = EvalPlan(
        metapath=metapath, mode=mode, root=root, naive_flops=naive,
        dp=dp, labels=labels,
    )
    _record_plan_metrics(plan)
    cache[ck] = plan
    return plan


# ---------------------------------------------------------------------------
# Workload-level sub-chain memoization
# ---------------------------------------------------------------------------


def factor_fingerprint(hin, relationship: str) -> str:
    """Content hash of one adjacency block (rows, cols, shape) —
    memoized per HIN instance; a delta produces a new HIN, so patched
    relationships re-hash while untouched ones reuse the parent's
    arrays (same content → same digest → the memo keeps hitting)."""
    cache = hin.__dict__.get("_block_fp_cache")
    if cache is None:
        cache = {}
        object.__setattr__(hin, "_block_fp_cache", cache)
    fp = cache.get(relationship)
    if fp is None:
        b = hin.block(relationship)
        h = hashlib.sha256()
        h.update(f"{relationship}:{b.shape};".encode())
        h.update(np.ascontiguousarray(b.rows, dtype=np.int64).tobytes())
        h.update(np.ascontiguousarray(b.cols, dtype=np.int64).tobytes())
        fp = cache[relationship] = h.hexdigest()[:16]
    return fp


def _span_key(node: PlanNode, steps: Sequence[Step], hin) -> tuple:
    """Memo key of one plan node: the (relationship, orientation,
    content-fingerprint) triple of every factor in its span, in order.
    Content-addressed, so equal keys denote bit-identical sub-chain
    results whatever plan (or graph epoch) produced them — two plans
    that associate the same span differently still share the entry."""
    return tuple(
        (st.relationship, st.reverse, factor_fingerprint(hin, st.relationship))
        for st in steps[node.lo: node.hi]
    )


class SubchainCache:
    """Workload-level memo of folded sub-chain COO factors.

    LRU under a byte budget; keys are content fingerprints (see
    :func:`_span_key`), so correctness never depends on invalidation —
    ``invalidate_relationships`` exists to *reclaim bytes* eagerly when
    a delta makes entries unreachable, and to make the invalidation
    rule auditable: only sub-chains whose factors changed are dropped.
    Thread-safe: serving lanes fold concurrently.

    ``factor_format`` (the tuning knob, DESIGN.md §29) stores entries
    through the packed layouts and charges them at their PACKED bytes
    against the budget — the same budget then holds 3-6× more shared
    sub-chains. Only canonical (sorted/coalesced) entries pack, so a
    warm hit hands back byte-identical arrays to the cold fold (raw
    leaf blocks — the one non-canonical producer — stay COO)."""

    def __init__(self, budget_bytes: int, factor_format: str = "coo"):
        self.factor_format = str(factor_format)
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        self._d: OrderedDict[tuple, sp.COOMatrix] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        from ..obs.metrics import get_registry

        reg = get_registry()
        self._m_hits = reg.counter(
            "dpathsim_plan_memo_hits_total", "sub-chain memo hits"
        ).labels()
        self._m_misses = reg.counter(
            "dpathsim_plan_memo_misses_total", "sub-chain memo misses"
        ).labels()
        self._m_evict = reg.counter(
            "dpathsim_plan_memo_evictions_total",
            "sub-chain memo evictions (budget pressure)",
        ).labels()
        self._m_bytes = reg.gauge(
            "dpathsim_plan_memo_bytes", "sub-chain memo resident bytes"
        ).labels()

    @staticmethod
    def _nbytes(c) -> int:
        from . import packed as pkd

        return pkd.factor_bytes(c)

    def _encode(self, c: sp.COOMatrix):
        """Entry representation for storage: packed when the format
        knob says so AND the entry is canonical (a warm hit must hand
        back byte-identical arrays — see class docstring)."""
        if self.factor_format == "coo":
            return c
        from . import packed as pkd

        if not pkd.is_canonical(c):
            return c
        return pkd.make_factor(c, self.factor_format)

    def get(self, key: tuple) -> sp.COOMatrix | None:
        with self._lock:
            hit = self._d.get(key)
            if hit is None:
                self.misses += 1
                self._m_misses.inc()
                return None
            self._d.move_to_end(key)
            self.hits += 1
            self._m_hits.inc()
        from . import packed as pkd

        # decode OUTSIDE the lock: a packed hit's O(nnz) unpack must
        # not serialize concurrent lanes
        return pkd.as_coo(hit)

    def put(self, key: tuple, c: sp.COOMatrix) -> None:
        if self.budget_bytes <= 0:
            return
        entry = self._encode(c)
        # An entry bigger than half the budget (a huge leaf factor at
        # full graph scale) would evict every interior fold the memo
        # exists for just to store one array the HIN already holds —
        # skip it; the fold recomputes it in O(nnz). Packed entries are
        # charged at their packed bytes — the budget's whole point.
        if 2 * self._nbytes(entry) > self.budget_bytes:
            return
        with self._lock:
            if key not in self._d:
                self._bytes += self._nbytes(entry)
            self._d[key] = entry
            self._d.move_to_end(key)
            while self._bytes > self.budget_bytes and len(self._d) > 1:
                _, dropped = self._d.popitem(last=False)
                self._bytes -= self._nbytes(dropped)
                self.evictions += 1
                self._m_evict.inc()
            self._m_bytes.set(self._bytes)

    def invalidate_relationships(self, rels) -> int:
        """Drop every entry whose span touches a changed relationship
        — the delta-update invalidation rule. Entries over untouched
        factors survive (and keep hitting, because their content
        fingerprints did not move)."""
        rels = set(rels)
        if not rels:
            return 0
        with self._lock:
            doomed = [
                key for key in self._d
                if any(rel in rels for rel, _, _ in key)
            ]
            for key in doomed:
                self._bytes -= self._nbytes(self._d[key])
                del self._d[key]
            self._m_bytes.set(self._bytes)
            return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()
            self._bytes = 0
            self._m_bytes.set(0)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._d),
                "bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


def default_memo_budget_bytes(n: int) -> int:
    """The tuned ``plan_memo_budget_mb`` knob → bytes (heuristic
    default 64 MB — comfortably holds every DBLP-schema sub-chain at
    dblp_large scale while staying irrelevant next to the factor
    itself)."""
    from .. import tuning

    mb = float(tuning.choose("plan_memo_budget_mb", n=n, default=64.0))
    return int(mb * (1 << 20))


# ---------------------------------------------------------------------------
# Execution: the sanctioned chain-evaluation doorway (MP001)
# ---------------------------------------------------------------------------


def _oriented_coo(hin, st: Step) -> sp.COOMatrix:
    c = sp.coo_from_block(hin.block(st.relationship))
    if st.reverse:
        c = sp.COOMatrix(
            rows=c.cols, cols=c.rows, weights=c.weights,
            shape=(c.shape[1], c.shape[0]),
        )
    return c


def _eval_coo_node(
    node: PlanNode,
    steps: Sequence[Step],
    hin,
    memo: SubchainCache | None,
) -> sp.COOMatrix:
    key = _span_key(node, steps, hin) if memo is not None else None
    if memo is not None:
        hit = memo.get(key)
        if hit is not None:
            return hit
    if node.is_leaf:
        out = _oriented_coo(hin, steps[node.lo])
    else:
        a = _eval_coo_node(node.left, steps, hin, memo)
        b = _eval_coo_node(node.right, steps, hin, memo)
        out = sp._matmul_summed(a, b)
    if memo is not None:
        memo.put(key, out)
    return out


def fold_half(
    hin,
    metapath: MetaPath,
    memo: SubchainCache | None = None,
    plan: EvalPlan | None = None,
) -> sp.COOMatrix:
    """Plan-ordered sparse fold of the symmetric half chain → the COO
    factor C every backend binds. Bit-compatible with the historical
    left-to-right fold: single-step halves return the raw oriented
    block (unsummed, exactly as before), multi-step folds coalesce at
    every product, and integer weights make every association order
    produce identical coalesced content."""
    if plan is None:
        plan = plan_metapath(hin, metapath)
    if plan.mode != "half":
        raise ValueError(
            f"metapath {metapath.name} is not symmetric; "
            "fold_half requires the half-chain factorization"
        )
    return _eval_coo_node(plan.root, plan.steps(), hin, memo)


def fold_general(
    hin,
    metapath: MetaPath,
    memo: SubchainCache | None = None,
    plan: EvalPlan | None = None,
) -> sp.COOMatrix:
    """Plan-ordered sparse fold of the FULL chain (general metapaths):
    the commuting matrix M as coalesced COO."""
    if plan is None:
        plan = plan_metapath(hin, metapath)
    steps = plan.steps()
    if plan.mode == "half":
        # M = C·Cᵀ: fold the half, join it with its transpose.
        c = fold_half(hin, metapath, memo=memo, plan=plan)
        ct = sp.COOMatrix(
            rows=c.cols, cols=c.rows, weights=c.weights,
            shape=(c.shape[1], c.shape[0]),
        )
        return sp._matmul_summed(c, ct)
    return _eval_coo_node(plan.root, steps, hin, memo)


def fold_blocks(
    blocks: Sequence[sp.COOMatrix],
    dense_cutover: float | None = None,
) -> sp.COOMatrix:
    """Plan-ordered fold of pre-oriented COO blocks (the delta
    algebra's general-chain refold and any caller that already
    materialized its factors). Stats come from the blocks themselves;
    no memoization (callers hold transient deltas, not graph state)."""
    if len(blocks) == 1:
        return blocks[0]
    stats = [
        factor_stats_from_coo(b.rows, b.cols, b.shape) for b in blocks
    ]
    root, _, _ = plan_chain(stats, dense_cutover=dense_cutover)

    def ev(node: PlanNode) -> sp.COOMatrix:
        if node.is_leaf:
            return blocks[node.lo]
        return sp._matmul_summed(ev(node.left), ev(node.right))

    return ev(root)


def dense_half(
    hin,
    metapath: MetaPath,
    dtype=np.float32,
    memo: SubchainCache | None = None,
) -> np.ndarray:
    """Dense [N, V] half-chain factor via the plan-ordered sparse fold
    — the planner-owned successor of ``ops.sparse.dense_half_chain``
    (the dense [N, P] intermediate of a naive chain product never
    exists)."""
    coo = fold_half(hin, metapath, memo=memo).summed()
    c = np.zeros(coo.shape, dtype=dtype)
    c[coo.rows, coo.cols] = coo.weights
    return c


def execute_dense_order(order, blocks, xp: Any = np):
    """Evaluate a dense block chain in the plan's association order
    (``order`` from :meth:`EvalPlan.order_tree`: leaf = block index,
    product = a (left, right) pair). Array-library agnostic and
    jit-safe — the order is static Python structure, so a jitted
    wrapper compiles once per order."""
    if isinstance(order, int):
        return blocks[order]
    left, right = order
    return xp.matmul(
        execute_dense_order(left, blocks, xp),
        execute_dense_order(right, blocks, xp),
    )


def execute_dense(plan: EvalPlan, blocks, xp: Any = np):
    """Dense chain product in plan order (the general-metapath M)."""
    return execute_dense_order(plan.order_tree(), blocks, xp)


def naive_dense(blocks, xp: Any = np):
    """The left-to-right reference fold — the baseline the property
    tests and the ordering bench compare the planner against (delegates
    to the seeded primitive; this doorway is why callers stay
    MP001-clean)."""
    return chain.chain_product(blocks, xp=xp)


def rowsums_fold(blocks, xp: Any = np):
    """Row sums of an arbitrary chain by the right-fold — a vector
    fold is already association-optimal (each step is one GEMV), so
    the planner simply sanctions the seeded primitive."""
    return chain.rowsums_general(blocks, xp=xp)
