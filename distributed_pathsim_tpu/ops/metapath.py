"""Metapath compiler: metapath spec → oriented adjacency-block chain.

This replaces the reference's GraphFrames motif DSL. The reference encodes
the APVPA meta-path as a 4-way motif string with per-binding type and
relationship filters (``DPathSim_APVPA.py:72-84``); every query re-plans
and re-executes the full distributed join. Here a metapath is *compiled
once* into a typed chain of oriented adjacency blocks; the commuting
matrix ``M`` of the metapath is their product, and the reference's two
kernels collapse into entries and row sums of ``M`` (SURVEY.md §3.3):

- pairwise walk(x, y)  = M[x, y]
- global walk(x)       = Σ_y M[x, y]   (row sum — the reference leaves
  ``author_2`` free, so this is NOT the textbook diagonal M[x,x])

For palindromic metapaths (APVPA, APA, APTPA …) the chain factors as
``M = C @ Cᵀ`` with ``C`` the first-half product — half the FLOPs, exact
symmetry by construction, and row sums computable as ``C @ (Σ_rows C)``
without materializing ``M`` at all. The compiler detects and exposes this
factorization; every backend exploits it.

Motif semantics preserved: vertex distinctness is NOT enforced (degenerate
paths with paper_1 == paper_2 or author_2 == author_1 count — exactly what
``gf.find`` returns and what makes the count equal the matrix entry).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

from ..data.schema import HINSchema

# Letter aliases for the compact "APVPA" spec syntax, DBLP convention.
DBLP_ALIASES = {"A": "author", "P": "paper", "V": "venue", "T": "topic"}


@dataclasses.dataclass(frozen=True)
class Step:
    """One oriented traversal: follow ``relationship`` forward (src→dst)
    or reversed (dst→src, i.e. the transposed block)."""

    relationship: str
    reverse: bool

    def __repr__(self) -> str:
        arrow = "←" if self.reverse else "→"
        return f"{arrow}{self.relationship}"


@dataclasses.dataclass(frozen=True)
class MetaPath:
    """A compiled metapath over a schema."""

    name: str
    node_types: tuple[str, ...]
    steps: tuple[Step, ...]

    @property
    def source_type(self) -> str:
        return self.node_types[0]

    @property
    def target_type(self) -> str:
        return self.node_types[-1]

    @property
    def length(self) -> int:
        return len(self.steps)

    @property
    def is_symmetric(self) -> bool:
        """Palindromic node sequence with mirrored steps: guarantees
        ``M = C @ Cᵀ`` with C the first-half chain product."""
        n = len(self.steps)
        if n % 2 != 0:
            return False
        if self.node_types != tuple(reversed(self.node_types)):
            return False
        for i in range(n // 2):
            a, b = self.steps[i], self.steps[n - 1 - i]
            if a.relationship != b.relationship or a.reverse == b.reverse:
                return False
        return True

    def half(self) -> tuple[Step, ...]:
        if not self.is_symmetric:
            raise ValueError(f"metapath {self.name} is not symmetric")
        return self.steps[: len(self.steps) // 2]

    def step_shapes(self, type_sizes: dict[str, int]) -> list[tuple[int, int]]:
        return [
            (type_sizes[self.node_types[i]], type_sizes[self.node_types[i + 1]])
            for i in range(len(self.steps))
        ]


def compile_metapath(
    spec: str | Sequence[str],
    schema: HINSchema,
    aliases: dict[str, str] | None = None,
    name: str | None = None,
) -> MetaPath:
    """Compile a metapath spec against a schema.

    ``spec`` is either a compact letter string (``"APVPA"``, resolved via
    ``aliases``, default DBLP letters) or an explicit node-type sequence
    (``["author", "paper", "venue", "paper", "author"]``). Each
    consecutive type pair is resolved to the unique schema relation with
    that signature, traversed forward or reverse; ambiguity or absence is
    a compile error — typed indices instead of string-interpolated SQL
    predicates (the reference formats filter values straight into Spark
    SQL, ``DPathSim_APVPA.py:77,97-98``).
    """
    if isinstance(spec, str):
        aliases = aliases or DBLP_ALIASES
        try:
            node_types = tuple(aliases[c] for c in spec)
        except KeyError as exc:
            raise ValueError(f"unknown metapath letter {exc} in {spec!r}") from exc
        default_name = spec
    else:
        node_types = tuple(spec)
        default_name = "".join(t[0].upper() for t in node_types)
    if len(node_types) < 2:
        raise ValueError("metapath needs at least two node types")
    schema.validate_metapath(node_types)

    steps: list[Step] = []
    for i in range(len(node_types) - 1):
        s, t = node_types[i], node_types[i + 1]
        forward = [r for r, sig in schema.relations.items() if sig == (s, t)]
        backward = [r for r, sig in schema.relations.items() if sig == (t, s)]
        candidates = [(r, False) for r in forward] + [(r, True) for r in backward]
        if not candidates:
            raise ValueError(
                f"no relation connects {s!r}→{t!r} in schema "
                f"{dict(schema.relations)}"
            )
        if len(candidates) > 1:
            raise ValueError(
                f"ambiguous relation for {s!r}→{t!r}: "
                f"{[c[0] for c in candidates]}; pass explicit steps"
            )
        rel, rev = candidates[0]
        steps.append(Step(relationship=rel, reverse=rev))

    return MetaPath(
        name=name or default_name, node_types=node_types, steps=tuple(steps)
    )
