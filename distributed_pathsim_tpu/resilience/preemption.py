"""Preemption-aware shutdown: SIGTERM/SIGINT → flush → resumable exit.

TPU hosts get preempted; schedulers send SIGTERM and give a grace
window. The reference's answer was an append-mode log whose shipped
artifact is a run that died mid-stage (SURVEY.md §5). Ours: a signal
sets a flag, the streaming tile loop notices it BETWEEN tiles, drains
its in-flight tiles through :class:`~..utils.checkpoint.CheckpointManager`
(so the manifest stays consistent), and raises :class:`Preempted`. The
CLI renders that as a one-line "resume with the same --checkpoint-dir"
message and exits with code :data:`PREEMPTED_EXIT_CODE` (75,
``EX_TEMPFAIL`` — "transient, try again").

A second signal during the grace drain escalates to ``KeyboardInterrupt``
so a stuck flush can still be killed interactively.
"""

from __future__ import annotations

import os
import signal
import threading

from ..utils.logging import runtime_event

# BSD sysexits EX_TEMPFAIL: the canonical "re-run me later" code.
PREEMPTED_EXIT_CODE = 75


class Preempted(RuntimeError):
    """The run was asked to stop and has flushed what it could.

    ``resumable`` is True when a checkpoint directory holds a manifest a
    restart can pick up from."""

    def __init__(self, message: str, checkpoint_dir: str | None = None):
        super().__init__(message)
        self.checkpoint_dir = checkpoint_dir

    @property
    def resumable(self) -> bool:
        return self.checkpoint_dir is not None


class PreemptionHandler:
    """Latches a stop request from a signal (or programmatically).

    Signal handlers only set a flag — all flushing happens in the
    compute thread at a safe point (between tiles), never inside the
    handler where arbitrary code is unsafe."""

    def __init__(self):
        self._requested = threading.Event()
        self._reason: str | None = None
        self._prev: dict[int, object] = {}

    def install(self, signals=(signal.SIGTERM, signal.SIGINT)) -> bool:
        """Install handlers; returns False (no-op) outside the main
        thread, where CPython forbids signal registration."""
        if self._prev:
            return True
        try:
            for sig in signals:
                self._prev[sig] = signal.signal(sig, self._on_signal)
        except ValueError:  # not the main thread
            self._prev.clear()
            return False
        return True

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()

    def _on_signal(self, signum, frame) -> None:
        if self._requested.is_set():
            # Second signal: the operator means it — stop waiting for
            # the graceful drain.
            raise KeyboardInterrupt(f"second signal {signum} during drain")
        # Signal context: only async-signal-safe work here. The buffered
        # runtime_event/metric channels are NOT reentrant (the signal
        # may have landed mid-write in the main thread), so operator
        # feedback goes through raw os.write and the structured event is
        # deferred to the compute thread's next check().
        self._reason = f"signal {signum}"
        self._requested.set()
        os.write(2, f"[pathsim:preempt_requested] reason=signal {signum}\n".encode())

    def request(self, reason: str = "requested") -> None:
        if not self._requested.is_set():
            self._reason = reason
            self._requested.set()
            runtime_event("preempt_requested", reason=reason)

    def requested(self) -> bool:
        return self._requested.is_set()

    @property
    def reason(self) -> str | None:
        return self._reason

    def reset(self) -> None:
        self._requested.clear()
        self._reason = None

    def check(self, checkpoint_dir: str | None = None) -> None:
        """Raise :class:`Preempted` iff a stop was requested. Call at
        safe points AFTER in-flight state has been flushed."""
        if self._requested.is_set():
            runtime_event(
                "preempted",
                reason=self._reason,
                checkpoint_dir=checkpoint_dir,
                resumable=checkpoint_dir is not None,
            )
            raise Preempted(
                f"preempted ({self._reason}); "
                + (
                    f"resume with --checkpoint-dir {checkpoint_dir}"
                    if checkpoint_dir is not None
                    else "no checkpoint directory — progress not saved"
                ),
                checkpoint_dir=checkpoint_dir,
            )


# One per process: signals are process-wide.
handler = PreemptionHandler()
