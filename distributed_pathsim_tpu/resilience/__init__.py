"""Fault-tolerant execution layer.

Four pieces, applied at the stack's failure seams (GEXF load, metapath
compile, backend init, per-tile execute, checkpoint write, multi-host
rendezvous):

- :mod:`.policy` — :class:`RetryPolicy`: exponential backoff + jitter,
  exception-class filters, overall deadlines; env-tunable defaults.
- :mod:`.inject` — :class:`FaultInjector`: a deterministic chaos
  harness (``PATHSIM_FAULT_PLAN``) that raises, delays, partially
  writes, or requests preemption at the same seams, so every recovery
  path runs on CPU in tier-1.
- :mod:`.degrade` — the graceful step-down chain
  (jax-sharded → jax → numpy; native loader → python loader).
- :mod:`.preemption` — SIGTERM/SIGINT → flush in-flight tiles through
  the CheckpointManager → exit 75 with a resumable manifest.

The one-line integration surface for seams is :func:`resilient_call`.
"""

from __future__ import annotations

from typing import Callable, TypeVar

from . import inject
from .degrade import backend_chain, create_backend_resilient
from .inject import FaultInjector, InjectedCrash, InjectedFault
from .policy import (
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    TransientError,
    policy_from_env,
)
from .preemption import PREEMPTED_EXIT_CODE, Preempted, handler as preemption_handler

T = TypeVar("T")

__all__ = [
    "Deadline",
    "DeadlineExceeded",
    "FaultInjector",
    "InjectedCrash",
    "InjectedFault",
    "PREEMPTED_EXIT_CODE",
    "Preempted",
    "RetryPolicy",
    "TransientError",
    "backend_chain",
    "create_backend_resilient",
    "policy_from_env",
    "preemption_handler",
    "resilient_call",
]


def resilient_call(
    seam: str,
    fn: Callable[[], T],
    policy: RetryPolicy | None = None,
) -> T:
    """Run ``fn`` as one seam attempt: consult the fault injector, then
    the real work, under ``policy`` (env default when None). Each retry
    attempt re-fires the injector — an injected fault counts as a
    failure of the operation itself."""
    policy = policy or policy_from_env()

    def attempt() -> T:
        inject.fire(seam)
        return fn()

    return policy.call(attempt, seam=seam)
