"""Retry policies: exponential backoff + jitter, class filters, deadlines.

The stack's failure seams (GEXF load, metapath compile, backend init,
per-tile execute, checkpoint I/O, multi-host rendezvous) all share one
failure taxonomy: *transient* faults — a flaky native loader, a rejected
remote compile (the HTTP 413 incident in git history), a preempted host,
a full-then-freed disk — deserve a bounded, backed-off retry; *semantic*
faults (bad metapath, wrong checkpoint directory) must surface on the
first attempt. :class:`RetryPolicy` encodes that split once so every
seam behaves identically and every retry is visible as a structured
``runtime_event``.

Defaults come from the environment so an operator can harden a flaky
deployment without touching call sites::

    PATHSIM_MAX_RETRIES=5 PATHSIM_RETRY_BASE_DELAY=0.2 dpathsim ...
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Callable, TypeVar

from ..obs.metrics import get_registry
from ..utils.logging import runtime_event

T = TypeVar("T")


class TransientError(Exception):
    """A failure worth retrying: the operation may succeed if repeated.

    Raised directly by the fault injector and usable by any subsystem
    that can classify its own failures (e.g. a remote compile service
    returning a retryable status)."""


# What a retry can plausibly fix. ValueError/KeyError (user input,
# schema mismatches) are deliberately absent: retrying a deterministic
# error just triples its latency.
DEFAULT_RETRYABLE: tuple[type[BaseException], ...] = (
    TransientError,
    OSError,
    ConnectionError,
    TimeoutError,
)

# Jitter is deterministic by default (seeded RNG): chaos runs and tests
# reproduce byte-for-byte. Operators fighting thundering herds across a
# pod set PATHSIM_RETRY_SEED to the process rank (or any varying value).
_rng = random.Random(int(os.environ.get("PATHSIM_RETRY_SEED", "0")))


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with jitter and an overall deadline.

    ``max_attempts`` counts the first try: 3 means one try + two
    retries. ``deadline_s`` bounds the *total* time spent inside
    :meth:`call` — an attempt whose next backoff would overrun the
    deadline is not slept for; the last error raises instead.
    """

    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25  # ± fraction of the nominal delay
    deadline_s: float | None = None
    retryable: tuple[type[BaseException], ...] = DEFAULT_RETRYABLE
    non_retryable: tuple[type[BaseException], ...] = ()

    def replace(self, **changes) -> "RetryPolicy":
        return dataclasses.replace(self, **changes)

    def backoff(self, attempt: int) -> float:
        """Nominal delay after the ``attempt``-th failure (1-based),
        before jitter."""
        return min(
            self.base_delay * self.multiplier ** (attempt - 1), self.max_delay
        )

    def _jittered(self, delay: float) -> float:
        if self.jitter <= 0:
            return delay
        return delay * (1.0 + self.jitter * (2.0 * _rng.random() - 1.0))

    def call(self, fn: Callable[[], T], seam: str = "") -> T:
        """Run ``fn`` under this policy. Non-retryable and unknown
        exception classes propagate immediately; retryable ones are
        retried with backoff until attempts or the deadline run out."""
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        t0 = time.monotonic()
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn()
            except self.non_retryable:
                raise
            except self.retryable as exc:
                if attempt >= self.max_attempts:
                    raise
                delay = self._jittered(self.backoff(attempt))
                if (
                    self.deadline_s is not None
                    and time.monotonic() - t0 + delay > self.deadline_s
                ):
                    runtime_event(
                        "retry_deadline",
                        seam=seam,
                        attempt=attempt,
                        deadline_s=self.deadline_s,
                        error=repr(exc),
                    )
                    raise
                get_registry().counter(
                    "dpathsim_retries_total", "retries by failure seam"
                ).inc(seam=seam or "unknown")
                runtime_event(
                    "retry",
                    seam=seam,
                    attempt=attempt,
                    max_attempts=self.max_attempts,
                    delay_s=round(delay, 4),
                    error=repr(exc),
                )
                time.sleep(delay)
        raise AssertionError("unreachable")  # pragma: no cover


class Deadline:
    """An absolute time budget, propagated end-to-end.

    Born at the protocol edge from a request's ``deadline_ms`` field and
    threaded through every layer that might wait, retry, or re-dispatch
    (router failover, hedged sends, worker dispatch): each hop asks for
    the *remaining* budget, so the sum of all retries can never overshoot
    what the caller asked for. Monotonic-clock based — wall steps under
    NTP must not expire (or resurrect) a request."""

    __slots__ = ("t_deadline",)

    def __init__(self, budget_s: float):
        self.t_deadline = time.monotonic() + float(budget_s)

    @classmethod
    def from_ms(cls, deadline_ms: float | None) -> "Deadline | None":
        """Protocol field → Deadline; None/absent means unbounded."""
        if deadline_ms is None:
            return None
        return cls(float(deadline_ms) / 1e3)

    def remaining_s(self) -> float:
        return self.t_deadline - time.monotonic()

    def remaining_ms(self) -> float:
        return self.remaining_s() * 1e3

    @property
    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def clamp(self, policy: "RetryPolicy") -> "RetryPolicy":
        """Bound a retry policy by the remaining budget: the tighter of
        the policy's own deadline and this one wins, so a seam's
        environment-tuned deadline can shrink but never extend what the
        caller granted."""
        remaining = max(self.remaining_s(), 0.0)
        if policy.deadline_s is None or policy.deadline_s > remaining:
            return policy.replace(deadline_s=remaining)
        return policy


class DeadlineExceeded(RuntimeError):
    """The caller's time budget ran out before an answer was produced.

    NOT a TransientError: retrying an expired request only wastes the
    replica a failover would have handed it to."""


def _env_float(name: str, default: float | None) -> float | None:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    return float(raw)


def policy_from_env(**overrides) -> RetryPolicy:
    """The environment-tuned default policy; ``overrides`` win over env,
    env wins over the dataclass defaults."""
    fields = {
        "max_attempts": int(os.environ.get("PATHSIM_MAX_RETRIES", "3")),
        "base_delay": _env_float("PATHSIM_RETRY_BASE_DELAY", 0.05),
        "max_delay": _env_float("PATHSIM_RETRY_MAX_DELAY", 2.0),
        "deadline_s": _env_float("PATHSIM_RETRY_DEADLINE", None),
    }
    fields.update({k: v for k, v in overrides.items() if v is not None})
    return RetryPolicy(**fields)
