"""Deterministic chaos harness: inject faults at the stack's seams.

Every recovery path in this repo (retry, degradation, checkpoint resume,
preemption flush) is testable on CPU in tier-1 because the seams consult
a process-wide :class:`FaultInjector` before doing real work. The plan is
env/config-driven and *deterministic*: a rule fires on specific
pass-counts through its seam, never on wall time or randomness, so a
chaos run is exactly reproducible.

Plan grammar (``PATHSIM_FAULT_PLAN``)::

    plan  := entry ("," entry)*
    entry := seam ":" kind [":" count ["@" skip] [":" arg]]

- ``seam``: one of :data:`SEAMS` (e.g. ``tile_execute``).
- ``kind``: ``error`` (raise :class:`InjectedFault` — retryable),
  ``crash`` (raise :class:`InjectedCrash` — NON-retryable, simulates a
  hard kill), ``delay`` (sleep ``arg`` seconds, default 0.01),
  ``partial`` (checkpoint writes only: truncate the temp file mid-write,
  then raise :class:`InjectedFault` — exercises write atomicity),
  ``preempt`` (request graceful preemption, as if SIGTERM arrived).
- ``count``: how many fires consume this rule (default 1).
- ``@skip``: let this many fires through first (default 0) — e.g.
  ``tile_execute:crash:1@2`` crashes on the THIRD tile.

Example — one transient failure at every seam::

    PATHSIM_FAULT_PLAN="gexf_load:error:1,metapath_compile:error:1,\
backend_init:error:1,tile_execute:error:1,checkpoint_write:partial:1"
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import IO

from ..utils.logging import runtime_event
from .policy import TransientError

ENV_VAR = "PATHSIM_FAULT_PLAN"

# The documented failure seams (DESIGN.md "Failure model & recovery").
# fire() accepts any name — new seams shouldn't need a registry edit to
# be testable — but the plan parser warns on unknown ones to catch typos.
SEAMS = (
    "gexf_load",
    "metapath_compile",
    "backend_init",
    "tile_execute",
    "device_execute",
    "checkpoint_write",
    "multihost_init",
    # Horizontal serving tier (router/): fired by a worker before each
    # query dispatch, by the router before each heartbeat probe, and by
    # the router before each per-worker delta send. An ``error`` at
    # worker_dispatch is a retriable per-request failure the router
    # reroutes; a ``delay`` simulates a stalled worker (hedging
    # territory); an ``error`` at delta_broadcast makes that worker
    # miss the update — the fencing machinery's test vector.
    "worker_dispatch",
    "heartbeat",
    "delta_broadcast",
)

_KINDS = ("error", "crash", "delay", "partial", "preempt")


class InjectedFault(TransientError):
    """A transient injected failure — retry policies absorb it."""


class InjectedCrash(RuntimeError):
    """A hard injected failure — never retried, kills the run like a
    real crash so checkpoint/resume paths can be exercised."""


@dataclasses.dataclass
class FaultRule:
    seam: str
    kind: str
    count: int = 1
    skip: int = 0
    arg: float | None = None
    fired: int = 0
    skipped: int = 0

    def consume(self) -> bool:
        """Whether this rule claims the current fire (and advance its
        skip/fire bookkeeping)."""
        if self.fired >= self.count:
            return False
        if self.skipped < self.skip:
            self.skipped += 1
            return False
        self.fired += 1
        return True


def parse_plan(plan: str) -> list[FaultRule]:
    rules: list[FaultRule] = []
    for raw in plan.split(","):
        entry = raw.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"bad fault-plan entry {entry!r}: need seam:kind[:count[@skip]][:arg]"
            )
        seam, kind = parts[0].strip(), parts[1].strip()
        if kind not in _KINDS:
            raise ValueError(
                f"bad fault-plan entry {entry!r}: unknown kind {kind!r} "
                f"(choose from {_KINDS})"
            )
        if seam not in SEAMS:
            runtime_event("fault_plan_unknown_seam", seam=seam, entry=entry)
        count, skip = 1, 0
        if len(parts) >= 3 and parts[2].strip():
            count_part = parts[2].strip()
            if "@" in count_part:
                c, s = count_part.split("@", 1)
                count, skip = int(c), int(s)
            else:
                count = int(count_part)
        arg = float(parts[3]) if len(parts) >= 4 and parts[3].strip() else None
        rules.append(FaultRule(seam=seam, kind=kind, count=count, skip=skip, arg=arg))
    return rules


class FaultInjector:
    """Holds the active rules plus per-seam hit counters.

    Hit counters tick on EVERY fire (rules or not): tests use them to
    assert e.g. that a resumed run re-executed only the unfinished
    tiles. The counters are cheap (one dict increment per seam pass, on
    paths that each do device dispatches or file I/O)."""

    def __init__(self, rules: list[FaultRule] | None = None):
        self.rules = rules or []
        self.hits: dict[str, int] = {}
        self.events: list[dict] = []

    @classmethod
    def from_plan(cls, plan: str) -> "FaultInjector":
        return cls(parse_plan(plan))

    @property
    def active(self) -> bool:
        return any(r.fired < r.count for r in self.rules)

    def _record(self, rule: FaultRule) -> None:
        ev = {
            "seam": rule.seam,
            "kind": rule.kind,
            "hit": self.hits.get(rule.seam, 0),
        }
        self.events.append(ev)
        from ..obs.metrics import get_registry

        get_registry().counter(
            "dpathsim_faults_injected_total",
            "chaos-harness faults fired, by seam and kind",
        ).inc(seam=rule.seam, kind=rule.kind)
        runtime_event("fault_injected", **ev)

    def fire(self, seam: str) -> None:
        """Called by a seam before (each attempt of) its real work.
        Applies at most one matching rule per fire."""
        self.hits[seam] = self.hits.get(seam, 0) + 1
        for rule in self.rules:
            if rule.seam != seam or rule.kind == "partial":
                continue  # partial is claimed by corrupt_stream()
            if not rule.consume():
                continue
            self._record(rule)
            if rule.kind == "error":
                raise InjectedFault(f"injected transient fault at {seam}")
            if rule.kind == "crash":
                raise InjectedCrash(f"injected crash at {seam}")
            if rule.kind == "delay":
                time.sleep(rule.arg if rule.arg is not None else 0.01)
                return
            if rule.kind == "preempt":
                from . import preemption

                preemption.handler.request(reason=f"injected at {seam}")
                return
        return

    def corrupt_stream(self, seam: str, f: IO[bytes]) -> None:
        """Partial-write injection point: called by atomic writers with
        the still-open temp file AFTER the payload is written. A pending
        ``partial`` rule truncates the file to half and raises — the
        rename never happens, so this simulates a writer dying mid-write
        (what the atomic temp+rename discipline exists to survive)."""
        for rule in self.rules:
            if rule.seam != seam or rule.kind != "partial":
                continue
            if not rule.consume():
                continue
            # no hit increment here: the enclosing save already fire()d
            self._record(rule)
            f.flush()
            size = f.tell()
            f.truncate(max(size // 2, 0))
            raise InjectedFault(f"injected partial write at {seam}")


# -- process-wide injector --------------------------------------------------
#
# None means "not yet resolved from the environment"; tests install an
# explicit injector (overriding env) and reset() back afterwards.

_injector: FaultInjector | None = None


def get_injector() -> FaultInjector:
    global _injector
    if _injector is None:
        plan = os.environ.get(ENV_VAR, "")
        _injector = FaultInjector.from_plan(plan) if plan else FaultInjector()
    return _injector


def install_plan(plan: str) -> FaultInjector:
    """Install an explicit plan (tests/chaos harness), overriding the
    environment. Returns the injector so callers can inspect hits."""
    global _injector
    _injector = FaultInjector.from_plan(plan)
    return _injector


def reset() -> None:
    """Drop the active injector; the next fire() re-reads the env."""
    global _injector
    _injector = None


def fire(seam: str) -> None:
    get_injector().fire(seam)


def corrupt_stream(seam: str, f: IO[bytes]) -> None:
    get_injector().corrupt_stream(seam, f)
