"""Graceful degradation: step down the backend chain instead of dying.

When a backend's init keeps failing transiently even after retries
(accelerator runtime wedged, device OOM on attach, a native extension
refusing to load), a long-running query workload (Atrapos framing,
PAPERS.md) is better served degraded than dead: the sharded backend
steps down to single-device dense, dense steps down to the numpy
oracle — slower, but every backend serves the identical primitives, so
results are unchanged. Each step emits a structured ``degrade`` event;
``--no-degrade`` (or ``degrade=False``) restores fail-fast behavior.

Degradation triggers ONLY on the retry policy's transient classes: a
deterministic config error (bad variant, asymmetric metapath on a
symmetric-only backend) raises immediately on the first backend — a
chain walk would just mask the user's actual mistake.
"""

from __future__ import annotations

from typing import Any

from ..utils.logging import runtime_event
from . import inject
from .policy import RetryPolicy, policy_from_env

def record_degrade(component: str) -> None:
    """The one registration site for ``dpathsim_degrades_total``: every
    degradation seam (backend chain here, loader fallback in engine.py,
    whatever comes next) counts through this, so the family's help text
    and label shape can never drift between call sites."""
    from ..obs.metrics import get_registry

    get_registry().counter(
        "dpathsim_degrades_total",
        "degradation-chain step-downs by component",
    ).inc(component=component)


# name → next step down. Every chain ends at the numpy f64 oracle, which
# has no device, no jit, and no native code to fail.
BACKEND_DEGRADATION: dict[str, str] = {
    "jax-sharded": "jax",
    "jax-sparse": "jax",
    "jax": "numpy",
}

# Options that only one family of backends understands; forwarding them
# down the chain would either crash the fallback or silently change its
# math, so they are dropped (with the drop recorded in the event).
_BACKEND_ONLY_OPTIONS = {
    "tile_rows": ("jax-sparse",),
    "n_devices": ("jax-sharded",),
}


def backend_chain(name: str) -> list[str]:
    """The degradation order starting at ``name`` (inclusive)."""
    chain = [name]
    while chain[-1] in BACKEND_DEGRADATION:
        chain.append(BACKEND_DEGRADATION[chain[-1]])
    return chain


def _options_for(name: str, options: dict[str, Any]) -> dict[str, Any]:
    return {
        k: v
        for k, v in options.items()
        if k not in _BACKEND_ONLY_OPTIONS or name in _BACKEND_ONLY_OPTIONS[k]
    }


def create_backend_resilient(
    name: str,
    hin,
    metapath,
    policy: RetryPolicy | None = None,
    degrade: bool = True,
    **options: Any,
):
    """:func:`..backends.base.create_backend` with retries at the
    ``backend_init`` seam and, when ``degrade``, the step-down chain."""
    from ..backends.base import create_backend

    policy = policy or policy_from_env()
    chain = backend_chain(name) if degrade else [name]
    last_exc: BaseException | None = None
    for step, candidate in enumerate(chain):
        opts = _options_for(candidate, options)

        def attempt(candidate=candidate, opts=opts):
            inject.fire("backend_init")
            return create_backend(candidate, hin, metapath, **opts)

        try:
            backend = policy.call(attempt, seam="backend_init")
        except policy.retryable as exc:
            last_exc = exc
            if candidate == chain[-1]:
                raise
            record_degrade("backend")
            runtime_event(
                "degrade",
                component="backend",
                from_=candidate,
                to=chain[step + 1],
                error=repr(exc),
            )
            continue
        if step > 0:
            runtime_event(
                "degraded_backend_active",
                requested=name,
                active=candidate,
            )
        return backend
    raise last_exc  # pragma: no cover — loop always returns or raises
