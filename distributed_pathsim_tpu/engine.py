"""Engine bootstrap: dataset → encoded HIN → compiled metapath → backend.

The analog of the reference's Spark bootstrap block
(``DPathSim_APVPA.py:146-168``) — except "starting the engine" here means
compiling a jit program, and the ``backend=`` flag (BASELINE.json) picks
the execution strategy instead of a pinned JVM package.
"""

from __future__ import annotations

from . import resilience
from .backends.base import PathSimBackend
from .config import RunConfig
from .data.encode import EncodedHIN, encode_hin
from .data.gexf import read_gexf
from .driver import PathSimDriver
from .ops.metapath import MetaPath, compile_metapath
from .resilience.policy import RetryPolicy
from .utils.logging import runtime_event


# --loader choice → read path: None prefers native with clean fallback,
# False forces the exact Python pipeline, True requires native. One map,
# shared by every caller that accepts the CLI-facing string.
USE_NATIVE_BY_LOADER = {"auto": None, "python": False, "native": True}


class _NativeUnavailable(Exception):
    """Native loader absent (no toolchain / import failure) — a
    deterministic condition, not a transient fault: never retried, falls
    straight to the Python pipeline."""


def _load_native(path: str) -> EncodedHIN:
    from .native import gexf_native

    if not gexf_native.available():
        raise _NativeUnavailable()
    # Parse + encode in one native pass: no per-edge Python objects
    # (the marshalling, not the XML, dominates at dblp_large scale —
    # see scripts/parser_bench.py artifact).
    return gexf_native.read_gexf_encoded(path)


def load_dataset(
    path: str,
    use_native: bool | None = None,
    policy: RetryPolicy | None = None,
) -> EncodedHIN:
    """GEXF → EncodedHIN. ``use_native`` mirrors read_gexf's tri-state:
    None prefers the C++ single-pass parse+encode with clean fallback,
    False forces the exact Python pipeline (the escape hatch if the
    native path ever misbehaves), True requires native.

    This is the ``gexf_load`` failure seam: each read path is retried
    under ``policy``; with ``use_native=None`` a native loader that
    keeps failing transiently degrades to the exact Python pipeline
    (with a structured ``degrade`` event) instead of killing the run."""
    # A missing file is deterministic, not transient: without this
    # filter the OSError-retryable default would back off 3x against a
    # typo'd path and emit a misleading loader-degrade event before the
    # CLI's clean one-line error.
    policy = policy or resilience.policy_from_env()
    policy = policy.replace(
        non_retryable=policy.non_retryable + (FileNotFoundError,)
    )
    if use_native is not False:
        try:
            return resilience.resilient_call(
                "gexf_load", lambda: _load_native(path), policy
            )
        except FileNotFoundError:
            raise
        except (_NativeUnavailable, ImportError):
            if use_native is True:
                # ValueError: the CLI renders it as a clean one-liner.
                raise ValueError(
                    "native GEXF loader requested but unavailable "
                    "(no C++ toolchain?)"
                ) from None
            # Loader simply not built — the normal CPU-dev case; quiet.
        except (OSError, resilience.TransientError) as exc:
            if use_native is True:
                raise ValueError(f"native GEXF loader failed: {exc}") from exc
            resilience.degrade.record_degrade("loader")
            runtime_event(
                "degrade",
                component="loader",
                from_="native",
                to="python",
                error=repr(exc),
            )
    return resilience.resilient_call(
        "gexf_load",
        lambda: encode_hin(
            read_gexf(path, use_native=False if use_native is False else None)
        ),
        policy,
    )


def build(
    config: RunConfig, timer=None
) -> tuple[EncodedHIN, MetaPath, PathSimBackend, PathSimDriver]:
    """Full batch bootstrap: :func:`build_backend` plus the driver."""
    hin, metapath, backend = build_backend(config, timer=timer)
    driver = PathSimDriver(backend, variant=config.variant)
    return hin, metapath, backend, driver


def build_backend(
    config: RunConfig, timer=None
) -> tuple[EncodedHIN, MetaPath, PathSimBackend]:
    """``timer``: optional StageTimer; bootstrap phases (GEXF load +
    encode, metapath compile, backend init — which for the sparse
    backend includes the host half-chain fold) are recorded on it.

    Every bootstrap phase is a resilience seam: transient failures are
    retried per ``config.max_retries``; a backend whose init keeps
    failing steps down the degradation chain (jax-sharded → jax →
    numpy) unless ``config.degrade`` is False.

    This is also the serving layer's (re)load path: ``dpathsim serve``
    builds a backend here, wraps it in a PathSimService, and a graph
    reload builds another one and swaps it in — the driver object is
    batch-CLI-only, hence the split."""
    if timer is None:
        from .utils.profiling import StageTimer

        timer = StageTimer()
    # Bootstrap is where the first XLA programs compile — install the
    # process-wide compile counter hook before any backend exists so
    # the obs registry sees every compilation from the very first.
    from .utils.xla_flags import install_compile_metrics

    install_compile_metrics()
    # Tuning dispatch next, BEFORE any tile/variant decision is made
    # (backend init consults it): explicit --tuning-table, else the
    # PATHSIM_TUNING_TABLE deploy default, else heuristics. An unusable
    # table degrades to heuristics with one tuning_fallback event — it
    # never fails the bootstrap.
    from . import tuning

    tuning.set_enabled(config.tuning)
    if config.tuning:
        if config.tuning_table:
            tuning.install_table(config.tuning_table)
        else:
            tuning.install_from_env()
    if config.loader not in USE_NATIVE_BY_LOADER:
        raise ValueError(
            f"unknown loader {config.loader!r}; "
            f"choose from {sorted(USE_NATIVE_BY_LOADER)}"
        )
    policy = resilience.policy_from_env(max_attempts=config.max_retries)
    with timer.stage("load_encode"):
        hin = load_dataset(
            config.dataset,
            use_native=USE_NATIVE_BY_LOADER[config.loader],
            policy=policy,
        )
        if config.headroom:
            # Reserve append capacity BEFORE any backend sees a shape:
            # the delta-ingestion contract (data/delta.py) — results are
            # bit-identical, shapes survive node growth.
            from .data.delta import with_headroom

            hin = with_headroom(hin, config.headroom)
    with timer.stage("metapath_compile"):
        metapath = resilience.resilient_call(
            "metapath_compile",
            lambda: compile_metapath(config.metapath, hin.schema),
            policy,
        )
    options = backend_options(config)
    with timer.stage("backend_init"):
        backend = resilience.create_backend_resilient(
            config.backend,
            hin,
            metapath,
            policy=policy,
            degrade=config.degrade,
            **options,
        )
    return hin, metapath, backend


def backend_options(config: RunConfig) -> dict:
    """Backend constructor kwargs from a RunConfig — shared by the
    bootstrap above and the serving layer's delta-fallback rebuild
    (PathSimService's backend factory must replay the SAME knobs, or a
    rebuild would silently change dtype/tiling mid-serve)."""
    options: dict = {}
    if config.n_devices is not None:
        options["n_devices"] = config.n_devices
    if config.dtype:
        options["dtype"] = _resolve_dtype(config.backend, config.dtype)
    if config.tile_rows is not None:
        options["tile_rows"] = config.tile_rows
    if config.approx:
        options["exact_counts"] = False
    if config.factor_format is not None:
        options["factor_format"] = config.factor_format
    return options


def _resolve_dtype(backend: str, dtype: str):
    """Map the config's dtype string to the backend's array library.
    float64 on JAX backends requires x64 mode (jax.config.jax_enable_x64)."""
    if backend == "numpy":
        import numpy as np

        return np.dtype(dtype)
    import jax.numpy as jnp

    return jnp.dtype(dtype)
