"""Engine bootstrap: dataset → encoded HIN → compiled metapath → backend.

The analog of the reference's Spark bootstrap block
(``DPathSim_APVPA.py:146-168``) — except "starting the engine" here means
compiling a jit program, and the ``backend=`` flag (BASELINE.json) picks
the execution strategy instead of a pinned JVM package.
"""

from __future__ import annotations

from .backends.base import PathSimBackend, create_backend
from .config import RunConfig
from .data.encode import EncodedHIN, encode_hin
from .data.gexf import read_gexf
from .driver import PathSimDriver
from .ops.metapath import MetaPath, compile_metapath


# --loader choice → read path: None prefers native with clean fallback,
# False forces the exact Python pipeline, True requires native. One map,
# shared by every caller that accepts the CLI-facing string.
USE_NATIVE_BY_LOADER = {"auto": None, "python": False, "native": True}


def load_dataset(path: str, use_native: bool | None = None) -> EncodedHIN:
    """GEXF → EncodedHIN. ``use_native`` mirrors read_gexf's tri-state:
    None prefers the C++ single-pass parse+encode with clean fallback,
    False forces the exact Python pipeline (the escape hatch if the
    native path ever misbehaves), True requires native."""
    if use_native is not False:
        try:
            from .native import gexf_native

            if gexf_native.available():
                # Parse + encode in one native pass: no per-edge Python
                # objects (the marshalling, not the XML, dominates at
                # dblp_large scale — see scripts/parser_bench.py artifact).
                return gexf_native.read_gexf_encoded(path)
            if use_native is True:
                # ValueError: the CLI renders it as a clean one-liner.
                raise ValueError(
                    "native GEXF loader requested but unavailable "
                    "(no C++ toolchain?)"
                )
        except OSError as exc:  # toolchain/loader trouble: Python is exact
            if use_native is True:
                raise ValueError(f"native GEXF loader failed: {exc}") from exc
    graph = read_gexf(path, use_native=False if use_native is False else None)
    return encode_hin(graph)


def build(
    config: RunConfig, timer=None
) -> tuple[EncodedHIN, MetaPath, PathSimBackend, PathSimDriver]:
    """``timer``: optional StageTimer; bootstrap phases (GEXF load +
    encode, metapath compile, backend init — which for the sparse
    backend includes the host half-chain fold) are recorded on it."""
    if timer is None:
        from .utils.profiling import StageTimer

        timer = StageTimer()
    if config.loader not in USE_NATIVE_BY_LOADER:
        raise ValueError(
            f"unknown loader {config.loader!r}; "
            f"choose from {sorted(USE_NATIVE_BY_LOADER)}"
        )
    with timer.stage("load_encode"):
        hin = load_dataset(
            config.dataset, use_native=USE_NATIVE_BY_LOADER[config.loader]
        )
    with timer.stage("metapath_compile"):
        metapath = compile_metapath(config.metapath, hin.schema)
    options = {}
    if config.n_devices is not None:
        options["n_devices"] = config.n_devices
    if config.dtype:
        options["dtype"] = _resolve_dtype(config.backend, config.dtype)
    if config.tile_rows is not None:
        options["tile_rows"] = config.tile_rows
    if config.approx:
        options["exact_counts"] = False
    with timer.stage("backend_init"):
        backend = create_backend(config.backend, hin, metapath, **options)
    driver = PathSimDriver(backend, variant=config.variant)
    return hin, metapath, backend, driver


def _resolve_dtype(backend: str, dtype: str):
    """Map the config's dtype string to the backend's array library.
    float64 on JAX backends requires x64 mode (jax.config.jax_enable_x64)."""
    if backend == "numpy":
        import numpy as np

        return np.dtype(dtype)
    import jax.numpy as jnp

    return jnp.dtype(dtype)
