"""Command-line surface for the NeuralPathSim index family.

Separate from the reference-parity CLI (`cli.py`) on purpose: that
surface mirrors the reference's single-source/ranking workflows and
its flag matrix; this one owns the model lifecycle of the
beyond-parity index — train/save, then query the analytic
(Cauchy-quadrature) or learned index, or the two-stage exact rerank.

    python -m distributed_pathsim_tpu.neural_cli train \
      --dataset dblp_small.gexf --out model.npz --steps 600

    python -m distributed_pathsim_tpu.neural_cli query \
      --model model.npz --dataset dblp_small.gexf \
      --source "Didier Dubois" --top-k 5 --index struct

`--platform cpu` pins host execution (same tunnel-safety contract as
the main CLI); training honors `--variant` for textbook PathSim.
"""

from __future__ import annotations

import argparse
import sys

from .ops.pathsim import VARIANTS


def _load_hin(args):
    from .engine import USE_NATIVE_BY_LOADER, load_dataset

    return load_dataset(
        args.dataset, use_native=USE_NATIVE_BY_LOADER[args.loader]
    )


def _pin_platform(platform: str) -> None:
    """Same tunnel-safety contract as the main CLI — literally: reuse
    its platform pin (which also clears an inherited JAX_PLATFORMS=cpu
    before backend init) and its loud-TPU check."""
    from .cli import _apply_platform, _require_tpu

    _apply_platform(platform)
    if platform == "tpu":
        _require_tpu()


def cmd_train(args) -> int:
    _pin_platform(args.platform)
    from .models.neural import NeuralPathSim

    hin = _load_hin(args)
    model = NeuralPathSim(
        hin, args.metapath, dim=args.dim, hidden=args.hidden,
        lr=args.lr, seed=args.seed, variant=args.variant,
    )
    if args.mine:
        pool_src, pool_cand = model.mine_hard_candidates(
            args.mine, k=args.mine_k, seed=args.seed
        )
        model.set_hard_pool(pool_src, pool_cand)
    losses = model.train(steps=args.steps, batch_size=args.batch,
                         seed=args.seed)
    model.save(args.out)
    trajectory = (
        f" (loss {losses[0]:.3f} -> {losses[-1]:.3f})" if losses else ""
    )
    print(
        f"Trained {args.steps} steps on {model.n} "
        f"{model.metapath.source_type} nodes{trajectory}; "
        f"saved to {args.out}"
    )
    return 0


def cmd_query(args) -> int:
    _pin_platform(args.platform)
    from .models.neural import NeuralPathSim

    hin = _load_hin(args) if args.dataset else None
    model = NeuralPathSim.load(args.model, hin=hin)
    node_type = (
        model.metapath.source_type if model.metapath.node_types else None
    )
    if hin is not None and node_type:
        if hin.type_size(node_type) != model.n:
            raise ValueError(
                f"--dataset has {hin.type_size(node_type)} {node_type} "
                f"nodes but the checkpoint was trained on {model.n} — "
                "labels would be wrong; pass the training dataset"
            )
        index = hin.indices[node_type]
        src = hin.resolve_source(
            node_type, label=args.source, node_id=args.source_id
        )

        def show(t):
            return f"{index.labels[t]} ({index.ids[t]})"
    else:
        if args.source is not None:
            raise SystemExit(
                "--source needs --dataset for the label lookup; "
                "use --source-id with a bare integer index instead"
            )
        src = int(args.source_id)
        # Bare indexes bypass the resolver's existence check: reject
        # out-of-range (raw IndexError otherwise) and negative values
        # (numpy would silently wrap and rank the wrong node).
        if not 0 <= src < model.n:
            raise ValueError(
                f"--source-id {src} is out of range for this checkpoint "
                f"(valid bare indexes: 0..{model.n - 1})"
            )

        def show(t):
            return f"index {t}"

    if args.index == "struct":
        ranked = model.topk_struct(src, k=args.top_k)
    elif args.index == "learned":
        ranked = model.topk(src, k=args.top_k)
    else:  # rerank: embedding prefilter + exact re-scoring
        ranked = model.topk_rerank(
            src, k=args.top_k, candidates=args.candidates,
            index=args.prefilter,
        )
    print(f"Top-{args.top_k} by the {args.index} index "
          f"({model.variant} variant):")
    for t, score in ranked:
        print(f"  {score:.6f}  {show(t)}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="distributed_pathsim_tpu.neural_cli")
    sub = p.add_subparsers(dest="command", required=True)

    t = sub.add_parser("train", help="train + save a neural index")
    t.add_argument("--dataset", required=True)
    t.add_argument("--out", required=True, help="checkpoint path (.npz)")
    t.add_argument("--metapath", default="APVPA")
    t.add_argument("--variant", default="rowsum", choices=list(VARIANTS))
    t.add_argument("--steps", type=int, default=600)
    t.add_argument("--batch", type=int, default=1024)
    t.add_argument("--dim", type=int, default=64)
    t.add_argument("--hidden", type=int, default=128)
    t.add_argument("--lr", type=float, default=1e-3)
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--mine", type=int, default=0, metavar="T",
                   help="mine exact-teacher hard candidates for T "
                   "sources and train half of each batch on them "
                   "(0 = off; lifts top-k resolution on skewed graphs)")
    t.add_argument("--mine-k", type=int, default=64,
                   help="mined candidates per source (--mine)")
    t.add_argument("--loader", default="auto",
                   choices=("auto", "python", "native"))
    t.add_argument("--platform", default="auto",
                   choices=("auto", "cpu", "tpu"))
    t.set_defaults(fn=cmd_train)

    q = sub.add_parser("query", help="query a saved index")
    q.add_argument("--model", required=True)
    q.add_argument("--dataset", default=None,
                   help="re-attach labels (required for --source)")
    src = q.add_mutually_exclusive_group(required=True)
    src.add_argument("--source", help="query node by label")
    src.add_argument("--source-id",
                     help="query node by id (or bare index w/o --dataset)")
    q.add_argument("--top-k", type=int, default=10)
    q.add_argument("--index", default="rerank",
                   choices=("struct", "learned", "rerank"))
    q.add_argument("--candidates", type=int, default=100,
                   help="prefilter width for --index rerank")
    q.add_argument("--prefilter", default="struct",
                   choices=("struct", "learned"),
                   help="which embedding index prefilters for --index "
                   "rerank (learned = O(d) scan from the trained tower)")
    q.add_argument("--loader", default="auto",
                   choices=("auto", "python", "native"))
    q.add_argument("--platform", default="auto",
                   choices=("auto", "cpu", "tpu"))
    q.set_defaults(fn=cmd_query)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except (KeyError, ValueError, RuntimeError, OSError) as e:
        msg = e.args[0] if isinstance(e, KeyError) and e.args else e
        print(f"error: {msg}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
