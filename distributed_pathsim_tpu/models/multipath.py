"""Multi-metapath batched scoring (BASELINE.json config 4).

The reference hard-codes one metapath (APVPA) and would need a full
re-run of its 2N-1 joins per additional path. Here R symmetric metapaths
are compiled once, their half-chain factors C_r padded to a common
contraction width and stacked [R, N, Vmax], and all R commuting matrices
and score tensors come out of ONE batched einsum program — the batch
dimension rides the MXU. A weighted ensemble (Σ_r w_r · sim_r) gives the
multi-path similarity used in practice for HIN search.

Padding is semantically inert: C_r's extra columns are zero, adding zero
to every dot product.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..data.encode import EncodedHIN
from ..ops import chain
from ..utils.compat import shard_map
from ..ops.metapath import MetaPath, compile_metapath


@functools.partial(jax.jit, static_argnames=("variant",))
def _batched_scores(c_stack: jax.Array, variant: str = "rowsum"):
    """[R, N, V] → (scores [R, N, N], denominators [R, N]), all on
    device. "rowsum" is reference semantics; "diagonal" is textbook
    PathSim — per path, diag(M_r) = Σ_v C_r², no extra matmul."""
    with jax.default_matmul_precision("highest"):
        m = jnp.einsum("rnv,rmv->rnm", c_stack, c_stack)
        if variant == "rowsum":
            colsums = jnp.sum(c_stack, axis=1)  # [R, V]
            d = jnp.einsum("rnv,rv->rn", c_stack, colsums)
        elif variant == "diagonal":
            d = jnp.einsum("rnv,rnv->rn", c_stack, c_stack)
        else:
            raise ValueError(f"unknown PathSim variant {variant!r}")
    denom = d[:, :, None] + d[:, None, :]
    scores = jnp.where(denom > 0, 2.0 * m / jnp.where(denom > 0, denom, 1.0), 0.0)
    return scores, d


@jax.jit
def _combine(scores: jax.Array, weights: jax.Array):
    return jnp.einsum("rnm,r->nm", scores, weights)


@functools.partial(
    jax.jit, static_argnames=("mesh", "k", "n_true", "variant")
)
def _sharded_combined_topk(c_stack, weights, mesh, k: int, n_true: int,
                           variant: str = "rowsum"):
    """Distributed weighted multi-path top-k: the author axis of the
    stacked half-chain factors [R, N_pad, V] is row-sharded over ``dp``;
    each device scores its row block of ALL R paths in one batched
    einsum against the gathered factor, combines with the ensemble
    weights in VMEM-resident form, and reduces to top-k locally. The
    only collectives are one ``psum`` (per-path column totals) and the
    ``all_gather`` of the C stack — the [R, N, N] score tensors never
    exist anywhere.
    """
    from jax.sharding import PartitionSpec as P

    from ..ops.sparse import chunked_row_topk

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, "dp", None), P()),
        out_specs=(P("dp", None), P("dp", None)),
    )
    def run(c_loc, w):  # c_loc: [R, n_loc, V]
        n_loc = c_loc.shape[1]
        my = jax.lax.axis_index("dp")
        with jax.default_matmul_precision("highest"):
            if variant == "rowsum":
                colsums = jax.lax.psum(jnp.sum(c_loc, axis=1), "dp")
                d_loc = jnp.einsum("rnv,rv->rn", c_loc, colsums)
            elif variant == "diagonal":  # purely local, no collective
                d_loc = jnp.einsum("rnv,rnv->rn", c_loc, c_loc)
            else:
                raise ValueError(f"unknown PathSim variant {variant!r}")
            c_full = jax.lax.all_gather(c_loc, "dp", axis=1, tiled=True)
            d_full = jax.lax.all_gather(d_loc, "dp", axis=1, tiled=True)
            m = jnp.einsum("rnv,rmv->rnm", c_loc, c_full)  # [R, n_loc, N]
        denom = d_loc[:, :, None] + d_full[:, None, :]
        s = jnp.where(denom > 0, 2.0 * m / jnp.where(denom > 0, denom, 1.0), 0.0)
        comb = jnp.einsum("rnm,r->nm", s, w)
        rows = my * n_loc + jax.lax.broadcasted_iota(jnp.int32, comb.shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, comb.shape, 1)
        comb = jnp.where(cols >= n_true, -jnp.inf, comb)
        comb = jnp.where(rows == cols, -jnp.inf, comb)
        return chunked_row_topk(comb, cols, k)

    return run(c_stack, weights)


class MultiMetapathScorer:
    """Batched PathSim over several symmetric metapaths on one HIN."""

    def __init__(
        self,
        hin: EncodedHIN,
        metapaths: Sequence[MetaPath | str],
        dtype=jnp.float32,
        variant: str = "rowsum",
    ):
        from ..ops.pathsim import VARIANTS

        if variant not in VARIANTS:
            raise ValueError(
                f"unknown PathSim variant {variant!r}; choose {VARIANTS}"
            )
        self.variant = variant
        self.hin = hin
        self.metapaths: list[MetaPath] = [
            compile_metapath(m, hin.schema) if isinstance(m, str) else m
            for m in metapaths
        ]
        if not self.metapaths:
            raise ValueError("need at least one metapath")
        src_types = {m.source_type for m in self.metapaths}
        if len(src_types) != 1:
            raise ValueError(f"metapaths must share a source type, got {src_types}")
        for m in self.metapaths:
            if not m.is_symmetric:
                raise ValueError(f"metapath {m.name} is not symmetric")

        self.n = hin.type_size(self.metapaths[0].source_type)
        # Per-path half factors stay SPARSE at rest (shapes differ per
        # path; the dense [N, P] intermediate of a naive chain product
        # never exists — same discipline as the backends and the neural
        # trainer). The padded dense stack for the batched all-pairs
        # einsum is built lazily: a path like APA has contraction width
        # P (papers), and padding every path to that width is a
        # [R, N, P] tensor — ~700 GB at the 227k dblp_large
        # reconstruction — while the streaming single-source path only
        # ever touches the O(nnz) factors.
        from ..ops import planner

        self._coo = [
            planner.fold_half(hin, m).summed() for m in self.metapaths
        ]
        self._c_stack_cache: jax.Array | None = None
        self._scores: np.ndarray | None = None
        self._rowsums: np.ndarray | None = None

    @property
    def names(self) -> list[str]:
        return [m.name for m in self.metapaths]

    # Refuse to build the padded dense stack beyond this many f32
    # entries (default ≈ 8 GiB). The batched all-pairs methods need it;
    # the streaming single-source path never does.
    _DENSE_STACK_MAX_ENTRIES = 1 << 31

    def _stack(self) -> jax.Array:
        """The padded [R, N, Vmax] dense factor stack for the batched
        einsum paths, built lazily from the sparse factors."""
        if self._c_stack_cache is None:
            vmax = max(c.shape[1] for c in self._coo)
            entries = len(self._coo) * self.n * vmax
            if entries > self._DENSE_STACK_MAX_ENTRIES:
                wide = self.names[
                    int(np.argmax([c.shape[1] for c in self._coo]))
                ]
                raise MemoryError(
                    f"padded factor stack would be {len(self._coo)}x"
                    f"{self.n}x{vmax} f32 ({4 * entries / 2**30:.0f} GiB; "
                    f"widest path {wide}); the batched all-pairs methods "
                    "can't run at this scale — use topk_row (streaming "
                    "single-source, O(nnz)) instead"
                )
            stack = np.zeros(
                (len(self._coo), self.n, vmax), dtype=np.float32
            )
            for r, c in enumerate(self._coo):
                stack[r, c.rows, c.cols] = c.weights
            self._c_stack_cache = jnp.asarray(stack)
        return self._c_stack_cache

    def _compute(self):
        if self._scores is None:
            s, d = _batched_scores(self._stack(), variant=self.variant)
            d64 = np.asarray(d, dtype=np.float64)
            # Guard BEFORE caching: if the exactness check raises, the
            # streaming state must stay intact — otherwise a later
            # scores()/topk_row() call would silently serve the inexact
            # f32-derived cache, and an exact streaming _rowsums (from
            # global_walks) would have been clobbered (ADVICE r5).
            chain.check_exact_counts(d64.max(initial=0.0), np.float32)
            self._scores = np.asarray(s)
            self._rowsums = d64
        return self._scores, self._rowsums

    def _streaming_rowsums(self) -> np.ndarray:
        """[R, N] per-path denominators straight from the sparse
        factors — exact f64 integer bookkeeping (bincount sums), no
        dense stack, no [R, N, N]."""
        d_all = np.zeros((len(self._coo), self.n))
        for r, c in enumerate(self._coo):
            w = c.weights
            if self.variant == "rowsum":
                colsum = np.bincount(
                    c.cols, weights=w, minlength=c.shape[1]
                )
                d_all[r] = np.bincount(
                    c.rows, weights=w * colsum[c.cols], minlength=self.n
                )
            else:  # diagonal: Σ_v C[i,v]²
                d_all[r] = np.bincount(
                    c.rows, weights=w * w, minlength=self.n
                )
        return d_all

    def _row_scores_streaming(self, row: int) -> np.ndarray:
        """Per-path single-source score rows [R, N] — the B=1 case of
        :meth:`_rows_scores_streaming` (one implementation, so the
        serving layer's batched path can never diverge from it)."""
        return self._rows_scores_streaming(np.asarray([row]))[:, 0, :]

    def _rows_scores_streaming(self, rows: np.ndarray) -> np.ndarray:
        """Batched streaming score rows [R, B, N] in O(B·Σ_r nnz_r):
        sim_r(row_b, j) = 2·(C_r[row_b]·C_r[j]) / (d_r[row_b] + d_r[j])
        with the numerators as one sparse gather-multiply-scatter per
        path for the WHOLE batch. Exact f64 (integer counts sum exactly
        below 2⁵³, so accumulation order is irrelevant) — the same
        exactness contract the single-row path has always had, now
        amortizing the per-path COO walk over every row the serving
        coalescer packed into the bucket. The dense stack never exists."""
        rows = np.asarray(rows, dtype=np.int64)
        d_all = self.global_walks()  # cached [R, N]; exact either way
        out = np.zeros((len(self._coo), rows.shape[0], self.n))
        for r, c in enumerate(self._coo):
            w = c.weights
            src = np.zeros((rows.shape[0], c.shape[1]))
            for b, row in enumerate(rows):
                mask = c.rows == row
                src[b, c.cols[mask]] = w[mask]  # coalesced: 1/col
            # cc[b, i] = Σ_e w_e · src[b, col_e] over entries of row i.
            # bincount per batch row, NOT one np.add.at scatter: add.at
            # is an unbuffered per-element ufunc loop, ~10-100× slower
            # than bincount's C path — and B=1 here IS the pre-existing
            # single-source CLI ensemble at dense-infeasible nnz.
            gathered = src[:, c.cols]  # [B, nnz]
            cc = np.stack([
                np.bincount(
                    c.rows, weights=w * gathered[b], minlength=self.n
                )
                for b in range(rows.shape[0])
            ])
            denom = d_all[r, rows][:, None] + d_all[r][None, :]
            out[r] = np.where(denom > 0, 2.0 * cc / np.where(
                denom > 0, denom, 1.0), 0.0)
        return out

    def scores(self) -> np.ndarray:
        """[R, N, N] per-path score tensors."""
        return self._compute()[0]

    def global_walks(self) -> np.ndarray:
        """[R, N] per-path denominators (the reference's global walks
        under "rowsum"; diag(M_r) under "diagonal"). Streams from the
        sparse factors unless the dense all-pairs cache already paid
        for itself — the CLI header must not force an [R, N, N]."""
        if self._rowsums is None:
            self._rowsums = self._streaming_rowsums()
        return self._rowsums

    def _resolve_weights(self, weights: Sequence[float] | None) -> np.ndarray:
        """Uniform default / float32 cast / shape check — one place, so
        the host and sharded paths can never diverge on weight handling."""
        r = len(self.metapaths)
        w = (
            np.full(r, 1.0 / r, dtype=np.float32)
            if weights is None
            else np.asarray(weights, dtype=np.float32)
        )
        if w.shape != (r,):
            raise ValueError(f"need {r} weights, got shape {w.shape}")
        return w

    def combined_scores(self, weights: Sequence[float] | None = None) -> np.ndarray:
        """Weighted multi-path similarity: Σ_r w_r · sim_r, [N, N].
        Default weights are uniform (mean over paths)."""
        self._compute()
        w = self._resolve_weights(weights)
        return np.asarray(_combine(jnp.asarray(self._scores), jnp.asarray(w)))

    def topk(self, k: int = 10, weights: Sequence[float] | None = None):
        """Top-k per source under the combined similarity.
        argpartition (O(N² + N·k log k)) rather than a full row sort."""
        s = self.combined_scores(weights).copy()
        np.fill_diagonal(s, -np.inf)
        k = min(k, s.shape[1] - 1)
        part = np.argpartition(-s, k - 1, axis=1)[:, :k]
        part_vals = np.take_along_axis(s, part, axis=1)
        order = np.argsort(-part_vals, axis=1, kind="stable")
        idxs = np.take_along_axis(part, order, axis=1)
        vals = np.take_along_axis(part_vals, order, axis=1)
        return vals, idxs

    def topk_sharded(
        self,
        k: int = 10,
        weights: Sequence[float] | None = None,
        n_devices: int | None = None,
    ):
        """Distributed :meth:`topk` over a ``dp`` device mesh (config-4
        batching × config-3 sharding): identical values and the
        ascending-column tie-breaks of ``lax.top_k`` (NB: :meth:`topk`'s
        host argpartition is value-identical but breaks ties
        arbitrarily). Scales the batched ensemble past one device's
        memory — the [R, N, N] score tensors never materialize.
        """
        from ..parallel.mesh import make_mesh, pad_to_multiple

        mesh = make_mesh(n_devices)
        w = self._resolve_weights(weights)
        n_pad = pad_to_multiple(self.n, mesh.shape["dp"])
        stack = self._stack()
        if n_pad != self.n:
            stack = jnp.pad(stack, ((0, 0), (0, n_pad - self.n), (0, 0)))
        vals, idxs = _sharded_combined_topk(
            stack, jnp.asarray(w), mesh, k=min(k, self.n - 1),
            n_true=self.n, variant=self.variant,
        )
        return (
            np.asarray(vals, dtype=np.float64)[: self.n],
            np.asarray(idxs, dtype=np.int64)[: self.n],
        )

    def topk_rows(
        self,
        rows,
        k: int = 10,
        weights: Sequence[float] | None = None,
    ):
        """Batched :meth:`topk_row` — the serving coalescer's dispatch
        unit for multi-metapath services: (values f64 [B, k], indices
        int64 [B, k]). ALWAYS the streaming exact-f64 O(B·nnz) path;
        the dense f32 all-pairs cache is deliberately not reused, so
        results are call-order independent — the same query on the same
        scorer must not change scores or tie orders depending on
        whether an all-pairs method ran first (ADVICE r5). Tie order is
        (descending score, ascending column), the oracle convention the
        single-backend serving path uses."""
        from ..ops import pathsim

        w = self._resolve_weights(weights).astype(np.float64)
        rows = np.asarray(rows, dtype=np.int64)
        s = np.einsum("rbn,r->bn", self._rows_scores_streaming(rows), w)
        s[np.arange(rows.shape[0]), rows] = -np.inf
        return pathsim.topk_from_score_rows(
            s, min(k, max(s.shape[1] - 1, 1))
        )

    def topk_row(self, row: int, k: int = 10, weights: Sequence[float] | None = None):
        """Top-k for ONE source row — the B=1 case of :meth:`topk_rows`
        (identical code path, so the coalesced serving dispatch can
        never diverge from the direct CLI query)."""
        vals, idxs = self.topk_rows(
            np.asarray([row], dtype=np.int64), k=k, weights=weights
        )
        return vals[0], idxs[0]
