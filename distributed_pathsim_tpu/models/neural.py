"""Neural PathSim: learned embeddings that approximate metapath similarity.

Exact PathSim ranks with O(N·V) work per query and cannot score nodes
added after encoding. Following the Neural-PathSim idea (inductive
similarity search in HINs — see PAPERS.md; pattern only, clean-room
implementation), a two-tower MLP maps each node's metapath feature
vector (its row of the half-chain factor C, degree-normalized) to a
d-dim embedding trained so that  σ-free inner products reproduce the
exact PathSim scores computed by this framework's own backends. Queries
become O(d) dot products; unseen nodes embed through the same tower.

Training is TPU-native data parallelism: the pair batch is sharded over
the ``dp`` mesh axis via explicit shardings on a jit'd optax step —
XLA inserts the gradient psum. The same step runs on one chip, 8 virtual
CPU devices (tests), or a real slice.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data.encode import EncodedHIN
from ..ops import chain
from ..ops.metapath import MetaPath, compile_metapath


class TwoTower(nn.Module):
    """Shared-weight encoder tower: features → embedding."""

    hidden: int = 128
    dim: int = 64

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        return nn.Dense(self.dim)(x)


@dataclasses.dataclass
class TrainState:
    params: dict
    opt_state: optax.OptState
    step: int = 0


class NeuralPathSim:
    """Trainer + index for embedding-based PathSim approximation."""

    def __init__(
        self,
        hin: EncodedHIN,
        metapath: MetaPath | str,
        dim: int = 64,
        hidden: int = 128,
        lr: float = 1e-3,
        mesh: Mesh | None = None,
        seed: int = 0,
    ):
        self.hin = hin
        self.metapath = (
            compile_metapath(metapath, hin.schema)
            if isinstance(metapath, str)
            else metapath
        )
        if not self.metapath.is_symmetric:
            raise ValueError("NeuralPathSim needs a symmetric metapath")
        self.mesh = mesh

        blocks = chain.oriented_dense_blocks(
            hin, self.metapath.half(), dtype=np.float32
        )
        c = blocks[0]
        for b in blocks[1:]:
            c = c @ b
        self._setup_from_c(c, dim=dim, hidden=hidden, lr=lr, seed=seed)

    def _setup_from_c(
        self, c: np.ndarray, dim: int, hidden: int, lr: float, seed: int
    ) -> None:
        """Derive all trainer state from the half-chain factor C — shared
        by the constructor and :meth:`load`."""
        self._config = {"dim": dim, "hidden": hidden, "lr": lr, "seed": seed}
        self.n, self.v = c.shape
        # Exact targets (rowsum-variant PathSim) are computed ON DEMAND per
        # batch from the half-chain factor C — never the dense N×N matrix,
        # so the trainer scales to graphs where exact all-pairs can't exist.
        self._c64 = c.astype(np.float64)
        self._d = self._c64 @ self._c64.sum(axis=0)  # row sums of M = C·Cᵀ
        # Positive-sample pool without touching M: a pair sharing any
        # contraction column (venue) has M[i,j] > 0, so sample a nonzero of
        # C then a co-occupant of its column. CSC-style column lists make
        # each draw O(1).
        nz_i, nz_v = np.nonzero(c)
        order = np.argsort(nz_v, kind="stable")
        self._nz_rows, nz_cols = nz_i[order], nz_v[order]
        self._col_ptr = np.searchsorted(nz_cols, np.arange(self.v + 1))
        # features: degree-normalized C rows (unit L2 where nonzero) PLUS
        # the degree itself. The rowsum is half of every score's
        # denominator, and unit normalization erases exactly that
        # magnitude — without it the tower cannot distinguish a prolific
        # venue-mate (low score) from a sparse one (high score), which
        # is what the ranking turns on.
        norms = np.linalg.norm(c, axis=1, keepdims=True)
        c_norm = (c / np.where(norms > 0, norms, 1)).astype(np.float32)
        deg = np.log1p(self._d)
        deg = (deg / max(float(deg.max(initial=0.0)), 1.0)).astype(np.float32)
        self.features = np.concatenate([c_norm, deg[:, None]], axis=1)
        # Standardized regression target: raw scores shrink like
        # 1/rowsum (~1e-3 at 65k authors), and MSE on them converges to
        # "predict 0 everywhere" — tiny loss, no ranking. Scale so the
        # mean positive target is O(1); ordering is unaffected and
        # predict_pairs divides back. Deterministic from (C, seed), so
        # save/load rebuilds the identical scale.
        rng0 = np.random.default_rng(seed)
        nnz = len(self._nz_rows)
        if nnz:
            sel = rng0.integers(0, nnz, size=min(4096, nnz))
            pr = self._nz_rows[sel]
            v0 = np.searchsorted(self._col_ptr, sel, side="right") - 1
            lo, hi = self._col_ptr[v0], self._col_ptr[v0 + 1]
            pc = self._nz_rows[lo + rng0.integers(0, np.maximum(hi - lo, 1))]
            pos = self.pair_scores(pr, pc)
            mean_pos = float(pos[pos > 0].mean()) if (pos > 0).any() else 0.0
        else:
            mean_pos = 0.0
        self.target_scale = 1.0 / mean_pos if mean_pos > 0 else 1.0
        self._scores_cache: np.ndarray | None = None
        self._emb_cache: np.ndarray | None = None

        self.model = TwoTower(hidden=hidden, dim=dim)
        rng = jax.random.PRNGKey(seed)
        params = self.model.init(
            rng, jnp.zeros((1, self.features.shape[1]), jnp.float32)
        )
        self.tx = optax.adam(lr)
        self.state = TrainState(params=params, opt_state=self.tx.init(params))
        self._train_step = self._build_train_step()

    # -- training ----------------------------------------------------------

    def _build_train_step(self):
        model, tx = self.model, self.tx

        def loss_fn(params, fi, fj, target):
            ei = model.apply(params, fi)
            ej = model.apply(params, fj)
            pred = jnp.sum(ei * ej, axis=-1)
            return jnp.mean((pred - target) ** 2)

        def step(params, opt_state, fi, fj, target):
            loss, grads = jax.value_and_grad(loss_fn)(params, fi, fj, target)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        if self.mesh is None:
            return jax.jit(step)
        # Data-parallel: batch axes sharded over dp, params replicated.
        # jit + shardings → XLA adds the psum over per-device gradients.
        repl = NamedSharding(self.mesh, P())
        batch = NamedSharding(self.mesh, P("dp"))
        return jax.jit(
            step,
            in_shardings=(repl, repl, batch, batch, batch),
            out_shardings=(repl, repl, repl),
        )

    def pair_scores(self, i: np.ndarray, j: np.ndarray) -> np.ndarray:
        """Exact rowsum-variant PathSim for arbitrary pairs, O(batch·V):
        2·(C[i]·C[j]) / (d[i]+d[j]) — no N×N matrix involved."""
        i = np.asarray(i)
        j = np.asarray(j)
        num = 2.0 * np.einsum("bv,bv->b", self._c64[i], self._c64[j])
        denom = self._d[i] + self._d[j]
        return np.where(denom > 0, num / np.where(denom > 0, denom, 1), 0.0)

    def sample_batch(self, batch_size: int, rng: np.random.Generator):
        """Half random pairs, half positive (nonzero-score) pairs so the
        mostly-zero score distribution doesn't drown the signal. Positives
        come from shared contraction columns (same venue ⇒ M[i,j] > 0);
        targets are computed on demand — everything is O(batch·V)."""
        n_pos = batch_size // 2
        i_rand = rng.integers(0, self.n, size=batch_size - n_pos)
        j_rand = rng.integers(0, self.n, size=batch_size - n_pos)
        nnz = len(self._nz_rows)
        if nnz:
            sel = rng.integers(0, nnz, size=n_pos)
            pos_rows = self._nz_rows[sel]
            # a random co-occupant of the same column
            v = np.searchsorted(self._col_ptr, sel, side="right") - 1
            lo, hi = self._col_ptr[v], self._col_ptr[v + 1]
            pos_cols = self._nz_rows[
                lo + rng.integers(0, np.maximum(hi - lo, 1))
            ]
        else:
            pos_rows = rng.integers(0, self.n, size=n_pos)
            pos_cols = rng.integers(0, self.n, size=n_pos)
        i = np.concatenate([i_rand, pos_rows])
        j = np.concatenate([j_rand, pos_cols])
        return i, j, self.pair_scores(i, j).astype(np.float32)

    def train(self, steps: int = 200, batch_size: int = 1024, seed: int = 0):
        """Run optimizer steps; returns the per-step loss history."""
        rng = np.random.default_rng(seed)
        losses = []
        # invalidate up front: params change from the first step, and an
        # exception mid-loop must not leave a stale cache behind
        self._emb_cache = None
        for _ in range(steps):
            i, j, target = self.sample_batch(batch_size, rng)
            fi = jnp.asarray(self.features[i])
            fj = jnp.asarray(self.features[j])
            params, opt_state, loss = self._train_step(
                self.state.params, self.state.opt_state, fi, fj,
                jnp.asarray(target * self.target_scale),
            )
            self.state = TrainState(params, opt_state, self.state.step + 1)
            losses.append(float(loss))
        return losses

    # -- inference ---------------------------------------------------------

    def embeddings(self, features: np.ndarray | None = None) -> np.ndarray:
        """Embed the given features, or the full corpus (cached — training
        invalidates the cache, so repeated queries don't re-run the MLP)."""
        if features is not None:
            return np.asarray(
                self.model.apply(
                    self.state.params, jnp.asarray(features, jnp.float32)
                )
            )
        if self._emb_cache is None:
            emb = np.asarray(
                self.model.apply(
                    self.state.params, jnp.asarray(self.features, jnp.float32)
                )
            )
            # read-only so a caller's in-place edit can't corrupt later
            # predict_pairs/topk results through the shared cache
            emb.flags.writeable = False
            self._emb_cache = emb
        return self._emb_cache

    def predict_pairs(self, i: Sequence[int], j: Sequence[int]) -> np.ndarray:
        """Approximate PathSim scores (inner products un-scaled back to
        score units — training regresses ``score · target_scale``)."""
        i = np.asarray(i)
        j = np.asarray(j)
        if self._emb_cache is not None:
            e = self._emb_cache
            return np.sum(e[i] * e[j], axis=-1) / self.target_scale
        # no corpus cache yet: embed only the requested rows
        ei = self.embeddings(self.features[i])
        ej = self.embeddings(self.features[j])
        return np.sum(ei * ej, axis=-1) / self.target_scale

    def topk(self, source_index: int, k: int = 10) -> list[tuple[int, float]]:
        e = self.embeddings()
        sims = (e @ e[source_index]) / self.target_scale
        sims[source_index] = -np.inf
        order = np.argsort(-sims)[:k]
        return [(int(t), float(sims[t])) for t in order]

    def topk_rerank(
        self, source_index: int, k: int = 10, candidates: int = 100
    ) -> list[tuple[int, float]]:
        """Two-stage query: the embedding index prefilters ``candidates``
        targets (O(N·d) scan), then the EXACT score re-ranks them
        (O(candidates·V) host math). Measured at 65k authors, d=64, the
        raw index's recall@10 is ~0.05 — the embedding resolves coarse
        structure, not the near-tie ordering the exact top-10 turns on —
        while the re-ranked two-stage query recovers most of it (see
        NEURAL_r03.json). Returned scores are exact for the candidates
        considered."""
        e = self.embeddings()
        sims = e @ e[source_index]
        sims[source_index] = -np.inf
        cand = np.argpartition(-sims, min(candidates, self.n - 1))[:candidates]
        cand = cand[cand != source_index]
        exact = self.pair_scores(np.full(len(cand), source_index), cand)
        order = np.argsort(-exact, kind="stable")[:k]
        return [(int(cand[t]), float(exact[t])) for t in order]

    # Refuse to densify the exact score matrix beyond this many entries.
    _DENSE_SCORES_MAX_ENTRIES = 1 << 26

    def exact_scores(self) -> np.ndarray:
        """The dense supervision-target matrix (exact rowsum-variant
        PathSim), for validation on small graphs. Guarded: training never
        needs it — use :meth:`pair_scores` for O(batch) exact targets."""
        if self._scores_cache is None:
            if self.n * self.n > self._DENSE_SCORES_MAX_ENTRIES:
                raise MemoryError(
                    f"dense scores would be {self.n}x{self.n}; "
                    "use pair_scores(i, j)"
                )
            from ..ops.pathsim import score_matrix

            self._scores_cache = score_matrix(
                self._c64 @ self._c64.T, variant="rowsum", xp=np
            )
        return self._scores_cache

    # -- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        """Persist the trained model to one ``.npz`` file: tower params,
        optimizer state, step counter, hyperparameters, metapath name, and
        the half-chain factor C (from which every derived structure —
        features, row sums, positive pool — is rebuilt on load). Written
        atomically so a crash mid-save can't corrupt an earlier snapshot.

        The reference has no model state at all (SURVEY.md §5,
        checkpoint row); this is the checkpoint/resume capability for the
        framework's learned-index model family.
        """
        import json
        import os

        from flax import serialization

        payload = {
            "c": self._c64.astype(np.float32),
            "params": np.frombuffer(
                serialization.to_bytes(self.state.params), dtype=np.uint8
            ),
            "opt_state": np.frombuffer(
                serialization.to_bytes(self.state.opt_state), dtype=np.uint8
            ),
            "step": np.int64(self.state.step),
            "config": np.frombuffer(
                json.dumps(
                    {**self._config, "metapath": self.metapath.name}
                ).encode(),
                dtype=np.uint8,
            ),
        }
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:  # stream: no second in-memory copy of C
            np.savez_compressed(f, **payload)
        os.replace(tmp, path)

    @classmethod
    def load(
        cls,
        path: str,
        hin: EncodedHIN | None = None,
        mesh: Mesh | None = None,
    ) -> "NeuralPathSim":
        """Restore a model saved by :meth:`save`.

        ``hin`` is optional: inference and resumed training only need the
        stored C factor. Pass it (with the same graph) to re-attach label
        lookups via ``self.hin``; the metapath is re-compiled against it,
        otherwise only its name survives the round-trip.
        """
        import json

        from flax import serialization

        with np.load(path) as z:
            c = z["c"]
            params_bytes = z["params"].tobytes()
            opt_bytes = z["opt_state"].tobytes()
            step = int(z["step"])
            config = json.loads(z["config"].tobytes().decode())

        metapath_name = config.pop("metapath")
        self = cls.__new__(cls)
        self.hin = hin
        self.metapath = (
            compile_metapath(metapath_name, hin.schema)
            if hin is not None
            else MetaPath(name=metapath_name, node_types=(), steps=())
        )
        self.mesh = mesh
        self._setup_from_c(c, **config)
        params = serialization.from_bytes(self.state.params, params_bytes)
        opt_state = serialization.from_bytes(self.state.opt_state, opt_bytes)
        self.state = TrainState(params=params, opt_state=opt_state, step=step)
        return self
