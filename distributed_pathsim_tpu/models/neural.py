"""Neural PathSim: a factorized analytic index + learned compact embeddings.

Exact PathSim ranks with O(N·V) work per query and cannot score nodes
added after encoding. This module provides two inner-product indexes
over the half-chain factor C (built sparsely — the dense N×P
intermediate of the naive chain product never exists):

1. **Structural (Cauchy-quadrature) index** — the rowsum-variant score
   2·(C_i·C_j)/(d_i+d_j) looks non-factorizable because the denominator
   couples i and j additively, but the Cauchy kernel identity
   1/(d_i+d_j) = ∫₀^∞ e^(-t·d_i) · e^(-t·d_j) dt turns it into an inner
   product: with log-spaced quadrature nodes t_k and weights w_k,
   φ(j) = vec_k( sqrt(2·w_k) · e^(-d_j·t_k) · C_j )  ∈ R^(m·V)
   satisfies φ(i)·φ(j) ≈ score(i,j) to ~3% RELATIVE error uniformly
   over 9 decades of d (m=12 suffices; measured rerank recall@10 = 1.0
   at 65k authors). No training, exact-by-construction ranking signal,
   inductive (new nodes embed analytically from their C row).

2. **Learned compact index** — a two-tower MLP compresses the same
   information into d≪m·V dims for O(d) queries, trained with a
   LISTWISE RANKING loss (per-source softmax cross-entropy against the
   exact-score distribution over a candidate slate) plus a small MSE
   calibration term that keeps raw inner products in score units for
   ``predict_pairs``. Plain MSE alone converges to "predict the
   magnitude, miss the order" — the ranking term optimizes what top-k
   retrieval actually turns on (see the Neural-PathSim idea in
   PAPERS.md; pattern only, clean-room implementation).

Training is TPU-native data parallelism: the source axis of the slate
batch is sharded over the ``dp`` mesh axis via explicit shardings on a
jit'd optax step — XLA inserts the gradient psum. The same step runs on
one chip, 8 virtual CPU devices (tests), or a real slice.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data.encode import EncodedHIN
from ..ops.metapath import MetaPath, compile_metapath


def cauchy_quadrature(
    d: np.ndarray, m: int = 12, margin: float = 2.0
) -> tuple[np.ndarray, np.ndarray]:
    """Log-spaced quadrature (nodes t, weights w) for the Cauchy kernel
    identity 1/(d_i+d_j) = ∫₀^∞ e^(-t·d_i)·e^(-t·d_j) dt over the
    observed denominator range: s = d_i + d_j ∈ [2·min d⁺, 2·max d],
    extended by ``margin`` on each side in u = log t (the trapezoid
    rule needs tail room for uniform relative accuracy). Shared by the
    trainer's feature gates and the index/ subsystem's analytic
    embedding map — one definition so the two can never drift."""
    d = np.asarray(d, dtype=np.float64)
    dpos = d[d > 0]
    if not dpos.size:  # degenerate graph: every denominator is zero
        return np.zeros(m), np.zeros(m)
    s_lo = max(2.0 * float(dpos.min()), 1e-12)
    s_hi = max(2.0 * float(dpos.max()), s_lo * (1.0 + 1e-9))
    u = np.linspace(
        np.log(1.0 / s_hi) - margin, np.log(1.0 / s_lo) + margin, m
    )
    h = float(u[1] - u[0]) if m > 1 else 1.0
    t = np.exp(u)
    return t, h * t


def quadrature_gates(d: np.ndarray, t: np.ndarray) -> np.ndarray:
    """Denominator gates E[j,k] = e^(-d_j·t_k) ∈ [0,1] (f32): the
    complete quadrature picture of 1/(d_j + ·)."""
    return np.exp(
        -np.clip(
            np.asarray(d, np.float64)[:, None] * np.asarray(t)[None, :],
            0.0, 700.0,
        )
    ).astype(np.float32)


class TwoTower(nn.Module):
    """Shared-weight encoder tower: features → embedding."""

    hidden: int = 128
    dim: int = 64

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        return nn.Dense(self.dim)(x)


@dataclasses.dataclass
class TrainState:
    params: dict
    opt_state: optax.OptState
    step: int = 0


class NeuralPathSim:
    """Trainer + index for embedding-based PathSim approximation."""

    # Optimizer-state pytree identity, stamped into checkpoints and
    # verified on load — one definition site so save() and load() can
    # never drift apart (a checkpoint saved under a different optax
    # chain must fail with a NAMED error, not a msgpack mismatch).
    _OPT_FORMAT = "clip1.0-adam-huber5-v2"

    def __init__(
        self,
        hin: EncodedHIN,
        metapath: MetaPath | str,
        dim: int = 64,
        hidden: int = 128,
        lr: float = 1e-3,
        mesh: Mesh | None = None,
        seed: int = 0,
        variant: str = "rowsum",
    ):
        self.hin = hin
        self.metapath = (
            compile_metapath(metapath, hin.schema)
            if isinstance(metapath, str)
            else metapath
        )
        if not self.metapath.is_symmetric:
            raise ValueError("NeuralPathSim needs a symmetric metapath")
        self.mesh = mesh

        # Sparse half-chain fold straight to [N, V] (V is the small
        # contraction width). The dense [N, P] intermediate of a naive
        # chain product would be ~86 GB at the 65k x 327k bench shape —
        # backends/jax_dense.py:94 refuses it for the same reason.
        from ..ops import planner

        c = planner.dense_half(hin, self.metapath)
        self._setup_from_c(
            c, dim=dim, hidden=hidden, lr=lr, seed=seed, variant=variant
        )

    # Quadrature width for the structural index: m log-spaced nodes
    # cover the full observed range of 2·d with ~3% max relative error
    # (m=12, margin=2 measured 7.1%-max/1.5%-mean on 9 decades; ranking
    # only needs relative fidelity, and rerank recall@10 at 65k authors
    # measured 1.0 — see NEURAL_r04.json).
    QUAD_M = 12
    _QUAD_MARGIN = 2.0

    def _setup_from_c(
        self, c: np.ndarray, dim: int, hidden: int, lr: float, seed: int,
        variant: str = "rowsum",
        target_scale: float | None = None,
        quad: tuple[np.ndarray, np.ndarray] | None = None,
    ) -> None:
        """Derive all trainer state from the half-chain factor C — shared
        by the constructor and :meth:`load`. ``target_scale`` and
        ``quad`` (nodes, weights) override the from-C derivation when
        restoring a checkpoint: both must match what the params were
        trained against, and a recompute from the f32-cast stored C
        could drift."""
        from ..ops.pathsim import VARIANTS

        if variant not in VARIANTS:
            raise ValueError(
                f"unknown PathSim variant {variant!r}; choose {VARIANTS}"
            )
        self.variant = variant
        self._config = {"dim": dim, "hidden": hidden, "lr": lr,
                        "seed": seed, "variant": variant}
        self.n, self.v = c.shape
        # Exact targets are computed ON DEMAND per batch from the
        # half-chain factor C — never the dense N×N matrix, so the
        # trainer scales to graphs where exact all-pairs can't exist.
        # Every downstream structure (quadrature, gates, targets, both
        # indexes) is generic in the denominator vector, so the variant
        # choice is made exactly once, here.
        self._c64 = c.astype(np.float64)
        if variant == "rowsum":
            self._d = self._c64 @ self._c64.sum(axis=0)  # rowsums of M
        else:  # diagonal: diag(M)[i] = Σ_v C[i,v]²
            self._d = np.einsum("nv,nv->n", self._c64, self._c64)
        # Cauchy-quadrature nodes for the structural index (module-level
        # cauchy_quadrature — shared with index/build.py's embedding map).
        if quad is not None:
            self._quad_t = np.asarray(quad[0], dtype=np.float64)
            self._quad_w = np.asarray(quad[1], dtype=np.float64)
        else:
            self._quad_t, self._quad_w = cauchy_quadrature(
                self._d, m=self.QUAD_M, margin=self._QUAD_MARGIN
            )
        # Denominator gates (quadrature_gates): the complete quadrature
        # picture of 1/(d_i + ·); also fed to the tower as well-scaled
        # features (log1p(d) alone is a single number; the gates give
        # the MLP the kernel the exact score actually uses).
        self._gates = quadrature_gates(self._d, self._quad_t)
        # Positive-sample pool without touching M: a pair sharing any
        # contraction column (venue) has M[i,j] > 0, so sample a nonzero of
        # C then a co-occupant of its column. CSC-style column lists make
        # each draw O(1). np.nonzero returns row-major order, so nz_i is
        # already sorted — the same arrays double as a CSR layout for
        # per-SOURCE candidate slates (columns of one source's row).
        nz_i, nz_v = np.nonzero(c)
        self._row_ptr = np.searchsorted(nz_i, np.arange(self.n + 1))
        self._row_cols = nz_v
        order = np.argsort(nz_v, kind="stable")
        self._nz_rows, nz_cols = nz_i[order], nz_v[order]
        self._col_ptr = np.searchsorted(nz_cols, np.arange(self.v + 1))
        # features: degree-normalized C rows (unit L2 where nonzero) PLUS
        # the degree itself PLUS the quadrature gates. The rowsum is half
        # of every score's denominator, and unit normalization erases
        # exactly that magnitude — without it the tower cannot
        # distinguish a prolific venue-mate (low score) from a sparse one
        # (high score), which is what the ranking turns on.
        norms = np.linalg.norm(c, axis=1, keepdims=True)
        c_norm = (c / np.where(norms > 0, norms, 1)).astype(np.float32)
        deg = np.log1p(self._d)
        deg = (deg / max(float(deg.max(initial=0.0)), 1.0)).astype(np.float32)
        self.features = np.concatenate(
            [c_norm, deg[:, None], self._gates], axis=1
        )
        # Standardized regression target: raw scores shrink like
        # 1/rowsum (~1e-3 at 65k authors), and MSE on them converges to
        # "predict 0 everywhere" — tiny loss, no ranking. Scale so the
        # mean positive target is O(1); ordering is unaffected and
        # predict_pairs divides back. Persisted in checkpoints (a
        # recompute from the f32-cast stored C could drift from the
        # scale the params were trained against).
        if target_scale is not None:
            self.target_scale = float(target_scale)
        else:
            rng0 = np.random.default_rng(seed)
            nnz = len(self._nz_rows)
            if nnz:
                sel = rng0.integers(0, nnz, size=min(4096, nnz))
                pr = self._nz_rows[sel]
                v0 = np.searchsorted(self._col_ptr, sel, side="right") - 1
                lo, hi = self._col_ptr[v0], self._col_ptr[v0 + 1]
                pc = self._nz_rows[
                    lo + rng0.integers(0, np.maximum(hi - lo, 1))
                ]
                pos = self.pair_scores(pr, pc)
                mean_pos = (
                    float(pos[pos > 0].mean()) if (pos > 0).any() else 0.0
                )
            else:
                mean_pos = 0.0
            self.target_scale = 1.0 / mean_pos if mean_pos > 0 else 1.0
        self._scores_cache: np.ndarray | None = None
        self._emb_cache: np.ndarray | None = None
        self._struct_cache: np.ndarray | None = None
        self._c32_cache: np.ndarray | None = None
        self._feat_dev = None
        # Hard-candidate pool for distillation-style slate sampling
        # (mine_hard_candidates / set_hard_pool). Not persisted by
        # save(): mining is a cheap, deterministic device pass.
        self._hard_src: np.ndarray | None = None
        self._hard_cand: np.ndarray | None = None

        self.model = TwoTower(hidden=hidden, dim=dim)
        rng = jax.random.PRNGKey(seed)
        params = self.model.init(
            rng, jnp.zeros((1, self.features.shape[1]), jnp.float32)
        )
        # global-norm clipping ahead of Adam: the ranking loss is
        # scale-free but slates from extreme-skew rows can still spike
        # a step's gradient (second stabilizer next to the Huber
        # calibration term)
        self.tx = optax.chain(
            optax.clip_by_global_norm(1.0), optax.adam(lr)
        )
        self.state = TrainState(params=params, opt_state=self.tx.init(params))
        self._train_step = self._build_train_step()

    # -- training ----------------------------------------------------------

    # Slate geometry and loss mix. The listwise term is a softmax cross-
    # entropy per source over SLATE candidates: the target distribution
    # is softmax(score/τ) with a per-row adaptive τ = max(score)/γ, so
    # every slate contributes the same sharpness regardless of its
    # absolute score scale (scores span decades with node degree). The
    # small MSE term keeps raw inner products calibrated to
    # score·target_scale so predict_pairs stays meaningful.
    SLATE = 32
    _RANK_GAMMA = 8.0
    # Fraction of each batch's sources drawn from the mined hard pool
    # when one is installed (set_hard_pool); the rest stay uniform so
    # unmined sources keep gradient coverage.
    HARD_FRAC = 0.5
    # λ sweep at 200 nodes, 600 steps, with the Huber calibration
    # (r04): 0.3 → corr .78/recall .72, 1.0 → corr .88/recall .76.
    # Under plain MSE high λ traded recall for calibration (.91/.69);
    # Huber's capped tail gradient removes the tradeoff, so take the
    # calibration margin.
    _MSE_WEIGHT = 1.0

    def _build_train_step(self):
        model, tx = self.model, self.tx
        gamma, lam = self._RANK_GAMMA, self._MSE_WEIGHT

        def loss_fn(params, feat, src_idx, cand_idx, target):
            # feat [N, F] (device-resident corpus); src_idx [B];
            # cand_idx [B, S]; target [B, S] (scaled). Gathering on
            # device means each step ships B·(S+1) int32 indices over
            # the host link instead of B·(S+1)·F f32 feature rows —
            # at the 227k/V=4111 reconstruction that is ~1 KB/step
            # versus ~135 MB/step through the tunnel.
            f_src = jnp.take(feat, src_idx, axis=0)
            f_cand = jnp.take(
                feat, cand_idx.reshape(-1), axis=0
            ).reshape((*cand_idx.shape, feat.shape[1]))
            e_src = model.apply(params, f_src)
            e_cand = model.apply(params, f_cand)
            pred = jnp.einsum("bd,bsd->bs", e_src, e_cand)
            row_max = jnp.max(target, axis=1, keepdims=True)
            tau = jnp.where(row_max > 0, row_max / gamma, 1.0)
            q = jax.nn.softmax(target / tau, axis=1)
            # true KL(q ‖ softmax(pred)): the target-entropy term is
            # constant in params (same gradients as plain CE) but pins
            # the floor at 0, so the loss trajectory reads as distance
            # from a perfect per-slate ordering.
            logq = jax.nn.log_softmax(target / tau, axis=1)
            rank = jnp.mean(
                jnp.sum(q * (logq - jax.nn.log_softmax(pred, axis=1)), axis=1)
            )
            # Huber, not plain MSE: scaled targets are heavy-tailed on
            # skewed graphs (mega-venue rows), and squared error on the
            # tail DIVERGED in practice — 4000 steps on the dblp_large
            # reconstruction blew the loss from 3.6 to 65 (see
            # NEURAL_r04.json real-skew records). Quadratic near zero
            # keeps the calibration; linear beyond δ caps the tail's
            # gradient.
            cal = jnp.mean(optax.huber_loss(pred, target, delta=5.0))
            return rank + lam * cal

        def step(params, opt_state, feat, src_idx, cand_idx, target):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, feat, src_idx, cand_idx, target
            )
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        if self.mesh is None:
            return jax.jit(step)
        # Data-parallel: the SOURCE axis of the slate batch is sharded
        # over dp, params and the feature corpus replicated. jit +
        # shardings → XLA adds the psum over per-device gradients; the
        # gather of replicated features by dp-sharded indices stays
        # local to each device.
        repl = NamedSharding(self.mesh, P())
        batch = NamedSharding(self.mesh, P("dp"))
        return jax.jit(
            step,
            in_shardings=(repl, repl, repl, batch, batch, batch),
            out_shardings=(repl, repl, repl),
        )

    def _features_device(self):
        """The full feature corpus resident on device (replicated under
        a mesh), placed once and cached — every train step and corpus
        embedding pass gathers from it instead of re-shipping rows."""
        if self._feat_dev is None:
            feat = jnp.asarray(self.features, jnp.float32)
            if self.mesh is not None:
                feat = jax.device_put(
                    feat, NamedSharding(self.mesh, P())
                )
            self._feat_dev = feat
        return self._feat_dev

    def pair_scores(self, i: np.ndarray, j: np.ndarray) -> np.ndarray:
        """Exact PathSim (this model's variant) for arbitrary pairs,
        O(batch·V): 2·(C[i]·C[j]) / (d[i]+d[j]) — no N×N matrix
        involved."""
        i = np.asarray(i)
        j = np.asarray(j)
        num = 2.0 * np.einsum("bv,bv->b", self._c64[i], self._c64[j])
        denom = self._d[i] + self._d[j]
        return np.where(denom > 0, num / np.where(denom > 0, denom, 1), 0.0)

    def sample_batch(self, batch_size: int, rng: np.random.Generator):
        """One slate batch: B = batch_size // SLATE sources, each with a
        SLATE-candidate list — half venue co-occupants of the source
        (nonzero exact score, the pairs ranking is decided on), half
        uniform negatives so the mostly-zero background stays
        represented. Targets are exact pair scores computed on demand —
        O(B·S·V), never N×N. Returns (src [B], cand [B, S], target
        [B, S]).

        When a hard pool is installed (:meth:`set_hard_pool`), the
        first ``HARD_FRAC`` of the batch's sources are drawn from the
        pool and most of their random-negative slots are replaced by
        their mined exact-top candidates — the slates the top-k
        ordering is actually decided on. Random venue co-occupant
        sampling alone almost never surfaces a skewed graph's true
        top-10 (a mega-venue co-occupant is overwhelmingly likely and
        scores near zero), which is why the r04 learned tower stalled
        at 0.66–0.77 rerank recall on the dblp_large reconstruction."""
        s = self.SLATE
        b = max(1, batch_size // s)
        if self.mesh is not None:
            # the source axis is the dp-sharded axis: round up to a
            # device multiple so any batch_size stays mesh-valid
            nd = self.mesh.shape["dp"]
            b = -(-b // nd) * nd
        src = rng.integers(0, self.n, size=b)
        hard_rows = 0
        if self._hard_src is not None and len(self._hard_src):
            # at least one pool row even when b == 1 (a tiny batch must
            # not silently disable the installed pool)
            hard_rows = min(b, max(1, int(round(b * self.HARD_FRAC))))
            pool_idx = rng.integers(0, len(self._hard_src), size=hard_rows)
            src[:hard_rows] = self._hard_src[pool_idx]
        cand = rng.integers(0, self.n, size=(b, s))
        n_pos = s // 2
        if len(self._row_cols):
            lo, hi = self._row_ptr[src], self._row_ptr[src + 1]
            has = hi > lo
            if has.any():
                # a random nonzero column of each source...
                sel = lo[:, None] + rng.integers(
                    0, np.maximum((hi - lo)[:, None], 1), size=(b, n_pos)
                )
                v = self._row_cols[np.minimum(sel, len(self._row_cols) - 1)]
                # ...then a random co-occupant of that column
                clo, chi = self._col_ptr[v], self._col_ptr[v + 1]
                cc = self._nz_rows[
                    clo + rng.integers(0, np.maximum(chi - clo, 1))
                ]
                cand[:, :n_pos] = np.where(has[:, None], cc, cand[:, :n_pos])
        if hard_rows:
            # Overwrite most of the RANDOM half for pool rows with
            # mined top candidates, keeping the co-occupant half and at
            # least s//8 uniform negatives so the background stays in
            # every slate's softmax.
            kk = self._hard_cand.shape[1]
            n_hard = min(kk, s - n_pos - max(1, s // 8))
            if n_hard > 0:
                pick = rng.integers(0, kk, size=(hard_rows, n_hard))
                cand[:hard_rows, n_pos:n_pos + n_hard] = self._hard_cand[
                    pool_idx[:, None], pick
                ]
        if self.mesh is not None and hard_rows:
            # The dp mesh shards the source axis CONTIGUOUSLY, and hard
            # pool rows were just assembled at the front of the batch —
            # without a shuffle every hard slate lands on the low-index
            # devices, skewing per-device gradients (and per-device
            # work) for the whole run (ADVICE r5). One permutation
            # restores exchangeability; slates stay intact because src,
            # cand, and (downstream) tgt are permuted together. Gated
            # on an installed pool: a pool-less batch is already
            # exchangeable, and consuming rng state for it would break
            # sharded == single-device batch parity.
            perm = rng.permutation(b)
            src = src[perm]
            cand = cand[perm]
        tgt = self.pair_scores(
            np.repeat(src, s), cand.reshape(-1)
        ).reshape(b, s)
        return src, cand, tgt.astype(np.float32)

    def train(self, steps: int = 200, batch_size: int = 1024, seed: int = 0):
        """Run optimizer steps; returns the per-step loss history.
        ``batch_size`` counts PAIRS (sources × slate), so throughput is
        comparable with the r03 pairwise trainer at equal batch_size.
        Under a mesh the source count rounds UP to a device multiple
        (sample_batch), so small batches train slightly larger rather
        than failing the dp-sharding divisibility check."""
        rng = np.random.default_rng(seed)
        losses = []
        # invalidate up front: params change from the first step, and an
        # exception mid-loop must not leave a stale cache behind
        self._emb_cache = None
        feat = self._features_device()
        idx_sharding = None
        if self.mesh is not None:
            idx_sharding = NamedSharding(self.mesh, P("dp"))
        for _ in range(steps):
            src, cand, target = self.sample_batch(batch_size, rng)
            src_idx = jnp.asarray(src, jnp.int32)
            cand_idx = jnp.asarray(cand, jnp.int32)
            tgt = jnp.asarray(target * self.target_scale)
            if idx_sharding is not None:
                src_idx = jax.device_put(src_idx, idx_sharding)
                cand_idx = jax.device_put(cand_idx, idx_sharding)
                tgt = jax.device_put(tgt, idx_sharding)
            params, opt_state, loss = self._train_step(
                self.state.params, self.state.opt_state, feat,
                src_idx, cand_idx, tgt,
            )
            self.state = TrainState(params, opt_state, self.state.step + 1)
            losses.append(float(loss))
        return losses

    # -- distillation: exact-teacher hard-candidate mining ----------------

    def mine_hard_candidates(
        self,
        n_sources: int,
        k: int = 64,
        seed: int = 0,
        exclude: Sequence[int] | None = None,
        chunk: int = 256,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Mine exact top-``k`` candidate lists for a pool of sources in
        one batched device pass — the exact score is its own perfect
        teacher (VERDICT r04 #4: "draw training slates from index
        candidates"). Per source chunk the score rows factorize as
        2·(C_S Cᵀ)/(d_S ⊕ d): an O(T·N·V) MXU matmul plus elementwise
        work and an on-device top-k. At the 227k dblp_large
        reconstruction (V=4111, T=2048) that is ~3.8e15 flops — minutes
        on one chip, a full day on this host's single core.

        ``exclude`` keeps a benchmark's held-out evaluation sources out
        of the mined pool. Returns ``(sources [T], cands [T, k])`` host
        arrays; install them with :meth:`set_hard_pool`.
        """
        if self.n < 2:
            raise ValueError("hard-candidate mining needs >= 2 nodes")
        k = min(k, self.n - 1)
        avail = np.arange(self.n)
        if exclude is not None and len(np.asarray(exclude)):
            avail = avail[~np.isin(avail, np.asarray(exclude))]
        rng = np.random.default_rng(seed)
        n_sources = min(n_sources, len(avail))
        sources = np.sort(rng.choice(avail, size=n_sources, replace=False))
        # The teacher must actually be exact: counts and row sums are
        # integers, exact in f32 only below 2²⁴ (and only if the matmul
        # runs full f32 passes — TPU f32 matmuls default to bf16
        # passes, whose exact-integer range ends at 256).
        from ..ops.chain import check_exact_counts

        check_exact_counts(float(self._d.max(initial=0.0)), np.float32)
        # C and d are jit ARGUMENTS, not closure captures: a captured
        # device array is baked into the lowered module as a constant,
        # and the axon tunnel's remote-compile endpoint rejects the
        # multi-GB request body (HTTP 413 at the 227k/V=4111 shape —
        # 3.7 GB of captured constants). Arguments ride the normal
        # buffer path and the executable is reused across chunks.
        c_dev = jnp.asarray(self._c32())
        d_dev = jnp.asarray(self._d.astype(np.float32))

        @jax.jit
        def _chunk_topk(c_all, d_all, idx):
            cs = jnp.take(c_all, idx, axis=0)          # [T, V]
            ds = jnp.take(d_all, idx)                  # [T]
            with jax.default_matmul_precision("highest"):
                cc = cs @ c_all.T                      # [T, N] on the MXU
            denom = ds[:, None] + d_all[None, :]
            sims = jnp.where(denom > 0, 2.0 * cc / denom, 0.0)
            sims = sims.at[jnp.arange(idx.shape[0]), idx].set(-jnp.inf)
            return jax.lax.top_k(sims, k)[1]

        cands = np.empty((n_sources, k), dtype=np.int64)
        for lo in range(0, n_sources, chunk):
            idx = sources[lo:lo + chunk]
            take = len(idx)
            if take < chunk and n_sources > chunk:
                # pad the tail chunk to the compiled shape (static
                # shapes: one executable for the whole sweep)
                idx = np.concatenate(
                    [idx, np.full(chunk - take, idx[-1], dtype=idx.dtype)]
                )
            out = np.asarray(
                _chunk_topk(c_dev, d_dev, jnp.asarray(idx, jnp.int32))
            )
            cands[lo:lo + take] = out[:take]
        return sources, cands

    def set_hard_pool(self, sources: np.ndarray, cands: np.ndarray) -> None:
        """Install a mined hard-candidate pool; subsequent
        :meth:`train` batches draw ``HARD_FRAC`` of their sources from
        it with slates built from the mined lists (see
        :meth:`sample_batch`). Not persisted by :meth:`save` — mining
        is a cheap deterministic device pass, re-run it after load."""
        # copies, not views: a caller mutating its buffer after install
        # would silently bypass the range validation below
        sources = np.array(sources, copy=True)
        cands = np.array(cands, copy=True)
        if (
            sources.ndim != 1
            or cands.ndim != 2
            or len(sources) != len(cands)
        ):
            raise ValueError(
                "hard pool must be (sources [T], cands [T, K]) with "
                f"matching T; got {sources.shape} / {cands.shape}"
            )
        if not (
            np.issubdtype(sources.dtype, np.integer)
            and np.issubdtype(cands.dtype, np.integer)
        ):
            raise ValueError("hard pool must hold integer node indexes")
        for name, a in (("sources", sources), ("cands", cands)):
            if a.size and (a.min() < 0 or a.max() >= self.n):
                # a pool persisted from a different graph: a negative
                # index would silently wrap and train slates against
                # the wrong node's exact score; fail at install time
                raise ValueError(
                    f"hard pool {name} out of range for this model "
                    f"(n={self.n}): [{a.min()}, {a.max()}]"
                )
        sources.flags.writeable = False
        cands.flags.writeable = False
        self._hard_src, self._hard_cand = sources, cands

    def clear_hard_pool(self) -> None:
        self._hard_src = self._hard_cand = None

    # -- inference ---------------------------------------------------------

    def _c32(self) -> np.ndarray:
        """f32 view of the half-chain factor for device/index math
        (cached; read-only so index paths can't corrupt it)."""
        if self._c32_cache is None:
            c32 = self._c64.astype(np.float32)
            c32.flags.writeable = False
            self._c32_cache = c32
        return self._c32_cache

    def embeddings(self, features: np.ndarray | None = None) -> np.ndarray:
        """Embed the given features, or the full corpus (cached — training
        invalidates the cache, so repeated queries don't re-run the MLP)."""
        if features is not None:
            return np.asarray(
                self.model.apply(
                    self.state.params, jnp.asarray(features, jnp.float32)
                )
            )
        if self._emb_cache is None:
            emb = np.asarray(
                self.model.apply(self.state.params, self._features_device())
            )
            # read-only so a caller's in-place edit can't corrupt later
            # predict_pairs/topk results through the shared cache
            emb.flags.writeable = False
            self._emb_cache = emb
        return self._emb_cache

    def predict_pairs(self, i: Sequence[int], j: Sequence[int]) -> np.ndarray:
        """Approximate PathSim scores (inner products un-scaled back to
        score units — training regresses ``score · target_scale``)."""
        i = np.asarray(i)
        j = np.asarray(j)
        if self._emb_cache is not None:
            e = self._emb_cache
            return np.sum(e[i] * e[j], axis=-1) / self.target_scale
        # no corpus cache yet: embed only the requested rows
        ei = self.embeddings(self.features[i])
        ej = self.embeddings(self.features[j])
        return np.sum(ei * ej, axis=-1) / self.target_scale

    def struct_embeddings(self) -> np.ndarray:
        """The analytic Cauchy-quadrature feature map φ [N, m·V]:
        φ(i)·φ(j) ≈ exact rowsum-variant PathSim to the quadrature's
        uniform relative error (~3–7% at m=12 over 9 decades of degree).
        No training involved; cached lazily (f32, m·V·4 bytes per node —
        ~3 GB at 1M authors × V=64, build it only if struct queries are
        used)."""
        if self._struct_cache is None:
            w = np.sqrt(2.0 * self._quad_w).astype(np.float32)
            c32 = self._c32()
            phi = (
                w[None, :, None] * self._gates[:, :, None] * c32[:, None, :]
            ).reshape(self.n, -1)
            phi.flags.writeable = False
            self._struct_cache = phi
        return self._struct_cache

    def topk(self, source_index: int, k: int = 10) -> list[tuple[int, float]]:
        e = self.embeddings()
        sims = (e @ e[source_index]) / self.target_scale
        sims[source_index] = -np.inf
        order = np.argsort(-sims)[:k]
        return [(int(t), float(sims[t])) for t in order]

    def struct_sims(self, source_index: int) -> np.ndarray:
        """Struct-index similarities of every node to ``source_index``
        WITHOUT materializing φ: the quadrature inner product
        factorizes, φ(i)·φ(j) = (C_i·C_j) · Σ_k 2·w_k·e^(-t_k·d_i)·
        e^(-t_k·d_j), so one query is an O(N·V) matvec plus an O(N·m)
        gate contraction. The materialized φ scan is O(N·m·V) and the
        map itself is [N, m·V] — ~45 GB at the dblp_large
        reconstruction's V=4111 — so this factorization is what makes
        the analytic index usable at realistic venue cardinality
        (ADVICE r04 #4). ``struct_embeddings`` remains for the
        inductive per-node embedding API on narrow factors."""
        c32 = self._c32()
        cc = c32 @ c32[source_index]
        gi = (
            2.0 * self._quad_w * self._gates[source_index]
        ).astype(np.float32)
        gg = self._gates @ gi
        return cc.astype(np.float64) * gg.astype(np.float64)

    def topk_struct(
        self, source_index: int, k: int = 10
    ) -> list[tuple[int, float]]:
        """Top-k by the structural index alone — returned scores are the
        quadrature approximations of the exact scores (same units)."""
        sims = self.struct_sims(source_index)
        sims[source_index] = -np.inf
        order = np.argsort(-sims)[:k]
        return [(int(t), float(sims[t])) for t in order]

    def topk_rerank(
        self,
        source_index: int,
        k: int = 10,
        candidates: int = 100,
        index: str = "struct",
    ) -> list[tuple[int, float]]:
        """Two-stage query: an embedding index prefilters ``candidates``
        targets (O(N·dim) scan), then the EXACT score re-ranks them
        (O(candidates·V) host math). ``index`` picks the prefilter:
        "struct" (default) uses the analytic Cauchy map — measured
        rerank recall@10 = 1.0 at 65k authors (NEURAL_r04.json);
        "learned" uses the compact trained tower for O(d) scans.
        Returned scores are exact for the candidates considered.

        The rerank routes through the SAME candidate-restricted exact
        primitives the serving ANN path uses (ops/pathsim.
        score_candidates + topk_from_candidate_scores), so both honor
        the oracle tie order (descending score, ascending column) and
        are bit-identical to the full exact top-k whenever the true
        top-k is inside the candidate set. The previous bespoke sort
        broke boundary ties by candidate-*position* (argpartition
        order), which could disagree with the exact engine on tied
        scores."""
        from ..ops.pathsim import score_candidates, topk_from_candidate_scores

        if index == "struct":
            sims = self.struct_sims(source_index)
        elif index == "learned":
            e = self.embeddings()
            sims = e @ e[source_index]
        else:
            raise ValueError(f"unknown index {index!r}")
        sims[source_index] = -np.inf
        cand = np.argpartition(-sims, min(candidates, self.n - 1))[:candidates]
        cand = cand[cand != source_index].astype(np.int64)
        # exact integer counts for the candidate columns only — O(C·V),
        # the same numbers the backend's full pairwise row carries
        counts = self._c64[cand] @ self._c64[source_index]
        scores = score_candidates(
            counts[None, :],
            np.asarray([self._d[source_index]]),
            self._d[cand][None, :],
        )
        vals, idxs = topk_from_candidate_scores(scores, cand[None, :], k)
        return [
            (int(j), float(v))
            for v, j in zip(vals[0], idxs[0])
            if np.isfinite(v)
        ]

    # Refuse to densify the exact score matrix beyond this many entries.
    _DENSE_SCORES_MAX_ENTRIES = 1 << 26

    def exact_scores(self) -> np.ndarray:
        """The dense supervision-target matrix (exact rowsum-variant
        PathSim), for validation on small graphs. Guarded: training never
        needs it — use :meth:`pair_scores` for O(batch) exact targets."""
        if self._scores_cache is None:
            if self.n * self.n > self._DENSE_SCORES_MAX_ENTRIES:
                raise MemoryError(
                    f"dense scores would be {self.n}x{self.n}; "
                    "use pair_scores(i, j)"
                )
            from ..ops.pathsim import score_matrix

            self._scores_cache = score_matrix(
                self._c64 @ self._c64.T, variant=self.variant, xp=np
            )
        return self._scores_cache

    # -- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        """Persist the trained model to one ``.npz`` file: tower params,
        optimizer state, step counter, hyperparameters, metapath name, and
        the half-chain factor C (from which every derived structure —
        features, row sums, positive pool — is rebuilt on load). Written
        atomically so a crash mid-save can't corrupt an earlier snapshot.

        The reference has no model state at all (SURVEY.md §5,
        checkpoint row); this is the checkpoint/resume capability for the
        framework's learned-index model family.
        """
        import json
        import os

        from flax import serialization

        payload = {
            "c": self._c64.astype(np.float32),
            "params": np.frombuffer(
                serialization.to_bytes(self.state.params), dtype=np.uint8
            ),
            "opt_state": np.frombuffer(
                serialization.to_bytes(self.state.opt_state), dtype=np.uint8
            ),
            "step": np.int64(self.state.step),
            # target_scale and the quadrature are persisted verbatim: a
            # recompute from the f32-cast C above could drift from the
            # values the params were trained against (silently wrong
            # predict_pairs units / feature gates).
            "target_scale": np.float64(self.target_scale),
            "quad_t": self._quad_t,
            "quad_w": self._quad_w,
            "config": np.frombuffer(
                json.dumps(
                    {
                        **self._config,
                        "metapath": self.metapath.name,
                        # optimizer-state pytree identity: a checkpoint
                        # saved under a different optimizer chain must
                        # fail with a NAMED error, not a flax/msgpack
                        # structure mismatch
                        "opt_format": self._OPT_FORMAT,
                    }
                ).encode(),
                dtype=np.uint8,
            ),
        }
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:  # stream: no second in-memory copy of C
            np.savez_compressed(f, **payload)
        os.replace(tmp, path)

    @classmethod
    def load(
        cls,
        path: str,
        hin: EncodedHIN | None = None,
        mesh: Mesh | None = None,
    ) -> "NeuralPathSim":
        """Restore a model saved by :meth:`save`.

        ``hin`` is optional: inference and resumed training only need the
        stored C factor. Pass it (with the same graph) to re-attach label
        lookups via ``self.hin``; the metapath is re-compiled against it,
        otherwise only its name survives the round-trip.
        """
        import json

        from flax import serialization

        with np.load(path) as z:
            c = z["c"]
            params_bytes = z["params"].tobytes()
            opt_bytes = z["opt_state"].tobytes()
            step = int(z["step"])
            config = json.loads(z["config"].tobytes().decode())
            if "target_scale" not in z or "quad_t" not in z:
                # Pre-r04 checkpoints cannot load even by recomputation:
                # the r04 feature map added QUAD_M gate columns, so the
                # stored tower params no longer match the first dense
                # layer — fail with the reason, not a flax shape error.
                raise ValueError(
                    f"{path!r} is a pre-r04 NeuralPathSim checkpoint "
                    "(no quadrature record); its tower was trained on "
                    "gate-free features and cannot be restored — "
                    "re-train and re-save"
                )
            target_scale = float(z["target_scale"])
            quad = (z["quad_t"], z["quad_w"])

        metapath_name = config.pop("metapath")
        opt_format = config.pop("opt_format", None)
        if opt_format != cls._OPT_FORMAT:
            raise ValueError(
                f"{path!r} was saved under optimizer format "
                f"{opt_format!r}; this build uses {cls._OPT_FORMAT!r} "
                "(different opt_state pytree) — re-train and re-save"
            )
        self = cls.__new__(cls)
        self.hin = hin
        self.metapath = (
            compile_metapath(metapath_name, hin.schema)
            if hin is not None
            else MetaPath(name=metapath_name, node_types=(), steps=())
        )
        self.mesh = mesh
        self._setup_from_c(c, **config, target_scale=target_scale, quad=quad)
        params = serialization.from_bytes(self.state.params, params_bytes)
        opt_state = serialization.from_bytes(self.state.opt_state, opt_bytes)
        self.state = TrainState(params=params, opt_state=opt_state, step=step)
        return self
