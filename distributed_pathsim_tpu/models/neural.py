"""Neural PathSim: learned embeddings that approximate metapath similarity.

Exact PathSim ranks with O(N·V) work per query and cannot score nodes
added after encoding. Following the Neural-PathSim idea (inductive
similarity search in HINs — see PAPERS.md; pattern only, clean-room
implementation), a two-tower MLP maps each node's metapath feature
vector (its row of the half-chain factor C, degree-normalized) to a
d-dim embedding trained so that  σ-free inner products reproduce the
exact PathSim scores computed by this framework's own backends. Queries
become O(d) dot products; unseen nodes embed through the same tower.

Training is TPU-native data parallelism: the pair batch is sharded over
the ``dp`` mesh axis via explicit shardings on a jit'd optax step —
XLA inserts the gradient psum. The same step runs on one chip, 8 virtual
CPU devices (tests), or a real slice.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data.encode import EncodedHIN
from ..ops import chain
from ..ops.metapath import MetaPath, compile_metapath


class TwoTower(nn.Module):
    """Shared-weight encoder tower: features → embedding."""

    hidden: int = 128
    dim: int = 64

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        return nn.Dense(self.dim)(x)


@dataclasses.dataclass
class TrainState:
    params: dict
    opt_state: optax.OptState
    step: int = 0


class NeuralPathSim:
    """Trainer + index for embedding-based PathSim approximation."""

    def __init__(
        self,
        hin: EncodedHIN,
        metapath: MetaPath | str,
        dim: int = 64,
        hidden: int = 128,
        lr: float = 1e-3,
        mesh: Mesh | None = None,
        seed: int = 0,
    ):
        self.hin = hin
        self.metapath = (
            compile_metapath(metapath, hin.schema)
            if isinstance(metapath, str)
            else metapath
        )
        if not self.metapath.is_symmetric:
            raise ValueError("NeuralPathSim needs a symmetric metapath")
        self.mesh = mesh

        blocks = chain.oriented_dense_blocks(
            hin, self.metapath.half(), dtype=np.float32
        )
        c = blocks[0]
        for b in blocks[1:]:
            c = c @ b
        self.n, self.v = c.shape
        # exact targets (rowsum-variant PathSim) from the oracle chain
        from ..ops.pathsim import score_matrix

        c64 = c.astype(np.float64)
        self._scores = score_matrix(c64 @ c64.T, variant="rowsum", xp=np)
        # nonzero pairs, precomputed once: positive-sample pool for training
        self._pos_i, self._pos_j = np.nonzero(self._scores)
        # features: degree-normalized C rows (unit L2 where nonzero)
        norms = np.linalg.norm(c, axis=1, keepdims=True)
        self.features = (c / np.where(norms > 0, norms, 1)).astype(np.float32)

        self.model = TwoTower(hidden=hidden, dim=dim)
        rng = jax.random.PRNGKey(seed)
        params = self.model.init(rng, jnp.zeros((1, self.v), jnp.float32))
        self.tx = optax.adam(lr)
        self.state = TrainState(params=params, opt_state=self.tx.init(params))
        self._train_step = self._build_train_step()

    # -- training ----------------------------------------------------------

    def _build_train_step(self):
        model, tx = self.model, self.tx

        def loss_fn(params, fi, fj, target):
            ei = model.apply(params, fi)
            ej = model.apply(params, fj)
            pred = jnp.sum(ei * ej, axis=-1)
            return jnp.mean((pred - target) ** 2)

        def step(params, opt_state, fi, fj, target):
            loss, grads = jax.value_and_grad(loss_fn)(params, fi, fj, target)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        if self.mesh is None:
            return jax.jit(step)
        # Data-parallel: batch axes sharded over dp, params replicated.
        # jit + shardings → XLA adds the psum over per-device gradients.
        repl = NamedSharding(self.mesh, P())
        batch = NamedSharding(self.mesh, P("dp"))
        return jax.jit(
            step,
            in_shardings=(repl, repl, batch, batch, batch),
            out_shardings=(repl, repl, repl),
        )

    def sample_batch(self, batch_size: int, rng: np.random.Generator):
        """Half random pairs, half positive (nonzero-score) pairs so the
        mostly-zero score matrix doesn't drown the signal. The positive
        pool is precomputed in __init__ — sampling is O(batch)."""
        n_pos = batch_size // 2
        i_rand = rng.integers(0, self.n, size=batch_size - n_pos)
        j_rand = rng.integers(0, self.n, size=batch_size - n_pos)
        if len(self._pos_i):
            sel = rng.integers(0, len(self._pos_i), size=n_pos)
            pos_rows, pos_cols = self._pos_i[sel], self._pos_j[sel]
        else:
            pos_rows = rng.integers(0, self.n, size=n_pos)
            pos_cols = rng.integers(0, self.n, size=n_pos)
        i = np.concatenate([i_rand, pos_rows])
        j = np.concatenate([j_rand, pos_cols])
        return i, j, self._scores[i, j].astype(np.float32)

    def train(self, steps: int = 200, batch_size: int = 1024, seed: int = 0):
        """Run optimizer steps; returns the per-step loss history."""
        rng = np.random.default_rng(seed)
        losses = []
        for _ in range(steps):
            i, j, target = self.sample_batch(batch_size, rng)
            fi = jnp.asarray(self.features[i])
            fj = jnp.asarray(self.features[j])
            params, opt_state, loss = self._train_step(
                self.state.params, self.state.opt_state, fi, fj,
                jnp.asarray(target),
            )
            self.state = TrainState(params, opt_state, self.state.step + 1)
            losses.append(float(loss))
        return losses

    # -- inference ---------------------------------------------------------

    def embeddings(self, features: np.ndarray | None = None) -> np.ndarray:
        f = self.features if features is None else features
        return np.asarray(
            self.model.apply(self.state.params, jnp.asarray(f, jnp.float32))
        )

    def predict_pairs(self, i: Sequence[int], j: Sequence[int]) -> np.ndarray:
        e = self.embeddings()
        return np.sum(e[np.asarray(i)] * e[np.asarray(j)], axis=-1)

    def topk(self, source_index: int, k: int = 10) -> list[tuple[int, float]]:
        e = self.embeddings()
        sims = e @ e[source_index]
        sims[source_index] = -np.inf
        order = np.argsort(-sims)[:k]
        return [(int(t), float(sims[t])) for t in order]

    def exact_scores(self) -> np.ndarray:
        """The supervision targets (exact rowsum-variant PathSim)."""
        return self._scores
