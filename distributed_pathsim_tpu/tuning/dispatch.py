"""Runtime dispatch: the one process-wide tuning-table consultation.

Consumers (kernel wrappers, backend builds, serving warmup) call
:func:`choose` with the knob name, their shape context, and their own
heuristic as ``default``. Resolution ladder:

- exact key hit → the tuned choice (``dpathsim_tuning_lookups_total``
  counter, result="hit");
- miss → nearest-bucket interpolation within the same (knob, device,
  dtype) (result="nearest");
- nothing applicable, tuning disabled, or no table installed → the
  caller's heuristic (result="default").

A table that was *requested* but unusable (absent / corrupt /
version-mismatched) degrades to heuristics with a single
``tuning_fallback`` runtime event for the whole process — loud once,
silent after, never a crash.

``choose`` must be called OUTSIDE any cached-jit boundary whose trace
would freeze the answer (the kernel wrappers resolve knobs before
entering their jitted cores for exactly this reason).
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from .registry import KNOBS
from .table import TableError, TuningTable, load_table, make_key

TUNING_TABLE_ENV = "PATHSIM_TUNING_TABLE"


class _State:
    def __init__(self):
        self.enabled = True
        self.table: TuningTable | None = None
        self.source: str | None = None
        self.fallback_emitted = False
        self.lock = threading.Lock()


_state = _State()
_device_kind_cache: str | None = None


def device_kind() -> str:
    """The first device's kind ('cpu', 'TPU v5 lite', …), cached for
    the process — tuning keys are per-device by construction. Callers
    reach here only after the CLI's platform pinning, so this never
    initializes a backend the run didn't want."""
    global _device_kind_cache
    if _device_kind_cache is None:
        try:
            import jax

            _device_kind_cache = jax.devices()[0].device_kind
        except Exception:
            _device_kind_cache = "unknown"
    return _device_kind_cache


_counter_cells: dict[tuple[str, str], Any] = {}


def _count(knob: str, result: str) -> None:
    # choose() sits on per-batch serving paths (the fused_topk
    # wrapper), so cells are bound once per (knob, result) and the hot
    # path pays one dict hit + one increment — the registry's stated
    # hot-path discipline. reset() zeroes registry cells in place, so
    # cached cells stay live across test resets.
    cell = _counter_cells.get((knob, result))
    if cell is None:
        from ..obs.metrics import get_registry

        cell = get_registry().counter(
            "dpathsim_tuning_lookups_total",
            "tuning-table lookups by knob and resolution",
        ).labels(knob=knob, result=result)
        _counter_cells[(knob, result)] = cell
    cell.inc()


def _emit_fallback(source: str, reason: str) -> None:
    """One structured event per process: operators must see that a run
    they believed tuned is on heuristics, without a crash and without
    per-lookup log spam."""
    from ..utils.logging import runtime_event

    with _state.lock:
        already = _state.fallback_emitted
        _state.fallback_emitted = True
    if not already:
        runtime_event("tuning_fallback", table=source, reason=reason)
    _count("_table", "fallback")


def set_enabled(enabled: bool) -> None:
    """``--no-tuning``: heuristics everywhere, no events, no table."""
    _state.enabled = bool(enabled)


def set_table(table: TuningTable | None, source: str | None = None) -> None:
    """Install an in-memory table (tests, the autotuner's self-check)."""
    _state.table = table
    _state.source = source


def active_table() -> TuningTable | None:
    return _state.table if _state.enabled else None


def reset() -> None:
    """Back to process defaults (tests)."""
    _state.enabled = True
    _state.table = None
    _state.source = None
    _state.fallback_emitted = False


def install_table(path: str | None, required_source: str = "flag") -> bool:
    """Load ``path`` as the process's dispatch table. On any defect:
    heuristics + the single ``tuning_fallback`` event. Returns whether
    a table is now active."""
    if path is None:
        return _state.table is not None
    try:
        table = load_table(path, device_kind())
    except TableError as exc:
        # drop any previously installed table too: the fallback event
        # says this process is on heuristics, and keeping an older
        # table active would make that a lie
        set_table(None)
        _emit_fallback(path, f"{required_source}: {exc}")
        return False
    set_table(table, source=path)
    from ..utils.logging import runtime_event

    runtime_event(
        "tuning_table_loaded",
        echo=False,
        table=path,
        digest=table.digest,
        entries=len(table.entries),
        device=table.device_kind,
    )
    return True


def install_from_env() -> bool:
    """Honor ``PATHSIM_TUNING_TABLE`` when no table was given
    explicitly — the deploy-wide default path."""
    import os

    path = os.environ.get(TUNING_TABLE_ENV)
    if not path or _state.table is not None:
        return _state.table is not None
    return install_table(path, required_source="env")


def choose(
    knob: str,
    *,
    n: int | None = None,
    v: int | None = None,
    nnz: int | None = None,
    dtype: str = "float32",
    default: Any | Callable[[], Any] = None,
) -> Any:
    """Resolve one knob for one shape. ``default`` is the caller's own
    heuristic (value or thunk) — returned verbatim on any miss, so an
    untuned process behaves exactly as it did before this subsystem."""
    if knob not in KNOBS:
        raise KeyError(f"unknown tuning knob {knob!r}; see tuning.registry")

    def _default():
        return default() if callable(default) else default

    table = active_table()
    if table is None:
        if _state.enabled:
            _count(knob, "default")
        return _default()
    key = make_key(knob, device_kind(), n=n, v=v, nnz=nnz, dtype=str(dtype))
    ent = table.lookup(key)
    if ent is not None:
        _count(knob, "hit")
        return _decode(ent.choice)
    near = table.nearest(key)
    if near is not None:
        _count(knob, "nearest")
        return _decode(near[0].choice)
    _count(knob, "default")
    return _default()


def _decode(choice: Any) -> Any:
    # JSON has no tuples; tile pairs round-trip as lists.
    if isinstance(choice, list):
        return tuple(choice)
    return choice


def lookup_stats() -> dict[str, int]:
    """Per-result lookup counts from the obs registry (tests and the
    ``stats()`` serving block read this instead of private state)."""
    from ..obs.metrics import get_registry

    counter = get_registry().counter(
        "dpathsim_tuning_lookups_total",
        "tuning-table lookups by knob and resolution",
    )
    out: dict[str, int] = {}
    for labels, cell in counter.cells():
        result = dict(labels).get("result", "?")
        out[result] = out.get(result, 0) + int(cell.get())
    return out
