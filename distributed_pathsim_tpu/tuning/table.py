"""Versioned, content-addressed on-disk dispatch table.

One JSON document per (device, jax-version) pair mapping tuning keys —
``knob|device|n{bucket}|v{bucket}|d{bucket}|dtype`` — to the measured
best choice plus its per-arm timings (so a table is auditable: every
choice carries the numbers that picked it).

Integrity ladder (each failure degrades to the built-in heuristics with
a single ``tuning_fallback`` runtime event — never a crash):

- unparsable / missing-field / wrong-digest JSON → ``corrupt``;
- ``schema_version`` ≠ ours → ``schema-mismatch`` (an old reader must
  not guess at a new writer's semantics);
- jax major.minor or device kind ≠ the running process → ``fingerprint-
  mismatch`` (timings from another device/runtime are not evidence
  here).

The digest is sha256 over the canonically-serialized entries — the
table's content address. Writes go through a temp file + ``os.replace``
so a crashed writer can never leave a half-written table that then
silently half-loads.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import tempfile
from typing import Any

SCHEMA_VERSION = 1

_ESTIMATOR = "interleaved-arms median-of-best (utils/benchrunner.py)"


class TableError(Exception):
    """A table that must not be used, with the reason ('corrupt',
    'schema-mismatch', 'fingerprint-mismatch', 'absent')."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason


def jax_fingerprint() -> str:
    """jax major.minor — the runtime half of the table fingerprint
    (kernel/XLA behavior shifts across minor releases; patch releases
    don't invalidate measurements)."""
    import jax

    return ".".join(str(jax.__version__).split(".")[:2])


def normalize_device(kind: str) -> str:
    return kind.strip().replace(" ", "_").lower() or "unknown"


def n_bucket(n: int | None) -> str:
    """Power-of-two size bucket: the exponent of the next pow-2 ≥ n.
    Shape sensitivity of kernel choice is multiplicative, so pow-2
    buckets give nearest-neighbor lookups a meaningful metric."""
    if n is None or n <= 0:
        return "na"
    return str((int(n) - 1).bit_length())


def density_bucket(n: int | None, v: int | None, nnz: int | None) -> str:
    """Decade bucket of nnz/(n*v) (0 = dense, -3 = one-in-a-thousand).
    'na' when the caller has no sparsity to speak of (dense tiers)."""
    if nnz is None or not n or not v:
        return "na"
    density = max(float(nnz) / (float(n) * float(v)), 1e-12)
    return str(max(-12, min(0, round(math.log10(density)))))


def make_key(
    knob: str,
    device: str,
    n: int | None = None,
    v: int | None = None,
    nnz: int | None = None,
    dtype: str = "float32",
) -> str:
    return "|".join(
        (
            knob,
            normalize_device(device),
            f"n{n_bucket(n)}",
            f"v{n_bucket(v)}",
            f"d{density_bucket(n, v, nnz)}",
            str(dtype),
        )
    )


def _parse_key(key: str) -> tuple[str, str, str, str, str, str] | None:
    parts = key.split("|")
    if len(parts) != 6:
        return None
    return tuple(parts)  # type: ignore[return-value]


def _axis_distance(a: str, b: str) -> int:
    """Distance between two bucket labels on one key axis. 'na' vs a
    number is a real mismatch (worth more than several bucket steps),
    'na' vs 'na' is a match."""
    if a == b:
        return 0
    if a == "na" or b == "na":
        return 8
    return abs(int(a) - int(b))


@dataclasses.dataclass
class Entry:
    choice: Any
    metric_ms: float | None = None
    arms: dict[str, float] | None = None

    def to_json(self) -> dict:
        out: dict[str, Any] = {"choice": self.choice}
        if self.metric_ms is not None:
            out["metric_ms"] = round(float(self.metric_ms), 6)
        if self.arms:
            out["arms"] = {k: round(float(v), 6) for k, v in self.arms.items()}
        return out

    @classmethod
    def from_json(cls, d: dict) -> "Entry":
        return cls(
            choice=d["choice"],
            metric_ms=d.get("metric_ms"),
            arms=d.get("arms"),
        )


def _entries_digest(entries: dict[str, dict]) -> str:
    blob = json.dumps(entries, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


class TuningTable:
    """In-memory dispatch table: exact-key lookup + nearest-bucket
    interpolation within (knob, device, dtype)."""

    def __init__(self, device_kind: str, jax_version: str | None = None):
        self.device_kind = normalize_device(device_kind)
        self.jax_version = jax_version or jax_fingerprint()
        self.entries: dict[str, Entry] = {}

    def put(self, key: str, choice: Any, metric_ms: float | None = None,
            arms: dict[str, float] | None = None) -> None:
        if _parse_key(key) is None:
            raise ValueError(f"malformed tuning key {key!r}")
        self.entries[key] = Entry(choice=choice, metric_ms=metric_ms,
                                  arms=arms)

    def lookup(self, key: str) -> Entry | None:
        return self.entries.get(key)

    def nearest(self, key: str) -> tuple[Entry, str] | None:
        """Closest same-(knob, device, dtype) entry by L1 bucket
        distance over (N, V, density); deterministic tie-break on the
        key string so a lookup never flaps between equidistant
        entries. Returns (entry, its key) or None."""
        want = _parse_key(key)
        if want is None:
            return None
        knob, device, nb, vb, db, dtype = want
        best: tuple[int, str] | None = None
        for cand_key in self.entries:
            got = _parse_key(cand_key)
            if got is None:
                continue
            if (got[0], got[1], got[5]) != (knob, device, dtype):
                continue
            dist = (
                _axis_distance(nb[1:], got[2][1:])
                + _axis_distance(vb[1:], got[3][1:])
                + _axis_distance(db[1:], got[4][1:])
            )
            if best is None or (dist, cand_key) < best:
                best = (dist, cand_key)
        if best is None:
            return None
        return self.entries[best[1]], best[1]

    @property
    def digest(self) -> str:
        return _entries_digest(
            {k: self.entries[k].to_json() for k in sorted(self.entries)}
        )

    def to_json(self) -> dict:
        entries = {k: self.entries[k].to_json() for k in sorted(self.entries)}
        return {
            "schema_version": SCHEMA_VERSION,
            "jax_version": self.jax_version,
            "device_kind": self.device_kind,
            "estimator": _ESTIMATOR,
            "digest": _entries_digest(entries),
            "entries": entries,
        }

    def save(self, path: str) -> str:
        """Atomic write (temp file + rename in the target directory, so
        the rename never crosses filesystems). Returns the digest."""
        doc = self.to_json()
        d = os.path.dirname(os.path.abspath(path)) or "."
        fd, tmp = tempfile.mkstemp(prefix=".tuning_", suffix=".json", dir=d)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return doc["digest"]


def load_table(path: str, device_kind: str) -> TuningTable:
    """Load + verify a table for the CURRENT runtime. Raises
    :class:`TableError` on every defect — callers degrade to heuristics
    (with the one ``tuning_fallback`` event); they never crash."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except FileNotFoundError as exc:
        raise TableError("absent", str(exc)) from exc
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as exc:
        raise TableError("corrupt", str(exc)) from exc
    if not isinstance(doc, dict):
        raise TableError("corrupt", "top-level JSON is not an object")
    try:
        version = doc["schema_version"]
        entries = doc["entries"]
        digest = doc["digest"]
        table_jax = doc["jax_version"]
        table_dev = doc["device_kind"]
    except KeyError as exc:
        raise TableError("corrupt", f"missing field {exc}") from exc
    if version != SCHEMA_VERSION:
        raise TableError(
            "schema-mismatch",
            f"table schema {version!r}, reader {SCHEMA_VERSION}",
        )
    if not isinstance(entries, dict):
        raise TableError("corrupt", "entries is not an object")
    if _entries_digest(entries) != digest:
        raise TableError("corrupt", "digest does not match entries")
    if table_jax != jax_fingerprint():
        raise TableError(
            "fingerprint-mismatch",
            f"table jax {table_jax}, runtime {jax_fingerprint()}",
        )
    if normalize_device(table_dev) != normalize_device(device_kind):
        raise TableError(
            "fingerprint-mismatch",
            f"table device {table_dev!r}, runtime {device_kind!r}",
        )
    t = TuningTable(table_dev, jax_version=table_jax)
    try:
        for key, ent in entries.items():
            t.entries[key] = Entry.from_json(ent)
    except (KeyError, TypeError, AttributeError) as exc:
        raise TableError("corrupt", f"bad entry: {exc!r}") from exc
    return t
