"""Candidate registry: every measured decision point, as a keyed knob.

Before this subsystem each tier hardcoded its own tuning heuristic —
``_default_scores_tiles`` in ops/pallas_kernels.py, the sparse
column-tile width in backends/jax_sparse.py, the rect-Pallas-vs-jnp
ring-step fold in parallel/sharded.py, the serving bucket ladder in
serving/buckets.py. KERNELS_r05 showed why a constant can't be right:
the promoted Pallas ``fused_scores`` tile wins at 8k authors and loses
to XLA's fusion at 32k. The right variant/tile flips with matrix shape
and density (Atrapos makes the same point for metapath workloads), so
each decision point is registered here as a *knob*: a name, the
candidate choices the offline autotuner may measure, and a short
contract for what the choice means. Runtime code asks
:func:`~distributed_pathsim_tpu.tuning.choose` for a knob's value and
passes its own heuristic as the default — a missing/failed table means
exactly the pre-tuning behavior.

Every knob is **bit-invisible by construction**: choices only move work
between tilings/variants that share the exact integer-count + f64-
normalize scoring primitives (verified by the cross-variant parity
tests in tests/test_tuning.py). A knob whose choices could change
results does not belong in this registry.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable


@dataclasses.dataclass(frozen=True)
class Knob:
    """One tunable decision point.

    ``candidates``: context → the JSON-serializable choices the
    autotuner may measure for this knob (context keys: n, v, nnz,
    dtype, max_batch, k — whatever the knob's bench needs). The
    runtime never enumerates candidates; it only validates that a
    tuned choice is still *feasible* (VMEM budgets, kernel gates)
    before using it.
    """

    name: str
    doc: str
    candidates: Callable[[dict], list[Any]]


def _scores_tile_candidates(ctx: dict) -> list[Any]:
    # The KERNELS_r05 sweep set; feasibility (VMEM fit at this V) is
    # re-checked by the consumer, not assumed here.
    return [[256, 256], [256, 512], [512, 256], [512, 512],
            [512, 1024], [1024, 512]]


KNOBS: dict[str, Knob] = {
    k.name: k
    for k in (
        Knob(
            name="scores_variant",
            doc="all-pairs dense scores implementation: the fused "
            "Pallas matmul+normalize kernel vs XLA's own fusion "
            "(fused_scores_reference). KERNELS_r05: Pallas wins at 8k "
            "(90.3% vs 86.7% of the f32 ceiling), XLA at 32k (87.0% "
            "vs 85.3%).",
            candidates=lambda ctx: ["pallas", "xla"],
        ),
        Knob(
            name="scores_tile",
            doc="fused_scores output tile (bm, bn): arithmetic "
            "intensity per HBM byte grows with the tile edge, bounded "
            "by the VMEM budget at this V.",
            candidates=_scores_tile_candidates,
        ),
        Knob(
            name="topk_rowtile",
            doc="fused_topk row-tile (bm): rows folded per grid step "
            "of the single-pass top-k kernel.",
            candidates=lambda ctx: [256, 512],
        ),
        Knob(
            name="k_tile",
            doc="contraction tile (bk) of the K-tiled kernel variants "
            "(wide half-chain factors, e.g. APA where V = #papers).",
            candidates=lambda ctx: [256, 512, 1024],
        ),
        Knob(
            name="sparse_tile_rows",
            doc="jax-sparse streaming column/row tile width: the "
            "[tile, tile] score block edge of the tiled sweep "
            "(memory/throughput trade at a given N, V, density).",
            candidates=lambda ctx: [
                t for t in (1024, 2048, 4096, 8192)
                if ctx.get("n") is None or t <= 4 * int(ctx["n"])
            ],
        ),
        Knob(
            name="sparse_nnz_floor",
            doc="floor of the pow-2 per-tile scatter-pad bucket in "
            "TiledHalfChain: a higher floor wastes pad entries but "
            "keeps more delta-drifted nnz inside one compiled scatter "
            "program.",
            candidates=lambda ctx: [1, 1024, 4096, 16384],
        ),
        Knob(
            name="ring_kernel",
            doc="sharded ring-step fold: the rectangular two-pass "
            "Pallas kernel vs the jnp fold (both bit-identical tie "
            "breaks; parallel/ring.ring_topk_step).",
            candidates=lambda ctx: ["rect-pallas", "jnp-fold"],
        ),
        Knob(
            name="ann_centroids",
            doc="MIPS index centroid count as a multiplier on √N "
            "(index/build.default_centroids): more centroids → "
            "smaller clusters → cheaper probes but weaker cluster "
            "locality (recall needs more nprobe).",
            candidates=lambda ctx: [0.5, 1.0, 2.0],
        ),
        Knob(
            name="ann_cluster_cap",
            doc="packed-cluster capacity (pad-to) of the MIPS index "
            "blocks: probe cost is nprobe·cap·dim, so a tight cap is "
            "cheaper per probe but spills more members off their "
            "nearest centroid (recall). Feasibility (K·cap ≥ N) is "
            "re-checked at build; an infeasible tuned cap is raised "
            "loudly, never trusted.",
            candidates=lambda ctx: [64, 128, 256, 512],
        ),
        Knob(
            name="ann_probe_variant",
            doc="ANN candidate generation strategy: 'rerank-all' "
            "routes only (centroid top-nprobe + member ids) and "
            "exact-reranks every probed member against packed "
            "per-cluster count blocks (wins when the half-chain "
            "width V is narrow — the rerank matmul is cheaper than "
            "embedding-space scoring); 'shortlist' scores the probed "
            "embedding blocks in one batched matmul and exact-reranks "
            "only the top cand_mult·k (wins at wide V / on matmul "
            "hardware). Both are exact-reranked, both bit-identical "
            "when the true top-k is covered.",
            candidates=lambda ctx: ["rerank-all", "shortlist"],
        ),
        Knob(
            name="ann_nprobe",
            doc="clusters probed per ANN query: the recall/latency "
            "dial of candidate generation. Arms failing the recall "
            "floor are excluded by the tuner, not merely slow.",
            candidates=lambda ctx: [8, 16, 32, 48, 64, 96],
        ),
        Knob(
            name="ann_cand_mult",
            doc="candidate multiplier: C = mult·k candidates survive "
            "the probe into the exact f64 rerank. Larger mult buys "
            "recall at O(C·V) rerank cost per query.",
            candidates=lambda ctx: [4, 8, 16, 32],
        ),
        Knob(
            name="learned_dim",
            doc="learned-tower output width (learned/trainer.py): "
            "probe cost per query is O(N·dim) and checkpoint bytes "
            "O(N·dim + F·hidden); wider towers separate candidates "
            "better (recall at a fixed cand_mult) but cost latency. "
            "Bit-invisible: towers only SHORTLIST — every answer is "
            "exact-f64 reranked, so dim moves recall/latency, never "
            "a served score.",
            candidates=lambda ctx: [16, 32, 64],
        ),
        Knob(
            name="learned_neg_ratio",
            doc="uniform-negative fraction of distillation training "
            "slates (1 - HARD_FRAC): more uniform negatives teach "
            "global score calibration, more exact-teacher hard "
            "candidates sharpen the top of the ranking the serving "
            "shortlist is cut from. Arms are judged by shadow "
            "score-recall at the shipped cand_mult.",
            candidates=lambda ctx: [0.25, 0.5, 0.75],
        ),
        Knob(
            name="learned_cand_mult",
            doc="learned candidate multiplier: C = mult·k shortlist "
            "survivors enter the exact f64 rerank (same recall-vs-"
            "rerank-cost dial as ann_cand_mult, against tower "
            "similarities instead of MIPS probes).",
            candidates=lambda ctx: [8, 16, 32, 64],
        ),
        Knob(
            name="learned_refresh_deltas",
            doc="background tower-refresh cadence: deltas absorbed "
            "between re-embed passes (serving/service.refresh_towers). "
            "Every landing fences its affected rows onto the exact "
            "path immediately, so a longer cadence batches the "
            "half-chain fold at the cost of more queries degrading "
            "meanwhile — speed, never correctness.",
            candidates=lambda ctx: [1, 4, 16],
        ),
        Knob(
            name="learned_conf_floor",
            doc="shadow score-recall floor of the learned confidence "
            "gate: measured recall below it disables the learned arm "
            "(every query degrades ann-then-exact, counted) until a "
            "refresh resets the gate. Higher floors trade learned-arm "
            "uptime for tighter worst-case recall.",
            candidates=lambda ctx: [0.95, 0.98, 0.99],
        ),
        Knob(
            name="plan_density_cutover",
            doc="metapath planner cost model: intermediate density at "
            "which a factor is costed as DENSE (2·m·r·n GEMM FLOPs) "
            "instead of the sparse join estimate (Atrapos density "
            "propagation). Affects only the plan's ORDER choice — "
            "integer path counts are association-invariant, so every "
            "order is bit-identical (the planner property tests gate "
            "it).",
            candidates=lambda ctx: [0.05, 0.1, 0.25, 0.5],
        ),
        Knob(
            name="plan_dp_max_len",
            doc="metapath planner DP size cutoff: chains longer than "
            "this skip the O(L³) interval DP and evaluate "
            "left-to-right (recorded on the plan as dp=False). Real "
            "metapaths are L ≤ 7; the cutoff exists so a pathological "
            "spec cannot stall plan compilation.",
            candidates=lambda ctx: [4, 8, 16, 32],
        ),
        Knob(
            name="plan_memo_budget_mb",
            doc="workload-level sub-chain memo budget (MB): folded "
            "sub-chain COO factors shared across concurrent metapath "
            "lanes (ops/planner.SubchainCache). Bigger budgets keep "
            "more shared prefixes resident across deltas; keys are "
            "content fingerprints, so the budget trades bytes for "
            "hit rate, never correctness.",
            candidates=lambda ctx: [16.0, 64.0, 256.0],
        ),
        Knob(
            name="factor_format",
            doc="resident layout of the sparse half-chain factor "
            "(ops/packed.py, DESIGN.md §29): 'coo' (24 B/nnz, zero "
            "transform cost), 'blocked' (chunked CSR, hub-first "
            "permuted narrow-dtype columns + narrow integer counts, "
            "~3-6 B/nnz), 'bitpacked' (blocked plus per-block "
            "fixed-width bit-packing of delta-encoded column ids, "
            "~1.5-3 B/nnz). Trades decode time per tile/patch for "
            "resident bytes — i.e. for max-N at a fixed memory "
            "budget, single-chip and per-partition. Bit-invisible: "
            "every accessor returns original ids and exact f64 "
            "integers (pack/unpack round trip property-tested).",
            candidates=lambda ctx: ["coo", "blocked", "bitpacked"],
        ),
        Knob(
            name="batch_block_rows",
            doc="batch-campaign sweep block height (batch/campaign.py, "
            "DESIGN.md §31): rows decoded + GEMM'd per block of a "
            "topk-all / simjoin sweep. Taller blocks amortize the "
            "resident Cᵀ operand over more rows but coarsen the "
            "checkpoint/preemption granularity and the simjoin prune "
            "intervals. Snapped to the pow-2 ladder so every block of "
            "a campaign shares ONE compiled program shape "
            "(zero steady-state recompiles). Bit-invisible: counts "
            "are exact integers in f64, so block height can never "
            "move a score.",
            candidates=lambda ctx: [128, 256, 512, 1024],
        ),
        Knob(
            name="compact_chain_len",
            doc="background-compaction chain trigger (serving/"
            "compact.py, DESIGN.md §30): deltas absorbed since the "
            "last re-encode before a compaction is scheduled. Shorter "
            "chains bound cache-version drift and keep the replay log "
            "tiny but pay the off-path rebuild more often; the arms "
            "race a sustained update+query workload end to end. "
            "Bit-invisible: compaction re-encodes the SAME logical "
            "graph (token, fingerprints, and caches preserved), so "
            "the choice moves only when work happens, never results.",
            candidates=lambda ctx: [64, 256, 1024],
        ),
        Knob(
            name="compact_headroom",
            doc="fresh capacity reserve of a compaction re-encode, as "
            "a fraction of the logical size (padded to pow-2 "
            "buckets): more headroom buys fewer headroom-triggered "
            "compactions per appended node at more resident padding. "
            "Measured on the same sustained firehose workload as "
            "compact_chain_len; results bit-identical by the padding "
            "invariant (data/delta.py with_headroom).",
            candidates=lambda ctx: [0.25, 0.5, 1.0],
        ),
        Knob(
            name="serve_buckets",
            doc="serving bucket-ladder geometry pre-compiled at "
            "warmup: 'pow2' (1,2,4,…; <2x pad waste, log2(B)+1 "
            "programs) vs 'coarse' (1 + powers of 4; about half the "
            "programs/warm time, <4x pad waste).",
            candidates=lambda ctx: ["pow2", "coarse"],
        ),
    )
}


def resolve_ladder(geometry: str, max_batch: int) -> tuple[int, ...]:
    """A ``serve_buckets`` choice → concrete ascending bucket ladder
    covering ``max_batch``. Shared by the serving warmup, the
    coalescer, and the tuner so geometry names can never drift."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if geometry == "pow2":
        step = 2
    elif geometry == "coarse":
        step = 4
    else:
        raise ValueError(f"unknown bucket geometry {geometry!r}")
    ladder = [1]
    while ladder[-1] < max_batch:
        ladder.append(ladder[-1] * step)
    return tuple(ladder)


# ---------------------------------------------------------------------------
# Sanctioned tile/bucket constants (scripts/lint_tuning.py)
# ---------------------------------------------------------------------------
#
# Hardcoded tile/bucket constants outside this registry are exactly how
# the pre-tuning heuristics fossilized, so the lint rejects NEW ones:
# any module-level or class-level integer/tuple constant whose name
# looks like a tile or bucket knob must either be a registry knob's
# default (owned here) or appear below with its justification. Each
# sanctioned entry is one of: (a) a kernel-internal layout invariant
# that is NOT a performance choice (lane widths, packing factors), or
# (b) the fallback floor a knob's heuristic returns when tuning is
# absent — the registry's own documented default.

SANCTIONED_CONSTANTS: dict[str, frozenset[str]] = {
    "ops/pallas_kernels.py": frozenset({
        "_BM",            # heuristic floor of scores_tile / topk_rowtile
        "_BN",            # heuristic floor of scores_tile
        "_BK",            # heuristic floor of k_tile
        "_BN_WIDE",       # twopass candidate-extraction stripe (layout)
        "_RECT_BN",       # rect kernel group tile — VMEM-stack-validated
        "_RECT_VMAX",     # rect un-tiled contraction bound (VMEM layout)
    }),
    "backends/jax_dense.py": frozenset({
        "_RECT_TILE_ROWS",  # rect streaming row tile (HBM-budget halver)
    }),
    "serving/buckets.py": frozenset({
        "DEFAULT_BUCKETS",  # serve_buckets 'pow2' default, documented
    }),
    "ops/planner.py": frozenset({
        "_DEG_BUCKETS",   # degree-histogram resolution (24 log2 buckets
        # cover any int32 index space) — an audit-layout invariant of
        # FactorStats, not a measured performance choice; the planner's
        # real knobs (plan_density_cutover, plan_dp_max_len,
        # plan_memo_budget_mb) are registry knobs above
    }),
    "ops/packed.py": frozenset({
        "_PACK_CHUNK_ROWS",    # delta re-encode / tile-alignment chunk
        # granularity — consumers pass their own tile width; this is
        # the standalone default, a layout invariant
        "_BLOCK_NNZ",          # bit-packing width-adaptation block size
        # (each block stores its own bit width) — layout invariant of
        # the bitpacked stream, not a measured perf choice
        "_PACK_BUCKET_FLOOR",  # pow-2 chunk-buffer capacity floor: the
        # realloc-stability contract (delta-drifted nnz stays inside
        # one bucket), analogous to sparse_nnz_floor's role but for
        # host buffers; the measured knob is factor_format above
    }),
    "obs/metrics.py": frozenset({
        "DEFAULT_BUCKETS_PER_DECADE",  # histogram resolution (quantile
        # rel-err bound is derived from it in tests) — an accuracy
        # layout invariant, not a measured performance choice
    }),
    "serving/service.py": frozenset({
        "tile_cache_bytes",  # ServeConfig capacity defaults: operator-
        "tile_rows",         # facing CLI config (cache budget/eviction
                             # granularity), not measured kernel knobs
    }),
}
