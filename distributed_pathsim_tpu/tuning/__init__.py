"""Measured autotuning & shape-aware dispatch (DESIGN.md §21).

Three pieces:

- :mod:`.registry` — the candidate registry: every real tuning decision
  point (fused_scores tile & Pallas-vs-XLA variant, fused_topk row
  tile, K-contraction tile, sparse streaming tile width & scatter-pad
  floor, ring-step kernel, serving bucket geometry) as a keyed knob.
- :mod:`.autotuner` — the offline measurer (``dpathsim tune`` /
  ``scripts/tune_sweep.py``): interleaved-arm, median-of-best timing
  per ``(device, N-bucket, V-bucket, density-bucket, dtype)`` key.
- :mod:`.table` + :mod:`.dispatch` — the versioned on-disk table and
  the runtime consultation: exact hit → tuned choice, miss → nearest
  bucket, unusable table → the built-in heuristics with one
  ``tuning_fallback`` event.

Tuning is bit-invisible: every choice routes between implementations
that share the exact integer-count + f64-normalize scoring primitives
(cross-variant parity is tested per backend in tests/test_tuning.py).
"""

from .dispatch import (  # noqa: F401
    TUNING_TABLE_ENV,
    active_table,
    choose,
    device_kind,
    install_from_env,
    install_table,
    lookup_stats,
    reset,
    set_enabled,
    set_table,
)
from .registry import KNOBS, resolve_ladder  # noqa: F401
from .table import (  # noqa: F401
    SCHEMA_VERSION,
    TableError,
    TuningTable,
    load_table,
    make_key,
)
