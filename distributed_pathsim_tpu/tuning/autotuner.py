"""Offline autotuner: measure every registered knob per shape key.

``dpathsim tune`` (and the bigger sweep in ``scripts/tune_sweep.py``)
micro-benchmarks each knob's candidate arms per key ``(device_kind,
N-bucket, V-bucket, density-bucket, dtype)`` and writes the winning
choices as a versioned, content-addressed dispatch table
(:mod:`~distributed_pathsim_tpu.tuning.table`).

Timing discipline is the shared estimator (utils/benchrunner.py):
candidate arms are **interleaved** per round and compared by
**median-of-best** — the BENCH_OBS_r08 note made concrete (CI-box
baselines drift up to 3×, so arms that don't interleave measure the
drift, not the kernel). Every table entry records all arms' summaries,
so a choice is auditable from the table alone.

Platform honesty: arms that cannot run for real on the current device
are *not* measured — a Pallas kernel timed in interpret mode would
produce a table that anti-tunes the real chip. Off-TPU the Pallas arms
are skipped and the affected knobs simply keep their dense-XLA
alternatives (or are omitted when no real arm exists).

Dtype hygiene, same principle: every bench arm computes in float32
(the scoring primitives' compute dtype), so entries are keyed
``float32``. Runtime lookups that pass a different backend dtype
(float64/bfloat16) miss to their built-in heuristics — f32 timings are
not evidence for another dtype's kernels — and the misses are visible
as ``dpathsim_tuning_lookups_total{result="default"}``.
"""

from __future__ import annotations

import argparse
import dataclasses
import itertools

import numpy as np

from ..utils import benchrunner as br
from ..utils.logging import runtime_event
from . import dispatch
from .registry import KNOBS, resolve_ladder
from .table import TuningTable, make_key


@dataclasses.dataclass(frozen=True)
class SweepPoint:
    """One shape key to tune: dense when nnz is None."""

    n: int
    v: int
    nnz: int | None = None

    @classmethod
    def parse(cls, spec: str) -> "SweepPoint":
        parts = [int(p) for p in spec.lower().split("x")]
        if len(parts) == 2:
            return cls(parts[0], parts[1])
        if len(parts) == 3:
            return cls(parts[0], parts[1], parts[2])
        raise ValueError(f"bad shape spec {spec!r}; want NxV or NxVxNNZ")


def _dense_factor(n: int, v: int, seed: int = 0, variants: int = 3):
    """Integer-valued C like the real half-chain factor, with several
    perturbed buffers so repeated timed calls never hand a result-
    caching relay identical (program, args) pairs (the kernel_bench
    lesson)."""
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(seed)
    c = jax.random.randint(key, (n, v), 0, 3).astype(jnp.float32)
    d = jnp.maximum(jnp.sum(c, axis=1), 1.0)
    cs = [c + (i * 1e-38) for i in range(max(variants, 1))]
    jax.block_until_ready(cs)
    jax.block_until_ready(d)
    return cs, d


def _cycled(fn, buffers):
    counter = itertools.count()

    def run():
        fn(buffers[next(counter) % len(buffers)])

    return run


def _sparse_coo(n: int, v: int, nnz: int, seed: int = 0):
    from ..ops import sparse as sp

    rng = np.random.default_rng(seed)
    return sp.COOMatrix(
        rows=rng.integers(0, n, size=nnz).astype(np.int64),
        cols=rng.integers(0, v, size=nnz).astype(np.int64),
        weights=np.ones(nnz, dtype=np.float64),
        shape=(n, v),
    )


# ---------------------------------------------------------------------------
# Per-knob benches. Each returns {knob: (choice, results_by_arm)} for one
# sweep point; the driver turns those into table entries.
# ---------------------------------------------------------------------------


def bench_scores(point: SweepPoint, reps: int) -> dict:
    """scores_variant (+ scores_tile when Pallas is real here): the
    all-pairs dense scores path, fused Pallas tiles vs XLA's fusion."""
    import jax
    import jax.numpy as jnp

    from ..ops import pallas_kernels as pk

    cs, d = _dense_factor(point.n, point.v)

    xla = jax.jit(lambda cc: jnp.max(pk.fused_scores_reference(cc, d)))
    arms = {"xla": _cycled(lambda cc: np.asarray(xla(cc)), cs)}
    if pk.pallas_supported():
        ctx = {"n": point.n, "v": point.v}
        for bm, bn in KNOBS["scores_tile"].candidates(ctx):
            if not pk.tile_fits_vmem(bm, bn, point.v):
                continue

            def pallas_fn(cc, bm=bm, bn=bn):
                return np.asarray(
                    jnp.max(pk.fused_scores(cc, d, bm=bm, bn=bn))
                )

            arms[f"pallas_{bm}x{bn}"] = _cycled(pallas_fn, cs)
    res = br.time_interleaved(arms, reps)
    win = br.best_arm(res)
    out = {
        "scores_variant": ("xla" if win == "xla" else "pallas", res),
    }
    pallas_res = {k: v for k, v in res.items() if k.startswith("pallas_")}
    if pallas_res:
        best_tile = br.best_arm(pallas_res)
        bm, bn = best_tile.removeprefix("pallas_").split("x")
        out["scores_tile"] = ([int(bm), int(bn)], pallas_res)
    return out


def bench_topk_rowtile(point: SweepPoint, reps: int) -> dict:
    """fused_topk row tile — Pallas-only (no real arm elsewhere)."""
    import jax.numpy as jnp

    from ..ops import pallas_kernels as pk

    # production routes wide-V shapes to the K-tiled variant (backends
    # gate on fits_vmem), so there is nothing for this knob to measure
    # there — and the single-pass kernel would blow VMEM
    if not pk.pallas_supported() or not pk.fits_vmem(point.v):
        return {}
    cs, d = _dense_factor(point.n, point.v)
    arms = {}
    for bm in KNOBS["topk_rowtile"].candidates({"n": point.n, "v": point.v}):
        # same hardware gate the runtime wrapper applies to a tuned bm:
        # an infeasible candidate must be skipped, not crash the sweep
        if not pk.tile_fits_vmem(bm, pk._BN, point.v):
            continue

        def fn(cc, bm=bm):
            return np.asarray(
                jnp.max(pk.fused_topk(cc, d, k=10, bm=bm)[0])
            )

        arms[f"bm{bm}"] = _cycled(fn, cs)
    if not arms:
        return {}
    res = br.time_interleaved(arms, reps)
    return {"topk_rowtile": (int(br.best_arm(res)[2:]), res)}


def bench_k_tile(point: SweepPoint, reps: int) -> dict:
    """K-contraction tile of the K-tiled kernels — Pallas-only, and
    only meaningful at contraction widths past one VMEM tile."""
    import jax.numpy as jnp

    from ..ops import pallas_kernels as pk

    if not pk.pallas_supported() or pk.fits_vmem(point.v):
        return {}
    cs, d = _dense_factor(point.n, point.v)
    arms = {}
    for bk in KNOBS["k_tile"].candidates({"n": point.n, "v": point.v}):

        def fn(cc, bk=bk):
            return np.asarray(
                jnp.max(pk.fused_scores_ktiled(cc, d, bk=bk))
            )

        arms[f"bk{bk}"] = _cycled(fn, cs)
    res = br.time_interleaved(arms, reps)
    return {"k_tile": (int(br.best_arm(res)[2:]), res)}


def bench_sparse_tiles(point: SweepPoint, reps: int, k: int = 10) -> dict:
    """jax-sparse streaming tile width: a full scanned streaming top-k
    pass per candidate width over the same synthetic COO factor."""
    import jax
    import jax.numpy as jnp

    from ..ops import sparse as sp

    nnz = point.nnz or 8 * point.n
    coo = _sparse_coo(point.n, point.v, nnz)
    ctx = {"n": point.n, "v": point.v}
    # clamp candidates to N and dedupe BEFORE measuring: the recorded
    # choice must be the tile width that actually ran, not a nominal
    # candidate silently clamped inside the bench (a table entry whose
    # timing evidence describes a different configuration is worse than
    # no entry)
    widths = sorted({
        min(int(cand), point.n)
        for cand in KNOBS["sparse_tile_rows"].candidates(ctx)
    })
    prepared = {}
    for cand in widths:
        t = sp.TiledHalfChain(coo, tile_rows=cand)
        c_all = t.dense_device()
        d_pad = np.zeros(t.n_tiles * t.tile_rows)
        d_pad[: t.n] = t.rowsums()
        d_dev = jnp.asarray(d_pad, dtype=t.dtype)
        prepared[f"tile{cand}"] = (t, c_all, d_dev)

    def run(name):
        t, c_all, d_dev = prepared[name]
        outs = [
            sp.stream_row_tile_topk(
                c_all, d_dev, jnp.int32(i * t.tile_rows),
                k=k, n_true=point.n, tile_rows=t.tile_rows,
            )
            for i in range(t.n_tiles)
        ]
        jax.block_until_ready(outs)

    arms = {name: (lambda name=name: run(name)) for name in prepared}
    res = br.time_interleaved(arms, reps)
    return {"sparse_tile_rows": (int(br.best_arm(res)[4:]), res)}


def bench_sparse_nnz_floor(point: SweepPoint, reps: int,
                           drift_steps: int = 6) -> dict:
    """Scatter-pad bucket floor under delta drift: each round walks a
    FRESH drifting-nnz sequence (per-round offsets keep the traced pad
    shapes from aliasing earlier rounds) and rebuilds + densifies the
    tile; a low floor re-crosses pow-2 pad boundaries and pays XLA
    retraces, a high floor pays pad-scatter waste — exactly the
    production trade. Shared executable caches mean a floor whose pad
    sizes coincide with another arm's measures warm, which is also what
    production sees (one program per distinct pad shape)."""
    import jax

    from ..ops import sparse as sp

    nnz = point.nnz or 8 * point.n

    def arm(floor: int):
        # per-ARM call counter: time_interleaved calls every arm once
        # per round, so call r of each arm shares the same base nnz —
        # every floor walks the identical drift sequence in a round and
        # the comparison is like against like (a shared counter would
        # hand each arm different bases, and a base that happens to
        # cross a pow-2 pad boundary would tax that arm alone)
        round_no = itertools.count()

        def run():
            base = nnz + 977 * next(round_no)
            for s in range(drift_steps):
                coo = _sparse_coo(point.n, point.v, base + 61 * s, seed=s)
                t = sp.TiledHalfChain(
                    coo, tile_rows=min(2048, point.n), nnz_bucket_floor=floor
                )
                jax.block_until_ready(t.tile(0))

        return run

    arms = {
        f"floor{f}": arm(f)
        for f in KNOBS["sparse_nnz_floor"].candidates(
            {"n": point.n, "v": point.v}
        )
    }
    res = br.time_interleaved(arms, reps, warmup=0)
    return {"sparse_nnz_floor": (int(br.best_arm(res)[5:]), res)}


def bench_planner(point: SweepPoint, reps: int) -> dict:
    """plan_density_cutover + plan_memo_budget_mb — real arms over a
    synthetic HIN at the sweep point's scale.

    Cutover arms: plan a 4-factor asymmetric COO chain (APVPT) under
    each density threshold and time the plan-ordered sparse fold —
    the threshold decides where the DP switches from the join-size
    estimate to the dense model, which flips the association order it
    picks; the measured fold time is the ground truth the estimate
    stands in for. Memo arms: a rotating mixed APVPA/APA/APTPA fold
    workload over several graph variants per budget — a small budget
    thrashes the LRU, a large one keeps every shared sub-chain
    resident."""
    from ..data.synthetic import synthetic_hin
    from ..ops import planner
    from ..ops import sparse as _sp
    from ..ops.metapath import compile_metapath

    n = point.n
    hin = synthetic_hin(
        n, 2 * n, max(point.v // 4, 4), n_topics=max(point.v // 8, 8),
        seed=3,
    )
    mp = compile_metapath("APVPT", hin.schema)
    blocks = []
    for st in mp.steps:
        b = _sp.coo_from_block(hin.block(st.relationship))
        if st.reverse:
            b = _sp.COOMatrix(
                rows=b.cols, cols=b.rows, weights=b.weights,
                shape=(b.shape[1], b.shape[0]),
            )
        blocks.append(b.summed())
    arms = {}
    for cut in KNOBS["plan_density_cutover"].candidates(
        {"n": n, "v": len(mp.steps)}
    ):

        def fn(cut=cut):
            out = planner.fold_blocks(blocks, dense_cutover=float(cut))
            return int(out.rows.shape[0])

        arms[f"cut{cut}"] = fn
    res = br.time_interleaved(arms, reps)
    out = {
        "plan_density_cutover": (
            float(br.best_arm(res).removeprefix("cut")), res
        ),
    }

    variants = [
        synthetic_hin(
            n, 2 * n, max(point.v // 4, 4),
            n_topics=max(point.v // 8, 8), seed=11 + s,
        )
        for s in range(4)
    ]
    paths = [
        compile_metapath(spec, variants[0].schema)
        for spec in ("APVPA", "APA", "APTPA")
    ]

    def memo_arm(mb: float):
        memo = planner.SubchainCache(int(mb * (1 << 20)))

        def run():
            for h in variants:
                for p in paths:
                    planner.fold_half(h, p, memo=memo)

        return run

    memo_arms = {
        f"mb{mb}": memo_arm(mb)
        for mb in KNOBS["plan_memo_budget_mb"].candidates({"n": n})
    }
    memo_res = br.time_interleaved(memo_arms, reps)
    out["plan_memo_budget_mb"] = (
        float(br.best_arm(memo_res).removeprefix("mb")), memo_res
    )
    return out


def bench_factor_format(point: SweepPoint, reps: int, k: int = 10) -> dict:
    """``factor_format`` — real arms: one jax-sparse backend per
    resident layout over the same graph, raced on the batched serving
    primitive (``topk_rows`` over a rotating row workload — the path
    where a packed layout pays its decode cost). The knob's trade is
    resident bytes vs decode time and fewer bytes is structurally
    never faster, so racing on time alone could never pick a packed
    layout: any arm within the measured noise of the fastest competes,
    and among those the smallest resident factor wins (the
    serve_buckets tie-break pattern). Measured bytes ride along per
    arm so the entry stays auditable."""
    from ..backends.base import create_backend
    from ..data.synthetic import synthetic_hin
    from ..ops.metapath import compile_metapath

    n = point.n
    hin = synthetic_hin(n, 2 * n, max(point.v // 4, 8), seed=0)
    mp = compile_metapath("APVPA", hin.schema)
    rng = np.random.default_rng(0)
    rows = [rng.integers(0, n, size=16) for _ in range(6)]
    backends = {}
    bytes_by: dict[str, int] = {}
    for fmt in KNOBS["factor_format"].candidates({"n": n}):
        b = create_backend("jax-sparse", hin, mp, factor_format=fmt)
        b.topk_rows(rows[0], k=k)  # compile outside the timed region
        backends[fmt] = b
        bytes_by[fmt] = int(b.factor_info()["bytes"])

    def arm(fmt: str):
        b = backends[fmt]
        return _cycled(lambda r: b.topk_rows(r, k=k), rows)

    res = br.time_interleaved({f: arm(f) for f in backends}, reps)
    for fmt in backends:
        res[fmt]["factor_bytes"] = bytes_by[fmt]
    noise = br.noise_bound(res)
    floor_ms = res[br.best_arm(res)]["median_of_best_ms"] * (1.0 + noise)
    winner = min(
        (f for f in backends
         if res[f]["median_of_best_ms"] <= floor_ms),
        key=lambda f: (bytes_by[f], f),
    )
    return {"factor_format": (winner, res)}


def bench_compaction(point: SweepPoint, reps: int, k: int = 10) -> dict:
    """``compact_chain_len`` + ``compact_headroom`` — real arms: a
    warm service absorbing a sustained delta stream (edge adds plus
    periodic node appends, interleaved with affected-row queries)
    under each trigger setting, end to end. Short chains re-encode
    often (paying build+swap more), long chains re-encode rarely (but
    let the headroom trigger — or, past the reserve, the synchronous
    inline rebuild — do the work); the headroom arms trade re-encode
    frequency against padded bytes. The numpy backend keeps the race
    about the knob's own trade — host-side re-encode/replay work vs
    per-delta bookkeeping — rather than XLA compile noise; compaction
    itself is bit-invisible (token, fingerprints, caches preserved),
    so every arm serves identical answers by construction."""
    from ..backends.base import create_backend
    from ..data import delta as dl
    from ..data.synthetic import synthetic_hin
    from ..ops.metapath import compile_metapath
    from ..serving import PathSimService, ServeConfig

    n = min(point.n, 2048)
    n_deltas = 96

    def workload(chain_len: int, headroom: float):
        hin = dl.with_headroom(
            synthetic_hin(n, 2 * n, max(point.v // 8, 8), seed=0), 0.25
        )
        mp = compile_metapath("APVPA", hin.schema)
        svc = PathSimService(
            create_backend("numpy", hin, mp),
            config=ServeConfig(
                max_batch=8, max_wait_ms=0.2, warm=False,
                compact_auto=True, compact_chain_len=chain_len,
                compact_headroom=headroom, compact_cooldown_s=0.0,
            ),
        )
        rng = np.random.default_rng(0)
        ap = hin.blocks["author_of"]
        existing = set(zip(ap.rows.tolist(), ap.cols.tolist()))
        append_seq = itertools.count()

        def run():
            for i in range(n_deltas):
                adds = []
                while len(adds) < 2:
                    e = (int(rng.integers(0, n)),
                         int(rng.integers(0, 2 * n)))
                    if e not in existing:
                        existing.add(e)
                        adds.append(e)
                nodes = ()
                if i % 6 == 5:
                    nodes = (
                        dl.NodeAppend(node_type="venue", count=1)
                        if hin.indices["venue"].size_override is not None
                        else dl.NodeAppend(
                            node_type="venue",
                            ids=(f"v_extra_{next(append_seq)}",),
                        ),
                    )
                svc.update(dl.DeltaBatch(
                    edges=(dl.edge_delta("author_of", add=adds),),
                    nodes=nodes,
                ))
                svc.topk_index(int(adds[0][0]), k=k)
            # fold any in-flight build into the measurement: the
            # arm's cost includes the re-encodes it scheduled
            svc._compactor._done.wait(60.0)

        return svc, run

    out: dict = {}
    for knob, arms_of in (
        ("compact_chain_len",
         lambda c: workload(int(c), 0.25)),
        ("compact_headroom",
         lambda c: workload(8, float(c))),
    ):
        services, arms = [], {}
        for cand in KNOBS[knob].candidates({"n": n}):
            svc, run = arms_of(cand)
            services.append(svc)
            arms[f"arm{cand}"] = run
        res = br.time_interleaved(arms, reps, warmup=0)
        win = br.best_arm(res)
        choice = win.removeprefix("arm")
        out[knob] = (
            int(choice) if knob == "compact_chain_len"
            else float(choice),
            res,
        )
        for svc in services:
            svc.close()
    return out


def bench_ring(point: SweepPoint, reps: int, k: int = 10) -> dict:
    """Ring-step fold choice on a 1-device mesh: the same compiled
    shard_map program a real slice runs per step, minus the ICI hop —
    per-step compute is what distinguishes the folds. The Pallas arm is
    only measured where the kernel is real (interpret mode would
    anti-tune the chip)."""
    import jax

    from ..ops import pallas_kernels as pk
    from ..parallel.mesh import make_mesh
    from ..parallel.sharded import shard_first_block_rows, sharded_topk

    rng = np.random.default_rng(0)
    c_np = rng.integers(0, 3, size=(point.n, point.v)).astype(np.float32)
    mesh = make_mesh(1)
    firsts = [
        shard_first_block_rows(c_np + np.float32(i * 1e-38), mesh)
        for i in range(3)
    ]

    def arm(use_pallas: bool):
        def fn(first):
            jax.block_until_ready(
                sharded_topk(
                    first, (), mesh=mesh, k=k, n_true=point.n,
                    use_pallas=use_pallas,
                )
            )

        return _cycled(fn, firsts)

    arms = {"jnp-fold": arm(False)}
    if pk.pallas_supported() and pk.rect_supported(point.v, k):
        arms["rect-pallas"] = arm(True)
    res = br.time_interleaved(arms, reps)
    return {"ring_kernel": (br.best_arm(res), res)}


def bench_serve_buckets(n_authors: int, max_batch: int, reps: int,
                        k: int = 10, seed: int = 0) -> dict:
    """Bucket-ladder geometry: steady-state batched dispatch over a
    mixed batch-size workload, per candidate ladder (all ladders warmed
    first so the timed phase is the serving steady state; the warm cost
    itself — the other half of the trade — is recorded per arm, each
    measured from cleared jit caches so the geometries share no
    compiled buckets and the numbers stay order-independent)."""
    import time as _time

    import jax

    from ..backends.base import create_backend
    from ..data.synthetic import synthetic_hin
    from ..ops.metapath import compile_metapath
    from ..serving import buckets as bk
    from ..utils.xla_flags import warm_compile_cache

    hin = synthetic_hin(n_authors, 2 * n_authors, 24, seed=seed)
    mp = compile_metapath("APVPA", hin.schema)
    backend = create_backend("jax", hin, mp)
    rng = np.random.default_rng(seed)
    sizes = rng.integers(1, max_batch + 1, size=24)
    rows = rng.integers(0, n_authors, size=(24, max_batch))

    geometries = KNOBS["serve_buckets"].candidates({"n": n_authors})
    warm_s: dict[str, float] = {}
    ladders: dict[str, tuple[int, ...]] = {}
    clear_caches = getattr(jax, "clear_caches", lambda: None)
    for g in geometries:
        ladder = resolve_ladder(g, max_batch)
        ladders[g] = ladder
        # the jit program cache is process-wide, so without clearing it
        # every geometry after the first would reuse the overlapping
        # buckets (1, 4, 16, ...) the previous warm compiled and report
        # a deflated warm cost
        clear_caches()
        t0 = _time.perf_counter()
        warm_compile_cache(backend, ladder, k=k)
        warm_s[g] = _time.perf_counter() - t0
    # re-warm the union so the timed steady-state arms below measure
    # dispatch, not the compiles the last clear_caches() threw away
    for g in geometries:
        warm_compile_cache(backend, ladders[g], k=k)

    def arm(g: str):
        ladder = ladders[g]

        def run():
            for i, bs in enumerate(sizes):
                bucket = bk.bucket_for(int(bs), ladder)
                padded = bk.pad_rows(rows[i, :bs], bucket)
                backend.topk_rows(padded, k=k)

        return run

    res = br.time_interleaved({g: arm(g) for g in geometries}, reps)
    for g in geometries:
        res[g]["warm_ms"] = warm_s[g] * 1e3
        res[g]["ladder"] = list(ladders[g])
    # the knob's trade is steady-state pad waste vs warm-compile count,
    # and a denser ladder's steady state is structurally never worse —
    # picking on dispatch time alone would mean 'coarse' (whose whole
    # point is halving the warmup programs) could never win. So: any
    # geometry whose steady state is within the measured noise of the
    # fastest competes, and among those the cheapest warm wins.
    noise = br.noise_bound(res)
    floor_ms = res[br.best_arm(res)]["median_of_best_ms"] * (1.0 + noise)
    winner = min(
        (g for g in geometries
         if res[g]["median_of_best_ms"] <= floor_ms),
        key=lambda g: (res[g]["warm_ms"], g),
    )
    return {"serve_buckets": (winner, res)}


def bench_ann(point: SweepPoint, reps: int, k: int = 10,
              recall_floor: float = 0.99) -> dict:
    """ANN index knobs (index/ subsystem), measured with a RECALL
    GATE: an arm that misses the recall floor is excluded from the
    race outright, not merely slower — a tuned index that forgot how
    to find the true top-k is wrong, not fast. Probe + exact-rerank
    wall time per query batch is the metric; measured recall rides
    along in every arm record so table entries stay auditable.

    Geometry knobs (``ann_centroids``, ``ann_cluster_cap``) each
    build a real index per arm; probe knobs (``ann_nprobe``,
    ``ann_cand_mult``) share one default-geometry index. All arms are
    real on any platform — the probe is an XLA matmul, CPU or TPU."""
    from ..data.synthetic import synthetic_hin
    from ..index.build import (
        build_index, default_centroids, half_chain_and_denominators,
    )
    from ..ops import pathsim
    from ..ops.metapath import compile_metapath

    n = point.n
    hin = synthetic_hin(n, 2 * n, 24, seed=0)
    mp = compile_metapath("APVPA", hin.schema)
    c, d = half_chain_and_denominators(hin, mp)
    rng = np.random.default_rng(0)
    # sample only ANN-eligible rows: the serving layer answers
    # degenerate rows (d <= 0, all-zero score ties) through the exact
    # path unconditionally, so scoring them against the index would
    # tax every arm with misses no arm can (or needs to) fix
    eligible = np.flatnonzero(d > 0)
    if eligible.size < 2:
        return {}
    sample = np.sort(rng.choice(
        eligible, size=min(64, eligible.size), replace=False
    ))
    oracle_kth: dict[int, float] = {}
    for row in sample:
        scores = pathsim.score_row(c @ c[row], d[row], d)
        scores[int(row)] = -np.inf
        vals, _ = pathsim.topk_from_score_rows(scores[None, :], k)
        oracle_kth[int(row)] = float(vals[0][-1])
    qrows = rng.choice(eligible, size=(8, 32))
    # the cache value keeps the INDEX alive too: keyed by id() alone,
    # a garbage-collected index from an earlier race could recycle its
    # address and hand the next race another geometry's blocks
    blocks_cache: dict[int, tuple] = {}

    def blocks_of(index) -> np.ndarray:
        hit = blocks_cache.get(id(index))
        if hit is not None and hit[0] is index:
            return hit[1]
        safe = np.maximum(index.members, 0)
        bl = c[safe.reshape(-1)].reshape(
            *index.members.shape, c.shape[1]
        )
        bl[index.members < 0] = 0.0
        blocks_cache[id(index)] = (index, bl)
        return bl

    def answer(index, batch, nprobe: int, cand_mult: int, variant: str):
        """Serve one probe batch the way the serving layer would, per
        variant; yields (row, vals)."""
        if variant == "rerank-all":
            mem, top_c = index.route_batch(batch, nprobe)
            blocks = blocks_of(index)
            for b, row in enumerate(batch):
                blk = blocks[top_c[b]]
                counts = blk.reshape(-1, blk.shape[-1]) @ c[row]
                cols = mem[b].astype(np.int64)
                dc = d[np.maximum(cols, 0)]
                sc = pathsim.score_candidates(
                    counts[None, :], np.asarray([d[row]]), dc[None, :]
                )
                vals, _ = pathsim.topk_from_candidate_scores(
                    sc, cols[None, :], k
                )
                yield int(row), vals[0]
        else:
            sims, mem = index.probe_batch(batch, nprobe)
            for b, row in enumerate(batch):
                cand = index.select_candidates(
                    sims[b], mem[b], cand_mult * k
                )
                counts = c[cand] @ c[row]
                sc = pathsim.score_candidates(
                    counts[None, :], np.asarray([d[row]]),
                    d[cand][None, :],
                )
                vals, _ = pathsim.topk_from_candidate_scores(
                    sc, cand[None, :], k
                )
                yield int(row), vals[0]

    def recall_of(index, nprobe: int, cand_mult: int,
                  variant: str) -> float:
        """Score recall@k (ties at the k boundary count — the serving
        shadow gate's metric, serving/ann.py)."""
        hits = tot = 0
        for row, vals in answer(index, sample.astype(np.int64),
                                nprobe, cand_mult, variant):
            kth = oracle_kth[row]
            got = vals[np.isfinite(vals)]
            hits += min(int((got >= kth).sum()), k)
            tot += k
        return hits / max(tot, 1)

    def timing_arm(index, nprobe: int, cand_mult: int, variant: str):
        def run():
            for batch in qrows:
                for _ in answer(index, batch, nprobe, cand_mult,
                                variant):
                    pass

        return run

    def race(names) -> tuple | None:
        """Measure the feasible arms of one knob; None when no arm
        meets the recall floor (the knob keeps its heuristic)."""
        arms, recalls = {}, {}
        for name, (index, nprobe, mult, variant) in names.items():
            r = recall_of(index, nprobe, mult, variant)
            recalls[name] = r
            if r >= recall_floor:
                arms[name] = timing_arm(index, nprobe, mult, variant)
        if not arms:
            return None
        res = br.time_interleaved(arms, reps)
        for name in res:
            res[name]["recall"] = round(recalls[name], 4)
        return br.best_arm(res), res

    out: dict = {}
    idx0 = build_index(
        c=c, d=d, metapath=mp,
        n_centroids=default_centroids(n, 1.0),
    )
    nprobe_w = min(max(16, idx0.n_centroids // 3), 96)
    mult_w = 16
    var_w = "rerank-all"

    raced = race({
        f"var-{v_}": (idx0, nprobe_w, mult_w, v_)
        for v_ in KNOBS["ann_probe_variant"].candidates({"n": n})
    })
    if raced is not None:
        win, res = raced
        var_w = win.removeprefix("var-")
        out["ann_probe_variant"] = (var_w, res)

    raced = race({
        f"nprobe{p}": (idx0, p, mult_w, var_w)
        for p in KNOBS["ann_nprobe"].candidates({"n": n})
        if p <= idx0.n_centroids
    })
    if raced is not None:
        win, res = raced
        nprobe_w = int(win.removeprefix("nprobe"))
        out["ann_nprobe"] = (nprobe_w, res)

    raced = race({
        f"mult{m}": (idx0, nprobe_w, m, "shortlist")
        for m in KNOBS["ann_cand_mult"].candidates({"n": n})
    })
    if raced is not None:
        win, res = raced
        mult_w = int(win.removeprefix("mult"))
        out["ann_cand_mult"] = (mult_w, res)

    raced = race({
        f"cmult{cm}": (
            build_index(
                c=c, d=d, metapath=mp,
                n_centroids=default_centroids(n, float(cm)),
            ),
            nprobe_w, mult_w, var_w,
        )
        for cm in KNOBS["ann_centroids"].candidates({"n": n})
    })
    if raced is not None:
        win, res = raced
        out["ann_centroids"] = (float(win.removeprefix("cmult")), res)

    raced = race({
        f"cap{cap}": (
            build_index(
                c=c, d=d, metapath=mp,
                n_centroids=default_centroids(n, 1.0),
                cluster_cap=cap,
            ),
            nprobe_w, mult_w, var_w,
        )
        for cap in KNOBS["ann_cluster_cap"].candidates({"n": n})
    })
    if raced is not None:
        win, res = raced
        out["ann_cluster_cap"] = (int(win.removeprefix("cap")), res)
    return out


_ANN_KNOBS = ("ann_nprobe", "ann_cand_mult", "ann_centroids",
              "ann_cluster_cap", "ann_probe_variant")


def bench_learned(point: SweepPoint, reps: int, k: int = 10,
                  recall_floor: float = 0.95) -> dict:
    """Learned-tier knobs (learned/ subsystem), measured with the same
    RECALL GATE discipline as :func:`bench_ann`: an arm whose tower
    shortlist misses the floor is excluded outright. Tower arms
    (``learned_dim``, ``learned_neg_ratio``) each distill a real tower
    per arm (tiny step budget — the race is about geometry, not final
    loss); ``learned_cand_mult`` re-serves one tower at different
    shortlist widths. ``learned_conf_floor`` picks the tightest floor
    the measured recall actually clears, and
    ``learned_refresh_deltas`` races a sustained delta+query stream
    end to end per cadence (the bench_compaction pattern: fold cost vs
    degraded-query cost, measured, not modeled)."""
    from ..data.synthetic import synthetic_hin
    from ..index.build import half_chain_and_denominators
    from ..learned.serving import LearnedState
    from ..learned.trainer import train_towers
    from ..ops import pathsim
    from ..ops.metapath import compile_metapath

    # cap the training graph: tower geometry trades are visible at 2k
    # rows, and per-arm distillation cost must stay offline-tolerable
    n = min(point.n, 2048)
    hin = synthetic_hin(n, 2 * n, 24, seed=0)
    mp = compile_metapath("APVPA", hin.schema)
    c, d = half_chain_and_denominators(hin, mp)
    rng = np.random.default_rng(0)
    eligible = np.flatnonzero(d > 0)
    if eligible.size < 2:
        return {}
    sample = np.sort(rng.choice(
        eligible, size=min(64, eligible.size), replace=False
    ))
    oracle_kth: dict[int, float] = {}
    for row in sample:
        scores = pathsim.score_row(c @ c[row], d[row], d)
        scores[int(row)] = -np.inf
        vals, _ = pathsim.topk_from_score_rows(scores[None, :], k)
        oracle_kth[int(row)] = float(vals[0][-1])
    qrows = rng.choice(eligible, size=(8, 32))

    encoders: dict[tuple, object] = {}

    def encoder_for(dim: int, neg_ratio: float):
        key = (dim, neg_ratio)
        if key not in encoders:
            enc, _ = train_towers(
                hin, "APVPA", dim=dim, hidden=64, steps=80, seed=0,
                hard_frac=1.0 - neg_ratio,
                hard_sources=min(n, 256), hard_k=2 * k,
            )
            encoders[key] = enc
        return encoders[key]

    states: list[LearnedState] = []

    def state_for(dim: int, neg_ratio: float,
                  cand_mult: int) -> LearnedState:
        st = LearnedState(
            encoder_for(dim, neg_ratio), c, d,
            cand_mult=cand_mult, shadow_every=0,
        )
        states.append(st)
        return st

    def recall_of(st: LearnedState) -> float:
        hits = tot = 0
        handle = st.probe_batch(sample.astype(np.int64))
        for b, row in enumerate(sample):
            vals, _ = st.answer_from_handle(handle, b, int(row), k)
            kth = oracle_kth[int(row)]
            got = vals[np.isfinite(vals)]
            hits += min(int((got >= kth).sum()), k)
            tot += k
        return hits / max(tot, 1)

    def timing_arm(st: LearnedState):
        def run():
            for batch in qrows:
                handle = st.probe_batch(batch)
                for b, row in enumerate(batch):
                    st.answer_from_handle(handle, b, int(row), k)

        return run

    def race(named_states: dict) -> tuple | None:
        arms, recalls = {}, {}
        for name, st in named_states.items():
            r = recall_of(st)
            recalls[name] = r
            if r >= recall_floor:
                arms[name] = timing_arm(st)
        if not arms:
            return None
        res = br.time_interleaved(arms, reps)
        for name in res:
            res[name]["recall"] = round(recalls[name], 4)
        return br.best_arm(res), res

    out: dict = {}
    try:
        dim_w, neg_w, mult_w = 32, 0.5, 16
        raced = race({
            f"dim{dm}": state_for(dm, neg_w, mult_w)
            for dm in KNOBS["learned_dim"].candidates({"n": n})
        })
        if raced is not None:
            win, res = raced
            dim_w = int(win.removeprefix("dim"))
            out["learned_dim"] = (dim_w, res)

        raced = race({
            f"neg{nr}": state_for(dim_w, nr, mult_w)
            for nr in KNOBS["learned_neg_ratio"].candidates({"n": n})
        })
        if raced is not None:
            win, res = raced
            neg_w = float(win.removeprefix("neg"))
            out["learned_neg_ratio"] = (neg_w, res)

        raced = race({
            f"mult{m}": state_for(dim_w, neg_w, m)
            for m in KNOBS["learned_cand_mult"].candidates({"n": n})
        })
        if raced is not None:
            win, res = raced
            mult_w = int(win.removeprefix("mult"))
            out["learned_cand_mult"] = (mult_w, res)

        # confidence floor: the tightest (highest) candidate floor the
        # measured recall of the SHIPPED configuration clears — a floor
        # above measured recall would trip the gate on day one, a floor
        # far below it wastes the safety margin the gate exists for
        final = state_for(dim_w, neg_w, mult_w)
        r_final = recall_of(final)
        floors = KNOBS["learned_conf_floor"].candidates({"n": n})
        feasible = [f for f in floors if f <= r_final]
        if feasible:
            ms = br.time_interleaved(
                {"final": timing_arm(final)}, reps
            )["final"]["median_of_best_ms"]
            res = {
                f"floor{f}": {
                    "median_of_best_ms": ms,
                    "recall": round(r_final, 4),
                }
                for f in floors
                if f <= r_final
            }
            out["learned_conf_floor"] = (max(feasible), res)

        # refresh cadence: a sustained delta+query stream, end to end
        # per arm — each "delta" stales a row block (those queries
        # answer through the exact path, the serving fallback), every
        # cadence-th landing pays the real half-chain fold + absorb
        enc_final = encoder_for(dim_w, neg_w)
        stale_blocks = rng.choice(
            eligible, size=(8, 32)).astype(np.int64)

        def cadence_arm(every: int):
            def run():
                st = LearnedState(
                    enc_final, c, d, cand_mult=mult_w, shadow_every=0
                )
                states.append(st)
                since = 0
                for i, block in enumerate(stale_blocks):
                    st.mark_stale(block)
                    since += 1
                    for b, row in enumerate(qrows[i % len(qrows)]):
                        row = int(row)
                        if st.peek(row) is not None:
                            scores = pathsim.score_row(
                                c @ c[row], d[row], d
                            )
                            scores[row] = -np.inf
                            pathsim.topk_from_score_rows(
                                scores[None, :], k
                            )
                        else:
                            h = st.probe_batch(
                                np.asarray([row], dtype=np.int64)
                            )
                            st.answer_from_handle(h, 0, row, k)
                    if since >= every:
                        c2, d2 = half_chain_and_denominators(hin, mp)
                        st.absorb(c2, d2, ("", i + 1))
                        since = 0

            return run

        res = br.time_interleaved(
            {
                f"every{e}": cadence_arm(e)
                for e in KNOBS["learned_refresh_deltas"]
                .candidates({"n": n})
            },
            reps,
        )
        win = br.best_arm(res)
        out["learned_refresh_deltas"] = (
            int(win.removeprefix("every")), res
        )
    finally:
        for st in states:
            st.close()
    return out


_LEARNED_KNOBS = ("learned_dim", "learned_neg_ratio",
                  "learned_cand_mult", "learned_conf_floor",
                  "learned_refresh_deltas")


# ---------------------------------------------------------------------------
# Sweep driver
# ---------------------------------------------------------------------------

_DENSE_KNOBS = ("scores_variant", "scores_tile", "topk_rowtile", "k_tile",
                "ring_kernel")
_SPARSE_KNOBS = ("sparse_tile_rows", "sparse_nnz_floor")


def tune(
    points: list[SweepPoint],
    knobs: list[str] | None = None,
    reps: int = 3,
    max_batch: int = 32,
    out: str | None = None,
) -> TuningTable:
    """Measure ``knobs`` (default: every knob with a real arm here)
    over ``points`` and return (and optionally save) the table."""
    want = set(knobs) if knobs else set(KNOBS)
    unknown = want - set(KNOBS)
    if unknown:
        raise ValueError(f"unknown knob(s) {sorted(unknown)}")
    table = TuningTable(dispatch.device_kind())

    def record(point: SweepPoint | None, results: dict,
               nnz: int | None = None) -> None:
        for knob, (choice, arms) in results.items():
            if knob not in want:
                continue
            key = make_key(
                knob, dispatch.device_kind(),
                n=point.n if point else None,
                v=point.v if point else None,
                nnz=nnz,
            )
            arms_out: dict[str, float] = {}
            for name, a in arms.items():
                arms_out[name] = a["median_of_best_ms"]
                if "warm_ms" in a:
                    # serve_buckets picks within the steady-state noise
                    # band by warm cost — persist the deciding number
                    # so the entry stays auditable from the table alone
                    arms_out[f"{name}_warm"] = a["warm_ms"]
                if "recall" in a:
                    # ann knobs gate on measured recall before racing
                    # on time — persist it per arm for the same reason
                    arms_out[f"{name}_recall"] = a["recall"]
                if "factor_bytes" in a:
                    # factor_format picks within the noise band by
                    # resident bytes — persist the deciding number
                    arms_out[f"{name}_bytes"] = float(a["factor_bytes"])
            table.put(
                key, choice,
                metric_ms=min(
                    a["median_of_best_ms"] for a in arms.values()
                ),
                arms=arms_out,
            )
            runtime_event(
                "tuning_measured", echo=False, knob=knob, key=key,
                choice=choice, arms=len(arms),
            )

    for point in points:
        if point.nnz is None:
            if want & {"scores_variant", "scores_tile"}:
                record(point, bench_scores(point, reps))
            if "topk_rowtile" in want:
                record(point, bench_topk_rowtile(point, reps))
            if "k_tile" in want:
                record(point, bench_k_tile(point, reps))
            if "ring_kernel" in want:
                record(point, bench_ring(point, reps))
            if want & set(_ANN_KNOBS):
                record(point, bench_ann(point, reps))
            if want & set(_LEARNED_KNOBS):
                record(point, bench_learned(point, reps))
            if want & {"plan_density_cutover", "plan_memo_budget_mb"}:
                record(point, bench_planner(point, reps))
            if "factor_format" in want:
                record(point, bench_factor_format(point, reps))
            if want & {"compact_chain_len", "compact_headroom"}:
                record(point, bench_compaction(point, reps))
        else:
            if "sparse_tile_rows" in want:
                record(point, bench_sparse_tiles(point, reps),
                       nnz=point.nnz)
            if "sparse_nnz_floor" in want:
                record(point, bench_sparse_nnz_floor(point, reps),
                       nnz=point.nnz)
    if "serve_buckets" in want:
        # keyed on (n_authors, max_batch): the ladder trade depends on
        # the batch ceiling, so it rides the V axis of the key (the
        # knob has no contraction width of its own)
        res = bench_serve_buckets(
            n_authors=min(512, max(p.n for p in points) if points else 512),
            max_batch=max_batch, reps=reps,
        )
        point = SweepPoint(
            n=min(512, max(p.n for p in points) if points else 512),
            v=max_batch,
        )
        record(point, res)
    if out:
        digest = table.save(out)
        runtime_event(
            "tuning_table_written", table=out, digest=digest,
            entries=len(table.entries),
        )
    return table


_QUICK_POINTS = [SweepPoint(1024, 384), SweepPoint(2048, 64, nnz=16384)]
_DEFAULT_POINTS = [
    SweepPoint(2048, 384),
    SweepPoint(8192, 384),
    SweepPoint(4096, 64, nnz=32768),
]


def tune_main(argv: list[str] | None = None) -> int:
    """``dpathsim tune`` — measure this device, write the table."""
    p = argparse.ArgumentParser(
        prog="dpathsim tune",
        description="autotune kernel/tile/bucket knobs on THIS device "
        "and write the dispatch table consulted by --tuning-table",
    )
    p.add_argument("--out", required=True, help="table JSON path")
    p.add_argument(
        "--shapes", default=None,
        help="comma-separated NxV (dense) / NxVxNNZ (sparse) sweep "
        "points; default a small dense+sparse set",
    )
    p.add_argument(
        "--knobs", default=None,
        help="comma-separated knob subset (default: all measurable here)",
    )
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--max-batch", type=int, default=32,
                   help="serving bucket ceiling for the serve_buckets knob")
    p.add_argument("--quick", action="store_true",
                   help="smallest sweep (seconds, CPU-safe)")
    args = p.parse_args(argv)

    if args.shapes:
        points = [SweepPoint.parse(s) for s in args.shapes.split(",") if s]
    else:
        points = _QUICK_POINTS if args.quick else _DEFAULT_POINTS
    knobs = (
        [k.strip() for k in args.knobs.split(",") if k.strip()]
        if args.knobs else None
    )
    table = tune(
        points, knobs=knobs, reps=args.reps, max_batch=args.max_batch,
        out=args.out,
    )
    runtime_event(
        "tuning_done",
        table=args.out,
        entries=len(table.entries),
        device=table.device_kind,
        digest=table.digest,
    )
    return 0
