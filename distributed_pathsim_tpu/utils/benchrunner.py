"""Shared benchmark estimator: interleaved arms, median-of-best.

The BENCH_OBS_r08 estimator note, turned into the one implementation
every harness uses: on a shared CI box the baseline drifts up to 3×
between reps, so (a) comparison arms must be **interleaved** — round
r runs every arm once, in a fixed order, so a drift window hits all
arms roughly equally instead of poisoning whichever arm happened to run
last — and (b) the point estimate must be **median-of-best**: external
load only ever *slows* a run down (noise is additive), so the fastest
samples are the least-contended windows, and the median over the
fastest half is robust both to drift (which the best samples dodge) and
to a single lucky fluke (which a bare min would canonize).

Consumers: ``bench_serving.py`` (obs arms), ``scripts/kernel_bench.py``
(per-kernel medians), and the offline autotuner
(``tuning/autotuner.py``), which fixed the 3× drift problem at one
site instead of three.
"""

from __future__ import annotations

import math
import time
from statistics import median
from typing import Any, Callable, Mapping, Sequence, TypeVar

__all__ = [
    "best_arm", "interleave", "median", "median_of_best", "noise_bound",
    "paired_ratio", "summarize", "time_interleaved",
]

T = TypeVar("T")


def interleave(
    arms: Mapping[str, Callable[[], T]], reps: int
) -> dict[str, list[T]]:
    """Run every arm once per round, ``reps`` rounds, in the mapping's
    fixed order. Returns each arm's per-round results. This is the
    drift-spreading half of the estimator; it collects whatever the
    arms return (timings, stats dicts, …)."""
    out: dict[str, list[T]] = {name: [] for name in arms}
    for _ in range(reps):
        for name, fn in arms.items():
            out[name].append(fn())
    return out


def median_of_best(xs: Sequence[float], keep_frac: float = 0.5) -> float:
    """Median of the fastest ``keep_frac`` of the samples (at least
    one). The estimator of record for arm comparisons — see module
    docstring for why neither the bare median (drift-inflated) nor the
    bare min (one lucky scheduler window) is it."""
    s = sorted(xs)
    keep = max(1, math.ceil(len(s) * keep_frac))
    return median(s[:keep])


def summarize(times_s: Sequence[float]) -> dict[str, float]:
    """The standard per-arm summary: every artifact records all three
    estimates so a reader can see when drift was larger than the effect
    being measured (median far from median_of_best = noisy run)."""
    return {
        "reps": len(times_s),
        "best_ms": min(times_s) * 1e3,
        "median_ms": median(times_s) * 1e3,
        "median_of_best_ms": median_of_best(times_s) * 1e3,
        "worst_ms": max(times_s) * 1e3,
    }


def time_interleaved(
    arms: Mapping[str, Callable[[], Any]],
    reps: int,
    warmup: int = 1,
) -> dict[str, dict[str, float]]:
    """Wall-time each arm ``reps`` times, interleaved, after ``warmup``
    untimed calls per arm (compiles and cache fills must not be
    attributed to the first round). Each round rotates its starting
    arm: with a fixed order, box load that correlates with the round
    phase (periodic background work, allocator/cache state left by the
    previous round's last arm) taxes the same position every round and
    interleaving alone can't cancel it. Returns per-arm summaries plus
    the raw samples (``times_ms``) for the artifact."""
    for _ in range(warmup):
        for fn in arms.values():
            fn()
    names = list(arms)
    samples: dict[str, list[float]] = {name: [] for name in arms}
    for r in range(max(1, reps)):
        start = r % len(names)
        for name in names[start:] + names[:start]:
            fn = arms[name]
            t0 = time.perf_counter()
            fn()
            samples[name].append(time.perf_counter() - t0)
    out: dict[str, dict[str, float]] = {}
    for name, ts in samples.items():
        s = summarize(ts)
        s["times_ms"] = [t * 1e3 for t in ts]
        out[name] = s
    return out


def paired_ratio(
    results: Mapping[str, Mapping[str, Any]],
    arm: str,
    versus: Sequence[str],
) -> float:
    """Median over rounds of ``arm``'s time divided by the fastest of
    ``versus`` in the SAME round — the paired comparison interleaving
    exists to enable. Box drift moves whole rounds (a round's arms run
    within one load window), so the within-round ratio cancels drift
    that aggregate estimates like median-of-best can only bound; use
    this for accept/regress gates between arms, and median-of-best for
    absolute per-arm numbers. Requires ``time_interleaved`` results
    (the raw ``times_ms`` samples)."""
    if not versus:
        raise ValueError("paired_ratio needs at least one versus arm")
    times = results[arm]["times_ms"]
    ratios = [
        t / max(min(results[v]["times_ms"][r] for v in versus), 1e-12)
        for r, t in enumerate(times)
    ]
    return median(ratios)


def best_arm(results: Mapping[str, Mapping[str, float]]) -> str:
    """The winning arm by median-of-best, deterministic tie-break on
    the arm name."""
    return min(
        results, key=lambda name: (results[name]["median_of_best_ms"], name)
    )


def noise_bound(results: Mapping[str, Mapping[str, float]],
                floor: float = 0.05) -> float:
    """A relative noise envelope for 'within noise' gates: the largest
    per-arm spread between the median and median-of-best estimates
    (drift that survived interleaving), floored so a suspiciously quiet
    run still gets a sane tolerance."""
    rel = floor
    for r in results.values():
        base = max(r["median_of_best_ms"], 1e-9)
        rel = max(rel, (r["median_ms"] - base) / base)
    return rel
