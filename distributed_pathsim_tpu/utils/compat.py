"""JAX version compatibility shims.

The sharded paths are written against the modern ``jax.shard_map``
surface (``check_vma=``). Older JAX (≤ 0.4.x) only ships
``jax.experimental.shard_map.shard_map`` with the pre-rename
``check_rep=`` argument — same semantics, different spelling. Every
shard_map call in this repo routes through :func:`shard_map` so a JAX
upgrade (or downgrade on a TPU image pinned to an older wheel) degrades
to the available API instead of dying with ``AttributeError`` at import
of the first sharded module.
"""

from __future__ import annotations

import functools

import jax


@functools.cache
def _impl():
    if hasattr(jax, "shard_map"):
        return jax.shard_map, "check_vma"
    from jax.experimental.shard_map import shard_map as sm

    return sm, "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` on new JAX, ``jax.experimental.shard_map`` (with
    ``check_vma`` translated to ``check_rep``) on old."""
    impl, vma_kwarg = _impl()
    return impl(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{vma_kwarg: check_vma},
    )


def axis_size(axis_name) -> int:
    """Static size of a shard_map mesh axis. Old JAX has no
    ``lax.axis_size``; ``psum`` of a Python literal constant-folds to a
    Python int there, which is exactly the static value the ring setup
    code (permutation tables, loop bounds) needs."""
    import jax.lax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast(x, axis_names, to: str = "varying"):
    """``lax.pcast`` marks values for the varying-axis (VMA) checker.
    Old JAX has neither the primitive nor the checker (its ``check_rep``
    machinery infers replication itself), so the declaration is simply
    dropped there."""
    import jax.lax

    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_names, to=to)
    return x
