"""Checkpoint / resume for long tiled runs.

The reference's only crash-resilience is an append-mode log flushed per
stage — its shipped artifact is literally a run that died mid-stage and
kept its partial results (``output/...log``, SURVEY.md §5). This module
generalizes that: a run directory holds a JSON manifest of completed
work units plus one .npy part per unit, written atomically (temp +
rename). A restarted run skips completed units — the all-pairs analog
of the reference's per-pair incremental writes.
"""

from __future__ import annotations

import json
import os
import pathlib

import numpy as np


class CheckpointManager:
    """Atomic per-unit result store with a completion manifest."""

    MANIFEST = "manifest.json"
    CONFIG_KEY = "__config__"

    def __init__(
        self,
        directory: str,
        config: dict | None = None,
        config_defaults: dict | None = None,
    ):
        """``config``: the run's identity (graph fingerprint, tiling, k…).
        On resume it must equal the stored one — a reused directory from a
        different run fails loudly instead of returning stale results.

        ``config_defaults``: values assumed for keys ABSENT from the
        stored config — lets a newer version add identity keys without
        invalidating old directories whose runs used the defaults."""
        self.dir = pathlib.Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self._manifest_path = self.dir / self.MANIFEST
        self._done: dict[str, dict] = {}
        if self._manifest_path.exists():
            self._done = json.loads(self._manifest_path.read_text())
        if config is not None:
            stored = self._done.get(self.CONFIG_KEY)
            if stored is not None and config_defaults:
                stored = {**config_defaults, **stored}
            if stored is not None and stored != config:
                if stored.get("format") != config.get("format"):
                    raise ValueError(
                        f"checkpoint directory {directory} was written with "
                        f"on-disk format {stored.get('format')!r} but this "
                        f"version uses {config.get('format')!r}: the stored "
                        "units cannot be resumed — delete the directory to "
                        "re-run from scratch"
                    )
                raise ValueError(
                    f"checkpoint directory {directory} belongs to a different "
                    f"run: stored config {stored} != requested {config}"
                )
            if stored is None:
                self._done[self.CONFIG_KEY] = config
                _atomic_write_text(
                    self._manifest_path,
                    json.dumps(self._done, indent=0, sort_keys=True),
                )

    # -- unit tracking -----------------------------------------------------

    def is_done(self, key: str) -> bool:
        return key != self.CONFIG_KEY and key in self._done

    def done_keys(self) -> list[str]:
        return sorted(k for k in self._done if k != self.CONFIG_KEY)

    def save_unit(self, key: str, **arrays: np.ndarray) -> None:
        """Persist a completed unit's arrays and mark it done (atomic:
        arrays land before the manifest references them).

        This is the ``checkpoint_write`` resilience seam: transient I/O
        failures (including injected partial writes — which die before
        the rename, so the previous manifest state stays valid) are
        retried; the whole unit write is idempotent, so a retry simply
        rewrites every array and the manifest."""
        from .. import resilience

        def write() -> None:
            names = {}
            for name, arr in arrays.items():
                fname = f"{_safe(key)}.{name}.npy"
                _atomic_save(self.dir / fname, arr)
                names[name] = fname
            self._done[key] = names
            _atomic_write_text(
                self._manifest_path,
                json.dumps(self._done, indent=0, sort_keys=True),
            )

        resilience.resilient_call("checkpoint_write", write)

    def load_unit(self, key: str) -> dict[str, np.ndarray]:
        names = self._done[key]
        return {name: np.load(self.dir / fname) for name, fname in names.items()}

    def drop_unit(self, key: str) -> None:
        """Forget a unit: remove it from the manifest first (so a crash
        mid-drop leaves at worst orphaned .npy files, never a manifest
        entry pointing at deleted data), then best-effort unlink."""
        names = self._done.pop(key, None)
        if names is None:
            return
        _atomic_write_text(
            self._manifest_path, json.dumps(self._done, indent=0, sort_keys=True)
        )
        for fname in names.values():
            try:
                (self.dir / fname).unlink()
            except OSError:
                pass


def _safe(key: str) -> str:
    return "".join(c if c.isalnum() or c in "-_." else "_" for c in key)


def _atomic_save(path: pathlib.Path, arr: np.ndarray) -> None:
    from ..resilience import inject

    tmp = path.with_suffix(path.suffix + f".tmp{os.getpid()}")
    try:
        with open(tmp, "wb") as f:  # explicit handle: np.save won't append .npy
            np.save(f, arr)
            # Chaos hook: a pending 'partial' rule truncates the temp
            # file and raises HERE — before the rename — proving the
            # final path never sees a torn write.
            inject.corrupt_stream("checkpoint_write", f)
        os.replace(tmp, path)
    except BaseException:
        try:
            tmp.unlink()
        except OSError:
            pass
        raise


def _atomic_write_text(path: pathlib.Path, text: str) -> None:
    tmp = path.with_suffix(path.suffix + f".tmp{os.getpid()}")
    try:
        tmp.write_text(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            tmp.unlink()
        except OSError:
            pass
        raise
