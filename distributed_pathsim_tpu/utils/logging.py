"""Run logging: the reference's exact log grammar + structured metrics.

The reference mirrors the walk/score lines to stdout AND an append-mode
UTF-8 file, but writes the ``***Stage/Overall done`` markers and ``---``
separators to the file only, flushing per stage
(``DPathSim_APVPA.py:24-68`` — ``print`` at :32,:42,:47,:56; file-only
writes at :63-64,:67). We reproduce both channels exactly. File grammar
per stage (see reference ``output/d_pathsim_output_20180417_020445.log:1-6``):

    Source author global walk: <int>
    Pairwise authors walk <target_id>: <int>
    Target author global walk: <int>
    Sim score <source_label> - <target_label>: <float>
    ***Stage done in: <seconds>
    ---
    ...
    ***Overall done in: <seconds>

Float rendering is Python ``str(float)``, same wording — file output is
byte-diffable against the reference log. A JSONL metrics channel is added
as a new capability.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import IO, Any

from ..obs.metrics import get_registry

# -- clock discipline --------------------------------------------------------
#
# Two clocks, two jobs, never mixed: time.time() (wall) is for HUMANS and
# cross-process joins; time.monotonic() is for ORDERING and durations (it
# never steps backward under NTP). Every JSONL event carries both, stamped
# by this one helper — the same monotonic clock the tracer's spans use
# (obs/trace.py), so events and spans join on the ts_mono axis. This is
# the only sanctioned time.time() call site in the package
# (scripts/lint_telemetry.py enforces it).


def timestamps() -> dict[str, float]:
    """One wall + monotonic stamp pair, read back to back."""
    return {"ts": time.time(), "ts_mono": time.monotonic()}


# -- runtime events (resilience channel) -----------------------------------
#
# Retries, degradations, fault injections, and preemption requests must be
# VISIBLE — a run that silently stepped down from the sharded backend to
# numpy is a debugging trap. Every such event goes through runtime_event():
# one structured line on stderr (never stdout — the reference grammar owns
# stdout), plus the JSONL metrics channel when a RunLogger is registered
# as the process-wide sink (the CLI registers its logger for the run),
# plus a per-event counter in the obs registry (the live aggregate the
# ``metrics`` protocol op and the Prometheus textfile expose).

_EVENT_SINK: "RunLogger | None" = None
# One lock for sink swaps AND stderr writes: runtime_event fires from the
# coalescer's worker threads concurrently with the main thread, so an
# unguarded sink swap could emit into a half-closed logger and two stderr
# prints could interleave their characters mid-line.
_EVENT_LOCK = threading.Lock()


def set_event_sink(logger: "RunLogger | None") -> None:
    """Register (or clear, with None) the RunLogger whose JSONL metrics
    channel receives runtime events."""
    global _EVENT_SINK
    with _EVENT_LOCK:
        _EVENT_SINK = logger


def runtime_event(event: str, echo: bool = True, **fields: Any) -> None:
    """Emit one structured resilience/runtime event.

    stderr rendering: ``[pathsim:EVENT] k=v k=v``; machine rendering: a
    metrics-JSONL record ``{"event": EVENT, ...fields}`` on the
    registered sink, plus ``dpathsim_events_total{event=...}`` in the
    obs registry. Values are stringified for stderr but passed through
    for JSONL (callers pre-repr exceptions).

    ``echo=False`` suppresses only the stderr line (the JSONL record
    always lands): high-rate serving events (per-batch accounting,
    sustained load shedding) must not turn the operator channel into
    the bottleneck, but still need to be machine-visible."""
    get_registry().counter(
        "dpathsim_events_total", "runtime_event emissions by event name"
    ).inc(event=event)
    with _EVENT_LOCK:
        if echo:
            rendered = " ".join(f"{k}={v}" for k, v in fields.items())
            # one write call, trailing newline included: the line lands
            # atomically even when worker threads emit concurrently
            sys.stderr.write(f"[pathsim:{event}] {rendered}".rstrip() + "\n")
        if _EVENT_SINK is not None:
            _EVENT_SINK.metric(event=event, **fields)


class RunLogger:
    """Dual-channel logger: reference-grammar text + optional JSONL."""

    def __init__(
        self,
        output_path: str | None = None,
        echo: bool = True,
        metrics_path: str | None = None,
    ):
        # The grammar file opens lazily on first write: a run that fails
        # during bootstrap, or a mode that never emits the reference
        # grammar (rank-all), must not leave a stray empty file behind.
        self._output_path = output_path
        self._file: IO[str] | None = None
        self._echo = echo
        self._metrics: IO[str] | None = (
            open(metrics_path, "a", encoding="utf-8") if metrics_path else None
        )
        # The JSONL channel is written from multiple threads (the CLI's
        # main thread, coalescer workers via runtime_event): one lock
        # keeps each record on its own line and close() race-free.
        self._metrics_lock = threading.Lock()
        self.overall_start = time.perf_counter()

    # -- reference grammar -------------------------------------------------

    def source_global_walk(self, count: int) -> None:
        self._line(f"Source author global walk: {count}")

    def pairwise_walk(self, target_id: str, count: int) -> None:
        self._line(f"Pairwise authors walk {target_id}: {count}")

    def target_global_walk(self, count: int) -> None:
        self._line(f"Target author global walk: {count}")

    def sim_score(self, source_label: str, target_label: str, score: float) -> None:
        self._line(f"Sim score {source_label} - {target_label}: {score}")

    def stage_done(self, seconds: float) -> None:
        self._write(f"***Stage done in: {seconds}\n")
        self._write("---\n")
        self.flush()

    def overall_done(self) -> None:
        self._write(
            f"***Overall done in: {time.perf_counter() - self.overall_start}\n"
        )
        # Only the reference-grammar file ends here; the metrics channel
        # stays open so post-run stage timings (e.g. a following rank-all
        # or all-pairs phase) still land in the JSONL.
        self._close_grammar_file()

    # -- structured channel (new capability) -------------------------------

    def metric(self, **fields: Any) -> None:
        # Both clocks from the one helper (see timestamps()): ts for
        # humans/joins across processes, ts_mono for ordering and
        # joining with span timestamps — a duration must never be
        # computed from ts (wall time steps under NTP).
        stamps = timestamps()
        fields.setdefault("ts", stamps["ts"])
        fields.setdefault("ts_mono", stamps["ts_mono"])
        with self._metrics_lock:
            if self._metrics is not None:
                self._metrics.write(json.dumps(fields) + "\n")
                self._metrics.flush()

    # -- plumbing ----------------------------------------------------------

    def _line(self, text: str) -> None:
        if self._echo:
            print(text)
        self._write(text + "\n")

    def _write(self, text: str) -> None:
        if self._output_path is None:
            return
        if self._file is None:
            self._file = open(self._output_path, "a", encoding="utf-8")
        self._file.write(text)

    def _close_grammar_file(self) -> None:
        # _output_path survives the close: the file opens in append mode,
        # so a library caller reusing one logger for a second
        # run_single_source call transparently reopens and appends —
        # writes after overall_done() must never be dropped silently.
        if self._file is not None:
            self._file.close()
            self._file = None

    def flush(self) -> None:
        if self._file is not None:
            self._file.flush()
        sys.stdout.flush()

    def close(self) -> None:
        # Terminal for BOTH channels (unlike overall_done, which leaves
        # the grammar path reopenable for a next run on the same logger):
        # after close(), grammar writes and metric() are both no-ops.
        self._close_grammar_file()
        self._output_path = None
        with self._metrics_lock:
            if self._metrics is not None:
                self._metrics.close()
                self._metrics = None
