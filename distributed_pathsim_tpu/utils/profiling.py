"""Profiling: stage wall-clock (reference parity) + device traces (new).

The reference's entire observability is ``timeit.default_timer`` deltas
written to its log (``DPathSim_APVPA.py:26,37,63,67``). StageTimer keeps
that capability behind a context manager; ``device_trace`` adds what the
reference never had — a real ``jax.profiler`` trace (XLA op timeline,
HBM usage) viewable in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator

import jax


class StageTimer:
    """Accumulates named stage timings; integrates with RunLogger.metric."""

    def __init__(self, logger=None):
        self.stages: list[tuple[str, float]] = []
        self._logger = logger

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.stages.append((name, dt))
            if self._logger is not None:
                self._logger.metric(event="stage_time", stage=name, seconds=dt)

    def total(self) -> float:
        return sum(dt for _, dt in self.stages)

    def summary(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for name, dt in self.stages:
            out[name] = out.get(name, 0.0) + dt
        return out


@contextlib.contextmanager
def device_trace(log_dir: str | None) -> Iterator[None]:
    """jax.profiler trace scope; no-op when log_dir is None."""
    if log_dir is None:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
