"""Profiling: stage wall-clock (reference parity) + device traces (new).

The reference's entire observability is ``timeit.default_timer`` deltas
written to its log (``DPathSim_APVPA.py:26,37,63,67``). StageTimer keeps
that capability — but since the obs subsystem (obs/) exists it is a
**thin shim over the tracer**: every ``stage()`` opens a hierarchical
span named ``stage:<name>`` (visible in ``--trace-out`` Perfetto dumps,
nested under whatever span is current), records the duration into the
``dpathsim_stage_seconds`` histogram, and still appends to ``.stages``
and emits the ``stage_time`` JSONL event — the engine/driver/test
callers of the old API run unchanged. ``device_trace`` adds what the
reference never had — a real ``jax.profiler`` trace (XLA op timeline,
HBM usage) viewable in TensorBoard/Perfetto; while it is open, tracer
spans also annotate the device timeline (``device_annotations``), so
the host hierarchy and the XLA ops land in one view.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator

import jax

from ..obs.metrics import get_registry
from ..obs.trace import get_tracer


class StageTimer:
    """Accumulates named stage timings; integrates with RunLogger.metric.

    Compat shim (deprecated entry point, kept working): new code should
    open tracer spans directly — this class exists so every pre-obs
    ``timer.stage(...)`` call site keeps its exact behavior while also
    feeding the span tree and the stage-duration histogram."""

    def __init__(self, logger=None):
        self.stages: list[tuple[str, float]] = []
        self._logger = logger

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            with get_tracer().span(f"stage:{name}"):
                yield
        finally:
            dt = time.perf_counter() - t0
            self.stages.append((name, dt))
            get_registry().histogram(
                "dpathsim_stage_seconds", "pipeline stage durations"
            ).observe(dt, stage=name)
            if self._logger is not None:
                self._logger.metric(event="stage_time", stage=name, seconds=dt)

    def total(self) -> float:
        return sum(dt for _, dt in self.stages)

    def summary(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for name, dt in self.stages:
            out[name] = out.get(name, 0.0) + dt
        return out


@contextlib.contextmanager
def device_trace(log_dir: str | None) -> Iterator[None]:
    """jax.profiler trace scope; no-op when log_dir is None. While
    open, obs tracer spans mirror into the device timeline as
    TraceAnnotations so one Perfetto view carries both hierarchies."""
    if log_dir is None:
        yield
        return
    tracer = get_tracer()
    was_annotating = tracer.device_annotations
    jax.profiler.start_trace(log_dir)
    tracer.configure(device_annotations=True)
    try:
        yield
    finally:
        tracer.configure(device_annotations=was_annotating)
        jax.profiler.stop_trace()
