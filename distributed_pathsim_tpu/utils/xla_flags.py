"""XLA_FLAGS helpers shared by the virtual-device provisioning paths.

XLA parses ``XLA_FLAGS`` exactly once, at the first backend
initialization — so forcing a host-platform device count means editing
the env var before that moment and restoring it right after (the
mutation must never leak into later subprocesses doing real single-chip
work; see ``__graft_entry__._try_ensure_devices``).
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"

# One cache location for every harness/script: remote compiles through
# the TPU tunnel cost tens of seconds per program, and the bench child's
# alarm budget assumes warm repeats.
COMPILE_CACHE_DIR = "/root/.jax_cache"


def enable_compile_cache() -> None:
    """Best-effort persistent compilation cache (no-op on jax versions
    without the knobs — the cache is an optimization, never a
    requirement)."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", COMPILE_CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass


def warm_compile_cache(
    backend,
    buckets,
    k: int = 10,
    variant: str = "rowsum",
) -> dict[int, float]:
    """Pre-compile the serving shape buckets at startup.

    One throwaway ``topk_rows`` call per bucket size drives the exact
    jit programs the coalescer will dispatch (gather + batched GEMM per
    static batch length), so the first real request of every bucket
    hits a warm executable instead of paying an XLA compile mid-query —
    through a TPU tunnel that compile is tens of seconds of p99. The
    persistent on-disk cache is enabled first (best effort), so even a
    process restart rewarms from disk rather than recompiling.

    Emits one structured ``compile_warm`` event per bucket with the
    measured warm time; returns {bucket: seconds}. Works against any
    backend exposing ``topk_rows`` (the non-jax ones just get their
    caches populated — harmless and fast).
    """
    import time

    import numpy as np

    from .logging import runtime_event

    enable_compile_cache()
    times: dict[int, float] = {}
    for b in sorted(set(int(x) for x in buckets)):
        rows = np.zeros(b, dtype=np.int64)
        t0 = time.perf_counter()
        backend.topk_rows(rows, k=k, variant=variant)
        times[b] = time.perf_counter() - t0
        runtime_event(
            "compile_warm",
            echo=False,
            backend=getattr(backend, "name", "?"),
            bucket=b,
            k=k,
            seconds=round(times[b], 6),
        )
    return times


_COMPILE_METRICS_INSTALLED = False


def install_compile_metrics() -> bool:
    """Register a PROCESS-LIFETIME jax.monitoring listener that counts
    every XLA backend compile into the obs registry
    (``dpathsim_xla_compiles_total``) — the always-on companion to the
    scoped :class:`CompileCounter` below, reusing the same event hook.
    A steady-state serving process whose counter moves is recompiling,
    which the shape-bucket/delta contracts forbid; the ``metrics``
    protocol op and the Prometheus textfile make that visible live.

    Idempotent (one listener no matter how many services start) and
    best-effort (exotic jax versions without the monitoring module just
    skip it). Returns whether the hook is installed."""
    global _COMPILE_METRICS_INSTALLED
    if _COMPILE_METRICS_INSTALLED:
        return True
    try:
        from jax._src import monitoring

        from ..obs.metrics import get_registry

        def _on_event(name: str, value, **kwargs) -> None:
            if name.endswith(CompileCounter._EVENT_SUFFIX):
                get_registry().counter(
                    "dpathsim_xla_compiles_total",
                    "XLA backend compilations since process start",
                ).inc()

        monitoring.register_event_duration_secs_listener(_on_event)
    except Exception:
        return False
    _COMPILE_METRICS_INSTALLED = True
    return True


class CompileCounter:
    """Counts XLA backend compiles via jax.monitoring — the
    zero-new-compiles assertion hook for the delta-serving contract
    (``bench_serving.py --regime update`` and ``make update-smoke``).

    Counts ``/jax/core/compile/backend_compile_duration`` events: one
    fires per actual XLA compilation, none on executable-cache hits —
    exactly the thing a steady-state delta update must never trigger.
    Context manager; ``count`` is cumulative while registered.
    """

    _EVENT_SUFFIX = "backend_compile_duration"

    def __init__(self):
        self.count = 0
        self._registered = False

    def _on_event(self, name: str, value, **kwargs) -> None:
        if name.endswith(self._EVENT_SUFFIX):
            self.count += 1

    def __enter__(self) -> "CompileCounter":
        from jax._src import monitoring

        monitoring.register_event_duration_secs_listener(self._on_event)
        self._registered = True
        return self

    def __exit__(self, *exc) -> None:
        if not self._registered:
            return
        try:
            from jax._src import monitoring

            monitoring._unregister_event_duration_listener_by_callback(
                self._on_event
            )
        except Exception:
            # Listener left behind on exotic jax versions: counting into
            # a dead object is harmless; never fail the caller.
            pass
        self._registered = False


def device_flags_value(n_devices: int, flags: str | None = None) -> str:
    """The XLA_FLAGS string with the host-device count forced to
    ``n_devices``, preserving any other flags present."""
    if flags is None:
        flags = os.environ.get("XLA_FLAGS", "")
    want = f"{_COUNT_FLAG}={n_devices}"
    if _COUNT_FLAG in flags:
        return re.sub(rf"{_COUNT_FLAG}=\d+", want, flags)
    return (flags + " " + want).strip()
