"""XLA_FLAGS helpers shared by the virtual-device provisioning paths.

XLA parses ``XLA_FLAGS`` exactly once, at the first backend
initialization — so forcing a host-platform device count means editing
the env var before that moment and restoring it right after (the
mutation must never leak into later subprocesses doing real single-chip
work; see ``__graft_entry__._try_ensure_devices``).
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"

# One cache location for every harness/script: remote compiles through
# the TPU tunnel cost tens of seconds per program, and the bench child's
# alarm budget assumes warm repeats.
COMPILE_CACHE_DIR = "/root/.jax_cache"


def enable_compile_cache() -> None:
    """Best-effort persistent compilation cache (no-op on jax versions
    without the knobs — the cache is an optimization, never a
    requirement)."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", COMPILE_CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass


def device_flags_value(n_devices: int, flags: str | None = None) -> str:
    """The XLA_FLAGS string with the host-device count forced to
    ``n_devices``, preserving any other flags present."""
    if flags is None:
        flags = os.environ.get("XLA_FLAGS", "")
    want = f"{_COUNT_FLAG}={n_devices}"
    if _COUNT_FLAG in flags:
        return re.sub(rf"{_COUNT_FLAG}=\d+", want, flags)
    return (flags + " " + want).strip()
