"""Inductive tower encoder: a pure-numpy forward over row-local features.

The inductive contract (DESIGN.md §32): every input the tower reads for
node ``j`` is computable from that node's OWN half-chain row ``C_j``
and denominator ``d_j`` once three train-time constants are pinned —
the Cauchy quadrature grid ``(t, w)``, the degree normalizer
``deg_denom`` and the calibration ``target_scale``. Nothing in the
feature map looks at any other row, so a node appended after training
embeds from its typed adjacency alone, and its embedding is
inner-product-consistent with the corpus embeddings by construction.

The forward is plain numpy (three Dense+relu layers — the exact
architecture of ``models/neural.TwoTower``, parameters exported from
the trained flax pytree). Two reasons it is NOT a jax call:

- serving's steady-state zero-recompile contract holds trivially — a
  cold-start re-embed of Δ rows compiles nothing because there is
  nothing to compile;
- corpus rows and cold-start rows go through the SAME arithmetic, so
  "inductively embedded" and "trained-corpus" embeddings can never
  drift by a compiler's reassociation.

The feature map mirrors ``NeuralPathSim._setup_from_c`` exactly
(unit-L2 C row | scaled log-degree | quadrature gates); the
``feature_format`` stamp in checkpoints exists so a map change here
fails a stale artifact loudly instead of silently skewing candidates.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# One definition of the feature-map identity, stamped into checkpoints
# and verified on load (the _OPT_FORMAT pattern of models/neural.py).
FEATURE_FORMAT = "l2c-deg-gates-r04"


def _gates(d: np.ndarray, t: np.ndarray) -> np.ndarray:
    """Quadrature denominator gates e^(-d·t_k) — same arithmetic as
    ``models.neural.quadrature_gates``, duplicated here in plain numpy
    so loading a checkpoint never imports flax/optax (the serving
    worker may hold towers without ever training)."""
    return np.exp(
        -np.clip(
            np.asarray(d, np.float64)[:, None] * np.asarray(t)[None, :],
            0.0, 700.0,
        )
    ).astype(np.float32)


@dataclasses.dataclass
class InductiveEncoder:
    """Frozen trained towers + the pinned train-time constants.

    ``layers`` is ``[(kernel, bias), ...]`` for the three Dense layers
    (f32). ``v`` is the half-chain contraction width the towers were
    trained on — a graph whose venue vocabulary grew past it cannot be
    embedded without retraining (the feature dimension moved), which
    callers must treat as a counted degradation, not an error.
    """

    layers: list[tuple[np.ndarray, np.ndarray]]
    quad_t: np.ndarray
    quad_w: np.ndarray
    deg_denom: float
    target_scale: float
    variant: str
    metapath: str
    meta: dict

    def __post_init__(self):
        if len(self.layers) != 3:
            raise ValueError(
                f"expected 3 tower layers, got {len(self.layers)}"
            )
        for kern, bias in self.layers:
            kern.flags.writeable = False
            bias.flags.writeable = False

    @property
    def v(self) -> int:
        """Contraction width of the training graph's half factor."""
        return int(self.layers[0][0].shape[0]) - 1 - len(self.quad_t)

    @property
    def dim(self) -> int:
        return int(self.layers[-1][0].shape[1])

    @property
    def hidden(self) -> int:
        return int(self.layers[0][0].shape[1])

    @classmethod
    def from_model(cls, model, meta: dict | None = None) -> "InductiveEncoder":
        """Export a trained :class:`~..models.neural.NeuralPathSim`'s
        towers into the numpy form (flax pytree → plain arrays)."""
        params = model.state.params["params"]
        layers = [
            (
                np.array(params[f"Dense_{i}"]["kernel"], dtype=np.float32),
                np.array(params[f"Dense_{i}"]["bias"], dtype=np.float32),
            )
            for i in range(3)
        ]
        deg = np.log1p(model._d)
        return cls(
            layers=layers,
            quad_t=np.asarray(model._quad_t, dtype=np.float64),
            quad_w=np.asarray(model._quad_w, dtype=np.float64),
            deg_denom=max(float(deg.max(initial=0.0)), 1.0),
            target_scale=float(model.target_scale),
            variant=model.variant,
            metapath=model.metapath.name,
            meta=dict(meta or {}),
        )

    # -- the row-local feature map ----------------------------------------

    def features(self, c_rows: np.ndarray, d_rows: np.ndarray) -> np.ndarray:
        """[B, V] half-chain rows + [B] denominators → [B, F] tower
        inputs. Row-local by construction: the three corpus statistics
        this normalization needs (quadrature grid, degree max) are the
        PINNED train-time constants, not recomputed."""
        c_rows = np.asarray(c_rows, dtype=np.float32)
        if c_rows.ndim != 2 or c_rows.shape[1] != self.v:
            raise ValueError(
                f"half-chain width {c_rows.shape} does not match the "
                f"towers' training width V={self.v} — the contraction "
                "vocabulary changed; retrain"
            )
        d_rows = np.asarray(d_rows, dtype=np.float64)
        norms = np.linalg.norm(c_rows, axis=1, keepdims=True)
        c_norm = c_rows / np.where(norms > 0, norms, 1)
        deg = (np.log1p(d_rows) / self.deg_denom).astype(np.float32)
        return np.concatenate(
            [c_norm, deg[:, None], _gates(d_rows, self.quad_t)], axis=1
        )

    def embed(self, c_rows: np.ndarray, d_rows: np.ndarray) -> np.ndarray:
        """Embed rows through the frozen towers: [B, dim] f32. Pure
        numpy — zero XLA involvement, so a serving-path re-embed can
        never recompile anything."""
        x = self.features(c_rows, d_rows)
        (w0, b0), (w1, b1), (w2, b2) = self.layers
        x = np.maximum(x @ w0 + b0, 0.0)
        x = np.maximum(x @ w1 + b1, 0.0)
        return x @ w2 + b2
