"""Serving-side learned-tower state: candidates → exact f64 rerank.

The service owns one :class:`LearnedState` when ``--topk-mode
learned`` (or ``--learned-checkpoint``) is configured. Safety story,
identical in shape to the ANN arm (serving/ann.py) and provably safe
by construction:

- the towers ONLY generate candidates. Every served answer is
  exact-f64 reranked through the same candidate-restricted primitives
  the exact engine uses (``ops/pathsim.score_candidates`` /
  ``topk_from_candidate_scores``) against the C/d snapshot, so a
  learned answer is bit-identical to the full exact top-k whenever the
  true top-k is inside the candidate set — and the shadow gate
  MEASURES how often that holds;
- **shadow-recall confidence**: every Nth learned dispatch also runs
  the exact oracle; measured score-recall below the floor disables the
  learned arm (every query degrades, counted) until a refresh;
- **cold start**: rows appended after training re-embed through the
  inductive encoder's row-local numpy forward — O(Δ) tower work, no
  full corpus re-embed, zero XLA compiles.

Fallback taxonomy (``dpathsim_learned_fallbacks_total{reason=...}``):
``no_towers``, ``stale``, ``uncovered``, ``degenerate``,
``low_confidence``, ``metapath``. Every degradation falls to
ANN-then-exact in the service's admission cascade.

**The LN001 doorway** (DESIGN.md §32): raw tower similarity scores are
approximations and must NEVER reach a host boundary unreranked — an
operator reading them as PathSim scores would be silently wrong in
score units. ``LEARNED_SURFACE`` names the raw-score internals
(parsed by the analyzer as a literal, the CF001/BT001 pattern); any
attribute access outside ``learned/`` is flagged. Callers hold the
probe result as an opaque handle and get answers only through
:meth:`LearnedState.answer_from_handle`, which reranks inside this
module.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..obs.metrics import get_registry
from ..ops import pathsim
from ..utils.logging import runtime_event

LEARNED_FALLBACK_REASONS = (
    "no_towers", "stale", "uncovered", "degenerate", "low_confidence",
    "metapath",
)

# The sealed raw-score surface (analyzer rule LN001): attributes that
# read or carry UNRERANKED tower similarities. Only modules inside
# learned/ may touch them; everyone else gets exact-reranked answers
# through answer_from_handle. Parsed by the analyzer as a literal so
# the rule and this registry cannot drift.
LEARNED_SURFACE = frozenset({
    "tower_sims",
    "raw_sims",
})


class ProbeHandle:
    """Opaque carrier of one batch's raw tower similarities between
    the dispatcher and completion threads. The payload attribute is
    LN001-sealed: unwrap only inside learned/."""

    __slots__ = ("raw_sims",)

    def __init__(self, sims: np.ndarray):
        self.raw_sims = sims


class LearnedState:
    """One service's learned answering state. Thread discipline
    mirrors :class:`~..serving.ann.AnnState`: eligibility under the
    service's swap lock; probe on the dispatcher thread (host numpy);
    rerank/shadow on the completion thread; absorb/refresh under the
    swap lock with the pipeline drained."""

    def __init__(
        self,
        encoder,
        c64: np.ndarray,
        d: np.ndarray,
        cand_mult: int = 16,
        shadow_every: int = 64,
        recall_floor: float = 0.98,
        min_shadow: int = 8,
        token: tuple[str, int] = ("", 0),
    ):
        self.encoder = encoder
        self.c64 = np.asarray(c64, dtype=np.float64)
        self.c64.flags.writeable = False
        self.d = np.asarray(d, dtype=np.float64)
        self.n = int(self.d.shape[0])
        # corpus embeddings through the SAME numpy forward cold-start
        # rows use — consistent by construction, compiles nothing
        self._emb = encoder.embed(self.c64, self.d)
        self._emb.flags.writeable = False
        self.stale = np.zeros(self.n, dtype=bool)
        self.token = (str(token[0]), int(token[1]))
        self.cand_mult = int(cand_mult)
        self.shadow_every = max(int(shadow_every), 0)
        self.recall_floor = float(recall_floor)
        self.min_shadow = int(min_shadow)
        self.enabled = True
        # independent per-request reranks fan over a small pool (numpy
        # releases the GIL) instead of serializing on the completion
        # thread — same sizing as the ANN rerank pool
        self.pool = ThreadPoolExecutor(
            max_workers=max(2, min(4, os.cpu_count() or 2)),
            thread_name_prefix="pathsim-learned-rerank",
        )
        self._lock = threading.Lock()
        self.shadow_n = 0
        self.recall_sum = 0.0
        self._since_shadow = 0
        # cold-start accounting: appended source rows (they land in
        # headroom slots, embedded as zero rows at build — a delta
        # makes them real and stale) that absorb has not re-embedded
        # yet. seen is cumulative; pending drains to 0 per absorb.
        self.appended_seen = 0
        self._appended_pending = 0
        reg = get_registry()
        self._m_requests = reg.counter(
            "dpathsim_learned_requests_total",
            "topk requests answered through the learned path",
        ).labels()
        self._m_fallbacks = reg.counter(
            "dpathsim_learned_fallbacks_total",
            "learned-requested queries degraded to ann/exact, by reason",
        )
        self._m_recall = reg.gauge(
            "dpathsim_learned_recall_ratio",
            "measured shadow score-recall@k of the learned path vs the "
            "exact oracle (cumulative over the shadow samples)",
        ).labels()
        self._m_recall.set(1.0)
        self._m_cold = reg.gauge(
            "dpathsim_learned_cold_start_ratio",
            "fraction of appended (cold-start) rows the learned path "
            "can already answer (1.0 = every append absorbed)",
        ).labels()
        self._m_cold.set(1.0)
        self._m_probe = reg.histogram(
            "dpathsim_learned_probe_seconds",
            "learned candidate-generation (tower matmul) latency per "
            "batch",
        ).labels()
        self._m_rerank = reg.histogram(
            "dpathsim_learned_rerank_seconds",
            "exact candidate rerank latency per request",
        ).labels()

    # -- eligibility -------------------------------------------------------

    def peek(self, row: int) -> str | None:
        """Eligibility WITHOUT the counter side effect (the worker's
        response annotation and the flight recorder read this; only
        the answering path counts)."""
        with self._lock:
            enabled = self.enabled
        if not enabled:
            return "low_confidence"
        if not 0 <= row < self.n:
            return "uncovered"
        if self.stale[row]:
            return "stale"
        if self.d[row] <= 0:
            return "degenerate"
        return None

    def eligible(self, row: int) -> str | None:
        """None when the learned path may answer ``row``; otherwise
        the fallback reason (also counted)."""
        reason = self.peek(row)
        if reason is not None:
            self.note_fallback(reason)
        return reason

    def note_fallback(self, reason: str) -> None:
        self._m_fallbacks.inc(reason=reason)

    # -- probe + exact rerank ----------------------------------------------

    def tower_sims(self, rows: np.ndarray) -> np.ndarray:
        """Raw tower similarities [B, N] — LN001-sealed: approximate
        score-scale numbers that must never leave learned/ unreranked."""
        return self._emb[rows] @ self._emb.T

    def probe_batch(self, rows: np.ndarray) -> ProbeHandle:
        """Dispatcher-thread half: one host matmul over the tower
        embeddings (O(B·N·dim) f32 — no device, no compile). Returns
        the opaque handle the completion half unwraps."""
        return ProbeHandle(self.tower_sims(np.asarray(rows)))

    def answer_from_handle(self, handle: ProbeHandle, b: int,
                           row: int, k: int):
        """Completion half for one request: select C = cand_mult·k
        candidates from the probed similarities and exact-f64 rerank
        them INSIDE this module — the only way an answer leaves the
        learned tier. Stale candidates are sound: only the QUERY row's
        freshness matters (an unaffected query row's entire exact
        score row is unchanged by the delta — the affected-rows
        superset guarantee), and a stale query never reaches here."""
        sims = handle.raw_sims[b].astype(np.float64, copy=True)
        sims[row] = -np.inf
        n_cand = max(k, min(self.cand_mult * k, self.n - 1))
        cand = np.argpartition(-sims, min(n_cand, self.n - 1))[:n_cand]
        cand = cand[cand != row].astype(np.int64)
        return self.rerank(row, cand, k)

    def rerank(self, row: int, cand: np.ndarray, k: int):
        """Exact f64 top-k over the candidate set: integer counts from
        the C snapshot, shared normalize + tie order with the full
        exact path — bit-identical to the full-row answer whenever the
        true top-k is inside ``cand``."""
        cand = np.asarray(cand, dtype=np.int64)
        counts = self.c64[cand] @ self.c64[row]
        scores = pathsim.score_candidates(
            counts[None, :], np.asarray([self.d[row]]),
            self.d[cand][None, :],
        )
        vals, idxs = pathsim.topk_from_candidate_scores(
            scores, cand[None, :], k
        )
        return vals[0], idxs[0]

    # -- staleness + cold-start absorption ---------------------------------

    @property
    def stale_count(self) -> int:
        return int(self.stale.sum())

    @property
    def pending_appends(self) -> int:
        with self._lock:
            return self._appended_pending

    def mark_stale(self, rows: np.ndarray) -> int:
        """Fence delta-affected rows onto the fallback path until a
        refresh re-embeds them (the PR-7 staleness contract)."""
        rows = np.asarray(rows)
        rows = rows[(rows >= 0) & (rows < self.n)]
        self.stale[rows] = True
        return int(rows.size)

    def note_appends(self, n_rows: int) -> None:
        """Record ``n_rows`` freshly appended source rows (cold-start
        authors): answered by counted fallback until :meth:`absorb`
        re-embeds them through the inductive encoder. Feeds the
        ``cold_start_answerable`` SLO gauge."""
        with self._lock:
            if n_rows > 0:
                self.appended_seen += int(n_rows)
                self._appended_pending += int(n_rows)
            pending = self._appended_pending
            seen = self.appended_seen
        self._m_cold.set(
            (seen - pending) / seen if seen else 1.0
        )

    def absorb(self, c_new: np.ndarray, d_new: np.ndarray,
               token: tuple[str, int]) -> dict:
        """Swap in the patched graph's C/d snapshot and re-embed ONLY
        the stale + appended rows through the inductive encoder — the
        O(Δ) "before any full re-embed" cold-start path. Caller holds
        the service swap lock with the pipeline drained. Raises
        ``ValueError`` when the contraction width changed (new venue
        vocabulary → feature space moved; retrain)."""
        c_new = np.asarray(c_new, dtype=np.float64)
        d_new = np.asarray(d_new, dtype=np.float64)
        n_new = int(d_new.shape[0])
        n_keep = min(self.n, n_new)
        need = np.flatnonzero(self.stale[:n_keep])
        appended = np.arange(n_keep, n_new, dtype=np.int64)
        rows = np.concatenate([need, appended])
        emb = np.empty((n_new, self._emb.shape[1]), dtype=np.float32)
        emb[:n_keep] = self._emb[:n_keep]
        if rows.size:
            # encoder.embed validates the width and raises before any
            # state moved — absorb is all-or-nothing
            emb[rows] = self.encoder.embed(c_new[rows], d_new[rows])
        c_new.flags.writeable = False
        emb.flags.writeable = False
        self.c64 = c_new
        self.d = d_new
        self._emb = emb
        self.n = n_new
        self.stale = np.zeros(n_new, dtype=bool)
        self.token = (str(token[0]), int(token[1]))
        with self._lock:
            absorbed = self._appended_pending
            self._appended_pending = 0
        self.note_appends(0)  # republish the gauge (pending now 0)
        return {
            "re_embedded": int(rows.size),
            "appended": absorbed,
        }

    # -- shadow-recall confidence ------------------------------------------

    def should_shadow(self) -> bool:
        if self.shadow_every <= 0:
            return False
        with self._lock:
            self._since_shadow += 1
            if self._since_shadow >= self.shadow_every:
                self._since_shadow = 0
                return True
        return False

    def record_shadow(self, got_vals, exact_vals, k: int) -> None:
        """Fold one shadow comparison into the confidence gate —
        SCORE recall@k, same metric and tie reasoning as the ANN gate
        (a returned item whose exact score clears the oracle's k-th
        score is a hit; learned answers are exact-reranked, so the
        comparison is bit-meaningful)."""
        ev = np.asarray(exact_vals)
        gv = np.asarray(got_vals)
        want = ev[np.isfinite(ev)]
        if want.size == 0:
            return
        kth = want.min()
        got = gv[np.isfinite(gv)]
        recall = min(float((got >= kth).sum()) / float(want.size), 1.0)
        with self._lock:
            self.shadow_n += 1
            self.recall_sum += recall
            ratio = self.recall_sum / self.shadow_n
            tripped = (
                self.enabled
                and self.shadow_n >= self.min_shadow
                and ratio < self.recall_floor
            )
            if tripped:
                self.enabled = False
            samples = self.shadow_n
        self._m_recall.set(ratio)
        if tripped:
            runtime_event(
                "learned_confidence_lost",
                recall=round(ratio, 4),
                floor=self.recall_floor,
                samples=samples,
            )

    def reset_confidence(self) -> None:
        """After an absorb/retrain the old shadow evidence describes a
        different tower state — start the gate fresh."""
        with self._lock:
            self.shadow_n = 0
            self.recall_sum = 0.0
            self._since_shadow = 0
            self.enabled = True
        self._m_recall.set(1.0)

    def close(self) -> None:
        self.pool.shutdown(wait=False)

    # -- accounting --------------------------------------------------------

    def count_answered(self) -> None:
        self._m_requests.inc()

    def observe_probe(self, seconds: float) -> None:
        self._m_probe.observe(seconds)

    def observe_rerank(self, seconds: float) -> None:
        self._m_rerank.observe(seconds)

    def snapshot(self) -> dict:
        with self._lock:
            shadow_n = self.shadow_n
            ratio = self.recall_sum / shadow_n if shadow_n else None
            pending = self._appended_pending
            seen = self.appended_seen
            enabled = self.enabled
        return {
            "enabled": enabled,
            "dim": self.encoder.dim,
            "hidden": self.encoder.hidden,
            "cand_mult": self.cand_mult,
            "embedded_rows": self.n,
            "stale_rows": self.stale_count,
            "pending_appends": pending,
            "appended_seen": seen,
            "cold_start_ratio": (
                round((seen - pending) / seen, 6) if seen else 1.0
            ),
            "token": list(self.token),
            "shadow_samples": shadow_n,
            "shadow_recall": (
                round(ratio, 6) if ratio is not None else None
            ),
        }
