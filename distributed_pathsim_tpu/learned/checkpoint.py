"""Versioned, fingerprint-keyed tower checkpoints (atomic save/load).

The ``index/mips.py`` persistence contract applied to trained towers:
one ``.npz`` artifact, written atomically (tmp + rename — a crash
mid-save can never corrupt an earlier snapshot), stamped with a schema
version, the feature-map identity, and the training graph's
``(base_fp, delta_seq)`` consistency token. Loading verifies all of
them and raises a NAMED :class:`TowerMismatch` — a stale or foreign
artifact degrades serving to the exact path with a loud event, never a
shape error three layers deep.
"""

from __future__ import annotations

import json
import os
import zipfile

import numpy as np

from .encoder import FEATURE_FORMAT, InductiveEncoder

_SCHEMA_VERSION = 1


class TowerMismatch(ValueError):
    """A tower artifact that cannot serve this graph/build: wrong
    schema version, wrong base fingerprint, or a feature-map identity
    this build does not produce."""


def save_towers(
    path: str, encoder: InductiveEncoder, token: tuple[str, int]
) -> None:
    """Persist an encoder atomically, keyed to its training graph."""
    payload: dict[str, np.ndarray] = {}
    for i, (kern, bias) in enumerate(encoder.layers):
        payload[f"w{i}"] = kern
        payload[f"b{i}"] = bias
    payload["quad_t"] = encoder.quad_t
    payload["quad_w"] = encoder.quad_w
    payload["meta"] = np.frombuffer(
        json.dumps(
            {
                **encoder.meta,
                "schema_version": _SCHEMA_VERSION,
                "feature_format": FEATURE_FORMAT,
                "base_fp": token[0],
                "delta_seq": int(token[1]),
                "variant": encoder.variant,
                "metapath": encoder.metapath,
                "deg_denom": encoder.deg_denom,
                "target_scale": encoder.target_scale,
                "dim": encoder.dim,
                "hidden": encoder.hidden,
            }
        ).encode(),
        dtype=np.uint8,
    )
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **payload)
    os.replace(tmp, path)


def load_towers(
    path: str, expect_base_fp: str | None = None
) -> tuple[InductiveEncoder, tuple[str, int]]:
    """Restore ``(encoder, token)``; every mismatch is a named
    :class:`TowerMismatch` naming what moved and how to fix it.
    A corrupt or truncated artifact (interrupted copy, bad disk) is a
    mismatch too — callers get ONE exception type to catch, never a
    zipfile error three layers deep."""
    try:
        handle = np.load(path)
    except (OSError, ValueError, EOFError, zipfile.BadZipFile) as exc:
        if isinstance(exc, FileNotFoundError):
            raise
        raise TowerMismatch(
            f"{path!r} is not a readable tower artifact ({exc}) — "
            "the file is corrupt or truncated; retrain or re-copy"
        ) from exc
    with handle as z:
        meta = json.loads(z["meta"].tobytes().decode())
        if meta.get("schema_version") != _SCHEMA_VERSION:
            raise TowerMismatch(
                f"{path!r} has tower schema "
                f"{meta.get('schema_version')!r}, this build reads "
                f"{_SCHEMA_VERSION} — retrain with `dpathsim learned "
                "train`"
            )
        if meta.get("feature_format") != FEATURE_FORMAT:
            raise TowerMismatch(
                f"{path!r} was trained on feature map "
                f"{meta.get('feature_format')!r}; this build encodes "
                f"{FEATURE_FORMAT!r} — the tower inputs changed shape "
                "or meaning; retrain"
            )
        base_fp = meta.pop("base_fp", "")
        delta_seq = int(meta.pop("delta_seq", 0))
        if expect_base_fp is not None and base_fp != expect_base_fp:
            raise TowerMismatch(
                f"{path!r} was trained for graph {base_fp!r}, not "
                f"{expect_base_fp!r} — retrain against the served "
                "dataset (and matching --headroom)"
            )
        layers = [
            (
                np.array(z[f"w{i}"], dtype=np.float32),
                np.array(z[f"b{i}"], dtype=np.float32),
            )
            for i in range(3)
        ]
        encoder = InductiveEncoder(
            layers=layers,
            quad_t=np.array(z["quad_t"], dtype=np.float64),
            quad_w=np.array(z["quad_w"], dtype=np.float64),
            deg_denom=float(meta.pop("deg_denom")),
            target_scale=float(meta.pop("target_scale")),
            variant=str(meta.pop("variant")),
            metapath=str(meta.pop("metapath")),
            meta=meta,
        )
    return encoder, (base_fp, delta_seq)
