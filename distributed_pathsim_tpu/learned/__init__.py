"""Learned serving tier: inductive two-tower index, exact-reranked.

The ``--topk-mode learned`` arm of the serving stack (DESIGN.md §32).
Four pieces, promoted from the ``models/neural.py`` trainer into a
first-class candidate-generation subsystem with ANN's safety story:

- :mod:`.trainer` — online distillation from the exact engine: the
  teacher is the exact score itself (hard-candidate mining) plus the
  batch tier's ``--emit-pairs`` JSONL stream;
- :mod:`.encoder` — the inductive half: a pure-numpy tower forward
  over ROW-LOCAL features, so a node the index has never seen embeds
  from its typed adjacency alone (cold-start answering);
- :mod:`.checkpoint` — versioned, fingerprint-keyed tower artifacts
  with atomic save/load (the ``index/mips.py`` contract);
- :mod:`.serving` — the query-path state: towers generate candidates
  ONLY, every answer is exact-f64 reranked inside this package
  (analyzer rule LN001 seals the raw-score surface), a shadow-recall
  gate disables the arm below floor, and every degradation is a
  counted fallback to ANN-then-exact.
"""

from .checkpoint import TowerMismatch, load_towers, save_towers
from .encoder import InductiveEncoder
from .serving import LEARNED_FALLBACK_REASONS, LEARNED_SURFACE, LearnedState
from .trainer import train_towers

__all__ = [
    "InductiveEncoder",
    "LEARNED_FALLBACK_REASONS",
    "LEARNED_SURFACE",
    "LearnedState",
    "TowerMismatch",
    "load_towers",
    "save_towers",
    "train_towers",
]
