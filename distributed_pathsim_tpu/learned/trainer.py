"""Online exact-teacher distillation into serving towers.

The trainer promotes ``models/neural.py``'s two-tower machinery into
the serving tier's model producer. Distillation has two teachers, both
exact:

- **hard-candidate mining** — the exact engine's own top-k lists for a
  pool of sources (``NeuralPathSim.mine_hard_candidates``): the slates
  the serving ordering is actually decided on;
- **the batch tier's ``--emit-pairs`` stream** — campaign-computed
  exact (row, col, score) hits (``batch/pairs.py`` schema). Their rows
  join the hard pool (the campaign already paid for those exact
  top-k lists — free mining), and a seeded BY-SOURCE validation split
  reports distillation quality on sources the pool never drew.

The output is an :class:`~.encoder.InductiveEncoder` (numpy towers +
pinned constants) plus a training-info dict; :func:`train_towers`
writes the fingerprint-keyed checkpoint when asked.
"""

from __future__ import annotations

import time

import numpy as np

from ..utils.logging import runtime_event
from .checkpoint import save_towers
from .encoder import InductiveEncoder


def _pairs_to_pool(
    rows: np.ndarray, cols: np.ndarray, scores: np.ndarray,
    n: int, width: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Group emitted pairs by source into a rectangular hard pool
    [T, width] (per-source candidates, best score first; short rows
    cycle their own candidates — slate sampling draws with replacement
    anyway). Out-of-range rows are dropped: a pairs file from a larger
    graph must not crash training on a subset."""
    keep = (rows < n) & (cols < n)
    rows, cols, scores = rows[keep], cols[keep], scores[keep]
    if not rows.size:
        return np.empty(0, np.int64), np.empty((0, width), np.int64)
    order = np.lexsort((-scores, rows))
    rows, cols = rows[order], cols[order]
    uniq, starts = np.unique(rows, return_index=True)
    bounds = np.append(starts, len(rows))
    pool = np.empty((len(uniq), width), dtype=np.int64)
    for t, (lo, hi) in enumerate(zip(bounds[:-1], bounds[1:])):
        pool[t] = np.resize(cols[lo:hi], width)
    return uniq.astype(np.int64), pool


def train_towers(
    hin,
    metapath,
    *,
    variant: str = "rowsum",
    dim: int = 32,
    hidden: int = 64,
    steps: int = 200,
    batch_size: int = 512,
    lr: float = 1e-3,
    seed: int = 0,
    hard_frac: float | None = None,
    hard_sources: int = 512,
    hard_k: int = 32,
    pairs: str | None = None,
    val_frac: float = 0.1,
    token: tuple[str, int] | None = None,
    out: str | None = None,
    mesh=None,
) -> tuple[InductiveEncoder, dict]:
    """Distill the exact engine into serving towers for ``hin``.

    ``pairs`` is an ``--emit-pairs`` JSONL path (optional); ``token``
    is the serving consistency token the checkpoint is keyed to
    (default: the graph fingerprint at delta_seq 0 — the same identity
    ``dpathsim index build`` stamps). ``out`` writes the checkpoint.
    Returns ``(encoder, info)``.
    """
    from ..models.neural import NeuralPathSim
    from ..serving.cache import graph_fingerprint

    t0 = time.perf_counter()
    model = NeuralPathSim(
        hin, metapath, dim=dim, hidden=hidden, lr=lr, seed=seed,
        variant=variant, mesh=mesh,
    )
    if hard_frac is not None:
        # per-instance override of the slate mix (the tuned
        # learned_neg_ratio knob arrives here as 1 - neg_ratio)
        model.HARD_FRAC = float(hard_frac)
    info: dict = {
        "n": model.n, "v": model.v, "dim": dim, "hidden": hidden,
        "steps": steps, "seed": seed, "variant": model.variant,
        "metapath": model.metapath.name,
    }

    # -- teacher 1: exact-engine hard mining ------------------------------
    pool_src = np.empty(0, np.int64)
    pool_cand = np.empty((0, min(hard_k, max(model.n - 1, 1))), np.int64)
    if model.n >= 2 and hard_sources > 0:
        pool_src, pool_cand = model.mine_hard_candidates(
            min(hard_sources, model.n), k=hard_k, seed=seed
        )

    # -- teacher 2: the batch tier's --emit-pairs stream ------------------
    val = None
    if pairs is not None:
        from ..batch.pairs import load_pairs, split_pairs

        p_rows, p_cols, p_scores = load_pairs(pairs)
        train_mask, val_mask = split_pairs(
            p_rows, val_frac=val_frac, seed=seed
        )
        info["pairs_total"] = int(p_rows.size)
        info["pairs_val"] = int(val_mask.sum())
        if val_mask.any():
            val = (p_rows[val_mask], p_cols[val_mask], p_scores[val_mask])
        extra_src, extra_cand = _pairs_to_pool(
            p_rows[train_mask], p_cols[train_mask], p_scores[train_mask],
            model.n, pool_cand.shape[1],
        )
        # campaign rows REPLACE mined rows on collision (the campaign's
        # lists are full exact top-k; mining may have sampled fewer)
        if extra_src.size:
            keep = ~np.isin(pool_src, extra_src)
            pool_src = np.concatenate([pool_src[keep], extra_src])
            pool_cand = np.concatenate([pool_cand[keep], extra_cand])

    if pool_src.size:
        model.set_hard_pool(pool_src, pool_cand)
    info["hard_pool"] = int(pool_src.size)

    losses = model.train(steps=steps, batch_size=batch_size, seed=seed)
    info["final_loss"] = round(float(losses[-1]), 6) if losses else None

    # -- distillation quality on the held-out sources ---------------------
    if val is not None:
        vr, vc, vs = val
        keep = (vr < model.n) & (vc < model.n)
        vr, vc, vs = vr[keep], vc[keep], vs[keep]
        if vr.size >= 2:
            pred = model.predict_pairs(vr, vc)
            # ranking is what serving turns on: Pearson corr of the
            # tower's raw prediction against the exact score over the
            # held-out pairs (scale-free enough at this granularity)
            vsn = vs - vs.mean()
            pn = pred - pred.mean()
            denom = float(np.linalg.norm(vsn) * np.linalg.norm(pn))
            info["val_score_corr"] = (
                round(float(vsn @ pn) / denom, 4) if denom > 0 else None
            )

    encoder = InductiveEncoder.from_model(
        model, meta={"steps": int(steps), "seed": int(seed)}
    )
    if token is None:
        token = (graph_fingerprint(hin), 0)
    info["token"] = list(token)
    info["train_s"] = round(time.perf_counter() - t0, 3)
    if out is not None:
        save_towers(out, encoder, token)
        info["out"] = out
    runtime_event("learned_train_done", echo=False, **{
        k: v for k, v in info.items() if k != "token"
    })
    return encoder, info
