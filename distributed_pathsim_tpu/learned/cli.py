"""``dpathsim learned`` — train / inspect serving tower checkpoints.

::

    dpathsim learned train --dataset dblp/dblp_small.gexf \
        --metapath APVPA --out towers.npz \
        --pairs pairs.jsonl --steps 400

    dpathsim learned inspect --towers towers.npz

``train`` distills the exact engine into a two-tower checkpoint
(exact-teacher hard mining + an optional ``--emit-pairs`` stream from
a batch campaign), keyed to the graph's base fingerprint —
``dpathsim serve --topk-mode learned --learned-checkpoint towers.npz``
refuses an artifact trained for a different graph. ``inspect`` prints
a checkpoint's geometry and keying without loading a dataset.
"""

from __future__ import annotations

import argparse
import json


def build_learned_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dpathsim learned",
        description="train / inspect learned serving towers",
    )
    sub = p.add_subparsers(dest="action", required=True)

    t = sub.add_parser("train", help="graph -> tower checkpoint")
    t.add_argument("--dataset", required=True,
                   help="GEXF path or synthetic:authors=..,papers=..,"
                   "venues=..,seed=..")
    t.add_argument("--metapath", default="APVPA")
    t.add_argument("--variant", default="rowsum",
                   choices=("rowsum", "diagonal"))
    t.add_argument("--out", required=True, help="checkpoint .npz path")
    t.add_argument("--pairs", default=None,
                   help="--emit-pairs JSONL from a batch campaign "
                   "(extra exact-teacher slates + held-out validation)")
    t.add_argument("--steps", type=int, default=400)
    t.add_argument("--dim", type=int, default=None,
                   help="tower output width (default: tuned learned_dim)")
    t.add_argument("--hidden", type=int, default=64)
    t.add_argument("--neg-ratio", type=float, default=None,
                   help="uniform-negative fraction of training slates "
                   "(default: tuned learned_neg_ratio)")
    t.add_argument("--hard-sources", type=int, default=512)
    t.add_argument("--hard-k", type=int, default=32)
    t.add_argument("--val-frac", type=float, default=0.1)
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--headroom", type=float, default=0.25,
                   help="capacity reserve MATCHING the serving "
                   "process's --headroom: the checkpoint is keyed to "
                   "the padded graph's fingerprint")
    t.add_argument("--tuning-table", default=None)

    q = sub.add_parser("inspect", help="print a checkpoint's identity")
    q.add_argument("--towers", required=True, help="checkpoint .npz path")
    return p


def _train(args) -> int:
    from .. import tuning
    from ..index.cli import _parse_dataset
    from ..ops.metapath import compile_metapath
    from .trainer import train_towers

    if args.tuning_table:
        tuning.install_table(args.tuning_table)
    hin = _parse_dataset(args.dataset)
    if args.headroom:
        from ..data.delta import with_headroom

        hin = with_headroom(hin, args.headroom)
    mp = compile_metapath(args.metapath, hin.schema)
    n = hin.type_size(mp.source_type)
    dim = args.dim or int(tuning.choose("learned_dim", n=n, default=32))
    neg_ratio = (
        args.neg_ratio
        if args.neg_ratio is not None
        else float(tuning.choose("learned_neg_ratio", n=n, default=0.5))
    )
    _, info = train_towers(
        hin, args.metapath, variant=args.variant,
        dim=dim, hidden=args.hidden, steps=args.steps,
        seed=args.seed, hard_frac=1.0 - neg_ratio,
        hard_sources=args.hard_sources, hard_k=args.hard_k,
        pairs=args.pairs, val_frac=args.val_frac, out=args.out,
    )
    print(json.dumps(info, indent=2))
    return 0


def _inspect(args) -> int:
    from .checkpoint import load_towers

    encoder, token = load_towers(args.towers)
    print(json.dumps({
        "towers": args.towers,
        "dim": encoder.dim,
        "hidden": encoder.hidden,
        "v": encoder.v,
        "variant": encoder.variant,
        "metapath": encoder.metapath,
        "base_fp": token[0],
        "delta_seq": token[1],
        "meta": encoder.meta,
    }, indent=2))
    return 0


def learned_main(argv: list[str] | None = None) -> int:
    args = build_learned_parser().parse_args(argv)
    if args.action == "train":
        return _train(args)
    if args.action == "inspect":
        return _inspect(args)
    raise ValueError(f"unknown learned action {args.action!r}")
