"""Test configuration.

All tests run on CPU with 8 virtual XLA devices so the real pjit/shard_map
sharded paths (the multi-chip code) are exercised without TPU hardware —
the standard JAX trick (SURVEY.md §4, item 4). These env vars must be set
before jax initializes its backends, hence module scope, before any import
of the package under test.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

# The axon TPU plugin's sitecustomize force-sets jax_platforms at
# interpreter startup, overriding the env var — undo it before any backend
# initializes so tests always run on the 8 virtual CPU devices.
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pathlib

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
REFERENCE = pathlib.Path("/root/reference")


@pytest.fixture(scope="session")
def dblp_small_path():
    p = REFERENCE / "dblp" / "dblp_small.gexf"
    if not p.exists():
        pytest.skip("dblp_small.gexf not available")
    return str(p)


@pytest.fixture(scope="session")
def dblp_small(dblp_small_path):
    from distributed_pathsim_tpu.data.gexf import read_gexf

    return read_gexf(dblp_small_path)


@pytest.fixture(scope="session")
def dblp_small_hin(dblp_small):
    from distributed_pathsim_tpu.data.encode import encode_hin

    return encode_hin(dblp_small)
