"""The metapath query engine (ops/planner.py, DESIGN.md §28).

Four layers:

- **Planner unit tests**: the DP picks the cheaper association on a
  chain where ordering matters, records estimated FLOPs/density on
  every node, exposes the order string, and falls back (recorded) past
  the DP size cutoff.
- **Property tests**: random metapaths (symmetric and asymmetric,
  length 3–7) × random small HINs — the planner path is bit-identical
  to the naive left-to-right ``chain_product`` oracle on all four
  backends, tie order included.
- **Memoization**: warm sub-chain folds equal cold folds bit-for-bit,
  concurrent metapath workloads share sub-chains, and random delta
  sequences invalidate exactly the entries whose factors changed.
- **Serving**: the per-request ``metapath`` field answers through its
  own coalescer lane, bit-identical to a dedicated service, and two
  engines demonstrably share a memoized sub-chain.
"""

from __future__ import annotations

import numpy as np
import pytest

from distributed_pathsim_tpu.backends.base import create_backend
from distributed_pathsim_tpu.data.synthetic import synthetic_hin
from distributed_pathsim_tpu.ops import chain, planner
from distributed_pathsim_tpu.ops import sparse as sp
from distributed_pathsim_tpu.ops.metapath import compile_metapath
from distributed_pathsim_tpu.ops.planner import (
    EvalPlan,
    SubchainCache,
    factor_stats_from_coo,
    plan_chain,
    plan_metapath,
)

# The type-adjacency walk graph of the synthetic DBLP schema: which
# letters can follow which (via exactly one relation each — compile
# stays unambiguous).
_NEXT = {"A": "P", "P": "AVT", "V": "P", "T": "P"}


def _hin(seed: int, n_authors=40, n_papers=70, n_venues=6, n_topics=5):
    return synthetic_hin(
        n_authors, n_papers, n_venues, n_topics=n_topics,
        topics_per_paper=1.4, seed=seed,
    )


def _random_metapath(rng, length: int) -> str:
    spec = [rng.choice(list("APVT"))]
    while len(spec) < length:
        spec.append(rng.choice(list(_NEXT[spec[-1]])))
    return "".join(spec)


def _naive_oracle(hin, mp):
    """Left-to-right f64 dense fold — the pre-planner reference
    semantics (exact integer counts below 2^53)."""
    blocks = chain.oriented_dense_blocks(hin, mp.steps, dtype=np.float64)
    m = planner.naive_dense(blocks, xp=np)
    return m, m.sum(axis=1)


# ---------------------------------------------------------------------------
# Planner unit tests
# ---------------------------------------------------------------------------


def _stats(m, n, nnz):
    rng = np.random.default_rng(7)
    rows = rng.integers(0, m, size=nnz)
    cols = rng.integers(0, n, size=nnz)
    return factor_stats_from_coo(rows, cols, (m, n))


def test_dp_beats_left_to_right_when_ordering_matters():
    # tall·wide·tall (dims 1000, 10, 1000, 10): left-to-right pays the
    # huge 1000×1000 intermediate; A·(B·C) contracts to 10×10 first.
    stats = [
        _stats(1000, 10, 4000),
        _stats(10, 1000, 4000),
        _stats(1000, 10, 4000),
    ]
    root, naive_flops, dp = plan_chain(
        stats, dense_cutover=0.25, dp_max_len=16
    )
    assert dp
    assert root.total_flops < naive_flops
    # every node carries auditable estimates
    def walk(n):
        assert n.est_flops >= 0 and 0.0 <= n.est_density <= 1.0
        if n.left:
            walk(n.left)
            walk(n.right)
    walk(root)


def test_dp_size_cutoff_recorded():
    stats = [_stats(50, 50, 200)] * 5
    root, _, dp = plan_chain(stats, dense_cutover=0.25, dp_max_len=3)
    assert not dp  # fell back to left-to-right, recorded on the plan
    assert root.hi - root.lo == 5


def test_plan_metapath_modes_and_audit():
    hin = _hin(0)
    sym = plan_metapath(hin, compile_metapath("APVPA", hin.schema))
    assert sym.mode == "half"
    assert sym.order()  # parenthesized expression renders
    d = sym.to_dict()
    assert d["tree"]["est_flops"] >= 0
    asym = plan_metapath(hin, compile_metapath("APV", hin.schema))
    assert asym.mode == "general"
    assert isinstance(asym, EvalPlan)
    # plan is memoized per (hin, metapath)
    again = plan_metapath(hin, compile_metapath("APVPA", hin.schema))
    assert again is sym


def test_fold_half_matches_legacy_shim_and_is_order_invariant():
    hin = _hin(1)
    mp = compile_metapath("APVPA", hin.schema)
    a = planner.fold_half(hin, mp).summed()
    b = sp.half_chain_coo(hin, mp).summed()  # deprecated shim → planner
    assert np.array_equal(a.rows, b.rows)
    assert np.array_equal(a.cols, b.cols)
    assert np.array_equal(a.weights, b.weights)


# ---------------------------------------------------------------------------
# Property tests: planner ≡ naive left-to-right, all four backends
# ---------------------------------------------------------------------------


def test_random_metapaths_bit_identical_numpy_and_jax():
    rng = np.random.default_rng(42)
    seen = set()
    for trial in range(10):
        length = int(rng.integers(3, 8))
        spec = _random_metapath(rng, length)
        if spec in seen:
            continue
        seen.add(spec)
        hin = _hin(100 + trial)
        mp = compile_metapath(spec, hin.schema)
        m_ref, rs_ref = _naive_oracle(hin, mp)
        for name in ("numpy", "jax"):
            b = create_backend(name, hin, mp)
            got_m = np.asarray(b.commuting_matrix(), dtype=np.float64)
            got_rs = np.asarray(b.global_walks(), dtype=np.float64)
            n_src = hin.type_size(mp.source_type)
            n_dst = hin.type_size(mp.target_type)
            assert np.array_equal(got_m, m_ref[:n_src, :n_dst]), (
                f"{name} M diverged on {spec} (symmetric="
                f"{mp.is_symmetric}, plan={b.plan.order()})"
            )
            assert np.array_equal(got_rs, rs_ref[:n_src]), (
                f"{name} rowsums diverged on {spec}"
            )


def test_random_symmetric_metapaths_all_four_backends_topk_ties():
    rng = np.random.default_rng(7)
    specs = ["APA", "APVPA", "APTPA", "PVP", "PAP", "PTP"]
    rng.shuffle(specs)
    for trial, spec in enumerate(specs[:4]):
        hin = _hin(200 + trial, n_authors=30, n_papers=50)
        mp = compile_metapath(spec, hin.schema)
        assert mp.is_symmetric
        oracle = create_backend("numpy", hin, mp)
        rows = np.arange(min(12, oracle.n_sources), dtype=np.int64)
        want_v, want_i = oracle.topk_rows(rows, k=5)
        for name in ("jax", "jax-sparse", "jax-sharded"):
            kwargs = {"n_devices": 2} if name == "jax-sharded" else {}
            b = create_backend(name, hin, mp, **kwargs)
            got_v, got_i = b.topk_rows(rows, k=5)
            # tie order (desc score, asc col) must survive the planner
            assert np.array_equal(got_i, want_i), f"{name}/{spec} ties"
            assert np.array_equal(got_v, want_v), f"{name}/{spec} values"


def test_asymmetric_pairwise_rows_match_oracle():
    rng = np.random.default_rng(3)
    for trial in range(4):
        spec = _random_metapath(rng, int(rng.integers(3, 6)))
        hin = _hin(300 + trial)
        mp = compile_metapath(spec, hin.schema)
        m_ref, _ = _naive_oracle(hin, mp)
        b = create_backend("numpy", hin, mp)
        rows = np.asarray([0, 1, 2], dtype=np.int64)
        got = b.pairwise_rows(rows)
        assert np.array_equal(
            got, m_ref[rows][:, : hin.type_size(mp.target_type)]
        ), spec


# ---------------------------------------------------------------------------
# Memoization
# ---------------------------------------------------------------------------


def _coo_equal(a, b) -> bool:
    a, b = a.summed(), b.summed()
    return (
        np.array_equal(a.rows, b.rows)
        and np.array_equal(a.cols, b.cols)
        and np.array_equal(a.weights, b.weights)
    )


def test_memo_warm_equals_cold_and_shares_subchains():
    hin = _hin(5)
    memo = SubchainCache(64 << 20)
    apvpa = compile_metapath("APVPA", hin.schema)
    aptpa = compile_metapath("APTPA", hin.schema)
    cold_apvpa = planner.fold_half(hin, apvpa)
    warm_apvpa = planner.fold_half(hin, apvpa, memo=memo)
    assert _coo_equal(cold_apvpa, warm_apvpa)
    h0 = memo.hits
    # APTPA's half shares the oriented A·P factor with APVPA's
    cold_aptpa = planner.fold_half(hin, aptpa)
    warm_aptpa = planner.fold_half(hin, aptpa, memo=memo)
    assert _coo_equal(cold_aptpa, warm_aptpa)
    assert memo.hits > h0, "shared A·P sub-chain should hit"
    # full re-fold of APVPA is now a pure hit path
    h1 = memo.hits
    again = planner.fold_half(hin, apvpa, memo=memo)
    assert _coo_equal(again, cold_apvpa)
    assert memo.hits > h1


def test_memo_correct_across_random_delta_sequences():
    from distributed_pathsim_tpu.data.delta import (
        DeltaBatch,
        apply_delta,
        edge_delta,
    )

    rng = np.random.default_rng(11)
    hin = _hin(6)
    memo = SubchainCache(64 << 20)
    mp = compile_metapath("APVPA", hin.schema)
    planner.fold_half(hin, mp, memo=memo)  # seed the memo
    for step in range(4):
        blk = hin.blocks["author_of"]
        existing = set(zip(blk.rows.tolist(), blk.cols.tolist()))
        # one random add + one random remove on author_of
        adds = []
        for a in rng.permutation(hin.type_size("author")):
            p = int(rng.integers(0, hin.type_size("paper")))
            if (int(a), p) not in existing:
                adds.append((int(a), p))
                break
        j = int(rng.integers(0, blk.rows.shape[0]))
        removes = [(int(blk.rows[j]), int(blk.cols[j]))]
        delta = DeltaBatch(
            edges=(edge_delta("author_of", add=adds, remove=removes),)
        )
        hin, grew = apply_delta(hin, delta)
        assert not grew
        warm = planner.fold_half(hin, mp, memo=memo)
        cold = planner.fold_half(hin, mp)
        assert _coo_equal(warm, cold), f"delta step {step}"


def test_memo_invalidation_drops_only_changed_factors():
    hin = _hin(8)
    memo = SubchainCache(64 << 20)
    planner.fold_half(hin, compile_metapath("APVPA", hin.schema), memo=memo)
    planner.fold_half(hin, compile_metapath("APTPA", hin.schema), memo=memo)
    before = memo.stats()["entries"]
    dropped = memo.invalidate_relationships({"submit_at"})
    # submit_at appears only in APVPA's sub-chains; the A·P leaf and
    # APTPA's has_topic sub-chains survive
    assert 0 < dropped < before
    assert memo.stats()["entries"] == before - dropped
    assert memo.invalidate_relationships({"no_such_rel"}) == 0


def test_memo_budget_evicts_lru_and_skips_oversized():
    def coo(nnz):
        return sp.COOMatrix(
            rows=np.zeros(nnz, dtype=np.int64),
            cols=np.zeros(nnz, dtype=np.int64),
            weights=np.ones(nnz), shape=(4, 4),
        )

    memo = SubchainCache(10_000)
    for i in range(8):  # 8 × ~2.4 kB under a 10 kB budget: must evict
        memo.put((("r", False, f"fp{i}"),), coo(100))
    st = memo.stats()
    assert st["evictions"] > 0
    assert st["bytes"] <= 10_000
    # an entry bigger than half the budget is skipped outright (it
    # would evict every interior fold just to store one huge leaf)
    memo.put((("r", False, "big"),), coo(1000))
    assert memo.get((("r", False, "big"),)) is None


# ---------------------------------------------------------------------------
# Serving: per-request metapath field, lanes, shared memo
# ---------------------------------------------------------------------------


@pytest.fixture()
def mp_service():
    from distributed_pathsim_tpu.serving import PathSimService, ServeConfig

    hin = _hin(21, n_authors=32, n_papers=60)
    mp = compile_metapath("APVPA", hin.schema)
    svc = PathSimService(
        create_backend("numpy", hin, mp),
        config=ServeConfig(max_wait_ms=1.0, warm=False),
    )
    yield hin, svc
    svc.close()


def test_serving_per_request_metapath_bit_identical(mp_service):
    hin, svc = mp_service
    for spec in ("APA", "APTPA"):
        mp2 = compile_metapath(spec, hin.schema)
        dedicated = create_backend("numpy", hin, mp2)
        for row in (0, 3, 7):
            vals, idxs = svc.topk_index(row, k=5, metapath=spec)
            want_v, want_i = dedicated.topk_row(row, k=5)
            assert np.array_equal(idxs, want_i), (spec, row)
            assert np.array_equal(vals, want_v), (spec, row)
    # engines share the sub-chain memo: the A·P factor crossed lanes
    st = svc.stats()
    assert set(st["plan"]["engines"]) == {"APA", "APTPA"}
    assert st["plan"]["memo"]["hits"] > 0
    assert st["plan"]["primary"]["metapath"] == "APVPA"


def test_serving_default_metapath_unchanged(mp_service):
    _, svc = mp_service
    v1, i1 = svc.topk_index(2, k=5)
    v2, i2 = svc.topk_index(2, k=5, metapath="APVPA")  # explicit default
    assert np.array_equal(i1, i2) and np.array_equal(v1, v2)


def test_serving_metapath_validation(mp_service):
    _, svc = mp_service
    with pytest.raises((KeyError, ValueError)):
        svc.topk_index(0, k=5, metapath="APV")  # not closed
    with pytest.raises((KeyError, ValueError)):
        svc.topk_index(0, k=5, metapath="AXA")  # unknown letter


def test_serving_scores_and_protocol_metapath(mp_service):
    from distributed_pathsim_tpu.serving.protocol import handle_request

    hin, svc = mp_service
    mp2 = compile_metapath("APA", hin.schema)
    dedicated = create_backend("numpy", hin, mp2)
    want = dedicated.scores_rows(np.asarray([4]))[0]
    got = svc.scores_index(4, metapath="APA")
    assert np.array_equal(got, want)
    resp = handle_request(
        svc, {"id": 1, "op": "topk", "row": 4, "k": 3, "metapath": "APA"}
    )
    assert resp["ok"], resp
    want_v, want_i = dedicated.topk_row(4, k=3)
    got_scores = [h["score"] for h in resp["result"]["topk"]]
    assert got_scores == [float(v) for v in want_v if np.isfinite(v)]
    resp = handle_request(
        svc, {"id": 2, "op": "scores", "row": 4, "metapath": "APA"}
    )
    assert resp["ok"] and resp["result"]["row"] == 4


def test_serving_update_invalidates_metapath_engines(mp_service):
    hin, svc = mp_service
    svc.topk_index(1, k=5, metapath="APA")  # build the engine pre-delta
    blk = svc.hin.blocks["author_of"]
    removes = [{
        "rel": "author_of",
        "src_row": int(blk.rows[0]), "dst_row": int(blk.cols[0]),
    }]
    from distributed_pathsim_tpu.data.delta import delta_from_records

    delta = delta_from_records(svc.hin, remove_edges=removes)
    result = svc.update(delta)
    assert result["engines_dropped"] >= 1
    # post-delta: the APA engine rebuilds lazily and answers from the
    # new graph, bit-identical to a fresh dedicated backend
    mp2 = compile_metapath("APA", svc.hin.schema)
    dedicated = create_backend("numpy", svc.hin, mp2)
    want_v, want_i = dedicated.topk_row(1, k=5)
    got_v, got_i = svc.topk_index(1, k=5, metapath="APA")
    assert np.array_equal(got_i, want_i)
    assert np.array_equal(got_v, want_v)


def test_serving_metapath_lane_coalesces_concurrently(mp_service):
    """Concurrent mixed-metapath submits: each lane forms its own
    batches (no cross-metapath mixing) and every future resolves to
    the right answer."""
    hin, svc = mp_service
    oracles = {
        spec: create_backend(
            "numpy", hin, compile_metapath(spec, hin.schema)
        )
        for spec in ("APVPA", "APA", "APTPA")
    }
    futs = []
    for i in range(24):
        spec = ("APVPA", "APA", "APTPA")[i % 3]
        row = i % 8
        futs.append((spec, row, svc.submit_topk(row, 4, metapath=spec)))
    for spec, row, fut in futs:
        vals, idxs = fut.result(timeout=30)
        want_v, want_i = oracles[spec].topk_row(row, k=4)
        assert np.array_equal(idxs, want_i), (spec, row)
        assert np.array_equal(vals, want_v), (spec, row)


# ---------------------------------------------------------------------------
# Bench smoke (tier-1 wiring of `make metapath-smoke`)
# ---------------------------------------------------------------------------


def test_bench_metapath_smoke(tmp_path):
    import bench_serving

    out = str(tmp_path / "metapath_smoke.json")
    result = bench_serving.run_metapath_smoke(out_path=out)
    assert result["checks"]["planner_beats_naive_measured"]
    assert result["checks"]["planner_beats_naive_estimated"]
    assert result["checks"]["memo_subchain_shared_across_lanes"]
    assert result["checks"]["mixed_lanes_bit_identical"]
    assert result["checks"]["zero_steady_state_recompiles"]
