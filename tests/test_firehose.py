"""Firehose ingestion: background compaction, coalesced updates, autoscale.

The ISSUE-15 surface (DESIGN.md §30), tested at three layers:

- **compaction unit contracts**: the hot-swap preserves the
  consistency token, the chained fingerprint, and both cache tiers;
  deltas landing mid-build replay onto the new backend; answers stay
  bit-identical to an oracle throughout.
- **coalescing property**: K sequentially valid deltas folded by
  :func:`~distributed_pathsim_tpu.data.delta.coalesce_deltas` into ONE
  batch produce the identical graph — bit-exact scores across all
  four backends, add/remove cancellation included.
- **chaos**: a worker SIGKILLed mid-compaction loses zero requests,
  the survivor swaps cleanly, and a freshly spawned replacement
  catches up by epoch replay to answers bit-identical to an oracle
  that absorbed the same deltas.

``test_bench_firehose_smoke`` wires ``make firehose-smoke`` into
tier-1 (short sustained stream + one forced steady-state compaction +
the coalescing burst + one autoscale step).
"""

from __future__ import annotations

import pathlib
import sys
import time

import numpy as np
import pytest

from distributed_pathsim_tpu.backends.base import create_backend
from distributed_pathsim_tpu.data import delta as dl
from distributed_pathsim_tpu.data.synthetic import synthetic_hin
from distributed_pathsim_tpu.ops.metapath import compile_metapath
from distributed_pathsim_tpu.serving import PathSimService, ServeConfig

REPO = str(pathlib.Path(__file__).resolve().parents[1])


def _mk_hin(n_authors=128, n_papers=224, n_venues=8, seed=0,
            headroom=0.25):
    return dl.with_headroom(
        synthetic_hin(n_authors, n_papers, n_venues, seed=seed,
                      materialize_ids=True),
        headroom,
    )


def _service(hin, mp, **cfg):
    cfg.setdefault("max_wait_ms", 0.2)
    cfg.setdefault("warm", False)
    return PathSimService(
        create_backend("numpy", hin, mp), config=ServeConfig(**cfg)
    )


def _fresh_edges(hin_or_set, rng, n, n_authors, n_papers):
    if isinstance(hin_or_set, set):
        existing = hin_or_set
    else:
        ap = hin_or_set.blocks["author_of"]
        existing = set(zip(ap.rows.tolist(), ap.cols.tolist()))
    adds = []
    while len(adds) < n:
        e = (int(rng.integers(0, n_authors)),
             int(rng.integers(0, n_papers)))
        if e not in existing:
            existing.add(e)
            adds.append(e)
    return adds


# -- compaction unit contracts ---------------------------------------------


def test_compact_preserves_token_fingerprint_and_caches():
    hin = _mk_hin()
    mp = compile_metapath("APVPA", hin.schema)
    svc = _service(hin, mp)
    try:
        rng = np.random.default_rng(0)
        adds = _fresh_edges(svc.hin, rng, 3, 128, 224)
        info = svc.update(dl.DeltaBatch(
            edges=(dl.edge_delta("author_of", add=adds),)
        ))
        assert info["mode"] == "delta"
        tok = svc.consistency_token
        fp = svc._fp
        v1, i1 = svc.topk_index(5, 5)
        hits0 = svc.stats()["result_cache"]["hits"]
        res = svc.compact()
        assert res["swapped"], res
        # token, fingerprint, caches: all preserved — compaction is
        # the one "update" that invalidates nothing
        assert svc.consistency_token == tok
        assert svc._fp == fp
        v2, i2 = svc.topk_index(5, 5)
        assert np.array_equal(v1, v2) and np.array_equal(i1, i2)
        assert svc.stats()["result_cache"]["hits"] == hits0 + 1
        # fresh pow-2 capacity actually reserved
        cap = res["capacity"]["author"]
        assert cap >= svc.n and (cap & (cap - 1)) == 0
    finally:
        svc.close()


def test_compact_replays_mid_build_deltas():
    """Deltas that land while the build is in flight replay onto the
    new backend at swap — the post-swap graph is the live graph, and
    answers stay bit-identical to an oracle that absorbed everything
    sequentially."""
    hin = _mk_hin()
    mp = compile_metapath("APVPA", hin.schema)
    svc = _service(hin, mp)
    oracle = _service(_mk_hin(), mp)
    try:
        rng = np.random.default_rng(1)
        # stall the factory so the build window is wide open
        real_factory = svc._backend_factory

        def slow_factory(h):
            time.sleep(0.15)
            return real_factory(h)

        svc._backend_factory = slow_factory
        svc._compactor.chain_len = 3
        svc._compactor.cooldown_s = 0.0
        deltas = []
        for i in range(6):
            adds = _fresh_edges(svc.hin, rng, 2, 128, 224)
            deltas.append(dl.DeltaBatch(
                edges=(dl.edge_delta("author_of", add=adds),)
            ))
            svc.update(deltas[-1])
            if i == 2:
                # the chain trigger just fired: yield until the build
                # thread has its snapshot, so the REMAINING updates
                # demonstrably land inside the build window
                deadline = time.monotonic() + 5
                while (
                    not svc._compactor.inflight
                    and time.monotonic() < deadline
                ):
                    time.sleep(0.005)
                time.sleep(0.03)
        svc._compactor._done.wait(30.0)
        comp = svc.stats()["compaction"]
        assert comp["compactions"] >= 1, comp
        assert (comp["last"].get("replayed_deltas", 0) > 0
                or comp["compactions"] > 1), comp
        for d in deltas:
            oracle.update(d)
        assert svc.consistency_token == oracle.consistency_token
        for row in (0, 7, 42, 99):
            a = svc.topk_index(row, 5)
            b = oracle.topk_index(row, 5)
            assert np.array_equal(a[0], b[0])
            assert np.array_equal(a[1], b[1])
    finally:
        svc.close()
        oracle.close()


def test_headroom_trigger_compacts_before_exhaustion():
    """A sustained append stream with auto compaction on never hits
    the synchronous headroom-exhausted inline rebuild: the background
    re-encode refreshes the reserve first."""
    hin = _mk_hin(headroom=0.10)
    mp = compile_metapath("APVPA", hin.schema)
    svc = _service(
        hin, mp, compact_auto=True, compact_chain_len=10_000,
        compact_headroom_frac=0.5, compact_cooldown_s=0.0,
        compact_headroom=1.0,
    )
    try:
        rng = np.random.default_rng(2)
        for i in range(40):
            n_auth = svc.hin.type_size("author")
            adds = [(n_auth, int(rng.integers(0, 224)))]
            svc.update(dl.DeltaBatch(
                edges=(dl.edge_delta("author_of", add=adds),),
                nodes=(dl.NodeAppend(node_type="author",
                                     ids=(f"fh_a{i}",)),),
            ))
            # bounded wait whenever a build is in flight: the stream
            # outpacing the builder is load, not a correctness issue
            svc._compactor._done.wait(30.0)
        st = svc.stats()
        assert st["delta"]["rebuilds"] == 0, st["delta"]
        assert st["compaction"]["compactions"] >= 1
        assert st["delta"]["seq"] == 40
    finally:
        svc.close()


# -- coalescing property (K coalesced == K sequential, all backends) -------


@pytest.mark.parametrize(
    "backend", ["numpy", "jax", "jax-sparse", "jax-sharded"]
)
def test_coalesced_deltas_bit_identical_all_backends(backend):
    hin0 = synthetic_hin(96, 160, 6, seed=3, materialize_ids=True)
    hin0 = dl.with_headroom(hin0, 0.25)
    mp = compile_metapath("APVPA", hin0.schema)
    rng = np.random.default_rng(3)
    existing = set(zip(hin0.blocks["author_of"].rows.tolist(),
                       hin0.blocks["author_of"].cols.tolist()))
    batches = []
    # batch 1: plain adds (one lands on an appended author)
    adds1 = _fresh_edges(existing, rng, 2, 96, 160)
    batches.append(dl.DeltaBatch(
        edges=(dl.edge_delta("author_of",
                             add=adds1 + [(96, 3)]),),
        nodes=(dl.NodeAppend(node_type="author", ids=("co_a0",)),),
    ))
    # batch 2: removes one of batch 1's adds (must cancel), adds more
    adds2 = _fresh_edges(existing, rng, 2, 96, 160)
    batches.append(dl.DeltaBatch(
        edges=(dl.edge_delta("author_of", add=adds2,
                             remove=[adds1[0]]),),
    ))
    # batch 3: re-adds the cancelled edge (net: present again) and
    # removes a base edge
    base_edge = next(iter(sorted(existing)))
    batches.append(dl.DeltaBatch(
        edges=(dl.edge_delta("author_of", add=[adds1[0]],
                             remove=[base_edge]),),
    ))
    # batch 4: adds touching the appended author again
    batches.append(dl.DeltaBatch(
        edges=(dl.edge_delta("author_of", add=[(96, 7)]),),
    ))

    hin_seq = hin0
    for b in batches:
        hin_seq, grew = dl.apply_delta(hin_seq, b)
        assert not grew
    merged = dl.coalesce_deltas(batches)
    assert merged.n_edge_changes < sum(b.n_edge_changes for b in batches)
    hin_co, grew = dl.apply_delta(hin0, merged)
    assert not grew

    b_seq = create_backend(backend, hin_seq, mp)
    b_co = create_backend(backend, hin_co, mp)
    rows = np.arange(0, hin_seq.type_size("author"), 7)
    vs, is_ = b_seq.topk_rows(rows, k=5)
    vc, ic = b_co.topk_rows(rows, k=5)
    assert np.array_equal(np.asarray(vs), np.asarray(vc))
    assert np.array_equal(np.asarray(is_), np.asarray(ic))
    ss = np.asarray(b_seq.scores_rows(rows[:4]))
    sc = np.asarray(b_co.scores_rows(rows[:4]))
    assert np.array_equal(ss, sc)


def test_coalesce_rejects_window_conflicts():
    e = (1, 2)
    add = dl.DeltaBatch(edges=(dl.edge_delta("author_of", add=[e]),))
    with pytest.raises(dl.NotCoalescable):
        dl.coalesce_deltas([add, add])
    rem = dl.DeltaBatch(edges=(dl.edge_delta("author_of", remove=[e]),))
    with pytest.raises(dl.NotCoalescable):
        dl.coalesce_deltas([rem, rem])
    # add → remove → add collapses to a single net add
    merged = dl.coalesce_deltas([add, rem, add])
    assert merged.edges[0].add.shape[0] == 1
    assert merged.edges[0].remove.shape[0] == 0


# -- chaos: kill a worker mid-compaction -----------------------------------


@pytest.mark.chaos
def test_kill_worker_mid_compaction():
    """SIGKILL one of two replicas while BOTH are compacting, under
    query load: zero lost requests, the survivor swaps cleanly (token
    unchanged, answers exact), and a freshly spawned replacement
    catches up by epoch replay to bit-identical answers vs an oracle
    absorbing the same deltas."""
    from distributed_pathsim_tpu.router import (
        InprocTransport, Router, RouterConfig, WorkerRuntime,
    )

    mp = compile_metapath(
        "APVPA", synthetic_hin(96, 160, 6, seed=4).schema
    )

    def make_transport(wid: str):
        svc = _service(_mk_hin(96, 160, 6, seed=4), mp)
        # widen the compaction window so the kill lands inside it
        real_factory = svc._backend_factory

        def slow_factory(h):
            time.sleep(0.25)
            return real_factory(h)

        svc._backend_factory = slow_factory
        return InprocTransport(wid, WorkerRuntime(svc, worker_id=wid))

    transports = {w: make_transport(w) for w in ("w0", "w1")}
    router = Router(transports, RouterConfig(
        heartbeat_interval_s=0.05, heartbeat_miss_limit=100,
        hedge_ms=None, max_inflight=8192, scrape_interval_s=0,
        retain_replay=True,
    ))
    router.start()
    oracle = _service(_mk_hin(96, 160, 6, seed=4), mp)
    try:
        rng = np.random.default_rng(4)
        deltas = []
        for _ in range(4):
            adds = _fresh_edges(oracle.hin, rng, 2, 96, 160)
            deltas.append([
                {"rel": "author_of", "src_row": int(r), "dst_row": int(c)}
                for r, c in adds
            ])
            resp = router.request(
                {"op": "update", "add_edges": deltas[-1]}, timeout=30,
            )
            assert resp["ok"], resp
            oracle.update(dl.delta_from_records(
                oracle.hin, add_edges=deltas[-1]
            ))
        tok_before = oracle.consistency_token
        # both replicas start compacting (the op blocks each worker's
        # loop mid-build); queries + the kill land inside the window
        for wid in ("w0", "w1"):
            router.workers[wid].transport.send(
                {"op": "compact", "id": f"force-{wid}"}
            )
        futs = [
            router.submit({"op": "topk", "row": int(r), "k": 5})
            for r in rng.integers(0, 96, size=24)
        ]
        time.sleep(0.05)  # inside w0's slowed build
        router.workers["w0"].transport.kill()
        lost = 0
        for f in futs:
            resp = f.result(timeout=60)
            if not resp.get("ok"):
                lost += 1
        assert lost == 0
        # survivor swapped cleanly: compaction ran, token unchanged
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            svc1 = transports["w1"].runtime.service
            if svc1._compactor.compactions >= 1 and (
                not svc1._compactor.inflight
            ):
                break
            time.sleep(0.02)
        assert svc1._compactor.compactions >= 1
        assert svc1.consistency_token == tok_before
        # keep the stream going on the survivor
        adds = _fresh_edges(oracle.hin, rng, 2, 96, 160)
        recs = [{"rel": "author_of", "src_row": int(r),
                 "dst_row": int(c)} for r, c in adds]
        resp = router.request({"op": "update", "add_edges": recs},
                              timeout=30)
        assert resp["ok"], resp
        oracle.update(dl.delta_from_records(oracle.hin, add_edges=recs))
        # a spawned replacement catches up by epoch replay ...
        transports["w2"] = make_transport("w2")
        router.add_worker("w2", transports["w2"])
        head = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with router._lock:
                w2 = router.workers["w2"]
                head = len(router._epochs) - 1
                if w2.epoch == head:
                    break
            time.sleep(0.02)
        with router._lock:
            assert router.workers["w2"].epoch == head
        # ... to answers bit-identical to the oracle
        for row in (0, 9, 33, 80):
            resp = router.request({"op": "topk", "row": row, "k": 5},
                                  timeout=30)
            assert resp["ok"], resp
            vals, idxs = oracle.topk_index(row, 5)
            want = [
                (oracle._ident(int(j))[0], float(v))
                for v, j in zip(vals, idxs) if np.isfinite(v)
            ]
            got = [(h["id"], h["score"]) for h in resp["result"]["topk"]]
            assert got == want
    finally:
        router.close()
        oracle.close()
        for t in transports.values():
            t.runtime.service.close()


# -- CI smoke: the acceptance measurement (make firehose-smoke) ------------


def test_bench_firehose_smoke(tmp_path):
    """``make firehose-smoke`` in-process: short sustained firehose +
    one forced steady-state compaction + the coalescing burst + one
    autoscale step — zero lost, zero non-compaction compiles, zero
    steady-state compaction compiles, bounded update-visible p99,
    spawn/drain reactions in the decision log (ISSUE 15 acceptance)."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench_serving

    result = bench_serving.run_firehose_smoke(
        str(tmp_path / "firehose.json")
    )
    assert all(result["smoke_checks"].values()), result["smoke_checks"]
    s = result["sustained"]
    assert s["compiles_outside_compaction"] == 0
    assert s["compaction"]["count"] >= 1
    assert result["fleet"]["broadcasts"] < result["fleet"]["updates"]
    assert result["autoscale"]["spawn_tick"] is not None
    assert result["autoscale"]["drain_tick"] is not None
