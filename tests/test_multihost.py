"""Multi-host plumbing, exercised single-process on 8 virtual devices:
mesh construction fallbacks, row-ownership math, and host-local assembly
feeding the real sharded chain."""

import jax
import numpy as np
import pytest

from distributed_pathsim_tpu.backends.base import create_backend
from distributed_pathsim_tpu.ops.metapath import compile_metapath
from distributed_pathsim_tpu.parallel.multihost import (
    distributed_first_block,
    host_row_range,
    initialize_multihost,
    make_hybrid_mesh,
)
from distributed_pathsim_tpu.parallel.sharded import (
    replicate,
    sharded_chain_outputs,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def test_initialize_noop_single_process(monkeypatch):
    for v in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS", "SLURM_JOB_ID"):
        monkeypatch.delenv(v, raising=False)
    assert initialize_multihost() is False  # no cluster env: must not raise


def test_hybrid_mesh_single_host_fallback():
    mesh = make_hybrid_mesh(tp=2)
    assert mesh.shape == {"dp": 4, "tp": 2}
    mesh1 = make_hybrid_mesh(tp=1)
    assert mesh1.shape == {"dp": 8, "tp": 1}
    with pytest.raises(ValueError, match="must divide"):
        make_hybrid_mesh(tp=3)


def test_host_row_range_covers_padding():
    mesh = make_hybrid_mesh(tp=2)  # dp=4
    start, stop = host_row_range(10, mesh)  # pads to 12
    assert (start, stop) == (0, 12)  # single process owns everything


def test_distributed_block_feeds_sharded_chain(dblp_small_hin):
    """Host-locally assembled first block must reproduce the oracle
    through the full sharded chain on a hybrid (dp, tp) mesh."""
    mp = compile_metapath("APVPA", dblp_small_hin.schema)
    oracle = create_backend("numpy", dblp_small_hin, mp)
    ap = dblp_small_hin.block("author_of").to_dense(np.float32)
    pv = dblp_small_hin.block("submit_at").to_dense(np.float32)

    mesh = make_hybrid_mesh(tp=2)
    first = distributed_first_block(
        lambda a, b: ap[a:b], ap.shape[0], ap.shape[1], mesh
    )
    assert first.shape[0] % mesh.shape["dp"] == 0
    m, rowsums = sharded_chain_outputs(
        first, (replicate(pv, mesh),), mesh=mesh
    )
    n = ap.shape[0]
    np.testing.assert_allclose(
        np.asarray(m, dtype=np.float64)[:n, :n],
        oracle.commuting_matrix(),
        atol=0,
    )
    np.testing.assert_allclose(
        np.asarray(rowsums, dtype=np.float64)[:n], oracle.global_walks(), atol=0
    )


def test_hybrid_mesh_runs_2d_tiling(dblp_small_hin):
    from distributed_pathsim_tpu.parallel.tiling import place_2d, tiled_scores_2d

    mp = compile_metapath("APVPA", dblp_small_hin.schema)
    oracle = create_backend("numpy", dblp_small_hin, mp)
    ap = dblp_small_hin.block("author_of").to_dense(np.float32)
    pv = dblp_small_hin.block("submit_at").to_dense(np.float32)
    c = (ap @ pv).astype(np.float32)
    d = (c @ c.sum(axis=0)).astype(np.float32)
    mesh = make_hybrid_mesh(tp=2)
    args = place_2d(c, d, mesh)
    s = np.asarray(tiled_scores_2d(*args, mesh=mesh), dtype=np.float64)
    n = c.shape[0]
    np.testing.assert_allclose(s[:n, :n], oracle.all_pairs_scores(), atol=1e-7)


def test_initialize_explicit_after_backend_init_raises(monkeypatch):
    """With backends already up (conftest), an explicit rendezvous request
    must fail with OUR actionable error, not jax's late-init RuntimeError
    deep inside distributed.initialize."""
    with pytest.raises(RuntimeError, match="before any JAX backend"):
        initialize_multihost(coordinator_address="127.0.0.1:9999")


def test_cli_flags_require_coordinator(dblp_small_path, capsys):
    from distributed_pathsim_tpu.cli import main

    rc = main([
        "--dataset", dblp_small_path, "--backend", "jax-sharded",
        "--num-processes", "2", "--all-pairs", "--quiet",
    ])
    assert rc == 1
    assert "--coordinator-address" in capsys.readouterr().err


def test_cli_multihost_single_process_rendezvous(dblp_small_path, tmp_path):
    """The product path end-to-end: CLI flags → jax.distributed
    rendezvous (a real single-process cluster on a loopback port) →
    jax-sharded backend with host-local C assembly → golden output."""
    import os
    import pathlib
    import socket
    import subprocess
    import sys
    import textwrap

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    out = tmp_path / "mh.log"
    repo = str(pathlib.Path(__file__).resolve().parents[1])
    code = textwrap.dedent(
        f"""
        import jax
        jax.config.update("jax_platforms", "cpu")
        from distributed_pathsim_tpu.cli import main
        rc = main([
            "--dataset", {dblp_small_path!r},
            "--backend", "jax-sharded",
            "--coordinator-address", "127.0.0.1:{port}",
            "--num-processes", "1", "--process-id", "0",
            "--source", "Didier Dubois",
            "--output", {str(out)!r}, "--quiet",
        ])
        assert rc == 0, rc
        assert jax.process_count() == 1
        print("MH_OK")
        """
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=300, cwd=repo,
        env=dict(os.environ,
                 XLA_FLAGS="--xla_force_host_platform_device_count=8"),
    )
    assert "MH_OK" in proc.stdout, proc.stderr
    assert "Source author global walk: 3" in out.read_text()


def test_cli_two_process_cluster_golden(dblp_small_path, tmp_path):
    """A REAL two-process cluster on loopback: both processes run the
    same CLI command (as on a pod), form a Gloo-backed 8-device global
    mesh, assemble C host-locally, and process 0 produces the golden
    log — including the cross-process fetch path (process_allgather).
    Non-zero processes are muted: the same command runs on every host,
    so a shared --output path must be written exactly once."""
    import os
    import pathlib
    import socket
    import subprocess
    import sys
    import textwrap

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    repo = str(pathlib.Path(__file__).resolve().parents[1])
    env = dict(os.environ, XLA_FLAGS="--xla_force_host_platform_device_count=4")

    def child(pid):
        out = tmp_path / f"mh2_{pid}.log"
        code = textwrap.dedent(
            f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            from distributed_pathsim_tpu.cli import main
            rc = main([
                "--dataset", {dblp_small_path!r},
                "--backend", "jax-sharded",
                "--coordinator-address", "127.0.0.1:{port}",
                "--num-processes", "2", "--process-id", "{pid}",
                "--source", "Didier Dubois",
                "--output", {str(out)!r}, "--quiet",
            ])
            assert rc == 0, rc
            assert jax.process_count() == 2
            assert len(jax.devices()) == 8
            print("MH2_OK")
            """
        )
        return subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=repo, env=env,
        )

    procs = [child(0), child(1)]
    outs = [p.communicate(timeout=300) for p in procs]
    for pid, (stdout, stderr) in enumerate(outs):
        assert "MH2_OK" in stdout, f"proc{pid}: {stderr[-2000:]}"
    log = (tmp_path / "mh2_0.log").read_text().splitlines()
    assert log[0] == "Source author global walk: 3"
    assert len(log) == 3847
    # process 1 ran the same command but must not have written its copy
    assert not (tmp_path / "mh2_1.log").exists()
