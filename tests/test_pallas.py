"""Pallas fused kernels vs oracle — interpret mode on CPU (SURVEY.md §4:
same kernels run compiled on real TPU; bench exercises that path)."""

import numpy as np
import pytest

from distributed_pathsim_tpu.backends.base import create_backend
from distributed_pathsim_tpu.ops import pallas_kernels as pk
from distributed_pathsim_tpu.ops.metapath import compile_metapath


@pytest.fixture(scope="module")
def cd(dblp_small_hin):
    import jax.numpy as jnp

    mp = compile_metapath("APVPA", dblp_small_hin.schema)
    ap = dblp_small_hin.block("author_of").to_dense(np.float32)
    pv = dblp_small_hin.block("submit_at").to_dense(np.float32)
    c = np.asarray(ap @ pv, dtype=np.float32)
    rowsums = np.asarray(c @ c.sum(axis=0), dtype=np.float32)
    oracle = create_backend("numpy", dblp_small_hin, mp)
    return jnp.asarray(c), jnp.asarray(rowsums), oracle


def test_fused_scores_interpret(cd):
    c, d, oracle = cd
    got = np.asarray(pk.fused_scores(c, d, interpret=True), dtype=np.float64)
    want = oracle.all_pairs_scores()
    np.testing.assert_allclose(got, want, atol=1e-7)


def test_fused_scores_xla_reference(cd):
    c, d, oracle = cd
    got = np.asarray(pk.fused_scores_reference(c, d), dtype=np.float64)
    np.testing.assert_allclose(got, oracle.all_pairs_scores(), atol=1e-7)


def test_fused_topk_interpret(cd):
    c, d, oracle = cd
    vals, idxs = pk.fused_topk(c, d, k=5, interpret=True)
    scores = oracle.all_pairs_scores()
    np.fill_diagonal(scores, -np.inf)
    for i in (0, 3, 100, 769):
        expect = np.sort(scores[i])[::-1][:5]
        np.testing.assert_allclose(np.asarray(vals[i], dtype=np.float64), expect,
                                   atol=1e-7)
        # indices must point at rows achieving those scores
        np.testing.assert_allclose(
            scores[i][np.asarray(idxs[i])], expect, atol=1e-7
        )


def test_fused_topk_no_self_mask(cd):
    c, d, oracle = cd
    vals, idxs = pk.fused_topk(c, d, k=1, mask_self=False, interpret=True)
    # with self-pairs allowed, Didier Dubois's best match is himself (1/3)
    assert idxs[0, 0] == 0
    assert vals[0, 0] == pytest.approx(1 / 3, abs=1e-7)


def test_padding_rows_are_invisible():
    """Shapes far from tile multiples + zero-degree rows."""
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    n, v = 130, 7
    c = (rng.random((n, v)) < 0.3).astype(np.float32)
    c[5] = 0  # isolated author: rowsum 0 → all scores 0
    d = (c @ c.sum(axis=0)).astype(np.float32)
    got = np.asarray(pk.fused_scores(jnp.asarray(c), jnp.asarray(d), interpret=True))
    m = c.astype(np.float64) @ c.astype(np.float64).T
    dd = m.sum(axis=1)
    denom = dd[:, None] + dd[None, :]
    want = np.where(denom > 0, 2 * m / np.where(denom > 0, denom, 1), 0.0)
    np.testing.assert_allclose(got, want, atol=1e-6)
    assert (got[5] == 0).all()


def test_backend_fused_path_matches_base(dblp_small_hin):
    mp = compile_metapath("APVPA", dblp_small_hin.schema)
    jx = create_backend("jax", dblp_small_hin, mp)  # use_pallas auto→False on CPU
    oracle = create_backend("numpy", dblp_small_hin, mp)
    np.testing.assert_allclose(
        jx.all_pairs_scores().astype(np.float64),
        oracle.all_pairs_scores(),
        atol=1e-7,
    )
    vals, idxs = jx.topk(k=3)
    scores = oracle.all_pairs_scores()
    np.fill_diagonal(scores, -np.inf)
    np.testing.assert_allclose(
        vals[0].astype(np.float64), np.sort(scores[0])[::-1][:3], atol=1e-7
    )


def test_fused_topk_zero_degree_targets_score_zero():
    """Zero-degree targets must appear with score 0 (like the oracle),
    not be masked out as padding."""
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    n, v = 12, 4
    c = (rng.random((n, v)) < 0.4).astype(np.float32)
    c[5] = 0  # isolated node
    d = (c @ c.sum(axis=0)).astype(np.float32)
    k = n - 1
    vals, idxs = pk.fused_topk(jnp.asarray(c), jnp.asarray(d), k=k, interpret=True)
    # every row's candidate set must include node 5 with score 0
    for i in range(n):
        if i == 5:
            continue
        row = dict(zip(np.asarray(idxs[i]).tolist(), np.asarray(vals[i]).tolist()))
        assert row.get(5) == 0.0


@pytest.fixture(scope="module")
def wide_cd(dblp_small_hin):
    """APA: C = A_AP, V = #papers = 1001 — two K-blocks at bk=512."""
    import jax.numpy as jnp

    mp = compile_metapath("APA", dblp_small_hin.schema)
    c = dblp_small_hin.block("author_of").to_dense(np.float32)
    rowsums = np.asarray(c @ c.sum(axis=0), dtype=np.float32)
    oracle = create_backend("numpy", dblp_small_hin, mp)
    return jnp.asarray(c), jnp.asarray(rowsums), oracle


def test_ktiled_scores_interpret(wide_cd):
    c, d, oracle = wide_cd
    got = np.asarray(pk.fused_scores_ktiled(c, d, interpret=True),
                     dtype=np.float64)
    np.testing.assert_allclose(got, oracle.all_pairs_scores(), atol=1e-7)


def test_ktiled_topk_interpret(wide_cd):
    c, d, oracle = wide_cd
    vals, idxs = pk.fused_topk_ktiled(c, d, k=5, interpret=True)
    scores = oracle.all_pairs_scores()
    np.fill_diagonal(scores, -np.inf)
    for i in (0, 3, 100, 769):
        expect = np.sort(scores[i])[::-1][:5]
        np.testing.assert_allclose(
            np.asarray(vals[i], dtype=np.float64), expect, atol=1e-7
        )
        np.testing.assert_allclose(
            scores[i][np.asarray(idxs[i])], expect, atol=1e-7
        )


def test_ktiled_matches_single_pass_on_narrow(cd):
    """On a V that fits one tile, K-tiled (n_kb=1) must equal the
    single-pass kernel bit for bit."""
    c, d, _ = cd
    a = np.asarray(pk.fused_scores(c, d, interpret=True))
    b = np.asarray(pk.fused_scores_ktiled(c, d, interpret=True))
    np.testing.assert_array_equal(a, b)


def test_ktiled_topk_matches_single_pass_on_narrow(cd):
    c, d, _ = cd
    v1, i1 = pk.fused_topk(c, d, k=5, interpret=True)
    v2, i2 = pk.fused_topk_ktiled(c, d, k=5, interpret=True)
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_twopass_topk_interpret(cd):
    c, d, oracle = cd
    vals, idxs = pk.fused_topk_twopass(c, d, k=5, interpret=True)
    scores = oracle.all_pairs_scores()
    np.fill_diagonal(scores, -np.inf)
    for i in (0, 3, 100, 769):
        expect = np.sort(scores[i])[::-1][:5]
        np.testing.assert_allclose(
            np.asarray(vals[i], dtype=np.float64), expect, atol=1e-7
        )
        np.testing.assert_allclose(
            scores[i][np.asarray(idxs[i])], expect, atol=1e-7
        )


def test_twopass_topk_wide_contraction(wide_cd):
    """APA: V = 1001 forces the K-tiled accumulator path inside the
    two-pass kernel."""
    c, d, oracle = wide_cd
    vals, idxs = pk.fused_topk_twopass(c, d, k=5, interpret=True)
    scores = oracle.all_pairs_scores()
    np.fill_diagonal(scores, -np.inf)
    for i in (0, 100, 769):
        expect = np.sort(scores[i])[::-1][:5]
        np.testing.assert_allclose(
            np.asarray(vals[i], dtype=np.float64), expect, atol=1e-7
        )


def test_twopass_matches_single_pass(cd):
    """Values must agree exactly with the fold kernel; indices must
    agree wherever values are distinct (both tie-break to the lowest
    column on equal values)."""
    c, d, _ = cd
    v1, i1 = pk.fused_topk(c, d, k=5, interpret=True)
    v2, i2 = pk.fused_topk_twopass(c, d, k=5, interpret=True)
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_twopass_no_self_mask(cd):
    c, d, _ = cd
    vals, idxs = pk.fused_topk_twopass(c, d, k=1, mask_self=False,
                                       interpret=True)
    assert idxs[0, 0] == 0
    assert vals[0, 0] == pytest.approx(1 / 3, abs=1e-7)


def test_twopass_multi_stripe_layout():
    """n > _BN_WIDE means several column-tile stripes (n_j >= 2) write
    distinct ROW blocks of the candidate buffer — the layout that makes
    the lane dim lower on real TPUs at every shape (a [bm, 16] column
    slice only lowers when n_j == 1). Pins the stripe-major reshape/
    transpose back to per-row candidate lists."""
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    n, v = 2304, 64  # n_pad = 3072 -> n_j = 3 stripes
    c = jnp.asarray(rng.integers(0, 3, (n, v)).astype(np.float32))
    d = jnp.maximum(c.sum(axis=1), 1.0)
    vals, idxs = pk.fused_topk_twopass(c, d, k=7, interpret=True)
    ref = np.asarray(pk.fused_scores_reference(c, d), dtype=np.float64)
    np.fill_diagonal(ref, -np.inf)
    for i in (0, 1023, 1024, 2303):  # rows straddling stripe boundaries
        expect = np.sort(ref[i])[::-1][:7]
        np.testing.assert_allclose(
            np.asarray(vals[i], dtype=np.float64), expect, atol=1e-6
        )
        np.testing.assert_allclose(
            ref[i][np.asarray(idxs[i])], expect, atol=1e-6
        )


def test_rect_twopass_matches_reference():
    """The rectangular (row-tile × full-column-range) kernel: values and
    indices vs a dense f64 recomputation, self-pairs excluded, at a
    shape with several packed stripes and padded tail columns."""
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    n, v, tile, k = 9000, 64, 512, 7  # n_pad -> 3 stripes of 4096
    c = rng.integers(0, 3, (n, v)).astype(np.float32)
    d = np.maximum(c.sum(axis=1), 1.0)
    c64 = c.astype(np.float64)
    m = c64 @ c64.T
    den = d[:, None] + d[None, :]
    ref = np.where(den > 0, 2 * m / np.where(den > 0, den, 1), 0.0)
    np.fill_diagonal(ref, -np.inf)

    i0 = 4096  # a row tile straddling nothing special; rows 4096..4607
    vals, idxs = pk.fused_topk_twopass_rect(
        jnp.asarray(c[i0 : i0 + tile]), jnp.asarray(c),
        jnp.asarray(d[i0 : i0 + tile], dtype=jnp.float32),
        jnp.asarray(d, dtype=jnp.float32),
        i0 + jnp.arange(tile, dtype=jnp.int32),
        k=k, interpret=True,
    )
    for r in (0, 1, 255, 511):
        expect = np.sort(ref[i0 + r])[::-1][:k]
        np.testing.assert_allclose(
            np.asarray(vals[r], dtype=np.float64), expect, atol=1e-6
        )
        np.testing.assert_allclose(
            ref[i0 + r][np.asarray(idxs[r])], expect, atol=1e-6
        )
        assert i0 + r not in np.asarray(idxs[r])  # self excluded


def test_rect_twopass_self_tile_keeps_k():
    """k+1 extraction rounds: when a row's entire non-self top-k lives
    in the SAME packed tile as its self column, dropping the self
    candidate must still leave k exact winners."""
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    n, v, k = 512, 8, 5
    # One dominant venue: every row's best matches live at low column
    # ids — the same 512-wide tile that holds the self column.
    c = np.zeros((n, v), dtype=np.float32)
    c[:, 0] = rng.integers(1, 4, n)
    d = np.maximum(c.sum(axis=1), 1.0)
    c64 = c.astype(np.float64)
    m = c64 @ c64.T
    den = d[:, None] + d[None, :]
    ref = np.where(den > 0, 2 * m / np.where(den > 0, den, 1), 0.0)
    np.fill_diagonal(ref, -np.inf)
    vals, idxs = pk.fused_topk_twopass_rect(
        jnp.asarray(c), jnp.asarray(c),
        jnp.asarray(d, dtype=jnp.float32), jnp.asarray(d, dtype=jnp.float32),
        jnp.arange(n, dtype=jnp.int32), k=k, interpret=True,
    )
    for r in (0, 100, 511):
        expect = np.sort(ref[r])[::-1][:k]
        np.testing.assert_allclose(
            np.asarray(vals[r], dtype=np.float64), expect, atol=1e-6
        )
        assert r not in np.asarray(idxs[r])


def test_rect_supported_gates():
    assert pk.rect_supported(64, 10)
    assert pk.rect_supported(384, 10)      # canonical bench width
    assert pk.rect_supported(512, 15)
    assert pk.rect_supported(513, 10)      # wide V: K-tiled rect kernel
    assert pk.rect_supported(4096, 10)     # realistic DBLP venue counts
    assert not pk.rect_supported(64, 16)   # no self-exclusion headroom
    assert not pk.rect_supported(2048, 16)


def test_rect_twopass_wide_contraction():
    """V=384 (the canonical bench width) exercises the multi-128-lane
    v_pad path."""
    import jax.numpy as jnp

    rng = np.random.default_rng(31)
    n, v, tile, k = 2500, 384, 256, 6
    c = rng.integers(0, 2, (n, v)).astype(np.float32)
    d = np.maximum(c.sum(axis=1), 1.0)
    c64 = c.astype(np.float64)
    m = c64 @ c64.T
    den = d[:, None] + d[None, :]
    ref = np.where(den > 0, 2 * m / np.where(den > 0, den, 1), 0.0)
    np.fill_diagonal(ref, -np.inf)
    i0 = 512
    vals, idxs = pk.fused_topk_twopass_rect(
        jnp.asarray(c[i0 : i0 + tile]), jnp.asarray(c),
        jnp.asarray(d[i0 : i0 + tile], dtype=jnp.float32),
        jnp.asarray(d, dtype=jnp.float32),
        i0 + jnp.arange(tile, dtype=jnp.int32), k=k, interpret=True,
    )
    for r in (0, 128, 255):
        expect = np.sort(ref[i0 + r])[::-1][:k]
        np.testing.assert_allclose(
            np.asarray(vals[r], dtype=np.float64), expect, atol=1e-6
        )


def test_rect_twopass_ktiled_wide_v_matches_reference():
    """V=2048 (realistic venue cardinality at dblp_large scale) takes
    the K-tiled rect kernel: contraction tiled at 512, [bm, stripe]
    VMEM accumulator, stripe-level top-(k+1) extraction. Values AND
    indices vs a dense f64 recomputation, self-pairs excluded."""
    import jax.numpy as jnp

    rng = np.random.default_rng(41)
    n, v, tile, k = 3000, 2048, 256, 6
    c = (rng.random((n, v)) < 0.02).astype(np.float32)
    d = np.maximum(c.sum(axis=1), 1.0)
    c64 = c.astype(np.float64)
    m = c64 @ c64.T
    den = d[:, None] + d[None, :]
    ref = np.where(den > 0, 2 * m / np.where(den > 0, den, 1), 0.0)
    np.fill_diagonal(ref, -np.inf)
    i0 = 1024
    vals, idxs = pk.fused_topk_twopass_rect(
        jnp.asarray(c[i0 : i0 + tile]), jnp.asarray(c),
        jnp.asarray(d[i0 : i0 + tile], dtype=jnp.float32),
        jnp.asarray(d, dtype=jnp.float32),
        i0 + jnp.arange(tile, dtype=jnp.int32), k=k, interpret=True,
    )
    for r in (0, 1, 128, 255):
        expect = np.sort(ref[i0 + r])[::-1][:k]
        np.testing.assert_allclose(
            np.asarray(vals[r], dtype=np.float64), expect, atol=1e-6
        )
        np.testing.assert_allclose(
            ref[i0 + r][np.asarray(idxs[r])], expect, atol=1e-6
        )
        assert i0 + r not in np.asarray(idxs[r])


def test_rect_twopass_ktiled_non_bk_multiple_v():
    """V=700 pads to 1024 (_BK-aligned): the zero-padded contraction
    tail must not perturb counts, and the padded tail COLUMNS (rows of
    c_cols beyond n) must never win a candidate slot."""
    import jax.numpy as jnp

    rng = np.random.default_rng(43)
    n, v, tile, k = 2100, 700, 256, 5  # n_pad -> 4096: 1996 pad cols
    c = (rng.random((n, v)) < 0.05).astype(np.float32)
    d = np.maximum(c.sum(axis=1), 1.0)
    c64 = c.astype(np.float64)
    m = c64 @ c64.T
    den = d[:, None] + d[None, :]
    ref = np.where(den > 0, 2 * m / np.where(den > 0, den, 1), 0.0)
    np.fill_diagonal(ref, -np.inf)
    vals, idxs = pk.fused_topk_twopass_rect(
        jnp.asarray(c[:tile]), jnp.asarray(c),
        jnp.asarray(d[:tile], dtype=jnp.float32),
        jnp.asarray(d, dtype=jnp.float32),
        jnp.arange(tile, dtype=jnp.int32), k=k, interpret=True,
    )
    assert int(np.asarray(idxs).max()) < n
    for r in (0, 17, 255):
        expect = np.sort(ref[r])[::-1][:k]
        np.testing.assert_allclose(
            np.asarray(vals[r], dtype=np.float64), expect, atol=1e-6
        )


def test_rect_prepadded_wide_v_matches_unpadded():
    """rect_pad_factor and the kernel wrapper must agree on the wide-V
    padded width (_rect_vpad), so the pad-once fast path returns the
    same winners as raw arrays in the K-tiled regime too."""
    import jax.numpy as jnp

    rng = np.random.default_rng(47)
    n, v, tile, k = 2500, 600, 256, 5
    c = (rng.random((n, v)) < 0.03).astype(np.float32)
    d = np.maximum(c.sum(axis=1), 1.0).astype(np.float32)
    cc, dc = pk.rect_pad_factor(jnp.asarray(c), jnp.asarray(d))
    i0 = 512
    ids = i0 + jnp.arange(tile, dtype=jnp.int32)
    v1, i1 = pk.fused_topk_twopass_rect(
        cc[i0 : i0 + tile], cc, dc[i0 : i0 + tile], dc, ids,
        k=k, n_true_cols=n, interpret=True,
    )
    v2, i2 = pk.fused_topk_twopass_rect(
        jnp.asarray(c[i0 : i0 + tile]), jnp.asarray(c),
        jnp.asarray(d[i0 : i0 + tile]), jnp.asarray(d), ids,
        k=k, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_default_scores_tiles_honor_vmem():
    """The sweep-winning tile defaults (KERNELS_r05) must shrink at
    factor widths where their C blocks would blow the VMEM budget —
    fits_vmem() approves V~4000 against the floor config, so the
    default pick must fall back to it rather than compile a 25 MB
    block set."""
    assert pk._default_scores_tiles(8192, 384) == (256, 512)
    assert pk._default_scores_tiles(32768, 384) == (512, 1024)
    # wide V: the 32k winner would hold (512+1024)*v_pad*4 > 12 MB
    assert pk._default_scores_tiles(32768, 2048) == (256, 512)
    assert pk._default_scores_tiles(8192, 4096) == (256, 256)
    for n, v in ((8192, 384), (32768, 384), (32768, 2048), (8192, 4096)):
        bm, bn = pk._default_scores_tiles(n, v)
        v_pad = pk._ceil_to(max(v, 128), 128)
        assert (bm + bn) * v_pad * 4 + bm * bn * 4 <= pk._VMEM_BUDGET_BYTES


def test_rect_fits_budget():
    # Candidate buffer = n_pad·(t_pad/16) bytes: 4.3 GB at 1M×8192
    # (measured to fit a 16 GB v5e), over budget at 2M×8192 — but a
    # smaller row tile brings the same N back under.
    assert pk.rect_fits(1_048_576, 8192)
    assert not pk.rect_fits(2_097_152, 8192)
    assert pk.rect_fits(2_097_152, 4096)


def test_rect_prepadded_factor_matches_unpadded():
    """The pad-once fast path (kernel-shaped inputs skip the internal
    pad) must return the same winners as handing raw arrays."""
    import jax.numpy as jnp

    rng = np.random.default_rng(23)
    n, v, tile, k = 3000, 48, 256, 5
    c = rng.integers(0, 3, (n, v)).astype(np.float32)
    d = np.maximum(c.sum(axis=1), 1.0).astype(np.float32)
    cc, dc = pk.rect_pad_factor(jnp.asarray(c), jnp.asarray(d))
    i0 = 1024
    ids = i0 + jnp.arange(tile, dtype=jnp.int32)
    v1, i1 = pk.fused_topk_twopass_rect(
        cc[i0 : i0 + tile], cc, dc[i0 : i0 + tile], dc, ids,
        k=k, n_true_cols=n, interpret=True,
    )
    v2, i2 = pk.fused_topk_twopass_rect(
        jnp.asarray(c[i0 : i0 + tile]), jnp.asarray(c),
        jnp.asarray(d[i0 : i0 + tile]), jnp.asarray(d), ids,
        k=k, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_twopass_rejects_large_k(cd):
    c, d, _ = cd
    with pytest.raises(ValueError):
        pk.fused_topk_twopass(c, d, k=17, interpret=True)


def test_twopass_zero_degree_targets_score_zero():
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    n, v = 12, 4
    c = (rng.random((n, v)) < 0.4).astype(np.float32)
    c[5] = 0
    d = (c @ c.sum(axis=0)).astype(np.float32)
    k = n - 1
    vals, idxs = pk.fused_topk_twopass(
        jnp.asarray(c), jnp.asarray(d), k=k, interpret=True
    )
    for i in range(n):
        if i == 5:
            continue
        row = dict(zip(np.asarray(idxs[i]).tolist(),
                       np.asarray(vals[i]).tolist()))
        assert row.get(5) == 0.0


def test_twopass_odd_shapes_and_k_boundary():
    """Non-tile-multiple N, k at the _CAND boundary, and k > n-1."""
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    n, v = 301, 17
    c = (rng.random((n, v)) < 0.2).astype(np.float32)
    d = (c @ c.sum(axis=0)).astype(np.float32)
    m = c.astype(np.float64) @ c.astype(np.float64).T
    dd = m.sum(axis=1)
    denom = dd[:, None] + dd[None, :]
    scores = np.where(denom > 0, 2 * m / np.where(denom > 0, denom, 1), 0.0)
    np.fill_diagonal(scores, -np.inf)
    for k in (1, 10, 16):
        vals, idxs = pk.fused_topk_twopass(
            jnp.asarray(c), jnp.asarray(d), k=k, interpret=True
        )
        assert vals.shape == (n, k)
        for i in (0, 150, 300):
            expect = np.sort(scores[i])[::-1][:k]
            np.testing.assert_allclose(
                np.asarray(vals[i], dtype=np.float64), expect, atol=1e-7
            )


def test_twopass_fits_budget():
    # The physical candidate buffer is ~n_pad^2 bytes (16-lane minor dim
    # padded to the 128-lane HBM tile), so the 8 GB budget tops out near
    # 92k authors — NOT the ~256k a naive 16-lane accounting suggests.
    assert pk.twopass_fits(32768)
    assert pk.twopass_fits(92160)
    assert not pk.twopass_fits(131072)
    assert not pk.twopass_fits(1_048_576)


def test_dense_topk_routes_rect_beyond_twopass_budget(monkeypatch):
    """Past the square two-pass candidate-buffer budget the dense tier
    must stream through the rect kernel, not fall back to the 8×-slower
    single-pass fold (the r03 ~92k-author cliff). Simulated by failing
    twopass_fits at a small N so interpret mode stays cheap."""
    import jax.numpy as jnp

    from distributed_pathsim_tpu.backends import jax_dense
    from distributed_pathsim_tpu.data.synthetic import synthetic_hin

    hin = synthetic_hin(700, 1000, 24, seed=9)
    mp = compile_metapath("APVPA", hin.schema)

    monkeypatch.setattr(pk, "twopass_fits", lambda n: False)
    calls = {"rect": 0, "fold": 0}
    real_rect = pk.fused_topk_twopass_rect
    monkeypatch.setattr(
        pk, "fused_topk_twopass_rect",
        lambda *a, **k_: (calls.__setitem__("rect", calls["rect"] + 1),
                          real_rect(*a, **k_))[1],
    )
    monkeypatch.setattr(
        pk, "fused_topk",
        lambda *a, **k_: (_ for _ in ()).throw(
            AssertionError("fold kernel used — rect routing failed")
        ),
    )

    jx = create_backend("jax", hin, mp, use_pallas=True)
    # small tile to exercise the multi-tile loop + final partial tile
    monkeypatch.setattr(jax_dense.JaxDenseBackend, "_RECT_TILE_ROWS", 256)
    vals, idxs = jx.topk(k=5)
    assert calls["rect"] >= 2  # streamed in row tiles

    oracle = create_backend("numpy", hin, mp)
    scores = oracle.all_pairs_scores()
    np.fill_diagonal(scores, -np.inf)
    for i in (0, 255, 256, 699):
        np.testing.assert_allclose(
            vals[i].astype(np.float64), np.sort(scores[i])[::-1][:5],
            atol=1e-6,
        )
        np.testing.assert_allclose(
            scores[i][np.asarray(idxs[i])],
            np.sort(scores[i])[::-1][:5], atol=1e-6,
        )


def test_dense_topk_rect_gate_respects_mask_and_dtype(monkeypatch):
    """mask_self=False or non-f32 dtypes must NOT take the rect path
    (the kernel always self-excludes and is f32-only)."""
    from distributed_pathsim_tpu.data.synthetic import synthetic_hin

    hin = synthetic_hin(300, 500, 16, seed=3)
    mp = compile_metapath("APVPA", hin.schema)
    monkeypatch.setattr(pk, "twopass_fits", lambda n: False)
    monkeypatch.setattr(
        pk, "fused_topk_twopass_rect",
        lambda *a, **k_: (_ for _ in ()).throw(
            AssertionError("rect path taken despite mask_self=False")
        ),
    )
    # the fold kernel can't lower on CPU — stand in an XLA equivalent
    # that proves the fallthrough chose it
    calls = {"fold": 0}

    def fold_stub(c, d, k, mask_self):
        import jax

        calls["fold"] += 1
        scores = pk.fused_scores_reference(c, d)
        if mask_self:
            n = scores.shape[0]
            import jax.numpy as jnp

            scores = jnp.where(jnp.eye(n, dtype=bool), -jnp.inf, scores)
        return jax.lax.top_k(scores, k)

    monkeypatch.setattr(pk, "fused_topk", fold_stub)
    jx = create_backend("jax", hin, mp, use_pallas=True)
    vals, idxs = jx.topk(k=3, mask_self=False)  # falls through to fold
    assert calls["fold"] == 1
    oracle = create_backend("numpy", hin, mp)
    scores = oracle.all_pairs_scores()
    np.testing.assert_allclose(
        vals[0].astype(np.float64), np.sort(scores[0])[::-1][:3], atol=1e-6
    )


def test_fused_scores_tile_overrides(cd):
    """bm/bn sweep configs (incl. a non-dividing pair, which exercises
    the lcm padding) must agree with the default tiling exactly."""
    c, d, oracle = cd
    want = oracle.all_pairs_scores()
    for bm, bn in ((512, 512), (256, 512), (256, 384)):
        got = np.asarray(
            pk.fused_scores(c, d, interpret=True, bm=bm, bn=bn),
            dtype=np.float64,
        )
        np.testing.assert_allclose(got, want, atol=1e-7, err_msg=f"{bm}x{bn}")
