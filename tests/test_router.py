"""Horizontal serving tier: routing, failover, hedging, fencing, drain.

The load-bearing guarantees (DESIGN.md §22):

- routing is deterministic, balanced, and minimally disruptive on
  member death (hash ring), or contiguous (range);
- the protocol's new ``request_id``/``deadline_ms`` fields round-trip
  and stay backward-compatible; expired budgets fail fast and clamp
  retry policies;
- a worker killed MID-BATCH loses nothing: its in-flight requests are
  re-dispatched and every answer is bit-identical to the
  single-process oracle;
- a stalled worker is hedged around; the loser's late answer is
  dropped by request-id dedup;
- a replica that missed a delta broadcast is fenced from every
  affected row until ordered catch-up brings its token to the head —
  verified as a property over random delta/query interleavings;
- graceful drain (SIGTERM or the in-band op) completes every accepted
  request before exit, at the serve loop, the worker loop, and the
  router.
"""

from __future__ import annotations

import io
import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import Counter

import numpy as np
import pytest

from distributed_pathsim_tpu.backends.base import create_backend
from distributed_pathsim_tpu.data.delta import delta_from_records, with_headroom
from distributed_pathsim_tpu.data.synthetic import synthetic_hin
from distributed_pathsim_tpu.ops.metapath import compile_metapath
from distributed_pathsim_tpu.resilience import Deadline, RetryPolicy, inject
from distributed_pathsim_tpu.router import (
    HashRing,
    InprocTransport,
    RangeRouter,
    Router,
    RouterConfig,
    RouterShed,
    WorkerRuntime,
)
from distributed_pathsim_tpu.serving import PathSimService, ServeConfig
from distributed_pathsim_tpu.serving.protocol import handle_request, serve_loop


@pytest.fixture(scope="module")
def hin():
    # headroom so protocol-level update ops can append without rebuild
    return with_headroom(synthetic_hin(140, 230, 8, seed=11), 0.25)


@pytest.fixture(scope="module")
def metapath(hin):
    return compile_metapath("APVPA", hin.schema)


def _service(hin, metapath, **cfg):
    cfg.setdefault("max_wait_ms", 1.0)
    cfg.setdefault("warm", False)
    return PathSimService(
        create_backend("numpy", hin, metapath),
        config=ServeConfig(**cfg),
    )


@pytest.fixture()
def oracle(hin, metapath):
    svc = _service(hin, metapath)
    yield svc
    svc.close()


def _oracle_topk(oracle, row: int, k: int):
    vals, idxs = oracle.topk_index(int(row), k)
    return [
        (oracle._ident(int(j))[0], float(v))
        for v, j in zip(vals, idxs)
        if np.isfinite(v)
    ]


def _got_topk(resp: dict):
    return [(h["id"], h["score"]) for h in resp["result"]["topk"]]


class _Fleet:
    """N inproc workers + a router, torn down as one unit."""

    def __init__(self, hin, metapath, n_workers: int, **router_cfg):
        self.transports = {}
        for i in range(n_workers):
            wid = f"w{i}"
            svc = _service(hin, metapath)
            self.transports[wid] = InprocTransport(
                wid, WorkerRuntime(svc, worker_id=wid)
            )
        router_cfg.setdefault("heartbeat_interval_s", 0.05)
        router_cfg.setdefault("hedge_ms", None)  # opt in per test
        self.router = Router(self.transports, RouterConfig(**router_cfg))
        self.router.start()

    def close(self):
        self.router.close()
        for t in self.transports.values():
            t.runtime.service.close()


@pytest.fixture()
def fleet3(hin, metapath):
    f = _Fleet(hin, metapath, 3)
    yield f
    f.close()


# -- routing policies ------------------------------------------------------


def test_hashring_deterministic_balanced_total():
    ring = HashRing(["a", "b", "c"], vnodes=64)
    again = HashRing(["c", "b", "a"], vnodes=64)  # order-independent
    owners = Counter()
    for row in range(3000):
        pref = ring.preference(row)
        assert sorted(pref) == ["a", "b", "c"]  # total order, no dupes
        assert again.preference(row) == pref
        owners[pref[0]] += 1
    # balanced within a small constant factor at 64 vnodes
    assert max(owners.values()) < 2.5 * min(owners.values())


def test_hashring_minimal_disruption():
    ring = HashRing(["a", "b", "c"], vnodes=64)
    shrunk = ring.without("b")
    for row in range(2000):
        old = ring.owner(row)
        if old != "b":
            # keys not owned by the dead member NEVER move
            assert shrunk.owner(row) == old
        else:
            # orphaned keys move to the old ring's next preference
            assert shrunk.owner(row) == ring.preference(row)[1]


def test_range_router_contiguous_and_total():
    rr = RangeRouter(["a", "b", "c"], n_rows=300)
    assert rr.owner(0) == "a" and rr.owner(150) == "b" and rr.owner(299) == "c"
    # every row routed, owner changes exactly at range boundaries
    owners = [rr.owner(r) for r in range(300)]
    assert owners == sorted(owners)
    for row in (0, 123, 299):
        assert sorted(rr.preference(row)) == ["a", "b", "c"]
    # label keys are total too
    assert rr.owner("some label") in ("a", "b", "c")


# -- protocol: request_id, deadline_ms, health (satellite) -----------------


def test_protocol_request_id_roundtrip(hin, metapath, oracle):
    svc = _service(hin, metapath)
    try:
        resp = handle_request(
            svc, {"id": 7, "op": "topk", "row": 3, "k": 4,
                  "request_id": "r-abc", "deadline_ms": 30000.0},
        )
        assert resp["ok"] and resp["request_id"] == "r-abc"
        assert _got_topk(resp) == _oracle_topk(oracle, 3, 4)
        # backward compatible: absent fields never appear in responses
        legacy = handle_request(svc, {"id": 8, "op": "topk", "row": 3})
        assert legacy["ok"] and "request_id" not in legacy
    finally:
        svc.close()


def test_protocol_deadline_expired_fails_fast(hin, metapath):
    svc = _service(hin, metapath)
    try:
        resp = handle_request(
            svc, {"id": 1, "op": "topk", "row": 0, "deadline_ms": -1.0},
        )
        assert not resp["ok"] and resp["deadline_exceeded"]
        # errors echo the request identity too
        resp = handle_request(
            svc, {"id": 2, "op": "topk", "row": 0, "deadline_ms": 0.0,
                  "request_id": "rX"},
        )
        assert not resp["ok"] and resp["request_id"] == "rX"
    finally:
        svc.close()


def test_protocol_health_op(hin, metapath):
    svc = _service(hin, metapath)
    try:
        resp = handle_request(svc, {"id": 1, "op": "health"})
        h = resp["result"]
        assert h["n"] == svc.n
        assert h["base_fp"] == svc.consistency_token[0]
        assert h["delta_seq"] == 0
        assert h["queue_depth"] == 0 and "compiles" in h
    finally:
        svc.close()


def test_deadline_clamps_retry_policy():
    d = Deadline(0.5)
    p = RetryPolicy(deadline_s=60.0)
    assert d.clamp(p).deadline_s <= 0.5
    tight = RetryPolicy(deadline_s=0.01)
    assert d.clamp(tight).deadline_s == 0.01  # tighter of the two wins
    assert Deadline.from_ms(None) is None
    assert Deadline.from_ms(-5).expired


def test_deadline_bounds_retry_wall_time():
    """Retries under a clamped policy never overshoot the caller's
    budget: the seam gives up instead of sleeping past the deadline."""
    calls = [0]

    def always_fails():
        calls[0] += 1
        raise inject.InjectedFault("flaky")

    policy = Deadline(0.05).clamp(
        RetryPolicy(max_attempts=50, base_delay=0.02, jitter=0.0)
    )
    t0 = time.monotonic()
    with pytest.raises(inject.InjectedFault):
        policy.call(always_fails, seam="test")
    assert time.monotonic() - t0 < 0.5
    assert calls[0] < 50  # gave up on the deadline, not on attempts


# -- serve-loop graceful drain (satellite) ---------------------------------


def test_serve_loop_graceful_drain(hin, metapath):
    """SIGTERM (latched via the preemption handler) after request N:
    requests 1..N all answered, the loop exits 0, nothing dropped."""
    from distributed_pathsim_tpu.resilience import preemption_handler

    svc = _service(hin, metapath)
    out = io.StringIO()

    def lines():
        for i in range(3):
            yield json.dumps({"id": i, "op": "topk", "row": i, "k": 3}) + "\n"
        preemption_handler.request("test drain")
        # the drain is latched: this line is read but never accepted
        yield json.dumps({"id": 99, "op": "topk", "row": 5}) + "\n"
        raise AssertionError("loop read past the drain point")

    try:
        rc = serve_loop(svc, lines(), out)
    finally:
        preemption_handler.reset()
        svc.close()
    assert rc == 0
    resps = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert [r["id"] for r in resps] == [0, 1, 2]
    assert all(r["ok"] for r in resps)


def test_serve_loop_drain_op(hin, metapath):
    svc = _service(hin, metapath)
    out = io.StringIO()
    stream = io.StringIO(
        json.dumps({"id": 1, "op": "topk", "row": 2}) + "\n"
        + json.dumps({"id": 2, "op": "drain"}) + "\n"
        + json.dumps({"id": 3, "op": "topk", "row": 4}) + "\n"
    )
    try:
        rc = serve_loop(svc, stream, out)
    finally:
        svc.close()
    assert rc == 0
    resps = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert [r["id"] for r in resps] == [1, 2]
    assert resps[1]["result"]["draining"]


# -- worker runtime: async completion, dedup, drain ------------------------


def _collector():
    got: list[dict] = []
    done = threading.Event()

    def reply(obj: dict) -> None:
        got.append(obj)
        done.set()

    return got, done, reply


def test_worker_runtime_async_topk(hin, metapath, oracle):
    svc = _service(hin, metapath)
    rt = WorkerRuntime(svc, worker_id="wA")
    got, done, reply = _collector()
    try:
        assert rt.handle(
            {"id": 5, "op": "topk", "row": 9, "k": 4, "request_id": "q1"},
            reply,
        ) == "ok"
        assert done.wait(10)
        resp = got[0]
        assert resp["ok"] and resp["request_id"] == "q1"
        assert _got_topk(resp) == _oracle_topk(oracle, 9, 4)
        assert rt.inflight == 0
    finally:
        svc.close()


def test_worker_runtime_update_dedup(hin, metapath):
    """The idempotency contract: a re-delivered update (same
    request_id) replays the cached ack; the delta applies ONCE."""
    svc = _service(hin, metapath)
    rt = WorkerRuntime(svc, worker_id="wB")
    upd = {
        "id": 1, "op": "update", "request_id": "u1",
        "add_edges": [{"rel": "author_of", "src_row": 3, "dst_row": 7}],
    }
    try:
        got1: list[dict] = []
        rt.handle(dict(upd), got1.append)
        assert got1[0]["ok"] and got1[0]["result"]["delta_seq"] == 1
        got2: list[dict] = []
        rt.handle({**upd, "id": 2}, got2.append)
        assert got2[0]["ok"] and got2[0]["deduped"]
        assert got2[0]["id"] == 2  # cached body, caller's envelope id
        assert rt.dedup_hits == 1
        assert svc.consistency_token[1] == 1  # applied exactly once
        # a DIFFERENT request_id applies again
        rt.handle(
            {"id": 3, "op": "update", "request_id": "u2",
             "remove_edges": [
                 {"rel": "author_of", "src_row": 3, "dst_row": 7}
             ]},
            [].append,
        )
        assert svc.consistency_token[1] == 2
    finally:
        svc.close()


def test_worker_runtime_drain_rejects_new_completes_inflight(hin, metapath):
    svc = _service(hin, metapath, max_wait_ms=40.0, max_batch=4)
    rt = WorkerRuntime(svc, worker_id="wC")
    got, done, reply = _collector()
    try:
        # in flight: sits in the coalescer's straggler window
        rt.handle({"id": 1, "op": "topk", "row": 2, "k": 3}, reply)
        rt.begin_drain("test")
        rejected: list[dict] = []
        rt.handle({"id": 2, "op": "topk", "row": 3, "k": 3},
                  rejected.append)
        assert not rejected[0]["ok"] and rejected[0]["draining"]
        assert rt.wait_idle(timeout=10)   # the accepted request finished
        assert done.wait(1) and got[0]["ok"]
    finally:
        svc.close()


# -- router: affinity, failover, hedging, shed, deadline -------------------


def test_router_affinity_and_oracle_parity(fleet3, oracle):
    """Every row's queries keep landing on one worker (cache
    affinity), and routed answers equal the single-process oracle."""
    sent: list[tuple[str, int]] = []
    for wid, t in fleet3.transports.items():
        orig = t.send

        def spy(obj, _orig=orig, _wid=wid):
            if obj.get("op") == "topk":
                sent.append((_wid, obj["row"]))
            _orig(obj)

        t.send = spy
    rows = [3, 77, 130, 3, 77, 130, 3]
    for i, row in enumerate(rows):
        resp = fleet3.router.request(
            {"id": i, "op": "topk", "row": row, "k": 5}, timeout=20
        )
        assert resp["ok"]
        assert _got_topk(resp) == _oracle_topk(oracle, row, 5)
    by_row: dict[int, set] = {}
    for wid, row in sent:
        by_row.setdefault(row, set()).add(wid)
    assert all(len(wids) == 1 for wids in by_row.values())


def test_router_kill_mid_batch_zero_lost(hin, metapath, oracle):
    """The headline chaos property: SIGKILL one replica while a batch
    is in flight — every admitted request still answers, bit-identical
    to the oracle."""
    f = _Fleet(hin, metapath, 3)
    try:
        futs = [
            f.router.submit({"id": i, "op": "topk",
                             "row": int(i % oracle.n), "k": 5})
            for i in range(60)
        ]
        f.transports["w1"].kill()
        resps = [fut.result(timeout=30) for fut in futs]
        assert all(r["ok"] for r in resps)
        for i, r in enumerate(resps):
            assert _got_topk(r) == _oracle_topk(oracle, i % oracle.n, 5)
        st = f.router.stats()["router"]["workers"]
        assert st["w1"]["status"] == "down"
        assert sum(1 for r in resps if r.get("failovers")) > 0
    finally:
        f.close()


def test_router_hedges_stalled_worker(hin, metapath, oracle):
    """A stalled (not dead) replica: the hedge races a duplicate on
    the next replica and the first answer wins; the stalled one's late
    answer is dropped by dedup."""
    f = _Fleet(hin, metapath, 2, hedge_ms=40.0)
    try:
        row = 17
        owner = f.router.policy.owner(row)
        # stall exactly the owner's NEXT dispatch for 1.2s
        inject.install_plan("worker_dispatch:delay:1:1.2")
        t0 = time.monotonic()
        resp = f.router.request(
            {"id": 1, "op": "topk", "row": row, "k": 5}, timeout=20
        )
        elapsed = time.monotonic() - t0
        assert resp["ok"] and resp.get("hedged")
        assert _got_topk(resp) == _oracle_topk(oracle, row, 5)
        assert elapsed < 1.0, "hedge should beat the 1.2s stall"
        assert owner in f.router.workers  # the stalled owner survives
    finally:
        inject.reset()
        f.close()


def test_router_sheds_when_all_saturated(hin, metapath):
    f = _Fleet(hin, metapath, 2, worker_queue_limit=0)
    try:
        resp = f.router.request(
            {"id": 1, "op": "topk", "row": 4, "k": 3}, timeout=10
        )
        assert not resp["ok"] and resp["shed"]
    finally:
        f.close()


def test_router_admission_bound_sheds(hin, metapath):
    f = _Fleet(hin, metapath, 2, max_inflight=0)
    try:
        with pytest.raises(RouterShed):
            f.router.submit({"id": 1, "op": "topk", "row": 4})
    finally:
        f.close()


def test_router_deadline_exceeded(fleet3):
    resp = fleet3.router.request(
        {"id": 1, "op": "topk", "row": 4, "deadline_ms": -1.0}, timeout=10
    )
    assert not resp["ok"] and resp["deadline_exceeded"]


def test_router_startup_rejects_divergent_graphs(hin, metapath):
    other = with_headroom(synthetic_hin(150, 230, 8, seed=99), 0.25)
    mp2 = compile_metapath("APVPA", other.schema)
    transports = {
        "w0": InprocTransport(
            "w0", WorkerRuntime(_service(hin, metapath), worker_id="w0")
        ),
        "w1": InprocTransport(
            "w1", WorkerRuntime(_service(other, mp2), worker_id="w1")
        ),
    }
    router = Router(transports, RouterConfig())
    try:
        with pytest.raises(ValueError, match="disagree on the base graph"):
            router.start()
    finally:
        router.close()
        for t in transports.values():
            t.runtime.service.close()


# -- delta broadcast, fencing, catch-up (satellite property test) ----------


def _apply_update_to_oracle(oracle, upd: dict) -> None:
    oracle.update(delta_from_records(
        oracle.hin,
        add_nodes=upd.get("add_nodes", ()),
        add_edges=upd.get("add_edges", ()),
        remove_edges=upd.get("remove_edges", ()),
    ))


def test_router_update_broadcast_all_ack(hin, metapath, oracle):
    f = _Fleet(hin, metapath, 2)
    try:
        upd = {"id": 9, "op": "update",
               "add_edges": [{"rel": "author_of", "src_row": 2,
                              "dst_row": 5}]}
        resp = f.router.request(dict(upd), timeout=30)
        assert resp["ok"]
        assert sorted(resp["result"]["applied"]) == ["w0", "w1"]
        assert resp["result"]["lagging"] == []
        assert resp["result"]["delta_seq"] == 1
        _apply_update_to_oracle(oracle, upd)
        # served answers reflect the delta on every replica
        for row in (2, 40):
            r = f.router.request(
                {"id": 1, "op": "topk", "row": row, "k": 5}, timeout=20
            )
            assert _got_topk(r) == _oracle_topk(oracle, row, 5)
        st = f.router.stats()["router"]
        assert st["epochs"] == 2
        assert all(w["lag"] == 0 for w in st["workers"].values())
    finally:
        f.close()


def test_router_fencing_property(hin, metapath, oracle):
    """The consistency property (acceptance criterion): over random
    rounds of (update with one replica missing the broadcast) →
    (queries), the lagging replica is NEVER handed a query for an
    affected row until caught up, and every response is bit-identical
    to a single-process oracle absorbing the same deltas."""
    # heartbeats off: catch-up happens only when the test triggers it,
    # so the fencing window is deterministic and spans the assertions
    f = _Fleet(hin, metapath, 2, heartbeat_interval_s=3600.0)
    rng = np.random.default_rng(5)
    router = f.router
    dispatched: list[tuple[str, int]] = []
    for wid, t in f.transports.items():
        orig = t.send

        def spy(obj, _orig=orig, _wid=wid):
            if obj.get("op") == "topk":
                dispatched.append((_wid, obj["row"]))
            _orig(obj)

        t.send = spy
    try:
        n = oracle.n
        for round_i in range(4):
            victim = f"w{round_i % 2}"
            # the victim misses this broadcast: fire the seam only on
            # its send (workers iterate in insertion order w0, w1)
            skip = 0 if victim == "w0" else 1
            inject.install_plan(f"delta_broadcast:error:1@{skip}")
            # a genuinely new edge: an add colliding with an existing
            # one is a malformed batch the delta machinery rejects
            ap = oracle.hin.blocks["author_of"]
            existing = set(zip(ap.rows.tolist(), ap.cols.tolist()))
            while True:
                src = int(rng.integers(0, 140))
                dst = int(rng.integers(0, 230))
                if (src, dst) not in existing:
                    break
            upd = {"id": round_i, "op": "update",
                   "add_edges": [{"rel": "author_of", "src_row": src,
                                  "dst_row": dst}]}
            resp = router.request(dict(upd), timeout=30)
            inject.reset()
            assert resp["ok"] and resp["result"]["lagging"] == [victim]
            _apply_update_to_oracle(oracle, upd)
            affected = router._epochs[-1].affected
            assert affected, "delta must affect at least the source row"
            dispatched.clear()
            # queries while the victim lags: mix affected + unaffected
            rows = list(affected)[:6] + [
                int(r) for r in rng.integers(0, n, size=6)
            ]
            for row in rows:
                r = router.request(
                    {"id": 1, "op": "topk", "row": int(row), "k": 5},
                    timeout=20,
                )
                assert r["ok"]
                assert _got_topk(r) == _oracle_topk(oracle, int(row), 5)
            # THE fence: no affected row ever reached the laggard
            for wid, row in dispatched:
                if wid == victim:
                    assert row not in affected, (
                        f"fenced row {row} dispatched to lagging {victim}"
                    )
            # catch-up: one health round-trip triggers the ordered
            # replay; the worker's token reaches the head
            assert router.worker_health(victim, timeout=10)
            for _ in range(200):
                st = router.stats()["router"]["workers"][victim]
                if st["lag"] == 0:
                    break
                time.sleep(0.01)
            else:
                raise AssertionError(f"{victim} never caught up")
            # post-catch-up: the ex-laggard answers affected rows
            # correctly (route around is gone)
            dispatched.clear()
            for row in list(affected)[:4]:
                r = router.request(
                    {"id": 1, "op": "topk", "row": int(row), "k": 5},
                    timeout=20,
                )
                assert _got_topk(r) == _oracle_topk(oracle, int(row), 5)
        # dedup saw no double-applies: each replica's delta_seq equals
        # the number of broadcasts
        for t in f.transports.values():
            assert t.runtime.service.consistency_token[1] == 4
        # epoch-log compaction: every replica has passed epochs 1..4,
        # so their replay payloads must be gone (a long-lived router
        # must not retain every delta's edge lists forever)
        assert router._compacted_to == 5
        assert all(e.wire_req is None for e in router._epochs[1:5])
    finally:
        inject.reset()
        f.close()


# -- chaos: the ambient-plan smoke (make chaos-router) ---------------------


@pytest.mark.chaos
def test_chaos_router_smoke(hin, metapath, oracle):
    """The router under an ambient fault plan + a mid-batch kill:
    transient dispatch failures, dropped heartbeats, a missed delta
    broadcast, a stall — zero lost requests, every answer bit-exact.
    ``make chaos-router`` re-runs this with the plan in the
    environment; here it is installed explicitly so plain tier-1
    exercises it too."""
    plan = os.environ.get("PATHSIM_FAULT_PLAN") or ",".join([
        "worker_dispatch:error:3",
        "worker_dispatch:delay:1:0.05",
        "heartbeat:error:2",
        "delta_broadcast:error:1@1",
    ])
    inject.install_plan(plan)
    f = _Fleet(hin, metapath, 3, hedge_ms=80.0)
    try:
        futs = [
            f.router.submit({"id": i, "op": "topk",
                             "row": int(i % oracle.n), "k": 5})
            for i in range(40)
        ]
        upd = {"id": 100, "op": "update",
               "add_edges": [{"rel": "author_of", "src_row": 8,
                              "dst_row": 12}]}
        uresp = f.router.request(dict(upd), timeout=30)
        assert uresp["ok"]
        _apply_update_to_oracle(oracle, upd)
        f.transports["w2"].kill()  # and THEN a worker dies
        resps = [fut.result(timeout=30) for fut in futs]
        assert all(r["ok"] for r in resps), [
            r for r in resps if not r["ok"]
        ][:3]
        # post-delta, post-kill queries: still oracle-exact
        for row in (8, 50, 100):
            r = f.router.request(
                {"id": 1, "op": "topk", "row": row, "k": 5}, timeout=30
            )
            assert r["ok"] and _got_topk(r) == _oracle_topk(oracle, row, 5)
    finally:
        inject.reset()
        f.close()


# -- worker process: SIGTERM drain + the full smoke (make router-smoke) ----


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_worker_subprocess_sigterm_drain():
    """A real ``dpathsim worker`` process: SIGTERM mid-stream → every
    accepted request answered, drained event emitted, exit 0."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "distributed_pathsim_tpu.cli", "worker",
         "--dataset", "synthetic:authors=48,papers=80,venues=4,seed=2",
         "--backend", "numpy", "--no-warm", "--worker-id", "wS"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, env=env, cwd=REPO,
    )
    try:
        ready = json.loads(proc.stdout.readline())
        assert ready["event"] == "ready"
        for i in range(3):
            proc.stdin.write(json.dumps(
                {"id": i, "op": "topk", "row": i, "k": 3}
            ) + "\n")
        proc.stdin.flush()
        got = [json.loads(proc.stdout.readline()) for _ in range(3)]
        assert all(r["ok"] for r in got)
        proc.send_signal(signal.SIGTERM)
        time.sleep(0.2)  # let the signal latch before the next event
        proc.stdin.write(json.dumps({"id": 9, "op": "topk", "row": 1}) + "\n")
        proc.stdin.flush()
        tail = [json.loads(ln) for ln in proc.stdout]
        assert proc.wait(timeout=30) == 0
        # the post-signal line was never accepted; the drained event is
        # the last thing out
        assert not any(r.get("id") == 9 for r in tail)
        assert any(r.get("event") == "drained" for r in tail)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()


def test_bench_router_smoke():
    """``make router-smoke`` as a tier-1 test: 2 real worker
    subprocesses, closed-loop load, a mid-load SIGKILL; gates zero
    lost requests, zero steady-state recompiles, oracle bit-parity,
    and a real rerouted failover."""
    sys.path.insert(0, REPO)
    try:
        import bench_serving

        result = bench_serving.run_router_smoke()
    finally:
        sys.path.remove(REPO)
    assert all(result["smoke_checks"].values()), result["smoke_checks"]


# -- ANN tier: index epoch in health, failover to an exact replica ---------


@pytest.mark.chaos
def test_router_ann_failover_to_exact_replica(hin, metapath, oracle):
    """ISSUE 8 satellite: kill the only ANN-indexed worker mid-batch —
    every in-flight ``mode: ann`` request re-dispatches onto the
    surviving EXACT-only replica (which has no index at all) and is
    answered exactly: zero lost requests, answers bit-identical to the
    single-process oracle, and the no_index fallback counted on the
    survivor. Also: the ``health`` op advertises each replica's index
    epoch, and the router surfaces it per worker in stats().

    Chaos-marked: ``make chaos-router`` re-runs the same kill under
    the ambient ROUTER_PLAN (transient dispatch faults, dropped
    heartbeats); here the plan is installed explicitly so plain tier-1
    exercises the faulted path too."""
    # a MILD ambient plan: with exactly two replicas and one killed,
    # injected dispatch errors on the lone survivor would exhaust the
    # preference list (a correct shed, but not this test's property) —
    # dropped heartbeats exercise the suspicion machinery without
    # consuming the survivor's attempts. Under `make chaos-router` the
    # full ROUTER_PLAN applies; its dispatch faults retry locally
    # first, and a run-to-run shed there is absorbed by that suite's
    # own assertions.
    inject.install_plan("heartbeat:error:2")

    def _svc(ann: bool):
        return PathSimService(
            create_backend("numpy", hin, metapath),
            config=ServeConfig(
                max_wait_ms=1.0, warm=False,
                topk_mode="ann" if ann else "exact",
                ann_shadow_every=0,
            ),
        )

    from distributed_pathsim_tpu.router import InprocTransport

    transports = {
        "w0": InprocTransport("w0", WorkerRuntime(_svc(True),
                                                  worker_id="w0")),
        "w1": InprocTransport("w1", WorkerRuntime(_svc(False),
                                                  worker_id="w1")),
    }
    router = Router(transports, RouterConfig(heartbeat_interval_s=0.05,
                                             hedge_ms=None))
    router.start()
    try:
        # health advertises the index epoch (and its absence)
        h0 = router.worker_health("w0")
        h1 = router.worker_health("w1")
        assert h0["index"] is not None
        assert h0["index"]["mode"] == "ann"
        assert h0["index"]["epoch"] == list(
            transports["w0"].runtime.service.consistency_token
        )
        assert h1["index"] is None
        st = router.stats()["router"]["workers"]
        assert st["w0"]["index"]["epoch"] == h0["index"]["epoch"]
        assert st["w1"]["index"] is None

        futs = [
            router.submit({"id": i, "op": "topk",
                           "row": int(i % oracle.n), "k": 5,
                           "mode": "ann"})
            for i in range(48)
        ]
        transports["w0"].kill()  # the indexed replica dies mid-batch
        resps = [fut.result(timeout=30) for fut in futs]
        assert all(r["ok"] for r in resps)
        for i, r in enumerate(resps):
            assert _got_topk(r) == _oracle_topk(oracle, i % oracle.n, 5)
        assert router.stats()["router"]["workers"]["w0"]["status"] == "down"
        # the kill must have orphaned real ann work onto the survivor
        assert sum(1 for r in resps if r.get("failovers")) > 0
    finally:
        inject.reset()
        router.close()
        for t in transports.values():
            t.runtime.service.close()


# -- Learned tier: per-mode epochs in health, tower-less failover ----------


@pytest.mark.chaos
def test_router_learned_failover_to_towerless_replica(hin, metapath, oracle):
    """ISSUE 19 satellite (mirrors the ann chaos case): kill the only
    tower-ed worker mid-batch — every in-flight ``mode: learned``
    request re-dispatches onto the surviving replica (which has no
    towers at all) and is answered exactly: zero lost requests,
    answers bit-identical to the single-process oracle, the no_towers
    fallback counted on the survivor. Also: ``health`` advertises the
    per-mode index-epoch map, and fleet-stats surfaces it per
    worker."""
    inject.install_plan("heartbeat:error:2")

    def _svc(learned: bool):
        return PathSimService(
            create_backend("numpy", hin, metapath),
            config=ServeConfig(
                max_wait_ms=1.0, warm=False,
                topk_mode="learned" if learned else "exact",
                learned_shadow_every=0, learned_auto_refresh=False,
                # candidate set >= n: learned answers are bit-identical
                # regardless of what 40 steps taught the towers
                learned_cand_mult=32, learned_steps=40,
            ),
        )

    transports = {
        "w0": InprocTransport("w0", WorkerRuntime(_svc(True),
                                                  worker_id="w0")),
        "w1": InprocTransport("w1", WorkerRuntime(_svc(False),
                                                  worker_id="w1")),
    }
    router = Router(transports, RouterConfig(heartbeat_interval_s=0.05,
                                             hedge_ms=None))
    router.start()

    def _no_towers() -> float:
        from distributed_pathsim_tpu.obs.metrics import get_registry

        return get_registry().counter(
            "dpathsim_learned_fallbacks_total",
            "learned-requested queries degraded to ann/exact, by reason",
        ).labels(reason="no_towers").value

    try:
        # health advertises the per-mode epoch map (and its absence)
        h0 = router.worker_health("w0")
        h1 = router.worker_health("w1")
        token0 = list(
            transports["w0"].runtime.service.consistency_token
        )
        assert h0["modes"]["learned"]["epoch"] == token0
        assert h0["modes"]["learned"]["enabled"]
        assert h0["modes"]["exact"]["epoch"] == token0
        assert h1["modes"]["learned"] is None
        assert h1["modes"]["exact"]["enabled"]
        st = router.stats()["router"]["workers"]
        assert st["w0"]["modes"]["learned"]["epoch"] == token0
        assert st["w1"]["modes"]["learned"] is None

        fb0 = _no_towers()
        futs = [
            router.submit({"id": i, "op": "topk",
                           "row": int(i % oracle.n), "k": 5,
                           "mode": "learned"})
            for i in range(48)
        ]
        transports["w0"].kill()  # the tower-ed replica dies mid-batch
        resps = [fut.result(timeout=30) for fut in futs]
        assert all(r["ok"] for r in resps)
        for i, r in enumerate(resps):
            assert _got_topk(r) == _oracle_topk(oracle, i % oracle.n, 5)
        assert router.stats()["router"]["workers"]["w0"]["status"] == "down"
        # the kill must have orphaned real learned work onto the
        # survivor, where each answer is a counted no_towers fallback
        assert sum(1 for r in resps if r.get("failovers")) > 0
        assert _no_towers() > fb0
    finally:
        inject.reset()
        router.close()
        for t in transports.values():
            t.runtime.service.close()
