"""Observability subsystem: histograms, spans, exporters, protocol op.

The load-bearing guarantees:

- streaming histogram quantiles track ``numpy.percentile`` within the
  documented geometric-bucket error bound on adversarial distributions
  (bimodal, heavy-tail, entirely below bucket-min, entirely above
  bucket-max) — WITHOUT storing samples;
- one served request is one connected trace across the coalescer's
  submit → dispatch → complete thread hops, under concurrent load;
- the Prometheus textfile is well-formed exposition format 0.0.4
  (cumulative buckets, ``+Inf`` == ``_count``) and is written
  atomically;
- the ``metrics`` protocol op round-trips through JSON and its cache
  hit counts agree exactly with the service-level cache counters;
- telemetry discipline (scripts/lint_telemetry.py) holds over the
  whole package;
- the obs-off arm costs nothing measurable and neither arm perturbs
  the steady-state zero-compile contract (``make obs-smoke``).
"""

from __future__ import annotations

import io
import json
import math
import re
import threading

import numpy as np
import pytest

from distributed_pathsim_tpu import obs
from distributed_pathsim_tpu.backends.base import create_backend
from distributed_pathsim_tpu.data.synthetic import synthetic_hin
from distributed_pathsim_tpu.obs.metrics import (
    MetricsRegistry,
    geometric_bounds,
    get_registry,
)
from distributed_pathsim_tpu.obs.trace import get_tracer
from distributed_pathsim_tpu.ops.metapath import compile_metapath
from distributed_pathsim_tpu.serving import PathSimService, ServeConfig


@pytest.fixture(scope="module")
def hin():
    return synthetic_hin(160, 260, 9, n_topics=4, seed=7)


@pytest.fixture(scope="module")
def metapath(hin):
    return compile_metapath("APVPA", hin.schema)


@pytest.fixture()
def tracing():
    """Enable tracing for one test; restore the process default (off)
    and drain the span ring afterwards so tests stay independent."""
    tracer = get_tracer()
    tracer.clear()
    tracer.configure(enabled=True, sample_every=1)
    try:
        yield tracer
    finally:
        tracer.configure(enabled=False, sample_every=1)
        tracer.clear()


def _service(hin, metapath, backend_name="numpy", **cfg):
    cfg.setdefault("max_wait_ms", 5.0)
    cfg.setdefault("warm", False)
    backend = create_backend(backend_name, hin, metapath)
    return PathSimService(backend, config=ServeConfig(**cfg))


# -- histogram quantile accuracy (satellite: adversarial distributions) ---

# Documented worst-case relative error of log-linear interpolation on
# geometric buckets: one bucket-width ratio, 10^(1/16)-1 ≈ 15.5% at the
# default resolution.
_REL_TOL = 10 ** (1 / 16) - 1 + 0.01


def _check_quantiles(samples: np.ndarray, qs=(0.50, 0.95, 0.99)) -> None:
    reg = MetricsRegistry()
    cell = reg.histogram("h").labels()
    for v in samples:
        cell.observe(float(v))
    for q in qs:
        est = cell.quantile(q)
        ref = float(np.percentile(samples, q * 100))
        assert abs(est - ref) <= _REL_TOL * abs(ref) + 1e-12, (
            q, est, ref, abs(est - ref) / abs(ref),
        )


def test_histogram_quantiles_bimodal():
    """Two tight modes three decades apart — the shape a cache-hit/
    dispatch latency split actually produces."""
    rng = np.random.default_rng(0)
    fast = rng.normal(2e-4, 2e-5, size=6000).clip(1e-5)
    slow = rng.normal(0.25, 0.02, size=4000).clip(1e-3)
    _check_quantiles(np.concatenate([fast, slow]))


def test_histogram_quantiles_heavy_tail():
    """Lognormal with a fat tail: p99 sits far from the mass."""
    rng = np.random.default_rng(1)
    _check_quantiles(np.exp(rng.normal(-6.0, 1.5, size=20000)))


def test_histogram_quantiles_below_bucket_min():
    """Everything under the lowest bound lands in underflow; the only
    honest answer is the exact observed min (documented edge clamp)."""
    rng = np.random.default_rng(2)
    samples = rng.uniform(1e-9, 5e-7, size=500)
    reg = MetricsRegistry()
    cell = reg.histogram("h").labels()
    for v in samples:
        cell.observe(float(v))
    for q in (0.50, 0.99):
        assert cell.quantile(q) == samples.min()


def test_histogram_quantiles_above_bucket_max():
    """Everything over the top bound lands in overflow; quantiles clamp
    to the exact observed max."""
    rng = np.random.default_rng(3)
    samples = rng.uniform(200.0, 900.0, size=500)
    reg = MetricsRegistry()
    cell = reg.histogram("h").labels()
    for v in samples:
        cell.observe(float(v))
    for q in (0.50, 0.99):
        assert cell.quantile(q) == samples.max()


def test_histogram_quantile_includes_discrete_tail():
    """The tail-inclusive rank convention: nine 1 ms requests plus one
    1 s request has its p99 IN the slow mass — a q·(count−1) walk
    would land one sample short and report ~1 ms, a 1000× under-report
    of exactly the signal a latency quantile exists to surface."""
    reg = MetricsRegistry()
    cell = reg.histogram("h").labels()
    for v in [0.001] * 9 + [1.0]:
        cell.observe(v)
    assert cell.quantile(0.99) == pytest.approx(1.0, rel=_REL_TOL)
    assert cell.quantile(0.50) == pytest.approx(0.001, rel=_REL_TOL)
    # and with only two observations, p99 sits at the slow one
    cell2 = reg.histogram("h2").labels()
    cell2.observe(2e-6)
    cell2.observe(90.0)
    assert cell2.quantile(0.99) == pytest.approx(90.0, rel=_REL_TOL)


def test_histogram_bounds_conflict_is_loud():
    """A family's bucket geometry belongs to its first registrant;
    handing a later caller different buckets than it asked for would
    corrupt its counts silently, so the mismatch raises instead."""
    reg = MetricsRegistry()
    reg.histogram("h", bounds=(1.0, 2.0, 4.0))
    reg.histogram("h")  # no opinion on bounds: reuses the family
    reg.histogram("h", bounds=(1.0, 2.0, 4.0))  # same bounds: fine
    with pytest.raises(TypeError):
        reg.histogram("h", bounds=(1.0, 8.0))


def test_histogram_bounded_memory_and_aggregates():
    """No samples stored: state size is fixed by the bucket geometry,
    while count/sum/min/max stay exact at any volume."""
    reg = MetricsRegistry()
    cell = reg.histogram("h").labels()
    n_state = len(cell.counts)
    rng = np.random.default_rng(4)
    samples = np.exp(rng.normal(-4, 2, size=50_000))
    for v in samples:
        cell.observe(float(v))
    assert len(cell.counts) == n_state  # nothing grew
    snap = cell.snapshot()
    assert snap["count"] == samples.size
    assert snap["min"] == samples.min() and snap["max"] == samples.max()
    assert math.isclose(snap["sum"], samples.sum(), rel_tol=1e-9)
    assert snap["p50"] <= snap["p95"] <= snap["p99"]


def test_geometric_bounds_shape():
    b = geometric_bounds(1e-3, 1.0, 8)
    assert b[0] == 1e-3 and b[-1] >= 1.0
    ratios = [b[i + 1] / b[i] for i in range(len(b) - 2)]
    assert all(math.isclose(r, 10 ** (1 / 8), rel_tol=1e-9) for r in ratios)
    with pytest.raises(ValueError):
        geometric_bounds(1.0, 0.5)


def test_registry_counters_gauges_and_disable():
    reg = MetricsRegistry()
    c = reg.counter("c", "help").labels(kind="x")
    g = reg.gauge("g")
    h = reg.histogram("h")
    c.inc()
    c.inc(2)
    g.set(7, shard="0")
    h.observe(0.5)
    assert c.get() == 3.0
    snap = reg.snapshot()
    assert snap["c"]["type"] == "counter"
    assert snap["g"]["values"][0] == {"labels": {"shard": "0"}, "value": 7.0}
    assert snap["h"]["values"][0]["count"] == 1
    with pytest.raises(TypeError):
        reg.counter("g")  # kind mismatch is a programming error
    # the global disable switch turns every mutation into a no-op …
    reg.enabled = False
    c.inc()
    g.set(99, shard="0")
    h.observe(1.0)
    reg.enabled = True
    assert c.get() == 3.0
    assert reg.gauge("g").labels(shard="0").get() == 7.0
    # … and reset() zeroes IN PLACE so bound cells stay live
    reg.reset()
    assert c.get() == 0.0
    c.inc()
    assert c.get() == 1.0


# -- tracing: hierarchy, cross-thread propagation, ring bound -------------


def test_span_nesting_and_ids(tracing):
    with tracing.span("outer") as outer:
        with tracing.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    spans = {s.name: s for s in tracing.spans()}
    assert spans["outer"].parent_id is None
    assert spans["outer"].trace_id == spans["outer"].span_id  # root rule
    assert spans["inner"].t_end_ns >= spans["inner"].t_start_ns


def test_span_cross_thread_handoff(tracing):
    """start_span on one thread, finish + activate on another — the
    coalescer's exact lifecycle, distilled."""
    root = tracing.start_span("root")
    seen = {}

    def worker():
        with tracing.activate(root.context):
            with tracing.span("child") as c:
                seen["child"] = c

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    tracing.finish(root)
    assert seen["child"].trace_id == root.trace_id
    assert seen["child"].parent_id == root.span_id
    # the two halves ran on different threads, and the trace knows
    names = {s.name: s.thread_name for s in tracing.spans()}
    assert names["child"] != names["root"]


def test_span_ring_is_bounded(tracing):
    tracing.configure(max_spans=16)
    try:
        for i in range(100):
            with tracing.span(f"s{i}"):
                pass
        spans = tracing.spans()
        assert len(spans) == 16
        assert spans[-1].name == "s99"  # newest kept, oldest dropped
    finally:
        tracing.configure(max_spans=200_000)


def test_span_error_marks_and_propagates(tracing):
    with pytest.raises(ValueError):
        with tracing.span("boom"):
            raise ValueError("x")
    (s,) = tracing.spans()
    assert "ValueError" in s.args["error"]


def test_finish_is_first_finish_wins(tracing):
    """Overlapping error paths may finish a span twice; the second
    call must neither duplicate the ring entry nor rewrite the
    already-recorded outcome."""
    s = tracing.start_span("once")
    tracing.finish(s, outcome="resolved")
    tracing.finish(s, outcome="error")
    spans = tracing.spans()
    assert len(spans) == 1
    assert spans[0].args["outcome"] == "resolved"


def test_head_sampling_every_nth_root(tracing):
    """sample_every=n admits every nth trace HEAD; children of a
    sampled-in head are never dropped, so admitted traces stay
    complete."""
    tracing.configure(sample_every=4)
    try:
        roots = []
        for i in range(16):
            with tracing.span(f"head{i}") as s:
                if s is not None:
                    roots.append(i)
                    with tracing.span("kid") as kid:
                        assert kid is not None  # child never sampled out
        assert len(roots) == 4  # 16 heads / 4
        # sampled-out heads created no spans at all
        spans = tracing.spans()
        assert len(spans) == 8  # 4 heads + 4 kids
        assert all(
            s.name == "kid" or int(s.name[4:]) in roots for s in spans
        )
    finally:
        tracing.configure(sample_every=1)


def test_sampled_out_head_suppresses_nested_heads(tracing):
    """A dropped head must poison its scope: a parentless span nested
    inside it (serve.op → serve.request on the protocol path) is
    suppressed outright and does NOT tick the sampler — otherwise the
    effective rate doubles and half the traces lose their envelope."""
    tracing.configure(sample_every=2)
    try:
        admitted = []
        for i in range(8):
            with tracing.span(f"outer{i}") as outer:
                # cross-thread form, as submit_topk uses it
                inner = tracing.start_span("inner")
                if outer is not None:
                    admitted.append(i)
                    assert inner is not None  # sampled-in: complete
                    assert inner.trace_id == outer.trace_id
                else:
                    assert inner is None  # dropped head: nothing below
                tracing.finish(inner)
        assert len(admitted) == 4  # exactly 1-in-2, not 2-in-2
        assert len(tracing.spans()) == 8  # 4 outer + 4 inner
    finally:
        tracing.configure(sample_every=1)


def test_sampling_rejects_bad_rate(tracing):
    with pytest.raises(ValueError):
        tracing.configure(sample_every=0)


def test_child_span_noops_without_parent(tracing):
    """child_span is for mid-pipeline segments: under a live parent it
    nests normally; with no current span it creates nothing (the
    sampled-out path must not leak orphan roots)."""
    with tracing.child_span("orphan") as s:
        assert s is None
    with tracing.span("root"):
        with tracing.child_span("kid") as kid:
            assert kid is not None
    assert {s.name for s in tracing.spans()} == {"root", "kid"}


def test_serving_sampled_tracing_no_orphans(hin, metapath, tracing):
    """Under head sampling, an unsampled serving request creates ZERO
    spans anywhere in the pipeline — every span in the ring still
    belongs to a sampled-in serve.request trace (or is a batch span
    parented into one), and sampled-in traces resolve with outcomes."""
    svc = _service(hin, metapath, "numpy", max_batch=4,
                   cache_entries=0, tile_cache_bytes=0)
    # sampling on AFTER the build: the backend.init span is not part of
    # this test's request accounting
    tracing.clear()
    tracing.configure(sample_every=4)
    try:
        for r in range(32):
            svc.topk_index(int(r % svc.n), k=3)
    finally:
        svc.close()
        tracing.configure(sample_every=1)
    spans = tracing.spans()
    roots = [s for s in spans if s.name == "serve.request"]
    assert len(roots) == 8  # 32 admissions / 4
    assert all("outcome" in s.args for s in roots)
    # no orphans: every span's parent chain ends at a serve.request
    by_id = {s.span_id: s for s in spans}
    for s in spans:
        top = s
        while top.parent_id is not None:
            assert top.parent_id in by_id, (s.name, top.name)
            top = by_id[top.parent_id]
        assert top.name == "serve.request", (s.name, top.name)


def test_disabled_tracer_yields_none_and_records_nothing():
    tracer = get_tracer()
    assert not tracer.enabled
    with tracer.span("ghost") as s:
        assert s is None
    tracer.finish(None)  # must be a no-op, not a crash
    assert tracer.spans() == []


def test_chrome_trace_export(tracing, tmp_path):
    with tracing.span("parent", detail=1):
        with tracing.span("kid"):
            pass
    path = tmp_path / "trace.json"
    n = obs.write_chrome_trace(str(path))
    assert n == 2
    doc = json.loads(path.read_text())
    events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in events} == {"parent", "kid"}
    for e in events:
        assert e["dur"] >= 0 and e["ts"] > 0
        assert {"trace_id", "span_id"} <= e["args"].keys()
    # thread-name metadata present for every tid that emitted spans
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {e["tid"] for e in meta} >= {e["tid"] for e in events}


# -- serving integration: one request = one connected trace ---------------


def test_serving_trace_connected_across_thread_hop(hin, metapath, tracing):
    """Concurrent submitters, coalesced batches: every span's parent
    resolves inside its own trace, and at least one dispatched request
    carries the full enqueue→dispatch→device→complete chain."""
    svc = _service(hin, metapath, "numpy", max_batch=4,
                   cache_entries=0, tile_cache_bytes=0)
    errs: list[BaseException] = []
    try:
        def client(rows):
            try:
                for r in rows:
                    svc.topk_index(int(r), k=5)
            except BaseException as exc:  # pragma: no cover
                errs.append(exc)

        rng = np.random.default_rng(11)
        threads = [
            threading.Thread(target=client,
                             args=(rng.integers(0, svc.n, 12),))
            for _ in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        svc.close()
    assert not errs
    spans = tracing.spans()
    by_id = {s.span_id: s for s in spans}
    by_trace: dict[int, list] = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)
    # no dangling or cross-trace parent links anywhere
    for s in spans:
        if s.parent_id is not None:
            assert s.parent_id in by_id, s.name
            assert by_id[s.parent_id].trace_id == s.trace_id, s.name
    # every root request span resolved with an outcome
    roots = [s for s in spans if s.name == "serve.request"]
    assert len(roots) == 6 * 12
    assert all("outcome" in s.args for s in roots)
    # at least one trace carries the full dispatched chain (the batch
    # head's trace owns dispatch/device/complete; members reach the
    # shared work through it)
    chain = {"serve.request", "serve.enqueue", "serve.dispatch",
             "serve.device_execute", "serve.complete",
             "serve.host_transfer", "serve.cache_fill"}
    full = [
        tid for tid, members in by_trace.items()
        if chain <= {s.name for s in members}
    ]
    assert full, "no dispatched request produced a connected full chain"
    # and the chain genuinely crossed threads
    tid = full[0]
    assert len({s.thread_name for s in by_trace[tid]}) >= 3


def test_stage_timer_is_a_span_shim(hin, tracing):
    """The deprecated StageTimer keeps its API and event while feeding
    the span tree and the stage histogram."""
    from distributed_pathsim_tpu.utils.logging import RunLogger
    from distributed_pathsim_tpu.utils.profiling import StageTimer

    get_registry().reset()
    buf = io.StringIO()
    logger = RunLogger(output_path=None, echo=False)
    logger._metrics = buf
    timer = StageTimer(logger)
    with timer.stage("outer_stage"):
        with timer.stage("inner_stage"):
            pass
    assert [name for name, _ in timer.stages] == [
        "inner_stage", "outer_stage",
    ]
    spans = {s.name: s for s in get_tracer().spans()}
    assert spans["stage:inner_stage"].parent_id \
        == spans["stage:outer_stage"].span_id
    events = [json.loads(line) for line in buf.getvalue().splitlines()]
    assert [e["event"] for e in events] == ["stage_time", "stage_time"]
    assert all("ts" in e and "ts_mono" in e for e in events)
    hist = get_registry().histogram("dpathsim_stage_seconds")
    assert hist.labels(stage="outer_stage").count == 1


def test_runtime_event_concurrent_lines_stay_atomic(monkeypatch):
    """Worker threads emitting concurrently must never interleave
    stderr characters mid-line (the locked single-write contract)."""
    import sys as _sys

    from distributed_pathsim_tpu.utils import logging as ulog

    buf = io.StringIO()
    monkeypatch.setattr(_sys, "stderr", buf)
    threads = [
        threading.Thread(
            target=lambda i=i: [
                ulog.runtime_event("obs_test", worker=i, seq=j)
                for j in range(50)
            ]
        )
        for i in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lines = buf.getvalue().splitlines()
    assert len(lines) == 400
    assert all(
        re.fullmatch(r"\[pathsim:obs_test\] worker=\d+ seq=\d+", ln)
        for ln in lines
    )


def test_timestamps_carry_both_clocks():
    from distributed_pathsim_tpu.utils.logging import timestamps

    a, b = timestamps(), timestamps()
    assert set(a) == {"ts", "ts_mono"}
    assert b["ts_mono"] >= a["ts_mono"]  # monotonic never steps back
    assert a["ts"] > 1e9  # wall clock is epoch-scaled


# -- Prometheus export (satellite: well-formedness + atomicity) -----------

_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"  # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"([^\"\\]|\\.)*\""  # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"([^\"\\]|\\.)*\")*\})?"  # rest
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$"
)


def _well_formed(text: str) -> None:
    assert text.endswith("\n")
    for line in text.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert _SAMPLE.match(line), line


def test_render_prometheus_well_formed():
    reg = MetricsRegistry()
    reg.counter("req_total", "requests").inc(5, op="topk")
    reg.counter("req_total").inc(2, op="stats")
    reg.gauge("depth", 'tricky "help"').set(3)
    h = reg.histogram("lat_seconds", "latency")
    for v in (1e-8, 1e-4, 3e-4, 0.02, 0.5, 500.0):  # under+mid+overflow
        h.observe(v)
    text = obs.render_prometheus(reg)
    _well_formed(text)
    assert "# TYPE req_total counter" in text
    assert "# TYPE lat_seconds histogram" in text
    assert 'req_total{op="topk"} 5' in text
    # cumulative buckets: non-decreasing, +Inf equals _count
    cums = [
        float(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("lat_seconds_bucket")
    ]
    assert cums == sorted(cums)
    assert cums[-1] == 6.0  # the +Inf bucket
    assert "lat_seconds_count 6" in text
    # underflow folded into the first bound, overflow only in +Inf
    assert cums[0] >= 1.0


def test_textfile_exporter_atomic_and_final_write(tmp_path):
    reg = MetricsRegistry()
    c = reg.counter("ticks").labels()
    path = tmp_path / "metrics.prom"
    exp = obs.PrometheusTextfileExporter(
        str(path), interval_s=3600, registry=reg
    )
    with exp:
        assert path.exists()  # first write is synchronous on start()
        _well_formed(path.read_text())
        c.inc(41)
        c.inc()
    # stop() performed a final write: shutdown state is on disk
    assert "ticks 42" in path.read_text()
    # atomicity: no temp droppings beside the target
    assert list(tmp_path.iterdir()) == [path]
    exp.stop()  # idempotent


def test_label_escaping_round_trip():
    reg = MetricsRegistry()
    reg.counter("c").inc(path='we"ird\\lab\nel')
    text = obs.render_prometheus(reg)
    _well_formed(text)
    assert '\\"' in text and "\\\\" in text and "\\n" in text


# -- the metrics protocol op (satellite: round-trip + agreement) ----------


def test_metrics_protocol_op_round_trip(hin, metapath):
    from distributed_pathsim_tpu.serving.protocol import (
        handle_request,
        serve_loop,
    )

    get_registry().reset()
    svc = _service(hin, metapath, "numpy", max_batch=4)
    try:
        for row in (5, 9, 5, 5, 9, 23):  # repeats → result-cache hits
            assert handle_request(
                svc, {"id": 1, "op": "topk", "row": row, "k": 3}
            )["ok"]
        resp = handle_request(svc, {"id": 2, "op": "metrics"})
        assert resp["ok"]
        payload = json.loads(json.dumps(resp))["result"]  # JSON-safe
        ops = payload["ops"]
        assert ops["topk"]["count"] == 6
        assert (
            0 <= ops["topk"]["p50_ms"] <= ops["topk"]["p95_ms"]
            <= ops["topk"]["p99_ms"]
        )
        # cache hit counts agree EXACTLY with the service-level
        # counters, and with the registry's per-tier cells
        caches = payload["caches"]
        assert caches["result"]["hits"] == svc.result_cache.hits == 3
        assert caches["result"]["misses"] == svc.result_cache.misses == 3
        assert caches["result"]["hit_rate"] == 0.5
        reg_hits = (
            get_registry()
            .counter("dpathsim_serve_cache_hits_total")
            .labels(tier="result")
            .get()
        )
        assert reg_hits == svc.result_cache.hits
        # full registry snapshot rides along for tooling
        assert "dpathsim_request_seconds" in payload["registry"]
        assert payload["enabled"]["metrics"] is True

        # and over the wire: one JSONL line in, one line out
        out = io.StringIO()
        rc = serve_loop(
            svc,
            io.StringIO('{"id": 7, "op": "metrics"}\n'
                        '{"id": 8, "op": "shutdown"}\n'),
            out,
        )
        assert rc == 0
        line = json.loads(out.getvalue().splitlines()[0])
        assert line["ok"] and line["result"]["ops"]["topk"]["count"] == 6
    finally:
        svc.close()


def test_stats_carries_live_latency_quantiles(hin, metapath):
    get_registry().reset()
    svc = _service(hin, metapath, "numpy", max_batch=4)
    try:
        for row in (3, 3, 3, 8):
            svc.topk_index(row, k=4)
        stats = svc.stats()
        lat = stats["obs"]["latency"]
        assert lat["dispatch"]["count"] == 2  # rows 3 and 8, cold
        assert lat["hit_result"]["count"] == 2  # row 3 repeats
        for entry in lat.values():
            assert entry["p50_ms"] <= entry["p99_ms"]
        assert stats["obs"]["metrics"] is True
    finally:
        svc.close()


def test_runtime_events_counted_in_registry(tmp_path):
    from distributed_pathsim_tpu.utils.logging import runtime_event

    get_registry().reset()
    runtime_event("obs_count_check", echo=False, a=1)
    runtime_event("obs_count_check", echo=False, a=2)
    cell = (
        get_registry()
        .counter("dpathsim_events_total")
        .labels(event="obs_count_check")
    )
    assert cell.get() == 2


# -- telemetry discipline lint (satellite: static analysis, tier-1) -------


def test_lint_telemetry():
    import pathlib
    import sys

    repo = pathlib.Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo / "scripts"))
    try:
        import lint_telemetry
    finally:
        sys.path.pop(0)
    violations = lint_telemetry.scan_package()
    assert not violations, "\n".join(v.render() for v in violations)


# -- obs smoke (satellite: CI gate, non-slow) -----------------------------


def test_bench_obs_smoke(tmp_path):
    """``make obs-smoke`` in-process: zero additional steady-state XLA
    compiles under every obs arm, connected traces in both tracing
    arms, head sampling genuinely suppressing span creation, and
    absolute added cost per fully-traced request under 1 ms — all arms
    interleaved against the obs-off baseline."""
    import pathlib
    import sys

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import bench_serving

    result = bench_serving.run_obs_smoke(str(tmp_path / "obs.json"))
    assert all(result["smoke_checks"].values()), result["smoke_checks"]
    audit = result["arms"]["traced"]["trace_audit"]
    assert audit["broken_parent_links"] == 0
    assert audit["unlinked_request_traces"] == 0
    assert (tmp_path / "obs.json").exists()
