"""Neural PathSim: training converges, sharded step == single-device step."""

import jax
import numpy as np
import pytest

from distributed_pathsim_tpu.data.synthetic import synthetic_hin
from distributed_pathsim_tpu.models.neural import NeuralPathSim
from distributed_pathsim_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def hin():
    return synthetic_hin(200, 300, 16, seed=5)


def test_training_reduces_loss(hin):
    model = NeuralPathSim(hin, "APVPA", dim=32, hidden=64, lr=3e-3, seed=0)
    losses = model.train(steps=60, batch_size=256, seed=0)
    assert losses[-1] < losses[0] * 0.5  # clear convergence
    e = model.embeddings()
    assert e.shape == (200, 32)


def test_predictions_correlate_with_exact(hin):
    """Quality gates on the signal that matters: correlation on pairs with
    nonzero exact score (random pairs are ~all zeros and only measure
    noise floor) and top-k ranking recall vs the exact backend."""
    model = NeuralPathSim(hin, "APVPA", dim=32, hidden=64, lr=3e-3, seed=0)
    model.train(steps=600, batch_size=1024, seed=1)

    exact = model.exact_scores()
    rng = np.random.default_rng(2)
    ii, jj = np.nonzero(exact > 0)
    sel = rng.integers(0, len(ii), size=500)
    corr = np.corrcoef(
        model.predict_pairs(ii[sel], jj[sel]), exact[ii[sel], jj[sel]]
    )[0, 1]
    assert corr > 0.8, corr

    e = model.embeddings()
    sims = e @ e.T
    masked = exact.copy()
    np.fill_diagonal(masked, -np.inf)
    np.fill_diagonal(sims, -np.inf)
    recalls = []
    for i in range(exact.shape[0]):
        npos = int((masked[i] > 0).sum())
        if npos == 0:
            continue
        k = min(10, npos)
        top_exact = set(np.argsort(-masked[i])[:k].tolist())
        top_pred = set(np.argsort(-sims[i])[:k].tolist())
        recalls.append(len(top_exact & top_pred) / k)
    assert np.mean(recalls) > 0.5, np.mean(recalls)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_step_matches_single_device(hin):
    single = NeuralPathSim(hin, "APVPA", dim=16, hidden=32, seed=3)
    sharded = NeuralPathSim(
        hin, "APVPA", dim=16, hidden=32, seed=3, mesh=make_mesh(8)
    )
    l1 = single.train(steps=5, batch_size=256, seed=7)
    l2 = sharded.train(steps=5, batch_size=256, seed=7)
    np.testing.assert_allclose(l1, l2, rtol=1e-4)
    np.testing.assert_allclose(
        single.embeddings(), sharded.embeddings(), atol=1e-5
    )


def test_asymmetric_rejected(hin):
    with pytest.raises(ValueError, match="symmetric"):
        NeuralPathSim(hin, "APV")


def test_pair_scores_match_dense_oracle(hin):
    """On-demand exact targets == the dense score matrix, pairwise."""
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    exact = model.exact_scores()
    rng = np.random.default_rng(3)
    i = rng.integers(0, 200, size=300)
    j = rng.integers(0, 200, size=300)
    np.testing.assert_allclose(model.pair_scores(i, j), exact[i, j], atol=1e-12)


def test_exact_scores_guarded(hin, monkeypatch):
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    monkeypatch.setattr(NeuralPathSim, "_DENSE_SCORES_MAX_ENTRIES", 100)
    with pytest.raises(MemoryError, match="pair_scores"):
        model.exact_scores()


def test_embedding_cache_invalidated_by_training(hin):
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    e0 = model.embeddings()
    assert model.embeddings() is e0  # cached, not recomputed
    model.train(steps=1, batch_size=64, seed=0)
    e1 = model.embeddings()
    assert e1 is not e0
    assert not np.allclose(e0, e1)


def test_embedding_cache_is_read_only(hin):
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    e = model.embeddings()
    with pytest.raises(ValueError):
        e[0, 0] = 99.0


def test_save_load_roundtrip(hin, tmp_path):
    model = NeuralPathSim(hin, "APVPA", dim=16, hidden=32, lr=3e-3, seed=0)
    model.train(steps=20, batch_size=256, seed=0)
    p = str(tmp_path / "model.npz")
    model.save(p)

    # inference-only restore: no HIN needed
    loaded = NeuralPathSim.load(p)
    np.testing.assert_allclose(loaded.embeddings(), model.embeddings(), atol=1e-6)
    assert loaded.state.step == model.state.step
    assert loaded.topk(3, k=5) == model.topk(3, k=5)

    # restore with HIN re-attaches the compiled metapath
    loaded2 = NeuralPathSim.load(p, hin=hin)
    assert loaded2.metapath.is_symmetric


def test_save_load_resume_training(hin, tmp_path):
    """A loaded model must continue training exactly like the original
    (same optimizer state, same step stream)."""
    a = NeuralPathSim(hin, "APVPA", dim=16, hidden=32, lr=3e-3, seed=0)
    a.train(steps=10, batch_size=256, seed=0)
    p = str(tmp_path / "model.npz")
    a.save(p)
    b = NeuralPathSim.load(p)
    la = a.train(steps=5, batch_size=256, seed=42)
    lb = b.train(steps=5, batch_size=256, seed=42)
    np.testing.assert_allclose(la, lb, rtol=1e-5)


def test_struct_index_approximates_scores(hin):
    """φ(i)·φ(j) must reproduce exact scores within the quadrature's
    uniform RELATIVE error bound — no training involved."""
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    exact = model.exact_scores()
    phi = model.struct_embeddings()
    approx = (phi @ phi.T).astype(np.float64)
    ii, jj = np.nonzero(exact > 0)
    rel = np.abs(approx[ii, jj] - exact[ii, jj]) / exact[ii, jj]
    assert rel.max() < 0.1, rel.max()  # m=12 measured ~7% worst-case


def test_struct_rerank_recall_is_near_perfect(hin):
    """The analytic index + exact rerank: recall@k vs the exact ranking
    (the learned tower plays no part)."""
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    exact = model.exact_scores()
    masked = exact.copy()
    np.fill_diagonal(masked, -np.inf)
    recalls = []
    for i in range(0, 200, 7):
        npos = int((masked[i] > 0).sum())
        if npos == 0:
            continue
        k = min(10, npos)
        kth = np.sort(masked[i])[::-1][k - 1]
        got = {t for t, _ in model.topk_rerank(i, k=k, candidates=50,
                                               index="struct")}
        recalls.append(sum(masked[i][t] >= kth for t in got) / k)
    assert np.mean(recalls) >= 0.99, np.mean(recalls)


def test_struct_index_untouched_by_training(hin):
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    before = model.topk_struct(3, k=5)
    model.train(steps=2, batch_size=64, seed=0)
    assert model.topk_struct(3, k=5) == before


def test_save_load_preserves_scale_and_quadrature(hin, tmp_path):
    """target_scale and the quadrature are restored verbatim, not
    recomputed from the f32-cast stored C (ADVICE r03)."""
    model = NeuralPathSim(hin, "APVPA", dim=16, hidden=32, seed=0)
    model.train(steps=5, batch_size=256, seed=0)
    p = str(tmp_path / "m.npz")
    model.save(p)
    loaded = NeuralPathSim.load(p)
    assert loaded.target_scale == model.target_scale
    np.testing.assert_array_equal(loaded._quad_t, model._quad_t)
    np.testing.assert_array_equal(loaded._quad_w, model._quad_w)
    assert loaded.topk_struct(3, k=5) == model.topk_struct(3, k=5)


def test_rerank_rejects_unknown_index(hin):
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    with pytest.raises(ValueError, match="unknown index"):
        model.topk_rerank(0, index="bogus")


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_hard_pool_rows_shuffled_under_mesh(hin):
    """ADVICE r5: hard-pool sources are assembled at the FRONT of the
    batch; under a dp mesh (contiguous source-axis shards) they must be
    shuffled so they don't all land on the low-index devices."""
    model = NeuralPathSim(
        hin, "APVPA", dim=8, hidden=16, seed=0, mesh=make_mesh(8)
    )
    pool_src = np.arange(190, 200)
    model.set_hard_pool(pool_src, np.tile(np.arange(4), (10, 1)))
    b = 16  # batch_size 512 / SLATE 32
    hard_rows = int(round(b * model.HARD_FRAC))
    src, cand, tgt = model.sample_batch(512, np.random.default_rng(0))
    assert src.shape[0] == b and len(cand) == len(src) == len(tgt)
    in_pool = np.isin(src, pool_src)
    assert in_pool.sum() >= hard_rows  # pool rows actually drawn
    # the pool draw must NOT sit as the exact front block (the
    # un-shuffled layout): fixed seed → deterministic assertion
    front_block_only = (
        in_pool[:hard_rows].all() and not in_pool[hard_rows:].any()
    )
    assert not front_block_only


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_small_batch_rounds_up(hin):
    """batch_size below SLATE·n_devices must train (source axis rounds
    up to a device multiple), not crash the dp-sharding divisibility."""
    model = NeuralPathSim(
        hin, "APVPA", dim=8, hidden=16, seed=3, mesh=make_mesh(8)
    )
    losses = model.train(steps=2, batch_size=64, seed=1)
    assert len(losses) == 2 and all(np.isfinite(losses))


def test_diagonal_variant_indexes(hin):
    """Both indexes serve textbook PathSim: the struct map approximates
    the diagonal-variant scores and save/load preserves the variant."""
    import tempfile

    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0,
                          variant="diagonal")
    exact = model.exact_scores()  # diagonal-variant matrix
    # cross-check against the generic score_matrix oracle
    from distributed_pathsim_tpu.ops.pathsim import score_matrix

    m = model._c64 @ model._c64.T
    np.testing.assert_allclose(
        exact, score_matrix(m, variant="diagonal", xp=np), atol=1e-12
    )
    phi = model.struct_embeddings()
    approx = (phi @ phi.T).astype(np.float64)
    ii, jj = np.nonzero(exact > 0)
    rel = np.abs(approx[ii, jj] - exact[ii, jj]) / exact[ii, jj]
    assert rel.max() < 0.1, rel.max()
    with tempfile.TemporaryDirectory() as td:
        p = f"{td}/m.npz"
        model.save(p)
        loaded = NeuralPathSim.load(p)
        assert loaded.variant == "diagonal"
        np.testing.assert_array_equal(loaded._d, model._d)


def test_unknown_variant_rejected(hin):
    with pytest.raises(ValueError, match="unknown PathSim variant"):
        NeuralPathSim(hin, "APVPA", dim=8, hidden=16, variant="bogus")


# -- factorized struct queries + exact-teacher mining (r05) ---------------


def test_struct_sims_matches_materialized_phi(hin):
    """The factorized per-source struct query (O(N·V), no [N, m·V] map)
    must agree with the materialized φ·φ inner product — same sum,
    different association order, so only f32 round-off apart."""
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    phi = model.struct_embeddings()
    for i in (0, 7, 113):
        ref = (phi @ phi[i]).astype(np.float64)
        got = model.struct_sims(i)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-9)


def test_mined_candidates_are_exact_topk(hin):
    """mine_hard_candidates must return each source's true exact top-k
    (up to score ties at the boundary) with the source excluded."""
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    exact = model.exact_scores()
    k = 8
    src, cand = model.mine_hard_candidates(16, k=k, seed=3, chunk=5)
    assert src.shape == (16,) and cand.shape == (16, k)
    assert len(np.unique(src)) == 16
    for row, s in enumerate(src):
        assert int(s) not in set(int(c) for c in cand[row])
        scores = exact[s].copy()
        scores[s] = -np.inf
        kth = np.sort(scores)[::-1][k - 1]
        # every mined candidate scores at least the k-th best (tie-safe)
        assert all(scores[c] >= kth for c in cand[row])


def test_mining_respects_exclusions(hin):
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    exclude = np.arange(0, 200, 2)  # all even ids
    src, _ = model.mine_hard_candidates(40, k=4, seed=0, exclude=exclude)
    assert not np.isin(src, exclude).any()


def test_hard_pool_shapes_validated(hin):
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    with pytest.raises(ValueError, match="hard pool"):
        model.set_hard_pool(np.arange(4), np.zeros((3, 2), int))


def test_hard_pool_slates_contain_mined_candidates(hin):
    """Pool rows must actually draw slate entries from their mined
    candidate lists (the distillation mechanism, not just plumbing)."""
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    src_pool, cand_pool = model.mine_hard_candidates(8, k=8, seed=1)
    model.set_hard_pool(src_pool, cand_pool)
    by_src = {int(s): set(map(int, cand_pool[r]))
              for r, s in enumerate(src_pool)}
    rng = np.random.default_rng(0)
    src, cand, tgt = model.sample_batch(256, rng)
    s = model.SLATE
    n_pos = s // 2
    hard_rows = int(round(len(src) * model.HARD_FRAC))
    assert hard_rows >= 1
    n_hard = min(cand_pool.shape[1], s - n_pos - max(1, s // 8))
    for r in range(hard_rows):
        assert int(src[r]) in by_src
        hard_slots = set(map(int, cand[r][n_pos:n_pos + n_hard]))
        # the overwritten slots are all mined candidates of that source
        assert hard_slots <= by_src[int(src[r])]
    # non-pool rows keep uniform sources (statistically: at least one
    # source outside the 8-element pool among the remaining rows)
    assert any(int(x) not in by_src for x in src[hard_rows:])
    assert tgt.shape == (len(src), s)


def test_training_with_hard_pool_converges(hin):
    model = NeuralPathSim(hin, "APVPA", dim=32, hidden=64, lr=3e-3, seed=0)
    src_pool, cand_pool = model.mine_hard_candidates(64, k=16, seed=2)
    model.set_hard_pool(src_pool, cand_pool)
    losses = model.train(steps=60, batch_size=256, seed=0)
    assert losses[-1] < losses[0] * 0.5
    model.clear_hard_pool()
    assert model._hard_src is None


def test_hard_pool_rejects_out_of_range_indexes(hin):
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    with pytest.raises(ValueError, match="out of range"):
        model.set_hard_pool(np.array([5, 1000]), np.zeros((2, 3), int))
    with pytest.raises(ValueError, match="out of range"):
        model.set_hard_pool(np.array([5, 6]), np.array([[0, -1, 2]] * 2))
    with pytest.raises(ValueError, match="integer"):
        model.set_hard_pool(np.array([5.0, 6.0]), np.zeros((2, 3), int))


def test_tiny_batch_keeps_one_hard_row(hin):
    model = NeuralPathSim(hin, "APVPA", dim=8, hidden=16, seed=0)
    src_pool, cand_pool = model.mine_hard_candidates(4, k=4, seed=0)
    model.set_hard_pool(src_pool, cand_pool)
    rng = np.random.default_rng(0)
    src, _, _ = model.sample_batch(model.SLATE, rng)  # b == 1
    assert int(src[0]) in set(map(int, src_pool))
