"""GEXF loader tests — counts from SURVEY.md §2 (C9), measured ground truth."""

import numpy as np


def test_counts(dblp_small):
    assert len(dblp_small.vertices) == 1866
    assert len(dblp_small.edges) == 2266
    assert dblp_small.counts() == {
        "author": 770,
        "paper": 1001,
        "venue": 85,
        "topic": 10,
    }


def test_edge_relationships(dblp_small):
    rels = {}
    for e in dblp_small.edges:
        rels[e.relationship] = rels.get(e.relationship, 0) + 1
    assert rels == {"author_of": 1265, "submit_at": 1001}


def test_find_by_label(dblp_small):
    # Didier Dubois is the first author in file order (SURVEY.md Appendix A).
    assert dblp_small.find_node_id_by_label("Didier Dubois") == "author_395340"
    assert dblp_small.find_node_id_by_label("Jiawei Han") is None  # not in small


def test_schema_inference(dblp_small):
    from distributed_pathsim_tpu.data.schema import infer_schema

    schema = infer_schema(dblp_small)
    assert schema.relations == {
        "author_of": ("author", "paper"),
        "submit_at": ("paper", "venue"),
    }
    # topic nodes are isolated but still typed
    assert "topic" in schema.node_types


def test_encoding_roundtrip(dblp_small, dblp_small_hin):
    hin = dblp_small_hin
    assert hin.type_size("author") == 770
    assert hin.type_size("paper") == 1001
    assert hin.type_size("venue") == 85
    ap = hin.block("author_of")
    pv = hin.block("submit_at")
    assert ap.shape == (770, 1001) and ap.nnz == 1265
    assert pv.shape == (1001, 85) and pv.nnz == 1001
    # id↔index round trip
    idx = hin.indices["author"]
    for i in (0, 100, 769):
        assert idx.index_of[idx.ids[i]] == i
    assert hin.find_index_by_label("author", "Didier Dubois") == 0


def test_vertex_tuple_view_matches_reference_shape(dblp_small):
    tup = dblp_small.vertex_tuples()[0]
    assert len(tup) == 3  # (id, label, node_type)
    et = dblp_small.edge_tuples()[0]
    assert len(et) == 3  # (src, dst, relationship)


def test_synthetic_roundtrip(tmp_path):
    from distributed_pathsim_tpu.data.gexf import read_gexf
    from distributed_pathsim_tpu.data.encode import encode_hin
    from distributed_pathsim_tpu.data.synthetic import synthetic_hin, write_gexf

    hin = synthetic_hin(50, 80, 7, n_topics=3, seed=1, materialize_ids=True)
    p = tmp_path / "syn.gexf"
    write_gexf(hin, str(p))
    g2 = read_gexf(str(p), use_native=False)
    hin2 = encode_hin(g2)
    for rel in hin.blocks:
        b1, b2 = hin.block(rel), hin2.block(rel)
        d1 = b1.to_dense()
        d2 = b2.to_dense()
        assert b1.shape == b2.shape
        np.testing.assert_array_equal(d1, d2)


def test_lazy_synthetic_reports_size():
    from distributed_pathsim_tpu.data.synthetic import synthetic_hin

    hin = synthetic_hin(1000, 1400, 30, seed=3)  # materialize_ids=False
    assert hin.type_size("author") == 1000
    assert hin.type_size("paper") == 1400
