"""Delta-ingestion engine: headroom, delta algebra, parity, serving.

The load-bearing guarantee (ISSUE 3 acceptance): a delta-patched
backend is BIT-identical to a full re-encode + rebuild — exact integer
path counts plus the shared f64 normalize/select — on every backend,
including after delta sequences that force the rebuild fallback. The
property test below drives random DeltaBatch sequences (edge adds,
edge removes, node appends, headroom overflow) through every backend's
``apply_delta`` and compares against a from-scratch build of the same
logical graph.
"""

from __future__ import annotations

import numpy as np
import pytest

from distributed_pathsim_tpu.backends.base import (
    DeltaUnsupported,
    create_backend,
)
from distributed_pathsim_tpu.data import delta as dl
from distributed_pathsim_tpu.data.synthetic import synthetic_hin
from distributed_pathsim_tpu.ops import sparse as sp
from distributed_pathsim_tpu.ops.metapath import compile_metapath
from distributed_pathsim_tpu.serving import (
    PathSimService,
    ServeConfig,
    chain_fingerprint,
    graph_fingerprint,
)
from distributed_pathsim_tpu.serving.cache import HotTileCache, ResultCache

BACKENDS = ["numpy", "jax", "jax-sparse", "jax-sharded"]


def _base_hin(headroom: float = 0.3):
    # materialized ids so node appends go through the id path (the
    # serving wire format's shape)
    return dl.with_headroom(
        synthetic_hin(96, 150, 7, seed=3, materialize_ids=True),
        headroom,
    )


def _random_delta(hin, rng, n_changes=12, append=False):
    """Random adds/removes over BOTH half-chain blocks (exercises both
    product-rule terms), optionally appending one author wired in by an
    added edge."""
    edges = []
    per_rel = max(n_changes // 2, 2)
    for rel in ("author_of", "submit_at"):
        b = hin.blocks[rel]
        n_src = hin.type_size(b.src_type)
        n_dst = hin.type_size(b.dst_type)
        n_rem = per_rel // 2
        rem_i = rng.choice(b.nnz, size=n_rem, replace=False)
        removes = np.stack([b.rows[rem_i], b.cols[rem_i]], axis=1)
        # removed pairs stay excluded from adds: add∩remove is rejected
        existing = set(zip(b.rows.tolist(), b.cols.tolist()))
        adds = []
        while len(adds) < per_rel - n_rem:
            e = (int(rng.integers(0, n_src)), int(rng.integers(0, n_dst)))
            if e not in existing:
                existing.add(e)
                adds.append(e)
        edges.append(dl.edge_delta(rel, add=adds, remove=removes))
    nodes = ()
    if append:
        n_auth = hin.type_size("author")
        nodes = (
            dl.NodeAppend(node_type="author", ids=(f"author_{n_auth}",)),
        )
        edges[0] = dl.edge_delta(
            "author_of",
            add=np.concatenate(
                [
                    edges[0].add,
                    [[n_auth, int(rng.integers(0, hin.type_size("paper")))]],
                ]
            ),
            remove=edges[0].remove,
        )
    return dl.DeltaBatch(edges=tuple(edges), nodes=nodes)


# -- headroom: padding is semantically invisible --------------------------


@pytest.mark.parametrize("backend_name", BACKENDS)
def test_headroom_is_bit_invisible(backend_name):
    """A capacity-padded build returns exactly what the unpadded build
    returns — scores, walks, and top-k tie order."""
    raw = synthetic_hin(96, 150, 7, seed=3, materialize_ids=True)
    padded = dl.with_headroom(raw, 0.3)
    mp = compile_metapath("APVPA", raw.schema)
    b_raw = create_backend(backend_name, raw, mp)
    b_pad = create_backend(backend_name, padded, mp)
    rows = np.arange(raw.type_size("author"))
    assert np.array_equal(
        b_pad.scores_rows(rows), b_raw.scores_rows(rows)
    )
    assert np.array_equal(b_pad.global_walks(), b_raw.global_walks())
    pv, pi = b_pad.topk_rows(rows, k=6)
    rv, ri = b_raw.topk_rows(rows, k=6)
    assert np.array_equal(pv, rv)
    assert np.array_equal(pi, ri)


def test_strip_headroom_roundtrip():
    raw = synthetic_hin(40, 70, 5, seed=1, materialize_ids=True)
    back = dl.strip_headroom(dl.with_headroom(raw, 0.5))
    for rel, b in raw.blocks.items():
        assert back.blocks[rel].shape == b.shape
        assert np.array_equal(back.blocks[rel].rows, b.rows)
    # same logical content → same content hash (the fingerprint hashes
    # logical sizes and COO, never the padding)
    assert graph_fingerprint(back) == graph_fingerprint(raw)


# -- the property test: random delta sequences, all backends --------------


@pytest.mark.parametrize("backend_name", BACKENDS)
def test_delta_sequence_parity(backend_name):
    """Random DeltaBatch sequence (adds + removes every step, a node
    append every other step) absorbed via apply_delta must stay
    bit-identical to a full rebuild of the same logical graph at every
    step — scores, walks, and top-k (values AND tie order)."""
    rng = np.random.default_rng(11)
    hin = _base_hin()
    mp = compile_metapath("APVPA", hin.schema)
    b = create_backend(backend_name, hin, mp)
    for step in range(4):
        delta = _random_delta(hin, rng, n_changes=12, append=step % 2 == 0)
        plan = dl.plan_delta(hin, delta, mp, max_delta_fraction=0.5)
        assert not plan.fallback, plan.reason
        b.apply_delta(plan)
        hin = plan.hin_new
        fresh = create_backend(backend_name, dl.strip_headroom(hin), mp)
        rows = np.arange(hin.type_size("author"))
        assert np.array_equal(
            b.scores_rows(rows), fresh.scores_rows(rows)
        ), (backend_name, step)
        assert np.array_equal(b.global_walks(), fresh.global_walks())
        bv, bi = b.topk_rows(rows, k=5)
        fv, fi = fresh.topk_rows(rows, k=5)
        assert np.array_equal(bv, fv), (backend_name, step)
        assert np.array_equal(bi, fi), (backend_name, step)


def test_jax_sparse_tile_shape_survives_appends():
    """The zero-recompile contract's shape half: a node append must not
    change the jax-sparse tile geometry (tile shapes are what the
    tiled programs specialize on — tied to capacity, not logical n)."""
    rng = np.random.default_rng(7)
    hin = _base_hin()
    mp = compile_metapath("APVPA", hin.schema)
    b = create_backend("jax-sparse", hin, mp)
    shape_before = (b.tiled.tile_rows, b.tiled.n_tiles, b.tiled._max_nnz)
    plan = dl.plan_delta(
        hin, _random_delta(hin, rng, append=True), mp, max_delta_fraction=0.5
    )
    assert not plan.fallback
    b.apply_delta(plan)
    assert (
        b.tiled.tile_rows, b.tiled.n_tiles, b.tiled._max_nnz
    ) == shape_before


def test_delta_add_then_remove_restores_scores():
    """Adding a batch and then removing exactly those edges returns the
    scores to the original — the delta algebra has a true inverse."""
    hin = _base_hin()
    mp = compile_metapath("APVPA", hin.schema)
    b = create_backend("numpy", hin, mp)
    rows = np.arange(hin.type_size("author"))
    before = b.scores_rows(rows).copy()
    blk = hin.blocks["author_of"]
    existing = set(zip(blk.rows.tolist(), blk.cols.tolist()))
    adds = [
        [a, p]
        for a in range(10)
        for p in (147, 148, 149)
        if (a, p) not in existing
    ][:3]
    fwd = dl.DeltaBatch(edges=(dl.edge_delta("author_of", add=adds),))
    plan = dl.plan_delta(hin, fwd, mp, max_delta_fraction=0.5)
    b.apply_delta(plan)
    assert not np.array_equal(b.scores_rows(rows), before)
    rev = dl.DeltaBatch(edges=(dl.edge_delta("author_of", remove=adds),))
    plan2 = dl.plan_delta(plan.hin_new, rev, mp, max_delta_fraction=0.5)
    b.apply_delta(plan2)
    assert np.array_equal(b.scores_rows(rows), before)


# -- fallback verdicts ----------------------------------------------------


def test_headroom_overflow_forces_fallback():
    """Appends past the capacity reserve change array shapes — the plan
    must say rebuild, and apply_delta must refuse the plan."""
    hin = _base_hin(headroom=0.0)  # min_slots=8 reserve only
    mp = compile_metapath("APVPA", hin.schema)
    n = hin.type_size("author")
    app = dl.NodeAppend(
        node_type="author",
        ids=tuple(f"author_{n + i}" for i in range(20)),
    )
    plan = dl.plan_delta(hin, dl.DeltaBatch(nodes=(app,)), mp)
    assert plan.fallback and "headroom" in plan.reason
    # the delta-applied HIN is still correct, just re-padded
    assert plan.hin_new.type_size("author") == n + 20
    b = create_backend("numpy", hin, mp)
    with pytest.raises(ValueError, match="rebuild"):
        b.apply_delta(plan)


def test_oversize_delta_and_asymmetric_chain_fall_back():
    hin = _base_hin()
    mp = compile_metapath("APVPA", hin.schema)
    d = _random_delta(hin, np.random.default_rng(0), n_changes=40)
    plan = dl.plan_delta(hin, d, mp, max_delta_fraction=0.0001)
    assert plan.fallback and "exceeds" in plan.reason
    apv = compile_metapath("APV", hin.schema)
    plan2 = dl.plan_delta(hin, _random_delta(hin, np.random.default_rng(1)),
                          apv)
    assert plan2.fallback and "not symmetric" in plan2.reason


def test_malformed_deltas_are_rejected():
    """Exactness depends on the graph staying simple: duplicate adds,
    phantom removes, and range violations must fail loudly."""
    hin = _base_hin()
    b = hin.blocks["author_of"]
    e0 = (int(b.rows[0]), int(b.cols[0]))
    with pytest.raises(ValueError, match="already exists"):
        dl.apply_delta(
            hin, dl.DeltaBatch(edges=(dl.edge_delta("author_of", add=[e0]),))
        )
    with pytest.raises(ValueError, match="nonexistent"):
        dl.apply_delta(
            hin,
            dl.DeltaBatch(
                edges=(dl.edge_delta("author_of", remove=[[95, 149]]),)
            ),
        )
    with pytest.raises(ValueError, match="duplicate"):
        dl.apply_delta(
            hin,
            dl.DeltaBatch(
                edges=(
                    dl.edge_delta("author_of", add=[[0, 149], [0, 149]]),
                )
            ),
        )
    with pytest.raises(ValueError, match="out of range"):
        dl.apply_delta(
            hin,
            dl.DeltaBatch(
                edges=(dl.edge_delta("author_of", add=[[96, 0]]),)
            ),
        )
    with pytest.raises(ValueError, match="unknown relationship"):
        dl.apply_delta(
            hin, dl.DeltaBatch(edges=(dl.edge_delta("cites", add=[[0, 0]]),))
        )
    with pytest.raises(ValueError, match="already present"):
        dl.apply_delta(
            hin,
            dl.DeltaBatch(
                nodes=(dl.NodeAppend(node_type="author", ids=("author_0",)),)
            ),
        )


# -- affected-rows soundness ----------------------------------------------


def test_affected_rows_is_sound_superset():
    """Every source row whose f64 score row changes under the delta (in
    either denominator variant) must be in plan.affected_rows."""
    rng = np.random.default_rng(23)
    hin = _base_hin()
    mp = compile_metapath("APVPA", hin.schema)
    for _ in range(3):
        delta = _random_delta(hin, rng, n_changes=10)
        plan = dl.plan_delta(hin, delta, mp, max_delta_fraction=0.5)
        assert not plan.fallback
        old = create_backend("numpy", dl.strip_headroom(hin), mp)
        new = create_backend("numpy", dl.strip_headroom(plan.hin_new), mp)
        rows = np.arange(hin.type_size("author"))
        aff = set(plan.affected_rows.tolist())
        for variant in ("rowsum", "diagonal"):
            changed = np.flatnonzero(
                np.any(
                    old.scores_rows(rows, variant=variant)
                    != new.scores_rows(rows, variant=variant),
                    axis=1,
                )
            )
            assert set(changed.tolist()) <= aff, variant
        hin = plan.hin_new


# -- fingerprint chaining -------------------------------------------------


def test_fingerprint_chains_without_rehashing():
    hin = _base_hin()
    mp = compile_metapath("APVPA", hin.schema)
    base = graph_fingerprint(hin)
    assert graph_fingerprint(hin) == base  # memoized, stable
    d = _random_delta(hin, np.random.default_rng(5))
    plan = dl.plan_delta(hin, d, mp, max_delta_fraction=0.5)
    assert plan.fingerprint == chain_fingerprint(base, d.digest())
    assert plan.fingerprint.startswith("~") and plan.fingerprint != base
    # the child HIN carries the chained fp — no block is ever re-hashed
    assert graph_fingerprint(plan.hin_new) == plan.fingerprint
    # delta identity is content-addressed: same records → same chain
    assert d.digest() == _random_delta(hin, np.random.default_rng(5)).digest()
    # id-based node appends are part of the identity (labels default to
    # ids — an empty-labels append must NOT hash like no append at all)
    empty = dl.DeltaBatch().digest()
    app_a = dl.DeltaBatch(
        nodes=(dl.NodeAppend(node_type="author", ids=("x",)),)
    )
    app_b = dl.DeltaBatch(
        nodes=(dl.NodeAppend(node_type="author", ids=("y",)),)
    )
    assert app_a.digest() != empty
    assert app_a.digest() != app_b.digest()


# -- row-granular cache invalidation --------------------------------------


def test_result_cache_purge_rows():
    c = ResultCache(capacity=32)
    for row in range(8):
        c.put(("fp", "APVPA", "rowsum", 0, row, 5),
              np.arange(5.0), np.arange(5))
    assert c.purge_rows([2, 5, 99]) == 2
    assert len(c) == 6
    assert c.get(("fp", "APVPA", "rowsum", 0, 2, 5)) is None
    assert c.get(("fp", "APVPA", "rowsum", 0, 3, 5)) is not None


def test_hot_tile_cache_purge_rows():
    c = HotTileCache(budget_bytes=1 << 20, tile_rows=4)
    epoch = ("fp", "APVPA", "rowsum", 0)
    for row in range(8):
        c.put_row(epoch, row, np.full(16, float(row)))
    before = c.bytes_used
    assert c.purge_rows([1, 6]) == 2
    assert c.get_row(epoch, 1) is None
    assert c.get_row(epoch, 2) is not None
    assert c.bytes_used < before


# -- serving integration --------------------------------------------------


def _service(hin, mp, backend_name="numpy", **cfg):
    cfg.setdefault("max_wait_ms", 5.0)
    cfg.setdefault("warm", False)
    return PathSimService(
        create_backend(backend_name, hin, mp), config=ServeConfig(**cfg)
    )


def test_service_update_keeps_unaffected_rows_cached():
    """The row-granular contract: after update, unaffected rows answer
    from tier 1; affected rows recompute and match a fresh build."""
    hin = _base_hin()
    mp = compile_metapath("APVPA", hin.schema)
    svc = _service(hin, mp)
    try:
        for r in range(40):
            svc.topk_index(r, k=5)
        delta = _random_delta(svc.hin, np.random.default_rng(9))
        info = svc.update(delta)
        assert info["mode"] == "delta"
        assert info["delta_seq"] == 1
        assert info["fingerprint"].startswith("~")
        affected = set(range(40)) & set(
            dl.plan_delta(hin, delta, mp, max_delta_fraction=0.5)
            .affected_rows.tolist()
        )
        unaffected = sorted(set(range(40)) - affected)
        h0 = svc.stats()["result_cache"]["hits"]
        for r in unaffected:
            svc.topk_index(r, k=5)
        assert (
            svc.stats()["result_cache"]["hits"] - h0 == len(unaffected)
        ), "unaffected rows must all hit tier 1"
        # affected rows give the NEW answer, equal to a fresh build
        fresh = create_backend(
            "numpy", dl.strip_headroom(svc.hin), mp
        )
        for r in sorted(affected)[:5]:
            vals, idxs = svc.topk_index(r, k=5)
            fv, fi = fresh.topk_row(r, k=5)
            assert np.array_equal(vals, fv) and np.array_equal(idxs, fi)
    finally:
        svc.close()


def test_service_update_rebuild_fallback_parity():
    """A delta past the threshold rebuilds (mode='rebuild'), and the
    swapped-in backend serves answers identical to a fresh build."""
    hin = _base_hin()
    mp = compile_metapath("APVPA", hin.schema)
    svc = _service(hin, mp, delta_threshold=1e-6)
    try:
        delta = _random_delta(svc.hin, np.random.default_rng(4))
        info = svc.update(delta)
        assert info["mode"] == "rebuild"
        assert svc.stats()["delta"]["rebuilds"] == 1
        fresh = create_backend("numpy", dl.strip_headroom(svc.hin), mp)
        for r in (0, 7, 33):
            vals, idxs = svc.topk_index(r, k=5)
            fv, fi = fresh.topk_row(r, k=5)
            assert np.array_equal(vals, fv) and np.array_equal(idxs, fi)
    finally:
        svc.close()


def test_protocol_update_op():
    """The JSONL ``update`` op end-to-end: id-level records resolve,
    appended nodes are queryable, the response carries the accounting."""
    from distributed_pathsim_tpu.serving.protocol import handle_request

    hin = _base_hin()
    mp = compile_metapath("APVPA", hin.schema)
    svc = _service(hin, mp)
    try:
        resp = handle_request(
            svc,
            {
                "id": 1,
                "op": "update",
                "add_nodes": [
                    {"type": "author", "id": "a_new", "label": "A. New"}
                ],
                "add_edges": [
                    {"rel": "author_of", "src": "a_new", "dst": "paper_3"},
                    {"rel": "author_of", "src": "author_0", "dst": "paper_9"},
                ],
                "remove_edges": [
                    {
                        "rel": "author_of",
                        "src_row": int(hin.blocks["author_of"].rows[0]),
                        "dst_row": int(hin.blocks["author_of"].cols[0]),
                    }
                ],
            },
        )
        assert resp["ok"], resp
        assert resp["result"]["mode"] == "delta"
        assert resp["result"]["node_appends"] == 1
        assert svc.n == 97
        # the appended author resolves by id and answers queries
        row = svc.hin.resolve_source("author", node_id="a_new")
        vals, idxs = svc.topk_index(row, k=3)
        assert vals.shape == (3,)
    finally:
        svc.close()


def test_delta_unsupported_surfaces():
    """Backends without a patch path raise DeltaUnsupported (a
    capability miss the service converts into a rebuild)."""
    hin = _base_hin()
    apv = compile_metapath("APV", hin.schema)
    b = create_backend("numpy", hin, apv)  # asymmetric: no half factor
    plan = dl.plan_delta(hin, _random_delta(hin, np.random.default_rng(2)),
                         apv)
    assert plan.fallback  # plan already says rebuild for asymmetric
    # force the backend-level refusal path
    sym_plan = type(plan)(
        delta=plan.delta, hin_old=plan.hin_old, hin_new=plan.hin_new,
        fingerprint=plan.fingerprint, n_edge_changes=plan.n_edge_changes,
        fallback=False, delta_c=None, half_old=None, half_new=None,
        affected_rows=np.empty(0, dtype=np.int64),
    )
    with pytest.raises(DeltaUnsupported):
        b.apply_delta(sym_plan)


# -- CI smoke: the acceptance measurement (make update-smoke) -------------


def test_bench_update_smoke(tmp_path):
    """``make update-smoke`` in-process: ≥10× update-vs-reload, zero
    steady-state XLA compiles (CompileCounter hook), unaffected rows
    retained — the ISSUE 3 acceptance gates on the 2048-author graph."""
    import pathlib
    import sys

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import bench_serving

    result = bench_serving.run_update_smoke(str(tmp_path / "update.json"))
    assert result["smoke_checks"]["speedup_ge_10x"]
    assert result["smoke_checks"]["zero_steady_state_compiles"]
    assert result["smoke_checks"]["unaffected_rows_retained"]
    assert result["steady_state_compiles"] == 0
    assert result["service"]["rebuilds"] == 0
