"""Serving layer: batched row API, coalescer, cache tiers, protocol.

The load-bearing guarantees:

- batched/bucketed dispatch is BIT-identical to the unbatched
  ``topk_row`` path (same scores, same tie ordering) on every backend;
- the coalescer routes each concurrent submitter's result to the right
  future;
- cache tiers hit/miss/invalidate correctly, including across a graph
  reload;
- admission control sheds at the queue bound with a structured event.
"""

from __future__ import annotations

import io
import json
import threading
import time

import numpy as np
import pytest

from distributed_pathsim_tpu.backends.base import create_backend
from distributed_pathsim_tpu.data.synthetic import synthetic_hin
from distributed_pathsim_tpu.ops.metapath import compile_metapath
from distributed_pathsim_tpu.serving import (
    LoadShedError,
    PathSimService,
    ServeConfig,
    graph_fingerprint,
)

BACKENDS = ["numpy", "jax", "jax-sparse", "jax-sharded"]


@pytest.fixture(scope="module")
def hin():
    return synthetic_hin(160, 260, 9, n_topics=4, seed=7)


@pytest.fixture(scope="module")
def metapath(hin):
    return compile_metapath("APVPA", hin.schema)


@pytest.fixture(scope="module")
def oracle(hin, metapath):
    return create_backend("numpy", hin, metapath)


def _service(hin, metapath, backend_name="numpy", **cfg):
    cfg.setdefault("max_wait_ms", 5.0)
    cfg.setdefault("warm", False)  # per-test services: skip warm loops
    backend = create_backend(backend_name, hin, metapath)
    return PathSimService(backend, config=ServeConfig(**cfg))


# -- batched multi-row backend API (satellite: all-backend parity) --------


@pytest.mark.parametrize("backend_name", BACKENDS)
def test_topk_rows_matches_topk_row(hin, metapath, backend_name):
    """Batched topk_rows must agree bit-for-bit (values AND tie order)
    with per-row topk_row — duplicates in the batch included."""
    b = create_backend(backend_name, hin, metapath)
    rows = np.array([0, 3, 17, 99, 3, 159])
    bv, bi = b.topk_rows(rows, k=7)
    for j, r in enumerate(rows):
        sv, si = b.topk_row(int(r), k=7)
        assert np.array_equal(bv[j], sv), (backend_name, r)
        assert np.array_equal(bi[j], si), (backend_name, r)


@pytest.mark.parametrize("backend_name", BACKENDS)
def test_topk_row_matches_argsort_oracle(hin, metapath, backend_name):
    """topk_row's selection = stable argsort of the f64 score row
    (descending score, ascending column among ties)."""
    b = create_backend(backend_name, hin, metapath)
    for r in (0, 42, 111):
        s = np.asarray(b.scores_from_source(r), dtype=np.float64).copy()
        s[r] = -np.inf
        order = np.argsort(-s, kind="stable")[:7]
        vals, idxs = b.topk_row(r, k=7)
        assert np.array_equal(idxs, order)
        assert np.array_equal(vals, s[order])


def test_bucket_padding_never_changes_scores(hin, metapath, oracle):
    """Power-of-two padding is semantically inert: every real row of a
    padded batch equals its unbatched result exactly."""
    from distributed_pathsim_tpu.serving.buckets import (
        bucket_for,
        bucket_ladder,
        pad_rows,
    )

    assert bucket_ladder(32) == (1, 2, 4, 8, 16, 32)
    assert bucket_ladder(5) == (1, 2, 4, 8)
    assert bucket_for(3, (1, 2, 4, 8)) == 4
    with pytest.raises(ValueError):
        bucket_for(9, (1, 2, 4, 8))

    b = create_backend("jax", hin, metapath)
    for rows in ([5], [5, 9, 31], [1, 2, 3, 4, 5]):
        rows = np.asarray(rows)
        bucket = bucket_for(len(rows), bucket_ladder(8))
        padded = pad_rows(rows, bucket)
        assert padded.shape[0] == bucket
        pv, pi = b.topk_rows(padded, k=6)
        for j, r in enumerate(rows):
            sv, si = b.topk_row(int(r), k=6)
            assert np.array_equal(pv[j], sv)
            assert np.array_equal(pi[j], si)


def test_multipath_topk_rows_matches_topk_row(hin):
    from distributed_pathsim_tpu.models.multipath import MultiMetapathScorer

    sc = MultiMetapathScorer(hin, ["APVPA", "APA"])
    rows = np.array([0, 12, 77, 12])
    bv, bi = sc.topk_rows(rows, k=5, weights=[0.7, 0.3])
    for j, r in enumerate(rows):
        sv, si = sc.topk_row(int(r), k=5, weights=[0.7, 0.3])
        assert np.array_equal(bv[j], sv)
        assert np.array_equal(bi[j], si)


# -- coalescer ------------------------------------------------------------


def test_coalescer_concurrent_submitters_route_correctly(
    hin, metapath, oracle
):
    """Concurrent clients through one service: every future resolves to
    ITS row's oracle answer, and coalescing actually happened."""
    svc = _service(hin, metapath, "jax", max_batch=8,
                   cache_entries=0, tile_cache_bytes=0)
    try:
        rows = [i % 60 for i in range(48)]  # includes duplicates
        results: dict[int, tuple] = {}

        def worker(slot, r):
            results[slot] = svc.topk_index(r, k=6)

        threads = [
            threading.Thread(target=worker, args=(slot, r))
            for slot, r in enumerate(rows)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for slot, r in enumerate(rows):
            ov, oi = oracle.topk_row(r, k=6)
            av, ai = results[slot]
            assert np.array_equal(av, ov), (slot, r)
            assert np.array_equal(ai, oi), (slot, r)
        st = svc.stats()["dispatch"]
        assert st["requests"] == len(rows)
        assert st["batches"] < len(rows)  # some coalescing happened
        assert st["shed"] == 0
    finally:
        svc.close()


def test_load_shedding_at_queue_bound(hin, metapath, tmp_path):
    """A full queue sheds immediately with a structured event; admitted
    requests still complete correctly."""
    from distributed_pathsim_tpu.utils.logging import (
        RunLogger,
        set_event_sink,
    )

    svc = _service(hin, metapath, "numpy", max_batch=1, max_wait_ms=0.0,
                   queue_depth=2, cache_entries=0, tile_cache_bytes=0)
    # Make every dispatch slow so the queue actually backs up.
    real = svc.backend.pairwise_rows

    def slow(rows):
        time.sleep(0.05)
        return real(rows)

    svc.backend.pairwise_rows = slow
    metrics = tmp_path / "events.jsonl"
    logger = RunLogger(output_path=None, echo=False,
                       metrics_path=str(metrics))
    set_event_sink(logger)
    try:
        futures, shed = [], 0
        for i in range(20):
            try:
                futures.append((i, svc.submit_topk(i, k=3)))
            except LoadShedError:
                shed += 1
        assert shed > 0
        assert svc.stats()["dispatch"]["shed"] == shed
        for i, fut in futures:
            vals, idxs = fut.result(timeout=30)
            sv, si = svc.backend.topk_row(i, k=3)
            assert np.array_equal(vals, sv) and np.array_equal(idxs, si)
    finally:
        set_event_sink(None)
        logger.close()
        svc.close()
    events = [json.loads(line) for line in metrics.read_text().splitlines()]
    sheds = [e for e in events if e["event"] == "serve_shed"]
    assert sheds and sheds[0]["depth"] == 2


# -- cache tiers ----------------------------------------------------------


def test_result_cache_hit_miss_and_invalidate(hin, metapath):
    svc = _service(hin, metapath, "numpy", max_batch=4)
    try:
        v1, i1 = svc.topk_index(7, k=5)
        s = svc.stats()["result_cache"]
        assert s["misses"] >= 1 and s["hits"] == 0
        v2, i2 = svc.topk_index(7, k=5)
        assert svc.stats()["result_cache"]["hits"] == 1
        assert np.array_equal(v1, v2) and np.array_equal(i1, i2)
        svc.invalidate()
        assert len(svc.result_cache) == 0
        v3, _ = svc.topk_index(7, k=5)
        assert np.array_equal(v3, v1)  # same graph → same answer
    finally:
        svc.close()


def test_tile_cache_serves_other_k_without_dispatch(hin, metapath):
    """Tier 2: a known score row answers a different k with zero new
    dispatches (the k is not in the tile key)."""
    svc = _service(hin, metapath, "numpy", max_batch=4)
    try:
        svc.topk_index(11, k=5)
        batches = svc.stats()["dispatch"]["batches"]
        vals, idxs = svc.topk_index(11, k=9)  # larger k: tier-1 miss
        assert svc.stats()["dispatch"]["batches"] == batches
        sv, si = svc.backend.topk_row(11, k=9)
        assert np.array_equal(vals, sv) and np.array_equal(idxs, si)
        assert svc.stats()["tile_cache"]["hits"] >= 1
    finally:
        svc.close()


def test_cache_invalidation_on_graph_reload(metapath):
    """Reload with a DIFFERENT graph: fingerprint changes, caches
    cleared, answers come from the new graph."""
    hin_a = synthetic_hin(120, 200, 8, n_topics=3, seed=1)
    hin_b = synthetic_hin(120, 200, 8, n_topics=3, seed=2)
    mp = compile_metapath("APVPA", hin_a.schema)
    assert graph_fingerprint(hin_a) != graph_fingerprint(hin_b)
    svc = _service(hin_a, mp, "numpy", max_batch=4)
    try:
        va, _ = svc.topk_index(5, k=5)
        fp_a = svc.stats()["fingerprint"]
        svc.reload(create_backend("numpy", hin_b, mp))
        assert svc.stats()["fingerprint"] != fp_a
        assert len(svc.result_cache) == 0
        vb, ib = svc.topk_index(5, k=5)
        ov, oi = create_backend("numpy", hin_b, mp).topk_row(5, k=5)
        assert np.array_equal(vb, ov) and np.array_equal(ib, oi)
        assert not np.array_equal(va, vb)  # different graph, new answers
    finally:
        svc.close()


def test_scores_index_matches_scores_from_source(hin, metapath, oracle):
    svc = _service(hin, metapath, "numpy", max_batch=2)
    try:
        row = 23
        got = svc.scores_index(row)
        want = oracle.scores_from_source(row)
        assert np.array_equal(got, want)
    finally:
        svc.close()


# -- warm compile (satellite) ---------------------------------------------


def test_warm_compile_cache_emits_bucket_events(hin, metapath, tmp_path):
    from distributed_pathsim_tpu.utils.logging import (
        RunLogger,
        set_event_sink,
    )
    from distributed_pathsim_tpu.utils.xla_flags import warm_compile_cache

    backend = create_backend("jax", hin, metapath)
    metrics = tmp_path / "warm.jsonl"
    logger = RunLogger(output_path=None, echo=False,
                       metrics_path=str(metrics))
    set_event_sink(logger)
    try:
        times = warm_compile_cache(backend, (1, 2, 4), k=3)
    finally:
        set_event_sink(None)
        logger.close()
    assert sorted(times) == [1, 2, 4]
    events = [json.loads(line) for line in metrics.read_text().splitlines()]
    warm = [e for e in events if e["event"] == "compile_warm"]
    assert [e["bucket"] for e in warm] == [1, 2, 4]
    assert all(e["seconds"] >= 0 for e in warm)


# -- JSONL protocol -------------------------------------------------------


def test_protocol_requests_and_serve_loop(hin, metapath):
    from distributed_pathsim_tpu.serving.protocol import (
        handle_request,
        serve_loop,
    )

    svc = _service(hin, metapath, "numpy", max_batch=4)
    try:
        assert handle_request(svc, {"id": 1, "op": "ping"})["ok"]
        resp = handle_request(svc, {"id": 2, "op": "topk", "row": 5, "k": 3})
        assert resp["ok"] and len(resp["result"]["topk"]) == 3
        sv, si = svc.backend.topk_row(5, k=3)
        assert [t["score"] for t in resp["result"]["topk"]] == sv.tolist()
        bad = handle_request(svc, {"id": 3, "op": "nope"})
        assert not bad["ok"] and "unknown op" in bad["error"]
        missing = handle_request(svc, {"id": 4, "op": "topk"})
        assert not missing["ok"]
        scores = handle_request(svc, {"id": 5, "op": "scores", "row": 5})
        assert scores["ok"] and len(scores["result"]["scores"]) == svc.n

        out = io.StringIO()
        rc = serve_loop(
            svc,
            io.StringIO(
                '{"id": 10, "op": "stats"}\n'
                "not json\n"
                '{"id": 11, "op": "shutdown"}\n'
                '{"id": 12, "op": "ping"}\n'  # after shutdown: unread
            ),
            out,
        )
        assert rc == 0
        lines = [json.loads(line) for line in out.getvalue().splitlines()]
        assert len(lines) == 3  # stats, bad-json error, shutdown ack
        assert lines[0]["ok"] and lines[0]["result"]["n"] == svc.n
        assert not lines[1]["ok"]
        assert lines[2]["result"] == {"shutdown": True}
    finally:
        svc.close()


# -- serve smoke (satellite: CI gate, non-slow) ---------------------------


def test_bench_serving_smoke(tmp_path):
    """``make serve-smoke`` in-process: warm-cache p50 beats cold-cache
    p50 and nothing sheds, on a small fixed-seed synthetic graph."""
    import pathlib
    import sys

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import bench_serving

    result = bench_serving.run_smoke(str(tmp_path / "smoke.json"))
    assert result["smoke_checks"]["warm_p50_lt_cold_p50"]
    assert result["smoke_checks"]["zero_shed"]
    r = result["regimes"]
    # directionally: batching beats serial dispatch on the same graph
    assert r["cold"]["qps"] > r["serial"]["qps"]
    assert (tmp_path / "smoke.json").exists()
