"""Inductive learned serving tier: distillation, cold start, safety.

The load-bearing guarantees (ISSUE 19 / DESIGN.md §32):

- the learned arm is NEVER WRONG, only slower: towers only generate
  candidates, every answer is exact-f64 reranked through the same
  ``score_candidates`` doorway as ann — bit-identical to the exact
  oracle whenever the candidate set covers (and the tests pin
  ``learned_cand_mult`` high enough that it always does);
- a NEVER-SEEN appended author is answerable in learned mode before
  any retrain or full re-embed: immediately through the counted
  'stale' fallback (exact, bit-identical), and through the towers
  proper after one O(Δ) inductive absorb (``refresh_towers``);
- every degradation is a counted fallback
  (``dpathsim_learned_fallbacks_total{reason}``): no_towers, stale,
  degenerate, low_confidence, metapath — each edge exercised here;
- checkpoints are keyed to (base fingerprint, delta seq, metapath,
  variant): a mismatched artifact is refused loudly (TowerMismatch),
  and the service falls back to in-process distillation;
- the ``--emit-pairs`` JSONL contract (batch/pairs.py): schema-checked
  load, seeded by-source train/val split, bounded negative sampling.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from distributed_pathsim_tpu.backends.base import create_backend
from distributed_pathsim_tpu.data import delta as dl
from distributed_pathsim_tpu.data.synthetic import synthetic_hin
from distributed_pathsim_tpu.learned import (
    LEARNED_FALLBACK_REASONS,
    TowerMismatch,
    load_towers,
    save_towers,
    train_towers,
)
from distributed_pathsim_tpu.ops.metapath import compile_metapath
from distributed_pathsim_tpu.serving import PathSimService, ServeConfig


@pytest.fixture(scope="module")
def hin():
    # headroom so deltas can append without rebuild
    return dl.with_headroom(synthetic_hin(120, 200, 8, seed=7), 0.25)


@pytest.fixture(scope="module")
def metapath(hin):
    return compile_metapath("APVPA", hin.schema)


def _learned_service(hin, metapath, **cfg):
    cfg.setdefault("max_wait_ms", 0.5)
    cfg.setdefault("warm", False)
    cfg.setdefault("topk_mode", "learned")
    cfg.setdefault("learned_steps", 40)
    cfg.setdefault("learned_shadow_every", 0)
    cfg.setdefault("learned_auto_refresh", False)
    # candidate set ≥ n on this graph: coverage is total, so every
    # learned answer must be bit-identical — the safety property under
    # test, independent of how good 40 training steps made the towers
    cfg.setdefault("learned_cand_mult", 32)
    return PathSimService(
        create_backend("numpy", hin, metapath),
        config=ServeConfig(**cfg),
    )


def _fallbacks(reason: str) -> float:
    from distributed_pathsim_tpu.obs.metrics import get_registry

    return get_registry().counter(
        "dpathsim_learned_fallbacks_total",
        "learned-requested queries degraded to ann/exact, by reason",
    ).labels(reason=reason).value


# -- the safety story: exact rerank, bit-identical answers -----------------


def test_learned_mode_answers_bit_identically(hin, metapath):
    svc = _learned_service(hin, metapath)
    try:
        assert svc.stats()["learned"] is not None
        lr = svc._learned
        eligible = np.flatnonzero(np.asarray(lr.d)[: svc.n] > 0)
        for row in eligible[:: max(eligible.size // 24, 1)]:
            lv, li = svc.topk_index(int(row), k=7, mode="learned")
            ev, ei = svc.topk_index(int(row), k=7, mode="exact")
            np.testing.assert_array_equal(lv, ev)
            np.testing.assert_array_equal(li, ei)
    finally:
        svc.close()


def test_degenerate_row_falls_back_exactly(hin, metapath):
    svc = _learned_service(hin, metapath)
    try:
        dead = np.flatnonzero(np.asarray(svc._learned.d)[: svc.n] <= 0)
        assert dead.size, "fixture graph needs a zero-denominator row"
        row = int(dead[0])
        assert svc.learned_fallback_reason(row, "learned") == "degenerate"
        before = _fallbacks("degenerate")
        lv, li = svc.topk_index(row, k=5, mode="learned")
        assert _fallbacks("degenerate") > before
        ev, ei = svc.topk_index(row, k=5, mode="exact")
        np.testing.assert_array_equal(lv, ev)
        np.testing.assert_array_equal(li, ei)
    finally:
        svc.close()


def test_no_towers_fallback_counted(hin, metapath):
    """mode=learned against an exact-only service: served exactly,
    degradation counted — the router re-dispatch story's local half."""
    svc = PathSimService(
        create_backend("numpy", hin, metapath),
        config=ServeConfig(max_wait_ms=0.5, warm=False),
    )
    try:
        assert svc.stats()["learned"] is None
        assert svc.learned_fallback_reason(3, "learned") == "no_towers"
        before = _fallbacks("no_towers")
        lv, li = svc.topk_index(3, k=5, mode="learned")
        assert _fallbacks("no_towers") > before
        ev, ei = svc.topk_index(3, k=5, mode="exact")
        np.testing.assert_array_equal(lv, ev)
        np.testing.assert_array_equal(li, ei)
    finally:
        svc.close()


def test_secondary_metapath_falls_back_counted(hin, metapath):
    """Towers are keyed to ONE metapath; a per-request secondary
    metapath in learned mode degrades (counted) to the secondary
    engine's exact path."""
    svc = _learned_service(hin, metapath)
    try:
        before = _fallbacks("metapath")
        lv, li = svc.topk_index(2, k=5, mode="learned", metapath="APA")
        assert _fallbacks("metapath") > before
        ev, ei = svc.topk_index(2, k=5, mode="exact", metapath="APA")
        np.testing.assert_array_equal(lv, ev)
        np.testing.assert_array_equal(li, ei)
    finally:
        svc.close()


def test_shadow_confidence_gate_trips_and_resets(hin, metapath):
    """An unreachable recall floor flips the learned arm off (the
    low_confidence fallback — answers stay exact) and refresh_towers
    re-arms the gate for the re-embedded towers."""
    svc = _learned_service(hin, metapath, learned_shadow_every=1,
                           learned_min_shadow=2,
                           learned_recall_floor=1.01)
    try:
        eligible = np.flatnonzero(np.asarray(svc._learned.d)[: svc.n] > 0)
        for row in eligible[:6]:
            svc.topk_index(int(row), k=5, mode="learned")
        assert (
            svc.learned_fallback_reason(int(eligible[0]), "learned")
            == "low_confidence"
        )
        before = _fallbacks("low_confidence")
        lv, li = svc.topk_index(int(eligible[0]), k=5, mode="learned")
        assert _fallbacks("low_confidence") > before
        ev, ei = svc.topk_index(int(eligible[0]), k=5, mode="exact")
        np.testing.assert_array_equal(lv, ev)
        np.testing.assert_array_equal(li, ei)
        # shadow evidence described the pre-absorb towers: refresh
        # clears it and the arm is answerable again
        svc.refresh_towers()
        assert svc.learned_fallback_reason(
            int(eligible[0]), "learned"
        ) is None
    finally:
        svc.close()


# -- cold start: a never-seen author, answerable immediately ---------------


def test_cold_start_appended_author_end_to_end(hin, metapath):
    """The acceptance property: append a NEVER-SEEN author (new row +
    edges in one delta) → answerable in learned mode at once through
    the counted 'stale' fallback, bit-identical to the exact oracle →
    one O(Δ) absorb (no retrain, no full re-embed) → answered through
    the towers proper, still bit-identical — with the cold-start gauge
    and fallback counters asserted along every edge."""
    svc = _learned_service(hin, metapath)
    try:
        n0 = svc.n  # the appended author's dense row index
        rng = np.random.default_rng(3)
        papers = sorted({
            int(p) for p in rng.integers(0, hin.type_size("paper"), 5)
        })
        info = svc.update(dl.DeltaBatch(
            nodes=(dl.NodeAppend(node_type="author", count=1),),
            edges=(dl.edge_delta(
                "author_of", add=[[n0, p] for p in papers]
            ),),
        ))
        assert info["mode"] == "delta"
        assert info["learned_pending_appends"] == 1
        assert info["learned_stale_rows"] > 0
        snap = svc.stats()["learned"]
        assert snap["pending_appends"] == 1
        assert snap["cold_start_ratio"] == 0.0
        assert svc.health()["modes"]["learned"]["pending_appends"] == 1

        # BEFORE any refresh: a real answer, exact, counted
        assert svc.learned_fallback_reason(n0, "learned") == "stale"
        before = _fallbacks("stale")
        lv, li = svc.topk_index(n0, k=6, mode="learned")
        assert _fallbacks("stale") > before
        ev, ei = svc.topk_index(n0, k=6, mode="exact")
        np.testing.assert_array_equal(lv, ev)
        np.testing.assert_array_equal(li, ei)
        assert np.isfinite(lv).any(), "cold author must have real hits"

        # one O(Δ) inductive absorb
        refresh = svc.refresh_towers()
        assert refresh["appended"] == 1
        assert refresh["stale_remaining"] == 0
        assert refresh["pending_appends"] == 0
        assert refresh["refreshed"] >= info["learned_stale_rows"]

        # AFTER: through the towers, same bytes
        assert svc.learned_fallback_reason(n0, "learned") is None
        lv2, li2 = svc.topk_index(n0, k=6, mode="learned")
        np.testing.assert_array_equal(lv2, ev)
        np.testing.assert_array_equal(li2, ei)
        snap2 = svc.stats()["learned"]
        assert snap2["pending_appends"] == 0
        assert snap2["cold_start_ratio"] == 1.0
        assert snap2["appended_seen"] == 1
    finally:
        svc.close()


# -- checkpoints: fingerprint-keyed, atomically saved, loudly refused ------


def test_checkpoint_roundtrip_and_mismatch(hin, metapath, tmp_path):
    enc, info = train_towers(hin, "APVPA", dim=16, hidden=32, steps=20,
                             hard_sources=48, hard_k=8,
                             token=("feedc0de", 0))
    assert info["hard_pool"] > 0
    path = str(tmp_path / "towers.npz")
    save_towers(path, enc, ("feedc0de", 0))
    enc2, token = load_towers(path, expect_base_fp="feedc0de")
    assert token == ("feedc0de", 0)
    assert enc2.dim == enc.dim and enc2.metapath == "APVPA"
    # identical forward pass bytes after the round trip
    rng = np.random.default_rng(0)
    c_rows = rng.random((8, enc.v))
    d_rows = rng.random(8) + 0.5
    np.testing.assert_array_equal(
        enc.embed(c_rows, d_rows), enc2.embed(c_rows, d_rows)
    )
    with pytest.raises(TowerMismatch):
        load_towers(path, expect_base_fp="0000000000000000")
    # a truncated artifact must refuse, not half-load
    bad = tmp_path / "broken.npz"
    bad.write_bytes(open(path, "rb").read()[:100])
    with pytest.raises((TowerMismatch, ValueError, OSError, KeyError)):
        load_towers(str(bad))


def test_service_boots_from_checkpoint_and_refuses_foreign(
    hin, metapath, tmp_path
):
    donor = _learned_service(hin, metapath)
    try:
        path = str(tmp_path / "towers.npz")
        save_towers(path, donor._learned.encoder,
                    donor.consistency_token)
        ev, ei = donor.topk_index(1, k=5, mode="exact")
    finally:
        donor.close()

    svc = PathSimService(
        create_backend("numpy", hin, metapath),
        config=ServeConfig(
            max_wait_ms=0.5, warm=False, topk_mode="learned",
            learned_checkpoint=path, learned_shadow_every=0,
            learned_auto_refresh=False, learned_cand_mult=32,
            learned_steps=10,
        ),
    )
    try:
        snap = svc.stats()["learned"]
        assert snap is not None and snap["enabled"]
        lv, li = svc.topk_index(1, k=5, mode="learned")
        np.testing.assert_array_equal(lv, ev)
        np.testing.assert_array_equal(li, ei)
    finally:
        svc.close()

    # a checkpoint keyed to a DIFFERENT graph: refused at startup, the
    # service falls back to in-process distillation and still serves
    foreign = str(tmp_path / "foreign.npz")
    enc, _ = train_towers(hin, "APVPA", dim=16, hidden=32, steps=10,
                          hard_sources=32, hard_k=6,
                          token=("0123456789abcdef", 0))
    save_towers(foreign, enc, ("0123456789abcdef", 0))
    svc2 = PathSimService(
        create_backend("numpy", hin, metapath),
        config=ServeConfig(
            max_wait_ms=0.5, warm=False, topk_mode="learned",
            learned_checkpoint=foreign, learned_shadow_every=0,
            learned_auto_refresh=False, learned_cand_mult=32,
            learned_steps=10,
        ),
    )
    try:
        snap = svc2.stats()["learned"]
        assert snap is not None, "must retrain after refusing the artifact"
        assert snap["token"] == list(svc2.consistency_token)
        lv, li = svc2.topk_index(1, k=5, mode="learned")
        np.testing.assert_array_equal(lv, ev)
        np.testing.assert_array_equal(li, ei)
    finally:
        svc2.close()


def test_encoder_refuses_width_change(hin, metapath):
    """A contraction-width change (new venue vocabulary moved the
    feature space) must be reported, never silently mis-embedded."""
    svc = _learned_service(hin, metapath)
    try:
        enc = svc._learned.encoder
        c = np.zeros((4, enc.v + 3), dtype=np.float64)
        d = np.ones(4, dtype=np.float64)
        with pytest.raises(ValueError):
            enc.features(c, d)
    finally:
        svc.close()


# -- the --emit-pairs JSONL contract (batch/pairs.py) ----------------------


def _write_pairs(path, recs):
    with open(path, "w", encoding="utf-8") as f:
        for rec in recs:
            f.write(json.dumps(rec) + "\n")


def test_load_pairs_roundtrip_and_schema_rejections(tmp_path):
    from distributed_pathsim_tpu.batch.pairs import load_pairs

    path = str(tmp_path / "pairs.jsonl")
    scores = [0.1, 1.0 / 3.0, 0.7071067811865476]
    _write_pairs(path, [
        {"row": i, "col": i + 1, "score": s}
        for i, s in enumerate(scores)
    ])
    rows, cols, got = load_pairs(path)
    assert rows.tolist() == [0, 1, 2]
    assert cols.tolist() == [1, 2, 3]
    np.testing.assert_array_equal(got, np.asarray(scores))  # bitwise

    for bad in (
        [{"row": 0, "col": 1}],                            # missing field
        [{"row": 0, "col": 1, "score": 0.5, "extra": 1}],  # drifted field
        [{"row": 0.5, "col": 1, "score": 0.5}],            # float index
        [{"row": -1, "col": 1, "score": 0.5}],             # negative
        [{"row": 0, "col": 1, "score": float("nan")}],     # non-finite
    ):
        p = str(tmp_path / "bad.jsonl")
        with open(p, "w", encoding="utf-8") as f:
            for rec in bad:
                f.write(json.dumps(rec) + "\n")
        with pytest.raises(ValueError):
            load_pairs(p)


def test_emitted_pairs_feed_the_loader(tmp_path):
    """Producer → consumer round trip: a real campaign's --emit-pairs
    stream loads, splits, and scores exactly."""
    from distributed_pathsim_tpu.batch import BatchEngine, run_topk_campaign
    from distributed_pathsim_tpu.batch.pairs import load_pairs

    base = synthetic_hin(120, 200, 8, seed=7)
    mp = compile_metapath("APVPA", base.schema)
    out = tmp_path / "pairs.jsonl"
    res = run_topk_campaign(
        BatchEngine(base, mp), 3, emit_pairs=str(out)
    )
    rows, cols, scores = load_pairs(str(out))
    assert rows.size > 0
    for i in range(0, rows.size, max(rows.size // 40, 1)):
        hit = np.flatnonzero(res.idxs[rows[i]] == cols[i])
        assert res.vals[rows[i]][hit[0]] == scores[i]  # bitwise


def test_split_pairs_deterministic_by_source(tmp_path):
    from distributed_pathsim_tpu.batch.pairs import split_pairs

    rows = np.repeat(np.arange(50), 3)
    tr1, va1 = split_pairs(rows, val_frac=0.2, seed=4)
    tr2, va2 = split_pairs(rows, val_frac=0.2, seed=4)
    np.testing.assert_array_equal(tr1, tr2)
    np.testing.assert_array_equal(va1, va2)
    assert np.all(tr1 ^ va1)  # a partition, not an overlap
    # by-source: every pair of one source on the same side
    for src in np.unique(rows):
        sides = va1[rows == src]
        assert sides.all() or not sides.any()
    tr3, va3 = split_pairs(rows, val_frac=0.2, seed=5)
    assert not np.array_equal(va1, va3), "seed must move the split"
    with pytest.raises(ValueError):
        split_pairs(rows, val_frac=1.0)


def test_sample_negatives_avoids_positives_and_diagonal():
    from distributed_pathsim_tpu.batch.pairs import sample_negatives

    rng = np.random.default_rng(0)
    rows = rng.integers(0, 30, 200)
    cols = rng.integers(0, 30, 200)
    keep = rows != cols
    rows, cols = rows[keep], cols[keep]
    nr, nc = sample_negatives(rows, cols, n_nodes=30, ratio=1.0, seed=1)
    assert nr.size > 0
    positives = set(zip(rows.tolist(), cols.tolist()))
    for r, c in zip(nr.tolist(), nc.tolist()):
        assert r != c
        assert (r, c) not in positives
    nr2, nc2 = sample_negatives(rows, cols, n_nodes=30, ratio=1.0, seed=1)
    np.testing.assert_array_equal(nr, nr2)
    np.testing.assert_array_equal(nc, nc2)


# -- CLI + flags-forward + smoke -------------------------------------------


def test_learned_cli_train_and_inspect(tmp_path, capsys):
    from distributed_pathsim_tpu.cli import main

    out = str(tmp_path / "towers.npz")
    rc = main([
        "learned", "train",
        "--dataset", "synthetic:authors=80,papers=140,venues=6,seed=3",
        "--out", out, "--steps", "15", "--dim", "8",
        "--hard-sources", "32", "--hard-k", "6",
    ])
    assert rc == 0
    info = json.loads(capsys.readouterr().out)
    assert info["dim"] == 8 and os.path.exists(out)
    rc = main(["learned", "inspect", "--towers", out])
    assert rc == 0
    meta = json.loads(capsys.readouterr().out)
    assert meta["dim"] == 8 and meta["metapath"] == "APVPA"
    assert meta["base_fp"] == info["token"][0]


def test_learned_router_worker_flags_forward():
    """Router CLI forwards the learned flags to worker children."""
    from distributed_pathsim_tpu.router.cli import (
        _worker_argv, build_router_parser,
    )

    args = build_router_parser().parse_args([
        "--workers", "2", "--topk-mode", "learned",
        "--learned-dim", "16", "--learned-cand-mult", "8",
        "--learned-checkpoint", "/tmp/towers.npz",
        "--no-learned-refresh",
    ])
    argv = _worker_argv(args, 0)
    assert "--topk-mode" in argv and "learned" in argv
    assert "--learned-dim" in argv and "16" in argv
    assert "--learned-cand-mult" in argv and "8" in argv
    assert "--learned-checkpoint" in argv and "/tmp/towers.npz" in argv
    assert "--no-learned-refresh" in argv


def test_fallback_reason_taxonomy_is_closed():
    assert set(LEARNED_FALLBACK_REASONS) == {
        "no_towers", "stale", "uncovered", "degenerate",
        "low_confidence", "metapath",
    }


def test_bench_learned_smoke():
    """`make learned-smoke`, wired non-slow (tier-1): score-recall
    gate at shipped defaults, zero steady-state recompiles, the
    cold-start exercise end to end, zero shed."""
    import bench_serving

    result = bench_serving.run_learned_smoke()
    assert all(result["smoke_checks"].values()), result["smoke_checks"]


@pytest.mark.slow
def test_learned_gate_2048():
    """The full acceptance gate (ISSUE 19): 2048 authors, shipped
    default knobs — score-recall ≥ 0.99 via exact rerank, zero
    steady-state compiles, the cold-start exercise bit-identical."""
    import bench_serving

    result = bench_serving.run_learned_bench()
    assert result["recall"]["recall_at_k"] >= 0.99
    assert result["steady_state_compiles"] == 0
    cs = result["cold_start"]
    assert cs["pre_refresh_answer_bit_identical"]
    assert cs["post_refresh_answer_bit_identical"]
    assert cs["cold_start_ratio_after_refresh"] == 1.0
