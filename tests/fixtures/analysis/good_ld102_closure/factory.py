"""GOOD: defining a blocking callback under a lock is not blocking
under a lock — the closure runs later, on another thread (LD102)."""
import queue
import threading


class Hub:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self.registered = 0

    def _make_cb(self):
        def cb():
            return self._q.get()
        return cb

    def start(self, register):
        with self._lock:
            self.registered += 1
            register(self._make_cb())
