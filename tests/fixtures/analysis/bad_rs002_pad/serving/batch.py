"""BAD: raw pad to the natural batch size (RS002)."""
import numpy as np


def form_batch(rows):
    return np.pad(rows, (0, 32 - len(rows)))
