"""GOOD: sorted() pins the iteration order."""
import hashlib


def fingerprint(parts):
    h = hashlib.sha256()
    names = set(parts)
    for name in sorted(names):
        h.update(name.encode())
    return h.hexdigest()
