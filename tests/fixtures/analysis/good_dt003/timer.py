"""GOOD: monotonic clock for durations."""
import time


def measure(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0
