"""GOOD: explicit seed."""
import numpy as np


def sample(n, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 10, n)
