"""BAD: calls a packed constructor around the factory (CF001)."""

from ..ops import packed


def sneaky_pack(c):
    return packed._pack_chunk(c.rows, c.cols, c.weights)
