"""BAD: unlocked write to a lock-guarded attribute (LD001)."""
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def add(self):
        with self._lock:
            self.count += 1

    def reset(self):
        self.count = 0
