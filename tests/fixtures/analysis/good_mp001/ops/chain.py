"""Chain-fold primitives (fixture mirror of ops/chain.py)."""


def chain_product(blocks, xp=None):
    m = blocks[0]
    for b in blocks[1:]:
        m = m @ b
    return m
