"""GOOD: chain evaluation goes through the planner doorway."""

from ..ops import planner


def commuting_matrix(plan, blocks):
    return planner.execute_dense(plan, blocks)
