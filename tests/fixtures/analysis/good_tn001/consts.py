"""GOOD: no knob-shaped constants outside the registry."""
_MY_WIDTH = 512
