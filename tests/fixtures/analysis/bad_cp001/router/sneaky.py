"""BAD: swaps a compaction-built backend around the doorway (CP001)."""


def hot_swap(service, backend, hin):
    service._swap_compacted(backend, hin)
