"""Fixture: the guarded compaction-doorway surface registry."""
COMPACTION_SURFACE = frozenset({"_apply_compaction", "_swap_compacted"})


class PathSimService:
    def _apply_compaction(self, backend, hin_c, token0):
        return {"replayed_deltas": 0}

    def _swap_compacted(self, backend, hin):
        self.backend = backend
