"""BAD: serves raw tower similarities without the rerank (LN001)."""


def answer_row(state, rows):
    handle = state.probe_batch(rows)
    return handle.raw_sims[0]
