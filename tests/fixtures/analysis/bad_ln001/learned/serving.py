"""Fixture: the guarded learned-score surface registry."""
LEARNED_SURFACE = frozenset({"tower_sims", "raw_sims"})


class ProbeHandle:
    def __init__(self, raw_sims):
        self.raw_sims = raw_sims


class LearnedState:
    def tower_sims(self, rows):
        return [[0.0]]

    def probe_batch(self, rows):
        return ProbeHandle(self.tower_sims(rows))

    def answer_from_handle(self, handle, b, row, k):
        return [0.0], [0]
