"""BAD: raw stderr print in library code (TL001)."""
import sys


def warn(msg):
    print("warning:", msg, file=sys.stderr)
