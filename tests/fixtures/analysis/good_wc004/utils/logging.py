"""GOOD: utils/logging.py owns the raw stream writes."""
import sys


def emit(line):
    sys.stderr.write(line + "\n")
