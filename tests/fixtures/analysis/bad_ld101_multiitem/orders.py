"""BAD: opposite orders spelled as multi-item withs (LD101)."""
import threading

_A = threading.Lock()
_B = threading.Lock()


def forward(jobs):
    with _A, _B:
        jobs.append("f")


def backward(jobs):
    with _B, _A:
        jobs.append("b")
