"""GOOD: reads hold the lock (and Conditions count as the lock)."""
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._ready = threading.Condition(self._lock)
        self.count = 0

    def add(self):
        with self._lock:
            self.count += 1
            self._ready.notify()

    def peek(self):
        with self._ready:
            return self.count
