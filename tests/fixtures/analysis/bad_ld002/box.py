"""BAD: unlocked read of a lock-guarded attribute (LD002)."""
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def add(self):
        with self._lock:
            self.count += 1

    def peek(self):
        return self.count
