"""BAD: undefaulted wire-field read (WC002)."""


def handle(req, reply):
    reply({"request_id": req["request_id"]})
