"""BAD: unhashable default on a keyword-only static argument (RS003)."""
import functools

import jax


@functools.partial(jax.jit, static_argnames=("tiles",))
def _fold_jit(x, *, tiles=[8, 16]):
    return x * tiles[0]
