"""GOOD: removal in a finally — exactly-once on every exit (EX003)."""


class Pending:
    def __init__(self):
        self._pending = {}

    def run(self, rid, work):
        self._pending[rid] = work
        try:
            return work()
        finally:
            self._pending.pop(rid, None)
