"""GOOD: defaulted / guarded wire-field reads."""


def handle(req, reply):
    rid = req.get("request_id")
    if req.get("deadline_ms") is not None:
        rid = (rid, req["deadline_ms"])
    reply({"request_id": rid})
