"""GOOD: the CLI surface may print to stderr."""
import sys


def warn(msg):
    print("warning:", msg, file=sys.stderr)
