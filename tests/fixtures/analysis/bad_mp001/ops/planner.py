"""Planner doorway (fixture mirror of ops/planner.py)."""

from . import chain


def execute_dense(plan, blocks, xp=None):
    return chain.chain_product(blocks, xp=xp)
