"""BAD: evaluates the chain directly, bypassing the planner."""

from ..ops import chain


def commuting_matrix(blocks):
    return chain.chain_product(blocks)
