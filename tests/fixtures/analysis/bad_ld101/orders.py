"""BAD: two paths acquire the same locks in opposite orders (LD101)."""
import threading

_A = threading.Lock()
_B = threading.Lock()


def forward(jobs):
    with _A:
        with _B:
            jobs.append("f")


def backward(jobs):
    with _B:
        with _A:
            jobs.append("b")
