"""BAD: raw stream write (WC004)."""
import sys


def emit(line):
    sys.stdout.write(line + "\n")
