"""BAD: float32 cast inside an f64 scoring path (DT002)."""
import numpy as np

from ..ops import pathsim


def rerank(counts, d_src, d_cand):
    scores = pathsim.score_candidates(counts, d_src, d_cand)
    return np.float32(scores)
