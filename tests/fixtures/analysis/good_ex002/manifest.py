"""GOOD: with-managed handle (EX002)."""
import json


def load_manifest(path):
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)
