"""The producing side: the field is read by the ping handler."""


def probe(transport):
    transport.send({"op": "ping", "echo_tag": 1})
