"""GOOD: every produced field has a reader (WC103)."""
PROTOCOL_OPS = frozenset({"ping"})


def _dispatch_op(service, op, req):
    if op == "ping":
        return {"pong": True, "echo_tag": req.get("echo_tag")}
    raise KeyError(op)
