"""GOOD: the exchange layer itself may touch the factor slice."""


def tile_for(fs, row):
    return fs.c_held[int(fs.held_slot_of[row])]
