"""GOOD: knob resolved in the wrapper, passed in as a static arg."""
import functools

import jax

from ..tuning import dispatch


def scores(c, k):
    bm, bn = dispatch.choose("scores_tile", n=8, default=(8, 8))
    return _scores_jit(c, k, bm, bn)


@functools.partial(jax.jit, static_argnames=("k", "bm", "bn"))
def _scores_jit(c, k, bm, bn):
    return c * bm * bn * k
