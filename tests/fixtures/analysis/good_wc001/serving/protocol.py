"""GOOD: every handled op is registered."""
PROTOCOL_OPS = frozenset({"ping", "frobnicate"})


def _dispatch_op(service, op, req):
    if op == "ping":
        return {"pong": True}
    if op == "frobnicate":
        return {"frobnicated": True}
    raise KeyError(op)
