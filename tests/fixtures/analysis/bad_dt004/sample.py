"""BAD: unseeded RNG in package code (DT004)."""
import numpy as np


def sample(n):
    rng = np.random.default_rng()
    return rng.integers(0, 10, n)
