"""GOOD: batch shapes come from the bucket ladder."""
import numpy as np

from . import buckets as bk


def form_batch(rows, ladder):
    bucket = bk.bucket_for(len(rows), ladder)
    return bk.pad_rows(np.asarray(rows), bucket)
