"""GOOD: every touch of guarded state holds the lock — including via a
private helper whose call sites all hold it (the held-method fixpoint)."""
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def add(self):
        with self._lock:
            self._bump()

    def _bump(self):
        self.count += 1

    def reset(self):
        with self._lock:
            self.count = 0
