"""BAD: unhashable default on a static argument (RS003)."""
import functools

import jax


@functools.partial(jax.jit, static_argnames=("tiles",))
def _fold_jit(x, tiles=[8, 8]):
    return x * tiles[0]
