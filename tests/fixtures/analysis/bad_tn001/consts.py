"""BAD: hardcoded tile constant outside the registry (TN001)."""
_MY_TILE = 512
