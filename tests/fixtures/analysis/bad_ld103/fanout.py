"""BAD: transport send while holding a lock (LD103)."""
import threading


class Fanout:
    def __init__(self, transport):
        self._lock = threading.Lock()
        self.transport = transport
        self.sent = 0

    def push(self, wire):
        with self._lock:
            self.transport.send(wire)
            self.sent += 1
