"""BAD: a raise between insert and removal leaks the entry (EX003)."""


class Pending:
    def __init__(self):
        self._pending = {}

    def run(self, rid, work):
        self._pending[rid] = work
        result = work()
        self._pending.pop(rid, None)
        return result
