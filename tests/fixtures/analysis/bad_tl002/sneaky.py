"""BAD: event-sink bypass (TL002)."""


def emit(logging_mod, event):
    logging_mod._EVENT_SINK.log(event)
