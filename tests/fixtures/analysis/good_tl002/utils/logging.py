"""GOOD: the sink is private to utils/logging.py."""
_EVENT_SINK = None


def runtime_event(event, **fields):
    if _EVENT_SINK is not None:
        _EVENT_SINK.log(event, **fields)
