"""BAD: set iteration feeds a fingerprint (DT001)."""
import hashlib


def fingerprint(parts):
    h = hashlib.sha256()
    names = set(parts)
    for name in names:
        h.update(name.encode())
    return h.hexdigest()
