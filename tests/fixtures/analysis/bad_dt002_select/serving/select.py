"""BAD: local argsort reimplements score selection (DT002)."""
import numpy as np


def pick_top(scores, k):
    return np.argsort(-scores)[:k]
