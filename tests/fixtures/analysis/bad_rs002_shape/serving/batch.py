"""BAD: Python-value-dependent device shape (RS002)."""
import jax.numpy as jnp


def form_batch(rows):
    return jnp.zeros((len(rows), 4))
