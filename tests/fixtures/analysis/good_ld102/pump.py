"""GOOD: wait outside the critical section, store inside (LD102)."""
import queue
import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self.last = None

    def take(self):
        item = self._q.get()
        with self._lock:
            self.last = item
        return item
