"""Fixture: the guarded factor-slice surface registry."""
FACTOR_SURFACE = frozenset({"c_held", "held_slot_of", "range_slots"})


class FactorSlice:
    def __init__(self, c_held, held_slot_of):
        self.c_held = c_held
        self.held_slot_of = held_slot_of
