"""BAD: reads the factor slice outside the exchange layer (PT001)."""


def peek_foreign_rows(fs, row):
    return fs.c_held[row]
