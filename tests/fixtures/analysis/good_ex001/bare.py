"""GOOD: release guaranteed in a finally (EX001)."""
import threading

_LOCK = threading.Lock()


def withdraw(account, amount):
    _LOCK.acquire()
    try:
        account.debit(amount)
    finally:
        _LOCK.release()
