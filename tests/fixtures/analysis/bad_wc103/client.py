"""The producing side: sends a field ping never reads."""


def probe(transport):
    transport.send({"op": "ping", "echo_tag": 1})
