"""BAD: a producer writes a field no handler reads (WC103)."""
PROTOCOL_OPS = frozenset({"ping"})


def _dispatch_op(service, op, req):
    if op == "ping":
        return {"pong": True}
    raise KeyError(op)
