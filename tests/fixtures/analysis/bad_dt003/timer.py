"""BAD: wall clock where ordering/durations need monotonic (DT003)."""
import time


def stamp():
    return time.time()
