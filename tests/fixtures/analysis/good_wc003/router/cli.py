"""GOOD: the CLI surface is the sanctioned print site."""


def announce(state):
    print("router state:", state)
