"""GOOD: send after releasing; the lock only guards bookkeeping (LD103)."""
import threading


class Fanout:
    def __init__(self, transport):
        self._lock = threading.Lock()
        self.transport = transport
        self.sent = 0

    def push(self, wire):
        self.transport.send(wire)
        with self._lock:
            self.sent += 1
