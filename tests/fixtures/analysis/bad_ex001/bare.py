"""BAD: bare acquire, release skipped on exception exits (EX001)."""
import threading

_LOCK = threading.Lock()


def withdraw(account, amount):
    _LOCK.acquire()
    account.debit(amount)
    _LOCK.release()
