"""BAD: print in a wire-owning package (WC003)."""


def announce(state):
    print("router state:", state)
