"""GOOD: one global acquisition order, everywhere (LD101)."""
import threading

_A = threading.Lock()
_B = threading.Lock()


def forward(jobs):
    with _A:
        with _B:
            jobs.append("f")


def also_forward(jobs):
    with _A:
        with _B:
            jobs.append("g")
