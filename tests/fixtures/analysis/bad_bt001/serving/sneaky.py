"""BAD: sweeps a block around the campaign doorway (BT001)."""


def answer_all(engine, k):
    return engine.sweep_topk_block(0, 256, k)
