"""Fixture: the guarded block-sweep surface registry."""
BATCH_SURFACE = frozenset({
    "sweep_topk_block", "sweep_scores_block", "sweep_pair_block",
})


class BatchEngine:
    def sweep_topk_block(self, lo, hi, k):
        return [], []

    def sweep_scores_block(self, lo, hi):
        return [], []

    def sweep_pair_block(self, rows_i, cols_j):
        return []


def run_topk_campaign(engine, k):
    return engine.sweep_topk_block(0, 1, k)
