"""BAD: handle closed only on the happy path (EX002)."""
import json


def load_manifest(path):
    f = open(path, "r", encoding="utf-8")
    data = json.load(f)
    f.close()
    return data
