"""BAD: op handled but not registered (WC001)."""
PROTOCOL_OPS = frozenset({"ping"})


def _dispatch_op(service, op, req):
    if op == "ping":
        return {"pong": True}
    if op == "frobnicate":
        return {"frobnicated": True}
    raise KeyError(op)
