"""Fixture mirror of ops/packed.py: surface + factory registries."""
PACKED_SURFACE = frozenset({"chunks", "row_counts", "block_bits", "col_perm"})
SANCTIONED_FACTORY = frozenset({"make_factor", "as_coo", "factor_bytes"})


def _pack_chunk(rows, cols, weights):
    return (rows, cols, weights)


def make_factor(c, fmt):
    return _pack_chunk(c.rows, c.cols, c.weights)


def as_coo(f):
    return f


def factor_bytes(f):
    return 0
