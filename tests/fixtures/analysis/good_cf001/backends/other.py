"""GOOD: packed factors built and read through the factory only."""

from ..ops import packed


def resident_bytes(c, fmt):
    return packed.factor_bytes(packed.make_factor(c, fmt))
