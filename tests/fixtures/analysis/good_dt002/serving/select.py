"""GOOD: selection goes through the shared f64 primitives."""
from ..ops import pathsim


def pick_top(scores, k):
    return pathsim.topk_from_score_rows(scores, k)
