"""BAD: the SECOND attribute of a tuple write under the lock is
guarded too — LD001 on the unlocked clobber."""
import threading


class Pair:
    def __init__(self):
        self._lock = threading.Lock()
        self.a = 0
        self.b = 0

    def set_both(self, x, y):
        with self._lock:
            self.a, self.b = x, y

    def clobber(self):
        self.b = 9
