"""BAD: tuning-knob resolution inside a jitted core (RS001)."""
import functools

import jax

from ..tuning import dispatch


@functools.partial(jax.jit, static_argnames=("k",))
def _scores_jit(c, k):
    bm, bn = dispatch.choose("scores_tile", n=8, default=(8, 8))
    return c * bm * bn * k
